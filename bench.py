#!/usr/bin/env python
"""Headline benchmark: TPC-H lineitem decode throughput (BASELINE config #2).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "rows/s", "vs_baseline": N}

* value        — rows/s decoding all 16 lineitem columns with the TPU engine
                 (end to end: file read, Snappy decompress, run-table parse,
                 host→HBM transfer, device expand+gather, block_until_ready),
                 under the bit-exact float64 policy ('bits': DOUBLE decodes
                 as exact IEEE-754 bit patterns — nothing is lost vs the
                 CPU baseline's exact doubles)
* vs_baseline  — ratio vs the single-thread CPU decode of the same file with
                 the host NumPy engine (the reference-equivalent decoder;
                 the reference publishes no numbers of its own — SURVEY.md §6)
* detail       — the full north-star metric set (BASELINE.json): GB/s decoded
                 (decompressed bytes / wall time), GB/s shipped over the
                 host→device link, and p50/p99 page-decode latency (the fused
                 device decode step of one row group, measured dispatch→ready
                 over pre-shipped bytes, divided across its data pages).

Env knobs: PFTPU_BENCH_ROWS (default 1_000_000), PFTPU_BENCH_REPS (default 3).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent XLA compile cache: decode-shape compiles are expensive over
# remote TPU links; cache them across bench invocations.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")


def _hist_p_ms(hist, p: float):
    """One rounding/None convention for every leg's histogram-quantile
    field: ``hist`` is a LogHistogram or None, result is ms or None."""
    v = None if hist is None else hist.percentile(p)
    return None if v is None else round(v * 1e3, 3)


def _decoded_bytes(reader) -> int:
    """Total decompressed bytes in the file (footer metadata: the sum of
    every column chunk's total_uncompressed_size — pages + headers)."""
    return sum(
        int(c.meta_data.total_uncompressed_size or 0)
        for rg in reader.row_groups
        for c in (rg.columns or [])
    )


def _count_pages(reader, rg_index: int) -> int:
    """Data pages in one row group (OffsetIndex page locations; falls back
    to 1 page/chunk when the writer emitted no index)."""
    pages = 0
    for chunk in reader.row_groups[rg_index].columns or []:
        oi = reader.read_offset_index(chunk)
        pages += len(oi.page_locations) if oi and oi.page_locations else 1
    return pages


def page_decode_latency(tpu_reader, reps: int = 30):
    """p50/p99 of the fused device decode step: one row group's pages,
    staged and shipped once, decode dispatched repeatedly and timed
    dispatch→block_until_ready.  Per-page latency divides the fused step
    across the pages it decodes (the engine decodes all of a group's pages
    in one launch — that IS the page-decode path)."""
    import jax

    sg = tpu_reader._stage_row_group(0, None)
    shipped = tpu_reader._ship(sg)
    pages = _count_pages(tpu_reader.reader, 0)
    # warm the compile
    jax.block_until_ready(
        [c.values for c in tpu_reader._decode_shipped(sg, shipped).values()]
    )
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        cols = tpu_reader._decode_shipped(sg, shipped)
        jax.block_until_ready([c.values for c in cols.values()])
        samples.append(time.perf_counter() - t0)
    import math

    samples.sort()
    p50 = samples[len(samples) // 2]
    p99 = samples[max(0, math.ceil(0.99 * len(samples)) - 1)]
    return {
        "group_decode_p50_ms": round(p50 * 1e3, 3),
        "group_decode_p99_ms": round(p99 * 1e3, 3),
        "pages_per_group": pages,
        # DERIVED, not separately measured: the fused launch decodes all
        # of a group's pages at once, so per-page latency is the
        # measured group decode divided by its page count
        "page_decode_p50_us_derived": round(p50 / max(pages, 1) * 1e6, 2),
        "page_decode_p99_us_derived": round(p99 / max(pages, 1) * 1e6, 2),
    }


def batch_face_leg(path, reps: int, raw_engine_best: float) -> dict:
    """Batch-protocol throughput (VERDICT r4 #4): rows/s through the
    flagship ``ParquetReader.stream_batches`` face on the device engine,
    arrays kept on device (no D2H — the protocol's intended shape,
    examples/tpch_q1_batches.py), plus the protocol's overhead vs the
    raw engine scan timed by the caller."""
    import jax

    from parquet_floor_tpu import ParquetReader

    def run():
        rows = 0
        for cols in ParquetReader.stream_batches(path, engine="tpu"):
            jax.block_until_ready([c.values for c in cols])
            rows += int(cols[0].values.shape[0])
        return rows

    rows = run()  # warm (compile shapes are shared with the raw scan)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return {
        "batch_rows_per_sec": round(rows / best, 1),
        # protocol overhead: batch-face wall over the raw engine scan of
        # the same file (1.0 = free; round-4 builder measurement: ~1.11)
        "batch_vs_raw_engine_x": round(best / raw_engine_best, 3),
    }


def _scan_paths(n_rows: int, n_files: int = 4):
    """The scan leg's dataset: ≥4 lineitem files, ≥2 row groups each."""
    from benchmarks.workloads import write_lineitem

    per = max(n_rows // n_files, 500)
    paths = []
    for i in range(n_files):
        p = os.path.join("/tmp", f"pftpu_bench_scan_{per}_{i}.parquet")
        if not os.path.exists(p):
            write_lineitem(p, per, row_group_rows=max(per // 2, 250), seed=i)
        paths.append(p)
    return paths


def scan_leg(n_rows: int, reps: int) -> dict:
    """Multi-file scan scheduler vs the sequential per-file loop
    (docs/scan.md), 4-file dataset, device engine: the per-file
    ``TpuRowGroupReader`` loop drains its stage‖ship‖decode pipeline at
    every file boundary; ``scan_device_groups`` rides it across.
    Reports ``scan_rows_per_sec``, the speedup, planner/executor trace
    counters, and a bit-identical check of the decoded output.  Runs on
    the already-initialized jax backend (after the headline legs, before
    the D2H-heavy chunked leg)."""
    import jax
    import numpy as np

    from parquet_floor_tpu.scan import ScanOptions, scan_device_groups
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    from parquet_floor_tpu.utils import trace

    paths = _scan_paths(n_rows)
    threads = min(4, os.cpu_count() or 1)
    sc = ScanOptions(threads=threads)

    def sequential():
        rows = 0
        for p in paths:
            with TpuRowGroupReader(p, float64_policy="bits") as tr:
                for cols in tr.iter_row_groups():
                    jax.block_until_ready([c.values for c in cols.values()])
                    rows += int(next(iter(cols.values())).values.shape[0])
        return rows

    def scan():
        rows = 0
        for _fi, _gi, cols in scan_device_groups(
            paths, scan=sc, float64_policy="bits"
        ):
            jax.block_until_ready([c.values for c in cols.values()])
            rows += int(next(iter(cols.values())).values.shape[0])
        return rows

    def check(n):
        # plain raise, not assert: the timed calls must survive python -O
        if n != rows:
            raise RuntimeError(f"scan leg row-count drift: {n} != {rows}")

    rows = sequential()  # warm compiles + page cache
    check(scan())
    seq_dt = float("inf")
    scan_dt = float("inf")
    for _ in range(max(reps, 2)):
        t0 = time.perf_counter()
        check(sequential())
        seq_dt = min(seq_dt, time.perf_counter() - t0)
        t0 = time.perf_counter()
        check(scan())
        scan_dt = min(scan_dt, time.perf_counter() - t0)

    # one counted pass under an isolated tracer scope (docs/observability.md):
    # merged counters for the flat detail fields, the ScanReport health
    # summary for the bench JSON, and — with PFTPU_TRACE_EXPORT=path — a
    # Chrome/Perfetto trace of the scan's read‖stage‖ship‖decode overlap
    with trace.scope() as t:
        t0 = time.perf_counter()
        check(scan())
        scoped_wall = time.perf_counter() - t0
    counters = t.metrics()
    stats = t.stats()
    scan_report = t.scan_report(
        wall_seconds=scoped_wall, budget_bytes=sc.prefetch_bytes
    )
    export_path = os.environ.get("PFTPU_TRACE_EXPORT")
    if export_path:
        t.export_chrome_trace(export_path)

    # bit-identical decoded output vs the per-file loop (one pass each;
    # fetches device arrays — keep AFTER every timed section)
    def fetch_all(groups_iter):
        out = []
        for cols in groups_iter:
            out.append({
                k: (np.asarray(v.values),
                    None if v.mask is None else np.asarray(v.mask))
                for k, v in cols.items()
            })
        return out

    def seq_groups():
        for p in paths:
            with TpuRowGroupReader(p, float64_policy="bits") as tr:
                yield from tr.iter_row_groups()

    got = fetch_all(
        cols for _fi, _gi, cols in scan_device_groups(
            paths, scan=sc, float64_policy="bits"
        )
    )
    want = fetch_all(seq_groups())
    bit_exact = len(got) == len(want)
    for a, b in zip(got, want):
        for name in b:
            va, ma = a[name]
            vb, mb = b[name]
            if not np.array_equal(va, vb):
                bit_exact = False
            if (ma is None) != (mb is None) or (
                ma is not None and not np.array_equal(ma, mb)
            ):
                bit_exact = False

    # one-launch contract (docs/perf.md): groups whose footer estimate
    # exceeds the arena cap legitimately take the multi-launch chunked
    # fallback — count them so check_bench_report only asserts strict
    # equality when every group is in-cap
    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.tpu.cost import arena_cap

    overcap = 0
    for p in paths:
        with ParquetFileReader(p) as r:
            for rg in r.row_groups:
                est = sum(
                    int(c.meta_data.total_uncompressed_size or 0)
                    for c in (rg.columns or [])
                )
                if est > arena_cap():
                    overcap += 1

    return {
        "scan_rows_per_sec": round(rows / scan_dt, 1),
        "scan_seq_rows_per_sec": round(rows / seq_dt, 1),
        "scan_vs_sequential_x": round(seq_dt / scan_dt, 3),
        "scan_bit_exact": bool(bit_exact),
        # the counted pass must dispatch exactly ONE fused launch per
        # in-cap row group
        "scan_groups": len(got),
        "scan_overcap_groups": overcap,
        "scan_launches": counters.get("engine.launches", 0),
        "scan_files": len(paths),
        "scan_threads": threads,
        "scan_extents_planned": counters.get("scan.extents_planned", 0),
        "scan_ranges_planned": counters.get("scan.ranges_planned", 0),
        "scan_overread_bytes": counters.get("scan.overread_bytes", 0),
        "scan_bytes_read": counters.get("scan.bytes_read", 0),
        "scan_queue_depth_max": counters.get("scan.queue_depth_max", 0),
        "scan_inflight_bytes_max": counters.get("scan.inflight_bytes_max", 0),
        "scan_prefetch_budget": sc.prefetch_bytes,
        # time the consumer spent waiting on the engine pipeline
        # (budget admission never blocks — the bound works by refusal —
        # so consumer stall is the scan's one wait metric)
        "scan_consumer_stall_ms": round(
            stats.get("scan.consumer_stall", {}).get("seconds", 0.0) * 1e3, 1
        ),
        # the full health summary (per-stage throughput, overlap/stall
        # fraction, budget utilization, over-read ratio, retries) — the
        # consumable ScanReport form of the counters above
        "scan_report": scan_report.as_dict(),
    }


def _pushdown_paths(n_rows: int, n_files: int = 4):
    """The pushdown leg's dataset: 4 pyarrow-written files (a FOREIGN
    writer — the differential claim is against pyarrow end to end), 2
    row groups each; ``k`` uniform in [0, 1e6) so ``k < 10_000`` is a
    ~1% filter, ``cat`` dictionary-encoded (8 keys) for the group-by."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    per = max(n_rows // n_files, 2000)
    cats = ["alpha", "beta", "gamma", "delta", "eps", "zeta", "eta", "theta"]
    paths = []
    for i in range(n_files):
        p = os.path.join("/tmp", f"pftpu_bench_push_{per}_{i}.parquet")
        if not os.path.exists(p):
            rng = np.random.default_rng(100 + i)
            t = pa.table({
                "k": rng.integers(0, 1_000_000, per).astype(np.int64),
                "v": rng.integers(0, 1_000, per).astype(np.int64),
                "cat": [cats[j] for j in rng.integers(0, len(cats), per)],
            })
            pq.write_table(
                t, p, row_group_size=per // 2, use_dictionary=["cat"],
                compression="NONE", data_page_size=1 << 20,
            )
        paths.append(p)
    return paths


def pushdown_leg(n_rows: int) -> dict:
    """Device pushdown compute (docs/pushdown.md), asserted by
    ``check_bench_report.check_pushdown_leg``:

    * a SELECTIVE (~1%) filter scan over the 4-file dataset ships
      device-COMPACTED rows — D2H bytes must be ≤ 0.1x the same scan's
      ship-columns baseline, with the one-launch contract intact
      (``engine.launches == groups``, zero capacity overflows) and the
      surviving rows bit-identical to ``pyarrow.compute``'s filter;
    * a group-by aggregate ships tiny per-group partial states
      (O(dictionary) D2H) whose combined result is bit-equal to
      pyarrow's ``group_by().aggregate``.
    """
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from parquet_floor_tpu.batch.aggregate import Aggregate
    from parquet_floor_tpu.batch.predicate import col
    from parquet_floor_tpu.scan import (
        ScanOptions,
        scan_aggregate,
        scan_device_groups,
    )
    from parquet_floor_tpu.utils import trace

    paths = _pushdown_paths(n_rows)
    threads = min(4, os.cpu_count() or 1)
    pred = col("k") < 10_000
    columns = ["k", "v"]

    # --- ship-columns baseline: full decode, full D2H ---------------------
    baseline_bytes = 0
    base_groups = 0
    base_rows = 0
    for _fi, _gi, cols in scan_device_groups(
        paths, columns=columns, scan=ScanOptions(threads=threads),
        float64_policy="bits",
    ):
        for c in cols.values():
            baseline_bytes += np.asarray(c.values).nbytes
            if c.mask is not None:
                baseline_bytes += np.asarray(c.mask).nbytes
        base_groups += 1
        base_rows += int(next(iter(cols.values())).values.shape[0])

    # --- pushdown filter scan: compacted D2H ------------------------------
    sc = ScanOptions(threads=threads, pushdown=True)
    got_k = []
    got_v = []
    push_bytes = 0
    with trace.scope() as t:
        groups = 0
        for _fi, _gi, cols in scan_device_groups(
            paths, columns=columns, scan=sc, predicate=pred,
            float64_policy="bits",
        ):
            ka = np.asarray(cols["k"].values)
            va = np.asarray(cols["v"].values)
            push_bytes += ka.nbytes + va.nbytes
            got_k.append(ka)
            got_v.append(va)
            groups += 1
    counters = t.counters()
    # the engine fetches one int64 selected-count per group (that small
    # sync IS part of the pushdown D2H story — charge it)
    push_bytes += 8 * counters.get("engine.pushdown_groups", groups)
    got_k = np.concatenate(got_k) if got_k else np.zeros(0, np.int64)
    got_v = np.concatenate(got_v) if got_v else np.zeros(0, np.int64)

    table = pa.concat_tables([pq.read_table(p) for p in paths])
    want = table.filter(pc.less(table["k"], 10_000))
    filter_exact = bool(
        np.array_equal(got_k, want["k"].to_numpy())
        and np.array_equal(got_v, want["v"].to_numpy())
    )

    # --- group-by aggregate: O(groups) D2H --------------------------------
    agg = Aggregate(
        (("v", "sum"), ("v", "min"), ("v", "max"), ("v", "count")),
        group_by="cat",
    )
    with trace.scope() as ta:
        part = scan_aggregate(
            paths, agg, predicate=pred,
            scan=ScanOptions(threads=threads), engine="tpu",
        )
    fin = part.finalize()
    gb = want.group_by("cat").aggregate(
        [("v", "sum"), ("v", "min"), ("v", "max"), ("v", "count")]
    ).to_pydict()
    agg_exact = len(fin) == len(gb["cat"])
    for i, key in enumerate(gb["cat"]):
        ours = fin.get(key.encode())
        if ours is None or ours["v_sum"] != gb["v_sum"][i] or \
                ours["v_min"] != gb["v_min"][i] or \
                ours["v_max"] != gb["v_max"][i] or \
                ours["v_count"] != gb["v_count"][i]:
            agg_exact = False
    # partial states: (1 rows + 4 nv + 3 value arrays) x (gcap+1) slots
    # of 8-byte lanes per group, plus the count scalar — the worst-case
    # D2H charge of the aggregate scan
    gcap = 16 + 1  # 8 keys bucket to 16; +1 null slot
    agg_groups = ta.counters().get("engine.pushdown_groups", base_groups)
    agg_bytes = agg_groups * (8 * gcap * 8 + 8)

    return {
        "pushdown_groups": groups,
        "pushdown_rows_in": base_rows,
        "pushdown_rows_selected": int(got_k.size),
        "pushdown_launches": counters.get("engine.launches", 0),
        "pushdown_overflows": counters.get("engine.pushdown_overflows", 0),
        "pushdown_rows_filtered_device": counters.get(
            "scan.rows_filtered_device", 0
        ),
        "pushdown_d2h_bytes": int(push_bytes),
        "pushdown_baseline_d2h_bytes": int(baseline_bytes),
        "pushdown_d2h_ratio": round(push_bytes / max(baseline_bytes, 1), 4),
        "pushdown_filter_exact": filter_exact,
        "pushdown_agg_exact": bool(agg_exact),
        "pushdown_agg_d2h_bytes": int(agg_bytes),
        "pushdown_agg_groups": len(fin),
    }


def exec_cache_leg(n_rows: int) -> dict:
    """Cold-vs-warm start on the persistent AOT executable cache
    (docs/perf.md): two FRESH subprocesses decode the same file's group
    0 against one shared ``PFTPU_EXEC_CACHE`` dir — the first pays the
    XLA compile and stores the executable, the second deserializes it
    and must skip compilation entirely.  ``check_bench_report.py``
    asserts the shape: the cold run compiles (misses >= 1), the warm
    run does not (hits >= 1, compile_ms == 0), the warm first-group
    wall is >= 10x better, the fused path is exactly ONE launch, and
    the decoded digests are bit-identical.

    The probe file uses small (256-row) groups: compile cost is shape-
    driven, not data-driven, so small groups put the measurement where
    the overhead actually is."""
    import subprocess
    import tempfile

    from benchmarks.workloads import write_lineitem

    per = max(min(n_rows, 2048), 512)
    path = os.path.join("/tmp", f"pftpu_bench_execcache_{per}.parquet")
    if not os.path.exists(path):
        write_lineitem(path, per, row_group_rows=256, seed=3)
    probe = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts", "exec_cache_probe.py",
    )
    import shutil

    cache_dir = tempfile.mkdtemp(prefix="pftpu_exec_cache_")
    env = dict(os.environ)
    env.pop("PFTPU_EXEC_CACHE", None)  # the probe sets its own

    def run():
        out = subprocess.run(
            [sys.executable, probe, path, cache_dir],
            capture_output=True, text=True, timeout=600, env=env,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"exec-cache probe failed: {out.stderr[-2000:]}"
            )
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        cold = run()
        # two warm processes, best-of: the warm wall is dominated by
        # the executable deserialize, which is noisy under CI load —
        # best-of measures what the cache DOES (skip the compile), not
        # the host's scheduling jitter.  Both must hit; the report
        # check asserts it.
        warms = [run(), run()]
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    warm = min(warms, key=lambda w: w["first_group_wall_ms"])
    speedup = (
        cold["first_group_wall_ms"] / warm["first_group_wall_ms"]
        if warm["first_group_wall_ms"] else None
    )
    return {
        "exec_cache_cold_first_group_wall_ms": cold["first_group_wall_ms"],
        "exec_cache_warm_first_group_wall_ms": warm["first_group_wall_ms"],
        "exec_cache_warm_speedup_x": (
            round(speedup, 2) if speedup is not None else None
        ),
        "exec_cache_cold_compile_ms": cold["compile_ms"],
        "exec_cache_warm_compile_ms": max(
            w["compile_ms"] for w in warms
        ),
        "exec_cache_cold_misses": cold["exec_cache_misses"],
        "exec_cache_cold_hits": cold["exec_cache_hits"],
        "exec_cache_warm_hits": min(w["exec_cache_hits"] for w in warms),
        "exec_cache_warm_misses": max(
            w["exec_cache_misses"] for w in warms
        ),
        "exec_cache_warm_walls_ms": [
            w["first_group_wall_ms"] for w in warms
        ],
        "exec_cache_cold_launches": cold["launches"],
        "exec_cache_warm_launches": warm["launches"],
        "exec_cache_bit_identical": bool(
            all(cold["digest"] == w["digest"] for w in warms)
        ),
    }


def multichip_leg(n_rows: int) -> dict:
    """The multi-chip scan scheduler (docs/multichip.md): one
    subprocess (scripts/multichip_probe.py) runs a serial baseline, a
    single-device pipelined pass, and a mesh pass over the same file
    and reports walls, digests, scheduler counters, and the
    inflate-overlap fraction.  ``check_bench_report.py`` asserts
    bit-identical delivery, launches == groups == mesh-placed groups,
    overlap >= 0.5 (vs the ~0 serial baseline), and — only when
    ``multichip_gate_expected`` (a real accelerator mesh; the CPU
    forced devices share one socket) — mesh throughput >= 0.7*k the
    single-chip pass."""
    import subprocess

    import jax

    from benchmarks.workloads import write_lineitem

    per = max(min(n_rows, 20_000), 4_000)
    group = max(per // 8, 256)
    path = os.path.join("/tmp", f"pftpu_bench_multichip_{per}.parquet")
    if not os.path.exists(path):
        write_lineitem(path, per, row_group_rows=group, seed=5)
    probe = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts", "multichip_probe.py",
    )
    env = dict(os.environ)
    env.pop("PFTPU_MESH_DEVICES", None)   # the probe drives the knob
    env.pop("PFTPU_EXEC_CACHE", None)     # walls must include compiles
    platform = jax.devices()[0].platform
    if platform == "cpu":
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
    out = subprocess.run(
        [sys.executable, probe, path],
        capture_output=True, text=True, timeout=600, env=env,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"multichip probe failed: {out.stderr[-2000:]}"
        )
    r = json.loads(out.stdout.strip().splitlines()[-1])
    k = r["devices"]
    speedup = (
        r["wall_single_ms"] / r["wall_mesh_ms"]
        if r["wall_mesh_ms"] else None
    )
    return {
        "multichip_platform": r["platform"],
        "multichip_devices": k,
        "multichip_groups": r["groups"],
        "multichip_mesh_groups": r["mesh_groups"],
        "multichip_launches": r["launches"],
        "multichip_wall_serial_ms": r["wall_serial_ms"],
        "multichip_wall_single_ms": r["wall_single_ms"],
        "multichip_wall_mesh_ms": r["wall_mesh_ms"],
        "multichip_speedup_x": (
            round(speedup, 3) if speedup is not None else None
        ),
        "multichip_bit_identical": bool(r["bit_identical"]),
        "multichip_overlap_fraction": r["overlap_fraction"],
        "multichip_overlap_serial": r["overlap_serial"],
        "multichip_events_dropped": r["events_dropped"],
        # the >= 0.7*k throughput gate only means something on a real
        # accelerator mesh — forced host devices share one socket
        "multichip_gate_expected": bool(
            r["platform"] != "cpu" and k > 1
        ),
    }


def _remote_paths(n_rows: int, n_files: int = 4, groups: int = 8):
    """The cold-storage leg's dataset: more, smaller row groups than the
    scan leg's (32 units keep the overlap statistics stable at smoke
    scale), 3 columns so the sequential baseline's per-chunk reads stay
    affordable at a 20 ms RTT."""
    import numpy as np

    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types

    per = max(n_rows // n_files, 320)
    group = max(per // groups, 40)
    per = group * groups
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    paths = []
    for i in range(n_files):
        p = os.path.join("/tmp", f"pftpu_bench_remote_{per}_{i}.parquet")
        if not os.path.exists(p):
            rng = np.random.default_rng(100 + i)
            with ParquetFileWriter(p, schema, WriterOptions(
                row_group_rows=group, data_page_values=group,
            )) as w:
                for lo in range(0, per, group):
                    w.write_columns({
                        "k": np.arange(lo, lo + group, dtype=np.int64),
                        "s": [None if j % 13 == 0 else f"s{j % 97}"
                              for j in range(lo, lo + group)],
                        "d": rng.standard_normal(group),
                    })
        paths.append(p)
    return paths


def _digest_batch(batch) -> tuple:
    """Bit-level digest of one decoded host row group (values, string
    pools, null masks) — the remote leg's bit-identical check input."""
    import zlib

    import numpy as np

    out = []
    for c in batch.columns:
        v = c.values
        if hasattr(v, "offsets"):  # ByteArrayColumn
            out.append(zlib.crc32(np.ascontiguousarray(v.offsets).tobytes()))
            out.append(zlib.crc32(np.ascontiguousarray(v.data).tobytes()))
        else:
            out.append(zlib.crc32(np.ascontiguousarray(v).tobytes()))
        if c.def_levels is not None:
            out.append(zlib.crc32(
                np.ascontiguousarray(c.def_levels).tobytes()
            ))
    return (batch.num_rows, tuple(out))


def remote_leg(n_rows: int) -> dict:
    """Cold-storage truth bench (docs/remote.md): the scan scheduler
    over a SIMULATED 20 ms-RTT object store, where the overlap win
    ``docs/scan.md`` admits is invisible on a warm page cache finally
    shows — and is asserted (``check_bench_report.py``): the scheduled
    scan's ``overlap_fraction`` must clear 0.5 while the sequential
    per-file loop stays under 0.1.  A second, fault-heavy pass (drops +
    throttles + heavy-tail latency + an outage window, fixed seeds)
    must complete BIT-IDENTICAL to the clean pass with hedge/retry/
    breaker counters all exercised.

    Per-unit consumer work is a fixed 2.2 ms sleep — a stand-in for a
    training step sized well under one RTT, so the sequential loop's
    overlap stays honest while the scheduled scan has real work to
    overlap I/O against."""
    import time as _time

    from parquet_floor_tpu import ReaderOptions
    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.scan import DatasetScanner, ScanOptions
    from parquet_floor_tpu.testing import RemoteProfile, SimulatedRemoteSource
    from parquet_floor_tpu.utils import trace

    paths = _remote_paths(n_rows)
    RTT_S = 0.02
    WORK_S = 0.0022
    threads = 12
    clean = RemoteProfile(base_latency_s=RTT_S, jitter_s=0.002)
    # outage_s sized so the footer read's retry ladder (0.04 backoff,
    # doubling) eats 3+ consecutive failures per source before its
    # first success — the deterministic breaker-trip shape; the
    # throttle bucket is smaller than one group burst, so back-pressure
    # fires at scan start and retry_after-aware backoff recovers it
    hostile = RemoteProfile(
        base_latency_s=RTT_S, jitter_s=0.002,
        tail_p=0.15, tail_latency_s=0.08,
        fault_rate=0.05, outage_s=0.25,
        throttle_rps=60, throttle_burst=2,
    )

    def factories(profile, **kw):
        return [
            (lambda p=p, i=i: SimulatedRemoteSource(
                p, profile=profile, seed=1000 + i, fetch_threads=4, **kw
            ))
            for i, p in enumerate(paths)
        ]

    def scan_pass(profile, retries, **kw):
        sc = ScanOptions(threads=threads, adaptive_prefetch=True)
        opts = ReaderOptions(io_retries=retries, io_retry_backoff_s=0.04)
        digests = []
        with trace.scope() as t:
            t0 = _time.perf_counter()
            with DatasetScanner(
                factories(profile, **kw), options=opts, scan=sc
            ) as s:
                for unit in s:
                    digests.append(_digest_batch(unit.batch))
                    _time.sleep(WORK_S)  # the modeled consumer step
            wall = _time.perf_counter() - t0
        report = t.scan_report(wall_seconds=wall,
                               budget_bytes=sc.prefetch_bytes)
        return digests, report, wall

    def sequential_pass(profile):
        opts = ReaderOptions(io_retries=4, io_retry_backoff_s=0.04)
        digests = []
        with trace.scope() as t:
            t0 = _time.perf_counter()
            for f in factories(profile):
                t_open = _time.perf_counter()
                reader = ParquetFileReader(f(), options=opts)
                trace.add("scan.consumer_stall",
                          _time.perf_counter() - t_open)
                with reader as r:
                    for gi in range(len(r.row_groups)):
                        t_read = _time.perf_counter()
                        batch = r.read_row_group(gi)
                        # the sequential loop's stall: the consumer is
                        # blocked for the whole read+decode
                        trace.add("scan.consumer_stall",
                                  _time.perf_counter() - t_read)
                        digests.append(_digest_batch(batch))
                        _time.sleep(WORK_S)
            wall = _time.perf_counter() - t0
        report = t.scan_report(wall_seconds=wall)
        return digests, report, wall

    clean_digests, clean_rep, clean_wall = scan_pass(clean, retries=4)
    seq_digests, seq_rep, _seq_wall = sequential_pass(clean)
    fault_digests, fault_rep, _fault_wall = scan_pass(
        hostile, retries=6,
        hedge_delay_s=0.06, breaker_threshold=3, breaker_cooldown_s=0.06,
    )
    rows = sum(d[0] for d in clean_digests)
    fc = fault_rep.counters

    def p_ms(rep, name, p):
        return _hist_p_ms(rep.histogram(name), p)

    return {
        # tail-latency truth from the new histograms (docs/
        # observability.md): storage-read latency under the clean and
        # fault-heavy profiles, split by hedge outcome on the latter
        "remote_read_p50_ms": p_ms(
            clean_rep, "io.remote.get_seconds.primary", 50
        ),
        "remote_read_p99_ms": p_ms(
            clean_rep, "io.remote.get_seconds.primary", 99
        ),
        "remote_fault_read_p99_ms": p_ms(
            fault_rep, "io.remote.get_seconds.primary", 99
        ),
        "remote_rtt_ms": RTT_S * 1e3,
        "remote_files": len(paths),
        "remote_units": len(clean_digests),
        "remote_threads": threads,
        "remote_scan_rows_per_sec": round(rows / clean_wall, 1),
        "remote_overlap_fraction": clean_rep.overlap_fraction,
        "remote_seq_overlap_fraction": seq_rep.overlap_fraction,
        "remote_seq_bit_identical": bool(seq_digests == clean_digests),
        "remote_fault_bit_identical": bool(fault_digests == clean_digests),
        "remote_hedges": fc.get("io.remote.hedges", 0),
        "remote_retries": fc.get("io.retries", 0),
        "remote_breaker_trips": fc.get("io.remote.breaker_trips", 0),
        "remote_throttles": fc.get("io.remote.throttles", 0),
        "remote_scan_report": clean_rep.as_dict(),
        "remote_fault_scan_report": fault_rep.as_dict(),
    }


def _serving_paths(n_rows: int, n_files: int = 2):
    """The serving leg's keyed dataset: ascending disjoint int64 keys
    (EVEN values only, so absent odd keys inside a group's min/max range
    exercise the bloom rung), several pages per row group, bloom filters
    on the key — the point-lookup pruning ladder's full input."""
    import numpy as np

    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types

    per = max(n_rows // n_files, 512)
    group = max(per // 4, 128)
    page = max(group // 4, 32)
    per = group * 4
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    paths = []
    for i in range(n_files):
        p = os.path.join("/tmp", f"pftpu_bench_serving_{per}_{i}.parquet")
        if not os.path.exists(p):
            rng = np.random.default_rng(500 + i)
            with ParquetFileWriter(p, schema, WriterOptions(
                row_group_rows=group, data_page_values=page,
                bloom_filter_columns={"k": True},
            )) as w:
                for lo in range(0, per, group):
                    base = 2 * (i * per + lo)
                    w.write_columns({
                        "k": base + 2 * np.arange(group, dtype=np.int64),
                        "s": [None if j % 11 == 0 else f"s{j % 63}"
                              for j in range(group)],
                        "d": rng.standard_normal(group),
                    })
        paths.append(p)
    return paths, per, group, page


def serving_leg(n_rows: int) -> dict:
    """Multi-tenant serving bench (docs/serving.md), asserted by
    ``check_bench_report.check_serving_leg``:

    * two tenants scan the SAME dataset through one shared buffer cache
      — the second tenant's pass must be served mostly from memory
      (hit-rate >= 0.5, measured from ITS OWN report counters);
    * two tenants scanning concurrently get DISJOINT, correctly
      attributed reports (each sees exactly one scan's bytes);
    * a hot ``Dataset.lookup`` (metadata pinned, fresh key) reads at
      most one data page of file bytes for a one-column probe — the
      cache's storage-byte counters prove it;
    * the pruning ladder's stats and bloom rungs both fire;
    * a tenant over the seeded remote-storage simulator rides the same
      cache (cold pass populates, warm pass hits).
    """
    import threading as _threading

    from parquet_floor_tpu import ReaderOptions
    from parquet_floor_tpu.serve import Dataset, Serving, SharedBufferCache
    from parquet_floor_tpu.testing import RemoteProfile, SimulatedRemoteSource

    scan_paths = _scan_paths(n_rows)
    total_bytes = sum(os.path.getsize(p) for p in scan_paths)
    cache = SharedBufferCache(data_bytes=max(4 * total_bytes, 64 << 20))
    srv = Serving(cache=cache, prefetch_bytes=32 << 20)

    def hit_rate(report) -> float:
        hit = report.counters.get("serve.cache_hit_bytes", 0)
        miss = report.counters.get("serve.cache_miss_bytes", 0)
        return hit / (hit + miss) if hit + miss else 0.0

    def scan_rows(tenant):
        rows = 0
        with tenant.scan(scan_paths) as s:
            for unit in s:
                rows += unit.batch.num_rows
        return rows

    try:
        ta = srv.tenant("alpha", weight=2)
        tb = srv.tenant("beta", weight=1)
        rows_a = scan_rows(ta)       # cold: populates the shared cache
        rows_b = scan_rows(tb)       # warm: served from the shared tiers
        rep_a, rep_b = ta.report(), tb.report()

        # concurrent pass, fresh tenants: attribution must stay disjoint
        tc = srv.tenant("gamma")
        td = srv.tenant("delta")
        results: dict = {}

        def run(name, tenant):
            results[name] = scan_rows(tenant)

        threads = [
            _threading.Thread(target=run, args=("c", tc)),
            _threading.Thread(target=run, args=("d", td)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rep_c, rep_d = tc.report(), td.report()
        used = rep_a.counters.get("scan.bytes_used", 0)
        disjoint = (
            results["c"] == rows_a and results["d"] == rows_a
            and rep_c.counters.get("scan.bytes_used", 0) == used
            and rep_d.counters.get("scan.bytes_used", 0) == used
        )
        sf_waits = cache.stats()["singleflight_waits"]
    finally:
        # the cache was passed in, so the context leaves it open; the
        # lookup section below closes it once the stats are captured
        srv.close()

    # -- point-lookup byte-cost proof (its own cache: the scans above
    # must not have pre-populated the probe pages) -----------------------
    lk_paths, per, group, page_rows = _serving_paths(n_rows)
    lk_cache = SharedBufferCache()
    detail: dict = {}
    with Dataset(lk_paths, "k", cache=lk_cache) as ds:
        from parquet_floor_tpu.utils import trace as _trace

        with _trace.scope() as lt:
            # warm pass, NO limit: every file opens and pins its probe
            # metadata, so the hot probe below pays pages only
            ds.lookup(0)
            page_bound = ds.page_size_bound()
            s0 = lk_cache.stats()
            # a key in a DIFFERENT page (second file, last group, last
            # page): metadata is hot, exactly one cold page per column
            hot_key = 2 * (2 * per - 1)
            hot_rows = ds.lookup(hot_key, columns=["k"])
            s1 = lk_cache.stats()
            # absent ODD keys inside group ranges: stats keep the group,
            # the bloom filter must kill it (deterministic for the fixed
            # seed; scan a few keys so one unlucky false positive cannot
            # starve the assertion)
            bloom0 = lt.counters().get("serve.lookup_bloom_skips", 0)
            probes = 0
            for off in range(1, 99, 2):
                probes += 1
                ds.lookup(off, limit=1)
                if lt.counters().get(
                    "serve.lookup_bloom_skips", 0
                ) > bloom0:
                    break
            lc = lt.counters()
            lh = lt.histograms()
        lk_hist = lh.get("serve.lookup_seconds")
        rd_hist = lh.get("io.read_seconds.file")
        detail.update({
            # the probe-latency distribution (every lookup above lands
            # in the scope's histogram), plus the storage-read split —
            # check_bench_report asserts the well-formedness law
            "serving_lookup_hist": (
                lk_hist.as_dict() if lk_hist is not None else None
            ),
            "serving_lookup_p50_ms": _hist_p_ms(lk_hist, 50),
            "serving_lookup_p99_ms": _hist_p_ms(lk_hist, 99),
            "serving_storage_read_hist": (
                rd_hist.as_dict() if rd_hist is not None else None
            ),
            "serving_lookup_rows": len(hot_rows),
            "serving_lookup_storage_bytes": (
                s1["miss_bytes"] - s0["miss_bytes"]
            ),
            "serving_lookup_page_bound": page_bound,
            "serving_lookup_bloom_skips": lc.get(
                "serve.lookup_bloom_skips", 0
            ),
            "serving_lookup_groups_pruned": lc.get(
                "serve.lookup_groups_pruned", 0
            ),
            "serving_lookup_pages_read": lc.get("serve.lookup_pages_read", 0),
            "serving_lookup_bloom_probes": probes,
        })
    lk_cache.close()
    cache.close()

    # -- the remote face: a tenant over the simulator, same cache law ----
    rm_cache = SharedBufferCache()
    rm = Serving(cache=rm_cache, prefetch_bytes=8 << 20)
    try:
        profile = RemoteProfile(base_latency_s=0.002, jitter_s=0.0005)
        factories = [
            (lambda p=p, i=i: SimulatedRemoteSource(
                p, profile=profile, seed=2000 + i, fetch_threads=4
            ))
            for i, p in enumerate(lk_paths)
        ]
        tr1 = rm.tenant("remote-cold")
        tr2 = rm.tenant("remote-warm")
        opts = ReaderOptions(io_retries=2, io_retry_backoff_s=0.01)
        rows_cold = 0
        with tr1.scan(factories, options=opts) as s:
            for unit in s:
                rows_cold += unit.batch.num_rows
        rows_warm = 0
        with tr2.scan(factories, options=opts) as s:
            for unit in s:
                rows_warm += unit.batch.num_rows
        remote_warm_rate = hit_rate(tr2.report())
    finally:
        rm.close()
        rm_cache.close()

    detail.update({
        "serving_rows": rows_a,
        "serving_second_rows": rows_b,
        "serving_hit_rate_first_pass": round(hit_rate(rep_a), 4),
        "serving_hit_rate_second_pass": round(hit_rate(rep_b), 4),
        "serving_tenants_disjoint": bool(disjoint),
        "serving_singleflight_waits": sf_waits,
        "serving_remote_rows": rows_warm if rows_warm == rows_cold else -1,
        "serving_remote_warm_hit_rate": round(remote_warm_rate, 4),
        "serving_report": rep_b.as_dict(),
    })
    return detail


def _traffic_worker_pass(paths, shards, profile_kwargs, seed0: int) -> dict:
    """One multi-worker scaling pass: a fresh ShmCacheTier, one
    ``scripts/serve_worker.py`` subprocess per shard over the seeded
    remote simulator, file-barrier start, per-worker walls from inside
    the timed probe loops."""
    import json as _json
    import pathlib
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    from parquet_floor_tpu.serve import ShmCacheTier

    worker_script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts",
        "serve_worker.py",
    )
    tmp = tempfile.mkdtemp(prefix="pftpu_traffic_")
    try:
        with ShmCacheTier.create(data_bytes=64 << 20,
                                 meta_bytes=16 << 20) as tier:
            go = os.path.join(tmp, "go")
            procs = []
            for wi, shard in enumerate(shards):
                cfg = {
                    "mode": "scale",
                    "shm": tier.name,
                    "paths": paths,
                    "warm_keys": shard[:1],
                    "keys": shard[1:],
                    "columns": ["k"],
                    "tenant": f"scale-{wi}",
                    "seed": seed0 + 100 * wi,
                    "remote": profile_kwargs,
                    "ready_file": os.path.join(tmp, f"ready-{wi}"),
                    "go_file": go,
                }
                cfg_path = os.path.join(tmp, f"cfg-{wi}.json")
                pathlib.Path(cfg_path).write_text(_json.dumps(cfg))
                procs.append(subprocess.Popen(
                    [_sys.executable, worker_script, cfg_path],
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                ))
            deadline = time.monotonic() + 300.0
            while not all(
                os.path.exists(os.path.join(tmp, f"ready-{wi}"))
                for wi in range(len(shards))
            ):
                if time.monotonic() > deadline:
                    for p in procs:
                        p.kill()
                    raise TimeoutError("traffic workers never all readied")
                time.sleep(0.01)
            pathlib.Path(go).touch()
            results = []
            for wi, p in enumerate(procs):
                out, err = p.communicate(timeout=300)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"traffic worker {wi} failed rc={p.returncode}:\n"
                        f"{err.decode()[-2000:]}"
                    )
                results.append(_json.loads(out.decode().splitlines()[-1]))
            shm = tier.stats()
        probes = sum(r["probes"] for r in results)
        wall = max(r["wall"] for r in results)
        return {
            "workers": len(shards),
            "probes": probes,
            "wall": wall,
            "rps": probes / wall if wall > 0 else 0.0,
            "rows": sum(r["rows"] for r in results),
            "shm_singleflight_waits": shm["singleflight_waits"],
            "shm_hits": shm["hits"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def traffic_leg(n_rows: int) -> dict:
    """The production-traffic truth bench (docs/serving.md), gated by
    ``check_bench_report.check_traffic_leg`` — the tail-latency metric
    a millions-of-users tier actually lives by, in three seeded passes:

    * **multi-worker scaling** — 1 vs 4 worker PROCESSES over one
      shared ``ShmCacheTier`` and the seeded remote simulator
      (latency-bound storage, the production regime): aggregate lookup
      throughput at 4 workers must reach >= 2.5x one worker;
    * **zipf open-loop** — Poisson arrivals at a fixed rate, zipf key
      popularity, weight-skewed tenants, over the
      ``SimulatedRemoteSource`` fault domain (transient faults +
      retries live): per-request latency measured from SCHEDULED
      arrival (queueing included — open-loop truth, not closed-loop
      flattery), p99 must hold the recorded SLO target;
    * **device-time fairness** — a 100%-cache-hit tenant offering 3x a
      light tenant's load through a 1-lane device WFQ gate must be held
      to its WEIGHT share of engine time (equal weights here: 0.5
      each), within the recorded band — storage bytes it never touches
      cannot buy it the decode engine.
    """
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from parquet_floor_tpu import ReaderOptions
    from parquet_floor_tpu.serve import Dataset, Serving
    from parquet_floor_tpu.testing import RemoteProfile, SimulatedRemoteSource
    from parquet_floor_tpu.utils.histogram import LogHistogram

    paths, per, group, page = _serving_paths(n_rows)
    n_files = len(paths)
    # one key per data page, spread across files and groups
    keys = [
        2 * (f * per + g * group + off)
        for f in range(n_files)
        for g in range(per // group)
        for off in range(page // 2, group, page)
    ]

    # -- pass 1: multi-worker scaling over the shm tier ---------------------
    profile_kwargs = {"base_latency_s": 0.015, "jitter_s": 0.002}
    one = _traffic_worker_pass(paths, [keys], profile_kwargs, seed0=9000)
    n_workers = 4
    shards = [keys[i::n_workers] for i in range(n_workers)]
    many = _traffic_worker_pass(paths, shards, profile_kwargs, seed0=9500)
    scaling_x = many["rps"] / one["rps"] if one["rps"] else 0.0

    # -- pass 2: zipf open-loop Poisson over the fault domain ---------------
    rate_rps = float(os.environ.get("PFTPU_BENCH_TRAFFIC_RPS", 120.0))
    duration_s = float(os.environ.get("PFTPU_BENCH_TRAFFIC_S", 3.0))
    slo_p99_s = float(os.environ.get("PFTPU_BENCH_TRAFFIC_SLO_S", 0.25))
    zipf_a = 1.4
    rng = np.random.default_rng(424242)
    profile = RemoteProfile(base_latency_s=0.006, jitter_s=0.002,
                            tail_p=0.02, tail_latency_s=0.02,
                            fault_rate=0.01)
    factories = [
        (lambda p=p, i=i: SimulatedRemoteSource(
            p, profile=profile, seed=7700 + i, fetch_threads=4
        ))
        for i, p in enumerate(paths)
    ]
    tenant_weights = {"gold": 2.0, "silver": 1.0, "bronze": 1.0}
    w_total = sum(tenant_weights.values())
    tnames = sorted(tenant_weights)
    tprobs = np.array([tenant_weights[t] for t in tnames]) / w_total
    n_req = max(int(rate_rps * duration_s), 50)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_req))
    req_tenants = rng.choice(len(tnames), size=n_req, p=tprobs)
    ranks = rng.zipf(zipf_a, size=n_req)
    req_keys = [keys[int(r) % len(keys)] for r in ranks]
    hists = {t: LogHistogram() for t in tnames}
    agg_hist = LogHistogram()
    hist_lock = _threading.Lock()
    with Serving(prefetch_bytes=32 << 20, device_lanes=2) as srv:
        tenants = {t: srv.tenant(t, w) for t, w in tenant_weights.items()}
        with Dataset(
            factories, "k",
            options=ReaderOptions(io_retries=3, io_retry_backoff_s=0.005),
        ) as ds:
            ds.lookup(keys[0])   # open files, pin metadata (untimed)

            def fire(t_sched, tenant_name, key):
                ds.lookup(key, columns=["k"],
                          tenant=tenants[tenant_name])
                lat = time.perf_counter() - t_sched
                with hist_lock:
                    hists[tenant_name].record(lat)
                    agg_hist.record(lat)

            with ThreadPoolExecutor(max_workers=24) as pool:
                t0 = time.perf_counter()
                futs = []
                for i in range(n_req):
                    t_sched = t0 + float(arrivals[i])
                    delay = t_sched - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    # open loop: submitted at the SCHEDULED time, never
                    # held back by completions; latency counts from the
                    # schedule, so queueing is in the number
                    futs.append(pool.submit(
                        fire, t_sched, tnames[int(req_tenants[i])],
                        req_keys[i],
                    ))
                for f in futs:
                    f.result()
        retries = sum(
            t.tracer.counters().get("io.retries", 0)
            for t in tenants.values()
        )
    p99_s = agg_hist.percentile(99)
    open_loop = {
        "requests": n_req,
        "rate_rps": rate_rps,
        "zipf_a": zipf_a,
        "p50_ms": round(agg_hist.percentile(50) * 1e3, 3),
        "p99_ms": round(p99_s * 1e3, 3),
        "slo_p99_ms": slo_p99_s * 1e3,
        "slo_ok": bool(p99_s <= slo_p99_s),
        "retries": retries,
        "tenant_p99_ms": {
            t: round(hists[t].percentile(99) * 1e3, 3) for t in tnames
        },
        "hist": agg_hist.as_dict(),
    }

    # -- pass 3: device-time fairness under a cache-hot aggressor -----------
    # same workload twice: once effectively UNGATED (8 lanes — more
    # than the threads can fill, sessions only measure) and once
    # through the 1-lane WFQ gate.  The aggressor (3x the light
    # tenant's threads, equal weights, everything cache-hot) must
    # exceed its weight share without the gate and be held to it with.
    fair_s = float(os.environ.get("PFTPU_BENCH_FAIR_S", 2.0))
    fair_band = 0.12

    def fair_pass(lanes: int) -> dict:
        with Serving(prefetch_bytes=32 << 20, device_lanes=lanes) as srv:
            hot = srv.tenant("hot", weight=1.0)
            light = srv.tenant("light", weight=1.0)
            with Dataset(paths, "k", cache=srv.cache) as ds:
                for k in keys:   # warm the EXACT probe shape: cache-hot
                    ds.range(k, k + 2 * page, columns=["k"])
                t_end = time.perf_counter() + fair_s

                def hammer(tenant):
                    i = 0
                    while time.perf_counter() < t_end:
                        # a 2-page range per probe: device work heavy
                        # enough that both tenants stay backlogged at
                        # the gate (the WFQ guarantee's precondition)
                        k = keys[i % len(keys)]
                        ds.range(k, k + 2 * page, columns=["k"],
                                 tenant=tenant)
                        i += 1

                threads = [
                    _threading.Thread(target=hammer, args=(hot,))
                    for _ in range(6)
                ] + [
                    _threading.Thread(target=hammer, args=(light,))
                    for _ in range(2)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            hot_s = hot.tracer.histograms()["serve.device_seconds"].total
            light_s = (
                light.tracer.histograms()["serve.device_seconds"].total
            )
            hc = hot.tracer.counters()
            hb = hc.get("serve.cache_hit_bytes", 0)
            mb = hc.get("serve.cache_miss_bytes", 0)
            return {
                "share": hot_s / (hot_s + light_s),
                "waits": (
                    hc.get("serve.device_waits", 0)
                    + light.tracer.counters().get("serve.device_waits", 0)
                ),
                "hit_rate_hot": hb / (hb + mb) if hb + mb else 0.0,
            }

    ungated = fair_pass(lanes=8)
    gated = fair_pass(lanes=1)

    return {
        "traffic_worker1_rps": round(one["rps"], 1),
        "traffic_workers": many["workers"],
        "traffic_workers_rps": round(many["rps"], 1),
        "traffic_scaling_x": round(scaling_x, 3),
        "traffic_shm_singleflight_waits": many["shm_singleflight_waits"],
        "traffic_requests": open_loop["requests"],
        "traffic_rate_rps": open_loop["rate_rps"],
        "traffic_zipf_a": open_loop["zipf_a"],
        "traffic_p50_ms": open_loop["p50_ms"],
        "traffic_p99_ms": open_loop["p99_ms"],
        "traffic_slo_p99_ms": open_loop["slo_p99_ms"],
        "traffic_slo_ok": open_loop["slo_ok"],
        "traffic_retries": open_loop["retries"],
        "traffic_tenant_p99_ms": open_loop["tenant_p99_ms"],
        "traffic_hist": open_loop["hist"],
        "traffic_fair_share_hot": round(gated["share"], 4),
        "traffic_fair_share_hot_ungated": round(ungated["share"], 4),
        "traffic_fair_ideal": 0.5,
        "traffic_fairness_err": round(abs(gated["share"] - 0.5), 4),
        "traffic_fair_band": fair_band,
        "traffic_fair_device_waits": gated["waits"],
        "traffic_fair_hot_hit_rate": round(gated["hit_rate_hot"], 4),
    }


def fleet_leg(n_rows: int) -> dict:
    """The fleet-survivability truth bench (docs/serving.md), gated by
    ``check_bench_report.check_fleet_leg`` — k serving daemons as ONE
    cache tier, driven over a COUNTED origin in two passes:

    * **exactly-once** — every node reads every unique range through
      its :class:`FleetCache`; fleet-wide origin reads must stay
      within 1.25x the unique-range count (non-primaries peer-fetch
      the owner instead of re-reading origin), with the peer leg and
      hot-range replication actually exercised;
    * **host-loss chaos** — one daemon dies MID-LOAD with the old
      membership still installed: every request must still answer
      byte-correct (a dead owner degrades to origin fallback, never an
      error), a stale-epoch asker must be FENCED (and itself degrade
      to origin, correctly), and p99 measured across the whole ordeal
      — failover, fence window, epoch-bumped reinstall — must hold the
      recorded SLO.

    Every request runs under a distributed trace
    (docs/observability.md), and the chaos pass doubles as the flight
    recorder's truth test, gated by ``check_fleet_trace``: the breaker
    trips / epoch fences it provokes must auto-produce an incident
    bundle whose merged timeline holds at least one request crossing
    two daemons with closed parent links and time-ordered tracks.
    """
    import pathlib as _pathlib
    import shutil as _shutil
    import tempfile as _tempfile
    import threading as _threading

    from parquet_floor_tpu.serve import (
        FleetCache,
        FleetMembership,
        PeerClient,
        ServeDaemon,
        Serving,
    )
    from parquet_floor_tpu.utils import trace as _trace
    from parquet_floor_tpu.utils.histogram import LogHistogram

    slo_p99_s = float(os.environ.get("PFTPU_BENCH_FLEET_SLO_S", 0.25))
    origin_latency_s = 0.004
    origin_lock = _threading.Lock()
    origin_counts: dict = {}

    def content(offset: int, length: int) -> bytes:
        pat = f"fleet:{offset}:{length}:".encode("ascii")
        return (pat * (length // len(pat) + 1))[:length]

    def origin_read(key, ranges):
        with origin_lock:
            for (o, n) in ranges:
                origin_counts[(o, n)] = origin_counts.get((o, n), 0) + 1
        time.sleep(origin_latency_s)  # the modeled storage RTT
        return [content(o, n) for (o, n) in ranges]

    node_ids = ["n0", "n1", "n2"]
    membership = FleetMembership.create(node_ids)
    key = ("bench-fleet", 1 << 20)
    servings, fleets, daemons = [], [], []
    client_tracers = {
        nid: _trace.Tracer(enabled=True) for nid in node_ids
    }
    metrics_dir = _tempfile.mkdtemp(prefix="pftpu-bench-fleet-metrics-")
    flight_dir = _tempfile.mkdtemp(prefix="pftpu-bench-fleet-flight-")
    try:
        for nid in node_ids:
            srv = Serving(prefetch_bytes=8 << 20)
            fc = FleetCache(
                nid, membership, origin=origin_read,
                peer_timeout_s=1.0, breaker_threshold=2,
                breaker_cooldown_s=0.2,
            )
            d = ServeDaemon(
                srv, {}, fleet=fc, max_inflight=4, max_pending=64,
                drain_timeout_s=2.0,
                metrics_dir=metrics_dir, flight_dir=flight_dir,
            ).start()
            servings.append(srv)
            fleets.append(fc)
            daemons.append(d)
        daemon_by = dict(zip(node_ids, daemons))
        peers = {
            nid: ("127.0.0.1", d.port)
            for nid, d in zip(node_ids, daemons)
        }
        for fc in fleets:
            fc.install_membership(membership, peers)

        def fold(counter: str) -> int:
            return sum(
                tr.counters().get(counter, 0)
                for tr in list(client_tracers.values())
                + [d.tracer for d in daemons]
            )

        # -- pass A: fleet-wide exactly-once origin reads -------------------
        ranges_a = [(i * 8192, 1536) for i in range(48)]
        wrong = 0
        for nid, fc in zip(node_ids, fleets):
            # the whole pass is one distributed request: its peer hops
            # land daemon-side spans in the owners' flight rings, so
            # the chaos pass's incident bundle has a cross-daemon
            # chain to show
            with _trace.using(client_tracers[nid]), \
                    _trace.use_flight_recorder(daemon_by[nid]._flight), \
                    _trace.start_trace("fleet_bench",
                                       attrs={"node": nid, "leg": "a"}):
                got = fc.read_through(
                    key, ranges_a, lambda rs: origin_read(key, rs))
            for (o, n), data in zip(ranges_a, got):
                if data != content(o, n):
                    wrong += 1
        with origin_lock:
            a_reads = sum(origin_counts.values())
        ratio = a_reads / len(ranges_a)

        # -- pass B: host-loss chaos ----------------------------------------
        base_b = 1 << 22
        ranges_b = [(base_b + i * 8192, 1536) for i in range(48)]
        survivors = [(node_ids[i], fleets[i]) for i in (0, 1)]
        hist = LogHistogram()
        chaos_requests = 0
        chaos_errors = 0
        killed = _threading.Event()

        def kill_victim():
            # mid-load host loss: drain answers in-flight peers, then
            # the port goes dead — askers see refusals, then
            # connection errors, and must degrade to origin
            daemons[2].close()
            fleets[2].close()
            killed.set()

        def chaos_read(nid, fc, o, n):
            nonlocal chaos_requests, chaos_errors
            chaos_requests += 1
            t0 = time.perf_counter()
            try:
                with _trace.using(client_tracers[nid]), \
                        _trace.use_flight_recorder(
                            daemon_by[nid]._flight), \
                        _trace.start_trace("fleet_chaos",
                                           attrs={"node": nid}):
                    data = fc.read_through(
                        key, [(o, n)], lambda rs: origin_read(key, rs))[0]
            except Exception:
                chaos_errors += 1
                hist.record(time.perf_counter() - t0)
                return 1
            hist.record(time.perf_counter() - t0)
            return 0 if data == content(o, n) else 1

        killer = None
        for i, (o, n) in enumerate(ranges_b):
            if i == len(ranges_b) // 3 and killer is None:
                killer = _threading.Thread(target=kill_victim)
                killer.start()
            nid, fc = survivors[i % 2]
            wrong += chaos_read(nid, fc, o, n)
        killer.join()
        # the victim is gone but epoch 1 is still installed: a full
        # re-read must survive dead-owner fetches via origin fallback
        for i, (o, n) in enumerate(ranges_b):
            nid, fc = survivors[(i + 1) % 2]
            wrong += chaos_read(nid, fc, o, n)
        # explicit fence probe: a stale-epoch asker must be refused
        with PeerClient("127.0.0.1", daemons[0].port) as probe:
            reply = probe.fetch(key, ranges_b[0][0], ranges_b[0][1],
                                epoch=999)
        fence_refused = (not reply.get("ok")
                         and reply.get("code") == "stale_epoch")
        # epoch-bumped reinstall, one survivor at a time: in the
        # window where n0 is on epoch 2 and n1 still on 1, n0's peer
        # fetches are FENCED and must degrade to origin — correctly
        new_membership = membership.without("n2")
        new_peers = {nid: peers[nid] for nid in new_membership.members}
        fleets[0].install_membership(new_membership, new_peers)
        base_c = 1 << 24
        ranges_c = [(base_c + i * 8192, 1536) for i in range(12)]
        for (o, n) in ranges_c[:6]:
            wrong += chaos_read("n0", fleets[0], o, n)
        fleets[1].install_membership(new_membership, new_peers)
        for i, (o, n) in enumerate(ranges_c):
            nid, fc = survivors[i % 2]
            wrong += chaos_read(nid, fc, o, n)
        p99_s = hist.percentile(99)

        # -- the flight-recorder truth check --------------------------------
        # chaos MUST have fired the recorder (breaker trips on the dead
        # host, fences in the reinstall window); the best bundle's
        # merged timeline is the one check_fleet_trace gates on
        bundles = sorted(_pathlib.Path(flight_dir).glob("incident-*"))
        ft = {
            "span_events": 0, "cross_node_traces": [],
            "trace_nodes": {}, "parent_links_ok": False,
            "monotonic_ok": False, "balanced_ok": False, "ok": False,
        }
        ft_offsets: dict = {}
        for b in bundles:
            try:
                tl = json.loads((b / "timeline.json").read_text())
            except (OSError, ValueError):
                continue
            v = _trace.verify_fleet_timeline(tl)
            better = (
                (len(v["cross_node_traces"]) > 0, v["ok"],
                 v["span_events"])
                > (len(ft["cross_node_traces"]) > 0, ft["ok"],
                   ft["span_events"])
            )
            if better:
                ft = v
                ft_offsets = tl.get("clock_offsets_s") or {}
        cross_max_nodes = max(
            (len(ft["trace_nodes"][t]) for t in ft["cross_node_traces"]),
            default=0,
        )

        return {
            "fleet_nodes": len(node_ids),
            "fleet_unique_ranges": len(ranges_a),
            "fleet_requests": len(node_ids) * len(ranges_a),
            "fleet_origin_reads": a_reads,
            "fleet_origin_ratio": round(ratio, 3),
            "fleet_origin_ratio_max": 1.25,
            "fleet_exactly_once_ok": bool(ratio <= 1.25),
            "fleet_peer_hits": fold("serve.fleet_peer_hits"),
            "fleet_replications": fold("serve.fleet_replications"),
            "fleet_peer_fallbacks": fold("serve.fleet_peer_fallbacks"),
            "fleet_fenced": fold("serve.fleet_epoch_fenced"),
            "fleet_fence_refused": fence_refused,
            "fleet_breaker_trips": fold("io.remote.breaker_trips"),
            "fleet_wrong": wrong,
            "fleet_chaos_requests": chaos_requests,
            "fleet_chaos_errors": chaos_errors,
            "fleet_chaos_p99_ms": round(p99_s * 1e3, 3),
            "fleet_chaos_slo_ms": slo_p99_s * 1e3,
            "fleet_chaos_slo_ok": bool(p99_s <= slo_p99_s),
            "fleet_chaos_hist": hist.as_dict(),
            "fleet_flight_bundles": len(bundles),
            "fleet_trace_span_events": ft["span_events"],
            "fleet_trace_cross_traces": len(ft["cross_node_traces"]),
            "fleet_trace_cross_max_nodes": cross_max_nodes,
            "fleet_trace_parent_links_ok": bool(ft["parent_links_ok"]),
            "fleet_trace_monotonic_ok": bool(ft["monotonic_ok"]),
            "fleet_trace_balanced_ok": bool(ft["balanced_ok"]),
            "fleet_trace_clock_offsets": ft_offsets,
            "fleet_trace_ok": bool(
                bundles and ft["ok"] and ft["cross_node_traces"]
            ),
        }
    finally:
        for d in daemons:
            d.close()  # idempotent — the chaos victim is already down
        for fc in fleets:
            fc.close()
        for srv in servings:
            srv.close()
        _shutil.rmtree(metrics_dir, ignore_errors=True)
        _shutil.rmtree(flight_dir, ignore_errors=True)


def write_leg(n_rows: int, reps: int) -> dict:
    """Device write path (docs/write.md), gated by
    ``check_bench_report.check_write_leg``: the fused encode engine
    writes the lineitem workload — dictionary build + index pack on
    device, host compression pipelined behind — and the recorded
    ``write_rows_per_sec`` must hold a floor of 0.25x the decode leg's
    ``scan_rows_per_sec`` (the acceptance ratio rides the bench JSON as
    ``write_vs_scan_x``).  A counted pass pins the two-launch-per-group
    shape and a read-back pass pins value exactness."""
    import numpy as np

    from benchmarks.workloads import lineitem_columns, lineitem_schema
    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.format.file_write import WriterOptions
    from parquet_floor_tpu.format.parquet_thrift import CompressionCodec
    from parquet_floor_tpu.utils import trace
    from parquet_floor_tpu.write import DeviceFileWriter

    schema = lineitem_schema()
    groups = 4
    per = max(n_rows // groups, 500)
    cols = lineitem_columns(per, seed=11)
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY, page_version=2,
        data_page_values=50_000, engine="tpu",
    )

    def run(idx) -> str:
        p = os.path.join("/tmp", f"pftpu_bench_write_{idx}.parquet")
        with DeviceFileWriter(p, schema, opts) as w:
            for _ in range(groups):
                w.write_columns(cols)
        return p

    path = run("warm")  # compiles the encode executables
    best = float("inf")
    for r in range(max(reps, 3)):
        t0 = time.perf_counter()
        run(r)
        best = min(best, time.perf_counter() - t0)
    rows = groups * per

    with trace.scope() as t:
        run("counted")
    counters = t.metrics()

    # value exactness: the written file reads back equal to the source
    # columns through our own reader (the pyarrow differential is the
    # test suite's job — tests/test_write.py)
    exact = True
    with ParquetFileReader(path) as r:
        for gi in range(groups):
            batch = r.read_row_group(gi)
            by = {c.descriptor.path[0]: c for c in batch.columns}
            for name, want in cols.items():
                got = by[name].values
                if hasattr(got, "to_list"):
                    from parquet_floor_tpu.format.encodings.plain import (
                        ByteArrayColumn,
                    )

                    if isinstance(want, ByteArrayColumn):
                        ok = got == want
                    else:
                        enc = [
                            v.encode() if isinstance(v, str) else v
                            for v in want
                            if v is not None
                        ]
                        ok = got.to_list() == enc
                else:
                    w_arr = np.asarray(
                        [v for v in want if v is not None]
                        if isinstance(want, list) else want
                    )
                    g_arr = np.asarray(got)
                    if g_arr.dtype.kind == "f":
                        ok = np.array_equal(
                            g_arr.view(np.uint64 if g_arr.itemsize == 8
                                       else np.uint32),
                            w_arr.astype(g_arr.dtype).view(
                                np.uint64 if g_arr.itemsize == 8
                                else np.uint32
                            ),
                        )
                    else:
                        ok = np.array_equal(g_arr, w_arr.astype(g_arr.dtype))
                if not ok:
                    exact = False

    return {
        "write_rows_per_sec": round(rows / best, 1),
        "write_rows": rows,
        "write_groups": counters.get("write.groups", 0),
        "write_launches": counters.get("write.launches", 0),
        "write_device_columns": counters.get("write.device_columns", 0),
        "write_host_columns": counters.get("write.host_columns", 0),
        "write_bytes_written": counters.get("write.bytes_written", 0),
        "write_exact": bool(exact),
    }


def compact_leg(n_rows: int, reps: int) -> dict:
    """Dataset compaction (docs/write.md), gated by
    ``check_bench_report.check_compact_leg``: re-shard the scan leg's
    4-file dataset into consolidated row groups at the configured
    target.  The floor — compaction ≥ 0.5x scan speed — compares
    against a device-scan pass over the SAME corpus timed INTERLEAVED
    rep-by-rep (one machine condition, the loader leg's comparator
    discipline), and the output group sizes must sit exactly in the
    target band."""
    import shutil

    import jax
    import numpy as np

    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.format.file_write import WriterOptions
    from parquet_floor_tpu.scan import ScanOptions, scan_device_groups
    from parquet_floor_tpu.utils import trace
    from parquet_floor_tpu.write import CompactOptions, DatasetCompactor

    paths = _scan_paths(n_rows)
    total = 0
    for p in paths:
        with ParquetFileReader(p) as r:
            total += r.record_count
    target = max(total // 2, 500)
    copts = CompactOptions(
        target_row_group_rows=target,
        read_leg="host",
        scan=ScanOptions(threads=8),
        # engine="auto": the fused encode launches on a real
        # accelerator, the pooled pipelined host encoder on the CPU
        # backend (resolve_writer's cost-model routing)
        writer=WriterOptions(
            engine="auto", compress_threads=8, write_pipeline_depth=3,
        ),
    )

    def compact(idx):
        out = os.path.join("/tmp", f"pftpu_bench_compact_{idx}")
        shutil.rmtree(out, ignore_errors=True)
        return DatasetCompactor(paths, out, copts).run()

    def scan_pass():
        rows = 0
        for _fi, _gi, cols in scan_device_groups(
            paths, scan=ScanOptions(threads=min(4, os.cpu_count() or 1)),
            float64_policy="bits",
        ):
            jax.block_until_ready([c.values for c in cols.values()])
            rows += int(next(iter(cols.values())).values.shape[0])
        return rows

    rep0 = compact("warm")
    scan_pass()
    best_c = float("inf")
    best_s = float("inf")
    for r in range(max(reps, 4)):
        t0 = time.perf_counter()
        scan_pass()
        best_s = min(best_s, time.perf_counter() - t0)
        t0 = time.perf_counter()
        compact(r)
        best_c = min(best_c, time.perf_counter() - t0)

    with trace.scope() as t:
        compact("counted")
    counters = t.metrics()

    # value exactness: output equals input in delivery order through
    # our own reader (no D2H — host read both sides)
    def read_rows(ps, name="l_quantity"):
        out = []
        for p in ps:
            with ParquetFileReader(p) as r:
                for gi in range(len(r.row_groups)):
                    cb = r.read_row_group(gi, {name})
                    out.append(np.asarray(cb.columns[0].values))
        return np.concatenate(out)

    exact = bool(np.array_equal(
        read_rows(paths), read_rows(rep0.paths)
    ))

    c_rps = rep0.rows_in / best_c
    s_rps = rep0.rows_in / best_s
    return {
        "compact_rows_per_sec": round(c_rps, 1),
        "compact_scan_rows_per_sec": round(s_rps, 1),
        "compact_vs_scan_x": round(c_rps / s_rps, 3),
        "compact_rows": rep0.rows_in,
        "compact_target_group_rows": target,
        "compact_group_rows": list(rep0.group_rows),
        "compact_files_out": len(rep0.paths),
        "compact_units_in": counters.get("compact.units_in", 0),
        "compact_groups_out": counters.get("compact.groups_out", 0),
        "compact_exact": exact,
    }


def query_leg(n_rows: int, reps: int) -> dict:
    """The query subsystem (docs/query.md), gated by
    ``check_bench_report.check_query_leg``: three floors on one pair of
    sort-compacted corpora.  (1) A full sorted-merge join must run at
    >= 0.5x the two-scan lower bound — reading BOTH corpora through the
    same row-materializing face the join uses, timed INTERLEAVED
    rep-by-rep (one machine condition).  (2) A point probe on a
    NON-sort column through an installed secondary index must cost at
    most ONE data page of cold storage bytes (``page_size_bound``),
    and an absent key must cost ZERO.  (3) An expression projection
    through the fused device scan must be BIT-equal to
    ``pyarrow.compute`` over the same arrays at <= 1 launch per row
    group."""
    import shutil

    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    from parquet_floor_tpu import (
        ParquetFileWriter, ParquetReader, WriterOptions, types,
    )
    from parquet_floor_tpu.api.hydrate import (
        HydratorSupplier, dict_hydrator,
    )
    from parquet_floor_tpu.query import qcol, sorted_merge_join
    from parquet_floor_tpu.query.index import SecondaryIndex
    from parquet_floor_tpu.scan import ScanOptions
    from parquet_floor_tpu.serve import Dataset, SharedBufferCache
    from parquet_floor_tpu.utils import trace
    from parquet_floor_tpu.write import CompactOptions, DatasetCompactor

    # corpora sized as a slice of the bench scale: the join is a
    # host-row face, the floors below are RATIOS against the same face
    n_q = max(2000, min(n_rows // 10, 100_000))
    root = os.path.join("/tmp", f"pftpu_bench_query_{n_q}")
    shutil.rmtree(root, ignore_errors=True)
    for sub in ("lsrc", "rsrc", "lout", "rout"):
        os.makedirs(os.path.join(root, sub))

    t = types
    lschema = t.message(
        "l", t.required(t.INT64).named("k"),
        t.required(t.DOUBLE).named("lv"),
        t.required(t.INT64).named("tag"),
    )
    rschema = t.message(
        "r", t.required(t.INT64).named("k"),
        t.required(t.DOUBLE).named("rv"),
    )
    rng = np.random.default_rng(1234)
    n_r = 3 * n_q // 4
    lk = np.sort(rng.integers(0, n_q // 2, n_q))
    rk = np.sort(rng.integers(n_q // 4, 3 * n_q // 4, n_r))
    lv = rng.random(n_q)
    rv = rng.random(n_r)
    tag = rng.permutation(n_q)   # unique per row: 1-span index probes
    lsrc = os.path.join(root, "lsrc", "a.parquet")
    rsrc = os.path.join(root, "rsrc", "a.parquet")
    with ParquetFileWriter(
        lsrc, lschema, WriterOptions(row_group_rows=512)
    ) as w:
        w.write_columns({"k": lk, "lv": lv, "tag": tag})
    with ParquetFileWriter(
        rsrc, rschema, WriterOptions(row_group_rows=512)
    ) as w:
        w.write_columns({"k": rk, "rv": rv})
    lrep = DatasetCompactor([lsrc], os.path.join(root, "lout"),
                            CompactOptions(
                                sort_by=["k"], target_row_group_rows=512,
                                target_file_rows=max(n_q // 2, 512),
                                index_columns=["tag"])).run()
    rrep = DatasetCompactor([rsrc], os.path.join(root, "rout"),
                            CompactOptions(
                                sort_by=["k"], target_row_group_rows=512,
                                target_file_rows=max(n_r // 2, 512))).run()

    # -- (1) join vs the two-scan lower bound ---------------------------
    def two_scan():
        rows = 0
        for paths in (lrep.paths, rrep.paths):
            for p in paths:
                r = ParquetReader(
                    p, HydratorSupplier.constantly(dict_hydrator())
                )
                for _row in r:
                    rows += 1
                r.close()
        return rows

    def join_pass():
        L = Dataset(lrep.paths, key_column="k")
        R = Dataset(rrep.paths, key_column="k")
        try:
            return sum(1 for _ in sorted_merge_join(L, R, on=["k"]))
        finally:
            L.close()
            R.close()

    in_rows = two_scan()          # warm page cache + the input count
    out_rows = join_pass()        # warm
    best_j = best_s = float("inf")
    for _ in range(max(reps, 3)):
        t0 = time.perf_counter()
        two_scan()
        best_s = min(best_s, time.perf_counter() - t0)
        t0 = time.perf_counter()
        join_pass()
        best_j = min(best_j, time.perf_counter() - t0)
    with trace.scope() as jt:
        join_pass()
    jc = jt.counters()

    # -- (2) indexed point probe on the NON-sort column -----------------
    idx = SecondaryIndex.open(lrep.index_paths[0])
    q_cache = SharedBufferCache()
    with Dataset(lrep.paths, "tag", cache=q_cache) as ds:
        ds.install_index(idx)
        with trace.scope() as it:
            ds.lookup(int(tag[0]))          # warm: pins metadata
            page_bound = ds.page_size_bound()
            s0 = q_cache.stats()
            # a MID-file row: the last rows' pages sit next to the
            # footer and ride into cache on coalesced metadata reads
            probe_rows = ds.lookup(
                int(tag[n_q // 2 + 37]), columns=["tag"]
            )
            s1 = q_cache.stats()
            absent = ds.lookup(n_q + 7)     # beyond the permutation
            s2 = q_cache.stats()
        ic = it.counters()
    probe_bytes = s1["miss_bytes"] - s0["miss_bytes"]
    absent_bytes = s2["miss_bytes"] - s1["miss_bytes"]

    # -- (3) expression projection, fused leg vs pyarrow.compute --------
    # INT64 inputs only: a plain-encoded DOUBLE input under the scan
    # face's bit-exact float64_policy='bits' refuses device compute by
    # contract (host fallback) — the launch-shape floor needs the
    # device leg
    expr = (qcol("k").cast("float64") / 8.0) + qcol("tag").cast("float64")
    sopts = ScanOptions(project_exprs=(("x", expr),))
    got, groups = [], 0
    with trace.scope() as et:
        for cols in ParquetReader.stream_batches(
            list(lrep.paths), engine="tpu", scan_options=sopts,
        ):
            by = {c.descriptor.path[0]: c for c in cols}
            got.append(np.asarray(by["x"].values))
            groups += 1
    ec = et.counters()
    got_x = np.concatenate(got)
    # lk was written globally sorted, so the compactor's stable
    # per-group sort preserved input row order exactly
    want = pc.add(
        pc.divide(pc.cast(pa.array(lk), pa.float64()), 8.0),
        pc.cast(pa.array(tag), pa.float64()),
    ).to_numpy()
    expr_exact = bool(
        got_x.dtype == np.float64
        and np.array_equal(got_x, want)
    )

    j_rps = in_rows / best_j
    s_rps = in_rows / best_s
    return {
        "query_join_rows_per_sec": round(j_rps, 1),
        "query_join_vs_twoscan_x": round(j_rps / s_rps, 3),
        "query_join_in_rows": in_rows,
        "query_join_out_rows": out_rows,
        "query_join_pages": jc.get("query.join_pages", 0),
        "query_join_counted_rows": jc.get("query.join_rows", 0),
        "query_index_probe_bytes": probe_bytes,
        "query_index_absent_bytes": absent_bytes,
        "query_index_page_bound": page_bound,
        "query_index_probe_rows": len(probe_rows),
        "query_index_absent_rows": len(absent),
        "query_index_hits": ic.get("serve.index_hits", 0),
        "query_index_skips": ic.get("serve.index_skips", 0),
        "query_expr_exact": expr_exact,
        "query_expr_groups": groups,
        "query_expr_launches": ec.get("engine.launches", 0),
        "query_expr_rows": ec.get("query.expr_rows", 0),
    }


def _bench_batch(paths) -> int:
    """The loader leg's batch size: the largest divisor (at or under
    4096) of the dataset's ACTUAL row-group size, read from the first
    file's footer — group-ALIGNED, so every steady-state group rides the
    batcher's static-slice fast path (docs/data.md documents exactly
    this sizing discipline for training configs), and a change to
    `_scan_paths`' sizing can never silently knock the leg off it."""
    from parquet_floor_tpu import ParquetFileReader

    with ParquetFileReader(paths[0]) as r:
        group = int(r.row_groups[0].num_rows)
    return next(
        b for b in range(min(group, 4096), 0, -1) if group % b == 0
    )


def _bench_loader(n_rows: int, shuffled: bool, num_epochs=1):
    """The loader leg's DataLoader over the scan leg's 4-file dataset:
    device engine, bit-exact DOUBLE policy, pad-remainder (every row
    counted); the shuffled form is the timed one, the unshuffled form is
    the reference stream the multiset check compares against."""
    from parquet_floor_tpu.data import DataLoader

    paths = _scan_paths(n_rows)
    batch = _bench_batch(paths)
    return DataLoader(
        paths, batch,
        shuffle_seed=7 if shuffled else None,
        shuffle_window=4 * batch if shuffled else 0,
        num_epochs=num_epochs, drop_remainder=False,
        engine="tpu", float64_policy="bits",
    )


def loader_leg_timed(n_rows: int, reps: int) -> dict:
    """Training-loader throughput (docs/data.md): seeded-shuffled epochs
    over the 4-file dataset through ``data.DataLoader`` on the device
    engine — unit permutation, window shuffle, and fixed-shape
    re-batching all included in the wall.  The loader PERSISTS across
    reps (``num_epochs=None``) and each rep times one full epoch, the
    steady state a training loop actually runs in — construction (a
    footer-only pass) and the warm-up epoch (compiles + page cache) stay
    outside the timed region, exactly as the scan leg's warm call does.
    Timed with NO device→host fetch (``block_until_ready`` only), so it
    runs before any D2H leg; the multiset-exactness check (which must
    fetch) runs separately in :func:`loader_leg_exactness`, after every
    timed section.

    The ``loader[_prefetch]_vs_scan_x`` ratios compare against a RAW
    device scan of the same dataset timed INSIDE this leg, with the
    three measurements interleaved rep-by-rep — the numerator and
    denominator see the same machine conditions, so the ratio measures
    the loader, not the load-average drift between two distant bench
    sections (the standalone scan leg still reports its own numbers)."""
    import jax

    from parquet_floor_tpu.scan import ScanOptions, scan_device_groups

    paths = _scan_paths(n_rows)
    sc = ScanOptions(threads=min(4, os.cpu_count() or 1))

    def run_scan():
        rows = 0
        for _fi, _gi, cols in scan_device_groups(
            paths, scan=sc, float64_policy="bits"
        ):
            jax.block_until_ready([c.values for c in cols.values()])
            rows += int(next(iter(cols.values())).values.shape[0])
        return rows

    with _bench_loader(n_rows, shuffled=True, num_epochs=None) as loader:
        batch = loader.batch_size
        window = loader.shuffle_window
        it = iter(loader)
        n_batches = loader.batches_per_epoch

        def run_epoch(source):
            rows = 0
            for _ in range(n_batches):
                b = next(source)
                jax.block_until_ready([c.values for c in b.columns])
                rows += b.num_valid
            return rows

        rows = run_epoch(it)    # warm compiles + page cache
        pf = loader.prefetch_to_device(2)
        run_epoch(pf)           # warm the prefetch path
        scan_rows = run_scan()  # warm the raw-scan comparator

        best = best_pf = best_scan = float("inf")
        # best-of-4 floor: at smoke scale an epoch is ~100 ms and the
        # assertion below compares two near-equal quantities — one rep
        # per side is scheduler noise, four interleaved reps converge
        # both minima under the same machine conditions
        for _ in range(max(reps, 4)):
            t0 = time.perf_counter()
            r = run_epoch(it)
            best = min(best, time.perf_counter() - t0)
            t0 = time.perf_counter()
            rp = run_epoch(pf)
            best_pf = min(best_pf, time.perf_counter() - t0)
            t0 = time.perf_counter()
            rs = run_scan()
            best_scan = min(best_scan, time.perf_counter() - t0)
            if r != rows or rp != rows or rs != scan_rows:
                raise RuntimeError(
                    f"loader leg row drift: {r}/{rp} != {rows} "
                    f"or scan {rs} != {scan_rows}"
                )
    scan_rps = scan_rows / best_scan
    return {
        "loader_rows_per_sec": round(rows / best, 1),
        "loader_prefetch_rows_per_sec": round(rows / best_pf, 1),
        "loader_scan_rows_per_sec": round(scan_rps, 1),
        "loader_vs_scan_x": round(rows / best / scan_rps, 3),
        "loader_prefetch_vs_scan_x": round(rows / best_pf / scan_rps, 3),
        "loader_rows": rows,
        "loader_batches": n_batches,
        "loader_batch_size": batch,
        "loader_shuffle_window": window,
    }


def loader_leg_exactness(n_rows: int) -> dict:
    """Bit-exactness of the shuffled loader stream vs the unshuffled
    reference SET: the same key values must come back, bit-identical as
    a multiset (shuffling reorders, never alters or drops).  Fetches
    device arrays — runs after every timed section."""
    import numpy as np

    def keys(shuffled):
        out = []
        with _bench_loader(n_rows, shuffled) as loader:
            for b in loader:
                out.append(
                    np.asarray(b.column("l_orderkey").values)[: b.num_valid]
                )
        return np.sort(np.concatenate(out)) if out else np.zeros(0, np.int64)

    shuf, ref = keys(True), keys(False)
    return {
        "loader_set_exact": bool(
            shuf.shape == ref.shape and np.array_equal(shuf, ref)
        ),
    }


def chunked_columns(path) -> list:
    """The chunked leg's column subset: 4 fields (mixed types) keeps
    the forced-chunking proof while compiling 4x fewer fresh shapes
    (each new shape costs ~seconds of XLA compile on the tunnel)."""
    from parquet_floor_tpu.format.file_read import ParquetFileReader

    with ParquetFileReader(path) as r:
        names = []
        for c in r.row_groups[0].columns or []:
            f = c.meta_data.path_in_schema[0]
            if f not in names:
                names.append(f)
        return names[:4]


def chunked_leg(path, single_cols, columns) -> dict:
    """Lowered-cap chunked decode (VERDICT r4 #4): group 0's subset
    again under a cap that forces >=3 launches, checked bit-exact
    against the single-launch decode.  Runs AFTER all timing legs — the
    bit-exact check fetches device arrays, and the first D2H degrades
    tunnelled links process-wide (BASELINE.md link characterization)."""
    import numpy as np

    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    from parquet_floor_tpu.utils import trace

    with ParquetFileReader(path) as r:
        est = sum(
            int(c.meta_data.total_uncompressed_size or 0)
            for c in (r.row_groups[0].columns or [])
            if c.meta_data.path_in_schema[0] in columns
        )
    cap = max(est // 4, 1 << 16)
    prev = os.environ.get("PFTPU_ARENA_CAP")
    os.environ["PFTPU_ARENA_CAP"] = str(cap)
    try:
        import jax

        trace.enable()
        trace.reset()
        t0 = time.perf_counter()
        with TpuRowGroupReader(path, float64_policy="bits") as tr:
            assert tr._arena_cap == cap
            chunk_cols = tr.read_row_group(0, columns=columns)
            # decode dispatches async — block before stopping the clock
            # (the wall still includes first-use XLA compiles for the
            # fresh chunk shapes; it is a health indicator, not a
            # steady-state rate like the timed legs above)
            jax.block_until_ready([c.values for c in chunk_cols.values()])
            wall = time.perf_counter() - t0
            launches = trace.stats().get("stage", {}).get("count", 0)
            trace.disable()
            bit_exact = True
            for name, sc in single_cols.items():
                cc = chunk_cols[name]
                if sc.lengths is not None:
                    sl = np.asarray(sc.lengths)
                    cl = np.asarray(cc.lengths)
                    if not np.array_equal(sl, cl):
                        bit_exact = False
                        continue
                    sv, cv = np.asarray(sc.values), np.asarray(cc.values)
                    w = min(sv.shape[1], cv.shape[1])
                    # beyond each row's length is padding; trim to the
                    # common bucket width and zero the slack
                    col_ix = np.arange(w)[None, :]
                    sm = col_ix < sl[:, None]
                    if not np.array_equal(
                        np.where(sm, sv[:, :w], 0),
                        np.where(sm, cv[:, :w], 0),
                    ):
                        bit_exact = False
                elif not np.array_equal(
                    np.asarray(sc.values), np.asarray(cc.values)
                ):
                    bit_exact = False
                if sc.mask is not None and not np.array_equal(
                    np.asarray(sc.mask), np.asarray(cc.mask)
                ):
                    bit_exact = False
    finally:
        if prev is None:
            os.environ.pop("PFTPU_ARENA_CAP", None)
        else:
            os.environ["PFTPU_ARENA_CAP"] = prev
    return {
        "chunked_launches": launches,
        "chunked_bit_exact": bool(bit_exact),
        "chunked_group0_wall_ms": round(wall * 1e3, 1),
        "chunked_cap_bytes": cap,
    }


def main():
    import numpy as np  # noqa: F401

    n_rows = int(os.environ.get("PFTPU_BENCH_ROWS", 1_000_000))
    reps = int(os.environ.get("PFTPU_BENCH_REPS", 3))
    path = os.path.join("/tmp", f"pftpu_bench_lineitem_{n_rows}.parquet")

    from benchmarks.workloads import write_lineitem

    if not os.path.exists(path):
        write_lineitem(path, n_rows)

    from parquet_floor_tpu.format.file_read import ParquetFileReader

    # --- CPU single-thread baseline (host NumPy engine) --------------------
    def cpu_decode():
        with ParquetFileReader(path) as r:
            rows = 0
            for batch in r.iter_row_groups():
                for col in batch.columns:
                    _ = col.values
                rows += batch.num_rows
            return rows

    cpu_decode()  # warm page cache
    cpu_dt = float("inf")
    for _ in range(2):  # best-of: the shared host's CPU clock is noisy
        t0 = time.perf_counter()
        rows = cpu_decode()
        cpu_dt = min(cpu_dt, time.perf_counter() - t0)
    cpu_rps = rows / cpu_dt

    # --- TPU engine (bit-exact DOUBLE decode: float64_policy='bits') -------
    import jax

    jax.config.update("jax_enable_x64", True)  # INT64/DOUBLE columns
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    from parquet_floor_tpu.utils import trace

    reader = TpuRowGroupReader(path, float64_policy="bits")
    decoded_bytes = _decoded_bytes(reader.reader)

    def tpu_decode():
        # streaming scan: every column of each group fully decoded on
        # device, then released — the per-group block also keeps exactly
        # one transfer in flight (see TpuRowGroupReader sync_transfers)
        rows = 0
        for cols in reader.iter_row_groups():
            jax.block_until_ready([c.values for c in cols.values()])
            rows += next(iter(cols.values())).values.shape[0]
            del cols
        return rows

    tpu_decode()  # compile warmup
    walls = []
    trace.enable()
    trace.reset()
    for _ in range(reps):
        t0 = time.perf_counter()
        rows_t = tpu_decode()
        walls.append(time.perf_counter() - t0)
    stages = trace.stats()
    trace.disable()
    assert rows_t == rows
    best = min(walls)
    tpu_rps = rows / best
    shipped_bytes = stages.get("ship", {}).get("bytes", 0) // max(reps, 1)
    ship_seconds = stages.get("ship", {}).get("seconds", 0.0) / max(reps, 1)

    latency = page_decode_latency(reader)
    # the front door's routing for this file (must be "tpu" here: the
    # cost model exists to route per-value-decode files to the device)
    from parquet_floor_tpu.tpu import cost as _cost

    auto_choice = _cost.choose_engine(reader.reader, purpose="batch")
    # the two flagship-path legs (VERDICT r4 #4).  Order matters: the
    # batch leg TIMES first (no D2H anywhere yet); the chunked leg's
    # bit-exact check then fetches arrays — after every timed section,
    # because the first D2H degrades a tunnelled link process-wide
    batch = batch_face_leg(path, reps, best)
    # training-loader leg, TIMED part (docs/data.md): device batches are
    # only block_until_ready'd — no D2H — so it runs among the timed legs
    loader_detail = loader_leg_timed(n_rows, reps)
    # multi-file scan scheduler leg (docs/scan.md): timed sections first,
    # its own bit-exact D2H check last — so it sits after every other
    # timed leg and before the (already post-D2H) chunked leg
    scan_detail = scan_leg(n_rows, reps)
    # cold-storage truth bench (docs/remote.md): host scan over the
    # simulated 20 ms-RTT store — no device work, no D2H; real sleeps
    # model the store, so it runs once, not per rep
    remote_detail = remote_leg(n_rows)
    # multi-tenant serving leg (docs/serving.md): host scans through the
    # shared buffer cache + the one-page point-lookup proof — no device
    # work, no D2H, runs once
    serving_detail = serving_leg(n_rows)
    # process-scale traffic truth bench (docs/serving.md): subprocess
    # workers + modeled remote latency — real sleeps, no device work,
    # runs once like the remote leg
    traffic_detail = traffic_leg(n_rows)
    # fleet-survivability truth bench (docs/serving.md): in-process
    # daemons over a counted origin — real sockets, real sleeps, no
    # device work, runs once
    fleet_detail = fleet_leg(n_rows)
    # exec-cache cold/warm leg (docs/perf.md): runs in SUBPROCESSES
    # (fresh jax each), so its placement among the timed legs is free
    exec_cache_detail = exec_cache_leg(n_rows)
    # multi-chip scheduler leg (docs/multichip.md): also a subprocess
    # (it forces its own device count on CPU)
    multichip_detail = multichip_leg(n_rows)
    # device pushdown leg (docs/pushdown.md): D2H-heavy by design (the
    # whole point is measuring shipped bytes), so it runs with the
    # post-timing D2H checks
    pushdown_detail = pushdown_leg(n_rows)
    # write path + compaction legs (docs/write.md): the encode engine
    # D2H-fetches its packed streams by design, so both run with the
    # post-timing group (their scan comparator is interleaved inside)
    write_detail = write_leg(n_rows, reps)
    compact_detail = compact_leg(n_rows, reps)
    # query subsystem leg (docs/query.md): join / index / expressions
    query_detail = query_leg(n_rows, reps)
    write_detail["write_vs_scan_x"] = round(
        write_detail["write_rows_per_sec"]
        / scan_detail["scan_rows_per_sec"], 3
    )
    # the loader's multiset-exactness check fetches device arrays: after
    # every timed section (the first D2H degrades tunnelled links
    # process-wide), alongside the scan leg's own D2H check
    loader_detail.update(loader_leg_exactness(n_rows))
    # loader_vs_scan_x / loader_prefetch_vs_scan_x come from the loader
    # leg itself (raw-scan comparator interleaved with the loader reps)
    chunk_cols_subset = chunked_columns(path)
    single_cols = reader.read_row_group(0, columns=chunk_cols_subset)
    reader.close()
    chunked = chunked_leg(path, single_cols, chunk_cols_subset)

    result = {
        "metric": "tpch_lineitem_snappy_dict_decode",
        "value": round(tpu_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 3),
        # observation band THIS run: speedup of every rep, not just the
        # best — the number any external record should land inside
        # (quoted bands in BASELINE.md/README union this with all prior
        # driver records)
        "vs_baseline_band": [
            round(rows / max(walls) / cpu_rps, 3),
            round(rows / min(walls) / cpu_rps, 3),
        ],
        "detail": {
            "rows": rows,
            "cpu_rows_per_sec": round(cpu_rps, 1),
            "tpu_rows_per_sec": round(tpu_rps, 1),
            "backend": jax.devices()[0].platform,
            "file_bytes": os.path.getsize(path),
            "float64_policy": "bits",
            "decoded_bytes": decoded_bytes,
            "decoded_GB_per_s": round(decoded_bytes / best / 1e9, 3),
            "cpu_decoded_GB_per_s": round(decoded_bytes / cpu_dt / 1e9, 3),
            "shipped_bytes_per_pass": shipped_bytes,
            "ship_GB_per_s": round(
                shipped_bytes / ship_seconds / 1e9, 3
            ) if ship_seconds else None,
            "auto_routes_to": auto_choice.engine,
            **latency,
            **batch,
            **chunked,
            **scan_detail,
            **remote_detail,
            **serving_detail,
            **traffic_detail,
            **fleet_detail,
            **exec_cache_detail,
            **multichip_detail,
            **pushdown_detail,
            **write_detail,
            **compact_detail,
            **query_detail,
            **loader_detail,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
