"""The REAL multi-process path: 2 OS processes × 4 virtual CPU devices,
joined via ``jax.distributed.initialize``, reading one file into global
sharded arrays (VERDICT round-2 weak #6 / next-round #5: the
``process_count() > 1`` branches of ``_agree_max`` and the layout
agreement must execute, not just pass review).

One spawned worker pair serves three separately-named tests (VERDICT r4
#7: a failure pinpoints the broken path without re-paying the 2-process
spawn): single-file sharded read, dataset assembly, and the
``engine="tpu"`` row stream.  Each worker reshards every global column
to fully-replicated and digests it; the tests assert the two processes
report byte-identical global content and that the digests match a
single-process read on this process's own 8-device mesh (same global
layout by construction).
"""

import hashlib
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax


def _digest(*arrays) -> str:
    """Keep in sync with multiproc_worker._digest (not imported: the
    worker module mutates env/jax config at import time)."""
    h = hashlib.sha256()
    for a in arrays:
        if a is None:
            h.update(b"<none>")
            continue
        a = np.asarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()

from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types

pytestmark = pytest.mark.skipif(
    os.environ.get("PFTPU_SKIP_MULTIPROC") == "1",
    reason="multi-process test disabled",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_file(path: str) -> None:
    """6 ragged row groups: INT64 id (sorted — predicate-prunable),
    optional DOUBLE, dictionary strings."""
    t = types
    schema = t.message(
        "t",
        t.required(t.INT64).named("id"),
        t.optional(t.DOUBLE).named("x"),
        t.optional(t.BYTE_ARRAY).as_(t.string()).named("s"),
    )
    sizes = [700, 700, 650, 700, 700, 550]
    base = 0
    with ParquetFileWriter(
        path, schema, WriterOptions(row_group_rows=700)
    ) as w:
        for sz in sizes:
            ids = list(range(base, base + sz))
            xs = [None if i % 7 == 0 else i * 0.25 for i in ids]
            ss = [None if i % 11 == 0 else f"s{i % 37}" for i in ids]
            w.write_columns({"id": ids, "x": xs, "s": ss})
            base += sz


def _write_dataset(dir_path: str) -> list:
    """3 files with UNEVEN groups-per-file (2, 1, 3) and ragged sizes —
    the cross-file global assembly of read_dataset_sharded."""
    t = types
    schema = t.message(
        "t",
        t.required(t.INT64).named("id"),
        t.optional(t.BYTE_ARRAY).as_(t.string()).named("s"),
    )
    os.makedirs(dir_path, exist_ok=True)
    paths = []
    base = 0
    for f, sizes in enumerate([[300, 250], [420], [150, 310, 200]]):
        p = os.path.join(dir_path, f"part{f}.parquet")
        with ParquetFileWriter(
            p, schema, WriterOptions(row_group_rows=max(sizes))
        ) as w:
            for sz in sizes:
                ids = list(range(base, base + sz))
                ss = [None if i % 9 == 0 else f"d{i % 23}" for i in ids]
                w.write_columns({"id": ids, "s": ss})
                base += sz
        paths.append(p)
    return paths


@pytest.fixture(scope="module")
def worker_pair(tmp_path_factory):
    """Spawn the 2-process pair ONCE for the whole module and return
    (report0, report1, file_path, dataset_dir)."""
    tmp_path = tmp_path_factory.mktemp("mp")
    path = str(tmp_path / "mp.parquet")
    _write_file(path)
    ds_dir = str(tmp_path / "dataset")
    _write_dataset(ds_dir)
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # fresh XLA_FLAGS: the worker appends its own device-count flag
        "XLA_FLAGS": "",
        "JAX_COMPILATION_CACHE_DIR": "/tmp/pftpu_jax_cache_mp",
    }
    procs, outs = [], []
    try:
        for pid in range(2):
            out = str(tmp_path / f"report{pid}.json")
            outs.append(out)
            procs.append(subprocess.Popen(
                [sys.executable, worker, coord, str(pid), "2", path, out],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            ))
        logs = []
        for p in procs:
            stdout, _ = p.communicate(timeout=420)
            logs.append(stdout.decode(errors="replace"))
    finally:
        # a hung coordinator handshake must not leak workers into the
        # rest of the CI job
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-4000:]}"
    r0, r1 = (json.load(open(o)) for o in outs)
    return r0, r1, path, ds_dir


def _mesh():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()).reshape(-1), ("rg",))


def _column_digests(out) -> str:
    dig = []
    for name in sorted(out):
        c = out[name]
        dig.append(_digest(
            None if c.values is None else np.asarray(c.values),
            None if c.mask is None else np.asarray(c.mask),
            None if c.lengths is None else np.asarray(c.lengths),
            None if c.row_mask is None else np.asarray(c.row_mask),
        ))
    return _digest(*[d.encode() for d in dig])


def test_two_process_single_file(worker_pair):
    """Plain / predicate / ghost reads of ONE file: both processes
    byte-identical, and equal to a single-process 8-device read."""
    r0, r1, path, _ = worker_pair
    assert r0["plain"] == r1["plain"]
    assert r0["pred"] == r1["pred"]
    assert r0["ghost"] == r1["ghost"]
    assert r0["num_rows"] == r1["num_rows"]
    assert r0["num_rows_pred"] == r1["num_rows_pred"]

    # single-process read on THIS process's 8-device mesh (identical
    # global layout by construction).  (_digest is duplicated here
    # rather than imported: importing the worker module would run its
    # env/jax.config side effects in the pytest process.)
    from parquet_floor_tpu.parallel.multihost import read_sharded_global

    out = read_sharded_global(path, _mesh(), float64_policy="float64")
    assert _column_digests(out) == r0["plain"]

    # totals: plain = all rows; the predicate keeps a strict non-empty
    # subset; ghost read = every group pruned, zero rows, dtypes via
    # schema metadata
    total = 700 + 700 + 650 + 700 + 700 + 550
    assert set(r0["num_rows"].values()) == {total}
    kept = set(r0["num_rows_pred"].values())
    assert len(kept) == 1
    assert 0 < next(iter(kept)) < total
    assert set(r0["ghost_rows"].values()) == {0}
    assert r0["ghost_dtypes"]["id"] == "int64"
    assert r0["ghost_dtypes"]["x"] == "float64"
    assert r0["ghost_dtypes"]["s"] == "uint8"


def test_two_process_dataset(worker_pair):
    """Multi-file dataset assembly (uneven 2/1/3 groups per file):
    processes agree with each other and with the single-process read."""
    r0, r1, _, ds_dir = worker_pair
    assert r0["dataset"] == r1["dataset"]
    assert r0["ds_rows"] == r1["ds_rows"]
    assert set(r0["ds_rows"].values()) == {300 + 250 + 420 + 150 + 310 + 200}

    from parquet_floor_tpu.parallel.multihost import read_dataset_sharded

    ds_paths = sorted(
        os.path.join(ds_dir, f)
        for f in os.listdir(ds_dir)
        if f.endswith(".parquet")
    )
    out_d = read_dataset_sharded(ds_paths, _mesh(), float64_policy="float64")
    assert _column_digests(out_d) == r0["dataset"]


def test_two_process_device_row_stream(worker_pair):
    """The engine="tpu" row stream ran under process_count()>1: both
    processes hydrated identical rows, matching this process's stream."""
    r0, r1, path, _ = worker_pair
    assert r0["tpu_rows"] == r1["tpu_rows"]
    assert r0["tpu_rows_n"] == r1["tpu_rows_n"] == 4000

    from parquet_floor_tpu import ParquetReader

    class _Rows:
        def start(self):
            return []

        def add(self, t, h, v):
            t.append(v)
            return t

        def finish(self, t):
            return tuple(t)

    h = hashlib.sha256()
    n_stream = 0
    for row in ParquetReader.stream_content(
        path, lambda c: _Rows(), engine="tpu"
    ):
        h.update(repr(row).encode())
        n_stream += 1
    assert h.hexdigest() == r0["tpu_rows"]
    assert n_stream == r0["tpu_rows_n"]
