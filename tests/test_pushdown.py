"""Device pushdown compute (docs/pushdown.md): differential filter and
aggregate tests against pyarrow.compute oracles, the one-launch /
capacity-overflow contract, exec-cache key separation, the chunked
over-cap fallback, the device page-prune rung, and the host twins
(eval_mask / host_partial / scan_aggregate / serve.Dataset.aggregate)."""

import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
pc = pytest.importorskip("pyarrow.compute")
pq = pytest.importorskip("pyarrow.parquet")

from parquet_floor_tpu import (  # noqa: E402
    Aggregate,
    ParquetFileWriter,
    WriterOptions,
    col,
    types,
)
from parquet_floor_tpu.batch.aggregate import AggPartial, host_partial  # noqa: E402
from parquet_floor_tpu.batch.predicate import eval_mask, tree, tree_columns  # noqa: E402
from parquet_floor_tpu.errors import UnsupportedFeatureError  # noqa: E402
from parquet_floor_tpu.format.file_read import ReaderOptions  # noqa: E402
from parquet_floor_tpu.scan import (  # noqa: E402
    DatasetScanner,
    ScanOptions,
    scan_aggregate,
    scan_device_groups,
)
from parquet_floor_tpu.tpu import exec_cache  # noqa: E402
from parquet_floor_tpu.tpu.compute import ComputeRequest  # noqa: E402
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader  # noqa: E402
from parquet_floor_tpu.utils import trace  # noqa: E402

rng = np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _no_cache(monkeypatch):
    monkeypatch.delenv("PFTPU_EXEC_CACHE", raising=False)
    exec_cache.activate(None)
    yield
    exec_cache.activate(None)


def _write_mixed(tmp_path, name="mixed.parquet", n=900, group=300,
                 with_nan=False):
    """Our writer: flat ints, optional int32, float32, DOUBLE, dict
    strings — 3 row groups."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.INT32).named("v"),
        types.required(types.FLOAT).named("f"),
        types.required(types.DOUBLE).named("d"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("cat"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("tag"),
    )
    path = tmp_path / name
    cats = ["apple", "pear", "plum", "fig", "quince"]
    with ParquetFileWriter(
        path, schema,
        WriterOptions(row_group_rows=group, data_page_values=group // 2),
    ) as w:
        for lo in range(0, n, group):
            m = min(group, n - lo)
            f = rng.integers(0, 1000, m).astype(np.float32)
            if with_nan:
                f[:: 7] = np.nan
            w.write_columns({
                "k": rng.integers(0, 1000, m).astype(np.int64),
                "v": [
                    None if i % 5 == 0 else int(rng.integers(0, 100))
                    for i in range(m)
                ],
                "f": f,
                "d": rng.integers(0, 1000, m).astype(np.float64),
                "cat": [cats[i] for i in rng.integers(0, len(cats), m)],
                "tag": [
                    None if i % 4 == 0 else ("hot" if i % 2 else "cold")
                    for i in range(m)
                ],
            })
    return path


def _oracle_filter(path, pa_mask_fn, columns):
    t = pq.read_table(str(path))
    keep = pa_mask_fn(t)
    # pyarrow filter drops null-mask rows — the pushdown contract
    got = t.filter(keep)
    return {c: got[c] for c in columns}


def _fetch(res, name):
    dc = res.columns[name]
    vals = np.asarray(dc.values)
    mask = None if dc.mask is None else np.asarray(dc.mask)
    return vals, mask


def _device_filter(path, pred, columns=None, policy="float64", **req_kw):
    with TpuRowGroupReader(str(path), float64_policy=policy) as tr:
        req = ComputeRequest(predicate=pred, **req_kw)
        parts = [
            tr.read_row_group_compute(i, req, columns=columns)
            for i in range(tr.num_row_groups)
        ]
    return parts


def _concat_col(parts, name):
    vals = np.concatenate([np.asarray(p.columns[name].values)
                           for p in parts])
    masks = [p.columns[name].mask for p in parts]
    if any(m is not None for m in masks):
        mask = np.concatenate([np.asarray(m) for m in masks])
    else:
        mask = None
    return vals, mask


# ---------------------------------------------------------------------------
# differential filters vs pyarrow.compute
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op,lit,pafn", [
    ("<", 300, lambda t: pc.less(t["k"], 300)),
    ("<=", 300, lambda t: pc.less_equal(t["k"], 300)),
    ("==", 7, lambda t: pc.equal(t["k"], 7)),
    ("!=", 7, lambda t: pc.not_equal(t["k"], 7)),
    (">", 700, lambda t: pc.greater(t["k"], 700)),
    (">=", 700, lambda t: pc.greater_equal(t["k"], 700)),
])
def test_filter_int_ops_differential(tmp_path, op, lit, pafn):
    path = _write_mixed(tmp_path)
    pred = {
        "<": col("k") < lit, "<=": col("k") <= lit,
        "==": col("k") == lit, "!=": col("k") != lit,
        ">": col("k") > lit, ">=": col("k") >= lit,
    }[op]
    parts = _device_filter(path, pred)
    want = _oracle_filter(path, pafn, ["k", "v"])
    got_k, _ = _concat_col(parts, "k")
    assert np.array_equal(got_k, want["k"].to_numpy())
    got_v, got_m = _concat_col(parts, "v")
    w = want["v"]
    wm = np.asarray([x is None for x in w.to_pylist()])
    assert np.array_equal(got_m, wm)
    wv = w.to_numpy(zero_copy_only=False)
    assert np.array_equal(got_v[~got_m], wv[~wm].astype(np.int32))


def test_filter_optional_null_semantics(tmp_path):
    """Comparisons on an optional column never select null cells —
    pyarrow's filter-drop behavior, bit-for-bit."""
    path = _write_mixed(tmp_path)
    parts = _device_filter(path, col("v") >= 0)  # all non-null rows
    want = _oracle_filter(
        path, lambda t: pc.greater_equal(t["v"], 0), ["k"]
    )
    got_k, _ = _concat_col(parts, "k")
    assert np.array_equal(got_k, want["k"].to_numpy())


def test_filter_dict_string_order_compare(tmp_path):
    """Order comparisons on dictionary strings run on the HOST
    dictionary (the per-group match mask) — full semantics on device."""
    path = _write_mixed(tmp_path)
    parts = _device_filter(path, col("cat") < "pear")
    want = _oracle_filter(
        path, lambda t: pc.less(t["cat"], "pear"), ["k", "cat"]
    )
    got_k, _ = _concat_col(parts, "k")
    assert np.array_equal(got_k, want["k"].to_numpy())


def test_filter_optional_string_and_isnull(tmp_path):
    path = _write_mixed(tmp_path)
    pred = (col("tag") == "hot") | col("tag").is_null()
    parts = _device_filter(path, pred)
    want = _oracle_filter(
        path,
        lambda t: pc.or_(
            pc.fill_null(pc.equal(t["tag"], "hot"), False),
            pc.is_null(t["tag"]),
        ),
        ["k"],
    )
    got_k, _ = _concat_col(parts, "k")
    assert np.array_equal(got_k, want["k"].to_numpy())


def test_filter_and_or_tree_differential(tmp_path):
    path = _write_mixed(tmp_path)
    pred = ((col("k") < 500) & (col("f") >= 100.0)) | (col("cat") == "fig")
    parts = _device_filter(path, pred)
    want = _oracle_filter(
        path,
        lambda t: pc.or_(
            pc.and_(pc.less(t["k"], 500),
                    pc.greater_equal(t["f"], np.float32(100.0))),
            pc.equal(t["cat"], "fig"),
        ),
        ["k", "f"],
    )
    got_k, _ = _concat_col(parts, "k")
    assert np.array_equal(got_k, want["k"].to_numpy())
    got_f, _ = _concat_col(parts, "f")
    assert np.array_equal(got_f, want["f"].to_numpy())


def test_filter_double_exact_policy(tmp_path):
    """DOUBLE comparisons need float64_policy='float64' (exact) —
    lossy policies reject instead of approximating."""
    path = _write_mixed(tmp_path)
    parts = _device_filter(path, col("d") < 500.0, policy="float64")
    want = _oracle_filter(path, lambda t: pc.less(t["d"], 500.0), ["d"])
    got_d, _ = _concat_col(parts, "d")
    assert np.array_equal(got_d, want["d"].to_numpy())
    with TpuRowGroupReader(str(path), float64_policy="bits") as tr:
        with pytest.raises(UnsupportedFeatureError, match="float64"):
            tr.read_row_group_compute(
                0, ComputeRequest(predicate=col("d") < 500.0)
            )


def test_empty_and_allpass_selections(tmp_path):
    path = _write_mixed(tmp_path)
    empty = _device_filter(path, col("k") < -1)
    assert all(p.num_selected == 0 for p in empty)
    assert all(p.columns["k"].values.shape[0] == 0 for p in empty)
    allp = _device_filter(path, col("k") >= 0)
    got_k, _ = _concat_col(allp, "k")
    want = pq.read_table(str(path))["k"].to_numpy()
    assert np.array_equal(got_k, want)


def test_mask_mode_matches_compact(tmp_path):
    path = _write_mixed(tmp_path)
    pred = col("k") < 250
    compact = _device_filter(path, pred)
    masked = _device_filter(path, pred, mode="mask")
    for cp, mp in zip(compact, masked):
        sel = np.asarray(mp.mask)
        assert mp.num_selected == cp.num_selected == int(sel.sum())
        assert np.array_equal(
            np.asarray(cp.columns["k"].values),
            np.asarray(mp.columns["k"].values)[sel],
        )


def test_projection_excludes_predicate_column(tmp_path):
    """A predicate column outside the projection is decoded for the
    filter but never shipped."""
    path = _write_mixed(tmp_path)
    parts = _device_filter(path, col("k") < 300, columns=["v"])
    assert all(set(p.columns) == {"v"} for p in parts)
    want = _oracle_filter(path, lambda t: pc.less(t["k"], 300), ["v"])
    got_v, got_m = _concat_col(parts, "v")
    wm = np.asarray([x is None for x in want["v"].to_pylist()])
    assert np.array_equal(got_m, wm)


def test_capacity_overflow_retry(tmp_path):
    """Survivors past the static capacity re-dispatch once with a grown
    capacity — counted, never wrong."""
    path = _write_mixed(tmp_path)
    pred = col("k") >= 0  # selects everything: guaranteed overflow
    with trace.scope() as t:
        parts = _device_filter(path, pred, initial_capacity=4)
    got_k, _ = _concat_col(parts, "k")
    want = pq.read_table(str(path))["k"].to_numpy()
    assert np.array_equal(got_k, want)
    c = t.counters()
    assert c.get("engine.pushdown_overflows", 0) >= 1
    # the HWM remembered: groups after the first never overflow again
    assert c["engine.pushdown_overflows"] < c["engine.pushdown_groups"]


def test_chunked_overcap_parity(tmp_path, monkeypatch):
    """An over-cap (multi-launch chunked) group evaluates the same
    request as follow-up device ops — results identical to the fused
    tail."""
    path = _write_mixed(tmp_path)
    pred = (col("k") < 400) & (col("cat") == "plum")
    want = _device_filter(path, pred)
    monkeypatch.setenv("PFTPU_ARENA_CAP", "4096")
    got = _device_filter(path, pred)
    for a, b in zip(got, want):
        assert a.num_selected == b.num_selected
        assert np.array_equal(
            np.asarray(a.columns["k"].values),
            np.asarray(b.columns["k"].values),
        )


def test_eval_mask_host_twin_identical(tmp_path):
    """The host eval_mask and the device tail select the SAME rows for
    the same predicate (one filter semantics across faces)."""
    path = _write_mixed(tmp_path)
    pred = ((col("k") < 600) | (col("tag") == "cold")) & (col("v") != 13)
    parts = _device_filter(path, pred, mode="mask")
    from parquet_floor_tpu.scan.executor import _batch_resolver

    host_masks = []
    with DatasetScanner([str(path)]) as scanner:
        for unit in scanner:
            host_masks.append(eval_mask(
                pred, _batch_resolver(unit.batch), unit.batch.num_rows
            ))
    for p, hm in zip(parts, host_masks):
        assert np.array_equal(np.asarray(p.mask), hm)


# ---------------------------------------------------------------------------
# aggregates
# ---------------------------------------------------------------------------

def _device_agg(path, agg, pred=None, policy="float64"):
    with TpuRowGroupReader(str(path), float64_policy=policy) as tr:
        req = ComputeRequest(predicate=pred, aggregate=agg)
        out = AggPartial(agg)
        for i in range(tr.num_row_groups):
            out.combine(tr.read_row_group_compute(i, req).agg)
    return out


def test_scalar_aggregates_differential(tmp_path):
    path = _write_mixed(tmp_path)
    agg = Aggregate((
        ("k", "sum"), ("k", "min"), ("k", "max"), ("v", "count"),
        ("v", "sum"), ("f", "sum"), ("f", "min"),
    ))
    fin = _device_agg(path, agg, pred=col("k") < 500).finalize()
    t = pq.read_table(str(path))
    w = t.filter(pc.less(t["k"], 500))
    assert fin["k_sum"] == pc.sum(w["k"]).as_py()
    assert fin["k_min"] == pc.min_max(w["k"])["min"].as_py()
    assert fin["k_max"] == pc.min_max(w["k"])["max"].as_py()
    assert fin["v_count"] == pc.count(w["v"]).as_py()
    assert fin["v_sum"] == pc.sum(w["v"]).as_py()
    # float32 sums accumulate in float64 exactly like pyarrow; the data
    # is integer-valued so the sum is order-independent and bit-equal
    assert fin["f_sum"] == pc.sum(w["f"]).as_py()
    assert fin["f_min"] == pc.min_max(w["f"])["min"].as_py()


def test_groupby_differential_with_null_keys(tmp_path):
    path = _write_mixed(tmp_path)
    agg = Aggregate(
        (("v", "sum"), ("v", "min"), ("v", "max"), ("v", "count")),
        group_by="tag",
    )
    fin = _device_agg(path, agg, pred=col("k") < 800).finalize()
    t = pq.read_table(str(path))
    w = t.filter(pc.less(t["k"], 800))
    gb = w.group_by("tag").aggregate(
        [("v", "sum"), ("v", "min"), ("v", "max"), ("v", "count")]
    ).to_pydict()
    assert len(fin) == len(gb["tag"])
    for i, key in enumerate(gb["tag"]):
        ours = fin[None if key is None else key.encode()]
        assert ours["v_sum"] == gb["v_sum"][i]
        assert ours["v_min"] == gb["v_min"][i]
        assert ours["v_max"] == gb["v_max"][i]
        assert ours["v_count"] == gb["v_count"][i]


def test_nan_sum_and_minmax_semantics(tmp_path):
    """Pinned to pyarrow: sum propagates NaN, min/max skip NaN."""
    path = _write_mixed(tmp_path, with_nan=True)
    agg = Aggregate((("f", "sum"), ("f", "min"), ("f", "max"),
                     ("f", "count")))
    fin = _device_agg(path, agg).finalize()
    t = pq.read_table(str(path))
    assert np.isnan(fin["f_sum"]) and np.isnan(pc.sum(t["f"]).as_py())
    mm = pc.min_max(t["f"])
    assert fin["f_min"] == mm["min"].as_py()
    assert fin["f_max"] == mm["max"].as_py()
    assert fin["f_count"] == pc.count(t["f"]).as_py()


def test_int64_overflow_sum_wraps(tmp_path):
    schema = types.message(
        "t", types.required(types.INT64).named("x"),
    )
    path = tmp_path / "wrap.parquet"
    big = np.full(8, 2**62, dtype=np.int64)
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns({"x": big})
    fin = _device_agg(path, Aggregate((("x", "sum"),))).finalize()
    t = pq.read_table(str(path))
    assert fin["x_sum"] == pc.sum(t["x"]).as_py()  # wrapped, both sides


def test_empty_selection_aggregate(tmp_path):
    path = _write_mixed(tmp_path)
    agg = Aggregate((("v", "sum"), ("v", "min"), ("v", "count")))
    fin = _device_agg(path, agg, pred=col("k") < -5).finalize()
    assert fin == {"v_sum": None, "v_min": None, "v_count": 0}


def test_combine_associativity(tmp_path):
    path = _write_mixed(tmp_path)
    agg = Aggregate((("v", "sum"), ("v", "max")), group_by="cat")
    with TpuRowGroupReader(str(path), float64_policy="float64") as tr:
        req = ComputeRequest(aggregate=agg)
        parts = [
            tr.read_row_group_compute(i, req).agg
            for i in range(tr.num_row_groups)
        ]
    left = AggPartial.merge(agg, parts)
    right = AggPartial(agg)
    for p in reversed(parts):
        right.combine(p)
    assert left.finalize() == right.finalize()


def test_host_partial_matches_device(tmp_path):
    """The NumPy host partial and the device tail agree bucket for
    bucket (the mixed device/host-fallback combine contract)."""
    path = _write_mixed(tmp_path)
    agg = Aggregate(
        (("v", "sum"), ("v", "min"), ("f", "sum")), group_by="cat"
    )
    pred = col("k") < 700
    dev = _device_agg(path, agg, pred=pred).finalize()
    host = scan_aggregate([str(path)], agg, predicate=pred,
                          engine="host").finalize()
    assert dev == host


def test_scan_aggregate_tpu_vs_host_multifile(tmp_path):
    paths = [
        str(_write_mixed(tmp_path, name=f"m{i}.parquet", n=600))
        for i in range(3)
    ]
    agg = Aggregate(
        (("v", "sum"), ("v", "count"), ("k", "max")), group_by="cat"
    )
    pred = col("k") < 650
    a = scan_aggregate(paths, agg, predicate=pred, engine="tpu").finalize()
    b = scan_aggregate(paths, agg, predicate=pred, engine="host").finalize()
    assert a == b


def test_scan_aggregate_host_fallback_on_plain_group_key(tmp_path):
    """A non-dictionary group key cannot group on device — the scan
    falls back to the host leg with identical results."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("g"),
        types.required(types.INT64).named("x"),
    )
    path = tmp_path / "plain.parquet"
    with ParquetFileWriter(
        path, schema, WriterOptions(enable_dictionary=False),
    ) as w:
        w.write_columns({
            "g": (np.arange(100) % 3).astype(np.int64),
            "x": np.arange(100).astype(np.int64),
        })
    agg = Aggregate((("x", "sum"),), group_by="g")
    with trace.scope() as t:
        got = scan_aggregate([str(path)], agg, engine="tpu").finalize()
    want = scan_aggregate([str(path)], agg, engine="host").finalize()
    assert got == want
    acts = [d.get("action") for d in t.decisions()
            if d.get("decision") == "engine.pushdown"]
    assert "host_fallback" in acts


# ---------------------------------------------------------------------------
# scan-face plumbing
# ---------------------------------------------------------------------------

def test_scan_pushdown_rows_and_counters(tmp_path):
    paths = [
        str(_write_mixed(tmp_path, name=f"s{i}.parquet", n=600))
        for i in range(2)
    ]
    pred = col("k") < 100
    with trace.scope() as t:
        rows = 0
        for _fi, _gi, cols in scan_device_groups(
            paths, columns=["k", "v"],
            scan=ScanOptions(pushdown=True), predicate=pred,
            float64_policy="bits",
        ):
            k = np.asarray(cols["k"].values)
            assert bool(np.all(k < 100))
            rows += k.size
    c = t.counters()
    assert c["engine.pushdown_groups"] > 0
    assert c["scan.rows_filtered_device"] == \
        c["engine.pushdown_rows_in"] - c["engine.pushdown_rows_selected"]
    assert rows == c["engine.pushdown_rows_selected"]
    # one-launch with the compute tail fused (no overflow at 10%)
    assert c["engine.launches"] == c["engine.pushdown_groups"] + \
        c.get("engine.pushdown_overflows", 0)
    # parity vs the host scan + host mask
    from parquet_floor_tpu.scan.executor import _batch_resolver

    want = 0
    with DatasetScanner(paths) as sc:
        for unit in sc:
            want += int(eval_mask(
                pred, _batch_resolver(unit.batch), unit.batch.num_rows
            ).sum())
    assert rows == want


def test_scan_pushdown_predicate_outside_projection(tmp_path):
    """A predicate column outside the scan projection still stages and
    filters; only the projection ships."""
    path = _write_mixed(tmp_path)
    pred = col("k") < 200
    got = []
    for _fi, _gi, cols in scan_device_groups(
        [str(path)], columns=["v"],
        scan=ScanOptions(pushdown=True), predicate=pred,
        float64_policy="bits",
    ):
        assert set(cols) == {"v"}
        got.append(np.asarray(cols["v"].values))
    got = np.concatenate(got)
    t = pq.read_table(str(path))
    w = t.filter(pc.less(t["k"], 200))["v"]
    wm = np.asarray([x is None for x in w.to_pylist()])
    wv = w.to_numpy(zero_copy_only=False)
    assert got.size == len(w)
    assert np.array_equal(
        got[~wm], wv[~wm].astype(np.int32)
    )


def test_scan_pushdown_salvage_rejected(tmp_path):
    path = _write_mixed(tmp_path)
    with pytest.raises(UnsupportedFeatureError, match="salvage"):
        list(scan_device_groups(
            [str(path)], scan=ScanOptions(pushdown=True),
            predicate=col("k") < 5,
            options=ReaderOptions(salvage=True),
        ))


def test_scan_aggregate_salvage_rejected_not_swallowed(tmp_path):
    """The device leg's salvage rejection must surface, NOT fall back to
    a host scan that silently aggregates around quarantined rows."""
    path = _write_mixed(tmp_path)
    agg = Aggregate((("v", "sum"),))
    with pytest.raises(UnsupportedFeatureError, match="salvage"):
        scan_aggregate([str(path)], agg,
                       options=ReaderOptions(salvage=True), engine="tpu")
    with pytest.raises(UnsupportedFeatureError, match="salvage"):
        scan_aggregate([str(path)], agg,
                       options=ReaderOptions(salvage=True), engine="host")


def test_chunked_overcap_lossy_double_rejected(tmp_path, monkeypatch):
    """The multi-launch fallback enforces the same DOUBLE-exactness rule
    as the fused tail: float64_policy='bits'/'f32' must reject, never
    compare or accumulate rounded values."""
    path = _write_mixed(tmp_path)
    monkeypatch.setenv("PFTPU_ARENA_CAP", "4096")
    with TpuRowGroupReader(str(path), float64_policy="bits") as tr:
        with pytest.raises(UnsupportedFeatureError, match="float64"):
            tr.read_row_group_compute(
                0, ComputeRequest(predicate=col("d") < 500.0)
            )
        with pytest.raises(UnsupportedFeatureError, match="float64"):
            tr.read_row_group_compute(
                0, ComputeRequest(aggregate=Aggregate((("d", "sum"),)))
            )
    # exact policy still works on the same over-cap group
    parts = _device_filter(path, col("d") < 500.0, policy="float64")
    want = _oracle_filter(path, lambda t: pc.less(t["d"], 500.0), ["d"])
    got_d, _ = _concat_col(parts, "d")
    assert np.array_equal(got_d, want["d"].to_numpy())


def test_index_form_aggregate_rejected(tmp_path):
    """Aggregating an index-form dictionary column would sum dictionary
    SLOTS — both paths reject it (count still works: it reads masks)."""
    path = _write_mixed(tmp_path)
    with TpuRowGroupReader(
        str(path), float64_policy="float64", dict_form="index"
    ) as tr:
        # "v" stages as dict_idx_num under dict_form="index"
        with pytest.raises(UnsupportedFeatureError, match="index-form"):
            tr.read_row_group_compute(
                0, ComputeRequest(aggregate=Aggregate((("v", "sum"),)))
            )
        out = tr.read_row_group_compute(
            0, ComputeRequest(aggregate=Aggregate((("v", "count"),)))
        )
    t = pq.read_table(str(path))
    want = sum(x is not None for x in t["v"].to_pylist()[:300])
    assert out.agg.finalize()["v_count"] == want


def test_surrogate_escape_string_key():
    """Predicate trees round-trip surrogate-escaped strings (a key
    copied from a row cell of a non-UTF8 BINARY column) instead of
    raising UnicodeEncodeError."""
    raw = b"\xff\xfekey"
    cell = raw.decode("utf-8", "surrogateescape")
    t = tree(col("s") == cell)
    assert t == ("cmp", "s", "==", raw)
    vals = np.array([raw, b"other"], dtype=object)
    m = eval_mask(col("s") == cell, lambda n: (vals, None), 2)
    assert list(m) == [True, False]


def test_device_page_prune_parity(tmp_path):
    """ScanOptions(page_prune=True) on the DEVICE leg: bit-parity with
    the host leg's covered rows (the storage rung composing under the
    device rung)."""
    # sorted key column → selective predicate prunes whole pages
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.INT64).named("x"),
    )
    path = tmp_path / "sorted.parquet"
    n, group = 1200, 400
    ks = np.arange(n, dtype=np.int64)
    xs = rng.integers(0, 10**6, n).astype(np.int64)
    with ParquetFileWriter(
        path, schema,
        WriterOptions(row_group_rows=group, data_page_values=100),
    ) as w:
        for lo in range(0, n, group):
            w.write_columns({
                "k": ks[lo:lo + group], "x": xs[lo:lo + group],
            })
    pred = (col("k") >= 150) & (col("k") < 250)
    sc = ScanOptions(page_prune=True)
    with trace.scope() as t:
        dev = []
        for _fi, _gi, cols in scan_device_groups(
            [str(path)], scan=sc, predicate=pred, float64_policy="bits",
        ):
            dev.append((np.asarray(cols["k"].values),
                        np.asarray(cols["x"].values)))
    assert t.counters().get("scan.pages_pruned", 0) > 0
    host = []
    with DatasetScanner([str(path)], scan=sc, predicate=pred) as s:
        for unit in s:
            res = {}
            for cb in unit.batch.columns:
                dense, _m = cb.dense()
                res[cb.descriptor.path[0]] = np.asarray(dense)
            host.append((res["k"], res["x"]))
    assert len(dev) == len(host)
    for (dk, dx), (hk, hx) in zip(dev, host):
        assert np.array_equal(dk, hk)
        assert np.array_equal(dx, hx)


def test_page_prune_composes_with_pushdown(tmp_path):
    """Storage rung + device rung: covered pages decode, the fused tail
    filters them — final rows identical to filtering the whole file."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.INT64).named("x"),
    )
    path = tmp_path / "sorted2.parquet"
    n, group = 1200, 400
    ks = np.arange(n, dtype=np.int64)
    xs = rng.integers(0, 10**6, n).astype(np.int64)
    with ParquetFileWriter(
        path, schema,
        WriterOptions(row_group_rows=group, data_page_values=100),
    ) as w:
        for lo in range(0, n, group):
            w.write_columns({
                "k": ks[lo:lo + group], "x": xs[lo:lo + group],
            })
    pred = (col("k") >= 190) & (col("k") < 210)
    got_k = []
    got_x = []
    for _fi, _gi, cols in scan_device_groups(
        [str(path)], scan=ScanOptions(page_prune=True, pushdown=True),
        predicate=pred, float64_policy="bits",
    ):
        got_k.append(np.asarray(cols["k"].values))
        got_x.append(np.asarray(cols["x"].values))
    got_k = np.concatenate(got_k)
    got_x = np.concatenate(got_x)
    sel = (ks >= 190) & (ks < 210)
    assert np.array_equal(got_k, ks[sel])
    assert np.array_equal(got_x, xs[sel])


# ---------------------------------------------------------------------------
# exec-cache interaction
# ---------------------------------------------------------------------------

def test_exec_cache_key_separation_per_predicate(tmp_path):
    """Same file, different predicate → different persistent entry;
    repeating a predicate in fresh 'processes' converges to hits.

    Since the persisted pushdown HWM landed (docs/pushdown.md), the
    FIRST warm run restores the observed selection HWM and therefore
    compiles once more at the right capacity (a different static
    signature than the cold run's initial-capacity guess); every run
    after that hits with zero compile — and never re-dispatches on an
    overflow, which is the trade the sidecar buys."""
    path = _write_mixed(tmp_path, n=300, group=300)
    cache_dir = tmp_path / "cache"

    def run(pred):
        exec_cache.activate(exec_cache.ExecutableCache(str(cache_dir)))
        try:
            with trace.scope() as t:
                with TpuRowGroupReader(
                    str(path), float64_policy="float64"
                ) as tr:
                    res = tr.read_row_group_compute(
                        0, ComputeRequest(predicate=pred)
                    )
                    k = np.asarray(res.columns["k"].values)
            return k, t.counters()
        finally:
            exec_cache.activate(None)

    k1, c1 = run(col("k") < 100)
    assert c1.get("engine.exec_cache_misses", 0) >= 1
    n_entries = len([
        f for f in os.listdir(cache_dir) if f.endswith(".pfexec")
    ])
    _k2, c2 = run(col("k") < 200)  # different literal → different entry
    n_entries2 = len([
        f for f in os.listdir(cache_dir) if f.endswith(".pfexec")
    ])
    assert n_entries2 > n_entries
    assert c2.get("engine.exec_cache_misses", 0) >= 1
    # warm run 1: the restored HWM re-keys the program at the observed
    # capacity — one more compile, zero overflows
    k3, c3 = run(col("k") < 100)
    assert np.array_equal(k1, k3)
    assert c3.get("engine.pushdown_overflows", 0) == 0
    # warm run 2 (same predicate, same restored HWM): pure hit
    k4, c4 = run(col("k") < 100)
    assert np.array_equal(k1, k4)
    assert c4.get("engine.exec_cache_hits", 0) >= 1
    assert c4.get("engine.exec_cache_misses", 0) == 0
    assert c4.get("engine.compile_ms", 0) == 0


def test_serve_dataset_aggregate(tmp_path):
    from parquet_floor_tpu.serve import Dataset

    path = _write_mixed(tmp_path)
    agg = Aggregate((("v", "sum"), ("v", "count")), group_by="cat")
    pred = col("k") < 400
    with Dataset([str(path)], key_column="k") as ds:
        with trace.scope() as t:
            fin = ds.aggregate(agg, predicate=pred).finalize()
    assert t.counters().get("serve.aggregate_probes") == 1
    want = scan_aggregate([str(path)], agg, predicate=pred,
                          engine="host").finalize()
    assert fin == want


def test_tree_export_and_columns():
    p = ((col("a") < 5) & (col("b") == "x")) | col("c").is_null()
    t = tree(p)
    assert t[0] == "or"
    assert tree_columns(t) == {"a", "b", "c"}
    with pytest.raises(TypeError):
        tree((col("a") == object()))


def test_host_partial_direct():
    """host_partial over raw arrays: the no-file unit contract."""
    agg = Aggregate((("x", "sum"), ("x", "min")), group_by="g")
    vals = {
        "x": (np.array([1, 2, 3, 4], np.int64), None),
        "g": (np.array([b"a", b"a", b"b", b"b"], object),
              np.array([False, False, False, True])),
    }
    part = host_partial(agg, lambda n: vals[n], 4,
                        sel=np.array([True, True, True, True]))
    fin = part.finalize()
    assert fin[b"a"] == {"x_sum": 3, "x_min": 1}
    assert fin[b"b"] == {"x_sum": 3, "x_min": 3}
    assert fin[None] == {"x_sum": 4, "x_min": 4}


# ---------------------------------------------------------------------------
# host-leg pushdown row compaction (PR 11 follow-on: both scan legs
# deliver the SAME row sets under ScanOptions(pushdown=True))
# ---------------------------------------------------------------------------

def test_host_leg_pushdown_matches_device_leg(tmp_path):
    """DatasetScanner under pushdown=True mask-compacts each decoded
    batch to exactly the rows the device leg's fused compact ships —
    including a string predicate and null-never-matches semantics."""
    paths = [
        str(_write_mixed(tmp_path, f"hp{i}.parquet", n=600, group=200))
        for i in range(2)
    ]
    pred = (col("d") < 500.0) & (col("cat") == "plum")
    sc = ScanOptions(pushdown=True, threads=2)
    with trace.scope() as t:
        with DatasetScanner(paths, predicate=pred, scan=sc) as s:
            host = [
                {cb.descriptor.path[0]: cb for cb in u.batch.columns}
                for u in s
            ]
    assert t.counters().get("scan.rows_filtered_host", 0) > 0
    dev = [
        cols for _f, _g, cols in scan_device_groups(
            paths, predicate=pred, scan=sc, float64_policy="float64"
        )
    ]
    assert len(host) == len(dev) > 0
    total = 0
    for h, d in zip(host, dev):
        assert set(h) == set(d)
        for name in ("k", "v", "f", "d"):
            hv = h[name].values
            dv = np.asarray(d[name].values)
            if h[name].def_levels is not None:
                # optional: device ships row-aligned values+mask, host
                # keeps non-null values — compare the present cells
                dm = np.asarray(d[name].mask)
                assert np.array_equal(np.asarray(hv), dv[~dm]), name
                assert np.array_equal(
                    np.asarray(h[name].null_mask), dm
                )
            else:
                assert np.array_equal(np.asarray(hv), dv), name
        # the string predicate held on every surviving row
        assert set(h["cat"].values.to_list()) <= {b"plum"}
        assert h["cat"].num_values == h["k"].num_values
        total += h["k"].num_values
    assert total > 0


def test_host_leg_pushdown_null_never_matches(tmp_path):
    """A predicate over an optional column: null cells never match on
    the host leg (pyarrow filter-drop semantics, device-identical)."""
    path = str(_write_mixed(tmp_path, "hpnull.parquet", n=400, group=200))
    pred = col("v") >= 0  # matches every NON-NULL v
    sc = ScanOptions(pushdown=True)
    rows = 0
    with DatasetScanner([path], predicate=pred, scan=sc) as s:
        for u in s:
            by = {cb.descriptor.path[0]: cb for cb in u.batch.columns}
            mask = by["v"].null_mask
            assert mask is not None and not mask.any()
            rows += u.batch.num_rows
    t = pq.read_table(path)
    assert rows == t.num_rows - t["v"].null_count


def test_host_leg_pushdown_composes_with_page_prune(tmp_path):
    """page_prune narrows what decodes; pushdown filters what ships —
    composed, the host leg still delivers exactly the predicate rows."""
    path = str(_write_mixed(tmp_path, "hppp.parquet", n=600, group=200))
    pred = col("k") < 100
    want = pq.read_table(path).filter(
        __import__("pyarrow").compute.less(
            pq.read_table(path)["k"], 100
        )
    )["k"].to_pylist()
    got = []
    sc = ScanOptions(pushdown=True, page_prune=True)
    with DatasetScanner([path], predicate=pred, scan=sc) as s:
        for u in s:
            by = {cb.descriptor.path[0]: cb for cb in u.batch.columns}
            got.extend(np.asarray(by["k"].values).tolist())
    assert sorted(got) == sorted(want)


def test_host_leg_pushdown_salvage_keeps_whole_groups(tmp_path):
    """Under salvage the host leg does NOT compact (quarantine
    decisions are group-wide): whole surviving batches deliver."""
    path = str(_write_mixed(tmp_path, "hpsal.parquet", n=400, group=200))
    pred = col("k") < 100
    sc = ScanOptions(pushdown=True)
    rows = sum(
        u.batch.num_rows
        for u in DatasetScanner(
            [path], predicate=pred, scan=sc,
            options=ReaderOptions(salvage=True),
        )
    )
    # groups the stats rung kept deliver WHOLE (no row compaction)
    t = pq.read_table(path)
    assert rows % 200 == 0 and rows >= 200


def test_host_leg_pushdown_rejects_repeated(tmp_path):
    schema = types.message(
        "r",
        types.required(types.INT64).named("a"),
        types.repeated(types.INT64).named("xs"),
    )
    p = tmp_path / "rep.parquet"
    with ParquetFileWriter(str(p), schema) as w:
        w.write_columns({"a": np.arange(4, dtype=np.int64),
                         "xs": [[1], [2, 3], [], [4]]})
    from parquet_floor_tpu.errors import UnsupportedFeatureError

    sc = ScanOptions(pushdown=True)
    with pytest.raises(UnsupportedFeatureError, match="flat"):
        list(DatasetScanner([str(p)], predicate=col("a") < 3, scan=sc))


def test_host_leg_pushdown_predicate_outside_projection(tmp_path):
    """The device-leg contract on host: a predicate column OUTSIDE the
    projection shapes the mask (decoded via the widened filter) but
    never ships — delivered batches carry exactly the projection, with
    the device leg's row sets."""
    paths = [
        str(_write_mixed(tmp_path, f"hproj{i}.parquet", n=600, group=200))
        for i in range(2)
    ]
    pred = col("d") < 400.0
    sc = ScanOptions(pushdown=True, threads=2)
    host = []
    with DatasetScanner(paths, columns=["k"], predicate=pred,
                        scan=sc) as s:
        for u in s:
            names = [cb.descriptor.path[0] for cb in u.batch.columns]
            assert names == ["k"]  # the predicate column never ships
            host.append(np.asarray(u.batch.columns[0].values))
    dev = [
        np.asarray(cols["k"].values)
        for _f, _g, cols in scan_device_groups(
            paths, columns=["k"], predicate=pred, scan=sc,
            float64_policy="float64",
        )
    ]
    assert len(host) == len(dev) > 0
    for h, d in zip(host, dev):
        assert np.array_equal(h, d)
