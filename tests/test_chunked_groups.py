"""Oversized-row-group chunking (VERDICT r3 #4): groups past the arena
cap split into multiple decode launches — column bins, then page-aligned
row segments — instead of erroring.  PFTPU_ARENA_CAP lowers the cap so
the chunk path proves bit-exact at test sizes; the reference streams
page-at-a-time with no group ceiling at all (ParquetReader.java:182-194).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader


def _assert_group_parity(path, dev_group, host_reader, gi):
    hb = host_reader.read_row_group(gi)
    for cb in hb.columns:
        nm = cb.descriptor.path[0]
        dc = dev_group[nm]
        dense, mask = cb.dense()
        if mask is not None:
            np.testing.assert_array_equal(np.asarray(dc.mask), mask, err_msg=nm)
        if isinstance(dense, ByteArrayColumn):
            lens = np.asarray(dc.lengths)
            rows = np.asarray(dc.values)
            got = [rows[i, : lens[i]].tobytes() for i in range(len(lens))]
            assert got == dense.to_list(), nm
        else:
            got = np.asarray(dc.values)
            if mask is not None:
                got = np.where(mask, 0, got)
                dense = np.where(mask, 0, dense)
            np.testing.assert_array_equal(got, dense, err_msg=nm)


def _write_mixed(path, n=6000, groups=2):
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.DOUBLE).named("b"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.INT32).named("c"),
    )
    rng = np.random.default_rng(11)
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY, data_page_values=500,
        enable_dictionary=True,
    )
    per = (n + groups - 1) // groups
    with ParquetFileWriter(path, schema, opts) as w:
        for lo in range(0, n, per):
            hi = min(lo + per, n)
            m = hi - lo
            w.write_columns({
                "a": rng.integers(-(2**62), 2**62, m).astype(np.int64),
                "b": [None if i % 9 == 0 else float(v)
                      for i, v in enumerate(rng.standard_normal(m))],
                "s": [None if i % 6 == 0 else f"str{i % 97}" for i in range(m)],
                "c": rng.integers(-(2**31), 2**31, m).astype(np.int32),
            })
    return str(path)


def test_column_bin_splitting(tmp_path, monkeypatch):
    """Cap far below the group size: every field decodes in its own
    launch; results merge bit-exact."""
    path = _write_mixed(tmp_path / "m.parquet")
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(24 << 10))
    with TpuRowGroupReader(path, float64_policy="float64") as tr, \
            ParquetFileReader(path) as hr:
        assert tr._arena_cap == 24 << 10
        for gi in range(tr.num_row_groups):
            est = tr._group_byte_estimate(tr.reader.row_groups[gi])
            assert est > tr._arena_cap  # the cap actually binds
            _assert_group_parity(path, tr.read_row_group(gi), hr, gi)


def test_row_split_single_big_column(tmp_path, monkeypatch):
    """One field alone exceeds the cap: it row-splits on the page grid
    and the segments concatenate bit-exact (required + optional +
    strings)."""
    path = _write_mixed(tmp_path / "r.parquet", n=8000, groups=1)
    # cap below every single field's bytes → every field row-splits
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(12 << 10))
    with TpuRowGroupReader(path, float64_policy="float64") as tr, \
            ParquetFileReader(path) as hr:
        _assert_group_parity(path, tr.read_row_group(0), hr, 0)


def test_iter_row_groups_mixes_chunked_and_pipelined(tmp_path, monkeypatch):
    path = _write_mixed(tmp_path / "i.parquet", n=9000, groups=3)
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(48 << 10))
    with TpuRowGroupReader(path, float64_policy="float64") as tr, \
            ParquetFileReader(path) as hr:
        groups = list(tr.iter_row_groups())
        assert len(groups) == tr.num_row_groups
        for gi, g in enumerate(groups):
            _assert_group_parity(path, g, hr, gi)


def test_projection_composes_with_chunking(tmp_path, monkeypatch):
    path = _write_mixed(tmp_path / "p.parquet")
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(24 << 10))
    with TpuRowGroupReader(path, float64_policy="float64") as tr, \
            ParquetFileReader(path) as hr:
        g = tr.read_row_group(0, columns=["a", "s"])
        assert set(g) == {"a", "s"}
        hb = hr.read_row_group(0)
        np.testing.assert_array_equal(
            np.asarray(g["a"].values), hb.column("a").values
        )


def test_ranged_read_respects_cap(tmp_path, monkeypatch):
    """read_row_group_ranges splits oversized covers into multiple
    launches too (the cap is an HBM bound — selective reads must not
    bypass it) and stays bit-exact vs the host ranged decode."""
    path = _write_mixed(tmp_path / "rr.parquet", n=8000, groups=1)
    ranges = [(100, 2600), (3100, 7400)]
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(12 << 10))
    with TpuRowGroupReader(path, float64_policy="float64") as tr, \
            ParquetFileReader(path) as hr:
        dev, covered = tr.read_row_group_ranges(0, ranges)
        assert covered and covered != [(0, 8000)]
        hb, hcov = hr.read_row_group_ranges(0, ranges)
        assert hcov == covered
        for cb in hb.columns:
            nm = cb.descriptor.path[0]
            dc = dev[nm]
            dense, mask = cb.dense()
            if mask is not None:
                np.testing.assert_array_equal(
                    np.asarray(dc.mask), mask, err_msg=nm
                )
            if isinstance(dense, ByteArrayColumn):
                lens = np.asarray(dc.lengths)
                rows = np.asarray(dc.values)
                got = [
                    rows[i, : lens[i]].tobytes() for i in range(len(lens))
                ]
                assert got == dense.to_list(), nm
            else:
                got = np.asarray(dc.values)
                if mask is not None:
                    got = np.where(mask, 0, got)
                    dense = np.where(mask, 0, dense)
                np.testing.assert_array_equal(got, dense, err_msg=nm)


def test_out_perm_composes_with_chunking(tmp_path, monkeypatch):
    """Oversized groups apply ``out_perm`` as a follow-up fused gather
    (_permuted_columns) instead of riding the decode executable: the
    permuted chunked read must equal the unpermuted read indexed by the
    permutation, across required/optional/string columns."""
    path = _write_mixed(tmp_path / "op.parquet", n=4000, groups=1)
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(24 << 10))
    rng = np.random.default_rng(5)
    perm = rng.permutation(4000).astype(np.int32)
    with TpuRowGroupReader(path, float64_policy="float64") as tr:
        est = tr._group_byte_estimate(tr.reader.row_groups[0])
        assert est > tr._arena_cap  # the chunk path actually runs
        plain = tr.read_row_group(0)
        shuffled = tr.read_row_group(0, out_perm=perm)
    for nm, dc in plain.items():
        sc = shuffled[nm]
        np.testing.assert_array_equal(
            np.asarray(sc.values), np.asarray(dc.values)[perm], err_msg=nm
        )
        if dc.mask is not None:
            np.testing.assert_array_equal(
                np.asarray(sc.mask), np.asarray(dc.mask)[perm], err_msg=nm
            )
        if dc.lengths is not None:
            np.testing.assert_array_equal(
                np.asarray(sc.lengths), np.asarray(dc.lengths)[perm],
                err_msg=nm,
            )


def test_no_offset_index_falls_back(tmp_path, monkeypatch):
    """A single over-cap column in a file WITHOUT an OffsetIndex cannot
    row-split: the device engine host-decodes the whole column in one
    launch instead of erroring (the reference streams page-at-a-time
    with no ceiling at all, ParquetReader.java:182-194), and records a
    chunk_fallback trace decision saying why."""
    from parquet_floor_tpu.utils import trace

    path = str(tmp_path / "noidx.parquet")
    pq.write_table(
        pa.table({"v": np.arange(50_000, dtype=np.int64)}),
        path, write_statistics=False, store_schema=False,
        use_dictionary=False, data_page_size=4 << 10,
        write_page_index=False, compression="NONE",
    )
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(16 << 10))
    trace.enable()
    trace.reset()
    try:
        with TpuRowGroupReader(path) as tr:
            g = tr.read_row_group(0)
            np.testing.assert_array_equal(
                np.asarray(g["v"].values), np.arange(50_000, dtype=np.int64)
            )
            assert "v" in tr._forced  # sticky host pin for later groups
        ds = [d for d in trace.decisions() if d["decision"] == "chunk_fallback"]
        assert ds and ds[-1]["why"] == "no OffsetIndex"
        assert "PFTPU_ARENA_CAP" in ds[-1]["action"]
    finally:
        trace.disable()


def test_single_huge_page_falls_back(tmp_path, monkeypatch):
    """An OffsetIndex exists but the one over-cap column is a single
    page — no boundary lands under the cap, so row-splitting is
    impossible and the host fallback runs instead of an error."""
    from parquet_floor_tpu.utils import trace

    # pyarrow caps pages at 20k rows regardless of data_page_size, so a
    # truly single-page over-cap chunk needs this repo's writer
    path = str(tmp_path / "onepage.parquet")
    schema = types.message("t", types.required(types.INT64).named("v"))
    opts = WriterOptions(
        codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
        data_page_values=100_000,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns({"v": np.arange(50_000, dtype=np.int64)})
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(16 << 10))
    trace.enable()
    trace.reset()
    try:
        with TpuRowGroupReader(path) as tr:
            g = tr.read_row_group(0)
            np.testing.assert_array_equal(
                np.asarray(g["v"].values), np.arange(50_000, dtype=np.int64)
            )
        ds = [d for d in trace.decisions() if d["decision"] == "chunk_fallback"]
        assert ds and ds[-1]["why"] == "no page boundary under the cap"
    finally:
        trace.disable()


def test_hostile_shape_matrix_front_door(tmp_path, monkeypatch):
    """VERDICT r4 #1 done-criterion: pyarrow-default hostile shapes (one
    big string column, no page index; plus a nullable big column) stream
    identically through engine=host/tpu/auto with zero user-visible
    errors, even when the over-cap column cannot row-split."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.tpu import cost
    from parquet_floor_tpu.tpu import engine as eng

    monkeypatch.setattr(eng, "_platform_is_tpu", lambda: True)
    monkeypatch.setenv("PFTPU_PALLAS", "0")
    monkeypatch.setattr(cost, "_probe_h2d_gbps", lambda: 1.25)
    monkeypatch.setattr(cost, "_probe_d2h_model", lambda: (0.035, 0.011))
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(32 << 10))

    n = 4000
    tables = {
        "bigstr": pa.table({
            "s": [f"payload-{i:06d}-" + "x" * (i % 37) for i in range(n)],
            "k": np.arange(n, dtype=np.int64),
        }),
        "nullable": pa.table({
            "v": pa.array(
                [None if i % 11 == 0 else float(i) for i in range(n)],
                type=pa.float64(),
            ),
        }),
    }

    class _Rows:
        def start(self):
            return []

        def add(self, t, h, v):
            t.append(v)
            return t

        def finish(self, t):
            return tuple(t)

    for name, table in tables.items():
        path = str(tmp_path / f"{name}.parquet")
        # pyarrow defaults: dictionary on, no page index
        pq.write_table(table, path, write_page_index=False)
        rows = {}
        for engine in ("host", "tpu", "auto"):
            rows[engine] = list(ParquetReader.stream_content(
                path, lambda c: _Rows(), engine=engine
            ))
        assert rows["host"] == rows["tpu"] == rows["auto"], name


def test_oversized_repeated_column_row_splits(tmp_path, monkeypatch):
    """Repeated leaves row-split too: segments' dense value streams pack
    by traced-count scatter and the assembled rows match the host
    (including empties/nulls and a string leaf)."""
    from parquet_floor_tpu.batch.nested import assemble_nested

    t = types
    rng = np.random.default_rng(5)
    for use_str in (False, True):
        eb = t.optional(t.BYTE_ARRAY if use_str else t.INT64)
        if use_str:
            eb = eb.as_(t.string())
        schema = t.message(
            "m", t.list_of(eb.named("element"), "v", optional=True)
        )
        path = str(tmp_path / f"rep{int(use_str)}.parquet")
        rows = []
        for i in range(12_000):
            r = rng.random()
            if r < 0.1:
                rows.append(None)
            else:
                ln = int(rng.integers(0, 4))
                rows.append([
                    None if rng.random() < 0.15
                    else (f"s{i % 31}" if use_str else int(i))
                    for _ in range(ln)
                ])
        with ParquetFileWriter(
            path, schema, WriterOptions(data_page_values=600)
        ) as w:
            w.write_columns({"v": rows})
        monkeypatch.setenv("PFTPU_ARENA_CAP", str(8 << 10))
        with ParquetFileReader(path) as hr:
            sch = hr.schema
            host_out = []
            for gi in range(len(hr.row_groups)):
                cb = hr.read_row_group(gi).columns[0]
                host_out.extend(assemble_nested(sch, cb).to_pylist())
        with TpuRowGroupReader(path) as tr:
            est = tr._group_byte_estimate(tr.reader.row_groups[0])
            assert est > tr._arena_cap  # the split path actually runs
            dev_out = []
            for gi in range(tr.num_row_groups):
                (dc,) = tr.read_row_group(gi).values()
                dev_out.extend(dc.assemble(sch).to_pylist())
        if use_str:
            host_out = [
                None if r is None
                else [None if e is None else bytes(e) for e in r]
                for r in host_out
            ]
            dev_out = [
                None if r is None
                else [None if e is None else bytes(e) for e in r]
                for r in dev_out
            ]
        assert dev_out == host_out, f"use_str={use_str}"
        # the RANGED read splits oversized repeated covers too
        with TpuRowGroupReader(path) as tr, ParquetFileReader(path) as hr:
            n0 = int(hr.row_groups[0].num_rows or 0)
            # interior range: whole pages fall outside, so the cover is
            # a strict subset and the ranged (not full-group) path runs
            ranges = [(2000, 4000), (7000, 9000)]
            dev, covered = tr.read_row_group_ranges(0, ranges)
            hb, hcov = hr.read_row_group_ranges(0, ranges)
            assert hcov == covered and covered != [(0, n0)]
            (dc,) = dev.values()
            got = dc.assemble(sch).to_pylist()
            want = assemble_nested(sch, hb.columns[0]).to_pylist()

            def norm(rows_):
                if not use_str:
                    return rows_
                return [
                    None if r is None
                    else [None if e is None else bytes(e) for e in r]
                    for r in rows_
                ]

            assert norm(got) == norm(want), f"ranged use_str={use_str}"
