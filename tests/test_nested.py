"""Dremel nested assembly/shredding vs the pyarrow oracle (BASELINE config
#5 capability; reference facade refuses nesting at ParquetReader.java:200-202
— this is the engine-level capability parquet-mr had underneath)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_floor_tpu import ParquetFileReader, ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.batch.nested import (
    assemble_nested,
    level_chain,
    shred_nested,
)


def _leaf_pylist(table, col, leaf_path):
    """Project pyarrow's nested pylist down to one leaf's nesting."""

    def proj(v, path):
        if v is None:
            return None
        if isinstance(v, list):
            # skip the synthetic 3-level wrapper names ("list", "element")
            return [proj(x, path[2:]) for x in v]
        if not path:
            return v
        if isinstance(v, dict):
            return proj(v.get(path[0]), path[1:])
        raise AssertionError(f"unexpected {v!r}")

    out = []
    for row in table.column(col).to_pylist():
        out.append(proj(row, leaf_path))
    return out


def _assemble_all(path):
    with ParquetFileReader(path) as r:
        out = {}
        for gi in range(len(r.row_groups)):
            for cb in r.read_row_group(gi).columns:
                if cb.rep_levels is None:
                    continue
                nc = assemble_nested(r.schema, cb)
                out.setdefault(cb.descriptor.path, []).extend(nc.to_pylist())
        return out


CASES = {
    "list_int": (
        pa.schema([("v", pa.list_(pa.int64()))]),
        {"v": [[1, 2, 3], [], None, [4], [5, 6]]},
    ),
    "list_struct": (
        pa.schema(
            [("v", pa.list_(pa.struct([("a", pa.int64()), ("b", pa.string())])))]
        ),
        {
            "v": [
                [{"a": 1, "b": "x"}, {"a": 2, "b": None}],
                [],
                None,
                [{"a": None, "b": "z"}],
            ]
        },
    ),
    "list_list": (
        pa.schema([("v", pa.list_(pa.list_(pa.int32())))]),
        {"v": [[[1], [2, 3]], [[]], [], None, [None, [4]]]},
    ),
    "struct_list": (
        pa.schema([("s", pa.struct([("xs", pa.list_(pa.float64()))]))]),
        {"s": [{"xs": [1.5, 2.5]}, {"xs": []}, {"xs": None}, None]},
    ),
    "deep": (
        pa.schema([("v", pa.list_(pa.struct([("w", pa.list_(pa.int64()))])))]),
        {
            "v": [
                [{"w": [1, 2]}, {"w": []}],
                [{"w": None}, None],
                [],
                None,
            ]
        },
    ),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_read_pyarrow_nested(tmp_path, case):
    schema, data = CASES[case]
    path = str(tmp_path / f"{case}.parquet")
    pq.write_table(pa.table(data, schema=schema), path)
    table = pq.read_table(path)
    got = _assemble_all(path)
    for leaf_path, rendered in got.items():
        col = leaf_path[0]
        exp = _leaf_pylist(table, col, list(leaf_path[1:]))
        exp = [_strip(e) for e in exp]
        rendered = [_strip(e) for e in rendered]
        assert rendered == exp, f"{case}:{'.'.join(leaf_path)}"


def _strip(v):
    """pyarrow leaf projection for a LIST renders the repeated level the
    same way we do — normalize floats/bytes for comparison."""
    if isinstance(v, list):
        return [_strip(x) for x in v]
    if isinstance(v, bytes):
        return v.decode()
    return v


def test_offsets_and_validity(tmp_path):
    schema = pa.schema([("v", pa.list_(pa.int64()))])
    data = {"v": [[1, 2, 3], [], None, [4]]}
    path = str(tmp_path / "o.parquet")
    pq.write_table(pa.table(data, schema=schema), path)
    with ParquetFileReader(path) as r:
        cb = r.read_row_group(0).columns[0]
        nc = assemble_nested(r.schema, cb)
    d = nc.depths[0]
    assert d.offsets.tolist() == [0, 3, 3, 3, 4]
    assert d.valid.tolist() == [True, True, False, True]
    assert nc.leaf_present.tolist() == [True, True, True, True]
    assert np.asarray(nc.values).tolist() == [1, 2, 3, 4]
    assert nc.num_rows == 4


def test_write_nested_roundtrip_pyarrow_reads(tmp_path):
    """Our writer shreds nested rows; pyarrow must read them identically."""
    schema = types.message(
        "m",
        types.list_of(
            types.required(types.INT64).named("element"), "v", optional=True
        ),
    )
    rows = [[1, 2, 3], [], None, [4], [5, 6, 7, 8]]
    path = str(tmp_path / "w.parquet")
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns({"v": rows})
    got = pq.read_table(path).column("v").to_pylist()
    assert got == rows
    # and our own reader agrees
    ours = _assemble_all(path)
    (leaf_rows,) = ours.values()
    assert leaf_rows == rows


def test_write_nested_list_of_strings(tmp_path):
    schema = types.message(
        "m",
        types.list_of(
            types.optional(types.BYTE_ARRAY).as_(types.string()).named("element"),
            "tags",
        ),
    )
    rows = [["a", "bb"], [], ["c", None, "dd"], []]
    path = str(tmp_path / "s.parquet")
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns({"tags": rows})
    got = pq.read_table(path).column("tags").to_pylist()
    assert got == rows


def test_shred_assemble_identity():
    schema = types.message(
        "m",
        types.list_of(
            types.required(types.INT32).named("element"), "v", optional=True
        ),
    )
    desc = schema.columns[0]
    rows = [[7], [], None, [1, 2, 3]]
    vals, defs, reps = shred_nested(schema, desc, rows)
    assert vals == [7, 1, 2, 3]
    # optional list (+1) + repeated group (+1); required element adds none
    assert defs.tolist() == [2, 1, 0, 2, 2, 2]
    assert reps.tolist() == [0, 0, 0, 0, 1, 1]


def test_level_chain():
    schema = types.message(
        "m",
        types.list_of(
            types.required(types.INT64).named("element"), "v", optional=True
        ),
    )
    chain = level_chain(schema, schema.columns[0].path)
    assert [(c.kind, c.def_level, c.rep_level) for c in chain] == [
        ("optional", 1, 0),
        ("repeated", 2, 1),
    ]


def test_multipage_nested(tmp_path):
    """Nested column split across several pages (writer keeps rows whole)."""
    rng = np.random.default_rng(5)
    rows = []
    for i in range(2000):
        k = int(rng.integers(0, 5))
        rows.append(None if k == 4 else [int(x) for x in rng.integers(0, 100, k)])
    schema = types.message(
        "m",
        types.list_of(
            types.required(types.INT64).named("element"), "v", optional=True
        ),
    )
    path = str(tmp_path / "mp.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=257)
    ) as w:
        w.write_columns({"v": rows})
    assert pq.read_table(path).column("v").to_pylist() == rows
    ours = _assemble_all(path)
    (leaf_rows,) = ours.values()
    assert leaf_rows == rows


# ---------------------------------------------------------------------------
# TPU engine: repeated columns decode on device, assemble on host
# ---------------------------------------------------------------------------

def _tpu_assembled(path):
    import jax
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    jax.config.update("jax_enable_x64", True)
    out = {}
    with ParquetFileReader(path) as host:
        schema = host.schema
    with TpuRowGroupReader(path) as r:
        for gi in range(r.num_row_groups):
            for name, dc in r.read_row_group(gi).items():
                assert dc.is_repeated
                nc = dc.assemble(schema)
                out.setdefault(name, []).extend(nc.to_pylist())
    return out


@pytest.mark.parametrize("version", [1, 2])
def test_tpu_engine_nested_ints(tmp_path, version):
    rng = np.random.default_rng(11)
    rows = []
    for i in range(3000):
        k = int(rng.integers(0, 6))
        rows.append(None if k == 5 else [int(x) for x in rng.integers(0, 50, k)])
    schema = types.message(
        "m",
        types.list_of(
            types.required(types.INT64).named("element"), "v", optional=True
        ),
    )
    path = str(tmp_path / "t.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=700, page_version=version)
    ) as w:
        w.write_columns({"v": rows})
    got = _tpu_assembled(path)
    assert got["v.list.element"] == rows


@pytest.mark.parametrize("version", [1, 2])
def test_tpu_engine_nested_strings(tmp_path, version):
    rng = np.random.default_rng(13)
    words = ["alpha", "bee", "ceratops", "", "dd"]
    rows = []
    for i in range(800):
        k = int(rng.integers(0, 4))
        rows.append([words[int(w)] for w in rng.integers(0, len(words), k)])
    schema = types.message(
        "m",
        types.list_of(
            types.required(types.BYTE_ARRAY).as_(types.string()).named("element"),
            "tags",
        ),
    )
    path = str(tmp_path / "s.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=300, page_version=version)
    ) as w:
        w.write_columns({"tags": rows})
    got = _tpu_assembled(path)
    assert [
        [e.decode() for e in row] for row in got["tags.list.element"]
    ] == rows


def test_tpu_engine_nested_pyarrow_file(tmp_path):
    """pyarrow-written LIST<STRUCT> (BASELINE config #5 shape) through the
    TPU engine."""
    rng = np.random.default_rng(17)
    data = []
    for i in range(1000):
        k = int(rng.integers(0, 4))
        data.append(
            None if k == 3 else [
                {"a": int(rng.integers(0, 9)), "b": float(rng.standard_normal())}
                for _ in range(k)
            ]
        )
    schema = pa.schema(
        [("v", pa.list_(pa.struct([("a", pa.int64()), ("b", pa.float64())])))]
    )
    path = str(tmp_path / "p.parquet")
    pq.write_table(pa.table({"v": data}, schema=schema), path)
    got = _tpu_assembled(path)
    exp_a = [None if row is None else [d["a"] for d in row] for row in data]
    exp_b = [None if row is None else [d["b"] for d in row] for row in data]
    # sibling leaves under one top-level group get distinct dotted keys
    assert got["v.list.element.a"] == exp_a
    assert got["v.list.element.b"] == exp_b


def test_map_type_read_and_write(tmp_path):
    """Parquet MAP columns: pyarrow-written maps assemble as parallel
    key/value leaves; our map_of schema round-trips through pyarrow."""
    # read: pyarrow-written
    t = pa.table({"m": pa.array(
        [[("a", 1), ("b", 2)], [], None, [("c", 3)]],
        type=pa.map_(pa.string(), pa.int64()),
    )})
    p1 = str(tmp_path / "pam.parquet")
    pq.write_table(t, p1)
    with ParquetFileReader(p1) as r:
        got = {}
        for cb in r.read_row_group(0).columns:
            got[cb.descriptor.path[-1]] = assemble_nested(r.schema, cb).to_pylist()
    assert got["key"] == [[b"a", b"b"], [], None, [b"c"]]
    assert got["value"] == [[1, 2], [], None, [3]]

    # write: our map_of schema, shredded per leaf, readable by pyarrow
    schema = types.message(
        "m",
        types.map_of(
            types.required(types.BYTE_ARRAY).as_(types.string()).named("key"),
            types.optional(types.INT64).named("value"),
            "tags", optional=True,
        ),
    )
    keys = [["x", "y"], [], None, ["z"]]
    vals = [[7, None], [], None, [9]]
    p2 = str(tmp_path / "ourm.parquet")
    with ParquetFileWriter(p2, schema, WriterOptions()) as w:
        w.write_columns({"tags.key_value.key": keys,
                         "tags.key_value.value": vals})
    back = pq.read_table(p2).column("tags").to_pylist()
    assert back == [[("x", 7), ("y", None)], [], None, [("z", 9)]]
