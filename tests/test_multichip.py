"""Multi-chip scan scheduler (``parallel/mesh.py`` + the engine's
(row group → device) placement; docs/multichip.md).

The load-bearing claims pinned here, all on the conftest's forced
8-device CPU mesh (``--xla_force_host_platform_device_count=8``):

* placement policy: CPU defaults OFF, ``PFTPU_MESH_DEVICES`` opts in /
  caps / disables, read at CALL time so env changes take effect;
* delivery is strictly in submission order and the decoded values are
  bit-identical to the single-device path (the whole speedup argument
  rests on this — every read face inherits it for free);
* per-device exec-cache entries: the key carries ``platform:id`` so k
  devices warm k DISTINCT persistent entries, and compilation locking
  is per-key (two devices' first compiles proceed concurrently);
* the DataLoader's mid-epoch checkpoint/resume stays bit-identical
  with the mesh on;
* abandoning a mesh scan drains every per-device ship worker;
* a tenant-bound sharded scan's device seconds land in that tenant's
  ledger (``Tenant.charge_device`` via the tracer hook).
"""

import os
import threading
import time

import jax
import numpy as np
import pytest

from parquet_floor_tpu import ReaderOptions, trace
from parquet_floor_tpu.parallel import mesh
from parquet_floor_tpu.scan import scan_device_groups
from parquet_floor_tpu.serve.tenancy import Serving

from tests.test_scan import _write

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("mesh_ds")
    return [_write(str(d / f"f{i}.parquet"), seed=i) for i in range(4)]


def _canon(cols):
    """Comparable content of one delivered group: raw values, strings
    trimmed to their lengths (pad widths follow staging order and are
    NOT contractual — the values are)."""
    out = {}
    for name, dc in sorted(cols.items()):
        v = np.asarray(dc.values)
        if getattr(dc, "lengths", None) is not None:
            ls = np.asarray(dc.lengths)
            out[name] = [bytes(row[:l]) for row, l in zip(v, ls)]
        else:
            out[name] = v.tobytes()
        if getattr(dc, "mask", None) is not None:
            out[name + "#mask"] = np.asarray(dc.mask).tobytes()
    return out


def _scan(paths, **kw):
    got = []
    for fi, gi, cols in scan_device_groups(paths, columns=["k", "d", "s"],
                                           **kw):
        got.append((fi, gi, _canon(cols)))
    return got


# ---------------------------------------------------------------------------
# placement policy
# ---------------------------------------------------------------------------


def test_mesh_policy_cpu_defaults_off(monkeypatch):
    monkeypatch.delenv("PFTPU_MESH_DEVICES", raising=False)
    assert mesh.mesh_devices() == []
    assert not mesh.mesh_enabled()


def test_mesh_policy_env_read_at_call_time(monkeypatch):
    monkeypatch.setenv("PFTPU_MESH_DEVICES", "4")
    devs = mesh.mesh_devices()
    assert len(devs) == 4
    assert devs == jax.local_devices()[:4]
    monkeypatch.setenv("PFTPU_MESH_DEVICES", "all")
    assert mesh.mesh_devices() == jax.local_devices()
    for off in ("0", "1"):
        monkeypatch.setenv("PFTPU_MESH_DEVICES", off)
        assert mesh.mesh_devices() == []
    monkeypatch.setenv("PFTPU_MESH_DEVICES", "many")
    with pytest.raises(ValueError, match="PFTPU_MESH_DEVICES"):
        mesh.mesh_devices()


def test_device_pools_contract():
    devs = jax.local_devices()[:3]
    with mesh.DevicePools(devs) as dp:
        assert len(dp) == 3
        names = [
            dp.submit(d, lambda: threading.current_thread().name).result()
            for d in devs
        ]
        assert all(n.startswith("pftpu-devship") for n in names)
        assert len(set(names)) == 3          # one worker PER device
        # per-device serialization: two tasks on one device run in
        # submission order on the same thread
        order = []
        f1 = dp.submit(devs[0], lambda: order.append(1))
        f2 = dp.submit(devs[0], lambda: order.append(2))
        f2.result(), f1.result()
        assert order == [1, 2]
    dp.shutdown()  # idempotent after __exit__
    with pytest.raises(RuntimeError):
        dp.submit(devs[0], lambda: None)


# ---------------------------------------------------------------------------
# delivery bit-identity + scheduler accounting
# ---------------------------------------------------------------------------


def test_mesh_scan_delivery_bit_identical(dataset, monkeypatch):
    monkeypatch.delenv("PFTPU_MESH_DEVICES", raising=False)
    single = _scan(dataset)
    n_groups = len(single)
    assert n_groups == 8  # 4 files x 2 groups

    monkeypatch.setenv("PFTPU_MESH_DEVICES", "4")
    with trace.scope() as t:
        meshed = _scan(dataset)
    assert [(fi, gi) for fi, gi, _ in meshed] == \
        [(fi, gi) for fi, gi, _ in single]            # strict order
    assert meshed == single                           # bit-identical
    c = t.counters()
    assert c.get("engine.mesh_groups") == n_groups    # all groups placed
    assert c.get("engine.launches") == n_groups       # one launch each
    assert t.gauges().get("engine.mesh_devices") == 4
    assert any(d.get("decision") == "engine.mesh" for d in t.decisions())


def test_mesh_scan_salvage_face_unchanged(dataset, tmp_path, monkeypatch):
    """Salvage units keep the single-device path under the mesh — the
    damaged-unit quarantine face is identical with the mesh on."""
    from tests.test_scan import _break_required_chunk

    paths = list(dataset)
    paths[1] = _break_required_chunk(dataset[1], tmp_path, 1, "k", "meshq")
    opts = ReaderOptions(salvage=True)
    monkeypatch.delenv("PFTPU_MESH_DEVICES", raising=False)
    single = _scan(paths, options=opts)
    monkeypatch.setenv("PFTPU_MESH_DEVICES", "4")
    assert _scan(paths, options=opts) == single


# ---------------------------------------------------------------------------
# per-device exec-cache entries, per-key compile locking
# ---------------------------------------------------------------------------


def test_exec_cache_per_device_entries(tmp_path):
    from parquet_floor_tpu.tpu.exec_cache import ExecutableCache

    cache = ExecutableCache(str(tmp_path))
    fn = jax.jit(lambda x: x * 2 + 1)
    args = [np.arange(16, dtype=np.int64)]
    devs = jax.local_devices()[:2]
    outs = [np.asarray(cache.call(fn, (), args, device=d)) for d in devs]
    entries = [n for n in os.listdir(tmp_path) if n.endswith(".pfexec")]
    assert len(set(entries)) == 2   # same program, one entry PER device
    assert np.array_equal(outs[0], outs[1])
    # a repeat on either device hits its own entry, no new file
    np.asarray(cache.call(fn, (), args, device=devs[0]))
    assert sorted(
        n for n in os.listdir(tmp_path) if n.endswith(".pfexec")
    ) == sorted(entries)


def test_compile_locks_are_per_key():
    """Two devices' first compiles must not contend on one global lock:
    the barrier below only releases if both keys' critical sections are
    held CONCURRENTLY (a shared lock would break the barrier)."""
    from parquet_floor_tpu.tpu import exec_cache as ec

    ka = ec._key_compile_lock("meshlock-a")
    assert ka is ec._key_compile_lock("meshlock-a")      # stable per key
    assert ka is not ec._key_compile_lock("meshlock-b")  # distinct keys

    bar = threading.Barrier(2)
    errs = []

    def hold(key):
        try:
            with ec._key_compile_lock(key):
                bar.wait(timeout=10)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=hold, args=(k,))
          for k in ("meshlock-a", "meshlock-b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    assert errs == []


def test_concurrent_compiles_restore_compilation_cache_flag():
    from parquet_floor_tpu.tpu import exec_cache as ec

    prev = bool(jax.config.jax_enable_compilation_cache)
    fns = [jax.jit(lambda x: x + 1), jax.jit(lambda x: x - 1)]
    args = [np.arange(8, dtype=np.int64)]
    errs = []

    def compile_one(i):
        try:
            ec._compile_fresh(fns[i], (), args, key=f"meshflag-{i}")
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=compile_one, args=(i,)) for i in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert errs == []
    assert ec._flag_depth == 0  # refcount fully unwound
    assert bool(jax.config.jax_enable_compilation_cache) == prev


# ---------------------------------------------------------------------------
# loader resume, abandonment, tenancy
# ---------------------------------------------------------------------------


def test_mesh_loader_resume_bit_identical(dataset, monkeypatch):
    from tests.test_data import _stream

    kw = dict(engine="tpu", loader_kw={"float64_policy": "float64"},
              num_epochs=1)
    monkeypatch.delenv("PFTPU_MESH_DEVICES", raising=False)
    single = _stream(dataset, **kw)
    monkeypatch.setenv("PFTPU_MESH_DEVICES", "4")
    assert _stream(dataset, **kw) == single
    assert _stream(dataset, restore_at=3, **kw) == single[3:]


def _devship_threads():
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("pftpu-devship")]


def test_mesh_abandonment_drains_device_workers(dataset, monkeypatch):
    from parquet_floor_tpu import ParquetFileReader
    from parquet_floor_tpu.tpu.engine import (
        TpuRowGroupReader,
        iter_dataset_row_groups,
    )

    monkeypatch.setenv("PFTPU_MESH_DEVICES", "4")
    opened = []

    def opener(fi):
        def open_():
            r = TpuRowGroupReader(ParquetFileReader(dataset[fi]))
            opened.append(r)
            return r
        return open_

    def stream():
        for fi in range(4):
            yield (opener(fi), 0, False)
            yield (opener(fi), 1, True)

    gen = iter_dataset_row_groups(stream(), columns=["k"])
    next(gen)
    assert _devship_threads()  # the mesh really span up per-device workers
    gen.close()                # abandon mid-stream
    assert all(r.reader._closed for r in opened)
    deadline = time.monotonic() + 10
    while _devship_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert _devship_threads() == []


def test_tenant_charged_for_mesh_device_seconds(dataset, monkeypatch):
    monkeypatch.setenv("PFTPU_MESH_DEVICES", "4")
    with Serving(prefetch_bytes=8 << 20) as srv:
        with srv.tenant("mesh-a") as ta:
            with trace.using(ta.tracer):
                n = len(_scan(dataset))
            assert n == 8
            hist = ta.tracer.histograms().get("serve.device_seconds")
            assert hist is not None and hist.count > 0
            rep = ta.report(wall_seconds=1.0)
            assert "serve.device_seconds" in rep.histograms
        # another tenant that never scanned has no device ledger
        with srv.tenant("mesh-b") as tb:
            assert tb.tracer.histograms().get("serve.device_seconds") is None
