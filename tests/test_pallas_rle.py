"""Interpret-mode equivalence: Pallas rle_expand kernel vs jnp reference.

The CI analogue of testing TPU kernels without a TPU (SURVEY.md §4 lesson):
``interpret=True`` runs the kernel's semantics on CPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
from parquet_floor_tpu.format.encodings.dictionary import encode_dict_indices
from parquet_floor_tpu.tpu import bitops
from parquet_floor_tpu.tpu.kernels.rle_kernel import (
    PL_MAX_RUNS,
    PL_RUN_WIN,
    TILE,
    max_aligned_span,
    rle_expand_pallas,
    rle_expand_pallas_hbm,
    tile_spans,
)


def _roundtrip_case(values: np.ndarray, bit_width: int):
    """Encode values as an RLE/bit-packed hybrid stream, parse the run
    table, and return everything both expanders need."""
    stream = e_rle.encode_rle_hybrid(values, bit_width)
    table, _ = e_rle.parse_runs(stream, len(values), bit_width)
    pad = bitops.bucket_size(max(len(table), 1), 16)
    plan = bitops.run_table_to_device_plan(table, len(values), pad)
    buf = np.zeros(len(stream) + 8, np.uint8)
    buf[: len(stream)] = np.frombuffer(stream, np.uint8)
    return buf, plan


def _expand_both(buf, plan, n, bw):
    lo, hi = tile_spans(plan["run_out_end"], n)
    got = rle_expand_pallas(
        jnp.asarray(buf),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        jnp.asarray(lo),
        jnp.asarray(hi),
        num_values=n,
        bit_width=bw,
        interpret=True,
    )
    want = bitops.rle_expand(
        jnp.asarray(buf),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        n,
        bw,
    )
    return np.asarray(got), np.asarray(want)


@pytest.mark.parametrize(
    "bw", [1, 2, 3, 5, 8, 9, 12, 15, 16, 17, 20, 23, 24, 25, 26, 27, 28,
           29, 30, 31, 32]
)
def test_mixed_runs_match_reference(bw):
    rng = np.random.default_rng(bw)
    n = 3 * TILE + 517  # several tiles + ragged tail
    # full-range values so every byte of wide fields is exercised
    vals = (
        rng.integers(0, 1 << 32, n, dtype=np.uint64) & ((1 << bw) - 1)
    ).astype(np.uint32)
    # carve long constant stretches so the stream mixes RLE and packed runs
    vals[100:2200] = 3
    vals[TILE : TILE + 900] = np.uint32((1 << bw) - 1)
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_run_boundary_mid_tile():
    # run flips exactly inside a tile; packed run starts mid-tile
    bw = 7
    n = 2 * TILE
    vals = np.full(n, 9, np.uint32)
    vals[TILE + 37 :] = np.arange(n - TILE - 37, dtype=np.uint32) % 100
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_single_short_tile():
    bw = 4
    n = 333
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 16, n).astype(np.uint32)
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def _expand_hbm(buf, plan, n, bw):
    """Expand via the HBM-plan kernel (run window DMA'd per tile)."""
    lo, hi = tile_spans(plan["run_out_end"], n)
    assert max_aligned_span(lo, hi) <= PL_RUN_WIN
    flat = np.concatenate([
        plan["run_out_end"], plan["run_kind"], plan["run_value"],
        plan["run_bytebase"], np.zeros_like(plan["run_out_end"]),
    ]).astype(np.int32)
    got = rle_expand_pallas_hbm(
        jnp.asarray(buf), jnp.asarray(flat), len(plan["run_out_end"]),
        jnp.asarray(lo), jnp.asarray(hi),
        num_values=n, bit_width=bw, interpret=True,
    )
    want = bitops.rle_expand(
        jnp.asarray(buf),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        n,
        bw,
    )
    return np.asarray(got), np.asarray(want)


@pytest.mark.parametrize("bw", [1, 3, 8, 12, 17, 24, 26, 29, 31, 32])
def test_hbm_plan_run_heavy(bw):
    """Run counts far past the scalar-prefetch gate decode via the
    HBM-plan kernel (VERDICT round-2 weak #1: ~125k-run streams)."""
    rng = np.random.default_rng(bw)
    n = 24 * TILE + 411
    # value repeated 9x → the encoder emits one RLE run per stretch:
    # ~5.5k runs, ~2.7x past PL_MAX_RUNS
    base = (
        rng.integers(0, 1 << 32, n // 9 + 1, dtype=np.uint64)
        & ((1 << bw) - 1)
    ).astype(np.uint32)
    vals = np.repeat(base, 9)[:n]
    # splice in packed stretches so both run kinds cross tile boundaries
    vals[TILE - 100 : TILE + 100] = (
        rng.integers(0, 1 << 32, 200, dtype=np.uint64) & ((1 << bw) - 1)
    ).astype(np.uint32)
    buf, plan = _roundtrip_case(vals, bw)
    assert len(plan["run_out_end"]) > PL_MAX_RUNS
    got, want = _expand_hbm(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_hbm_plan_alternating_single_values():
    """Worst-case run density: the encoder's packed groups flip every 8
    values; tiles intersect hundreds of runs, windows stay in bounds."""
    bw = 5
    n = 8 * TILE
    rng = np.random.default_rng(99)
    # alternate 8-long constant stretches and 8-long random stretches
    vals = np.empty(n, np.uint32)
    for s in range(0, n, 16):
        vals[s : s + 8] = rng.integers(0, 32)
        vals[s + 8 : s + 16] = rng.integers(0, 32, 8)
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_hbm(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_hbm_matches_smem_kernel():
    """Both kernel formulations agree on the same mid-size stream."""
    bw = 11
    n = 5 * TILE + 77
    rng = np.random.default_rng(7)
    vals = np.repeat(
        rng.integers(0, 1 << bw, n // 12 + 1).astype(np.uint32), 12
    )[:n]
    buf, plan = _roundtrip_case(vals, bw)
    got_hbm, want = _expand_hbm(buf, plan, n, bw)
    got_smem, _ = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got_hbm, want)
    np.testing.assert_array_equal(got_smem, want)


def test_engine_routes_run_heavy_to_hbm_kernel(tmp_path, monkeypatch):
    """End to end: with the scalar-prefetch gate forced tiny, a dictionary
    file's index stream takes the HBM-plan kernel and still decodes
    exactly (the engine's _pallas_plan → _expand dispatch)."""
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
    from parquet_floor_tpu.format.file_read import ParquetFileReader
    from parquet_floor_tpu.tpu import engine as eng_mod
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    monkeypatch.setenv("PFTPU_PALLAS", "1")  # interpret-mode kernels on CPU
    monkeypatch.setattr(eng_mod.plk, "PL_MAX_RUNS", 16)

    rng = np.random.default_rng(5)
    n = 3 * TILE
    data = np.repeat(rng.integers(0, 50, n // 9 + 1), 9)[:n].astype(np.int64)
    schema = types.message("t", types.required(types.INT64).named("v"))
    path = str(tmp_path / "runheavy.parquet")
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns({"v": data})

    with TpuRowGroupReader(path) as t:
        sg = t._stage_row_group(0, None)
        specs = {s.name: s for s in sg.program}
        assert specs["v"].kind == "dict"
        assert specs["v"].pl_idx and specs["v"].pl_idx[4] == 1, specs["v"].pl_idx
        cols = t._launch(sg)
        got = np.asarray(cols["v"].values)
    with ParquetFileReader(path) as r:
        want = r.read_row_group(0).columns[0].values
    np.testing.assert_array_equal(got, want)


def test_dictionary_stream_shape():
    # end-to-end: a real dictionary-index stream as the writer emits it
    rng = np.random.default_rng(1)
    n = TILE + 777
    idx = rng.integers(0, 200, n).astype(np.uint32)
    idx[50:4000] = 11
    stream = encode_dict_indices(idx, 200)
    bw = stream[0]
    table, _ = e_rle.parse_runs(stream, n, bw, 1)
    pad = bitops.bucket_size(max(len(table), 1), 16)
    plan = bitops.run_table_to_device_plan(table, n, pad)
    buf = np.zeros(len(stream) + 8, np.uint8)
    buf[: len(stream)] = np.frombuffer(stream, np.uint8)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, idx.astype(np.int32))
