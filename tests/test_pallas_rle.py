"""Interpret-mode equivalence: Pallas rle_expand kernel vs jnp reference.

The CI analogue of testing TPU kernels without a TPU (SURVEY.md §4 lesson):
``interpret=True`` runs the kernel's semantics on CPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
from parquet_floor_tpu.format.encodings.dictionary import encode_dict_indices
from parquet_floor_tpu.tpu import bitops
from parquet_floor_tpu.tpu.kernels.rle_kernel import (
    TILE,
    rle_expand_pallas,
    tile_spans,
)


def _roundtrip_case(values: np.ndarray, bit_width: int):
    """Encode values as an RLE/bit-packed hybrid stream, parse the run
    table, and return everything both expanders need."""
    stream = e_rle.encode_rle_hybrid(values, bit_width)
    table, _ = e_rle.parse_runs(stream, len(values), bit_width)
    pad = bitops.bucket_size(max(len(table), 1), 16)
    plan = bitops.run_table_to_device_plan(table, len(values), pad)
    buf = np.zeros(len(stream) + 8, np.uint8)
    buf[: len(stream)] = np.frombuffer(stream, np.uint8)
    return buf, plan


def _expand_both(buf, plan, n, bw):
    lo, hi = tile_spans(plan["run_out_end"], n)
    got = rle_expand_pallas(
        jnp.asarray(buf),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        jnp.asarray(lo),
        jnp.asarray(hi),
        num_values=n,
        bit_width=bw,
        interpret=True,
    )
    want = bitops.rle_expand(
        jnp.asarray(buf),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        n,
        bw,
    )
    return np.asarray(got), np.asarray(want)


@pytest.mark.parametrize(
    "bw", [1, 2, 3, 5, 8, 9, 12, 15, 16, 17, 20, 23, 24, 27, 32]
)
def test_mixed_runs_match_reference(bw):
    rng = np.random.default_rng(bw)
    n = 3 * TILE + 517  # several tiles + ragged tail
    # full-range values so every byte of wide fields is exercised
    vals = (
        rng.integers(0, 1 << 32, n, dtype=np.uint64) & ((1 << bw) - 1)
    ).astype(np.uint32)
    # carve long constant stretches so the stream mixes RLE and packed runs
    vals[100:2200] = 3
    vals[TILE : TILE + 900] = np.uint32((1 << bw) - 1)
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_run_boundary_mid_tile():
    # run flips exactly inside a tile; packed run starts mid-tile
    bw = 7
    n = 2 * TILE
    vals = np.full(n, 9, np.uint32)
    vals[TILE + 37 :] = np.arange(n - TILE - 37, dtype=np.uint32) % 100
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_single_short_tile():
    bw = 4
    n = 333
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 16, n).astype(np.uint32)
    buf, plan = _roundtrip_case(vals, bw)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)


def test_dictionary_stream_shape():
    # end-to-end: a real dictionary-index stream as the writer emits it
    rng = np.random.default_rng(1)
    n = TILE + 777
    idx = rng.integers(0, 200, n).astype(np.uint32)
    idx[50:4000] = 11
    stream = encode_dict_indices(idx, 200)
    bw = stream[0]
    table, _ = e_rle.parse_runs(stream, n, bw, 1)
    pad = bitops.bucket_size(max(len(table), 1), 16)
    plan = bitops.run_table_to_device_plan(table, n, pad)
    buf = np.zeros(len(stream) + 8, np.uint8)
    buf[: len(stream)] = np.frombuffer(stream, np.uint8)
    got, want = _expand_both(buf, plan, n, bw)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, idx.astype(np.int32))
