"""Second-chance (CLOCK) eviction in the shm cache rings
(serve/shm_cache.py): a re-read entry carries an access stamp, and the
evictor rescues a stamped tail back to the head instead of dropping it
— hot ranges survive a cold churn that would flush a pure FIFO ring."""

import pytest

from parquet_floor_tpu.serve.shm_cache import ShmCacheTier

KEY = ("lru-test", 1 << 20)


@pytest.fixture()
def tier():
    t = ShmCacheTier.create(data_bytes=64 << 10, meta_bytes=64 << 10,
                            slots=256, flights=16)
    try:
        yield t
    finally:
        t.close()


def test_hot_range_survives_cold_churn(tier):
    hot = b"h" * 2048
    tier.put(KEY, 0, hot)
    assert tier.get(KEY, 0, 2048) == hot
    # churn: 200 cold inserts (~6x the ring), re-touching the hot
    # entry between batches so its stamp is fresh at each eviction
    for i in range(200):
        tier.put(KEY, (i + 1) << 12, b"c" * 2048)
        if i % 4 == 0:
            assert tier.get(KEY, 0, 2048) == hot
    assert tier.get(KEY, 0, 2048) == hot
    st = tier.stats()
    assert st["rescues"] >= 1, st
    assert st["evictions"] >= 100  # the cold mass really churned


def test_cold_entries_still_evict(tier):
    # never-re-read entries must NOT be rescued — the ring would
    # deadlock at capacity otherwise
    for i in range(200):
        tier.put(KEY, i << 12, b"c" * 2048)
    st = tier.stats()
    assert st["evictions"] >= 150, st
    assert st["entries"] <= 40
    # the oldest cold entries are gone
    assert tier.get(KEY, 0, 2048) is None


def test_rescue_preserves_bytes_and_lookup(tier):
    # a rescued entry must still serve its exact bytes from the NEW
    # heap position
    data = bytes(range(256)) * 8
    tier.put(KEY, 0, data)
    tier.get(KEY, 0, len(data))  # stamp it
    for i in range(200):
        tier.put(KEY, (i + 1) << 12, b"c" * 2048)
        if i % 3 == 0:
            got = tier.get(KEY, 0, len(data))
            if got is not None:
                assert got == data
    # whether it ultimately survived depends on churn length; what is
    # NEVER allowed is a corrupt rescue
    got = tier.get(KEY, 0, len(data))
    assert got is None or got == data


def test_stamp_is_one_shot(tier):
    # one lookup buys ONE rescue, not immortality: a stamped entry
    # that is never re-read again is evicted on its second lap
    tier.put(KEY, 0, b"h" * 2048)
    tier.get(KEY, 0, 2048)  # single stamp, never touched again
    for i in range(400):
        tier.put(KEY, (i + 1) << 12, b"c" * 2048)
    assert tier.get(KEY, 0, 2048) is None
    assert tier.stats()["rescues"] >= 1
