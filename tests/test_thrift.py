"""Thrift compact protocol unit tests (SURVEY.md §4: per-layer tests the
reference skipped because parquet-mr owned the format)."""

import pytest

from parquet_floor_tpu.format.thrift import (
    CompactReader,
    CompactWriter,
    T_BOOL,
    T_BINARY,
    T_I32,
    T_I64,
    T_STRING,
    TList,
    ThriftStruct,
    zigzag_decode,
    zigzag_encode,
)
from parquet_floor_tpu.format.parquet_thrift import (
    FileMetaData,
    LogicalType,
    PageHeader,
    SchemaElement,
    Statistics,
    StringType,
)


class Inner(ThriftStruct):
    FIELDS = {1: ("a", T_I32), 2: ("name", T_STRING)}


class Outer(ThriftStruct):
    FIELDS = {
        1: ("flag", T_BOOL),
        2: ("big", T_I64),
        3: ("inner", Inner),
        4: ("items", TList(T_I32)),
        5: ("blob", T_BINARY),
        16: ("far_field", T_I32),  # forces long-form field header
    }


def test_zigzag_roundtrip():
    for v in [0, 1, -1, 2, -2, 63, -64, 2**31 - 1, -(2**31), 2**62, -(2**62)]:
        assert zigzag_decode(zigzag_encode(v)) == v


def test_varint_roundtrip():
    w = CompactWriter()
    values = [0, 1, 127, 128, 300, 2**21, 2**35, 2**63 - 1]
    for v in values:
        w.write_varint(v)
    r = CompactReader(w.getvalue())
    assert [r.read_varint() for _ in values] == values


def test_struct_roundtrip():
    obj = Outer(
        flag=True,
        big=-(2**40),
        inner=Inner(a=-5, name="héllo"),
        items=[1, 2, 3, -4, 5000],
        blob=b"\x00\xff\x10",
        far_field=42,
    )
    data = obj.to_bytes()
    back, end = Outer.from_bytes(data)
    assert end == len(data)
    assert back == obj


def test_false_bool_and_none_fields():
    obj = Outer(flag=False, items=[])
    back, _ = Outer.from_bytes(obj.to_bytes())
    assert back.flag is False
    assert back.items == []
    assert back.big is None and back.inner is None


def test_unknown_field_skipped():
    class V2(ThriftStruct):
        FIELDS = {1: ("a", T_I32), 2: ("extra", Inner), 3: ("z", T_STRING)}

    class V1(ThriftStruct):
        FIELDS = {1: ("a", T_I32), 3: ("z", T_STRING)}

    v2 = V2(a=7, extra=Inner(a=1, name="x"), z="keep")
    v1, _ = V1.from_bytes(v2.to_bytes())
    assert v1.a == 7 and v1.z == "keep"


def test_long_list_header():
    class L(ThriftStruct):
        FIELDS = {1: ("xs", TList(T_I32))}

    xs = list(range(100))
    back, _ = L.from_bytes(L(xs=xs).to_bytes())
    assert back.xs == xs


def test_nested_parquet_structures():
    ph = PageHeader(
        type=0,
        uncompressed_page_size=100,
        compressed_page_size=50,
        crc=-123456,
    )
    back, _ = PageHeader.from_bytes(ph.to_bytes())
    assert back == ph

    se = SchemaElement(name="col", type=2, repetition_type=1,
                       logicalType=LogicalType(STRING=StringType()))
    back, _ = SchemaElement.from_bytes(se.to_bytes())
    assert back.logicalType.STRING is not None

    st = Statistics(null_count=3, min_value=b"\x01", max_value=b"\x09",
                    is_max_value_exact=True)
    back, _ = Statistics.from_bytes(st.to_bytes())
    assert back == st


def test_empty_filemetadata_fields():
    fm = FileMetaData(version=2, num_rows=0, schema=[SchemaElement(name="root", num_children=0)])
    back, _ = FileMetaData.from_bytes(fm.to_bytes())
    assert back.version == 2
    assert back.num_rows == 0
    assert len(back.schema) == 1


def test_unknown_list_of_bool_field_skipped():
    """Regression: bools occupy one byte as container elements; skipping an
    unknown list<bool> field must consume them and stay in sync."""

    class V2(ThriftStruct):
        FIELDS = {1: ("bools", TList(T_BOOL)), 2: ("x", T_I32)}

    class V1(ThriftStruct):
        FIELDS = {2: ("x", T_I32)}

    v2 = V2(bools=[True, False, True], x=42)
    v1, end = V1.from_bytes(v2.to_bytes())
    assert v1.x == 42
    assert end == len(v2.to_bytes())


def test_bool_list_roundtrip():
    """Regression: bools as container elements occupy one payload byte in
    compact protocol (1=true, 2=false) — they are NOT header-encoded like
    field-position bools.  Mis-reading desyncs every later field."""
    from parquet_floor_tpu.format.parquet_thrift import ColumnIndex

    ci = ColumnIndex(
        null_pages=[False, True, False],
        min_values=[b"a", b"", b"c"],
        max_values=[b"z", b"", b"y"],
        boundary_order=0,
        null_counts=[0, 5, 1],
    )
    out, _ = ColumnIndex.from_bytes(ci.to_bytes())
    assert out.null_pages == [False, True, False]
    assert out.min_values == [b"a", b"", b"c"]
    assert out.max_values == [b"z", b"", b"y"]
    assert out.null_counts == [0, 5, 1]
