"""Thrift compact protocol unit tests (SURVEY.md §4: per-layer tests the
reference skipped because parquet-mr owned the format)."""

import pytest

from parquet_floor_tpu.format.thrift import (
    CompactReader,
    CompactWriter,
    T_BOOL,
    T_BINARY,
    T_I32,
    T_I64,
    T_STRING,
    TList,
    ThriftStruct,
    zigzag_decode,
    zigzag_encode,
)
from parquet_floor_tpu.format.parquet_thrift import (
    FileMetaData,
    LogicalType,
    PageHeader,
    SchemaElement,
    Statistics,
    StringType,
)


class Inner(ThriftStruct):
    FIELDS = {1: ("a", T_I32), 2: ("name", T_STRING)}


class Outer(ThriftStruct):
    FIELDS = {
        1: ("flag", T_BOOL),
        2: ("big", T_I64),
        3: ("inner", Inner),
        4: ("items", TList(T_I32)),
        5: ("blob", T_BINARY),
        16: ("far_field", T_I32),  # forces long-form field header
    }


def test_zigzag_roundtrip():
    for v in [0, 1, -1, 2, -2, 63, -64, 2**31 - 1, -(2**31), 2**62, -(2**62)]:
        assert zigzag_decode(zigzag_encode(v)) == v


def test_varint_roundtrip():
    w = CompactWriter()
    values = [0, 1, 127, 128, 300, 2**21, 2**35, 2**63 - 1]
    for v in values:
        w.write_varint(v)
    r = CompactReader(w.getvalue())
    assert [r.read_varint() for _ in values] == values


def test_struct_roundtrip():
    obj = Outer(
        flag=True,
        big=-(2**40),
        inner=Inner(a=-5, name="héllo"),
        items=[1, 2, 3, -4, 5000],
        blob=b"\x00\xff\x10",
        far_field=42,
    )
    data = obj.to_bytes()
    back, end = Outer.from_bytes(data)
    assert end == len(data)
    assert back == obj


def test_false_bool_and_none_fields():
    obj = Outer(flag=False, items=[])
    back, _ = Outer.from_bytes(obj.to_bytes())
    assert back.flag is False
    assert back.items == []
    assert back.big is None and back.inner is None


def test_unknown_field_skipped():
    class V2(ThriftStruct):
        FIELDS = {1: ("a", T_I32), 2: ("extra", Inner), 3: ("z", T_STRING)}

    class V1(ThriftStruct):
        FIELDS = {1: ("a", T_I32), 3: ("z", T_STRING)}

    v2 = V2(a=7, extra=Inner(a=1, name="x"), z="keep")
    v1, _ = V1.from_bytes(v2.to_bytes())
    assert v1.a == 7 and v1.z == "keep"


def test_long_list_header():
    class L(ThriftStruct):
        FIELDS = {1: ("xs", TList(T_I32))}

    xs = list(range(100))
    back, _ = L.from_bytes(L(xs=xs).to_bytes())
    assert back.xs == xs


def test_nested_parquet_structures():
    ph = PageHeader(
        type=0,
        uncompressed_page_size=100,
        compressed_page_size=50,
        crc=-123456,
    )
    back, _ = PageHeader.from_bytes(ph.to_bytes())
    assert back == ph

    se = SchemaElement(name="col", type=2, repetition_type=1,
                       logicalType=LogicalType(STRING=StringType()))
    back, _ = SchemaElement.from_bytes(se.to_bytes())
    assert back.logicalType.STRING is not None

    st = Statistics(null_count=3, min_value=b"\x01", max_value=b"\x09",
                    is_max_value_exact=True)
    back, _ = Statistics.from_bytes(st.to_bytes())
    assert back == st


def test_empty_filemetadata_fields():
    fm = FileMetaData(version=2, num_rows=0, schema=[SchemaElement(name="root", num_children=0)])
    back, _ = FileMetaData.from_bytes(fm.to_bytes())
    assert back.version == 2
    assert back.num_rows == 0
    assert len(back.schema) == 1


def test_unknown_list_of_bool_field_skipped():
    """Regression: bools occupy one byte as container elements; skipping an
    unknown list<bool> field must consume them and stay in sync."""

    class V2(ThriftStruct):
        FIELDS = {1: ("bools", TList(T_BOOL)), 2: ("x", T_I32)}

    class V1(ThriftStruct):
        FIELDS = {2: ("x", T_I32)}

    v2 = V2(bools=[True, False, True], x=42)
    v1, end = V1.from_bytes(v2.to_bytes())
    assert v1.x == 42
    assert end == len(v2.to_bytes())


def test_bool_list_roundtrip():
    """Regression: bools as container elements occupy one payload byte in
    compact protocol (1=true, 2=false) — they are NOT header-encoded like
    field-position bools.  Mis-reading desyncs every later field."""
    from parquet_floor_tpu.format.parquet_thrift import ColumnIndex

    ci = ColumnIndex(
        null_pages=[False, True, False],
        min_values=[b"a", b"", b"c"],
        max_values=[b"z", b"", b"y"],
        boundary_order=0,
        null_counts=[0, 5, 1],
    )
    out, _ = ColumnIndex.from_bytes(ci.to_bytes())
    assert out.null_pages == [False, True, False]
    assert out.min_values == [b"a", b"", b"c"]
    assert out.max_values == [b"z", b"", b"y"]
    assert out.null_counts == [0, 5, 1]


def test_native_split_pages_matches_python(tmp_path):
    """The native page-header scan must produce headers identical to the
    Python Thrift parser on real files (v1, v2, dict, optional)."""
    import numpy as np
    import pytest
    from parquet_floor_tpu import ParquetFileReader, ParquetFileWriter, WriterOptions, types
    from parquet_floor_tpu.format import pages as pg
    from parquet_floor_tpu.native import binding

    if not binding.available():
        pytest.skip("native lib not built")

    for version in (1, 2):
        schema = types.message(
            "t",
            types.required(types.INT64).named("a"),
            types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        )
        path = tmp_path / f"sp{version}.parquet"
        rng = np.random.default_rng(9)
        with ParquetFileWriter(
            path, schema,
            WriterOptions(page_version=version, data_page_values=300),
        ) as w:
            w.write_columns({
                "a": rng.integers(0, 50, 2000).astype(np.int64),
                "s": [None if i % 5 == 0 else f"w{i % 37}" for i in range(2000)],
            })
        with ParquetFileReader(path) as r:
            for chunk in r.row_groups[0].columns:
                meta = chunk.meta_data
                start = meta.data_page_offset
                if meta.dictionary_page_offset:
                    start = min(start, meta.dictionary_page_offset)
                # copy: read_at may hand back an mmap-backed view, which
                # must not outlive the reader
                raw = bytes(r.source.read_at(start, meta.total_compressed_size))
                nat, nat_offsets = pg._split_pages_native(raw, meta.num_values)
                assert len(nat_offsets) == len(nat)
                # force the python path
                import parquet_floor_tpu.format.pages as pgm
                saved = pgm._native
                pgm._native = None
                try:
                    py = pg.split_pages(raw, meta.num_values)
                finally:
                    pgm._native = saved
                assert len(nat) == len(py)
                for a, b in zip(nat, py):
                    assert a.header.type == b.header.type
                    assert a.header.compressed_page_size == b.header.compressed_page_size
                    assert a.header.uncompressed_page_size == b.header.uncompressed_page_size
                    assert a.header.crc == b.header.crc
                    assert a.payload == b.payload
                    for attr in ("data_page_header", "data_page_header_v2",
                                 "dictionary_page_header"):
                        ha, hb = getattr(a.header, attr), getattr(b.header, attr)
                        assert (ha is None) == (hb is None), attr
                        if ha is not None:
                            for f in hb.FIELDS.values():
                                name = f[0]
                                if name == "statistics":
                                    continue
                                va = getattr(ha, name, None)
                                vb = getattr(hb, name, None)
                                # native leaves absent optionals None; the
                                # python parser may carry defaults
                                if vb is not None or va is not None:
                                    assert va == vb, (attr, name, va, vb)


def test_native_split_pages_hostile_input():
    """Hostile header bytes (deep struct nesting, negative field ids) must
    raise ValueError, never crash or corrupt memory."""
    import pytest
    from parquet_floor_tpu.native import binding

    if not binding.available():
        pytest.skip("native lib not built")
    # a long run of struct-open bytes: unbounded skip recursion without a
    # depth limit
    deep = bytes([0x1C]) * 200_000
    with pytest.raises(ValueError):
        binding.split_pages(deep, 1000)
    # long-form field header with a negative zigzag field id inside a
    # nested data_page_header (ctype 5 = i32, fid -3 zigzag = 5)
    hostile = bytes([
        0x15, 0x00,        # fid1 type = 0 (DATA_PAGE)
        0x15, 0x02,        # fid2 uncompressed = 1
        0x15, 0x02,        # fid3 compressed = 1
        0x2C,              # fid5 struct (data_page_header)
        0x05, 0x05, 0x04,  # long-form: ctype i32, fid zigzag(5) = -3, value 2
        0x00,              # stop inner
        0x00,              # stop outer
        0xAA,              # payload byte
    ])
    try:
        binding.split_pages(hostile, 10)
    except ValueError:
        pass  # clean rejection is fine; silent OOB write is what we fear


def test_native_split_pages_hostile_containers():
    """Nested lists and huge bool-element maps must be rejected bounded in
    time and stack (the depth guard covers every container path)."""
    import pytest
    from parquet_floor_tpu.native import binding

    if not binding.available():
        pytest.skip("native lib not built")
    # unbounded LIST nesting: each 0x19 byte = list header (size 1, list elem)
    deep_lists = bytes([0x19]) * 200_000
    with pytest.raises(ValueError):
        binding.split_pages(deep_lists, 1000)
    # map with an astronomical count of bool elements must not spin:
    # field header ctype 11 (map), varint count 2^35, kv types bool/bool
    hostile = bytes([0x1B]) + bytes([0x80] * 4 + [0x02]) + bytes([0x11])
    with pytest.raises(ValueError):
        binding.split_pages(hostile + b"\x00" * 8, 1000)
