"""engine="auto" cost-model routing (tpu/cost.py): the one front door
must pick the WINNING engine per file, not per platform — the reference
exposes one API whose engine is invisible (ParquetReader.java:47-61)."""

import numpy as np
import pytest

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    ParquetReader,
    WriterOptions,
    types,
)
from parquet_floor_tpu.tpu import cost
from parquet_floor_tpu.utils import trace


def _write_plain_int64(path, n=20_000):
    """Config-#1-shaped: PLAIN uncompressed required INT64 (view-class:
    the host engine serves it at memcpy speed, the device path can only
    lose the ship time — BASELINE.md's one sub-1x row)."""
    schema = types.message("t", types.required(types.INT64).named("v"))
    opts = WriterOptions(
        codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
        page_version=2, data_page_values=100_000,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns({"v": np.arange(n, dtype=np.int64)})
    return str(path)


def _write_dict_strings(path, n=20_000):
    """Config-#2-shaped: Snappy + RLE_DICTIONARY strings and numerics
    (value-class: per-value host decode, the device engine's 15x win)."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY, enable_dictionary=True,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns({
            "k": (np.arange(n, dtype=np.int64) % 50),
            "s": [f"val{i % 40}" for i in range(n)],
        })
    return str(path)


@pytest.fixture
def tunnel_probes(monkeypatch):
    """Pin the link probes to the measured axon-tunnel numbers AND the
    host decode rates to the shipped fallback constants, so routing
    decisions are deterministic under test (BASELINE.md link
    characterization: H2D 1.25 GB/s; D2H ~35 ms fixed + 11 MB/s).
    ``test_calibrated_rates_preserve_headline_routing`` covers the
    live-calibration path separately."""
    monkeypatch.setattr(cost, "_probe_h2d_gbps", lambda: 1.25)
    monkeypatch.setattr(cost, "_probe_d2h_model", lambda: (0.035, 0.011))
    monkeypatch.setattr(cost, "_probe_host_rates", lambda: dict(cost._CLASS_GBPS))


def test_classify_chunk(tmp_path):
    p1 = _write_plain_int64(tmp_path / "plain.parquet")
    with ParquetFileReader(p1) as r:
        chunk = r.row_groups[0].columns[0]
        desc = r.schema.column(tuple(chunk.meta_data.path_in_schema))
        assert cost.classify_chunk(desc, chunk.meta_data) == "view"
    p2 = _write_dict_strings(tmp_path / "dict.parquet")
    with ParquetFileReader(p2) as r:
        for chunk in r.row_groups[0].columns:
            desc = r.schema.column(tuple(chunk.meta_data.path_in_schema))
            assert cost.classify_chunk(desc, chunk.meta_data) == "value"
    # optional PLAIN fixed-width → levels class
    schema = types.message("t", types.optional(types.DOUBLE).named("d"))
    p3 = str(tmp_path / "opt.parquet")
    opts = WriterOptions(
        codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
    )
    with ParquetFileWriter(p3, schema, opts) as w:
        w.write_columns({"d": [None if i % 5 == 0 else float(i) for i in range(500)]})
    with ParquetFileReader(p3) as r:
        chunk = r.row_groups[0].columns[0]
        desc = r.schema.column(tuple(chunk.meta_data.path_in_schema))
        assert cost.classify_chunk(desc, chunk.meta_data) == "levels"


def test_estimate_routes_by_file_shape(tmp_path, tunnel_probes):
    """Under the measured tunnel link numbers, the model sends the
    memcpy-class file host and the per-value-class file device — for
    both the batch and the rows purposes."""
    p1 = _write_plain_int64(tmp_path / "plain.parquet", n=1_000_000)
    p2 = _write_dict_strings(tmp_path / "dict.parquet", n=1_000_000)
    with ParquetFileReader(p1) as r:
        assert cost.estimate(r, purpose="batch").engine == "host"
        assert cost.estimate(r, purpose="rows").engine == "host"
    with ParquetFileReader(p2) as r:
        est_b = cost.estimate(r, purpose="batch")
        est_r = cost.estimate(r, purpose="rows")
    assert est_b.engine == "tpu"
    assert est_r.engine == "tpu"
    # the estimate carries its accounting for the trace
    assert est_b.bytes_by_class["value"] > 0
    assert "est" in str(est_b.reason) or est_b.reason


def test_choose_engine_platform_gate(tmp_path):
    """On a non-TPU backend auto is host, and the decision is traced."""
    p = _write_dict_strings(tmp_path / "d.parquet")
    trace.enable()
    trace.reset()
    try:
        with ParquetFileReader(p) as r:
            choice = cost.choose_engine(r)
        assert choice.engine == "host"
        assert "not a TPU" in choice.reason
        ds = trace.decisions()
        assert ds and ds[-1]["decision"] == "engine.auto"
        assert ds[-1]["engine"] == "host"
    finally:
        trace.disable()


def test_front_door_auto_routing(tmp_path, tunnel_probes, monkeypatch):
    """With the platform gate forced open, ParquetReader(engine="auto")
    routes per file: view-class → host cursors, value-class → the device
    engine — same rows either way."""
    from parquet_floor_tpu.tpu import engine as eng

    monkeypatch.setattr(eng, "_platform_is_tpu", lambda: True)
    # the forced platform gate must not also force compiled Pallas
    # kernels (CPU backend only supports interpret mode)
    monkeypatch.setenv("PFTPU_PALLAS", "0")
    p1 = _write_plain_int64(tmp_path / "plain.parquet", n=1_000_000)
    p2 = _write_dict_strings(tmp_path / "dict.parquet", n=1_000_000)

    class _Rows:
        def start(self):
            return []

        def add(self, t, h, v):
            t.append(v)
            return t

        def finish(self, t):
            return tuple(t)

    r1 = ParquetReader.spliterator(p1, lambda c: _Rows(), engine="auto")
    try:
        assert r1.engine == "host"
    finally:
        r1.close()
    r2 = ParquetReader.spliterator(p2, lambda c: _Rows(), engine="auto")
    try:
        assert r2.engine == "tpu"
        rows_auto = [next(r2) for _ in range(5)]
    finally:
        r2.close()
    rows_host = list(
        ParquetReader.stream_content(p2, lambda c: _Rows(), engine="host")
    )[:5]
    assert rows_auto == rows_host


def test_estimate_accounts_for_unsplittable_fields(tmp_path, tunnel_probes,
                                                   monkeypatch):
    """Splittability is part of the routing input (VERDICT r4 #1): an
    over-cap value-class field with no OffsetIndex host-decodes inside
    the device engine (chunk fallback), so the model must charge it
    host rates + ship on the device side — flipping a file that fused
    decode alone would have routed to the device."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 200_000
    table = pa.table({"s": [f"val{i % 40}" for i in range(n)]})
    p_no = str(tmp_path / "no_oi.parquet")
    p_oi = str(tmp_path / "oi.parquet")
    pq.write_table(table, p_no, write_page_index=False,
                   data_page_size=16 << 10)
    pq.write_table(table, p_oi, write_page_index=True,
                   data_page_size=16 << 10)
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(64 << 10))
    with ParquetFileReader(p_no) as r:
        est_no = cost.estimate(r, purpose="batch")
    with ParquetFileReader(p_oi) as r:
        est_oi = cost.estimate(r, purpose="batch")
    # with the OffsetIndex the field row-splits: fused decode wins
    assert est_oi.engine == "tpu"
    assert "unsplit" not in est_oi.bytes_by_class
    # without it the device path does the same host decode PLUS the
    # ship — it can only lose, so auto must route host
    assert est_no.engine == "host"
    assert est_no.bytes_by_class["unsplit"] > 0
    assert est_no.tpu_s > est_no.host_s
    # an OffsetIndex with no interior boundary (single huge page) is
    # just as unsplittable — the model must treat it like the engine
    p_1p = str(tmp_path / "onepage.parquet")
    schema = types.message(
        "t", types.required(types.BYTE_ARRAY).as_(types.string()).named("s")
    )
    with ParquetFileWriter(p_1p, schema,
                           WriterOptions(data_page_values=10**9)) as w:
        w.write_columns({"s": [f"val{i % 40}" for i in range(n)]})
    with ParquetFileReader(p_1p) as r:
        est_1p = cost.estimate(r, purpose="batch")
    assert est_1p.engine == "host"
    assert est_1p.bytes_by_class["unsplit"] > 0


def test_host_rate_calibration(monkeypatch):
    """VERDICT r4 #3: the host decode rates are measured per process
    (real page-decode path on ~1 MiB synthetic pages), cached, ordered
    view > levels > value, and fall back to the shipped constants when
    the probe cannot run."""
    monkeypatch.setattr(cost, "_host_rates", None)
    rates = cost._probe_host_rates()
    assert set(rates) == {"view", "levels", "value"}
    for v in rates.values():
        assert 1e-4 <= v <= 100.0
    # the class ordering the whole model rests on must hold as measured
    # (guarded like test_calibrated_rates_preserve_headline_routing: a
    # descheduled probe rep on a loaded host is noise, not a defect)
    if rates["view"] < 2.0:
        pytest.skip(f"host too noisy for a meaningful probe: {rates}")
    assert rates["view"] > rates["levels"] > rates["value"]
    assert cost._probe_host_rates() is rates  # cached per process
    # probe failure → shipped constants, never an error
    monkeypatch.setattr(cost, "_host_rates", None)
    monkeypatch.setattr(
        cost, "_measure_host_rates",
        lambda: (_ for _ in ()).throw(RuntimeError("no numpy")),
    )
    fallback = cost._probe_host_rates()
    assert fallback == cost._CLASS_GBPS


def test_calibrated_rates_preserve_headline_routing(tmp_path, monkeypatch):
    """VERDICT r4 #3 done-criterion: with LIVE per-process calibration
    (only the link probes pinned), the model still routes config #1 →
    host and config #2 → tpu.  Skipped when the machine is too noisy to
    measure a memcpy-class view rate (the assertion would test the
    neighbor's load, not the model)."""
    monkeypatch.setattr(cost, "_probe_h2d_gbps", lambda: 1.25)
    monkeypatch.setattr(cost, "_probe_d2h_model", lambda: (0.035, 0.011))
    monkeypatch.setattr(cost, "_host_rates", None)
    rates = cost._probe_host_rates()
    if rates["view"] < 2.0:
        pytest.skip(f"host too noisy for a meaningful probe: {rates}")
    p1 = _write_plain_int64(tmp_path / "plain.parquet", n=1_000_000)
    p2 = _write_dict_strings(tmp_path / "dict.parquet", n=1_000_000)
    with ParquetFileReader(p1) as r:
        assert cost.estimate(r, purpose="rows").engine == "host"
    with ParquetFileReader(p2) as r:
        assert cost.estimate(r, purpose="rows").engine == "tpu"


def test_dict_pool_estimate_from_footer(tmp_path):
    """The dictionary fetch estimate reads the dict page header's exact
    uncompressed size (located by the footer's offsets), not the old
    //3 ratio guess."""
    n = 100_000
    p = _write_dict_strings(tmp_path / "d.parquet", n=n)
    with ParquetFileReader(p) as r:
        chunk = next(
            c for c in r.row_groups[0].columns
            if c.meta_data.path_in_schema[0] == "s"
        )
        meta = chunk.meta_data
        est = cost._dict_pool_estimate(
            r, meta, int(meta.total_uncompressed_size)
        )
        # real pool: 40 distinct "valNN" strings, PLAIN-encoded
        # (4-byte length prefix + chars) — the header size is exact
        real = sum(4 + len(f"val{i}") for i in range(40))
        assert est == real, (est, real)
        # offsets absent → the conservative fallback ratio
        meta2 = type(meta)(
            total_compressed_size=meta.total_compressed_size,
            total_uncompressed_size=meta.total_uncompressed_size,
            data_page_offset=meta.data_page_offset,
        )
        assert cost._dict_pool_estimate(r, meta2, 9000) == 3000


def test_auto_degrades_to_host_without_x64(tmp_path, tunnel_probes, monkeypatch):
    """auto must never error for environment reasons: with x64 off the
    device engine cannot construct, so auto picks host."""
    import jax

    from parquet_floor_tpu.tpu import engine as eng

    monkeypatch.setattr(eng, "_platform_is_tpu", lambda: True)
    p = _write_dict_strings(tmp_path / "d.parquet")
    jax.config.update("jax_enable_x64", False)
    try:
        with ParquetFileReader(p) as r:
            choice = cost.choose_engine(r)
        assert choice.engine == "host"
        assert "x64" in choice.reason
    finally:
        jax.config.update("jax_enable_x64", True)
