# floorlint: scope=FL-ASYNC
"""Seeded-bad: ``await`` while holding a *threading* lock — the
coroutine parks at the await with the lock held; every pool worker
contending on it now waits on the event loop's scheduling."""
import threading


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []

    async def flush(self, sink):
        with self._lock:
            batch = list(self._buf)
            del self._buf[:]
            await sink.send(batch)  # parked with the thread lock held
