# floorlint: scope=FL-EXC003
"""Clean: the raise carries location-context kwargs."""


class CorruptPageError(ValueError):
    def __init__(self, message, path=None, offset=None):
        super().__init__(message)
        self.path = path
        self.offset = offset


def read_page(buf, path):
    if len(buf) < 8:
        raise CorruptPageError("page shorter than its header",
                               path=path, offset=0)
    return buf
