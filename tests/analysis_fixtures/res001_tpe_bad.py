"""Seeded-bad: the executor-leak shapes — a ThreadPoolExecutor whose
threads outlive an exception between construction and shutdown, and a
scan handle abandoned without close on the error path."""

from concurrent.futures import ThreadPoolExecutor

from parquet_floor_tpu.scan import DatasetScanner


def decode_all(paths, decode):
    pool = ThreadPoolExecutor(max_workers=4)
    futs = [pool.submit(decode, p) for p in paths]  # a raise here leaks threads
    out = [f.result() for f in futs]
    pool.shutdown()
    return out


def first_batch(paths):
    scanner = DatasetScanner(paths)
    unit = next(iter(scanner))  # any raise leaks the scan worker pool
    scanner.close()
    return unit
