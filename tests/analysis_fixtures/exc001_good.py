# floorlint: scope=FL-EXC001
"""Clean: the transient classes re-raise before the broad wrap (the
hand-rolled equivalent of errors.classified_decode_errors)."""


class BoomDecodeError(ValueError):
    pass


def decode(data):
    try:
        return data.decode("utf-8")
    except (OSError, MemoryError):
        raise
    except Exception as e:
        raise BoomDecodeError(f"decode failed: {e}") from e
