# floorlint: scope=FL-TPU
"""Cross-module half A: a jitted function calling a helper imported
from tpu_xmod_helper.py.  Analyzed TOGETHER (one project), the chain
resolves and FL-TPU001 fires here at the call site; analyzed alone the
import edge dangles and the file is clean — pinning that chain findings
need the project pass, not guesswork."""

from .tpu_xmod_helper import read_limit


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


@jit
def decode_step(payload, path):
    limit = read_limit(path)  # cross-module hop
    return payload[:limit]
