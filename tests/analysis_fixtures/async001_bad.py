# floorlint: scope=FL-ASYNC
"""Seeded-bad: blocking sinks in coroutine context — a direct
``time.sleep`` in the handler, and a storage read buried in the sync
helper the coroutine calls (reported at the call site with the
chain)."""
import time


class Daemon:
    def __init__(self, pool, source):
        self._pool = pool
        self._source = source

    async def handle(self, req):
        time.sleep(0.01)  # direct blocking sink on the loop
        return self._execute(req)  # the helper blocks two frames down

    def _execute(self, req):
        return self._source.read_at(req.offset, req.length)
