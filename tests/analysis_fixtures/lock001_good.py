# floorlint: scope=FL-LOCK
"""Clean: with-managed acquires, plus the acquire/finally-release
spelling for code that cannot use `with` (conditional hold-over)."""

import threading

_lock = threading.Lock()


def update(registry, key, value):
    with _lock:
        registry[key] = value


def update_guarded(registry, key, value):
    _lock.acquire()
    try:
        registry[key] = value
    finally:
        _lock.release()


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self, amount):
        with self._lock:
            self.value += amount
