"""Seeded-good: the per-device pool shapes released correctly — a
with-managed DevicePools, and a hand-rolled per-device container whose
members are shut down by ITERATING it in a finally guard (the
DevicePools.shutdown shape FL-RES001 must recognize)."""

from concurrent.futures import ThreadPoolExecutor

from parquet_floor_tpu.parallel.mesh import DevicePools


def ship_all(devices, groups, ship):
    with DevicePools(devices) as dpools:
        futs = [dpools.submit(d, ship, g)
                for d, g in zip(devices, groups)]
        return [f.result() for f in futs]


def ship_handrolled(devices, groups, ship):
    pools = {}
    try:
        for d in devices:
            pools[d] = ThreadPoolExecutor(max_workers=1)
        return [pools[d].submit(ship, g).result()
                for d, g in zip(devices, groups)]
    finally:
        for p in pools.values():
            p.shutdown(wait=False)
