"""Seeded-bad: the fleet-fabric leak shapes — a FleetCache (owns every
installed PeerClient socket plus its local byte store) and a bare
PeerClient (a live connection a peer daemon's drain must then wait out)
bound to locals with no exception path releasing them."""

from parquet_floor_tpu.serve import FleetCache, PeerClient


def mount_fleet(membership, origin):
    fc = FleetCache("n0", membership, origin=origin)
    fc.read_through(("f", 1), [(0, 64)], origin)  # a raise leaks peers
    fc.close()
    return True


def probe_peer(port, membership):
    peer = PeerClient("127.0.0.1", port)
    reply = peer.fetch(("f", 1), 0, 64, epoch=membership.epoch)
    peer.close()  # any error above leaks the socket
    return reply
