# floorlint: scope=FL-RACE
"""Seeded-good FP pin: the single-flight release-before-wait shape from
the serving cache — every touch of the flights dict holds the flight
lock, while waiters block on the checked-out Event OUTSIDE it (waiting
under the lock would serialize the flight it exists to share).  The
Event is a local once checked out; the analysis must not confuse
waiting on it with touching the guarded dict."""
import threading


class SingleFlight:
    def __init__(self):
        self._lock = threading.Lock()
        self._flights = {}

    def reset(self):
        with self._lock:
            self._flights.clear()

    def fetch(self, key, load):
        lead = False
        with self._lock:
            ev = self._flights.get(key)
            if ev is None:
                ev = threading.Event()
                self._flights[key] = ev
                lead = True
        if lead:
            try:
                value = load(key)
            finally:
                with self._lock:
                    self._flights.pop(key, None)
                ev.set()
            return value
        ev.wait(timeout=30.0)  # release-before-wait: the pinned escape
        return load(key)
