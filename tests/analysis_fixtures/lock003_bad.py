# floorlint: scope=FL-LOCK
"""Seeded-bad: Condition.wait() guarded by `if` — a spurious wakeup (or
a predicate re-falsified between notify and wakeup) sails straight
through the gate with the predicate still false."""

import threading


class Gate:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def wait_ready(self):
        with self._cv:
            if not self._ready:  # one wakeup == one check: unsound
                self._cv.wait()
            return self._ready

    def set_ready(self):
        with self._cv:
            self._ready = True
            self._cv.notify_all()
