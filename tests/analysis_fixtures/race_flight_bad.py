# floorlint: scope=FL-RACE
"""Seeded-bad: the single-flight shape gone wrong — the lead's cleanup
pops the flight entry OUTSIDE the flight lock, so a racing caller can
observe a dead Event and wait forever on a flight nobody owns."""
import threading


class SingleFlight:
    def __init__(self):
        self._lock = threading.Lock()
        self._flights = {}

    def reset(self):
        with self._lock:
            self._flights.clear()

    def fetch(self, key, load):
        lead = False
        with self._lock:
            ev = self._flights.get(key)
            if ev is None:
                ev = threading.Event()
                self._flights[key] = ev
                lead = True
        if lead:
            try:
                value = load(key)
            finally:
                self._flights.pop(key, None)  # outside the guard
                ev.set()
            return value
        ev.wait(timeout=30.0)
        return load(key)
