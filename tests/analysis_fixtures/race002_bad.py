# floorlint: scope=FL-RACE
"""Seeded-bad: check-then-act with the guard dropped, both arms — the
classic shape (an ``if`` reads a guarded field and its branch writes it,
lock not held across the statement) and the writer-side shape (an
unlocked read decides a write performed under the lock, with no
re-check inside the guarded region)."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}

    def add(self, key, item):
        with self._lock:
            self._slots.setdefault(key, []).append(item)

    def drop(self, key):
        with self._lock:
            self._slots.pop(key, None)

    def ensure(self, key):
        if key not in self._slots:  # check runs unlocked...
            self._slots[key] = []   # ...act writes: the lost-update window


class Versioned:
    def __init__(self):
        self._lock = threading.Lock()
        self._snap = None

    def install(self, snap):
        if self._snap is not None and snap.epoch <= self._snap.epoch:
            raise ValueError("stale epoch")
        with self._lock:
            self._snap = snap  # the check above never ran under this lock
