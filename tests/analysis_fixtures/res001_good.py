"""Clean: every acquisition is context-managed or closed on all
exception paths (the constructor-guard shape)."""


class Reader:
    def __init__(self, path, parse):
        self._fh = open(path, "rb")
        try:
            self.header = parse(self._fh)
        except BaseException:
            self._fh.close()
            raise

    def close(self):
        self._fh.close()


def read_header(path):
    with open(path, "rb") as f:
        return f.read()[:16]
