# floorlint: scope=FL-LOCK
"""Seeded-bad: bare acquires whose release an exception can skip — the
raise between acquire() and release() wedges the lock for every later
caller (the serving hazard: one wedged cache lock stalls all tenants)."""

import threading

_lock = threading.Lock()


def update(registry, key, value):
    _lock.acquire()
    registry[key] = value  # a raise here wedges _lock forever
    _lock.release()


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self, amount):
        self._lock.acquire()
        self.value += amount  # same shape on an attribute lock
        self._lock.release()
