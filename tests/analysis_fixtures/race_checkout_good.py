# floorlint: scope=FL-RACE
"""Seeded-good FP pin: the PeerClient connection-checkout shape — the
pooled socket field is only ever touched under the pool lock; a request
checks the connection OUT (swap-to-None under the lock), uses the
now-private local outside it, and checks it back in.  The analysis must
not flag the unlocked use of the checked-out LOCAL."""
import threading


class PeerClient:
    def __init__(self, host, port):
        self._lock = threading.Lock()
        self._sock = None
        self._host = host
        self._port = port

    def _checkout(self):
        with self._lock:
            sock, self._sock = self._sock, None
        return sock

    def _checkin(self, sock):
        with self._lock:
            if self._sock is None:
                self._sock = sock
                return
        sock.close()

    def request(self, payload):
        sock = self._checkout()  # the connection leaves the pool...
        try:
            sock.sendall(payload)  # ...and is used as a LOCAL, unlocked
            return sock.recv(65536)
        finally:
            self._checkin(sock)
