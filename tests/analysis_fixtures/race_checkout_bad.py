# floorlint: scope=FL-RACE
"""Seeded-bad: the connection-checkout shape gone wrong — ``request``
uses the pooled socket field DIRECTLY, outside the pool lock, so two
threads can interleave sends on one connection and corrupt the
framing."""
import threading


class PeerClient:
    def __init__(self, host, port):
        self._lock = threading.Lock()
        self._sock = None
        self._host = host
        self._port = port

    def _checkout(self):
        with self._lock:
            sock, self._sock = self._sock, None
        return sock

    def _checkin(self, sock):
        with self._lock:
            if self._sock is None:
                self._sock = sock
                return
        sock.close()

    def request(self, payload):
        self._sock.sendall(payload)  # guarded field used outside the lock
        return self._sock.recv(65536)
