# floorlint: scope=FL-EXC002
"""Clean: `from e` preserves the cause chain."""


def parse_count(text):
    try:
        return int(text)
    except ValueError as e:
        raise KeyError("count field is not an integer") from e
