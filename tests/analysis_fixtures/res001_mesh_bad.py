"""Seeded-bad: the per-device pool leak shapes (docs/multichip.md) — a
DevicePools (owns one worker thread per mesh device) bound with no
exception path releasing it, and acquisitions collected INTO a local
container whose members nothing ever shuts down."""

from concurrent.futures import ThreadPoolExecutor

from parquet_floor_tpu.parallel.mesh import DevicePools


def ship_all(devices, groups, ship):
    dpools = DevicePools(devices)
    futs = [dpools.submit(d, ship, g)  # a raise here leaks k workers
            for d, g in zip(devices, groups)]
    out = [f.result() for f in futs]
    dpools.shutdown()
    return out


def ship_handrolled(devices, groups, ship):
    pools = {}
    for d in devices:
        pools[d] = ThreadPoolExecutor(max_workers=1)  # members never shut
    return [pools[d].submit(ship, g).result()
            for d, g in zip(devices, groups)]
