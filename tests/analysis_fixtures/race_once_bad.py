# floorlint: scope=FL-RACE
"""Seeded-bad: NOT assign-once — the snapshot field is republished from
two sites, so the immutable-after-publish escape does not apply and the
unlocked read of the guarded field reports."""
import threading


class Config:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None

    def publish(self, table):
        with self._lock:
            self._table = table

    def clear(self):
        with self._lock:
            self._table = None

    def lookup(self, key):
        if self._table is None:  # unlocked read of a guarded field
            return None
        return self._table.get(key)
