# floorlint: scope=FL-ALLOC
"""Seeded-bad: allocation sized straight from a parsed length field — a
flipped bit in the header becomes a multi-GiB allocation attempt."""

import numpy as np


def decode_block(buf):
    n = int.from_bytes(buf[:4], "little")
    values = np.empty(n, dtype=np.uint8)
    frame = bytes(n * 4)
    return values, frame
