# floorlint: scope=FL-RACE
"""Seeded-good twin of race002: the whole check-then-act sequence is
atomic — the classic arm holds the guard around the ``if``, and the
writer-side arm re-validates under the lock (double-checked locking:
the unlocked read is an advisory fast path, the guarded region
re-checks before acting)."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}

    def add(self, key, item):
        with self._lock:
            self._slots.setdefault(key, []).append(item)

    def drop(self, key):
        with self._lock:
            self._slots.pop(key, None)

    def ensure(self, key):
        with self._lock:
            if key not in self._slots:
                self._slots[key] = []


class Versioned:
    def __init__(self):
        self._lock = threading.Lock()
        self._snap = None

    def install(self, snap, build):
        if self._snap is None:  # advisory fast path, re-checked below
            with self._lock:
                if self._snap is None:
                    self._snap = build(snap)
