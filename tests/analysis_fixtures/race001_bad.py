# floorlint: scope=FL-RACE
"""Seeded-bad: a guarded field touched outside its inferred guard —
the multi-site arm (written under the lock at two sites) and the
thread-reachable arm (one locked write site inside a method handed to
``Thread(target=)``)."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def add(self, n):
        with self._lock:
            self._count += n

    def reset(self):
        with self._lock:
            self._count = 0

    def bump_unlocked(self):
        self._count += 1  # write outside the guard

    def peek(self):
        return self._count  # read outside the guard


class Widget:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        with self._lock:
            self._state = "running"

    def state(self):
        return self._state  # read outside the thread-inferred guard
