# floorlint: scope=FL-TPU
"""Seeded-bad: host work hidden in helpers the project call graph
resolves from a jitted function — one through a plain call, one through
a ``functools.partial`` hop two levels down.  The violation is reported
AT THE JIT SITE (the call inside the traced function) with the chain."""

from functools import partial


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


def _read_config(path):
    with open(path) as fh:  # host I/O: runs once at trace time
        return int(fh.read())


def _limit_for(path):
    loader = partial(_read_config, path)
    return loader()


@jit
def decode_step(payload, path):
    limit = _limit_for(path)  # depth 2, through the partial
    return payload[:limit]
