# floorlint: scope=FL-TPU
"""Seeded-bad: host materialization inside a traced function — int() on
a traced value crashes at trace time; .item() forces a device→host sync
mid-program."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


@jit
def reduce_step(acc, x):
    total = int(x) + acc.item()
    return total
