"""Seeded-bad: the remote session/pool leak shapes — a RemoteSource (or
simulator) whose fetch pool and transport outlive an exception between
acquisition and close, and a ParallelRangeReader abandoned mid-read."""

from parquet_floor_tpu.io.remote import ParallelRangeReader, RemoteSource
from parquet_floor_tpu.testing import SimulatedRemoteSource


def fetch_footer(transport):
    src = RemoteSource(transport)
    tail = src.read_at(src.size - 8, 8)  # a raise here leaks the pool
    src.close()
    return tail


def simulate(path, profile):
    sim = SimulatedRemoteSource(path, profile=profile)
    data = sim.read_at(0, 16)  # any raise leaks pool + transport
    sim.close()
    return data


def fan_out(inner, ranges):
    reader = ParallelRangeReader(inner)
    out = reader.read_many(ranges)  # a range error leaks the fan-out pool
    reader.close()
    return out
