# floorlint: scope=FL-TPU
"""Clean: helpers reached from the traced function are pure, and host
work FOUR hops down sits past the bounded traversal (CALL_DEPTH) — the
depth limit is pinned by this fixture staying clean."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


def _h1(path):
    return _h2(path)


def _h2(path):
    return _h3(path)


def _h3(path):
    return _h4(path)


def _h4(path):
    with open(path) as fh:  # 4 hops from decode_step: beyond the bound
        return len(fh.read())


def _pure(x):
    return x + 1


@jit
def decode_step(payload, path):
    return _pure(payload) + _h1(path)
