# floorlint: scope=FL-EXC002
"""Seeded-bad: the re-raise drops the cause chain — the original
traceback (WHICH bytes were bad) is gone from the report."""


def parse_count(text):
    try:
        return int(text)
    except ValueError as e:
        raise KeyError("count field is not an integer")
