# floorlint: scope=FL-ASYNC
"""Seeded-bad: a coroutine invoked as a bare statement — the coroutine
object is created and dropped, the body NEVER runs (the silent-no-op
bug class)."""


class Notifier:
    async def _notify(self, peer, payload):
        await peer.send(payload)

    async def broadcast(self, peers, payload):
        for peer in peers:
            self._notify(peer, payload)  # never awaited: a silent no-op
