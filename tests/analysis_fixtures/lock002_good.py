# floorlint: scope=FL-LOCK
"""Clean: the blessed single-flight spelling (serve/cache.py's shape) —
classify under the lock, do the blocking work AFTER releasing it.  The
leader reads outside the critical section; followers wait on the Event
they were handed under the lock, not on the lock itself."""

import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}
        self._flights = {}

    def fetch(self, key, read_fn):
        with self._lock:
            if key in self._data:
                return self._data[key]
            flight = self._flights.get(key)
            leader = flight is None
            if leader:
                flight = self._flights[key] = threading.Event()
        if not leader:
            flight.wait()  # outside the lock: followers block on the
            with self._lock:  # flight, never on the cache lock
                return self._data[key]
        data = read_fn()  # the blocking read, after release
        with self._lock:
            self._data[key] = data
            self._flights.pop(key, None)
        flight.set()
        return data
