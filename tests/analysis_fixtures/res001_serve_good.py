"""Clean: serving-layer acquisitions are with-managed, released in a
finally, or transferred to an owner that manages them."""

from parquet_floor_tpu.serve import Dataset, Serving, SharedBufferCache


def build_cache():
    with SharedBufferCache(data_bytes=1 << 20) as cache:
        cache.put(("f", 1), 0, b"xyz")
        return True


def serve_scan(paths):
    with Serving(prefetch_bytes=1 << 20) as srv:
        with srv.tenant("a").scan(paths) as scan:
            return sum(u.batch.num_rows for u in scan)


def probe(paths, key):
    ds = Dataset(paths, "k")
    try:
        return ds.lookup(key)
    finally:
        ds.close()


class _Owner:
    """Ownership transfer: the owner's close() releases the cache."""

    def __init__(self, nbytes):
        self.cache = SharedBufferCache(data_bytes=nbytes)

    def close(self):
        self.cache.close()
