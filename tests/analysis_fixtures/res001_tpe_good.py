"""Clean: pools and scan handles are with-managed or released in a
finally (shutdown counts as the release verb for executors)."""

from concurrent.futures import ThreadPoolExecutor

from parquet_floor_tpu.scan import DatasetScanner


def decode_all(paths, decode):
    with ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(decode, p) for p in paths]
        return [f.result() for f in futs]


def first_batch(paths):
    scanner = DatasetScanner(paths)
    try:
        return next(iter(scanner))
    finally:
        scanner.close()


def pooled_loader(paths, decode):
    pool = ThreadPoolExecutor(max_workers=2)
    try:
        return [pool.submit(decode, p).result() for p in paths]
    finally:
        pool.shutdown(wait=True)
