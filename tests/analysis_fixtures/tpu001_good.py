# floorlint: scope=FL-TPU
"""Clean: the traced function is pure; CRC policy and config reads live
on the host, outside the compiled region."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


@jit
def decode_step(payload, limit):
    return payload[:limit]
