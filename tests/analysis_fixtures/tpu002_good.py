# floorlint: scope=FL-TPU
"""Clean: static shapes may be read with int(x.shape[i]); everything
else stays traced."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


@jit
def reduce_step(acc, x):
    rows = int(x.shape[0])
    return acc + x.sum() * rows
