"""Seeded-good: the fleet-fabric shapes, properly managed —
with-managed, transferred, or closed in a finally."""

from parquet_floor_tpu.serve import FleetCache, PeerClient, ServeDaemon


def mount_fleet(membership, origin):
    with FleetCache("n0", membership, origin=origin) as fc:
        return fc.read_through(("f", 1), [(0, 64)], origin)


def mount_daemon(serving, membership):
    # ownership transfer: the returned daemon's owner closes both
    return ServeDaemon(serving, {},
                       fleet=FleetCache("n0", membership))


def probe_peer(port, membership):
    with PeerClient("127.0.0.1", port) as peer:
        return peer.fetch(("f", 1), 0, 64, epoch=membership.epoch)


def probe_fenced(port, membership):
    peer = PeerClient("127.0.0.1", port)
    try:
        return peer.epoch()
    finally:
        peer.close()
