"""Seeded-bad: the PR 1 fd-leak shapes — a chained read whose handle
lives until GC, and a linear open/use/close that leaks when `use`
raises."""


def read_header(path):
    return open(path, "rb").read()[:16]


def read_trailer(path, parse):
    f = open(path, "rb")
    data = parse(f)  # any raise here leaks f
    f.close()
    return data
