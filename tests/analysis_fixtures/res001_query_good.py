"""Seeded-good: the query-subsystem shapes released correctly — a
with-managed JoinCursor, and an explicit try/finally close around a
partial drain (the release shapes FL-RES001 must recognize)."""

from parquet_floor_tpu.query.join import JoinCursor


def drain_join(left, right):
    with JoinCursor(left, right, on=["k"]) as cur:
        rows = []
        while True:
            page = cur.next_page()
            if not page:
                break
            rows.extend(page)
        return rows


def first_page(left, right):
    cur = JoinCursor(left, right, on=["k"], page_rows=64)
    try:
        return cur.next_page()
    finally:
        cur.close()
