"""Seeded-bad: the process-scale serving leak shapes — a ShmCacheTier
(shared-memory SEGMENT + lock-file fd: the creator's abandoned handle
leaks host-wide memory, not just a process resource), a ServeDaemon
(listening socket + event-loop thread + worker pool), and a
DaemonClient (a live connection some drain must then wait out) bound to
locals with no exception path releasing them."""

from parquet_floor_tpu.serve import DaemonClient, ServeDaemon, ShmCacheTier


def build_tier():
    tier = ShmCacheTier.create(data_bytes=1 << 20)
    tier.put(("f", 1), 0, b"xyz")  # a raise here leaks the segment
    tier.close()
    return True


def attach_tier(name):
    tier = ShmCacheTier.attach(name)
    data = tier.get(("f", 1), 0, 3)  # a raise here leaks the lock fd
    tier.close()
    return data


def run_daemon(serving, datasets):
    daemon = ServeDaemon(serving, datasets)
    daemon.start()  # a bind failure leaks the pool and the loop thread
    daemon.close()
    return True


def probe_daemon(port):
    client = DaemonClient("127.0.0.1", port, "t")
    rows = client.lookup("ds", 7)  # any error leaks the connection
    client.close()
    return rows
