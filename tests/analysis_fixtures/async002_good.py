# floorlint: scope=FL-ASYNC
"""Seeded-good twin: snapshot under the threading lock, RELEASE, then
await — the lock is never held across a suspension point."""
import threading


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []

    async def flush(self, sink):
        with self._lock:
            batch = list(self._buf)
            del self._buf[:]
        await sink.send(batch)  # the lock was released before the await
