# floorlint: scope=FL-RACE
"""Seeded-good twin of race001: every access of the guarded fields —
multi-site and thread-reachable alike — holds the inferred guard."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def add(self, n):
        with self._lock:
            self._count += n

    def reset(self):
        with self._lock:
            self._count = 0

    def peek(self):
        with self._lock:
            return self._count


class Widget:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        with self._lock:
            self._state = "running"

    def state(self):
        with self._lock:
            return self._state
