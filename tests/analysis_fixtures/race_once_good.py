# floorlint: scope=FL-RACE
"""Seeded-good: the assign-once / immutable-after-publish escape — one
post-init publish site (under the lock), readers take the reference
unlocked: CPython's atomic attribute store means they see the old or
the new snapshot, never a torn one (the epoch-fenced membership
pattern)."""
import threading


class Config:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None

    def publish(self, table):
        with self._lock:
            self._table = table

    def lookup(self, key):
        table = self._table  # snapshot read: assign-once blessed
        if table is None:
            return None
        return table.get(key)
