# floorlint: scope=FL-TPU
"""Seeded-bad: dynamic dispatch through ANNOTATED receivers (the PR 10
blind spot).  No constructor call is visible anywhere — the receiver
types come only from annotations: a parameter annotation (string form
included), an annotated local, and a class-body attribute annotation.
The call graph must still follow ``.load()`` into host I/O from the
jitted functions."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


class ConfigStore:
    def load(self, path):
        with open(path) as fh:  # host I/O: runs once at trace time
            return int(fh.read())


def make_store():
    return ConfigStore()


@jit
def decode_param(payload, store: "ConfigStore", path):
    limit = store.load(path)  # receiver typed ONLY by the annotation
    return payload[:limit]


@jit
def decode_local(payload, path):
    s: ConfigStore = make_store()  # factory return, annotation pins it
    return payload[: s.load(path)]


class Decoder:
    store: ConfigStore  # class-body annotation; __init__ assigns untyped

    def __init__(self, store):
        self.store = store

    @jit
    def decode(self, payload, path):
        limit = self.store.load(path)  # attr typed by the annotation
        return payload[:limit]
