# floorlint: scope=FL-TPU
"""Seeded-good twin of ``tpu_attr_chain_bad``: the same chained
annotated-attribute dispatch, but the resolved methods are pure — the
chain walk must not fabricate host-I/O findings, and a chain broken by
one UNtyped hop must stay silent (under-approximation)."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


class ConfigStore:
    def load_pure(self, x):
        return x + 1

    def load(self, path):
        with open(path) as fh:  # host I/O — but only reachable through
            return int(fh.read())  # an untyped hop below


class Session:
    store: ConfigStore

    def __init__(self, store):
        self.store = store


@jit
def decode_chained(payload, sess: "Session"):
    return payload[: sess.store.load_pure(1)]  # pure through the chain


@jit
def decode_untyped_hop(payload, sess, path):
    # ``sess`` carries NO annotation: the first hop is untyped, the
    # chain does not resolve, and no edge (hence no finding) is made
    return payload[: len(str(sess.store))]
