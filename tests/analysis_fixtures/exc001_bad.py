# floorlint: scope=FL-EXC001
"""Seeded-bad: broad except wraps EVERYTHING as a decode error — a flaky
mount's OSError or host-pressure MemoryError becomes 'corruption'."""


class BoomDecodeError(ValueError):
    pass


def decode(data):
    try:
        return data.decode("utf-8")
    except Exception as e:
        raise BoomDecodeError(f"decode failed: {e}") from e
