# floorlint: scope=FL-EXC003
"""Seeded-bad: a taxonomy error raised at a decode boundary with no
location context — in a thousand-file scan nobody learns WHICH bytes."""


class CorruptPageError(ValueError):
    pass


def read_page(buf):
    if len(buf) < 8:
        raise CorruptPageError("page shorter than its header")
    return buf
