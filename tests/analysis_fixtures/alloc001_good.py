# floorlint: scope=FL-ALLOC
"""Clean: the parsed size flows through the checked i32 size-cap helper
before it drives any allocation."""

import numpy as np


def checked_alloc_size(n, what):  # stand-in for errors.checked_alloc_size
    n = int(n)
    if n < 0 or n >= 1 << 31:
        raise ValueError(f"implausible {what} size {n}")
    return n


def decode_block(buf):
    n = checked_alloc_size(int.from_bytes(buf[:4], "little"), "block")
    values = np.empty(n, dtype=np.uint8)
    frame = bytes(n * 4)
    return values, frame
