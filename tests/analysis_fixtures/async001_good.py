# floorlint: scope=FL-ASYNC
"""Seeded-good twin / FP pin: the serve daemon's executor-offload shape
— the loop awaits ``asyncio.sleep`` and hands the blocking helper to
``run_in_executor`` as a REFERENCE (never calling it in coroutine
context), so the storage read inside it runs on a pool thread."""
import asyncio


class Daemon:
    def __init__(self, pool, source):
        self._pool = pool
        self._source = source

    async def handle(self, req):
        await asyncio.sleep(0.01)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._pool, self._execute, req)

    def _execute(self, req):
        return self._source.read_at(req.offset, req.length)
