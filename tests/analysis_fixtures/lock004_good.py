# floorlint: scope=FL-LOCK
"""Clean: both paths acquire in the same accounts→audit order (one
project-wide order is the whole discipline — which order is chosen
does not matter, only that every chain agrees)."""

import threading


class Ledger:
    def __init__(self):
        self._accounts = threading.Lock()
        self._audit = threading.Lock()
        self.balance = {}
        self.log = []

    def debit(self, key, n):
        with self._accounts:
            with self._audit:
                self.log.append((key, -n))
                self.balance[key] = self.balance.get(key, 0) - n

    def credit(self, key, n):
        with self._accounts:  # same order as debit, helper included
            self._locked_credit(key, n)

    def _locked_credit(self, key, n):
        with self._audit:
            self.log.append((key, n))
            self.balance[key] = self.balance.get(key, 0) + n
