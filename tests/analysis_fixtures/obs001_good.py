# floorlint: scope=FL-OBS
"""Clean counterpart: registered names pass, and dynamic names are out
of the rule's reach (it guards literals, not reflection)."""

from parquet_floor_tpu.utils import trace


def plan_one(extents, metric_name):
    trace.count("scan.bytes_read", sum(e.length for e in extents))
    trace.gauge_max("scan.queue_depth_max", len(extents))
    trace.count(metric_name, 1)  # dynamic: not checked
    with trace.span("decode", attrs={"extents": len(extents)}):
        return len(extents)


def emit_batch(tracer, n):
    # the data.* family (docs/data.md) is registered like every other
    tracer.count("data.rows_emitted", n)
    tracer.gauge_max("data.carry_rows_max", n)
    tracer.decision("data.resume", {"epoch": 0, "batch": 0})
    with tracer.span("data.next_batch"):
        return n


def probe_wall(tracer, dt, hist_name):
    # the histogram family (PR 14) is registered like every other kind
    tracer.observe("serve.lookup_seconds", dt)
    trace.observe("serve.fair_wait_seconds", dt)
    trace.observe(hist_name, dt)  # dynamic: not checked


def decode_timed(extents):
    with trace.span("decode", observe="engine.launch_seconds"):
        return len(extents)


def hop_traced(peer):
    # the distributed-tracing family (docs/observability.md) is
    # registered like every other
    trace.count("trace.ctx_propagated")
    trace.gauge_max("trace.clock_offset_us", 12)
    with trace.span("serve.fleet_serve", attrs={"peer": peer}):
        return peer
