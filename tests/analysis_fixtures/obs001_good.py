# floorlint: scope=FL-OBS
"""Clean counterpart: registered names pass, and dynamic names are out
of the rule's reach (it guards literals, not reflection)."""

from parquet_floor_tpu.utils import trace


def plan_one(extents, metric_name):
    trace.count("scan.bytes_read", sum(e.length for e in extents))
    trace.gauge_max("scan.queue_depth_max", len(extents))
    trace.count(metric_name, 1)  # dynamic: not checked
    with trace.span("decode", attrs={"extents": len(extents)}):
        return len(extents)
