# floorlint: scope=FL-TPU
"""Seeded-bad: CHAINED annotated attribute receivers — the PR 12 blind
spot closed in PR 14.  The host I/O hides behind ``param.attr.method()``
(and a deeper ``self.attr.sub.method()``): the receiver's class comes
from a parameter annotation, the ATTRIBUTE's class from that class's
own annotation, and only then does the method resolve."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


class ConfigStore:
    def load(self, path):
        with open(path) as fh:  # host I/O: runs once at trace time
            return int(fh.read())


class Session:
    store: ConfigStore  # the chain's middle hop, typed by annotation

    def __init__(self, store):
        self.store = store


class Runtime:
    session: Session

    def __init__(self, session):
        self.session = session


@jit
def decode_chained(payload, sess: "Session", path):
    limit = sess.store.load(path)  # param.attr.method(): two typed hops
    return payload[:limit]


class Decoder:
    runtime: Runtime

    def __init__(self, runtime):
        self.runtime = runtime

    @jit
    def decode(self, payload, path):
        # self.attr.attr.method(): three typed hops through two classes
        limit = self.runtime.session.store.load(path)
        return payload[:limit]
