# floorlint: scope=FL-OBS
"""Deliberately violating fixture: FL-OBS001 — a typo'd trace counter
name (``scan.bytes_raed``) and an unregistered span stage would silently
split metrics; both must trip the registry check."""

from parquet_floor_tpu.utils import trace


def plan_one(extents):
    trace.count("scan.bytes_raed", sum(e.length for e in extents))  # typo
    with trace.span("decoed"):  # typo'd stage name
        return len(extents)


def emit_batch(tracer, n):
    tracer.count("data.rows_emited", n)  # typo'd loader counter
    return n


def probe_wall(tracer, dt):
    tracer.observe("serve.lookup_secs", dt)  # typo'd histogram name
    trace.observe("sevre.fair_wait_seconds", dt)  # transposed prefix


def decode_timed(extents):
    with trace.span("decode", observe="engine.lanch_seconds"):  # typo
        return len(extents)


def hop_traced(peer):
    trace.count("trace.ctx_propagatd")  # typo'd propagation counter
    trace.gauge_max("trace.clock_offset_uss", 12)  # typo'd offset gauge
    with trace.span("serve.fleet_sreve", attrs={"peer": peer}):  # typo
        return peer
