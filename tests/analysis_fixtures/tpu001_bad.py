# floorlint: scope=FL-TPU
"""Seeded-bad: host file I/O and host CRC inside a jitted function —
both run once at trace time, not per call, and crc32 cannot see device
bytes at all."""

import zlib


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


@jit
def decode_step(payload):
    with open("/tmp/decode.cfg") as f:
        limit = int(f.read())
    if zlib.crc32(payload) == 0:
        return payload
    return payload[:limit]
