# floorlint: scope=FL-ASYNC
"""Seeded-good twin: every coroutine invocation is awaited or scheduled
— direct await, and fan-out through ``asyncio.gather`` (a wrapping call
consumes the coroutine object)."""
import asyncio


class Notifier:
    async def _notify(self, peer, payload):
        await peer.send(payload)

    async def broadcast(self, peers, payload):
        for peer in peers:
            await self._notify(peer, payload)

    async def broadcast_parallel(self, peers, payload):
        await asyncio.gather(
            *(self._notify(peer, payload) for peer in peers)
        )
