# floorlint: scope=FL-LOCK
"""Clean: the while-predicate loop (the serve/tenancy.py WFQ gate's
shape) — every wakeup re-checks the predicate before proceeding."""

import threading


class Gate:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def wait_ready(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()
            return self._ready

    def set_ready(self):
        with self._cv:
            self._ready = True
            self._cv.notify_all()
