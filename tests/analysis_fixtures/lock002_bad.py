# floorlint: scope=FL-LOCK
"""Seeded-bad: blocking while a lock is held — directly (file I/O in
the critical section) and through a helper the project call graph
resolves (the sleep+storage-read two frames down still stalls every
waiter of the lock)."""

import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}

    def refill_direct(self, key, path):
        with self._lock:
            with open(path, "rb") as fh:  # host I/O under the lock
                self._data[key] = fh.read()

    def refill_chained(self, key, source):
        with self._lock:
            self._data[key] = self._fetch(source)  # blocks via the chain

    def _fetch(self, source):
        time.sleep(0.05)  # backoff: every waiter of _lock pays it
        return source.read_at(0, 16)
