"""Seeded-bad: the query-subsystem leak shapes (docs/query.md) — a
JoinCursor (pins open readers of BOTH corpora's files mid-scan) bound
with no exception path releasing it, and one abandoned entirely after a
partial page drain."""

from parquet_floor_tpu.query.join import JoinCursor


def drain_join(left, right):
    cur = JoinCursor(left, right, on=["k"])
    rows = []
    while True:
        page = cur.next_page()  # a raise here leaks both corpora's fds
        if not page:
            break
        rows.extend(page)
    cur.close()
    return rows


def first_page(left, right):
    cur = JoinCursor(left, right, on=["k"], page_rows=64)
    return cur.next_page()  # never closed: iterators pin readers forever
