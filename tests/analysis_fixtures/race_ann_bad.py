# floorlint: scope=FL-RACE
"""Seeded-bad: the same loop-thread-owned-field shape as the good twin
but WITHOUT the ``# floorlint: unguarded=`` annotation — the unlocked
touches of the guarded field report."""
import threading


class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0

    def enqueue(self):
        with self._lock:
            self._pending += 1

    def done(self):
        with self._lock:
            self._pending -= 1

    def backlog(self):
        return self._pending  # unlocked read, no blessing
