# floorlint: scope=FL-TPU
"""Cross-module half B: the helper module.  Clean on its own — nothing
here is traced; the host I/O only matters when tpu_xmod_jit.py's traced
function reaches it through the import edge."""


def read_limit(path):
    with open(path) as fh:  # host I/O — fine on the host
        return int(fh.read())
