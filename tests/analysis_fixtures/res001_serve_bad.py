"""Seeded-bad: the serving-layer leak shapes — a SharedBufferCache /
Serving context / lookup Dataset bound to a local with no exception path
releasing it (the Dataset keeps file descriptors OPEN by design, so an
abandoned one is an fd leak, not just memory)."""

from parquet_floor_tpu.serve import Dataset, Serving, SharedBufferCache


def build_cache():
    cache = SharedBufferCache(data_bytes=1 << 20)
    cache.put(("f", 1), 0, b"xyz")  # a raise here leaks the buffers
    cache.close()
    return True


def serve_scan(paths):
    srv = Serving(prefetch_bytes=1 << 20)
    rows = sum(
        u.batch.num_rows for u in srv.tenant("a").scan(paths)
    )  # any scan error leaks the context and its owned cache
    srv.close()
    return rows


def probe(paths, key):
    ds = Dataset(paths, "k")
    rows = ds.lookup(key)  # a corrupt file here leaks every open reader
    ds.close()
    return rows
