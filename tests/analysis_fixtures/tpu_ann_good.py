# floorlint: scope=FL-TPU
"""Seeded-good twin of ``tpu_ann_bad``: the same annotated-receiver
dispatch shapes, but the resolved methods are pure compute — the
annotation-driven edges must not fabricate host-I/O findings."""


def jit(fn):  # stand-in so the fixture parses without jax installed
    return fn


class ConfigStore:
    def load_pure(self, x):
        return x + 1


def make_store():
    return ConfigStore()


@jit
def decode_param(payload, store: "ConfigStore"):
    return payload[: store.load_pure(1)]


@jit
def decode_local(payload):
    s: ConfigStore = make_store()
    return payload[: s.load_pure(2)]


class Decoder:
    store: ConfigStore

    def __init__(self, store):
        self.store = store

    @jit
    def decode(self, payload):
        return payload[: self.store.load_pure(3)]
