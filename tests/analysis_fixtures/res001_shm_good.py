"""Seeded-good: the process-scale serving shapes, properly managed —
with-managed, transferred, or closed in a finally."""

from parquet_floor_tpu.serve import (
    DaemonClient,
    ServeDaemon,
    SharedBufferCache,
    ShmCacheTier,
)


def build_tier():
    with ShmCacheTier.create(data_bytes=1 << 20) as tier:
        tier.put(("f", 1), 0, b"xyz")
    return True


def attach_tier(name):
    tier = ShmCacheTier.attach(name)
    try:
        return tier.get(("f", 1), 0, 3)
    finally:
        tier.close()


def mount_tier(name):
    # ownership transfer: the cache's caller owns the tier's close
    return SharedBufferCache(shm=ShmCacheTier.attach(name))


def run_daemon(serving, datasets):
    with ServeDaemon(serving, datasets) as daemon:  # __enter__ starts
        return daemon.port


def probe_daemon(port):
    with DaemonClient("127.0.0.1", port, "t") as client:
        return client.lookup("ds", 7)
