"""Clean: remote sessions/pools are with-managed, released in a
finally, transferred into an owner, or returned through a factory
lambda (the scan scheduler's lazy-open protocol — the caller that
resolves the factory owns the close)."""

from parquet_floor_tpu.io.remote import ParallelRangeReader, RemoteSource
from parquet_floor_tpu.testing import SimulatedRemoteSource


def fetch_footer(transport):
    with RemoteSource(transport) as src:
        return src.read_at(src.size - 8, 8)


def simulate(path, profile):
    sim = SimulatedRemoteSource(path, profile=profile)
    try:
        return sim.read_at(0, 16)
    finally:
        sim.close()


def fan_out(inner, ranges):
    with ParallelRangeReader(inner) as reader:
        return reader.read_many(ranges)


def dataset_factories(paths, profile):
    # ownership transfer: each factory's RemoteSource is opened — and
    # closed — by the scan executor that calls it
    return [
        (lambda p=p: SimulatedRemoteSource(p, profile=profile))
        for p in paths
    ]
