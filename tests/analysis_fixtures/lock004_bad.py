# floorlint: scope=FL-LOCK
"""Seeded-bad: inconsistent lock-acquisition order — `debit` nests
accounts→audit lexically while `credit` reaches audit→accounts through
a helper call.  Two threads running one of each deadlock: each holds
the lock the other needs."""

import threading


class Ledger:
    def __init__(self):
        self._accounts = threading.Lock()
        self._audit = threading.Lock()
        self.balance = {}
        self.log = []

    def debit(self, key, n):
        with self._accounts:
            with self._audit:  # order: accounts -> audit
                self.log.append((key, -n))
                self.balance[key] = self.balance.get(key, 0) - n

    def credit(self, key, n):
        with self._audit:  # order: audit -> accounts, via the helper
            self._locked_credit(key, n)

    def _locked_credit(self, key, n):
        with self._accounts:
            self.log.append((key, n))
            self.balance[key] = self.balance.get(key, 0) + n
