# floorlint: scope=FL-RACE
"""Seeded-good: the ``# floorlint: unguarded=<why>`` escape — a field
the analysis would otherwise guard, blessed class-wide with an in-code
justification (the rationale also gets a row in
``docs/static_analysis.md``'s suppression table when used live)."""
import threading


class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        # floorlint: unguarded=observability-only approximation, exact
        self._pending = 0

    def enqueue(self):
        with self._lock:
            self._pending += 1

    def done(self):
        with self._lock:
            self._pending -= 1

    def backlog(self):
        return self._pending  # blessed: a stale read is acceptable here
