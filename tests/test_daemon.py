"""Serving daemon (serve/daemon.py, docs/serving.md): the protocol,
per-connection tenant attribution, admission control, graceful drain,
and the multi-worker metrics fold."""

import json
import os
import threading
import time

import numpy as np
import pytest

from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.serve import (
    DaemonClient,
    Dataset,
    ServeDaemon,
    Serving,
)

GROUP = 128
PAGE = 32
GROUPS = 3
FILES = 2
PER = GROUP * GROUPS


@pytest.fixture(scope="module")
def paths(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("daemon")
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    out = []
    for i in range(FILES):
        p = str(tmp / f"f{i}.parquet")
        rng = np.random.default_rng(i)
        with ParquetFileWriter(p, schema, WriterOptions(
            row_group_rows=GROUP, data_page_values=PAGE,
            bloom_filter_columns={"k": True},
        )) as w:
            for lo in range(0, PER, GROUP):
                base = 2 * (i * PER + lo)
                w.write_columns({
                    "k": base + 2 * np.arange(GROUP, dtype=np.int64),
                    "s": [f"s{j % 17}" for j in range(GROUP)],
                })
        out.append(p)
    return out


def serving_daemon(paths, **daemon_kw):
    """(serving, dataset, daemon) context helper — the caller closes
    via the returned daemon context."""
    srv = Serving(prefetch_bytes=8 << 20, device_lanes=2)
    ds = Dataset(paths, "k", cache=srv.cache)
    daemon = ServeDaemon(srv, {"t": ds}, **daemon_kw)
    return srv, ds, daemon


def test_lookup_range_and_errors(paths):
    srv, ds, daemon = serving_daemon(paths)
    with srv, ds, daemon:
        with DaemonClient("127.0.0.1", daemon.port, "alice") as c:
            assert c.ping()
            assert c.lookup("t", 0, columns=["k"]) == [{"k": 0}]
            assert c.lookup("t", 3) == []      # absent key
            rows = c.range("t", 0, 40)
            assert [r["k"] for r in rows] == list(range(0, 41, 2))
            assert c.range("t", 0, 40, limit=5) == rows[:5]
            # unknown dataset / op / malformed line keep the
            # connection usable
            r = c.request("lookup", dataset="nope", key=1)
            assert r["ok"] is False and r["code"] == "bad_request"
            r = c.request("frobnicate")
            assert r["ok"] is False and r["code"] == "bad_request"
            c._sock.sendall(b"this is not json\n")
            r = json.loads(c._rfile.readline())
            assert r["ok"] is False and r["code"] == "bad_request"
            assert c.lookup("t", 0, columns=["k"]) == [{"k": 0}]


def test_hello_required_and_weight_conflict(paths):
    srv, ds, daemon = serving_daemon(paths)
    with srv, ds, daemon:
        import socket as _socket

        s = _socket.create_connection(("127.0.0.1", daemon.port), 10)
        try:
            s.sendall(b'{"op": "lookup", "dataset": "t", "key": 0}\n')
            r = json.loads(s.makefile("rb").readline())
            assert r["code"] == "hello_required"
        finally:
            s.close()
        with DaemonClient("127.0.0.1", daemon.port, "w", weight=2.0):
            # re-registering the same tenant at a DIFFERENT weight is
            # the serving layer's rejection, surfaced at hello
            with pytest.raises(RuntimeError, match="already registered"):
                with DaemonClient("127.0.0.1", daemon.port, "w",
                                  weight=3.0):
                    pass


def test_per_connection_tenant_attribution(paths):
    srv, ds, daemon = serving_daemon(paths)
    with srv, ds, daemon:
        with DaemonClient("127.0.0.1", daemon.port, "ta") as ca, \
                DaemonClient("127.0.0.1", daemon.port, "tb") as cb:
            for i in range(4):
                ca.lookup("t", 2 * i, columns=["k"])
            cb.lookup("t", 0, columns=["k"])
            ta = srv.tenant("ta")
            tb = srv.tenant("tb")
            assert ta.tracer.counters().get("serve.lookup_probes") == 4
            assert tb.tracer.counters().get("serve.lookup_probes") == 1
            # the device WFQ gate metered every daemon probe
            assert "serve.device_seconds" in ta.tracer.histograms()
            assert ta.tracer.histograms()[
                "serve.daemon_request_seconds"
            ].count == 4


def test_range_page_stateless_paging(paths):
    srv, ds, daemon = serving_daemon(paths)
    with srv, ds, daemon:
        brute = ds.range(0, 2 * PER)
        with DaemonClient("127.0.0.1", daemon.port, "pager") as c:
            got, cur, pages = [], None, 0
            while True:
                rows, cur = c.range_page("t", 0, 2 * PER, page_rows=29,
                                         cursor=cur)
                got.extend(rows)
                pages += 1
                if cur is None:
                    break
            assert got == brute
            assert pages >= 2
            # resume an abandoned cursor mid-stream, fresh connection
            rows1, cur1 = c.range_page("t", 0, 2 * PER, page_rows=13)
        with DaemonClient("127.0.0.1", daemon.port, "pager2") as c2:
            rest, cur2 = [], cur1
            while cur2 is not None:
                rows, cur2 = c2.range_page("t", 0, 2 * PER, page_rows=50,
                                           cursor=cur2)
                rest.extend(rows)
            assert rows1 + rest == brute


def test_admission_control_rejects_over_cap(paths):
    """Flood a 1-wide, 2-pending daemon through a slow dataset: some
    requests must be rejected with the overloaded code + retry hint,
    and every accepted one completes correctly."""

    class SlowDataset:
        def __init__(self, inner):
            self._inner = inner

        def lookup(self, key, columns=None, tenant=None, limit=None):
            time.sleep(0.05)
            return self._inner.lookup(key, columns=columns,
                                      tenant=tenant, limit=limit)

    import contextlib

    with Serving(prefetch_bytes=8 << 20) as srv, \
            Dataset(paths, "k", cache=srv.cache) as ds:
        with ServeDaemon(srv, {"t": SlowDataset(ds)},
                         max_inflight=1, max_pending=2) as daemon:
            with contextlib.ExitStack() as stack:
                clients = [
                    stack.enter_context(
                        DaemonClient("127.0.0.1", daemon.port, f"c{i}")
                    )
                    for i in range(6)
                ]
                outs = {}

                def fire(i):
                    outs[i] = clients[i].request(
                        "lookup", dataset="t", key=0, columns=["k"],
                    )

                threads = [threading.Thread(target=fire, args=(i,))
                           for i in range(6)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                rejected = [o for o in outs.values()
                            if not o.get("ok")]
                accepted = [o for o in outs.values() if o.get("ok")]
                assert rejected, "nothing was rejected at 6x overload"
                for o in rejected:
                    assert o["code"] == "overloaded"
                    assert o["retry_after_ms"] > 0
                for o in accepted:
                    assert o["rows"] == [{"k": 0}]
                snap = daemon.worker_snapshot()
                assert snap["counters"]["serve.daemon_rejected"] == \
                    len(rejected)
                assert snap["counters"]["serve.daemon_requests"] == \
                    len(accepted)


def test_graceful_drain_finishes_inflight(paths):
    """A request in flight when drain starts must complete and be
    delivered; post-drain requests get the draining rejection."""

    class GateDataset:
        def __init__(self, inner, release):
            self._inner = inner
            self._release = release
            self.entered = threading.Event()

        def lookup(self, key, columns=None, tenant=None, limit=None):
            self.entered.set()
            assert self._release.wait(10)
            return self._inner.lookup(key, columns=columns,
                                      tenant=tenant, limit=limit)

    release = threading.Event()
    with Serving(prefetch_bytes=8 << 20) as srv, \
            Dataset(paths, "k", cache=srv.cache) as ds:
        gate = GateDataset(ds, release)
        with ServeDaemon(srv, {"t": gate}) as daemon:
            with DaemonClient("127.0.0.1", daemon.port, "d") as c:
                out = {}

                def fire():
                    out["r"] = c.request("lookup", dataset="t", key=0,
                                         columns=["k"])

                t = threading.Thread(target=fire)
                t.start()
                assert gate.entered.wait(10)
                drained = {}

                def do_drain():
                    drained["clean"] = daemon.drain(10.0)

                dt = threading.Thread(target=do_drain)
                dt.start()
                time.sleep(0.05)       # drain is now waiting on us
                release.set()
                t.join(10)
                dt.join(10)
                assert drained["clean"] is True
                assert out["r"]["ok"] and out["r"]["rows"] == [{"k": 0}]
                r = c.request("lookup", dataset="t", key=0)
                assert r["code"] == "draining"


def test_metrics_fold_across_workers(paths, tmp_path):
    """The daemon's metrics op folds OTHER workers' pushed snapshots
    with its own live tenants — and the push/merge round-trips."""
    from parquet_floor_tpu.utils.metrics_export import write_snapshot

    mdir = str(tmp_path / "metrics")
    os.makedirs(mdir)
    write_snapshot(
        {"counters": {"serve.lookup_probes": 7},
         "gauges": {}, "stages": {}, "histograms": {}},
        os.path.join(mdir, "worker-else.json"),
    )
    with Serving(prefetch_bytes=8 << 20) as srv, \
            Dataset(paths, "k", cache=srv.cache) as ds:
        with ServeDaemon(srv, {"t": ds}, metrics_dir=mdir) as daemon:
            with DaemonClient("127.0.0.1", daemon.port, "m") as c:
                for i in range(3):
                    c.lookup("t", 2 * i, columns=["k"])
                merged = c.metrics()
                assert merged["counters"]["serve.lookup_probes"] == 10
                assert "serving health:" in c.health()
            daemon.drain(5.0)
            # drain pushed OUR snapshot; a fresh dir fold now carries it
            from parquet_floor_tpu.utils.metrics_export import (
                merge_snapshot_dir,
            )

            folded = merge_snapshot_dir(mdir)
            assert folded["counters"]["serve.lookup_probes"] == 10


def test_daemon_rejects_bad_config(paths):
    with Serving(prefetch_bytes=8 << 20) as srv, \
            Dataset(paths, "k", cache=srv.cache) as ds:
        with pytest.raises(ValueError, match="max_inflight"):
            with ServeDaemon(srv, {"t": ds}, max_inflight=0):
                pass
        with pytest.raises(ValueError, match="max_pending"):
            with ServeDaemon(srv, {"t": ds}, max_inflight=4,
                             max_pending=2):
                pass


def test_malformed_hello_weight_keeps_connection_usable(paths):
    """A non-numeric hello weight answers bad_request — it must not
    kill the connection (the documented error contract)."""
    import socket as _socket

    srv, ds, daemon = serving_daemon(paths)
    with srv, ds, daemon:
        s = _socket.create_connection(("127.0.0.1", daemon.port), 10)
        try:
            rf = s.makefile("rb")
            s.sendall(b'{"op": "hello", "tenant": "t", '
                      b'"weight": "heavy"}\n')
            r = json.loads(rf.readline())
            assert r["ok"] is False and r["code"] == "bad_request"
            s.sendall(b'{"op": "hello", "tenant": "t", '
                      b'"weight": null}\n')
            r = json.loads(rf.readline())
            assert r["ok"] is False and r["code"] == "bad_request"
            # the same socket registers cleanly afterwards
            s.sendall(b'{"op": "hello", "tenant": "t"}\n')
            r = json.loads(rf.readline())
            assert r["ok"] is True and r["weight"] == 1.0
            s.sendall(b'{"op": "lookup", "dataset": "t", "key": 0, '
                      b'"columns": ["k"]}\n')
            r = json.loads(rf.readline())
            assert r["ok"] is True and r["rows"] == [{"k": 0}]
        finally:
            s.close()
