"""Multi-tenant serving layer (``parquet_floor_tpu.serve``): shared
buffer cache tiers + single-flight + eviction safety, fair-share
tenancy and per-tenant report attribution, and the point/range lookup
face's pruning ladder and byte-cost contract (docs/serving.md)."""

import threading
import time

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    UnsupportedFeatureError,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.scan import DatasetScanner, ScanOptions
from parquet_floor_tpu.serve import (
    CachedSource,
    Dataset,
    Serving,
    SharedBufferCache,
    source_key,
)
from parquet_floor_tpu.serve.tenancy import _FairGate, _TenantShare

GROUP = 200
PAGE = 50
GROUPS = 3


def _write_keyed(path, file_index=0, groups=GROUPS, bloom=True):
    """Ascending EVEN int64 keys (odd keys absent but inside range —
    the bloom rung's food), several pages per group."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    per = GROUP * groups
    rng = np.random.default_rng(file_index)
    with ParquetFileWriter(path, schema, WriterOptions(
        row_group_rows=GROUP, data_page_values=PAGE,
        bloom_filter_columns={"k": True} if bloom else None,
    )) as w:
        for lo in range(0, per, GROUP):
            base = 2 * (file_index * per + lo)
            w.write_columns({
                "k": base + 2 * np.arange(GROUP, dtype=np.int64),
                "s": [None if j % 9 == 0 else f"s{j % 23}"
                      for j in range(GROUP)],
                "d": rng.standard_normal(GROUP),
            })
    return str(path)


@pytest.fixture(scope="module")
def keyed(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_ds")
    return [
        _write_keyed(str(d / f"f{i}.parquet"), file_index=i)
        for i in range(2)
    ]


# ---------------------------------------------------------------------------
# SharedBufferCache
# ---------------------------------------------------------------------------


def test_cache_get_put_containment_and_lru_eviction():
    with SharedBufferCache(data_bytes=100, meta_bytes=100) as c:
        key = ("f", 1)
        c.put(key, 0, b"a" * 40)
        c.put(key, 100, b"b" * 40)
        assert bytes(c.get(key, 5, 10)) == b"a" * 10   # sub-range containment
        assert c.get(key, 40, 10) is None               # gap between entries
        # the get() above touched [0,40): inserting 40 more evicts the
        # LRU entry [100,140), not the freshly-touched one
        c.put(key, 200, b"c" * 40)
        assert c.get(key, 100, 40) is None
        assert bytes(c.get(key, 0, 40)) == b"a" * 40
        assert c.stats()["evictions"] == 1


def test_eviction_never_corrupts_inflight_borrow():
    with SharedBufferCache(data_bytes=64, meta_bytes=64) as c:
        key = ("f", 1)
        c.put(key, 0, b"x" * 60)
        view = c.get(key, 0, 60)
        c.put(key, 1000, b"y" * 60)  # evicts [0, 60)
        assert c.get(key, 0, 60) is None
        assert bytes(view) == b"x" * 60  # the borrow is immune to eviction


def test_pinned_tier_survives_data_churn_and_has_its_own_lru():
    with trace.scope() as t:
        with SharedBufferCache(data_bytes=64, meta_bytes=64) as c:
            key = ("f", 1)
            c.put(key, 0, b"m" * 40, pinned=True)
            for i in range(8):  # data churn far past the data budget
                c.put(key, 1000 + 100 * i, b"d" * 60)
            assert bytes(c.get(key, 0, 40)) == b"m" * 40  # still pinned
            c.put(key, 500, b"n" * 40, pinned=True)  # meta over budget
            assert c.get(key, 0, 40) is None  # meta LRU evicted, counted
            assert c.stats()["meta_evictions"] == 1
    assert t.counters()["serve.meta_evictions"] == 1


def test_pinned_put_promotes_existing_entry():
    with SharedBufferCache(data_bytes=64, meta_bytes=1 << 20) as c:
        key = ("f", 1)
        c.put(key, 0, b"m" * 40)            # data tier
        c.put(key, 0, b"m" * 40, pinned=True)  # promote, don't duplicate
        c.put(key, 1000, b"d" * 60)         # would evict a data entry
        assert bytes(c.get(key, 0, 40)) == b"m" * 40
        st = c.stats()
        assert st["meta_bytes_used"] == 40 and st["data_bytes_used"] == 60


def test_single_flight_dedup_one_storage_read():
    with SharedBufferCache() as c:
        key = ("f", 1)
        reads = []
        inflight = threading.Event()
        results = {}

        def leader_read(ranges):
            reads.append(ranges)
            inflight.set()
            # hold the flight open until the waiter is registered
            deadline = time.monotonic() + 5
            while c.stats()["singleflight_waits"] < 1:
                if time.monotonic() > deadline:
                    raise AssertionError("waiter never arrived")
                time.sleep(0.001)
            return [b"z" * n for _, n in ranges]

        def lead():
            results["lead"] = bytes(
                c.fetch(key, 0, 8, lambda: leader_read([(0, 8)])[0])
            )

        def wait():
            inflight.wait(5)
            results["wait"] = bytes(c.fetch(
                key, 0, 8,
                lambda: (_ for _ in ()).throw(AssertionError("dup read")),
            ))

        t1 = threading.Thread(target=lead)
        t2 = threading.Thread(target=wait)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        assert results["lead"] == results["wait"] == b"z" * 8
        st = c.stats()
        assert st["misses"] == 1 and st["singleflight_waits"] == 1


def test_single_flight_error_propagates_and_clears():
    with SharedBufferCache() as c:
        key = ("f", 1)
        inflight = threading.Event()
        errs = []

        def failing_read():
            inflight.set()
            deadline = time.monotonic() + 5
            while c.stats()["singleflight_waits"] < 1:
                if time.monotonic() > deadline:
                    raise AssertionError("waiter never arrived")
                time.sleep(0.001)
            raise OSError("flaky")

        def lead():
            try:
                c.fetch(key, 0, 8, failing_read)
            except OSError as e:
                errs.append(("lead", str(e)))

        def wait():
            inflight.wait(5)
            try:
                c.fetch(key, 0, 8, failing_read)
            except OSError as e:
                errs.append(("wait", str(e)))

        t1 = threading.Thread(target=lead)
        t2 = threading.Thread(target=wait)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        assert sorted(w for w, _ in errs) == ["lead", "wait"]
        # the flight is cleared: a later fetch re-issues and succeeds
        assert bytes(c.fetch(key, 0, 8, lambda: b"ok" * 4)) == b"ok" * 4


def test_concurrent_mutation_under_load_serves_true_bytes():
    """Two threads fetching/evicting under a tiny budget: every byte
    served must match ground truth — eviction churn may forget, never
    corrupt."""
    truth = bytes(np.random.default_rng(0).integers(0, 256, 4096,
                                                    dtype=np.uint8))
    with SharedBufferCache(data_bytes=512, meta_bytes=512) as c:
        key = ("f", len(truth))
        stop = time.monotonic() + 1.0
        failures = []

        def worker(seed):
            rng = np.random.default_rng(seed)
            while time.monotonic() < stop:
                off = int(rng.integers(0, len(truth) - 64))
                n = int(rng.integers(1, 64))
                got = c.fetch(
                    key, off, n, lambda o=off, m=n: truth[o : o + m]
                )
                if bytes(got) != truth[off : off + n]:
                    failures.append((off, n))
                    return

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures
        assert c.stats()["evictions"] > 0  # the churn actually churned


def test_cache_close_refuses_and_invalidate_forgets():
    c = SharedBufferCache()
    key = ("f", 1)
    try:
        c.put(key, 0, b"abc")
        c.invalidate(key)
        assert c.get(key, 0, 3) is None
    finally:
        c.close()
    with pytest.raises(ValueError):
        c.fetch(key, 0, 3, lambda: b"abc")
    c.close()  # idempotent


# ---------------------------------------------------------------------------
# CachedSource in the scan chain
# ---------------------------------------------------------------------------


def test_cached_scan_bit_identical_and_second_scan_hits(keyed):
    def digest(units):
        out = []
        for u in units:
            for b in u.batch.columns:
                v = b.values
                if hasattr(v, "offsets"):
                    out.append((bytes(np.asarray(v.offsets).data),
                                bytes(np.asarray(v.data).data)))
                else:
                    out.append(bytes(np.ascontiguousarray(v).data))
        return out

    with DatasetScanner(keyed) as s:
        want = digest(s)
    with Serving(prefetch_bytes=8 << 20) as srv:
        ta = srv.tenant("a")
        tb = srv.tenant("b")
        with ta.scan(keyed) as s:
            got_a = digest(s)
        with tb.scan(keyed) as s:
            got_b = digest(s)
        assert got_a == want and got_b == want
        rb = tb.report()
        hit = rb.counters.get("serve.cache_hit_bytes", 0)
        miss = rb.counters.get("serve.cache_miss_bytes", 0)
        assert hit / (hit + miss) >= 0.5  # the acceptance floor
        ra = ta.report()
        assert ra.counters.get("serve.cache_misses", 0) > 0
        # attribution is disjoint: A's tracer never saw B's hits
        assert ra.counters.get("serve.cache_hit_bytes", 0) < hit


def test_concurrent_tenant_reports_disjoint(keyed):
    with Serving(prefetch_bytes=8 << 20) as srv:
        warm = srv.tenant("warm")
        with warm.scan(keyed) as s:
            rows = sum(u.batch.num_rows for u in s)
        t1 = srv.tenant("one", weight=2)
        t2 = srv.tenant("two")
        results = {}

        def run(name, tenant):
            with tenant.scan(keyed) as s:
                results[name] = sum(u.batch.num_rows for u in s)

        threads = [threading.Thread(target=run, args=(n, t))
                   for n, t in (("one", t1), ("two", t2))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {"one": rows, "two": rows}
        used = warm.report().counters.get("scan.bytes_used")
        for t in (t1, t2):
            rep = t.report()
            assert rep.counters.get("scan.bytes_used") == used
            assert rep.counters.get("data.rows_emitted") is None


def test_source_key_shared_across_opens(keyed):
    with SharedBufferCache() as c:
        with ParquetFileReader(keyed[0]) as r:
            pass
        from parquet_floor_tpu.io.source import FileSource

        s1 = FileSource(keyed[0])
        s2 = FileSource(keyed[0])
        try:
            assert source_key(s1) == source_key(s2)
            cs1 = CachedSource(s1, c)
            cs2 = CachedSource(s2, c)
            assert bytes(cs1.read_at(0, 4)) == b"PAR1"
            assert bytes(cs2.read_at(0, 4)) == b"PAR1"
            st = c.stats()
            assert st["misses"] == 1 and st["hits"] == 1
        finally:
            s1.close()
            s2.close()


# ---------------------------------------------------------------------------
# Fair-share gate + budget admission
# ---------------------------------------------------------------------------


def test_fair_gate_grants_in_weighted_virtual_time_order():
    """Backlogged 1-slot gate, weight-2 vs weight-1 tenants enqueueing
    alternately: grants must follow WFQ virtual finish tags (heavy tags
    advance by cost/2, light by cost), not arrival order."""
    gate = _FairGate(capacity_bytes=100)
    heavy = _TenantShare(2.0, gate)
    light = _TenantShare(1.0, gate)
    gate.acquire(heavy, 100)  # saturate: everything below queues
    order = []
    lock = threading.Lock()

    def worker(share, name):
        gate.acquire(share, 100)
        with lock:
            order.append(name)
        gate.release(100)

    # arrival h1,l1,h2,l2,h3,l3,h4,l4 — tags: h 50,100,150,200;
    # l 0,100,200,300 (light starts at the current virtual clock, so
    # its FIRST request rightly jumps the heavy backlog; from then on
    # heavy interleaves 2:1 by tag, ties broken by arrival)
    threads = []
    for name, share in (("h1", heavy), ("l1", light), ("h2", heavy),
                        ("l2", light), ("h3", heavy), ("l3", light),
                        ("h4", heavy), ("l4", light)):
        t = threading.Thread(target=worker, args=(share, name))
        threads.append(t)
        t.start()
        time.sleep(0.05)  # deterministic arrival (and seq) order
    gate.release(100)  # open: each grant's release cascades the next
    for t in threads:
        t.join()
    assert order == ["l1", "h1", "h2", "l2", "h3", "l3", "h4", "l4"]


def test_fair_gate_counts_waits_and_gauges():
    gate = _FairGate(capacity_bytes=10)
    share = _TenantShare(1.0, gate)
    with trace.scope() as t:
        gate.acquire(share, 10)
        done = threading.Event()

        def blocked():
            gate.acquire(share, 10)
            gate.release(10)
            done.set()

        # carry the scope onto the worker (contextvars do not cross
        # thread spawns — the CachedSource gate path rides Tracer.run
        # the same way via the scan pools)
        th = threading.Thread(target=t.run, args=(blocked,))
        th.start()
        time.sleep(0.05)
        gate.release(10)
        th.join()
        assert done.is_set()
    assert t.counters()["serve.fair_share_waits"] == 1
    assert t.gauges()["serve.inflight_storage_bytes_max"] == 10


def test_budget_shares_follow_weights():
    with Serving(prefetch_bytes=30 << 20) as srv:
        heavy = srv.tenant("heavy", weight=2)
        light = srv.tenant("light", weight=1)
        assert heavy.prefetch_share() == 20 << 20
        assert light.prefetch_share() == 10 << 20
        sc = light.scan_options(ScanOptions(threads=2))
        assert sc.prefetch_bytes == 10 << 20 and sc.threads == 2
        light.close()  # weights rebalance
        assert heavy.prefetch_share() == 30 << 20
        with pytest.raises(ValueError):
            light.scan([])
        with pytest.raises(ValueError):
            srv.tenant("heavy", weight=5)  # conflicting re-registration
        assert srv.tenant("heavy", weight=2) is heavy


# ---------------------------------------------------------------------------
# The lookup face
# ---------------------------------------------------------------------------


def test_lookup_point_and_range_match_brute_force(keyed):
    with Dataset(keyed, "k") as ds:
        per = GROUP * GROUPS
        key = 2 * (per + 123)  # file 1
        rows = ds.lookup(key)
        assert [r["k"] for r in rows] == [key]
        assert set(rows[0]) == {"k", "s", "d"}
        lo, hi = 2 * (per - 5), 2 * (per + 5)  # spans the file boundary
        got = sorted(r["k"] for r in ds.range(lo, hi))
        assert got == list(range(lo, hi + 1, 2))
        assert ds.lookup(2 * per + 1) == []         # absent odd key
        assert ds.lookup(10 ** 12) == []            # outside every range
        one = ds.lookup(key, columns=["k"], limit=1)
        assert one == [{"k": key}]


def test_lookup_prunes_counts_and_bloom_skips(keyed):
    with trace.scope() as t:
        with Dataset(keyed, "k") as ds:
            ds.lookup(0)          # warm: pins metadata everywhere
            c0 = t.counters()
            assert c0.get("serve.lookup_groups_pruned", 0) >= 1
            # absent odd key inside group 0's [min, max]: stats keep the
            # group, the bloom filter must kill it (no page decoded)
            for off in range(1, 99, 2):
                ds.lookup(off, limit=1)
                if t.counters().get("serve.lookup_bloom_skips", 0):
                    break
            c1 = t.counters()
            assert c1.get("serve.lookup_bloom_skips", 0) >= 1
            assert c1.get("serve.lookup_probes", 0) >= 2
            assert c1.get("serve.lookup_rows", 0) >= 1


def test_hot_lookup_costs_at_most_one_page(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            ds.lookup(0)  # warm every file's metadata pins
            bound = ds.page_size_bound()
            s0 = cache.stats()
            per = GROUP * GROUPS
            rows = ds.lookup(2 * (2 * per - 1), columns=["k"])
            cost = cache.stats()["miss_bytes"] - s0["miss_bytes"]
            assert len(rows) == 1
            assert 0 < cost <= bound


def test_lookup_reuses_cached_footer_across_datasets(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            ds.lookup(0)
            assert cache.stats()["footers"] == len(keyed)
        with Dataset(keyed, "k", cache=cache) as ds2:
            # parsed footers come back from the object tier; the raw
            # footer/index/bloom bytes are already pinned, so the only
            # storage traffic is the probe's data page(s)
            s0 = cache.stats()
            ds2.lookup(0)
            assert cache.stats()["misses"] == s0["misses"]


def test_lookup_rejects_salvage_and_closed_use(keyed):
    with pytest.raises(UnsupportedFeatureError):
        # the constructor itself rejects salvage — nothing is acquired
        Dataset(keyed, "k",  # floorlint: disable=FL-RES001
                options=ReaderOptions(salvage=True))
    ds = Dataset(keyed, "k")
    try:
        assert ds.lookup(0)
    finally:
        ds.close()
    with pytest.raises(ValueError):
        ds.lookup(0)
    ds.close()  # idempotent


def test_lookup_concurrent_probes_with_tenant_attribution(keyed):
    with Serving(prefetch_bytes=8 << 20) as srv:
        with Dataset(keyed, "k", cache=srv.cache) as ds:
            ds.lookup(0)  # open + pin
            ta = srv.tenant("ap")
            tb = srv.tenant("bp")
            per = GROUP * GROUPS
            out = {}

            def probe(name, tenant, base):
                got = []
                for j in range(20):
                    got.extend(
                        r["k"] for r in
                        ds.lookup(2 * (base + j), tenant=tenant)
                    )
                out[name] = got

            t1 = threading.Thread(target=probe, args=("a", ta, 10))
            t2 = threading.Thread(target=probe, args=("b", tb, per + 10))
            t1.start()
            t2.start()
            t1.join()
            t2.join()
            assert out["a"] == [2 * (10 + j) for j in range(20)]
            assert out["b"] == [2 * (per + 10 + j) for j in range(20)]
            assert ta.tracer.counters()["serve.lookup_probes"] == 20
            assert tb.tracer.counters()["serve.lookup_probes"] == 20


def test_per_tenant_histograms_disjoint_across_concurrent_scans(keyed):
    """Two tenants probing CONCURRENTLY through their own scoped
    tracers: each tenant's latency histogram must hold exactly its own
    probes (count attribution), nothing leaked across scopes — the
    distribution mirror of test_concurrent_tenant_reports_disjoint."""
    probes = {"one": 9, "two": 17}
    with Serving(prefetch_bytes=8 << 20) as srv:
        t1 = srv.tenant("one", weight=2)
        t2 = srv.tenant("two")
        with Dataset(keyed, "k", cache=srv.cache) as ds:
            ds.lookup(0)  # warm: opens files outside the timed scans

            def run(tenant, n):
                for i in range(n):
                    ds.lookup(2 * i, columns=["k"], tenant=tenant)

            threads = [
                threading.Thread(target=run, args=(t1, probes["one"])),
                threading.Thread(target=run, args=(t2, probes["two"])),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for tenant, name in ((t1, "one"), (t2, "two")):
            rep = tenant.report()
            h = rep.histogram("serve.lookup_seconds")
            assert h is not None and h.count == probes[name], name
            assert rep.counters.get("serve.lookup_probes") == probes[name]
        # and a scan's histograms stay inside the scanning tenant too
        t3 = srv.tenant("three")
        with t3.scan(keyed) as s:
            for _ in s:
                pass
        assert "serve.lookup_seconds" not in t3.tracer.histograms()
        assert t1.tracer.histograms()["serve.lookup_seconds"].count == \
            probes["one"]


# ---------------------------------------------------------------------------
# device-time WFQ (the second metered resource — docs/serving.md)
# ---------------------------------------------------------------------------


def test_device_gate_orders_by_weighted_virtual_time():
    """Deterministic grant order: with one lane held, queued sessions
    from a weight-2 and a weight-1 tenant interleave 2:1 by virtual
    finish time, not FIFO."""
    from parquet_floor_tpu.serve.tenancy import _DeviceGate

    gate = _DeviceGate(lanes=1)
    heavy = _TenantShare(2.0, _FairGate(1 << 20), gate)
    light = _TenantShare(1.0, _FairGate(1 << 20), gate)
    # occupy the lane so every queued acquire must wait
    blocker = _TenantShare(1.0, _FairGate(1 << 20), gate)
    hold = gate.acquire(blocker)
    order = []
    lock = threading.Lock()

    def session(share, name):
        lease = gate.acquire(share)
        with lock:
            order.append(name)
        gate.release(lease, 0.001)

    def park(share, name, expect_waiters):
        """Start one session and WAIT until it is parked in the heap,
        so arrival order — and therefore the vtag/seq assignment — is
        fully deterministic."""
        t = threading.Thread(target=session, args=(share, name))
        t.start()
        deadline = time.monotonic() + 5
        while gate.stats()["waiters"] < expect_waiters:
            if time.monotonic() > deadline:
                raise AssertionError(f"{name} never parked")
            time.sleep(0.001)
        return t

    # arrival order H0, H1, L0.  vtags at the default estimate e:
    # H0 = v, H1 = v + e/2 (heavy's finish advanced by e/weight=e/2),
    # L0 = v with a later seq.  Weighted virtual-time order is
    # therefore H0, L0, H1 — a FIFO gate would grant H0, H1, L0.
    threads = [
        park(heavy, "H0", 1),
        park(heavy, "H1", 2),
        park(light, "L0", 3),
    ]
    gate.release(hold, 0.001)
    for t in threads:
        t.join()
    assert order == ["H0", "L0", "H1"], order
    stats = gate.stats()
    assert stats["busy"] == 0 and stats["waiters"] == 0


def test_device_gate_backlogged_shares_follow_weights():
    """The fairness law end to end: two continuously-backlogged
    tenants with 2:1 weights through a 1-lane gate split measured
    device seconds ~2:1 — equal offered load (2 threads each), the
    WEIGHT decides the split."""
    with Serving(prefetch_bytes=8 << 20, device_lanes=1) as srv:
        heavy = srv.tenant("heavy", weight=2.0)
        light = srv.tenant("light", weight=1.0)
        t_end = time.perf_counter() + 0.8

        def hammer(tenant):
            while time.perf_counter() < t_end:
                with tenant.device_session():
                    time.sleep(0.002)

        threads = (
            [threading.Thread(target=hammer, args=(heavy,))
             for _ in range(2)]
            + [threading.Thread(target=hammer, args=(light,))
               for _ in range(2)]
        )
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        hs = heavy.tracer.histograms()["serve.device_seconds"].total
        ls = light.tracer.histograms()["serve.device_seconds"].total
        share = hs / (hs + ls)
        assert abs(share - 2 / 3) < 0.15, share
        assert light.tracer.counters().get("serve.device_waits", 0) > 0


def test_cache_hot_tenant_held_to_weight_share(keyed):
    """The acceptance pin: a 100%-cache-hit tenant offering 3x the
    light tenant's load through a 1-lane device gate is held to its
    weight share of engine time (equal weights: one half), where
    ungated it exceeds it."""

    def run(lanes):
        with Serving(prefetch_bytes=8 << 20, device_lanes=lanes) as srv:
            hot = srv.tenant("hot")
            light = srv.tenant("light")
            with Dataset(keyed, "k", cache=srv.cache) as ds:
                keys = [2 * (g * GROUP + off)
                        for g in range(GROUPS)
                        for off in range(PAGE // 2, GROUP, PAGE)]
                for k in keys:   # warm with the EXACT probe shape
                    ds.range(k, k + 2 * PAGE, columns=["k"])
                t_end = time.perf_counter() + 0.8

                def hammer(tenant):
                    i = 0
                    while time.perf_counter() < t_end:
                        k = keys[i % len(keys)]
                        ds.range(k, k + 2 * PAGE, columns=["k"],
                                 tenant=tenant)
                        i += 1

                threads = (
                    [threading.Thread(target=hammer, args=(hot,))
                     for _ in range(6)]
                    + [threading.Thread(target=hammer, args=(light,))
                       for _ in range(2)]
                )
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            hs = hot.tracer.histograms()["serve.device_seconds"].total
            ls = light.tracer.histograms()["serve.device_seconds"].total
            hc = hot.tracer.counters()
            hit = hc.get("serve.cache_hit_bytes", 0)
            miss = hc.get("serve.cache_miss_bytes", 0)
            assert hit > 0 and miss == 0   # genuinely cache-hot
            return hs / (hs + ls)

    gated = run(lanes=1)
    ungated = run(lanes=8)
    assert ungated > 0.58, ungated     # the aggressor CAN exceed
    assert abs(gated - 0.5) < 0.13, (gated, ungated)


def test_charge_device_pushes_tenant_back_in_queue():
    """A post-hoc charge_device advances the tenant's virtual clock:
    its next contended acquire queues behind a fresh tenant."""
    from parquet_floor_tpu.serve.tenancy import _DeviceGate

    gate = _DeviceGate(lanes=1)
    with Serving(prefetch_bytes=8 << 20, device_lanes=1) as srv:
        charged = srv.tenant("charged")
        fresh = srv.tenant("fresh")
        charged.charge_device(5.0)
        assert charged._share.dfinish > fresh._share.dfinish
        h = charged.tracer.histograms()["serve.device_seconds"]
        assert h.total == pytest.approx(5.0)
    assert gate.stats()["waiters"] == 0


def test_health_shows_device_gate_and_tenant_device_seconds(keyed):
    with Serving(prefetch_bytes=8 << 20, device_lanes=3) as srv:
        t = srv.tenant("h")
        with t.device_session():
            pass
        page = srv.health()
        assert "device gate" in page and "0/3 lane(s)" in page
        assert "device=" in page


def test_serving_device_lanes_validation():
    with pytest.raises(ValueError, match="lanes"):
        with Serving(device_lanes=0):
            pass


# ---------------------------------------------------------------------------
# negative-lookup cache (PR 9 follow-on — docs/serving.md)
# ---------------------------------------------------------------------------


def test_negative_cache_short_circuits_repeat_absent_probes(keyed):
    with SharedBufferCache() as cache, trace.scope() as t:
        with Dataset(keyed, "k", cache=cache) as ds:
            assert ds.lookup(3) == []        # odd key: provably absent
            c0 = t.counters()
            assert c0.get("serve.negative_hits", 0) == 0
            pruned0 = c0.get("serve.lookup_groups_pruned", 0)
            bloom0 = c0.get("serve.lookup_bloom_skips", 0)
            assert ds.lookup(3) == []        # second probe, same key
            c1 = t.counters()
            assert c1.get("serve.negative_hits") == len(keyed)
            # the ladder never ran: no new prunes, no new bloom skips
            assert c1.get("serve.lookup_groups_pruned") == pruned0
            assert c1.get("serve.lookup_bloom_skips") == bloom0
            # present keys are never poisoned
            assert ds.lookup(0, columns=["k"]) == [{"k": 0}]
            assert ds.lookup(0, columns=["k"]) == [{"k": 0}]


def test_negative_cache_capped_lru(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache, negative_keys=4) as ds:
            for key in (1, 3, 5, 7, 9):     # 5 absent keys, cap 4
                ds.lookup(key)
            lf = ds._file(0)
            assert len(lf.neg) == 4
            assert 1 not in lf.neg          # oldest evicted
            with trace.scope() as t:
                ds.lookup(1)                # re-probe pays the ladder
                assert t.counters().get("serve.negative_hits", 0) == 0
                ds.lookup(9)                # cached absent: short-circuit
                assert t.counters().get("serve.negative_hits") == \
                    len(keyed)


def test_negative_cache_disabled_and_range_not_cached(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache, negative_keys=0) as ds:
            with trace.scope() as t:
                ds.lookup(3)
                ds.lookup(3)
                assert t.counters().get("serve.negative_hits", 0) == 0
        with Dataset(keyed, "k", cache=cache) as ds:
            with trace.scope() as t:
                # a range probe over an empty span records nothing
                assert ds.range(3, 3) == []
                ds.lookup(3)
                # ...so this lookup still descended the ladder fresh
                assert t.counters().get("serve.negative_hits", 0) == 0
    with pytest.raises(ValueError, match="negative_keys"):
        with Dataset(keyed, "k", negative_keys=-1):
            pass


def test_limit_stop_records_only_fully_descended_files(keyed):
    """A limit= early stop records absence ONLY for files that were
    fully descended and empty: file 0 (the key provably isn't there)
    yes, the file that SERVED the row never, and the row keeps being
    served on the short-circuited re-probe."""
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            per = GROUP * GROUPS
            key = 2 * per                   # lives in file 1 only
            assert ds.lookup(key, columns=["k"], limit=1) == [{"k": key}]
            assert key in ds._file(0).neg       # proven absent there
            assert key not in ds._file(1).neg   # it served the row
            with trace.scope() as t:
                assert ds.lookup(key, columns=["k"], limit=1) == \
                    [{"k": key}]
                assert t.counters().get("serve.negative_hits") == 1


# ---------------------------------------------------------------------------
# streaming range cursor (PR 9 follow-on — docs/serving.md)
# ---------------------------------------------------------------------------


def test_range_cursor_matches_range_and_pages_bounded(keyed):
    with SharedBufferCache() as cache, trace.scope() as t:
        with Dataset(keyed, "k", cache=cache) as ds:
            per = GROUP * GROUPS
            lo, hi = 10, 2 * per + 600
            brute = ds.range(lo, hi)
            cur = ds.range_cursor(lo, hi, page_rows=64)
            pages = []
            while True:
                page = cur.next_page()
                if not page:
                    break
                assert len(page) <= 64
                pages.append(page)
            assert [r for p in pages for r in p] == brute
            assert cur.exhausted and cur.token is None
            assert len(pages) >= 2
            assert t.counters().get("serve.cursor_pages") == \
                len(pages) + 1      # + the final empty page


def test_range_cursor_resume_token_json_safe(keyed):
    import json as _json

    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            brute = ds.range(0, 900)
            cur = ds.range_cursor(0, 900, page_rows=37)
            first = cur.next_page()
            token = _json.loads(_json.dumps(cur.token))
            rest = list(ds.range_cursor(0, 900, page_rows=64,
                                        cursor=token))
            assert first + rest == brute


def test_range_cursor_resume_at_every_page_boundary(keyed):
    """Exactly-once delivery across a resume at ANY page boundary —
    including mid-group and across the file boundary."""
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            per = GROUP * GROUPS
            lo, hi = 2 * (per - 80), 2 * (per + 80)   # spans both files
            brute = ds.range(lo, hi)
            cur = ds.range_cursor(lo, hi, page_rows=16)
            seen = []
            while True:
                page = cur.next_page()
                if not page:
                    break
                seen.extend(page)
                tok = cur.token
                if tok is not None:
                    remainder = list(ds.range_cursor(
                        lo, hi, page_rows=200, cursor=dict(tok)
                    ))
                    assert seen + remainder == brute
            assert seen == brute


def test_range_cursor_iteration_and_validation(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            assert list(ds.range_cursor(0, 100)) == ds.range(0, 100)
            assert list(ds.range_cursor(5, 3)) == []
            with pytest.raises(ValueError, match="page_rows"):
                ds.range_cursor(0, 10, page_rows=0)
            with pytest.raises(ValueError, match="cursor token"):
                ds.range_cursor(0, 10, cursor={"bogus": 1})


def test_range_cursor_tenant_attribution(keyed):
    with Serving(prefetch_bytes=8 << 20) as srv:
        t = srv.tenant("cur")
        with Dataset(keyed, "k", cache=srv.cache) as ds:
            list(ds.range_cursor(0, 400, tenant=t, page_rows=32))
            c = t.tracer.counters()
            assert c.get("serve.cursor_pages", 0) >= 2
            assert c.get("serve.lookup_rows", 0) == len(ds.range(0, 400))
            assert "serve.device_seconds" in t.tracer.histograms()
