"""Multi-tenant serving layer (``parquet_floor_tpu.serve``): shared
buffer cache tiers + single-flight + eviction safety, fair-share
tenancy and per-tenant report attribution, and the point/range lookup
face's pruning ladder and byte-cost contract (docs/serving.md)."""

import threading
import time

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    UnsupportedFeatureError,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.scan import DatasetScanner, ScanOptions
from parquet_floor_tpu.serve import (
    CachedSource,
    Dataset,
    Serving,
    SharedBufferCache,
    source_key,
)
from parquet_floor_tpu.serve.tenancy import _FairGate, _TenantShare

GROUP = 200
PAGE = 50
GROUPS = 3


def _write_keyed(path, file_index=0, groups=GROUPS, bloom=True):
    """Ascending EVEN int64 keys (odd keys absent but inside range —
    the bloom rung's food), several pages per group."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    per = GROUP * groups
    rng = np.random.default_rng(file_index)
    with ParquetFileWriter(path, schema, WriterOptions(
        row_group_rows=GROUP, data_page_values=PAGE,
        bloom_filter_columns={"k": True} if bloom else None,
    )) as w:
        for lo in range(0, per, GROUP):
            base = 2 * (file_index * per + lo)
            w.write_columns({
                "k": base + 2 * np.arange(GROUP, dtype=np.int64),
                "s": [None if j % 9 == 0 else f"s{j % 23}"
                      for j in range(GROUP)],
                "d": rng.standard_normal(GROUP),
            })
    return str(path)


@pytest.fixture(scope="module")
def keyed(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_ds")
    return [
        _write_keyed(str(d / f"f{i}.parquet"), file_index=i)
        for i in range(2)
    ]


# ---------------------------------------------------------------------------
# SharedBufferCache
# ---------------------------------------------------------------------------


def test_cache_get_put_containment_and_lru_eviction():
    with SharedBufferCache(data_bytes=100, meta_bytes=100) as c:
        key = ("f", 1)
        c.put(key, 0, b"a" * 40)
        c.put(key, 100, b"b" * 40)
        assert bytes(c.get(key, 5, 10)) == b"a" * 10   # sub-range containment
        assert c.get(key, 40, 10) is None               # gap between entries
        # the get() above touched [0,40): inserting 40 more evicts the
        # LRU entry [100,140), not the freshly-touched one
        c.put(key, 200, b"c" * 40)
        assert c.get(key, 100, 40) is None
        assert bytes(c.get(key, 0, 40)) == b"a" * 40
        assert c.stats()["evictions"] == 1


def test_eviction_never_corrupts_inflight_borrow():
    with SharedBufferCache(data_bytes=64, meta_bytes=64) as c:
        key = ("f", 1)
        c.put(key, 0, b"x" * 60)
        view = c.get(key, 0, 60)
        c.put(key, 1000, b"y" * 60)  # evicts [0, 60)
        assert c.get(key, 0, 60) is None
        assert bytes(view) == b"x" * 60  # the borrow is immune to eviction


def test_pinned_tier_survives_data_churn_and_has_its_own_lru():
    with trace.scope() as t:
        with SharedBufferCache(data_bytes=64, meta_bytes=64) as c:
            key = ("f", 1)
            c.put(key, 0, b"m" * 40, pinned=True)
            for i in range(8):  # data churn far past the data budget
                c.put(key, 1000 + 100 * i, b"d" * 60)
            assert bytes(c.get(key, 0, 40)) == b"m" * 40  # still pinned
            c.put(key, 500, b"n" * 40, pinned=True)  # meta over budget
            assert c.get(key, 0, 40) is None  # meta LRU evicted, counted
            assert c.stats()["meta_evictions"] == 1
    assert t.counters()["serve.meta_evictions"] == 1


def test_pinned_put_promotes_existing_entry():
    with SharedBufferCache(data_bytes=64, meta_bytes=1 << 20) as c:
        key = ("f", 1)
        c.put(key, 0, b"m" * 40)            # data tier
        c.put(key, 0, b"m" * 40, pinned=True)  # promote, don't duplicate
        c.put(key, 1000, b"d" * 60)         # would evict a data entry
        assert bytes(c.get(key, 0, 40)) == b"m" * 40
        st = c.stats()
        assert st["meta_bytes_used"] == 40 and st["data_bytes_used"] == 60


def test_single_flight_dedup_one_storage_read():
    with SharedBufferCache() as c:
        key = ("f", 1)
        reads = []
        inflight = threading.Event()
        results = {}

        def leader_read(ranges):
            reads.append(ranges)
            inflight.set()
            # hold the flight open until the waiter is registered
            deadline = time.monotonic() + 5
            while c.stats()["singleflight_waits"] < 1:
                if time.monotonic() > deadline:
                    raise AssertionError("waiter never arrived")
                time.sleep(0.001)
            return [b"z" * n for _, n in ranges]

        def lead():
            results["lead"] = bytes(
                c.fetch(key, 0, 8, lambda: leader_read([(0, 8)])[0])
            )

        def wait():
            inflight.wait(5)
            results["wait"] = bytes(c.fetch(
                key, 0, 8,
                lambda: (_ for _ in ()).throw(AssertionError("dup read")),
            ))

        t1 = threading.Thread(target=lead)
        t2 = threading.Thread(target=wait)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        assert results["lead"] == results["wait"] == b"z" * 8
        st = c.stats()
        assert st["misses"] == 1 and st["singleflight_waits"] == 1


def test_single_flight_error_propagates_and_clears():
    with SharedBufferCache() as c:
        key = ("f", 1)
        inflight = threading.Event()
        errs = []

        def failing_read():
            inflight.set()
            deadline = time.monotonic() + 5
            while c.stats()["singleflight_waits"] < 1:
                if time.monotonic() > deadline:
                    raise AssertionError("waiter never arrived")
                time.sleep(0.001)
            raise OSError("flaky")

        def lead():
            try:
                c.fetch(key, 0, 8, failing_read)
            except OSError as e:
                errs.append(("lead", str(e)))

        def wait():
            inflight.wait(5)
            try:
                c.fetch(key, 0, 8, failing_read)
            except OSError as e:
                errs.append(("wait", str(e)))

        t1 = threading.Thread(target=lead)
        t2 = threading.Thread(target=wait)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        assert sorted(w for w, _ in errs) == ["lead", "wait"]
        # the flight is cleared: a later fetch re-issues and succeeds
        assert bytes(c.fetch(key, 0, 8, lambda: b"ok" * 4)) == b"ok" * 4


def test_concurrent_mutation_under_load_serves_true_bytes():
    """Two threads fetching/evicting under a tiny budget: every byte
    served must match ground truth — eviction churn may forget, never
    corrupt."""
    truth = bytes(np.random.default_rng(0).integers(0, 256, 4096,
                                                    dtype=np.uint8))
    with SharedBufferCache(data_bytes=512, meta_bytes=512) as c:
        key = ("f", len(truth))
        stop = time.monotonic() + 1.0
        failures = []

        def worker(seed):
            rng = np.random.default_rng(seed)
            while time.monotonic() < stop:
                off = int(rng.integers(0, len(truth) - 64))
                n = int(rng.integers(1, 64))
                got = c.fetch(
                    key, off, n, lambda o=off, m=n: truth[o : o + m]
                )
                if bytes(got) != truth[off : off + n]:
                    failures.append((off, n))
                    return

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures
        assert c.stats()["evictions"] > 0  # the churn actually churned


def test_cache_close_refuses_and_invalidate_forgets():
    c = SharedBufferCache()
    key = ("f", 1)
    try:
        c.put(key, 0, b"abc")
        c.invalidate(key)
        assert c.get(key, 0, 3) is None
    finally:
        c.close()
    with pytest.raises(ValueError):
        c.fetch(key, 0, 3, lambda: b"abc")
    c.close()  # idempotent


# ---------------------------------------------------------------------------
# CachedSource in the scan chain
# ---------------------------------------------------------------------------


def test_cached_scan_bit_identical_and_second_scan_hits(keyed):
    def digest(units):
        out = []
        for u in units:
            for b in u.batch.columns:
                v = b.values
                if hasattr(v, "offsets"):
                    out.append((bytes(np.asarray(v.offsets).data),
                                bytes(np.asarray(v.data).data)))
                else:
                    out.append(bytes(np.ascontiguousarray(v).data))
        return out

    with DatasetScanner(keyed) as s:
        want = digest(s)
    with Serving(prefetch_bytes=8 << 20) as srv:
        ta = srv.tenant("a")
        tb = srv.tenant("b")
        with ta.scan(keyed) as s:
            got_a = digest(s)
        with tb.scan(keyed) as s:
            got_b = digest(s)
        assert got_a == want and got_b == want
        rb = tb.report()
        hit = rb.counters.get("serve.cache_hit_bytes", 0)
        miss = rb.counters.get("serve.cache_miss_bytes", 0)
        assert hit / (hit + miss) >= 0.5  # the acceptance floor
        ra = ta.report()
        assert ra.counters.get("serve.cache_misses", 0) > 0
        # attribution is disjoint: A's tracer never saw B's hits
        assert ra.counters.get("serve.cache_hit_bytes", 0) < hit


def test_concurrent_tenant_reports_disjoint(keyed):
    with Serving(prefetch_bytes=8 << 20) as srv:
        warm = srv.tenant("warm")
        with warm.scan(keyed) as s:
            rows = sum(u.batch.num_rows for u in s)
        t1 = srv.tenant("one", weight=2)
        t2 = srv.tenant("two")
        results = {}

        def run(name, tenant):
            with tenant.scan(keyed) as s:
                results[name] = sum(u.batch.num_rows for u in s)

        threads = [threading.Thread(target=run, args=(n, t))
                   for n, t in (("one", t1), ("two", t2))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {"one": rows, "two": rows}
        used = warm.report().counters.get("scan.bytes_used")
        for t in (t1, t2):
            rep = t.report()
            assert rep.counters.get("scan.bytes_used") == used
            assert rep.counters.get("data.rows_emitted") is None


def test_source_key_shared_across_opens(keyed):
    with SharedBufferCache() as c:
        with ParquetFileReader(keyed[0]) as r:
            pass
        from parquet_floor_tpu.io.source import FileSource

        s1 = FileSource(keyed[0])
        s2 = FileSource(keyed[0])
        try:
            assert source_key(s1) == source_key(s2)
            cs1 = CachedSource(s1, c)
            cs2 = CachedSource(s2, c)
            assert bytes(cs1.read_at(0, 4)) == b"PAR1"
            assert bytes(cs2.read_at(0, 4)) == b"PAR1"
            st = c.stats()
            assert st["misses"] == 1 and st["hits"] == 1
        finally:
            s1.close()
            s2.close()


# ---------------------------------------------------------------------------
# Fair-share gate + budget admission
# ---------------------------------------------------------------------------


def test_fair_gate_grants_in_weighted_virtual_time_order():
    """Backlogged 1-slot gate, weight-2 vs weight-1 tenants enqueueing
    alternately: grants must follow WFQ virtual finish tags (heavy tags
    advance by cost/2, light by cost), not arrival order."""
    gate = _FairGate(capacity_bytes=100)
    heavy = _TenantShare(2.0, gate)
    light = _TenantShare(1.0, gate)
    gate.acquire(heavy, 100)  # saturate: everything below queues
    order = []
    lock = threading.Lock()

    def worker(share, name):
        gate.acquire(share, 100)
        with lock:
            order.append(name)
        gate.release(100)

    # arrival h1,l1,h2,l2,h3,l3,h4,l4 — tags: h 50,100,150,200;
    # l 0,100,200,300 (light starts at the current virtual clock, so
    # its FIRST request rightly jumps the heavy backlog; from then on
    # heavy interleaves 2:1 by tag, ties broken by arrival)
    threads = []
    for name, share in (("h1", heavy), ("l1", light), ("h2", heavy),
                        ("l2", light), ("h3", heavy), ("l3", light),
                        ("h4", heavy), ("l4", light)):
        t = threading.Thread(target=worker, args=(share, name))
        threads.append(t)
        t.start()
        time.sleep(0.05)  # deterministic arrival (and seq) order
    gate.release(100)  # open: each grant's release cascades the next
    for t in threads:
        t.join()
    assert order == ["l1", "h1", "h2", "l2", "h3", "l3", "h4", "l4"]


def test_fair_gate_counts_waits_and_gauges():
    gate = _FairGate(capacity_bytes=10)
    share = _TenantShare(1.0, gate)
    with trace.scope() as t:
        gate.acquire(share, 10)
        done = threading.Event()

        def blocked():
            gate.acquire(share, 10)
            gate.release(10)
            done.set()

        # carry the scope onto the worker (contextvars do not cross
        # thread spawns — the CachedSource gate path rides Tracer.run
        # the same way via the scan pools)
        th = threading.Thread(target=t.run, args=(blocked,))
        th.start()
        time.sleep(0.05)
        gate.release(10)
        th.join()
        assert done.is_set()
    assert t.counters()["serve.fair_share_waits"] == 1
    assert t.gauges()["serve.inflight_storage_bytes_max"] == 10


def test_budget_shares_follow_weights():
    with Serving(prefetch_bytes=30 << 20) as srv:
        heavy = srv.tenant("heavy", weight=2)
        light = srv.tenant("light", weight=1)
        assert heavy.prefetch_share() == 20 << 20
        assert light.prefetch_share() == 10 << 20
        sc = light.scan_options(ScanOptions(threads=2))
        assert sc.prefetch_bytes == 10 << 20 and sc.threads == 2
        light.close()  # weights rebalance
        assert heavy.prefetch_share() == 30 << 20
        with pytest.raises(ValueError):
            light.scan([])
        with pytest.raises(ValueError):
            srv.tenant("heavy", weight=5)  # conflicting re-registration
        assert srv.tenant("heavy", weight=2) is heavy


# ---------------------------------------------------------------------------
# The lookup face
# ---------------------------------------------------------------------------


def test_lookup_point_and_range_match_brute_force(keyed):
    with Dataset(keyed, "k") as ds:
        per = GROUP * GROUPS
        key = 2 * (per + 123)  # file 1
        rows = ds.lookup(key)
        assert [r["k"] for r in rows] == [key]
        assert set(rows[0]) == {"k", "s", "d"}
        lo, hi = 2 * (per - 5), 2 * (per + 5)  # spans the file boundary
        got = sorted(r["k"] for r in ds.range(lo, hi))
        assert got == list(range(lo, hi + 1, 2))
        assert ds.lookup(2 * per + 1) == []         # absent odd key
        assert ds.lookup(10 ** 12) == []            # outside every range
        one = ds.lookup(key, columns=["k"], limit=1)
        assert one == [{"k": key}]


def test_lookup_prunes_counts_and_bloom_skips(keyed):
    with trace.scope() as t:
        with Dataset(keyed, "k") as ds:
            ds.lookup(0)          # warm: pins metadata everywhere
            c0 = t.counters()
            assert c0.get("serve.lookup_groups_pruned", 0) >= 1
            # absent odd key inside group 0's [min, max]: stats keep the
            # group, the bloom filter must kill it (no page decoded)
            for off in range(1, 99, 2):
                ds.lookup(off, limit=1)
                if t.counters().get("serve.lookup_bloom_skips", 0):
                    break
            c1 = t.counters()
            assert c1.get("serve.lookup_bloom_skips", 0) >= 1
            assert c1.get("serve.lookup_probes", 0) >= 2
            assert c1.get("serve.lookup_rows", 0) >= 1


def test_hot_lookup_costs_at_most_one_page(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            ds.lookup(0)  # warm every file's metadata pins
            bound = ds.page_size_bound()
            s0 = cache.stats()
            per = GROUP * GROUPS
            rows = ds.lookup(2 * (2 * per - 1), columns=["k"])
            cost = cache.stats()["miss_bytes"] - s0["miss_bytes"]
            assert len(rows) == 1
            assert 0 < cost <= bound


def test_lookup_reuses_cached_footer_across_datasets(keyed):
    with SharedBufferCache() as cache:
        with Dataset(keyed, "k", cache=cache) as ds:
            ds.lookup(0)
            assert cache.stats()["footers"] == len(keyed)
        with Dataset(keyed, "k", cache=cache) as ds2:
            # parsed footers come back from the object tier; the raw
            # footer/index/bloom bytes are already pinned, so the only
            # storage traffic is the probe's data page(s)
            s0 = cache.stats()
            ds2.lookup(0)
            assert cache.stats()["misses"] == s0["misses"]


def test_lookup_rejects_salvage_and_closed_use(keyed):
    with pytest.raises(UnsupportedFeatureError):
        # the constructor itself rejects salvage — nothing is acquired
        Dataset(keyed, "k",  # floorlint: disable=FL-RES001
                options=ReaderOptions(salvage=True))
    ds = Dataset(keyed, "k")
    try:
        assert ds.lookup(0)
    finally:
        ds.close()
    with pytest.raises(ValueError):
        ds.lookup(0)
    ds.close()  # idempotent


def test_lookup_concurrent_probes_with_tenant_attribution(keyed):
    with Serving(prefetch_bytes=8 << 20) as srv:
        with Dataset(keyed, "k", cache=srv.cache) as ds:
            ds.lookup(0)  # open + pin
            ta = srv.tenant("ap")
            tb = srv.tenant("bp")
            per = GROUP * GROUPS
            out = {}

            def probe(name, tenant, base):
                got = []
                for j in range(20):
                    got.extend(
                        r["k"] for r in
                        ds.lookup(2 * (base + j), tenant=tenant)
                    )
                out[name] = got

            t1 = threading.Thread(target=probe, args=("a", ta, 10))
            t2 = threading.Thread(target=probe, args=("b", tb, per + 10))
            t1.start()
            t2.start()
            t1.join()
            t2.join()
            assert out["a"] == [2 * (10 + j) for j in range(20)]
            assert out["b"] == [2 * (per + 10 + j) for j in range(20)]
            assert ta.tracer.counters()["serve.lookup_probes"] == 20
            assert tb.tracer.counters()["serve.lookup_probes"] == 20


def test_per_tenant_histograms_disjoint_across_concurrent_scans(keyed):
    """Two tenants probing CONCURRENTLY through their own scoped
    tracers: each tenant's latency histogram must hold exactly its own
    probes (count attribution), nothing leaked across scopes — the
    distribution mirror of test_concurrent_tenant_reports_disjoint."""
    probes = {"one": 9, "two": 17}
    with Serving(prefetch_bytes=8 << 20) as srv:
        t1 = srv.tenant("one", weight=2)
        t2 = srv.tenant("two")
        with Dataset(keyed, "k", cache=srv.cache) as ds:
            ds.lookup(0)  # warm: opens files outside the timed scans

            def run(tenant, n):
                for i in range(n):
                    ds.lookup(2 * i, columns=["k"], tenant=tenant)

            threads = [
                threading.Thread(target=run, args=(t1, probes["one"])),
                threading.Thread(target=run, args=(t2, probes["two"])),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for tenant, name in ((t1, "one"), (t2, "two")):
            rep = tenant.report()
            h = rep.histogram("serve.lookup_seconds")
            assert h is not None and h.count == probes[name], name
            assert rep.counters.get("serve.lookup_probes") == probes[name]
        # and a scan's histograms stay inside the scanning tenant too
        t3 = srv.tenant("three")
        with t3.scan(keyed) as s:
            for _ in s:
                pass
        assert "serve.lookup_seconds" not in t3.tracer.histograms()
        assert t1.tracer.histograms()["serve.lookup_seconds"].count == \
            probes["one"]
