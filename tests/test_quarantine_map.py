"""Persistent quarantine map (ISSUE 6 tentpole part c): a JSON sidecar
keyed by file fingerprint remembers each file's quarantined units, so a
re-scan of a known-corrupt corpus replays the identical losses without
re-tripping the decode errors.  The replay contract: the map never
changes WHAT is lost — only how cheaply the loss is re-established."""

import json
import pathlib

import numpy as np
import pytest

from parquet_floor_tpu import ReaderOptions, trace
from parquet_floor_tpu.format.file_read import SalvageReport, SalvageSkip
from parquet_floor_tpu.io.source import FileSource
from parquet_floor_tpu.quarantine import QuarantineMap, fingerprint
from parquet_floor_tpu.scan import DatasetScanner

from tests.test_salvage import (  # noqa: F401  (fixture re-export)
    N_GROUPS,
    PAGE_VALUES,
    ROWS_PER_GROUP,
    _break_page_header,
    _decode_all,
    _flip_in_page,
    salvage_file,
)


def _skip_keys(report):
    return [s.key() for s in report.skips]


# ---------------------------------------------------------------------------
# fingerprint + sidecar mechanics
# ---------------------------------------------------------------------------

def test_fingerprint_stable_and_tail_sensitive(salvage_file, tmp_path):
    """Same bytes → same key (twice, through fresh sources); a tail
    byte change (a rewritten footer) re-fingerprints; same content at a
    DIFFERENT path fingerprints the same — the key is the bytes, not
    the name."""
    with FileSource(salvage_file) as s:
        fp1 = fingerprint(s)
    with FileSource(salvage_file) as s:
        assert fingerprint(s) == fp1

    data = bytearray(pathlib.Path(salvage_file).read_bytes())
    copy = tmp_path / "copy.parquet"
    copy.write_bytes(bytes(data))
    with FileSource(str(copy)) as s:
        assert fingerprint(s) == fp1  # content-addressed, not path-keyed

    data[-1] ^= 0x01
    moved = tmp_path / "rewritten.parquet"
    moved.write_bytes(bytes(data))
    with FileSource(str(moved)) as s:
        assert fingerprint(s) != fp1


def test_options_reject_map_without_salvage():
    """Strict mode never quarantines; an ignored map would be a silent
    misconfiguration, so it fails at options construction."""
    with pytest.raises(ValueError, match="salvage"):
        ReaderOptions(quarantine_map=QuarantineMap())


def test_record_dedups_and_save_round_trips(tmp_path):
    rep = SalvageReport(skips=[
        SalvageSkip(column="d", row_group=0, page=None, rows=500,
                    error="boom", kind="chunk"),
        SalvageSkip(column="s", row_group=1, page=2, rows=400,
                    error="crc", kind="page_null"),
    ])
    p = tmp_path / "q.json"
    m = QuarantineMap(p)
    assert m.record("123:deadbeef", rep, path="a.parquet") == 2
    # re-recording the same losses is a no-op: repeated scans keep the
    # sidecar stable
    assert m.record("123:deadbeef", rep) == 0
    m.save()

    m2 = QuarantineMap.open(p)
    assert len(m2) == 1
    assert m2.entries("123:deadbeef") == m.entries("123:deadbeef")
    kb = m2.known_bad("123:deadbeef")
    assert kb[(0, "d")]["chunk"]["rows"] == 500
    assert kb[(1, "s")]["pages"][2]["kind"] == "page_null"
    assert m2.entries("unknown") == [] and m2.known_bad("unknown") == {}


def test_open_missing_empty_corrupt_and_versioned(tmp_path):
    """A missing sidecar starts empty (bound to its path for save); a
    sidecar that does not parse — or has a version this code does not
    speak — raises: a corrupt MAP must never silently discard the
    quarantine history it was supposed to carry."""
    fresh = QuarantineMap.open(tmp_path / "new.json")
    assert len(fresh) == 0
    fresh.save()
    assert json.loads((tmp_path / "new.json").read_text())["version"] == 1

    bad = tmp_path / "garbage.json"
    bad.write_text("{not json")
    with pytest.raises(ValueError, match="does not parse"):
        QuarantineMap.open(bad)

    versioned = tmp_path / "future.json"
    versioned.write_text(json.dumps({"version": 99, "files": {}}))
    with pytest.raises(ValueError, match="version"):
        QuarantineMap.open(versioned)


# ---------------------------------------------------------------------------
# replay: re-scans skip known-bad units without re-tripping decode errors
# ---------------------------------------------------------------------------

def test_chunk_quarantine_replays_from_map(salvage_file, tmp_path):
    """Scan 1 trips the decode error and records the chunk quarantine;
    scan 2 (same sidecar, reloaded) short-circuits: identical surviving
    groups, identical report, but the quarantine arrives via
    ``salvage.map_skip`` with the chunk's bytes never decoded."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "map_chunk")
    sidecar = tmp_path / "corpus.quarantine.json"

    qmap = QuarantineMap.open(sidecar)
    groups1, rep1 = _decode_all(bad, salvage=True, quarantine_map=qmap)
    assert _skip_keys(rep1) == [(0, "d", None, "chunk")]
    qmap.save()

    qmap2 = QuarantineMap.open(sidecar)
    trace.enable()
    try:
        trace.reset()
        groups2, rep2 = _decode_all(bad, salvage=True, quarantine_map=qmap2)
        kinds = [d["decision"] for d in trace.decisions()]
        assert "salvage.map_skip" in kinds
        # the decode error is NOT re-tripped: no fresh quarantine
        # decision, only the replay
        assert "salvage.quarantine_chunk" not in kinds
        assert trace.counters().get("salvage.map_skips") == 1
    finally:
        trace.disable()
        trace.reset()

    # the map never changes WHAT is lost: reports and surviving bytes
    # are identical either way
    assert _skip_keys(rep2) == _skip_keys(rep1)
    assert rep2.summary() == rep1.summary()
    assert [g.num_rows for g in groups2] == [g.num_rows for g in groups1]
    for g1, g2 in zip(groups1, groups2):
        assert [c.descriptor.path for c in g1.columns] == \
            [c.descriptor.path for c in g2.columns]
        for c1, c2 in zip(g1.columns, g2.columns):
            assert np.array_equal(
                np.asarray(c1.values), np.asarray(c2.values)
            )


def test_row_mask_replays_byte_identical(salvage_file, tmp_path):
    """The page-tier replay: a row-masked REQUIRED page substitutes its
    recorded outcome on re-scan — the replayed skip records (error
    string included) and the surviving rows are byte-identical to the
    fresh scan's."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "map_rm")
    sidecar = tmp_path / "rm.quarantine.json"

    qmap = QuarantineMap.open(sidecar)
    groups1, rep1 = _decode_all(
        bad, verify_crc=True, salvage=True, quarantine_map=qmap
    )
    assert [s.kind for s in rep1.skips] == ["row_mask"]
    qmap.save()

    groups2, rep2 = _decode_all(
        bad, verify_crc=True, salvage=True,
        quarantine_map=QuarantineMap.open(sidecar),
    )
    assert [s.as_dict() for s in rep2.skips] == \
        [s.as_dict() for s in rep1.skips]
    assert [g.num_rows for g in groups1] == \
        [ROWS_PER_GROUP - PAGE_VALUES, ROWS_PER_GROUP]
    for g1, g2 in zip(groups1, groups2):
        assert g1.num_rows == g2.num_rows
        for c1, c2 in zip(g1.columns, g2.columns):
            assert np.array_equal(
                np.asarray(c1.values), np.asarray(c2.values)
            )


def _write_clean_companion(tmp_path, seed=17, rows=1800):
    """A second clean file with DIFFERENT bytes (size included): the
    tail fingerprint must not collide with the salvage fixture's."""
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types

    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(seed)
    path = tmp_path / f"companion{seed}.parquet"
    with ParquetFileWriter(path, schema,
                           WriterOptions(data_page_values=600)) as w:
        w.write_columns({
            "a": rng.integers(0, 10_000, rows).astype(np.int64),
            "s": [f"c{i % 57}" for i in range(rows)],
            "d": rng.standard_normal(rows),
        })
    return str(path)


def test_scan_face_records_and_replays(salvage_file, tmp_path):
    """The concurrent host scan face shares one map across the dataset:
    scan 1 records the damaged file's losses under its fingerprint
    (clean files add no units); scan 2 replays them — identical fold,
    identical delivery."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "map_scan")
    clean = _write_clean_companion(tmp_path)
    paths = [clean, bad]
    sidecar = tmp_path / "scan.quarantine.json"

    qmap = QuarantineMap.open(sidecar)
    with DatasetScanner(
        paths, options=ReaderOptions(salvage=True, quarantine_map=qmap)
    ) as sc:
        units1 = list(sc)
        fold1 = sc.salvage_report
    qmap.save()
    with FileSource(bad) as s:
        bad_fp = fingerprint(s)
    assert [u["kind"] for u in qmap.entries(bad_fp)] == ["chunk"]

    with DatasetScanner(
        paths,
        options=ReaderOptions(
            salvage=True, quarantine_map=QuarantineMap.open(sidecar)
        ),
    ) as sc:
        units2 = list(sc)
        fold2 = sc.salvage_report

    assert _skip_keys(fold2) == _skip_keys(fold1) == [(0, "d", None, "chunk")]
    assert [(u.file_index, u.group_index, u.batch.num_rows) for u in units1] \
        == [(u.file_index, u.group_index, u.batch.num_rows) for u in units2]


def test_rewritten_file_misses_the_map(salvage_file, tmp_path):
    """A file repaired the normal way — rewritten through a writer, so
    its footer bytes move — re-fingerprints: the old quarantine entries
    do not apply and the clean decode sees no losses."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "map_rewrite")
    sidecar = tmp_path / "rewrite.quarantine.json"
    qmap = QuarantineMap.open(sidecar)
    _decode_all(bad, salvage=True, quarantine_map=qmap)
    qmap.save()
    assert len(qmap) == 1

    # the compactor repair story: a fresh file replaces the corrupt one
    repaired = _write_clean_companion(tmp_path, seed=5)
    pathlib.Path(bad).write_bytes(pathlib.Path(repaired).read_bytes())
    groups, rep = _decode_all(
        bad, salvage=True, quarantine_map=QuarantineMap.open(sidecar)
    )
    assert rep.skips == []
    assert sum(g.num_rows for g in groups) == 1800


def test_in_place_repair_caveat_is_reported_not_silent(salvage_file,
                                                       tmp_path):
    """The fingerprint's documented blind spot (quarantine.py): an
    in-place restore that preserves size and tail keeps the old
    fingerprint, so the stale quarantine REPLAYS — but it lands in the
    report and the ``salvage.map_skip`` decision stream, never as
    silent loss.  If this test starts failing because the fingerprint
    got byte-exact, delete it (and the docstring caveat) with joy."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "map_inplace")
    sidecar = tmp_path / "inplace.quarantine.json"
    qmap = QuarantineMap.open(sidecar)
    _decode_all(bad, salvage=True, quarantine_map=qmap)
    qmap.save()

    # restore the pristine mid-file bytes: size and tail unchanged
    pathlib.Path(bad).write_bytes(pathlib.Path(salvage_file).read_bytes())
    groups, rep = _decode_all(
        bad, salvage=True, quarantine_map=QuarantineMap.open(sidecar)
    )
    assert _skip_keys(rep) == [(0, "d", None, "chunk")]  # replayed, visible
    assert all(c.descriptor.path != ("d",)
               for c in groups[0].columns)


# ---------------------------------------------------------------------------
# ISSUE 7 satellites: page-tier replay without I/O + content fingerprint
# ---------------------------------------------------------------------------

class _RangeRecordingSource:
    """FileSource wrapper recording every byte range actually read —
    how the no-I/O replay test proves the known-bad page's bytes were
    never fetched."""

    def __init__(self, path):
        self._inner = FileSource(path)
        self.ranges = []

    @property
    def name(self):
        return self._inner.name

    @property
    def size(self):
        return self._inner.size

    def read_at(self, offset, length):
        self.ranges.append((offset, length))
        return self._inner.read_at(offset, length)

    def read_many(self, ranges):
        ranges = list(ranges)
        self.ranges.extend(ranges)
        return [self._inner.read_at(o, n) for o, n in ranges]

    def close(self):
        self._inner.close()


def test_page_tier_replay_skips_the_bytes(salvage_file, tmp_path):
    """Page-tier entries skip reading the damaged page's BYTES, like the
    chunk tier always did: the recorded byte span is excluded from the
    chunk read (vectored complement), the replayed records — byte span
    included — are identical to the fresh scan's, and the skip is
    accounted (``salvage.map_skips`` counter + decision)."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "map_noio")
    sidecar = tmp_path / "noio.quarantine.json"

    qmap = QuarantineMap.open(sidecar)
    groups1, rep1 = _decode_all(
        bad, verify_crc=True, salvage=True, quarantine_map=qmap
    )
    assert [s.kind for s in rep1.skips] == ["row_mask"]
    bspan = rep1.skips[0].byte_span
    assert bspan and bspan[1] > bspan[0]
    qmap.save()
    # the span persists in the sidecar (the replay's no-I/O contract)
    with FileSource(bad) as s:
        fp = fingerprint(s)
    entry = QuarantineMap.open(sidecar).entries(fp)[0]
    assert tuple(entry["byte_span"]) == tuple(bspan)

    src = _RangeRecordingSource(bad)
    trace.enable()
    try:
        trace.reset()
        opts = ReaderOptions(verify_crc=True, salvage=True,
                             quarantine_map=QuarantineMap.open(sidecar))
        from parquet_floor_tpu import ParquetFileReader

        with ParquetFileReader(src, options=opts) as r:
            groups2 = [
                r.read_row_group(i) for i in range(len(r.row_groups))
            ]
            rep2 = r.salvage_report
        a, b = bspan
        overlap = [
            (o, n) for o, n in src.ranges if o < b and a < o + n
        ]
        assert not overlap, \
            f"known-bad page bytes were read: {overlap} vs span {bspan}"
        assert trace.counters().get("salvage.map_skips") == 1
        kinds = [d["decision"] for d in trace.decisions()]
        assert "salvage.map_skip" in kinds
    finally:
        trace.disable()
        trace.reset()

    assert [s.as_dict() for s in rep2.skips] == \
        [s.as_dict() for s in rep1.skips]
    assert [g.num_rows for g in groups2] == [g.num_rows for g in groups1]
    for g1, g2 in zip(groups1, groups2):
        for c1, c2 in zip(g1.columns, g2.columns):
            assert np.array_equal(
                np.asarray(c1.values), np.asarray(c2.values)
            )


def test_page_null_tier_also_replays_without_io(salvage_file, tmp_path):
    """The OPTIONAL-column tier (page_null) gets the same no-I/O
    replay."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 1, "s", 2, "map_noio_s")
    sidecar = tmp_path / "noio_s.quarantine.json"
    qmap = QuarantineMap.open(sidecar)
    groups1, rep1 = _decode_all(
        bad, verify_crc=True, salvage=True, quarantine_map=qmap
    )
    assert [s.kind for s in rep1.skips] == ["page_null"]
    bspan = rep1.skips[0].byte_span
    assert bspan
    qmap.save()

    src = _RangeRecordingSource(bad)
    from parquet_floor_tpu import ParquetFileReader

    opts = ReaderOptions(verify_crc=True, salvage=True,
                         quarantine_map=QuarantineMap.open(sidecar))
    with ParquetFileReader(src, options=opts) as r:
        groups2 = [r.read_row_group(i) for i in range(len(r.row_groups))]
        rep2 = r.salvage_report
    a, b = bspan
    assert not [(o, n) for o, n in src.ranges if o < b and a < o + n]
    assert [s.as_dict() for s in rep2.skips] == \
        [s.as_dict() for s in rep1.skips]
    for g1, g2 in zip(groups1, groups2):
        for c1, c2 in zip(g1.columns, g2.columns):
            assert np.array_equal(
                np.asarray(c1.values), np.asarray(c2.values)
            )
            if c1.def_levels is not None:
                assert np.array_equal(
                    np.asarray(c1.def_levels), np.asarray(c2.def_levels)
                )


def test_content_fingerprint_round_trip(salvage_file, tmp_path):
    """QuarantineMap(fingerprint="content"): records replay across
    save/open (round-trip), and the mode is persisted — reopening under
    a conflicting mode raises instead of silently mis-keying."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "map_content")
    sidecar = tmp_path / "content.quarantine.json"

    qmap = QuarantineMap.open(sidecar, fingerprint="content")
    assert qmap.fingerprint == "content"
    groups1, rep1 = _decode_all(bad, salvage=True, quarantine_map=qmap)
    assert _skip_keys(rep1) == [(0, "d", None, "chunk")]
    qmap.save()
    assert json.loads(sidecar.read_text())["fingerprint"] == "content"

    reloaded = QuarantineMap.open(sidecar)
    assert reloaded.fingerprint == "content"
    with FileSource(bad) as s:
        fp = fingerprint(s, "content")
        assert fp.split(":")[1] == "c"
        assert reloaded.entries(fp)
        # tail and content keys never collide
        assert fingerprint(s) != fp

    trace.enable()
    try:
        trace.reset()
        groups2, rep2 = _decode_all(
            bad, salvage=True, quarantine_map=reloaded
        )
        assert trace.counters().get("salvage.map_skips") == 1
    finally:
        trace.disable()
        trace.reset()
    assert _skip_keys(rep2) == _skip_keys(rep1)
    assert [g.num_rows for g in groups2] == [g.num_rows for g in groups1]

    with pytest.raises(ValueError, match="mis-key"):
        QuarantineMap.open(sidecar, fingerprint="tail")
    with pytest.raises(ValueError, match="fingerprint mode"):
        QuarantineMap(fingerprint="sha1000")


def test_content_fingerprint_closes_in_place_repair_blind_spot(
        salvage_file, tmp_path):
    """The stale-entry contract: an in-place mid-file repair preserves
    size and tail — the tail fingerprint replays stale quarantines
    (documented blind spot), but the CONTENT fingerprint re-keys and
    the clean decode re-establishes the truth with zero skips."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "map_inplace_c")
    sidecar = tmp_path / "inplace_c.quarantine.json"
    qmap = QuarantineMap.open(sidecar, fingerprint="content")
    _decode_all(bad, salvage=True, quarantine_map=qmap)
    qmap.save()
    assert len(qmap) == 1

    # in-place restore: size and tail unchanged, mid-file bytes healed
    pathlib.Path(bad).write_bytes(pathlib.Path(salvage_file).read_bytes())
    groups, rep = _decode_all(
        bad, salvage=True, quarantine_map=QuarantineMap.open(sidecar)
    )
    assert rep.skips == []  # stale entries MISSED: blind spot closed
    assert sum(g.num_rows for g in groups) == N_GROUPS * ROWS_PER_GROUP
    assert any(c.descriptor.path == ("d",) for c in groups[0].columns)
