"""Acceptance tests: the reference's round-trip test translated
(``ParquetReadWriteTest.java:28-83``) plus the documented facade semantics
(SURVEY.md §2.1 behavioral facts)."""

import pytest

from parquet_floor_tpu import (
    ParquetReader,
    ParquetWriter,
    types,
)
from parquet_floor_tpu.api.hydrate import (
    FnDehydrator,
    FnHydrator,
    HydratorSupplier,
    dict_hydrator,
)


def _schema():
    # parity: required INT64 id + required BINARY-as-string email
    # (ParquetReadWriteTest.java:32-35)
    return types.message(
        "import",
        types.required(types.INT64).named("id"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("email"),
    )


def _write_two_rows(path):
    dehydrator = FnDehydrator(
        lambda record, vw: (vw.write("id", record[0]), vw.write("email", record[1]))
    )
    ParquetWriter.write_file(
        _schema(), path, dehydrator, [(1, "hello1@example.com"), (2, "hello2@example.com")]
    )


def test_writes_and_reads_parquet(tmp_path):
    """Direct translation of ``writes_and_reads_parquet``."""
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)

    records = list(ParquetReader.stream_content(path, HydratorSupplier.constantly(dict_hydrator())))
    assert records == [
        {"id": 1, "email": "hello1@example.com"},
        {"id": 2, "email": "hello2@example.com"},
    ]


def test_column_projection(tmp_path):
    """Projection keeps only the named top-level column (test part 4)."""
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)
    records = list(
        ParquetReader.stream_content(
            path, HydratorSupplier.constantly(dict_hydrator()), columns={"id"}
        )
    )
    assert records == [{"id": 1}, {"id": 2}]


def test_empty_projection_means_all(tmp_path):
    # empty/None selection = all columns (ParquetReader.java:76)
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)
    for sel in (None, []):
        records = list(
            ParquetReader.stream_content(
                path, HydratorSupplier.constantly(dict_hydrator()), columns=sel
            )
        )
        assert len(records) == 2 and "email" in records[0]


def test_hydrator_receives_columns_in_order(tmp_path):
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)
    seen_columns = []
    order = []

    def supplier(columns):
        seen_columns.extend(columns)
        return FnHydrator(
            start=list,
            add=lambda t, h, v: (order.append(h), t.append(v), t)[2],
            finish=tuple,
        )

    records = list(ParquetReader.stream_content(path, supplier))
    assert [c.path[0] for c in seen_columns] == ["id", "email"]
    assert order[:2] == ["id", "email"]  # column order (HydratorSupplier.java:10-15)
    assert records[0] == (1, "hello1@example.com")


def test_stream_content_to_strings(tmp_path):
    # debug reader: "name=value" strings (ParquetReader.java:86-107)
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)
    rows = list(ParquetReader.stream_content_to_strings(path))
    assert rows == [
        ["id=1", "email=hello1@example.com"],
        ["id=2", "email=hello2@example.com"],
    ]


def test_read_metadata(tmp_path):
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)
    meta = ParquetReader.read_metadata(path)
    assert meta.num_rows == 2
    assert meta.schema.fields[0].name == "id"
    # open-reader metadata access (ParquetReader.java:229-231)
    r = ParquetReader.spliterator(path, HydratorSupplier.constantly(dict_hydrator()))
    assert r.metadata.num_rows == 2
    assert r.estimate_size() == 2
    r.close()


def test_null_values_hydrate_as_none(tmp_path):
    schema = types.message(
        "m",
        types.required(types.INT64).named("id"),
        types.optional(types.INT64).named("opt"),
    )
    path = tmp_path / "n.parquet"
    dehydrator = FnDehydrator(
        lambda rec, vw: (
            vw.write("id", rec[0]),
            vw.write("opt", rec[1]) if rec[1] is not None else None,
        )
    )
    ParquetWriter.write_file(schema, path, dehydrator, [(1, 10), (2, None), (3, 30)])
    records = list(
        ParquetReader.stream_content(path, HydratorSupplier.constantly(dict_hydrator()))
    )
    assert records == [
        {"id": 1, "opt": 10},
        {"id": 2, "opt": None},
        {"id": 3, "opt": 30},
    ]


def test_write_type_surface_rejections(tmp_path):
    """Write facade rejects unsupported value types (ParquetWriter.java:147-161)."""
    schema = types.message("m", types.required(types.INT64).named("x"))
    path = tmp_path / "x.parquet"
    bad = FnDehydrator(lambda rec, vw: vw.write("x", "not an int"))
    with pytest.raises(ValueError, match="Cannot write value"):
        ParquetWriter.write_file(schema, path, bad, [object()])

    # BINARY without string annotation is rejected
    schema2 = types.message("m", types.required(types.BYTE_ARRAY).named("raw"))
    bad2 = FnDehydrator(lambda rec, vw: vw.write("raw", b"bytes"))
    with pytest.raises(ValueError, match="Cannot write value"):
        ParquetWriter.write_file(schema2, tmp_path / "y.parquet", bad2, [object()])


def test_spliterator_surface(tmp_path):
    """try_split declines (ParquetReader.java:214-217); characteristics
    report ORDERED|NONNULL|DISTINCT (:224-227); estimate_size is the
    footer's exact row count (:219-222)."""
    schema = types.message("m", types.required(types.INT64).named("x"))
    path = tmp_path / "sp.parquet"
    ParquetWriter.write_file(
        schema, path,
        FnDehydrator(lambda rec, vw: vw.write("x", rec)), list(range(7)),
    )
    with ParquetReader.spliterator(path, lambda c: dict_hydrator()) as r:
        assert r.try_split() is None
        assert r.characteristics() == {"ORDERED", "NONNULL", "DISTINCT"}
        assert r.estimate_size() == 7


def test_row_bytes_counts_utf8_bytes():
    """The row_group_bytes flush estimate counts str values in UTF-8
    bytes, not characters (non-ASCII text must not flush late)."""
    from parquet_floor_tpu.api.writer import ParquetWriter as PW

    ascii_cost = PW._row_bytes(["abcd"])
    multibyte_cost = PW._row_bytes(["äöüß"])  # 4 chars, 8 UTF-8 bytes
    assert ascii_cost == 4 + 4
    assert multibyte_cost == 8 + 4


def test_unknown_field_name_raises(tmp_path):
    schema = types.message("m", types.required(types.INT64).named("x"))
    bad = FnDehydrator(lambda rec, vw: vw.write("nope", 1))
    with pytest.raises(KeyError):
        ParquetWriter.write_file(schema, tmp_path / "z.parquet", bad, [object()])


def test_repeated_field_raises_on_read(tmp_path):
    """Flat-only guard parity (ParquetReader.java:200-202)."""
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    table = pa.table({"xs": pa.array([[1, 2], [3]], type=pa.list_(pa.int64()))})
    path = tmp_path / "rep.parquet"
    pq.write_table(table, path)
    with pytest.raises(RuntimeError, match="Failed to read parquet"):
        list(
            ParquetReader.stream_content(
                path, HydratorSupplier.constantly(dict_hydrator())
            )
        )


def test_read_errors_are_wrapped(tmp_path):
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)

    class Exploding(FnHydrator):
        def __init__(self):
            super().__init__(dict, self._boom, dict)

        @staticmethod
        def _boom(t, h, v):
            raise KeyError("user plugin failure")

    it = ParquetReader.stream_content(path, HydratorSupplier.constantly(Exploding()))
    with pytest.raises(RuntimeError, match="Failed to read parquet"):
        next(iter(it))


def test_stringified_types(tmp_path):
    """BINARY/FLBA read back stringified (ParquetReader.java:147-163)."""
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    table = pa.table(
        {
            "raw": pa.array([b"\x01\x02", b"\xff"], type=pa.binary()),
            "fx": pa.array([b"ABCD", b"WXYZ"], type=pa.binary(4)),
        }
    )
    path = tmp_path / "bin.parquet"
    pq.write_table(table, path)
    records = list(
        ParquetReader.stream_content(path, HydratorSupplier.constantly(dict_hydrator()))
    )
    assert records[0]["raw"] == "0x0102"
    assert records[1]["raw"] == "0xFF"
    assert records[0]["fx"] == "0x41424344"


def test_reader_as_context_manager_and_iterator(tmp_path):
    path = tmp_path / "foo.parquet"
    _write_two_rows(path)
    with ParquetReader.spliterator(
        path, HydratorSupplier.constantly(dict_hydrator())
    ) as r:
        ids = [rec["id"] for rec in r]
    assert ids == [1, 2]


def test_checkpoint_resume(tmp_path):
    """Scan state round-trips: stop anywhere, resume in a fresh reader,
    and the concatenation equals one uninterrupted scan."""
    from parquet_floor_tpu import WriterOptions, ParquetFileWriter

    schema = types.message(
        "t", types.required(types.INT64).named("v"),
    )
    path = str(tmp_path / "ck.parquet")
    with ParquetFileWriter(path, schema, WriterOptions(row_group_rows=50)) as w:
        for lo in range(0, 220, 50):
            w.write_columns({"v": list(range(lo, min(lo + 50, 220)))})

    def fresh():
        return ParquetReader(
            path, HydratorSupplier.constantly(dict_hydrator())
        )

    full = [r["v"] for r in fresh()]
    assert full == list(range(220))

    for stop in (0, 1, 49, 50, 51, 120, 219, 220):
        r1 = fresh()
        head = [next(r1)["v"] for _ in range(stop)]
        st = r1.state()
        r1.close()
        r2 = fresh().restore(st)
        tail = [row["v"] for row in r2]
        r2.close()
        assert head + tail == full, f"stop={stop}"

    # bad states raise
    import pytest as _pytest
    with _pytest.raises(ValueError):
        fresh().restore({"row_group": 99, "row_in_group": 0})
    with _pytest.raises(ValueError):
        fresh().restore({"row_group": 0, "row_in_group": 51})


def test_logical_type_stringifiers():
    """Logical-type-aware rendering, parity with parquet-mr's
    PrimitiveStringifier family (used by the reference's debug reader at
    ParquetReader.java:147-163)."""
    from parquet_floor_tpu import types as t
    from parquet_floor_tpu.format.parquet_thrift import Type as PT
    from parquet_floor_tpu.format.schema import PrimitiveType

    def prim(pt, lt, length=None):
        return PrimitiveType("c", pt, logical_type=lt, type_length=length)

    assert prim(PT.INT32, t.decimal(9, 2)).stringify(12345) == "123.45"
    assert prim(PT.INT64, t.decimal(18, 0)).stringify(-7) == "-7"
    assert prim(
        PT.FIXED_LEN_BYTE_ARRAY, t.decimal(9, 3), 4
    ).stringify((-12345).to_bytes(4, "big", signed=True)) == "-12.345"
    assert prim(PT.INT32, t.date()).stringify(0) == "1970-01-01"
    assert prim(PT.INT32, t.date()).stringify(19723) == "2024-01-01"
    assert prim(PT.INT32, t.date()).stringify(-1) == "1969-12-31"
    assert prim(
        PT.INT32, t.time("MILLIS")
    ).stringify(13 * 3600_000 + 59 * 60_000 + 7_123) == "13:59:07.123"
    assert prim(
        PT.INT64, t.time("MICROS")
    ).stringify(1_000_001) == "00:00:01.000001"
    assert prim(
        PT.INT64, t.timestamp("MILLIS")
    ).stringify(1_700_000_000_123) == "2023-11-14T22:13:20.123"
    assert prim(
        PT.INT64, t.timestamp("MICROS")
    ).stringify(1_700_000_000_123_456) == "2023-11-14T22:13:20.123456"
    u = bytes(range(16))
    assert prim(PT.FIXED_LEN_BYTE_ARRAY, t.uuid(), 16).stringify(u) == (
        "00010203-0405-0607-0809-0a0b0c0d0e0f"
    )
    iv = (14).to_bytes(4, "little") + (3).to_bytes(4, "little") + (
        500
    ).to_bytes(4, "little")
    assert prim(PT.FIXED_LEN_BYTE_ARRAY, None, 12).stringify(iv).startswith("0x")
    from parquet_floor_tpu.format.schema import LogicalAnnotation

    assert prim(
        PT.FIXED_LEN_BYTE_ARRAY, LogicalAnnotation("INTERVAL"), 12
    ).stringify(iv) == "interval(14 months, 3 days, 500 millis)"
    # null + defaults unchanged
    assert prim(PT.INT32, t.date()).stringify(None) == "null"
    assert prim(PT.BOOLEAN, None).stringify(True) == "true"


def test_logical_stringifiers_through_strings_reader(tmp_path):
    """Reference parity: the row verbs stringify ONLY BYTE_ARRAY / FLBA /
    INT96 (ParquetReader.java:147-163) — so annotated binary types render
    logical-type-aware (FLBA DECIMAL scaled, UUID canonical) while
    numeric logical types pass through raw, exactly like the reference's
    readValue type switch."""
    from parquet_floor_tpu import (
        ParquetFileWriter, ParquetReader, types as t,
    )

    schema = t.message(
        "t",
        t.required(t.INT32).as_(t.date()).named("day"),
        t.required(t.FIXED_LEN_BYTE_ARRAY).length(4).as_(
            t.decimal(9, 2)
        ).named("amount"),
        t.required(t.FIXED_LEN_BYTE_ARRAY).length(16).as_(
            t.uuid()
        ).named("id"),
    )
    path = str(tmp_path / "lt.parquet")
    import numpy as np

    amounts = np.frombuffer(
        (123456).to_bytes(4, "big", signed=True)
        + (-50).to_bytes(4, "big", signed=True),
        np.uint8,
    ).reshape(2, 4)
    uuids = np.frombuffer(bytes(range(16)) + bytes(range(16, 32)), np.uint8
                          ).reshape(2, 16)
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"day": [19723, 0], "amount": amounts, "id": uuids})
    rows = list(ParquetReader.stream_content_to_strings(path))
    # numeric DATE passes raw (reference readValue returns getInteger());
    # annotated FLBA goes through the logical stringifier
    assert rows[0] == [
        "day=19723",
        "amount=1234.56",
        "id=00010203-0405-0607-0809-0a0b0c0d0e0f",
    ]
    assert rows[1] == [
        "day=0",
        "amount=-0.50",
        "id=10111213-1415-1617-1819-1a1b1c1d1e1f",
    ]
    # the TPU-backed rows agree cell for cell
    from tests.test_api_tpu import _RowHydrator

    tpu = list(ParquetReader.stream_content(
        path, lambda c: _RowHydrator(), engine="tpu"
    ))
    assert [f"{h}={v}" for h, v in tpu[0]] == rows[0]


def test_interval_roundtrip_and_stringify(tmp_path):
    """INTERVAL rides the legacy ConvertedType alone (the thrift
    LogicalType union never gained it): a written FLBA(12) INTERVAL
    column reads back with the annotation intact and stringifies to the
    decomposed (months, days, millis) form."""
    import numpy as np

    from parquet_floor_tpu import (
        ParquetFileReader, ParquetFileWriter, ParquetReader, types as t,
    )
    from parquet_floor_tpu.format.schema import (
        LogicalAnnotation, MessageType, PrimitiveType,
    )
    from parquet_floor_tpu.format.parquet_thrift import (
        ConvertedType, Type as PT,
    )

    schema = MessageType("t", [
        PrimitiveType("iv", PT.FIXED_LEN_BYTE_ARRAY, type_length=12,
                      logical_type=LogicalAnnotation("INTERVAL")),
    ])
    iv = (
        (14).to_bytes(4, "little") + (3).to_bytes(4, "little")
        + (500).to_bytes(4, "little")
    )
    rows = np.frombuffer(iv + iv, np.uint8).reshape(2, 12)
    path = str(tmp_path / "iv.parquet")
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"iv": rows})
    with ParquetFileReader(path) as r:
        prim = r.schema.columns[0].primitive
        assert prim.logical_type is not None
        assert prim.logical_type.kind == "INTERVAL"
    # footer carries converted_type INTERVAL and no logicalType
    with ParquetFileReader(path) as r:
        els = r.metadata.file_meta.schema
        el = [e for e in els if e.name == "iv"][0]
        assert el.converted_type == ConvertedType.INTERVAL
        assert el.logicalType is None
    strs = list(ParquetReader.stream_content_to_strings(path))
    assert strs[0] == ["iv=interval(14 months, 3 days, 500 millis)"]
