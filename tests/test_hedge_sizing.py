"""Byte-size-informed hedging (io/remote.py): the adaptive hedge delay
is the latency p95 WIDENED by the extra transfer time the request's
size implies over the sampled mean — a 16 MiB fetch must not hedge on
a p95 learned from footer-sized reads."""

from parquet_floor_tpu.io.remote import LatencyStats, RemoteSource


class _NullTransport:
    name = "null://"
    size = 1 << 30

    def get_range(self, offset, length):  # pragma: no cover - unused
        return b"\x00" * length


def make_store(**kw):
    kw.setdefault("fetch_threads", 1)
    return RemoteSource(_NullTransport(), **kw)


def feed(store, n=32, seconds=0.010, nbytes=64 << 10):
    for _ in range(n):
        store.latency.observe(seconds, nbytes)


def test_latency_stats_sizes_ring():
    st = LatencyStats(cap=4)
    for i in range(8):  # wraps: only the last 4 sized samples remain
        st.observe(0.01, (i + 1) * 1000)
    assert st.mean_size() == (5 + 6 + 7 + 8) * 1000 / 4
    bw = st.bandwidth_Bps()
    assert bw == (5 + 6 + 7 + 8) * 1000 / 0.04


def test_unsized_samples_are_excluded():
    st = LatencyStats()
    st.observe(0.01)          # unsized — a ping, not a transfer
    assert st.mean_size() is None and st.bandwidth_Bps() is None
    st.observe(0.01, 1000)
    assert st.mean_size() == 1000


def test_cold_store_does_not_hedge():
    store = make_store(hedge_min_samples=8)
    try:
        assert store.hedge_delay() is None
        assert store.hedge_delay(16 << 20) is None
    finally:
        store.close()


def test_big_read_widens_delay_beyond_p95():
    store = make_store(hedge_min_delay_s=0.001, hedge_max_delay_s=60.0)
    try:
        # 64 KiB reads at 10 ms → p95 0.01 s, bandwidth 6.55 MB/s
        feed(store, n=32, seconds=0.010, nbytes=64 << 10)
        base = store.hedge_delay()
        assert base == 0.010
        small = store.hedge_delay(64 << 10)
        big = store.hedge_delay(16 << 20)
        # at/below the mean size: no widening
        assert small == base
        # 16 MiB at ~6.55 MB/s implies seconds of legitimate transfer
        assert big > base + 1.0
        # and the widening is exactly (length - mean)/bandwidth
        bw = store.latency.bandwidth_Bps()
        mean = store.latency.mean_size()
        assert big == base + ((16 << 20) - mean) / bw
    finally:
        store.close()


def test_widened_delay_clamps_to_hedge_max():
    store = make_store(hedge_min_delay_s=0.001, hedge_max_delay_s=0.5)
    try:
        feed(store, n=32, seconds=0.010, nbytes=64 << 10)
        assert store.hedge_delay(1 << 30) == 0.5
    finally:
        store.close()


def test_fixed_delay_ignores_size():
    store = make_store(hedge_delay_s=0.123)
    try:
        feed(store, n=32, seconds=0.010, nbytes=64 << 10)
        assert store.hedge_delay() == 0.123
        assert store.hedge_delay(16 << 20) == 0.123
    finally:
        store.close()


def test_no_size_data_falls_back_to_p95():
    store = make_store(hedge_min_delay_s=0.001)
    try:
        for _ in range(32):
            store.latency.observe(0.010)  # all unsized
        assert store.hedge_delay(16 << 20) == store.hedge_delay()
    finally:
        store.close()


def test_simulator_big_read_does_not_spuriously_hedge():
    # fixed-seed end to end: warm the p95 on small reads against a
    # bandwidth-bound store, then issue one read 64x the mean — its
    # transfer time alone dwarfs the small-read p95, and the widened
    # delay must keep the hedge holstered for a HEALTHY big read
    import numpy as np

    from parquet_floor_tpu.testing import (
        RemoteProfile,
        SimulatedRemoteSource,
    )
    from parquet_floor_tpu.utils import trace

    data = bytes(np.random.default_rng(3).integers(
        0, 256, 1 << 21, dtype=np.uint8))
    profile = RemoteProfile(base_latency_s=0.001,
                            bandwidth_bytes_per_s=50e6)
    tracer = trace.Tracer(enabled=True)
    with SimulatedRemoteSource(data, profile=profile, seed=11,
                               hedge_min_samples=8,
                               hedge_min_delay_s=0.001) as src:
        with trace.using(tracer):
            for i in range(16):  # 16 KiB reads: ~1.3 ms each
                src.read_at(i << 14, 1 << 14)
            big = src.read_at(0, 1 << 20)  # ~21 ms of honest transfer
        assert bytes(big) == data[:1 << 20]
        assert tracer.counters().get("io.remote.hedges", 0) == 0
        # the widened delay really is wider than the small-read p95
        assert src.hedge_delay(1 << 20) > src.hedge_delay(1 << 14)
