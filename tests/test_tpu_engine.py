"""TPU decode engine vs the host format engine: every device path must match
the NumPy decode bit-for-bit (run on CPU backend; same code runs on TPU)."""

import numpy as np
import pytest

import jax

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader, f64bits_to_f32

rng = np.random.default_rng(21)


def _write(tmp_path, cols_spec, options, n=3000):
    fields = []
    data = {}
    for name, (ptype, values, optional, logical) in cols_spec.items():
        b = types.optional(ptype) if optional else types.required(ptype)
        if logical:
            b = b.as_(logical)
        fields.append(b.named(name))
        data[name] = values
    schema = types.message("t", *fields)
    path = tmp_path / "t.parquet"
    with ParquetFileWriter(path, schema, options) as w:
        w.write_columns(data)
    return path


def _check_against_host(path, columns=None):
    """Decode with both engines and compare dense arrays."""
    tpu = TpuRowGroupReader(path)
    host = ParquetFileReader(path)
    try:
        for gi in range(len(host.row_groups)):
            dev_cols = tpu.read_row_group(gi, columns)
            host_batch = host.read_row_group(gi, set(columns) if columns else None)
            for cb in host_batch.columns:
                name = cb.descriptor.path[0]
                dc = dev_cols[name]
                h_dense, h_mask = cb.dense()
                if h_mask is None:
                    assert dc.mask is None or not np.asarray(dc.mask).any()
                else:
                    np.testing.assert_array_equal(np.asarray(dc.mask), h_mask, err_msg=name)
                if isinstance(h_dense, ByteArrayColumn):
                    lens = np.asarray(dc.lengths)
                    rows = np.asarray(dc.values)
                    got = [rows[i, : lens[i]].tobytes() for i in range(len(lens))]
                    exp = h_dense.to_list()
                    assert got == exp, f"strings mismatch in {name}"
                else:
                    np.testing.assert_array_equal(
                        np.asarray(dc.values), h_dense, err_msg=name
                    )
    finally:
        tpu.close()
        host.close()


def _std_cols(n=3000, dict_friendly=True):
    mod = 50 if dict_friendly else 100000
    return {
        "i64": (types.INT64, (rng.integers(0, mod, n) * 7 - 3).astype(np.int64), False, None),
        "i32": (types.INT32, rng.integers(0, mod, n).astype(np.int32), False, None),
        "f32": (types.FLOAT, rng.integers(0, mod, n).astype(np.float32), False, None),
        "f64": (types.DOUBLE, rng.integers(0, mod, n).astype(np.float64) * 0.5, False, None),
        "s": (types.BYTE_ARRAY, [f"word_{i % (mod // 2)}" for i in range(n)],
              False, types.string()),
        "b": (types.BOOLEAN, rng.integers(0, 2, n).astype(bool), False, None),
        "opt64": (types.INT64, [None if i % 7 == 0 else i % mod for i in range(n)], True, None),
        "opts": (types.BYTE_ARRAY,
                 [None if i % 5 == 0 else f"s{i % 9}" for i in range(n)],
                 True, types.string()),
    }


@pytest.mark.parametrize("codec", [CompressionCodec.UNCOMPRESSED, CompressionCodec.SNAPPY])
@pytest.mark.parametrize("version", [1, 2])
def test_dict_path(tmp_path, codec, version):
    path = _write(tmp_path, _std_cols(), WriterOptions(codec=codec, page_version=version))
    _check_against_host(path)


@pytest.mark.parametrize("version", [1, 2])
def test_plain_path(tmp_path, version):
    path = _write(
        tmp_path,
        _std_cols(dict_friendly=False),
        WriterOptions(enable_dictionary=False, page_version=version,
                      codec=CompressionCodec.SNAPPY),
    )
    _check_against_host(path)


def test_multi_page_chunks(tmp_path):
    path = _write(
        tmp_path, _std_cols(), WriterOptions(data_page_values=257), n=3000
    )
    _check_against_host(path)


def test_delta_path(tmp_path):
    n = 2000
    cols = {
        "d32": (types.INT32, np.cumsum(rng.integers(-3, 90, n)).astype(np.int32), False, None),
        "d64": (types.INT64, np.cumsum(rng.integers(-3, 90, n)).astype(np.int64), False, None),
    }
    path = _write(
        tmp_path, cols,
        WriterOptions(enable_dictionary=False, delta_integers=True),
    )
    _check_against_host(path)


def test_projection(tmp_path):
    path = _write(tmp_path, _std_cols(), WriterOptions())
    tpu = TpuRowGroupReader(path)
    cols = tpu.read_row_group(0, ["i64", "s"])
    assert set(cols) == {"i64", "s"}
    tpu.close()


def test_pyarrow_files_through_tpu_engine(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    n = 2500
    table = pa.table(
        {
            "a": pa.array(rng.integers(0, 40, n), type=pa.int64()),
            "b": pa.array([f"cat_{i % 11}" for i in range(n)]),
            "c": pa.array(rng.standard_normal(n), type=pa.float64()),
            "opt": pa.array([None if i % 3 == 0 else int(i) for i in range(n)], type=pa.int32()),
        }
    )
    path = tmp_path / "pa.parquet"
    pq.write_table(table, path, compression="SNAPPY", row_group_size=900)
    _check_against_host(path)


def test_f64bits_to_f32():
    vals = np.array([1.5, -2.75e10, 3.14159, 0.0, np.inf, -np.inf, 1e38, -1e-30],
                    dtype=np.float64)
    import jax.numpy as jnp

    bits = jnp.asarray(vals.view(np.int64))
    out = np.asarray(f64bits_to_f32(bits))
    np.testing.assert_allclose(out, vals.astype(np.float32), rtol=1e-6)
    nan_out = np.asarray(f64bits_to_f32(jnp.asarray(np.array([np.nan]).view(np.int64))))
    assert np.isnan(nan_out[0])


def test_float64_policies(tmp_path):
    n = 500
    cols = {"f64": (types.DOUBLE, rng.standard_normal(n), False, None)}
    path = _write(tmp_path, cols, WriterOptions(enable_dictionary=False))
    expect = None
    with ParquetFileReader(path) as r:
        expect = np.asarray(r.read_row_group(0).columns[0].values)
    for policy, dtype in [("float64", np.float64), ("float32", np.float32), ("bits", np.int64)]:
        t = TpuRowGroupReader(path, float64_policy=policy)
        got = np.asarray(t.read_row_group(0)["f64"].values)
        assert got.dtype == dtype
        if policy == "float64":
            np.testing.assert_array_equal(got, expect)
        elif policy == "float32":
            np.testing.assert_allclose(got, expect.astype(np.float32), rtol=1e-6)
        else:
            np.testing.assert_array_equal(got.view(np.float64), expect)
        t.close()


def test_int64_delta_overflow_stays_exact(tmp_path):
    """Regression: INT64 delta columns whose running sum leaves int32 range
    must decode exactly (round 1: host fallback; now the wide device
    reconstruction), never silently wrap in int32."""
    n = 300_000
    vals = (np.arange(n, dtype=np.int64) * 10_000)  # max 3e9 > int32
    cols = {"big": (types.INT64, vals, False, None)}
    path = _write(tmp_path, cols, WriterOptions(enable_dictionary=False, delta_integers=True))
    t = TpuRowGroupReader(path)
    got = np.asarray(t.read_row_group(0)["big"].values)
    np.testing.assert_array_equal(got, vals)
    t.close()


def test_all_null_column_device_path(tmp_path):
    """Regression: an entirely-null row group must decode (zeros + full
    mask), not crash the device gather."""
    for enable_dict in (False, True):
        cols = {"x": (types.DOUBLE, [None] * 200, True, None)}
        path = _write(tmp_path, cols, WriterOptions(enable_dictionary=enable_dict))
        t = TpuRowGroupReader(path)
        dc = t.read_row_group(0)["x"]
        assert np.asarray(dc.mask).all()
        assert dc.values.shape[0] == 200
        t.close()


def test_x64_requirement_error():
    import jax

    try:
        jax.config.update("jax_enable_x64", False)
        with pytest.raises(RuntimeError, match="jax_enable_x64"):
            TpuRowGroupReader.__new__(TpuRowGroupReader).__init__("/nonexistent")
    finally:
        jax.config.update("jax_enable_x64", True)


def test_all_null_page_within_dict_column(tmp_path):
    """Regression: a dict column whose *middle page* is entirely null has no
    value section on that page — staging must not probe its (absent)
    bit-width byte, which would read the next page's bytes and could
    force-host the column (or mis-plan it)."""
    for version in (1, 2):
        vals = [float(i % 7) for i in range(100)] + [None] * 100 + [
            float(i % 5) for i in range(100)
        ]
        cols = {"x": (types.DOUBLE, vals, True, None)}
        path = _write(
            tmp_path,
            cols,
            WriterOptions(data_page_values=100, page_version=version),
            n=300,
        )
        _check_against_host(path)
        # and it must have stayed on the device path (not sticky-forced)
        t = TpuRowGroupReader(path)
        t.read_row_group(0)
        assert not t._forced, f"v{version}: column fell back to host"
        t.close()


def test_shared_dict_content_across_columns(tmp_path):
    """Regression: two string columns whose dictionary *content* coincides
    in a later row group (but whose shape buckets differ) must not evict
    each other's device pools mid-flight."""
    a_g0 = [f"word{i:02d}" for i in range(40)] * 3   # 40-entry dictionary
    small = ["x", "y"] * 60                          # 2-entry dictionary
    cols0 = {
        "a": (types.BYTE_ARRAY, a_g0, False, types.string()),
        "b": (types.BYTE_ARRAY, small, False, types.string()),
    }
    fields = [
        types.required(types.BYTE_ARRAY).as_(types.string()).named("a"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("b"),
    ]
    schema = types.message("t", *fields)
    path = tmp_path / "sd.parquet"
    with ParquetFileWriter(path, schema, WriterOptions(row_group_rows=120)) as w:
        w.write_columns({"a": a_g0[:120], "b": small[:120]})
        # group 1: both columns carry the identical 2-entry dictionary
        w.write_columns({"a": small[:120], "b": small[:120]})
    _check_against_host(path)


def test_trace_spans(tmp_path):
    """The tracing subsystem records stage/ship/decode spans per group."""
    from parquet_floor_tpu.utils import trace

    cols = {"x": (types.INT64, list(range(500)), False, None)}
    path = _write(tmp_path, cols, WriterOptions(), n=500)
    trace.reset()
    trace.enable()
    try:
        t = TpuRowGroupReader(path)
        t.read_row_group(0)
        t.close()
        st = trace.stats()
        assert st["stage"]["count"] == 1
        assert st["ship"]["count"] == 1 and st["ship"]["bytes"] > 0
        assert st["decode"]["count"] == 1
        assert "stage" in trace.report()
    finally:
        trace.disable()
        trace.reset()


def test_pallas_integrated_decode(tmp_path, monkeypatch):
    """PFTPU_PALLAS=1 on CPU routes uniform-width streams through the
    Pallas kernel in interpret mode — output must match the host engine."""
    rng_l = np.random.default_rng(31)
    n = 5000
    vals = [None if rng_l.random() < 0.3 else float(i % 50) for i in range(n)]
    ints = rng_l.integers(0, 200, n)
    cols = {
        "x": (types.DOUBLE, vals, True, None),
        "k": (types.INT64, list(ints), False, None),
    }
    path = _write(tmp_path, cols, WriterOptions(), n=n)
    monkeypatch.setenv("PFTPU_PALLAS", "1")
    t = TpuRowGroupReader(path)
    try:
        assert t._pl_enabled and t._pl_interp
        cols_d = t.read_row_group(0)
        # at least one spec must actually use a Pallas plan
        sg = t._stage_row_group(0, None)
        assert any(
            s.pl_lvl or s.pl_idx or s.pl_rep for s in sg.program
        ), "no stream took the Pallas path"
    finally:
        t.close()
    host = ParquetFileReader(path)
    try:
        hb = host.read_row_group(0)
        for cb in hb.columns:
            name = cb.descriptor.path[0]
            dense, mask = cb.dense()
            got = np.asarray(cols_d[name].values)
            if mask is not None:
                np.testing.assert_array_equal(np.asarray(cols_d[name].mask), mask)
                got = np.where(mask, 0, got)
                dense = np.where(mask, 0, dense)
            np.testing.assert_allclose(got, dense)
    finally:
        host.close()


@pytest.mark.parametrize("version", [1, 2])
def test_plain_strings_device_path(tmp_path, version):
    """PLAIN (non-dict) BYTE_ARRAY decodes on device: host walks length
    chains, device gathers padded rows."""
    rng_l = np.random.default_rng(37)
    n = 4000
    words = ["", "a", "hello-world", "x" * 40, "mid"]
    req = [words[int(i)] for i in rng_l.integers(0, len(words), n)]
    opt = [None if rng_l.random() < 0.3 else words[int(i)]
           for i in rng_l.integers(0, len(words), n)]
    cols = {
        "s": (types.BYTE_ARRAY, req, False, types.string()),
        "o": (types.BYTE_ARRAY, opt, True, types.string()),
    }
    path = _write(
        tmp_path, cols,
        WriterOptions(enable_dictionary=False, page_version=version,
                      data_page_values=700),
        n=n,
    )
    _check_against_host(path)
    # confirm the device path was used (no host fallback)
    t = TpuRowGroupReader(path)
    sg = t._stage_row_group(0, None)
    assert all(s.kind == "plain_str" for s in sg.program), [s.kind for s in sg.program]
    t.close()


def test_plain_flba_int96_device_path(tmp_path):
    """FIXED_LEN_BYTE_ARRAY and INT96 PLAIN decode on device as byte rows."""
    rng_l = np.random.default_rng(39)
    n = 1000
    flba = rng_l.integers(0, 256, (n, 16)).astype(np.uint8)
    i96 = rng_l.integers(0, 256, (n, 12)).astype(np.uint8)
    fields = [
        types.required(types.FIXED_LEN_BYTE_ARRAY).length(16).named("f"),
        types.required(types.INT96).named("t96"),
    ]
    schema = types.message("t", *fields)
    path = tmp_path / "fl.parquet"
    with ParquetFileWriter(
        path, schema, WriterOptions(enable_dictionary=False)
    ) as w:
        w.write_columns({"f": flba, "t96": i96})
    t = TpuRowGroupReader(path)
    sg = t._stage_row_group(0, None)
    assert all(s.kind == "plain" and s.vdtype == "u8rows" for s in sg.program)
    cols_d = t.read_row_group(0)
    np.testing.assert_array_equal(np.asarray(cols_d["f"].values), flba)
    np.testing.assert_array_equal(np.asarray(cols_d["t96"].values), i96)
    t.close()


@pytest.mark.parametrize("version", [1, 2])
def test_delta_multipage_optional_device(tmp_path, version):
    """DELTA_BINARY_PACKED across several pages and with nulls decodes on
    device (segmented reconstruction)."""
    rng_l = np.random.default_rng(43)
    n = 5000
    req32 = np.cumsum(rng_l.integers(-5, 9, n)).astype(np.int32)
    req64 = np.cumsum(rng_l.integers(-100, 200, n)).astype(np.int64)
    opt = [None if rng_l.random() < 0.25 else int(v) for v in req32]
    cols = {
        "a": (types.INT32, list(req32), False, None),
        "b": (types.INT64, list(req64), False, None),
        "c": (types.INT32, opt, True, None),
    }
    path = _write(
        tmp_path, cols,
        WriterOptions(enable_dictionary=False, delta_integers=True,
                      page_version=version, data_page_values=700),
        n=n,
    )
    t = TpuRowGroupReader(path)
    sg = t._stage_row_group(0, None)
    assert all(s.kind == "delta" for s in sg.program), [s.kind for s in sg.program]
    t.close()
    _check_against_host(path)


@pytest.mark.parametrize("version", [1, 2])
def test_byte_stream_split_device(tmp_path, version):
    """BYTE_STREAM_SPLIT floats decode on device via the strided gather."""
    rng_l = np.random.default_rng(47)
    n = 4000
    f32 = rng_l.standard_normal(n).astype(np.float32)
    f64 = rng_l.standard_normal(n)
    optf = [None if rng_l.random() < 0.3 else float(v) for v in f32]
    cols = {
        "x": (types.FLOAT, f32, False, None),
        "y": (types.DOUBLE, f64, False, None),
        "z": (types.FLOAT, optf, True, None),
    }
    path = _write(
        tmp_path, cols,
        WriterOptions(enable_dictionary=False, byte_stream_split_floats=True,
                      page_version=version, data_page_values=900),
        n=n,
    )
    t = TpuRowGroupReader(path)
    sg = t._stage_row_group(0, None)
    assert all(s.kind == "bss" for s in sg.program), [s.kind for s in sg.program]
    t.close()
    _check_against_host(path)


def test_delta_all_null_page(tmp_path):
    """An all-null page inside an optional DELTA column has no value
    section; staging must skip it, not crash parsing an empty stream."""
    vals = [int(i) for i in range(100)] + [None] * 100 + [int(i) for i in range(100)]
    cols = {"d": (types.INT32, vals, True, None)}
    path = _write(
        tmp_path, cols,
        WriterOptions(enable_dictionary=False, delta_integers=True,
                      data_page_values=100),
        n=300,
    )
    _check_against_host(path)


def test_delta_length_byte_array_device(tmp_path):
    """DELTA_LENGTH_BYTE_ARRAY strings: host decodes the length stream,
    device gathers the bytes — verified against pyarrow-written files."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng_l = np.random.default_rng(53)
    n = 3000
    vals = ["w" * int(k) + str(int(k)) for k in rng_l.integers(0, 30, n)]
    opt = [None if rng_l.random() < 0.3 else v for v in vals]
    path = str(tmp_path / "dl.parquet")
    pq.write_table(
        pa.table({"s": vals, "o": opt}), path,
        use_dictionary=False, column_encoding={"s": "DELTA_LENGTH_BYTE_ARRAY",
                                               "o": "DELTA_LENGTH_BYTE_ARRAY"},
        use_byte_stream_split=False, version="2.6",
    )
    t = TpuRowGroupReader(path)
    sg = t._stage_row_group(0, None)
    assert all(s.kind == "plain_str" for s in sg.program), [
        s.kind for s in sg.program
    ]
    t.close()
    _check_against_host(path)


def test_tpu_ranged_decode(tmp_path):
    """TpuRowGroupReader.read_row_group_ranges stages only covered pages
    and matches the host ranged decode exactly."""
    from parquet_floor_tpu.batch.predicate import col

    n = 2000
    rng_l = np.random.default_rng(71)
    vals = np.arange(n, dtype=np.int64)
    ds = rng_l.standard_normal(n)
    ss = [f"s{i % 113}" for i in range(n)]
    cols = {
        "x": (types.INT64, list(vals), False, None),
        "d": (types.DOUBLE, ds, False, None),
        "s": (types.BYTE_ARRAY, ss, False, types.string()),
    }
    path = _write(tmp_path, cols, WriterOptions(data_page_values=200), n=n)
    with ParquetFileReader(path) as h:
        pred = (col("x") >= 450) & (col("x") < 850)
        ranges = pred.row_ranges(h, 0)
        host_batch, host_cov = h.read_row_group_ranges(0, ranges)
    t = TpuRowGroupReader(path)
    try:
        dev, cov = t.read_row_group_ranges(0, ranges)
        assert cov == host_cov == [(400, 1000)]
        np.testing.assert_array_equal(
            np.asarray(dev["x"].values), host_batch.column("x").values
        )
        np.testing.assert_allclose(
            np.asarray(dev["d"].values), host_batch.column("d").values
        )
        sc = dev["s"]
        rows = np.asarray(sc.values); lens = np.asarray(sc.lengths)
        got = [rows[i, : lens[i]].tobytes().decode() for i in range(cov[0][1] - cov[0][0])]
        assert got == ss[400:1000]
        # empty and full requests
        empty, ecov = t.read_row_group_ranges(0, [])
        assert empty == {} and ecov == []
        full, fcov = t.read_row_group_ranges(0, [(0, n)])
        assert fcov == [(0, n)] and np.asarray(full["x"].values).shape[0] == n
    finally:
        t.close()


def test_mixed_dict_plain_string_chunk(tmp_path):
    """pyarrow dictionary-overflow chunks (dict pages then PLAIN fallback
    pages in one chunk) decode on the device string path."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 30_000
    vals = [f"unique-value-{i:07d}" for i in range(n)]
    path = str(tmp_path / "mix.parquet")
    pq.write_table(
        pa.table({"s": vals}), path, use_dictionary=True,
        dictionary_pagesize_limit=16 * 1024, compression="SNAPPY",
    )
    t = TpuRowGroupReader(path)
    try:
        sg = t._stage_row_group(0, None)
        assert [s.kind for s in sg.program] == ["plain_str"], [
            s.kind for s in sg.program
        ]
        dc = t.read_row_group(0)["s"]
        rows = np.asarray(dc.values)
        lens = np.asarray(dc.lengths)
        got = [rows[i, : lens[i]].tobytes().decode() for i in range(0, n, 501)]
        assert got == vals[0::501]
    finally:
        t.close()
    _check_against_host(path)


def test_mixed_chunk_python_fallback_scan(tmp_path, monkeypatch):
    """Regression: the mixed_str dict-pool scan must work with the pure-
    Python chain walker too (exact count from the dict page header)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    import parquet_floor_tpu.native.binding as binding

    vals = [f"unique-value-{i:07d}" for i in range(8000)]
    path = str(tmp_path / "mixpy.parquet")
    pq.write_table(
        pa.table({"s": vals}), path, use_dictionary=True,
        dictionary_pagesize_limit=8 * 1024, compression="SNAPPY",
    )
    monkeypatch.setattr(binding, "available", lambda: False)
    t = TpuRowGroupReader(path)
    try:
        dc = t.read_row_group(0)["s"]
        rows = np.asarray(dc.values)
        lens = np.asarray(dc.lengths)
        got = [rows[i, : lens[i]].tobytes().decode() for i in range(0, 8000, 497)]
        assert got == vals[0::497]
    finally:
        t.close()


def test_pallas_run_heavy_takes_hbm_plan(tmp_path, monkeypatch):
    """Streams with huge run tables exceed the scalar-prefetch budget but
    are served by the HBM-plan kernel (each tile DMAs its own run window)
    instead of falling back to the jnp expansion."""
    n = 60_000
    # alternating 9-runs of null/value: each stretch becomes its own RLE
    # run (~6.7k runs for 60k values)
    vals = [None if (i // 9) % 2 else float(i) for i in range(n)]
    cols = {"x": (types.DOUBLE, vals, True, None)}
    path = _write(tmp_path, cols, WriterOptions(), n=n)
    monkeypatch.setenv("PFTPU_PALLAS", "1")
    t = TpuRowGroupReader(path)
    try:
        sg = t._stage_row_group(0, None)
        (spec,) = sg.program
        assert spec.r_lvl > 2048
        assert spec.pl_lvl and spec.pl_lvl[4] == 1, spec.pl_lvl
        # and it decodes exactly
        cols_d = t._launch(sg)
        got = np.asarray(cols_d["x"].values)
        mask = np.asarray(cols_d["x"].mask)
        want = np.array([0.0 if v is None else v for v in vals])
        np.testing.assert_array_equal(np.where(mask, 0, got), want)
        np.testing.assert_array_equal(mask, [v is None for v in vals])
    finally:
        t.close()


def test_int64_delta_wide_single_page_device(tmp_path):
    """VERDICT r1 item 6: wide-range INT64 delta columns decode ON DEVICE
    (delta1w: int64 reconstruction, hi/lo split constants), bit-exact vs
    host — including miniblock widths over 32 bits and a negative base."""
    rng_l = np.random.default_rng(5)
    n = 5000
    # huge jumps force >32-bit miniblock widths; base far outside int32
    vals = (
        np.cumsum(rng_l.integers(-(2**40), 2**40, n)) - 2**55
    ).astype(np.int64)
    cols = {"big": (types.INT64, vals, False, None)}
    path = _write(
        tmp_path, cols, WriterOptions(enable_dictionary=False, delta_integers=True)
    )
    t = TpuRowGroupReader(path)
    try:
        sg = t._stage_row_group(0, None)
        assert [s.kind for s in sg.program] == ["delta1w"], [
            s.kind for s in sg.program
        ]
        got = np.asarray(t.read_row_group(0)["big"].values)
        np.testing.assert_array_equal(got, vals)
    finally:
        t.close()
    _check_against_host(path)


def test_int64_delta_wide_multipage_optional_device(tmp_path):
    """Wide delta across several pages with nulls: the segmented deltaw
    kind (int64 page firsts as hi/lo rows) stays on device."""
    rng_l = np.random.default_rng(6)
    n = 4000
    dense = (np.cumsum(rng_l.integers(-(2**38), 2**38, n))
             + 2**52).astype(np.int64)
    vals = [None if i % 11 == 0 else int(dense[i]) for i in range(n)]
    cols = {"o": (types.INT64, vals, True, None)}
    path = _write(
        tmp_path, cols,
        WriterOptions(enable_dictionary=False, delta_integers=True,
                      data_page_values=512),
    )
    t = TpuRowGroupReader(path)
    try:
        sg = t._stage_row_group(0, None)
        assert [s.kind for s in sg.program] == ["deltaw"], [
            s.kind for s in sg.program
        ]
        dc = t.read_row_group(0)["o"]
        mask = np.asarray(dc.mask)
        got = np.asarray(dc.values)
        exp_mask = np.array([v is None for v in vals])
        np.testing.assert_array_equal(mask, exp_mask)
        np.testing.assert_array_equal(
            got[~mask], np.array([v for v in vals if v is not None])
        )
    finally:
        t.close()
    _check_against_host(path)


def test_int64_delta_narrow_stays_fast(tmp_path):
    """Counterpart: when interval arithmetic proves int32 exactness the
    narrow kinds keep serving (no blanket widening)."""
    vals = np.arange(10_000, dtype=np.int64) * 3 + 100
    cols = {"x": (types.INT64, vals, False, None)}
    path = _write(
        tmp_path, cols, WriterOptions(enable_dictionary=False, delta_integers=True)
    )
    t = TpuRowGroupReader(path)
    try:
        sg = t._stage_row_group(0, None)
        assert [s.kind for s in sg.program] == ["delta1"]
        np.testing.assert_array_equal(
            np.asarray(t.read_row_group(0)["x"].values), vals
        )
    finally:
        t.close()


def test_int64_delta_wide_pyarrow_interop(tmp_path):
    """Nanosecond-scale timestamps from pyarrow's DELTA_BINARY_PACKED
    writer (the classic wide-delta workload) decode on device."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng_l = np.random.default_rng(7)
    n = 20_000
    ts = (1_600_000_000_000_000_000
          + np.cumsum(rng_l.integers(0, 10**12, n))).astype(np.int64)
    path = str(tmp_path / "ts.parquet")
    pq.write_table(
        pa.table({"ts": ts}), path, use_dictionary=False,
        column_encoding={"ts": "DELTA_BINARY_PACKED"},
    )
    t = TpuRowGroupReader(path)
    try:
        sg = t._stage_row_group(0, None)
        assert sg.program[0].kind in ("delta1w", "deltaw"), sg.program[0].kind
        np.testing.assert_array_equal(
            np.asarray(t.read_row_group(0)["ts"].values), ts
        )
    finally:
        t.close()


def test_chunked_ship_matches_host(tmp_path, monkeypatch):
    """Intra-group chunked arena shipping (fill↔transfer overlap) must be
    bit-identical to the bulk path: force a tiny chunk so a multi-column
    mixed group crosses many chunk boundaries mid-stream."""
    import parquet_floor_tpu.tpu.engine as eng

    monkeypatch.setenv("PFTPU_CHUNKED_SHIP", "1")
    monkeypatch.setattr(eng, "_SHIP_CHUNK", 1 << 14)  # 16 KiB chunks
    n = 20_000
    svals = np.array(
        [f"name_{i % 700:04d}".encode() for i in range(n)], dtype=object
    )
    cols = {
        "a": (types.INT64, rng.integers(-(2**55), 2**55, n), False, None),
        "b": (types.DOUBLE, rng.normal(size=n), True, None),
        "s": (types.BYTE_ARRAY, svals, False, types.string()),
    }
    path = _write(tmp_path, cols, WriterOptions(data_page_values=4096), n=n)
    _check_against_host(path)


def test_fill_chunks_covers_every_job(tmp_path):
    """fill_chunks yields each fixed chunk exactly once, in order, only
    after every job overlapping it ran; the filled arena equals fill()."""
    import parquet_floor_tpu.tpu.engine as eng

    b = eng._ArenaBuilder(lead=100)
    payloads = []
    r = np.random.default_rng(3)
    for sz in (5000, 1, 70000, 123, 4096, 999):
        data = r.integers(0, 256, sz).astype(np.uint8).tobytes()
        payloads.append(data)
        b.add_copy(data, sz)
    cap = b.size + 64
    a1 = np.zeros(cap, np.uint8)
    b.fill(a1)
    a2 = np.zeros(cap, np.uint8)
    spans = list(b.fill_chunks(a2, 4096))
    np.testing.assert_array_equal(a1, a2)
    # spans tile [0, cap) exactly
    assert spans[0][0] == 0 and spans[-1][1] == cap
    for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
        assert e0 == s1
    assert all(e - s == 4096 for s, e in spans[:-1])


def test_native_delta_plan_matches_python():
    """The native DELTA plan parser must agree with the Python walk on
    every field, including the wide/int32-exactness decision."""
    from parquet_floor_tpu.format.encodings import delta as e_delta
    from parquet_floor_tpu.native import binding as nb
    import parquet_floor_tpu.tpu.engine as eng

    if not nb.available():
        pytest.skip("native library not built")
    r = np.random.default_rng(5)
    cases = []
    for dt, lohi in [
        (np.int32, (-(2**31), 2**31 - 1)),
        (np.int64, (-(2**31), 2**31 - 1)),       # narrow int64 -> fast path
        (np.int64, (-(2**62), 2**62)),           # wide int64
    ]:
        for n in (1, 2, 100, 5000):
            vals = r.integers(lohi[0], lohi[1], n).astype(dt)
            cases.append((vals, dt))
    cases.append((np.arange(3, dtype=np.int64) + 2**40, np.int64))
    for vals, dt in cases:
        stream = e_delta.encode_delta_binary_packed(vals)
        buf = np.frombuffer(stream, np.uint8)
        wide_ok = np.dtype(dt).itemsize > 4
        got = nb.delta_parse_plan(buf, np.dtype(dt).itemsize, wide_ok)
        # force the Python walk for the reference result
        import unittest.mock as mock
        with mock.patch.object(nb, "available", lambda: False):
            want = eng.parse_delta_plan(buf, dt, allow_wide=wide_ok)
        assert (got is None) == (want is None), (dt, len(vals))
        if got is None:
            continue
        for key in ("first_value", "values_per_miniblock", "total",
                    "end_pos", "wide"):
            assert got[key] == want[key], (key, dt, len(vals))
        for key in ("mb_bytebase", "mb_bw", "mb_min_delta"):
            np.testing.assert_array_equal(got[key], want[key], err_msg=key)


def test_dict_form_index_output_and_stable_pool_keys(tmp_path):
    """dict_form="index": dictionary columns come back as packed index
    streams; string pools carry the engine's STABLE content key (never
    id()-keyed — ids recycle after GC and would alias pools) and numeric
    pools carry key None so consumers convert them fresh per group."""
    n = 3000
    rng_l = np.random.default_rng(11)
    schema = types.message(
        "t",
        types.required(types.INT64).named("v"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    path = str(tmp_path / "df.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(row_group_rows=1000)
    ) as w:
        for g in range(3):
            # per-group distinct pools (the aliasing hazard scenario)
            vals = rng_l.integers(g * 100, g * 100 + 40, 1000) * 1000
            strs = [f"g{g}-{i % 30}" for i in range(1000)]
            w.write_columns({"v": [int(x) for x in vals], "s": strs})
    with TpuRowGroupReader(path, dict_form="index") as t:
        with ParquetFileReader(path) as hr:
            for g in range(3):
                cols = t.read_row_group(g)
                sv, vv = cols["s"], cols["v"]
                assert sv.dict_ref is not None and vv.dict_ref is not None
                skind, skey, srows, slens = sv.dict_ref
                assert skind in ("host_str", "dev") and skey is not None
                vkind, vkey, vpool = vv.dict_ref
                assert vkind == "host" and vkey is None
                # packed index dtypes: pools are small here
                assert np.asarray(sv.values).dtype == np.uint8
                assert np.asarray(vv.values).dtype == np.uint8
                # exact reconstruction vs the host engine
                hb = hr.read_row_group(g)
                want_v = hb.column("v").values
                got_v = np.asarray(vpool)[np.asarray(vv.values)]
                np.testing.assert_array_equal(got_v, want_v)
                srows_np, slens_np = np.asarray(srows), np.asarray(slens)
                idx = np.asarray(sv.values)
                got_s = [
                    srows_np[i, : slens_np[i]].tobytes().decode()
                    for i in idx[:50]
                ]
                want_s = [hb.column("s").cell(i).decode() for i in range(50)]
                assert got_s == want_s, f"group {g}"


def test_dict_form_index_selective_ranges(tmp_path):
    """read_row_group_ranges composes with dict_form="index": only
    intersecting pages stage, and the index stream + pool reconstruct
    the covered rows exactly."""
    from parquet_floor_tpu import col

    n = 6000
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    path = str(tmp_path / "sel.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=500)
    ) as w:
        w.write_columns({
            "k": list(range(n)),
            "s": [f"v{i % 40}" for i in range(n)],
        })
    with TpuRowGroupReader(path, dict_form="index") as t:
        ranges = (col("k") >= 4200).row_ranges(t.reader, 0)
        cols, covered = t.read_row_group_ranges(0, ranges)
        assert covered and covered[0][0] <= 4200
        total = sum(b - a for a, b in covered)
        sv = cols["s"]
        assert sv.dict_ref is not None
        kind, key, rows_p, lens_p = sv.dict_ref
        idx = np.asarray(sv.values)
        assert len(idx) == total
        rows_np, lens_np = np.asarray(rows_p), np.asarray(lens_p)
        start = covered[0][0]
        for off in (0, total // 2, total - 1):
            i = int(idx[off])
            got = rows_np[i, : lens_np[i]].tobytes().decode()
            assert got == f"v{(start + off) % 40}", (off, got)
        kv = np.asarray(cols["k"].values)
        np.testing.assert_array_equal(
            kv, np.arange(start, start + total)
        )
