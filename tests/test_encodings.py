"""Encoding unit tests: round-trips + edge cases for every codec
(SURVEY.md §4 "per-encoding unit tests (RLE hybrid, bit-pack, DELTA_*,
dictionary)")."""

import numpy as np
import pytest

from parquet_floor_tpu.format.encodings import plain as e_plain
from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
from parquet_floor_tpu.format.encodings import delta as e_delta
from parquet_floor_tpu.format.encodings import byte_stream_split as e_bss
from parquet_floor_tpu.format.encodings.dictionary import (
    build_dictionary,
    decode_dict_indices,
    encode_dict_indices,
    gather,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.format.parquet_thrift import Type

rng = np.random.default_rng(42)


# ---------------------------------------------------------------------- PLAIN

@pytest.mark.parametrize(
    "ptype,dtype",
    [
        (Type.INT32, np.int32),
        (Type.INT64, np.int64),
        (Type.FLOAT, np.float32),
        (Type.DOUBLE, np.float64),
    ],
)
def test_plain_fixed_roundtrip(ptype, dtype):
    if np.issubdtype(dtype, np.integer):
        values = rng.integers(np.iinfo(dtype).min, np.iinfo(dtype).max, 1000).astype(dtype)
    else:
        values = rng.standard_normal(1000).astype(dtype)
    data = e_plain.encode_plain(values, ptype)
    out, consumed = e_plain.decode_plain(data, len(values), ptype)
    assert consumed == len(data)
    np.testing.assert_array_equal(out, values)


def test_plain_boolean_roundtrip():
    for n in [0, 1, 7, 8, 9, 1000]:
        values = rng.integers(0, 2, n).astype(bool)
        data = e_plain.encode_plain(values, Type.BOOLEAN)
        out, _ = e_plain.decode_plain(data, n, Type.BOOLEAN)
        np.testing.assert_array_equal(out, values)


def test_plain_byte_array_roundtrip():
    values = [b"", b"a", b"hello world", bytes(range(256)), b"x" * 10000]
    col = ByteArrayColumn.from_list(values)
    data = e_plain.encode_plain(col, Type.BYTE_ARRAY)
    out, consumed = e_plain.decode_plain(data, len(values), Type.BYTE_ARRAY)
    assert consumed == len(data)
    assert out.to_list() == values


def test_plain_fixed_len_byte_array():
    values = rng.integers(0, 256, (10, 16)).astype(np.uint8)
    data = e_plain.encode_plain(values, Type.FIXED_LEN_BYTE_ARRAY, type_length=16)
    out, _ = e_plain.decode_plain(data, 10, Type.FIXED_LEN_BYTE_ARRAY, type_length=16)
    np.testing.assert_array_equal(out, values)


def test_plain_int96():
    values = rng.integers(0, 256, (5, 12)).astype(np.uint8)
    data = e_plain.encode_plain(values, Type.INT96)
    out, _ = e_plain.decode_plain(data, 5, Type.INT96)
    np.testing.assert_array_equal(out, values)


# ------------------------------------------------------------------ RLE hybrid

@pytest.mark.parametrize("bit_width", [1, 2, 3, 5, 7, 8, 12, 17, 20, 24, 31, 32])
def test_bit_pack_unpack(bit_width):
    n = 64
    maxv = (1 << bit_width) - 1
    values = rng.integers(0, maxv + 1, n, dtype=np.uint64)
    packed = np.frombuffer(e_rle.bit_pack(values, bit_width), dtype=np.uint8)
    out = e_rle.bit_unpack(packed, bit_width, n)
    np.testing.assert_array_equal(out, values)


@pytest.mark.parametrize("bit_width", [1, 2, 4, 10, 20])
def test_rle_hybrid_roundtrip_random(bit_width):
    maxv = (1 << bit_width) - 1
    for n in [1, 5, 8, 100, 1023]:
        values = rng.integers(0, maxv + 1, n, dtype=np.uint32)
        data = e_rle.encode_rle_hybrid(values, bit_width)
        out, _ = e_rle.decode_rle_hybrid(data, n, bit_width)
        np.testing.assert_array_equal(out, values)


def test_rle_hybrid_runs():
    # long runs → RLE encoding path
    values = np.repeat(np.array([3, 1, 2, 0], dtype=np.uint32), [100, 8, 9, 50])
    data = e_rle.encode_rle_hybrid(values, 2)
    out, _ = e_rle.decode_rle_hybrid(data, len(values), 2)
    np.testing.assert_array_equal(out, values)
    # mixed short/long
    values = np.concatenate([
        np.array([1, 0, 1, 0, 1], dtype=np.uint32),
        np.full(64, 1, dtype=np.uint32),
        np.array([0, 1, 0], dtype=np.uint32),
    ])
    data = e_rle.encode_rle_hybrid(values, 1)
    out, _ = e_rle.decode_rle_hybrid(data, len(values), 1)
    np.testing.assert_array_equal(out, values)


def test_rle_length_prefixed():
    values = rng.integers(0, 2, 500, dtype=np.uint32)
    data = e_rle.encode_length_prefixed(values, 1)
    out, end = e_rle.decode_length_prefixed(data + b"trailing", len(values), 1)
    assert end == len(data)
    np.testing.assert_array_equal(out, values)


def test_rle_bit_width_zero():
    out, end = e_rle.decode_rle_hybrid(b"", 10, 0)
    np.testing.assert_array_equal(out, np.zeros(10))


# --------------------------------------------------------------------- DELTA_*

@pytest.mark.parametrize("dtype", [np.int32, np.int64])
def test_delta_binary_packed_roundtrip(dtype):
    info = np.iinfo(dtype)
    cases = [
        np.array([], dtype=dtype),
        np.array([42], dtype=dtype),
        np.arange(1000, dtype=dtype),
        rng.integers(info.min, info.max, 777).astype(dtype),
        np.array([info.min, info.max, 0, -1, 1], dtype=dtype),
        np.full(300, -7, dtype=dtype),
    ]
    for values in cases:
        data = e_delta.encode_delta_binary_packed(values)
        out, _ = e_delta.decode_delta_binary_packed(data, out_dtype=dtype)
        np.testing.assert_array_equal(out.astype(dtype), values)


def test_delta_extreme_deltas():
    # deltas overflow int64 → wraparound arithmetic must be bit-exact
    v = np.array([-(2**62), 2**62, -(2**62), 0, 2**63 - 1, -(2**63)], dtype=np.int64)
    data = e_delta.encode_delta_binary_packed(v)
    out, _ = e_delta.decode_delta_binary_packed(data)
    np.testing.assert_array_equal(out, v)


def test_delta_length_byte_array():
    values = [b"alpha", b"", b"gamma" * 100, b"d"]
    col = ByteArrayColumn.from_list(values)
    data = e_delta.encode_delta_length_byte_array(col)
    out, _ = e_delta.decode_delta_length_byte_array(data)
    assert out.to_list() == values


def test_delta_byte_array():
    values = [b"apple", b"applesauce", b"application", b"banana", b"band", b""]
    col = ByteArrayColumn.from_list(values)
    data = e_delta.encode_delta_byte_array(col)
    out, _ = e_delta.decode_delta_byte_array(data)
    assert out.to_list() == values


# ----------------------------------------------------------- BYTE_STREAM_SPLIT

@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_byte_stream_split(dtype):
    values = rng.standard_normal(257).astype(dtype)
    data = e_bss.encode_byte_stream_split(values)
    out = e_bss.decode_byte_stream_split(data, len(values), dtype)
    np.testing.assert_array_equal(out, values)


# ------------------------------------------------------------------ dictionary

def test_dictionary_int():
    values = rng.integers(0, 50, 1000).astype(np.int64)
    d, idx = build_dictionary(values, Type.INT64)
    np.testing.assert_array_equal(gather(d, idx), values)
    # first-appearance order
    seen = []
    for v in values:
        if v not in seen:
            seen.append(v)
    np.testing.assert_array_equal(d, np.array(seen, dtype=np.int64))


def test_dictionary_byte_array():
    words = [b"foo", b"bar", b"foo", b"baz", b"bar", b"foo"]
    col = ByteArrayColumn.from_list(words)
    d, idx = build_dictionary(col, Type.BYTE_ARRAY)
    assert d.to_list() == [b"foo", b"bar", b"baz"]
    assert gather(d, idx).to_list() == words


def test_dict_indices_roundtrip():
    idx = rng.integers(0, 1000, 5000).astype(np.uint32)
    data = e_rle_dict = encode_dict_indices(idx, 1000)
    out, _ = decode_dict_indices(data, len(idx))
    np.testing.assert_array_equal(out, idx)


def test_count_equal_native_vs_python():
    """count_equal (native + fallback) vs full expansion, across widths."""
    import numpy as np
    from parquet_floor_tpu.format.encodings import rle_hybrid as e
    from parquet_floor_tpu.native import binding

    rng = np.random.default_rng(7)
    for bw in (1, 2, 3, 5, 7, 8, 12, 20):
        hi = 1 << bw
        vals = rng.integers(0, min(hi, 6), 5000).astype(np.uint64)
        vals[100:900] = min(hi, 6) - 1  # a long RLE run
        stream = e.encode_rle_hybrid(vals, bw)
        buf = np.frombuffer(stream, np.uint8)
        expanded, _ = e.decode_rle_hybrid(buf, len(vals), bw, 0)
        for target in (0, min(hi, 6) - 1, hi - 1):
            exp = int(np.count_nonzero(expanded == target))
            got = e.count_equal(buf, len(vals), bw, target)
            assert got == exp, (bw, target)
            if binding.available():
                nat = binding.rle_count_equal(buf, len(vals), bw, target)
                assert nat == exp, (bw, target, "native")
    # offset (pos) handling
    pad = 3
    vals = rng.integers(0, 4, 1000).astype(np.uint64)
    stream = e.encode_rle_hybrid(vals, 2)
    buf = np.frombuffer(b"\xff" * pad + stream, np.uint8)
    expanded, _ = e.decode_rle_hybrid(buf[pad:], len(vals), 2, 0)
    for target in (0, 3):
        exp = int(np.count_nonzero(expanded == target))
        assert e.count_equal(buf, len(vals), 2, target, pos=pad) == exp


def test_native_rejects_hostile_run_headers():
    """Corrupt varint headers (huge group counts) must error, not read OOB."""
    import numpy as np
    import pytest
    from parquet_floor_tpu.native import binding

    if not binding.available():
        pytest.skip("native lib not built")
    # bit-packed header claiming ~2^62 groups: varint 0xFF...0x7F, LSB set
    hostile = bytes([0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0x7F]) + b"\x00" * 16
    with pytest.raises(ValueError):
        binding.rle_parse_runs(hostile, 1000, 4)
    with pytest.raises(ValueError):
        binding.rle_count_equal(hostile, 1000, 4, 1)


# --------------------------------------------------- legacy BIT_PACKED levels

def test_bit_packed_legacy_levels():
    """Deprecated MSB-first BIT_PACKED level decode (very old v1 files)."""
    from parquet_floor_tpu.format.encodings.rle_hybrid import (
        decode_bit_packed_legacy,
    )

    # spec example: levels 0..7 with bw=3 pack MSB-first as
    # 000 001 010 011 100 101 110 111 -> bytes 0b00000101, 0b00111001, 0b01110111
    data = bytes([0b00000101, 0b00111001, 0b01110111])
    vals, end = decode_bit_packed_legacy(data, 8, 3)
    assert vals.tolist() == [0, 1, 2, 3, 4, 5, 6, 7]
    assert end == 3
    # bw=1: bits MSB-first within each byte
    vals, _ = decode_bit_packed_legacy(bytes([0b10110000]), 4, 1)
    assert vals.tolist() == [1, 0, 1, 1]
    # truncation raises
    import pytest as _p
    with _p.raises(ValueError):
        decode_bit_packed_legacy(b"\x01", 8, 3)


def test_bit_packed_legacy_page_roundtrip():
    """A synthetic v1 page with BIT_PACKED def levels decodes via the host
    page decoder (parity with parquet-mr's legacy-file support)."""
    import numpy as np
    from parquet_floor_tpu.format import pages as pg
    from parquet_floor_tpu.format.encodings.plain import encode_plain
    from parquet_floor_tpu.format.parquet_thrift import (
        CompressionCodec,
        DataPageHeader,
        Encoding,
        PageHeader,
        PageType,
    )
    from parquet_floor_tpu.format.schema import types as t

    schema = t.message("m", t.optional(t.INT32).named("x"))
    desc = schema.columns[0]
    # 8 slots: values at even positions, nulls at odd (def levels 1,0,...)
    defs = np.array([1, 0, 1, 0, 1, 0, 1, 0], np.uint32)
    present = np.array([10, 20, 30, 40], np.int32)
    # MSB-first bw=1 packing of defs: 0b10101010
    level_bytes = bytes([0b10101010])
    payload = level_bytes + encode_plain(present, Type.INT32)
    header = PageHeader(
        type=PageType.DATA_PAGE,
        uncompressed_page_size=len(payload),
        compressed_page_size=len(payload),
        data_page_header=DataPageHeader(
            num_values=8,
            encoding=Encoding.PLAIN,
            definition_level_encoding=Encoding.BIT_PACKED,
            repetition_level_encoding=Encoding.BIT_PACKED,
        ),
    )
    page = pg.RawPage(header=header, payload=payload)
    out = pg.decode_data_page(page, desc, CompressionCodec.UNCOMPRESSED, None)
    assert out.def_levels.tolist() == defs.tolist()
    np.testing.assert_array_equal(out.values, present)


# ------------------------------------------- vectorized dedup / stats bounds

@pytest.mark.parametrize("native", [True, False])
def test_build_dictionary_nul_and_size_boundaries(native, monkeypatch):
    """The string dedup's tricky cases (ADVICE/review r5) on BOTH
    implementations — the native O(n) hash table and the numpy padded-
    key fallback (which must stay correct for environments without the
    C++ runtime): embedded-NUL distinctness (b"a" vs b"a\\x00"), the
    numpy path's 64/65-byte fast-vs-fallback boundary, and list-input
    parity with the packed column input."""
    import numpy as np

    from parquet_floor_tpu.format.encodings.dictionary import build_dictionary
    from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
    from parquet_floor_tpu.format.parquet_thrift import Type as T
    from parquet_floor_tpu.native import binding

    if native and not binding.available():
        pytest.skip("native runtime not built")
    if not native:
        monkeypatch.setattr(binding, "available", lambda: False)

    def ref(vals):
        seen, uniq, idx = {}, [], []
        for v in vals:
            if v not in seen:
                seen[v] = len(uniq)
                uniq.append(v)
            idx.append(seen[v])
        return uniq, idx

    nul_cases = [
        [b"a", b"a\x00", b"a", b"a\x00\x00", b""],
        [b"a\x00", b"a", b"\x00", b"", b"\x00\x00"],
    ]
    # 64 = last fast-path width; 65 = first fallback width — both must
    # agree with the reference dedup and with each other's semantics
    for w in (63, 64, 65):
        nul_cases.append([b"x" * w, b"y" * w, b"x" * w, b"x" * (w - 1)])
    for vals in nul_cases:
        for form in (vals, ByteArrayColumn.from_list(vals)):
            d, idx = build_dictionary(form, T.BYTE_ARRAY)
            ru, ri = ref(vals)
            assert d.to_list() == ru, vals
            assert idx.tolist() == ri, vals
    rng = np.random.default_rng(11)
    fuzz = [
        bytes(rng.integers(0, 3, int(rng.integers(0, 6))).astype(np.uint8))
        for _ in range(3000)
    ]
    d, idx = build_dictionary(ByteArrayColumn.from_list(fuzz), T.BYTE_ARRAY)
    ru, ri = ref(fuzz)
    assert d.to_list() == ru and idx.tolist() == ri


def test_string_stats_nul_tiebreak_and_gate():
    """_lex_min_max_bytearray: padded ties break by length (b"a" <
    b"a\\x00"), and the 256/257 vectorized-vs-fallback gate returns
    identical stats."""
    from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
    from parquet_floor_tpu.format.file_write import (
        _lex_min_max_bytearray,
        _min_max_bytes,
    )
    from parquet_floor_tpu.format.schema import types as t

    desc = t.message(
        "m", t.required(t.BYTE_ARRAY).as_(t.string()).named("s")
    ).columns[0]
    vals = [b"a\x00", b"a", b"a\x00\x01", b"b"]
    col = ByteArrayColumn.from_list(vals)
    assert _lex_min_max_bytearray(col) == (min(vals), max(vals))
    for w in (255, 256, 257):  # gate straddles 256
        vs = [b"m" * w, b"a", b"z", b"m" * (w - 1)]
        got = _min_max_bytes(desc, ByteArrayColumn.from_list(vs))
        assert got == (min(vs), max(vs)), w


@pytest.mark.parametrize("native", [True, False])
def test_build_dictionary_numeric_bits_dedup(native, monkeypatch):
    """Fixed-width dictionary builds dedup by raw BITS on both
    implementations: -0.0 stays distinct from 0.0 and distinct NaN
    payloads stay apart, so the file bytes do not depend on whether
    the native runtime was present at write time."""
    from parquet_floor_tpu.native import binding

    if native and not binding.available():
        pytest.skip("native runtime not built")
    if not native:
        monkeypatch.setattr(binding, "available", lambda: False)
    nan2 = np.frombuffer(
        np.uint64(0x7FF8000000000001).tobytes(), dtype=np.float64
    )[0]
    arr = np.array([0.0, -0.0, 1.5, np.nan, 1.5, -0.0, nan2], np.float64)
    d, idx = build_dictionary(arr, Type.DOUBLE)
    assert len(d) == 5  # 0.0, -0.0, 1.5, nan, nan2 all distinct
    np.testing.assert_array_equal(
        np.asarray(d).view(np.uint64)[idx], arr.view(np.uint64)
    )
    iv = np.array([5, 3, 5, 7, 3], np.int64)
    d2, idx2 = build_dictionary(iv, Type.INT64)
    assert d2.tolist() == [5, 3, 7]
    np.testing.assert_array_equal(np.asarray(d2)[idx2], iv)
