"""Remote-storage failure domain (ISSUE 7 tentpole): the seeded
latency/fault simulator, hedged reads, the per-source circuit breaker,
error classification riding the retry budgets, and latency-adaptive
prefetch — every scenario deterministic under fixed seeds."""

import time

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.errors import (
    BreakerOpenError,
    RemoteFatalError,
    RemoteThrottledError,
    RemoteTransientError,
    TruncatedFileError,
)
from parquet_floor_tpu.io.remote import (
    CircuitBreaker,
    LatencyStats,
    ParallelRangeReader,
    RemoteSource,
)
from parquet_floor_tpu.io.source import FileSource, RetryingSource
from parquet_floor_tpu.scan import DatasetScanner, ScanOptions
from parquet_floor_tpu.testing import RemoteProfile, SimulatedRemoteSource

DATA = bytes(np.random.default_rng(0).integers(0, 256, 1 << 16, dtype=np.uint8))


def _src(**kw):
    kw.setdefault("seed", 7)
    return SimulatedRemoteSource(DATA, **kw)


# ---------------------------------------------------------------------------
# simulator: determinism + failure-mode modeling
# ---------------------------------------------------------------------------

def test_simulator_serves_exact_bytes_and_counts():
    with _src(profile=RemoteProfile(base_latency_s=0.001)) as s:
        assert bytes(s.read_at(100, 64)) == DATA[100:164]
        out = s.read_many([(0, 16), (4096, 32), (65520, 16)])
        assert [bytes(b) for b in out] == [
            DATA[:16], DATA[4096:4128], DATA[65520:],
        ]
        assert s.transport.requests == 4
        assert s.transport.bytes_served == 128
        with pytest.raises(TruncatedFileError):
            s.read_at(len(DATA) - 8, 16)


def test_simulator_keyed_draws_are_order_independent():
    """The determinism contract: which requests are slow/faulty is keyed
    by (seed, offset, length, attempt-ordinal), so issue ORDER cannot
    change the outcome set."""
    prof = RemoteProfile(fault_rate=0.3, tail_p=0.3, tail_latency_s=0.0)

    def outcome_map(order):
        out = {}
        with _src(profile=prof, seed=11, hedge=False) as s:
            for off in order:
                try:
                    s.read_at(off, 32)
                    out[off] = "ok"
                except OSError:
                    out[off] = "fault"
        return out

    offsets = [0, 512, 1024, 2048, 4096, 8192, 16384, 32768]
    assert outcome_map(offsets) == outcome_map(list(reversed(offsets)))


def test_simulator_bandwidth_cap_adds_transfer_time():
    slow = RemoteProfile(bandwidth_bytes_per_s=1e6)  # 1 MB/s
    with _src(profile=slow, hedge=False) as s:
        t0 = time.perf_counter()
        s.read_at(0, 50_000)  # 50 ms of transfer
        assert time.perf_counter() - t0 >= 0.04


# ---------------------------------------------------------------------------
# hedged reads — the satellite's four edge cases, scripted + seeded
# ---------------------------------------------------------------------------

def test_hedge_fires_then_primary_wins():
    with trace.scope() as t:
        with _src(
            latency_overrides={(64, 0): 0.06, (64, 1): 0.5},
            hedge_delay_s=0.02,
        ) as s:
            t0 = time.perf_counter()
            assert bytes(s.read_at(64, 128)) == DATA[64:192]
            dt = time.perf_counter() - t0
    c = t.counters()
    assert c.get("io.remote.hedges") == 1
    assert c.get("io.remote.hedge_wins", 0) == 0       # primary won
    assert c.get("io.remote.hedges_cancelled") == 1    # loser counted
    assert dt < 0.4  # did NOT wait for the 0.5 s loser
    assert any(d["decision"] == "io.hedge" for d in t.decisions())


def test_hedge_wins_over_straggling_primary():
    with trace.scope() as t:
        with _src(
            latency_overrides={(64, 0): 0.5, (64, 1): 0.005},
            hedge_delay_s=0.02,
        ) as s:
            t0 = time.perf_counter()
            assert bytes(s.read_at(64, 128)) == DATA[64:192]
            dt = time.perf_counter() - t0
    c = t.counters()
    assert c.get("io.remote.hedge_wins") == 1
    assert c.get("io.remote.hedges_cancelled") == 1
    assert dt < 0.3  # the 0.5 s primary straggler was hedged around


def test_both_fail_raises_primary_error_deterministically():
    """Whichever request fails FIRST, the reported error is the
    primary's — error order never depends on thread timing."""
    for lat0, lat1 in [(0.05, 0.005), (0.005, 0.05)]:
        with _src(
            latency_overrides={(64, 0): lat0, (64, 1): lat1},
            fault_overrides={(64, 0): "primary boom", (64, 1): "hedge boom"},
            hedge_delay_s=0.002,
        ) as s:
            with pytest.raises(OSError, match="primary boom"):
                s.read_at(64, 128)


def test_deadline_crossing_mid_hedge():
    """Primary AND hedge both in flight when the per-range deadline
    crosses: the fetch abandons both, raises the retryable transient
    class, and counts the deadline."""
    with trace.scope() as t:
        with _src(
            latency_overrides={(64, 0): 0.4, (64, 1): 0.4},
            hedge_delay_s=0.01, range_deadline_s=0.05,
        ) as s:
            t0 = time.perf_counter()
            with pytest.raises(RemoteTransientError, match="deadline"):
                s.read_at(64, 128)
            assert time.perf_counter() - t0 < 0.3
    c = t.counters()
    assert c.get("io.remote.deadlines") == 1
    assert c.get("io.remote.hedges") == 1


def test_no_hedge_when_deadline_shorter_than_delay():
    """A wait that times out on the (shorter) deadline remainder must
    not be mistaken for the hedge delay elapsing: no duplicate request
    fires, and no phantom hedge activity lands on the counters."""
    with trace.scope() as t:
        with _src(
            latency_overrides={(64, 0): 0.3},
            hedge_delay_s=0.2, range_deadline_s=0.05,
        ) as s:
            with pytest.raises(RemoteTransientError, match="deadline"):
                s.read_at(64, 128)
            assert s.transport.requests == 1  # the primary, nothing else
    c = t.counters()
    assert c.get("io.remote.hedges", 0) == 0
    assert c.get("io.remote.hedges_cancelled", 0) == 0
    assert c.get("io.remote.deadlines") == 1


def test_adaptive_hedge_delay_tracks_p95():
    stats = LatencyStats()
    for v in [0.01] * 95 + [0.5] * 5:
        stats.observe(v)
    assert 0.009 <= stats.p95() <= 0.51
    with _src(hedge_min_delay_s=0.001, hedge_max_delay_s=0.05) as s:
        assert s.hedge_delay() is None  # too few samples: no tail estimate
        for v in [0.02] * 16:
            s.latency.observe(v)
        d = s.hedge_delay()
        assert 0.001 <= d <= 0.05
    with _src(hedge=False) as s:
        assert s.hedge_delay() is None


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_trips_fast_fails_and_recovers_half_open():
    with trace.scope() as t:
        with _src(
            hedge=False,
            fault_overrides={(0, 0): "f", (64, 0): "f", (128, 0): "f"},
            breaker_threshold=3, breaker_cooldown_s=0.05,
        ) as s:
            for off in (0, 64, 128):
                with pytest.raises(OSError):
                    s.read_at(off, 16)
            assert s.breaker.state == "open"
            # fail-fast without touching the network
            reqs = s.transport.requests
            with pytest.raises(BreakerOpenError) as ei:
                s.read_at(256, 16)
            assert s.transport.requests == reqs
            assert 0 < ei.value.retry_after_s <= 0.05
            # cooldown passes → ONE half-open probe → success closes
            time.sleep(0.06)
            assert bytes(s.read_at(256, 16)) == DATA[256:272]
            assert s.breaker.state == "closed"
    c = t.counters()
    assert c.get("io.remote.breaker_trips") == 1
    assert c.get("io.remote.breaker_fast_fails") == 1
    states = [d["state"] for d in t.decisions()
              if d["decision"] == "io.breaker"]
    assert states == ["open", "closed"]


def test_breaker_failed_probe_reopens():
    with _src(
        hedge=False,
        fault_overrides={
            (0, 0): "f", (64, 0): "f", (128, 0): "f",
            (256, 0): "probe fails too",
        },
        breaker_threshold=3, breaker_cooldown_s=0.04,
    ) as s:
        for off in (0, 64, 128):
            with pytest.raises(OSError):
                s.read_at(off, 16)
        time.sleep(0.05)
        with pytest.raises(OSError, match="probe"):
            s.read_at(256, 16)  # the half-open probe
        assert s.breaker.state == "open"  # re-opened for a fresh cooldown
        with pytest.raises(BreakerOpenError):
            s.read_at(512, 16)
        time.sleep(0.05)
        assert bytes(s.read_at(256, 16)) == DATA[256:272]  # k=1 succeeds
        assert s.breaker.state == "closed"


def test_breaker_probe_released_when_throttled():
    """A half-open probe that gets THROTTLED judges nothing about the
    endpoint — it must release the probe slot (not wedge the breaker
    open forever failing fast): the next request becomes a fresh probe
    and closes the breaker."""
    class Transport:
        size = 1024
        name = "probe-throttle"

        def __init__(self):
            self.calls = 0

        def get_range(self, offset, length):
            self.calls += 1
            if self.calls <= 3:
                raise OSError("down")
            if self.calls == 4:
                raise RemoteThrottledError("busy", retry_after_s=0.005)
            return bytes(length)

    with RemoteSource(Transport(), hedge=False, breaker_threshold=3,
                      breaker_cooldown_s=0.02) as s:
        for off in (0, 64, 128):
            with pytest.raises(OSError):
                s.read_at(off, 8)
        assert s.breaker.state == "open"
        time.sleep(0.03)
        with pytest.raises(RemoteThrottledError):
            s.read_at(0, 8)  # the admitted probe, throttled away
        # released, not wedged: this request is a fresh probe
        assert bytes(s.read_at(0, 8)) == bytes(8)
        assert s.breaker.state == "closed"


def test_throttle_never_trips_breaker():
    with _src(
        hedge=False,
        profile=RemoteProfile(throttle_rps=1000, throttle_burst=1),
        breaker_threshold=2, breaker_cooldown_s=10.0,
    ) as s:
        throttled = 0
        for i in range(8):
            try:
                s.read_at(i * 64, 16)
            except RemoteThrottledError as e:
                throttled += 1
                assert e.retry_after_s > 0
        assert throttled >= 2
        assert s.breaker.state == "closed"


def test_breaker_validation():
    with pytest.raises(ValueError, match="threshold"):
        CircuitBreaker(threshold=0)
    with pytest.raises(ValueError, match="cooldown"):
        CircuitBreaker(cooldown_s=0)


# ---------------------------------------------------------------------------
# classification × RetryingSource composition
# ---------------------------------------------------------------------------

def test_retrying_source_honors_throttle_retry_after():
    sleeps = []
    with _src(
        hedge=False,
        profile=RemoteProfile(throttle_rps=100, throttle_burst=1),
    ) as s:
        r = RetryingSource(s, retries=4, backoff_s=0.0001,
                           sleep=lambda d: (sleeps.append(d),
                                            time.sleep(min(d, 0.05))))
        out = r.read_many([(i * 64, 16) for i in range(4)])
        assert [bytes(b) for b in out] == [
            DATA[i * 64: i * 64 + 16] for i in range(4)
        ]
    # throttle-aware backoff: at least one sleep stretched to the
    # bucket's retry_after (way past the 0.1 ms base backoff)
    assert any(d >= 0.005 for d in sleeps), sleeps


def test_fatal_error_is_not_retried():
    attempts = []

    # a transport that raises a NON-OSError is classified fatal and
    # never retried
    class DeniedTransport:
        size = 1024
        name = "denied"

        def get_range(self, offset, length):
            attempts.append(offset)
            raise ValueError("credentials rejected")

    with RemoteSource(DeniedTransport(), hedge=False) as s:
        r = RetryingSource(s, retries=5, backoff_s=0.0001)
        with pytest.raises(RemoteFatalError, match="credentials"):
            r.read_at(0, 16)
    assert len(attempts) == 1  # zero retries burned


def test_outage_recovery_through_retries():
    """The bench's fault-heavy shape in miniature: every request inside
    the outage window fails, retries back off past it, the breaker
    trips and half-open-recovers, and the BYTES come back identical."""
    with trace.scope() as t:
        with _src(
            hedge=False, seed=5,
            profile=RemoteProfile(outage_s=0.08),
            breaker_threshold=3, breaker_cooldown_s=0.03,
        ) as s:
            r = RetryingSource(s, retries=6, backoff_s=0.02)
            out = r.read_many([(i * 100, 50) for i in range(5)])
            assert all(
                bytes(b) == DATA[i * 100: i * 100 + 50]
                for i, b in enumerate(out)
            )
    c = t.counters()
    assert c.get("io.remote.breaker_trips", 0) >= 1
    assert c.get("io.retries", 0) >= 1
    assert c.get("io.remote.faults", 0) >= 3


def test_compose_retrying_respects_precomposed_chains():
    """The ONE chain-composition spelling (reader + scan executor both
    call it): remote sources get RetryingSource below ParallelRangeReader;
    already-composed chains pass through untouched, so attempts never
    multiply and the fan-out never serializes behind an outer retry."""
    from parquet_floor_tpu.io.remote import compose_retrying

    with _src() as s:
        chain = compose_retrying(s, 3)
        assert isinstance(chain, ParallelRangeReader)
        assert compose_retrying(chain, 3) is chain  # no double wrap
    inner_retry = RetryingSource(FileSource(DATA), 2)
    assert compose_retrying(inner_retry, 3) is inner_retry
    inner_retry.close()
    r = compose_retrying(FileSource(DATA), 2)
    assert isinstance(r, RetryingSource)  # local source: no fan-out layer
    r.close()
    with FileSource(DATA) as plain:
        assert compose_retrying(plain, 0) is plain  # retries off: untouched


def test_parallel_range_reader_orders_results_and_errors():
    with FileSource(DATA) as inner:
        with ParallelRangeReader(FileSource(DATA), threads=4) as p:
            out = p.read_many([(0, 16), (64, 16), (128, 16)])
            assert [bytes(b) for b in out] == [
                DATA[:16], DATA[64:80], DATA[128:144],
            ]
        assert bytes(inner.read_at(0, 4)) == DATA[:4]

    class Flaky:
        size = len(DATA)
        name = "flaky"

        def read_at(self, o, n):
            if o == 64:
                raise OSError("boom at 64")
            if o == 128:
                raise OSError("boom at 128")
            return memoryview(DATA)[o:o + n]

        def close(self):
            pass

    with ParallelRangeReader(Flaky(), threads=4) as p:
        # first-LISTED failure raises, regardless of completion order
        with pytest.raises(OSError, match="boom at 64"):
            p.read_many([(0, 16), (64, 16), (128, 16)])


# ---------------------------------------------------------------------------
# scan faces over the simulator: correctness + adaptive prefetch
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def remote_dataset(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("remote_ds")
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(9)
    paths = []
    for i in range(2):
        p = tmp / f"f{i}.parquet"
        with ParquetFileWriter(p, schema,
                               WriterOptions(data_page_values=200)) as w:
            for _ in range(3):
                w.write_columns({
                    "a": rng.integers(0, 1 << 40, 400).astype(np.int64),
                    "d": rng.standard_normal(400),
                })
        paths.append(str(p))
    return paths


def _digest_units(units):
    out = []
    for u in units:
        cols = tuple(
            np.asarray(c.values).tobytes() for c in u.batch.columns
        )
        out.append((u.file_index, u.group_index, u.batch.num_rows,
                    tuple(hash(c) for c in cols)))
    return out


def _scan_digest(paths, profile, seed, sc, retries=4, hedge_kw=None):
    opts = ReaderOptions(io_retries=retries)
    kw = hedge_kw or {}
    factories = [
        (lambda p=p: SimulatedRemoteSource(
            p, profile=profile, seed=seed, fetch_threads=4, **kw
        ))
        for p in paths
    ]
    with DatasetScanner(factories, options=opts, scan=sc) as s:
        return _digest_units(s)


def test_remote_scan_bit_identical_under_faults(remote_dataset):
    """The acceptance shape: a fault-heavy seeded scan (drops + throttle
    + tail latency) completes BIT-IDENTICAL to the clean run, with
    retry/hedge counters exercised."""
    sc = ScanOptions(threads=4, adaptive_prefetch=True)
    clean = _scan_digest(
        remote_dataset, RemoteProfile(base_latency_s=0.002), 13, sc,
    )
    hostile = RemoteProfile(
        base_latency_s=0.002, jitter_s=0.001,
        tail_p=0.25, tail_latency_s=0.03,
        fault_rate=0.1, outage_s=0.03,
        throttle_rps=2000, throttle_burst=4,
    )
    with trace.scope() as t:
        faulty = _scan_digest(
            remote_dataset, hostile, 13, sc,
            hedge_kw={"hedge_delay_s": 0.02,
                      "breaker_threshold": 3,
                      "breaker_cooldown_s": 0.02},
        )
    assert faulty == clean
    c = t.counters()
    assert c.get("io.retries", 0) >= 1, c
    assert c.get("io.remote.faults", 0) >= 1, c
    # every emitted counter name is registered (the trace.names contract)
    assert set(c) <= trace.names.ALL, c


def test_remote_scan_matches_local_scan(remote_dataset):
    sc = ScanOptions(threads=4)
    with DatasetScanner(remote_dataset, scan=sc) as s:
        local = _digest_units(s)
    remote = _scan_digest(
        remote_dataset, RemoteProfile(base_latency_s=0.001), 3,
        ScanOptions(threads=4, adaptive_prefetch=True),
    )
    assert remote == local


def test_adaptive_budget_scales_with_latency(remote_dataset):
    """The latency-adaptive controller: a slow store earns a deeper
    effective budget than a local one, both observable through the
    gauge/decision, and neither changes the decoded bytes."""
    base = ScanOptions(threads=4, adaptive_prefetch=True)

    def peak_budget(profile, seed):
        with trace.scope() as t:
            _scan_digest(remote_dataset, profile, seed, base)
        return (t.gauges().get("scan.adaptive_budget_bytes", 0),
                [d for d in t.decisions()
                 if d["decision"] == "scan.adaptive_budget"])

    slow_cap, slow_dec = peak_budget(
        RemoteProfile(base_latency_s=0.03), 21
    )
    assert slow_cap > 0 and slow_dec

    with trace.scope() as t:
        with DatasetScanner(
            remote_dataset, scan=base
        ) as s:  # local files: RTT « 2 ms
            list(s)
    fast_cap = t.gauges().get("scan.adaptive_budget_bytes", 0)
    assert fast_cap > 0
    # the 30 ms store pipelines deeper than the local SSD
    assert slow_cap >= fast_cap


def test_adaptive_depth_hint_on_device_scan(remote_dataset, monkeypatch):
    jax = pytest.importorskip("jax")
    jax.config.update("jax_enable_x64", True)
    monkeypatch.delenv("PFTPU_PREFETCH_DEPTH", raising=False)
    from parquet_floor_tpu.scan import scan_device_groups

    factories = [
        (lambda p=p: SimulatedRemoteSource(
            p, profile=RemoteProfile(base_latency_s=0.025), seed=2,
            fetch_threads=4,
        ))
        for p in remote_dataset
    ]
    with trace.scope() as t:
        rows = 0
        for _fi, _gi, cols in scan_device_groups(
            factories, scan=ScanOptions(threads=4, adaptive_prefetch=True),
            float64_policy="bits",
        ):
            rows += int(next(iter(cols.values())).values.shape[0])
    assert rows == 2400
    hints = [d for d in t.decisions()
             if d["decision"] == "scan.adaptive_depth"]
    assert hints and hints[0]["depth"] > 3, hints


def test_sequential_reader_over_remote_source(remote_dataset):
    """The sequential face composes too: ReaderOptions(io_retries) wraps
    the remote source, faults recover, bytes match the local read."""
    with ParquetFileReader(remote_dataset[0]) as r:
        want = [
            np.asarray(c.values).tobytes()
            for c in r.read_row_group(0).columns
        ]
    with SimulatedRemoteSource(
        remote_dataset[0], seed=31, hedge=False,
        profile=RemoteProfile(fault_rate=0.2),
    ) as src:
        with ParquetFileReader(
            src,
            options=ReaderOptions(io_retries=6, io_retry_backoff_s=0.001),
        ) as r:
            got = [
                np.asarray(c.values).tobytes()
                for c in r.read_row_group(0).columns
            ]
    assert got == want


def test_remote_source_validation():
    with pytest.raises(ValueError, match="fetch_threads"):
        _src(fetch_threads=0)
    with pytest.raises(ValueError, match="hedge_delay_s"):
        _src(hedge_delay_s=0)
    with pytest.raises(ValueError, match="range_deadline_s"):
        _src(range_deadline_s=-1)
    with pytest.raises(ValueError, match="tail_p"):
        RemoteProfile(tail_p=1.5)
    with pytest.raises(ValueError, match="bandwidth"):
        RemoteProfile(bandwidth_bytes_per_s=0)
