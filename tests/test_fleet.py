"""Fleet cache fabric (serve/fleet.py, docs/serving.md): rendezvous
ownership, the peer-fetch failure domain, epoch fencing, replication,
token-bucket admission, and the daemon-side fleet ops — including the
failure COMPOSITIONS (drain with an in-flight peer fetch, limiter +
admission under overload, a stale owner fenced mid-fleet)."""

import threading
import time

import pytest

from parquet_floor_tpu.serve import (
    DaemonClient,
    FleetCache,
    FleetMembership,
    PeerClient,
    ServeDaemon,
    Serving,
    TenantRateLimiter,
    TokenBucket,
)
from parquet_floor_tpu.serve.shm_cache import _digest
from parquet_floor_tpu.utils import trace

KEY = ("fleet-test", 4 << 20)


def content(offset: int, length: int) -> bytes:
    pat = f"t:{offset}:{length}:".encode("ascii")
    return (pat * (length // len(pat) + 1))[:length]


class CountedOrigin:
    """A thread-safe counted origin: deterministic bytes per range,
    every read recorded, optional per-call latency."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.lock = threading.Lock()
        self.counts: dict = {}

    def __call__(self, key, ranges):
        with self.lock:
            for (o, n) in ranges:
                self.counts[(o, n)] = self.counts.get((o, n), 0) + 1
        if self.delay_s:
            time.sleep(self.delay_s)
        return [content(o, n) for (o, n) in ranges]

    def total(self) -> int:
        with self.lock:
            return sum(self.counts.values())


# ---------------------------------------------------------------------------
# membership / ownership


def test_membership_create_sorts_and_dedups():
    m = FleetMembership.create(["b", "a", "b"], epoch=3)
    assert m.members == ("a", "b")
    assert m.epoch == 3


def test_membership_needs_a_member():
    with pytest.raises(ValueError):
        FleetMembership.create([])


def test_owners_deterministic_and_spread():
    m = FleetMembership.create(["a", "b", "c"])
    seen = {n: 0 for n in m.members}
    for i in range(300):
        dk = _digest(KEY, i * 4096, 1024)
        owners = m.owners(dk[0], dk[1])
        assert owners == m.owners(dk[0], dk[1])  # deterministic
        assert len(owners) == 2 and owners[0] != owners[1]
        seen[owners[0]] += 1
    # rendezvous hashing spreads primaries roughly evenly
    assert all(40 <= c <= 160 for c in seen.values()), seen


def test_membership_change_moves_only_lost_ranges():
    m = FleetMembership.create(["a", "b", "c"])
    m2 = m.without("c")
    assert m2.epoch == m.epoch + 1
    assert m2.members == ("a", "b")
    for i in range(200):
        dk = _digest(KEY, i * 4096, 1024)
        before = m.owners(dk[0], dk[1])[0]
        after = m2.owners(dk[0], dk[1])[0]
        if before != "c":
            # the minimal-reassignment law: a surviving primary keeps
            # every range it owned
            assert after == before
    with pytest.raises(ValueError):
        m2.without("a").without("b")
    assert m2.with_member("c").members == ("a", "b", "c")
    assert m2.with_member("c").epoch == m2.epoch + 1


# ---------------------------------------------------------------------------
# token buckets


def test_token_bucket_admits_burst_then_meters():
    t = [0.0]
    bucket = TokenBucket(rate_per_s=2.0, burst=2.0, clock=lambda: t[0])
    assert bucket.try_acquire() is None
    assert bucket.try_acquire() is None
    retry = bucket.try_acquire()
    assert retry == pytest.approx(0.5)
    t[0] += 0.5  # one token refilled
    assert bucket.try_acquire() is None
    assert bucket.try_acquire() is not None


def test_token_bucket_caps_at_burst():
    t = [0.0]
    bucket = TokenBucket(rate_per_s=10.0, burst=2.0, clock=lambda: t[0])
    t[0] += 100.0  # a long idle must not bank more than the burst
    assert bucket.try_acquire() is None
    assert bucket.try_acquire() is None
    assert bucket.try_acquire() is not None


def test_rate_limiter_per_tenant_and_overrides():
    t = [0.0]
    lim = TenantRateLimiter(rate_per_s=1.0, burst=1.0,
                            overrides={"vip": 100.0},
                            clock=lambda: t[0])
    assert lim.admit("a") is None
    assert lim.admit("a") is not None   # a's bucket is dry
    assert lim.admit("b") is None       # b has its own bucket
    for _ in range(50):                 # vip's override rate holds
        assert lim.admit("vip") is None


# ---------------------------------------------------------------------------
# FleetCache, single node (no sockets)


def test_single_node_reads_origin_once():
    origin = CountedOrigin()
    m = FleetMembership.create(["solo"])
    with FleetCache("solo", m, origin=origin) as fc:
        ranges = [(i * 4096, 512) for i in range(8)]
        got = fc.read_through(KEY, ranges, lambda rs: origin(KEY, rs))
        assert [bytes(b) for b in got] == [content(o, n)
                                           for (o, n) in ranges]
        again = fc.read_through(KEY, ranges, lambda rs: origin(KEY, rs))
        assert [bytes(b) for b in again] == [bytes(b) for b in got]
    assert origin.total() == len(ranges)  # second pass was all local


def test_absent_peer_falls_back_to_origin():
    # a non-primary with NO reachable peer must still answer — the
    # fallback path is the read's availability floor
    origin = CountedOrigin()
    m = FleetMembership.create(["me", "ghost1", "ghost2"])
    tracer = trace.Tracer(enabled=True)
    with FleetCache("me", m, origin=origin) as fc:
        ranges = [(i * 4096, 512) for i in range(24)]
        with trace.using(tracer):
            got = fc.read_through(KEY, ranges, lambda rs: origin(KEY, rs))
        assert [bytes(b) for b in got] == [content(o, n)
                                           for (o, n) in ranges]
    c = tracer.counters()
    assert c.get("serve.fleet_peer_fallbacks", 0) >= 1
    assert c.get("serve.fleet_served") == len(ranges)


def test_node_must_be_member():
    with pytest.raises(ValueError):
        FleetCache(  # floorlint: disable=FL-RES001 — ctor raises
            "stranger", FleetMembership.create(["a", "b"]))


def test_membership_epoch_cannot_regress():
    m = FleetMembership.create(["a", "b"], epoch=5)
    with FleetCache("a", m) as fc:
        with pytest.raises(ValueError):
            fc.install_membership(
                FleetMembership.create(["a", "b"], epoch=4))


def test_serve_range_fences_stale_epoch():
    origin = CountedOrigin()
    m = FleetMembership.create(["a"], epoch=7)
    tracer = trace.Tracer(enabled=True)
    with FleetCache("a", m, origin=origin) as fc:
        with trace.using(tracer):
            status, data = fc.serve_range(KEY, 0, 512, epoch=6)
            assert (status, data) == ("stale_epoch", None)
            assert fc.put_remote(KEY, 0, b"x" * 512, epoch=6) \
                == "stale_epoch"
            status, data = fc.serve_range(KEY, 0, 512, epoch=7)
        assert status == "ok" and data == content(0, 512)
    assert tracer.counters().get("serve.fleet_epoch_fenced") == 2
    assert origin.total() == 1


# ---------------------------------------------------------------------------
# the wire: daemons as peers


@pytest.fixture()
def fleet3():
    """Three daemons over one counted origin, membership installed."""
    origin = CountedOrigin()
    node_ids = ["n0", "n1", "n2"]
    membership = FleetMembership.create(node_ids)
    servings, fleets, daemons = [], [], []
    try:
        for nid in node_ids:
            srv = Serving(prefetch_bytes=4 << 20)
            fc = FleetCache(nid, membership, origin=origin,
                            peer_timeout_s=1.0, breaker_threshold=2,
                            breaker_cooldown_s=0.15)
            d = ServeDaemon(srv, {}, fleet=fc, max_inflight=4,
                            max_pending=32, drain_timeout_s=3.0)
            d.start()
            servings.append(srv)
            fleets.append(fc)
            daemons.append(d)
        peers = {nid: ("127.0.0.1", d.port)
                 for nid, d in zip(node_ids, daemons)}
        for fc in fleets:
            fc.install_membership(membership, peers)
        yield origin, fleets, daemons, peers
    finally:
        for d in daemons:
            d.close()
        for fc in fleets:
            fc.close()
        for srv in servings:
            srv.close()


def test_fleet_exactly_once_and_peer_hits(fleet3):
    origin, fleets, daemons, _ = fleet3
    ranges = [(i * 4096, 768) for i in range(24)]
    tracer = trace.Tracer(enabled=True)
    for fc in fleets:
        with trace.using(tracer):
            got = fc.read_through(KEY, ranges, lambda rs: origin(KEY, rs))
        assert [bytes(b) for b in got] == [content(o, n)
                                           for (o, n) in ranges]
    with origin.lock:
        assert all(c == 1 for c in origin.counts.values()), origin.counts
    assert tracer.counters().get("serve.fleet_peer_hits", 0) >= 1


def test_dead_owner_degrades_to_origin(fleet3):
    origin, fleets, daemons, _ = fleet3
    ranges = [(i * 4096, 768) for i in range(24)]
    # kill n2 BEFORE any traffic: every n2-primary range must be
    # answered via origin fallback, correctly, with no exception
    daemons[2].close()
    fleets[2].close()
    tracer = trace.Tracer(enabled=True)
    with trace.using(tracer):
        got = fleets[0].read_through(KEY, ranges,
                                     lambda rs: origin(KEY, rs))
    assert [bytes(b) for b in got] == [content(o, n)
                                       for (o, n) in ranges]
    c = tracer.counters()
    assert c.get("serve.fleet_peer_fallbacks", 0) >= 1
    assert c.get("serve.fleet_peer_errors", 0) >= 1


def test_breaker_trips_then_recovers(fleet3):
    origin, fleets, daemons, peers = fleet3
    # pick a range whose PRIMARY is n1, asked from n0
    target = None
    for i in range(200):
        o = (1 << 20) + i * 4096
        dk = _digest(KEY, o, 768)
        if fleets[0].membership.owners(dk[0], dk[1])[0] == "n1":
            target = (o, 768)
            break
    assert target is not None
    daemons[1].close()
    fleets[1].close()
    tracer = trace.Tracer(enabled=True)
    with trace.using(tracer):
        # threshold=2 and two attempts per fetch: the FIRST read trips
        # the breaker; the second must not even dial (fast-fail)
        fleets[0].read_through(KEY, [target], lambda rs: origin(KEY, rs))
        errors_after_first = tracer.counters().get(
            "serve.fleet_peer_errors", 0)
        assert errors_after_first >= 1
        o2 = (target[0] + 4096, 768)
        dk2 = _digest(KEY, o2[0], o2[1])
        if fleets[0].membership.owners(dk2[0], dk2[1])[0] == "n1":
            fleets[0].read_through(KEY, [o2],
                                   lambda rs: origin(KEY, rs))
    assert tracer.counters().get("io.remote.breaker_trips", 0) >= 1
    # half-open recovery: bring a NEW daemon up on n1's slot and wait
    # out the cooldown — the breaker must admit the probe and close
    srv = Serving(prefetch_bytes=4 << 20)
    fc1 = FleetCache("n1", fleets[0].membership, origin=origin,
                     peer_timeout_s=1.0)
    d1 = ServeDaemon(srv, {}, fleet=fc1, max_inflight=2,
                     max_pending=8)
    d1.start()
    try:
        fc1.install_membership(
            fleets[0].membership,
            {**peers, "n1": ("127.0.0.1", d1.port)})
        fleets[0].install_membership(
            fleets[0].membership,
            {**peers, "n1": ("127.0.0.1", d1.port)})
        time.sleep(0.2)  # past breaker_cooldown_s=0.15
        tracer2 = trace.Tracer(enabled=True)
        with trace.using(tracer2):
            got = fleets[0].read_through(
                KEY, [target], lambda rs: origin(KEY, rs))
        # target is cached on n0 from the fallback read — use a fresh
        # n1-primary range to force the peer leg
        fresh = None
        for i in range(200):
            o = (1 << 24) + i * 4096
            dk = _digest(KEY, o, 768)
            if fleets[0].membership.owners(dk[0], dk[1])[0] == "n1":
                fresh = (o, 768)
                break
        with trace.using(tracer2):
            got = fleets[0].read_through(
                KEY, [fresh], lambda rs: origin(KEY, rs))
        assert bytes(got[0]) == content(*fresh)
        assert tracer2.counters().get("serve.fleet_peer_hits", 0) >= 1
    finally:
        d1.close()
        fc1.close()
        srv.close()


def test_stale_owner_is_fenced_over_the_wire(fleet3):
    origin, fleets, daemons, peers = fleet3
    # n0 and n1 move to epoch 2; n2 stays stale.  A STALE OWNER must
    # be refused (fenced) — and the fresh asker must degrade to
    # origin, correctly.
    survivors = fleets[0].membership.without("n2")
    new_peers = dict(peers)
    for fc in fleets[:2]:
        fc.install_membership(survivors, new_peers)
    # the stale node asks a fresh one: fenced
    with PeerClient("127.0.0.1", daemons[0].port) as probe:
        reply = probe.fetch(KEY, 0, 512, epoch=1)
    assert not reply.get("ok") and reply.get("code") == "stale_epoch"
    assert reply.get("epoch") == survivors.epoch
    # a fresh node asking the stale one is ALSO fenced — and falls
    # back to origin with the right bytes
    tracer = trace.Tracer(enabled=True)
    target = None
    for i in range(300):
        o = (1 << 21) + i * 4096
        dk = _digest(KEY, o, 512)
        if survivors.owners(dk[0], dk[1])[0] == "n2":
            target = (o, 512)
            break
    if target is not None:
        # n2 left the membership, so no peer entry — exercised via the
        # absent-peer fallback; the fence law over the wire is the
        # probe above
        with trace.using(tracer):
            got = fleets[0].read_through(
                KEY, [target], lambda rs: origin(KEY, rs))
        assert bytes(got[0]) == content(*target)


def test_drain_waits_for_inflight_peer_fetch():
    # composition: drain() with a peer fetch mid-flight on the pool
    # must wait it out and report a CLEAN drain — the fetch completes
    # with the right bytes, not an error
    origin = CountedOrigin(delay_s=0.3)
    m = FleetMembership.create(["a"])
    srv = Serving(prefetch_bytes=4 << 20)
    fc = FleetCache("a", m, origin=origin)
    d = ServeDaemon(srv, {}, fleet=fc, max_inflight=2, max_pending=8,
                    drain_timeout_s=5.0)
    d.start()
    try:
        result = {}

        def fetchit():
            with PeerClient("127.0.0.1", d.port, timeout_s=5.0) as pc:
                result["reply"] = pc.fetch(KEY, 0, 512, epoch=m.epoch)

        t = threading.Thread(target=fetchit)
        t.start()
        time.sleep(0.1)  # let the fetch land on the pool
        clean = d.drain()
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert clean is True
        assert result["reply"].get("ok")
        assert result["reply"]["data"] == content(0, 512)
        # post-drain fetches are refused with "draining"
        with PeerClient("127.0.0.1", d.port) as pc2:
            with pytest.raises(OSError):
                # the listener is closed — new connections fail
                pc2.fetch(KEY, 4096, 512, epoch=m.epoch)
    finally:
        d.close()
        fc.close()
        srv.close()


def test_overload_pushback_composes_with_peer_fallback():
    # composition: a daemon at max_pending refuses a peer with
    # `overloaded` (+retry_after_ms), and the ASKER degrades that
    # refusal to an origin fallback — never an error, never a queue
    origin = CountedOrigin(delay_s=0.25)
    m = FleetMembership.create(["busy", "asker"])
    srv = Serving(prefetch_bytes=4 << 20)
    fc = FleetCache("busy", m, origin=origin)
    d = ServeDaemon(srv, {}, fleet=fc, max_inflight=1, max_pending=1,
                    drain_timeout_s=3.0)
    d.start()
    try:
        # find two busy-primary ranges
        targets = []
        for i in range(400):
            o = i * 4096
            dk = _digest(KEY, o, 512)
            if m.owners(dk[0], dk[1])[0] == "busy" and len(targets) < 2:
                targets.append((o, 512))
        assert len(targets) == 2
        # occupy the single pending slot with a slow direct fetch
        blocker_reply = {}

        def blocker():
            with PeerClient("127.0.0.1", d.port, timeout_s=5.0) as pc:
                blocker_reply["r"] = pc.fetch(
                    KEY, targets[0][0], targets[0][1], epoch=m.epoch)

        t = threading.Thread(target=blocker)
        t.start()
        time.sleep(0.08)
        with PeerClient("127.0.0.1", d.port) as pc2:
            reply = pc2.fetch(KEY, targets[1][0], targets[1][1],
                              epoch=m.epoch)
        assert not reply.get("ok")
        assert reply.get("code") == "overloaded"
        assert reply.get("retry_after_ms", 0) >= 1
        t.join(timeout=5.0)
        assert blocker_reply["r"].get("ok")
        # the asker-side composition: same overload, through the
        # FleetCache face — answers from origin, no exception
        asker = FleetCache("asker", m,
                           peers={"busy": ("127.0.0.1", d.port)})
        tracer = trace.Tracer(enabled=True)
        try:
            t2 = threading.Thread(target=lambda: origin(KEY, [(0, 1)]))
            blocker2 = threading.Thread(target=blocker)
            blocker2.start()
            time.sleep(0.08)
            with trace.using(tracer):
                got = asker.read_through(
                    KEY, [targets[1]], lambda rs: origin(KEY, rs))
            assert bytes(got[0]) == content(*targets[1])
            blocker2.join(timeout=5.0)
            del t2
        finally:
            asker.close()
    finally:
        d.close()
        fc.close()
        srv.close()


def test_rate_limiter_rejects_before_admission():
    # composition: an over-rate tenant is rejected at the DOOR — no
    # pending slot consumed, daemon_requests untouched, fleet ops and
    # the connection unaffected
    srv = Serving(prefetch_bytes=4 << 20)
    lim = TenantRateLimiter(rate_per_s=1.0, burst=1.0)
    d = ServeDaemon(srv, {}, max_inflight=2, max_pending=8,
                    rate_limiter=lim)
    d.start()
    try:
        with DaemonClient("127.0.0.1", d.port, tenant="greedy") as c:
            first = c.request("lookup", dataset="none", key=1)
            assert first.get("code") == "bad_request"  # admitted
            requests_after_first = d.tracer.counters().get(
                "serve.daemon_requests", 0)
            second = c.request("lookup", dataset="none", key=1)
            assert second.get("code") == "rate_limited"
            assert second.get("retry_after_ms", 0) >= 1
            # the rejection consumed NO admission budget
            assert d.tracer.counters().get(
                "serve.daemon_requests", 0) == requests_after_first
            assert c.ping()
        # the rejection was attributed to the tenant's tracer
        greedy = srv.tenant("greedy")
        assert greedy.tracer.counters().get(
            "serve.ratelimit_rejected", 0) >= 1
    finally:
        d.close()
        srv.close()


def test_replication_pushes_hot_range_to_replica(fleet3):
    origin, fleets, daemons, _ = fleet3
    # find an n0-primary range with n1 as replica
    target = None
    for i in range(400):
        o = (1 << 23) + i * 4096
        dk = _digest(KEY, o, 640)
        owners = fleets[0].membership.owners(dk[0], dk[1])
        if owners == ["n0", "n1"]:
            target = (o, 640)
            break
    assert target is not None
    tracer = trace.Tracer(enabled=True)
    with trace.using(tracer):
        # replicate_after=2: two primary serves push to the replica
        fleets[0].read_through(KEY, [target], lambda rs: origin(KEY, rs))
        fleets[0]._local_get(KEY, *target)  # warm check only
        # second HEAT must come from a serve that reaches the heat
        # counter: peer fetch via n2
        fleets[2].read_through(KEY, [target], lambda rs: origin(KEY, rs))
    deadline = time.time() + 2.0
    while time.time() < deadline:
        if fleets[1]._local_get(KEY, *target) is not None:
            break
        time.sleep(0.02)
    assert fleets[1]._local_get(KEY, *target) == content(*target), \
        "hot range never replicated to the next-on-ring member"
    assert origin.total() == 1  # replication moved bytes, not origin


def test_wire_carries_extent_sized_payloads(fleet3):
    # regression: the peer plane is a JSON line protocol, and a
    # replication push (fleet_put) carries the range payload base64
    # inline — asyncio's DEFAULT 64 KiB readline limit severed the
    # connection for any extent past ~48 KiB.  A 256 KiB payload must
    # round-trip both directions: put lands at the peer, and a fetch
    # answers with the same bytes on one origin read.
    origin, fleets, daemons, peers = fleet3
    big = (1 << 20, 256 << 10)  # offset, length: 4x the old limit
    payload = content(*big)
    epoch = fleets[0].membership.epoch
    with PeerClient("127.0.0.1", daemons[1].port) as probe:
        reply = probe.put(KEY, big[0], payload, epoch)
        assert reply.get("ok"), reply
        reply = probe.fetch(KEY, big[0], big[1], epoch)
    assert reply.get("ok"), reply
    assert reply["data"] == payload
    assert fleets[1]._local_get(KEY, *big) == payload
    assert origin.total() == 0  # the push seeded it; fetch was a hit
