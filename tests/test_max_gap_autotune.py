"""max_gap_bytes auto-tune (scan/plan.py, scan/executor.py):
``ScanOptions(max_gap_bytes=None)`` lets the executor derive the
coalescing gap from the adaptive controller's measured RTT x bandwidth
— a slow store widens the gap (fewer round trips buy more than the
wasted bytes cost), a local chain clamps to the static default."""

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileWriter,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.scan import DatasetScanner, ScanOptions
from parquet_floor_tpu.scan.executor import _AdaptiveController
from parquet_floor_tpu.scan.plan import DEFAULT_MAX_GAP_BYTES


@pytest.fixture(scope="module")
def path(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("autotune") / "t.parquet")
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    n = 2000
    data = {
        "k": np.arange(n, dtype=np.int64),
        "s": [None if i % 7 == 0 else f"v{i % 37}" for i in range(n)],
    }
    opts = WriterOptions(row_group_rows=500, data_page_values=200)
    with ParquetFileWriter(p, schema, opts) as w:
        for lo in range(0, n, 500):
            w.write_columns({k: v[lo:lo + 500]
                             for k, v in data.items()})
    return p


def test_options_accept_none_gap():
    sc = ScanOptions(max_gap_bytes=None)
    assert sc.max_gap_bytes is None
    with pytest.raises(ValueError):
        ScanOptions(max_gap_bytes=-1)


def test_default_gap_unchanged():
    assert ScanOptions().max_gap_bytes == DEFAULT_MAX_GAP_BYTES


def test_controller_learns_bandwidth():
    ctl = _AdaptiveController(base_cap=8 << 20, threads=2)
    assert ctl.bandwidth_Bps() is None
    ctl.observe_load(10_000_000, 0.1)  # 100 MB/s
    assert ctl.bandwidth_Bps() == pytest.approx(1e8)
    ctl.observe_load(5_000_000, 0.1)   # 50 MB/s → EWMA pulls down
    bw = ctl.bandwidth_Bps()
    assert bw == pytest.approx(0.7 * 1e8 + 0.3 * 5e7)
    ctl.observe_load(0, 0.1)           # zero-byte loads are ignored
    assert ctl.bandwidth_Bps() == pytest.approx(bw)


def _scanner(path, sc):
    return DatasetScanner([path], scan=sc)


def test_effective_scan_defaults_without_measurements(path):
    # auto mode with no RTT/bandwidth on record resolves to the
    # static default — never a crash, never a zero gap
    with _scanner(path, ScanOptions(max_gap_bytes=None,
                                    adaptive_prefetch=True)) as s:
        eff = s._effective_scan()
        assert eff.max_gap_bytes == DEFAULT_MAX_GAP_BYTES


def test_effective_scan_widens_for_slow_store(path):
    with _scanner(path, ScanOptions(max_gap_bytes=None,
                                    adaptive_prefetch=True)) as s:
        # a 20 ms RTT at 100 MB/s: gap ≈ rtt x bw = 2 MB
        for _ in range(8):
            s._adaptive.observe_load(2_000_000, 0.02)
        eff = s._effective_scan()
        rtt = s._adaptive.rtt_s()
        bw = s._adaptive.bandwidth_Bps()
        expect = int(min(s._scan.max_extent_bytes,
                         max(DEFAULT_MAX_GAP_BYTES, rtt * bw)))
        assert eff.max_gap_bytes == expect
        assert eff.max_gap_bytes > DEFAULT_MAX_GAP_BYTES


def test_effective_scan_clamps_to_max_extent(path):
    with _scanner(path, ScanOptions(max_gap_bytes=None,
                                    adaptive_prefetch=True,
                                    max_extent_bytes=1 << 20)) as s:
        # absurd rtt x bw must clamp at max_extent_bytes — an extent
        # can never be wider than the extent ceiling itself
        for _ in range(8):
            s._adaptive.observe_load(100_000_000, 1.0)  # 100 MB/s, 1 s RTT
        assert s._effective_scan().max_gap_bytes == 1 << 20


def test_fast_local_chain_keeps_default(path):
    with _scanner(path, ScanOptions(max_gap_bytes=None,
                                    adaptive_prefetch=True)) as s:
        # 0.5 ms loads at disk speed: rtt x bw « 64 KiB → floor holds
        for _ in range(8):
            s._adaptive.observe_load(64 << 10, 0.0005)
        assert s._effective_scan().max_gap_bytes == DEFAULT_MAX_GAP_BYTES


def test_autotune_decision_emitted_once(path):
    tracer = trace.Tracer(enabled=True)
    with _scanner(path, ScanOptions(max_gap_bytes=None,
                                    adaptive_prefetch=True)) as s:
        with trace.using(tracer):
            s._effective_scan()
            s._effective_scan()  # same gap → deduped
        hits = [d for d in tracer.decisions()
                if d["decision"] == "scan.max_gap_autotuned"]
        assert len(hits) == 1
        assert hits[0]["gap_bytes"] == DEFAULT_MAX_GAP_BYTES
        # a gap CHANGE re-emits
        for _ in range(8):
            s._adaptive.observe_load(2_000_000, 0.02)
        with trace.using(tracer):
            s._effective_scan()
        hits = [d for d in tracer.decisions()
                if d["decision"] == "scan.max_gap_autotuned"]
        assert len(hits) == 2


def test_scan_with_auto_gap_matches_explicit(path):
    # end to end: auto mode decodes the same rows as the static default
    with _scanner(path, ScanOptions(max_gap_bytes=None)) as s:
        auto = [u.batch.num_rows for u in s]
    with _scanner(path, ScanOptions()) as s:
        fixed = [u.batch.num_rows for u in s]
    assert auto == fixed and sum(auto) == 2000
