"""Device decode primitives vs the NumPy reference codecs (SURVEY.md §4:
"kernel-vs-NumPy-reference equivalence tests")."""

import numpy as np
import pytest

import jax.numpy as jnp

from parquet_floor_tpu.format.encodings import rle_hybrid as rle
from parquet_floor_tpu.format.encodings import delta as e_delta
from parquet_floor_tpu.tpu import bitops

rng = np.random.default_rng(13)


def _pad8(b: bytes) -> jnp.ndarray:
    return jnp.asarray(np.frombuffer(b + b"\x00" * 8, dtype=np.uint8))


@pytest.mark.parametrize("bw", [1, 2, 3, 7, 8, 13, 17, 24, 31])
def test_bit_unpack_matches_numpy(bw):
    n = 1024
    vals = rng.integers(0, 1 << bw, n, dtype=np.uint64)
    packed = rle.bit_pack(vals, bw)
    out = bitops.bit_unpack(_pad8(packed), bw, n)
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.int32))


@pytest.mark.parametrize("bw", [1, 5, 12, 20, 32])
def test_extract_bits_matches_numpy(bw):
    n = 777
    vals = rng.integers(0, 1 << bw, n, dtype=np.uint64)
    packed = rle.bit_pack(np.concatenate([vals, np.zeros((-n) % 8, np.uint64)]), bw)
    bitpos = jnp.arange(n, dtype=jnp.int32) * bw
    out = bitops.extract_bits(_pad8(packed), bitpos, bw)
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.uint32))


@pytest.mark.parametrize("bw", [1, 3, 9, 20])
def test_rle_expand_matches_numpy(bw):
    n = 4000
    # mix of long runs and noise → both run kinds
    vals = rng.integers(0, 1 << bw, n, dtype=np.uint32)
    vals[500:2500] = 5 % (1 << bw)
    data = rle.encode_rle_hybrid(vals, bw)
    table, _ = rle.parse_runs(data, n, bw)
    plan = bitops.run_table_to_device_plan(table, n, bitops.bucket_size(len(table), 16))
    out = bitops.rle_expand(
        _pad8(data),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        n,
        bw,
    )
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.int32))


def test_dense_scatter():
    present = np.array([1, 0, 1, 1, 0, 0, 1], dtype=bool)
    values = np.array([10.0, 20.0, 30.0, 40.0])
    out = bitops.dense_scatter(jnp.asarray(values), jnp.asarray(present))
    np.testing.assert_array_equal(
        np.asarray(out), [10.0, 0, 20.0, 30.0, 0, 0, 40.0]
    )


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32, np.float64])
def test_bitcast_bytes(dtype):
    n = 256
    if np.issubdtype(dtype, np.integer):
        vals = rng.integers(np.iinfo(dtype).min, np.iinfo(dtype).max, n).astype(dtype)
    else:
        vals = rng.standard_normal(n).astype(dtype)
    out = bitops.bitcast_bytes(
        jnp.asarray(np.frombuffer(vals.tobytes(), np.uint8)), dtype, n
    )
    np.testing.assert_array_equal(np.asarray(out), vals)


def test_unpack_bools():
    n = 1003
    vals = rng.integers(0, 2, n).astype(bool)
    packed = np.packbits(vals, bitorder="little")
    out = bitops.unpack_bools(jnp.asarray(packed), n)
    np.testing.assert_array_equal(np.asarray(out), vals)


def test_delta_expand_matches_numpy():
    n = 1000
    vals = np.cumsum(rng.integers(-50, 50, n)).astype(np.int32)
    data = e_delta.encode_delta_binary_packed(vals)
    ref, _ = e_delta.decode_delta_binary_packed(data, out_dtype=np.int32)
    np.testing.assert_array_equal(ref, vals)

    # host-side header parse mirrors the engine's plan builder
    from parquet_floor_tpu.tpu.engine import parse_delta_plan

    plan = parse_delta_plan(np.frombuffer(data, np.uint8), np.int32)
    assert plan is not None
    out = bitops.delta_expand(
        _pad8(data),
        jnp.asarray(plan["mb_bytebase"]),
        jnp.asarray(plan["mb_bw"]),
        jnp.asarray(plan["mb_min_delta"]),
        plan["first_value"],
        n,
        plan["values_per_miniblock"],
    )
    np.testing.assert_array_equal(np.asarray(out), vals)


def test_plan_offsets_beyond_256mib():
    """Plans carry byte offsets (int32 to 2 GiB): a run based past the old
    256 MiB bit-offset ceiling must survive both plan builders intact."""
    off = 1_500_000_000  # ~1.4 GiB: *8 would overflow int32
    table = np.array([[1, 64, off]], dtype=np.int64)  # bit-packed, 64 values
    plan = bitops.run_table_to_device_plan(table, 64, 4)
    assert plan["run_bytebase"][0] == off
    flat = bitops.tables_to_plan5([(table, 7)], 64, 4)
    assert flat.reshape(5, 4)[3, 0] == off
