"""Device decode primitives vs the NumPy reference codecs (SURVEY.md §4:
"kernel-vs-NumPy-reference equivalence tests")."""

import numpy as np
import pytest

import jax.numpy as jnp

from parquet_floor_tpu.format.encodings import rle_hybrid as rle
from parquet_floor_tpu.format.encodings import delta as e_delta
from parquet_floor_tpu.tpu import bitops

rng = np.random.default_rng(13)


def _pad8(b: bytes) -> jnp.ndarray:
    return jnp.asarray(np.frombuffer(b + b"\x00" * 8, dtype=np.uint8))


@pytest.mark.parametrize("bw", [1, 2, 3, 7, 8, 13, 17, 24, 31])
def test_bit_unpack_matches_numpy(bw):
    n = 1024
    vals = rng.integers(0, 1 << bw, n, dtype=np.uint64)
    packed = rle.bit_pack(vals, bw)
    out = bitops.bit_unpack(_pad8(packed), bw, n)
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.int32))


@pytest.mark.parametrize("bw", [1, 5, 12, 20, 32])
def test_extract_bits_matches_numpy(bw):
    n = 777
    vals = rng.integers(0, 1 << bw, n, dtype=np.uint64)
    packed = rle.bit_pack(np.concatenate([vals, np.zeros((-n) % 8, np.uint64)]), bw)
    bitpos = jnp.arange(n, dtype=jnp.int32) * bw
    out = bitops.extract_bits(_pad8(packed), bitpos, bw)
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.uint32))


@pytest.mark.parametrize("bw", [1, 3, 9, 20])
def test_rle_expand_matches_numpy(bw):
    n = 4000
    # mix of long runs and noise → both run kinds
    vals = rng.integers(0, 1 << bw, n, dtype=np.uint32)
    vals[500:2500] = 5 % (1 << bw)
    data = rle.encode_rle_hybrid(vals, bw)
    table, _ = rle.parse_runs(data, n, bw)
    plan = bitops.run_table_to_device_plan(table, n, bitops.bucket_size(len(table), 16))
    out = bitops.rle_expand(
        _pad8(data),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        n,
        bw,
    )
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.int32))


def test_dense_scatter():
    present = np.array([1, 0, 1, 1, 0, 0, 1], dtype=bool)
    values = np.array([10.0, 20.0, 30.0, 40.0])
    out = bitops.dense_scatter(jnp.asarray(values), jnp.asarray(present))
    np.testing.assert_array_equal(
        np.asarray(out), [10.0, 0, 20.0, 30.0, 0, 0, 40.0]
    )


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32, np.float64])
def test_bitcast_bytes(dtype):
    n = 256
    if np.issubdtype(dtype, np.integer):
        vals = rng.integers(np.iinfo(dtype).min, np.iinfo(dtype).max, n).astype(dtype)
    else:
        vals = rng.standard_normal(n).astype(dtype)
    out = bitops.bitcast_bytes(
        jnp.asarray(np.frombuffer(vals.tobytes(), np.uint8)), dtype, n
    )
    np.testing.assert_array_equal(np.asarray(out), vals)


def test_unpack_bools():
    n = 1003
    vals = rng.integers(0, 2, n).astype(bool)
    packed = np.packbits(vals, bitorder="little")
    out = bitops.unpack_bools(jnp.asarray(packed), n)
    np.testing.assert_array_equal(np.asarray(out), vals)


def test_delta_expand_matches_numpy():
    n = 1000
    vals = np.cumsum(rng.integers(-50, 50, n)).astype(np.int32)
    data = e_delta.encode_delta_binary_packed(vals)
    ref, _ = e_delta.decode_delta_binary_packed(data, out_dtype=np.int32)
    np.testing.assert_array_equal(ref, vals)

    # host-side header parse mirrors the engine's plan builder
    from parquet_floor_tpu.tpu.engine import parse_delta_plan

    plan = parse_delta_plan(np.frombuffer(data, np.uint8), np.int32)
    assert plan is not None
    out = bitops.delta_expand(
        _pad8(data),
        jnp.asarray(plan["mb_bytebase"]),
        jnp.asarray(plan["mb_bw"]),
        jnp.asarray(plan["mb_min_delta"]),
        plan["first_value"],
        n,
        plan["values_per_miniblock"],
    )
    np.testing.assert_array_equal(np.asarray(out), vals)


def test_plan_offsets_beyond_256mib():
    """Plans carry byte offsets (int32 to 2 GiB): a run based past the old
    256 MiB bit-offset ceiling must survive both plan builders intact."""
    off = 1_500_000_000  # ~1.4 GiB: *8 would overflow int32
    table = np.array([[1, 64, off]], dtype=np.int64)  # bit-packed, 64 values
    plan = bitops.run_table_to_device_plan(table, 64, 4)
    assert plan["run_bytebase"][0] == off
    flat = bitops.tables_to_plan5([(table, 7)], 64, 4)
    assert flat.reshape(5, 4)[3, 0] == off


def test_plan5_native_matches_fallback():
    """plan5_from_streams: native one-pass plan must be byte-identical to
    the table-based Python fallback, including synthetic bw-0 streams."""
    import unittest.mock as mock

    from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
    from parquet_floor_tpu.native import binding as nb

    if not nb.available():
        pytest.skip("native library not built")
    r = np.random.default_rng(9)
    buf = bytearray()
    streams = []
    total = 0
    for bw, n in [(3, 700), (13, 2048), (1, 50), (0, 33), (24, 999)]:
        if bw == 0:
            streams.append((0, n, 0))
            total += n
            continue
        vals = r.integers(0, 1 << bw, n).astype(np.uint32)
        vals[5:40] = 2  # carve an RLE run
        enc = e_rle.encode_rle_hybrid(vals, bw)
        streams.append((len(buf), n, bw))
        buf.extend(enc)
        total += n
    data = np.frombuffer(bytes(buf) + b"\0" * 8, np.uint8)
    pad = 4096
    got, gr = bitops.plan5_from_streams(data, streams, total, pad)
    with mock.patch.object(nb, "available", lambda: False):
        want, wr = bitops.plan5_from_streams(data, streams, total, pad)
    assert gr == wr
    np.testing.assert_array_equal(got, want)


def test_plan5_errors():
    from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
    from parquet_floor_tpu.native import binding as nb

    if not nb.available():
        pytest.skip("native library not built")
    vals = (np.arange(5000) % 97).astype(np.uint32)
    enc = e_rle.encode_rle_hybrid(vals, 7)
    data = np.frombuffer(bytes(enc) + b"\0" * 8, np.uint8)
    # pad too small: exact needed count reported, one retry suffices
    with pytest.raises(bitops.PlanPadExceeded) as ei:
        bitops.plan5_from_streams(data, [(0, 5000, 7)], 5000, 4)
    needed = ei.value.needed
    plan, r = bitops.plan5_from_streams(data, [(0, 5000, 7)], 5000, needed)
    assert r == needed
    # counts that don't sum to total
    with pytest.raises(ValueError, match="sum"):
        bitops.plan5_from_streams(data, [(0, 5000, 7)], 4999, needed)
    # malformed stream
    with pytest.raises(ValueError):
        bitops.plan5_from_streams(
            np.frombuffer(b"\xff" * 4, np.uint8), [(0, 100, 7)], 100, 64
        )
