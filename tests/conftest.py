"""Test env: force the CPU backend with an 8-device virtual mesh so
multi-chip sharding tests run without TPU hardware (SURVEY.md §4).

jax is preimported at interpreter startup in this image and the shell env
pins JAX_PLATFORMS to the TPU plugin, so plain env-var setting is too late —
configure through jax.config before any backend initializes instead.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
