"""The batch face of the Hydrator boundary: ``stream_batches`` +
``BatchColumn`` export (DLPack / Arrow) must agree cell-for-cell with the
row face on both engines (VERDICT r3 #2; SURVEY.md §7 L3 "zero-copy
batch/Arrow-style access")."""

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileWriter,
    ParquetReader,
    WriterOptions,
    batch_to_arrow,
    col,
    types,
)
from parquet_floor_tpu.api.hydrate import FnBatchHydrator

ENGINES = ("host", "tpu")


def _write_mixed(path, n=5000, groups=3):
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.DOUBLE).named("d"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.BOOLEAN).named("b"),
    )
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY,
        row_group_rows=(n + groups - 1) // groups,
        enable_dictionary=True,
    )
    rng = np.random.default_rng(7)
    data = {
        "k": np.arange(n, dtype=np.int64),
        "d": [None if i % 11 == 0 else float(v)
              for i, v in enumerate(rng.standard_normal(n))],
        "s": [None if i % 7 == 0 else f"v{i % 30}" for i in range(n)],
        "b": [bool(i % 3 == 0) for i in range(n)],
    }
    per = (n + groups - 1) // groups
    with ParquetFileWriter(path, schema, opts) as w:
        for lo in range(0, n, per):
            hi = min(lo + per, n)
            w.write_columns({
                k: (v[lo:hi] if isinstance(v, list) else v[lo:hi])
                for k, v in data.items()
            })
    return str(path), data


class _RowTuples:
    def start(self):
        return []

    def add(self, t, h, v):
        t.append(v)
        return t

    def finish(self, t):
        return tuple(t)


def _rows_from_batch(cols):
    """Rebuild API-equivalent row tuples from one group's BatchColumns."""
    out_cols = []
    for c in cols:
        if c.is_strings:
            cells = c.bytes_list()
            stringify = c.descriptor.primitive.stringify
            cells = [stringify(b) for b in cells]
        else:
            v = c.to_numpy()
            if v.ndim == 2:
                stringify = c.descriptor.primitive.stringify
                cells = [stringify(v[i].tobytes()) for i in range(len(v))]
            else:
                cells = v.tolist()
        if c.mask is not None:
            m = np.asarray(c.mask)
            cells = [None if m[i] else cells[i] for i in range(len(cells))]
        out_cols.append(cells)
    return list(zip(*out_cols))


@pytest.mark.parametrize("engine", ENGINES)
def test_batch_face_agrees_with_row_face(tmp_path, engine):
    path, _ = _write_mixed(tmp_path / "m.parquet")
    rows = list(ParquetReader.stream_content(
        path, lambda c: _RowTuples(), engine=engine
    ))
    batch_rows = []
    for cols in ParquetReader.stream_batches(path, engine=engine):
        batch_rows.extend(_rows_from_batch(cols))
    assert batch_rows == rows


@pytest.mark.parametrize("engine", ENGINES)
def test_batch_to_arrow_matches_pyarrow(tmp_path, engine):
    path, data = _write_mixed(tmp_path / "a.parquet")
    oracle = pq.read_table(path)
    got = {"k": [], "d": [], "s": [], "b": []}
    for cols in ParquetReader.stream_batches(path, engine=engine):
        rb = batch_to_arrow(cols)
        assert rb.schema.names == ["k", "d", "s", "b"]
        for nm in got:
            got[nm].extend(rb.column(nm).to_pylist())
    assert got["k"] == oracle.column("k").to_pylist()
    assert got["b"] == oracle.column("b").to_pylist()
    assert got["d"] == oracle.column("d").to_pylist()
    exp_s = [
        None if v is None else v.encode()
        for v in oracle.column("s").to_pylist()
    ]
    assert got["s"] == exp_s


def test_ordering_contract_and_projection(tmp_path):
    """Columns arrive in the order the supplier saw (the
    HydratorSupplier.java:10-15 contract at batch granularity), under
    projection."""
    path, data = _write_mixed(tmp_path / "p.parquet")
    seen = {}

    def supplier(columns):
        seen["paths"] = [c.path[0] for c in columns]
        return FnBatchHydrator(
            lambda gi, cols: [c.descriptor.path[0] for c in cols]
        )

    orders = list(ParquetReader.stream_batches(
        path, supplier, columns=["s", "k"]
    ))
    assert seen["paths"] == ["k", "s"]  # schema order, projected
    assert all(o == ["k", "s"] for o in orders)


def test_predicate_keeps_real_group_indices(tmp_path):
    path, data = _write_mixed(tmp_path / "q.parquet")
    idx = []
    gen = ParquetReader.stream_batches(
        path, FnBatchHydrator(lambda gi, cols: gi),
        predicate=col("k") >= 2000,
    )
    idx = list(gen)
    assert idx and 0 not in idx  # first group (k < 1667) pruned


def test_dlpack_and_f64_bits(tmp_path):
    path, data = _write_mixed(tmp_path / "z.parquet")
    host_d = []
    for cols in ParquetReader.stream_batches(path, engine="host"):
        k = cols[0]
        arr = np.from_dlpack(k)  # zero-copy DLPack export
        np.testing.assert_array_equal(arr, np.asarray(k.values))
        host_d.append(cols[1].to_numpy())
    tpu_d = []
    for cols in ParquetReader.stream_batches(path, engine="tpu"):
        d = cols[1]
        assert d.f64_bits and np.asarray(d.values).dtype == np.int64
        tpu_d.append(d.to_numpy())  # bit-form views back to float64
        assert d.to_numpy().dtype == np.float64
    for h, t in zip(host_d, tpu_d):
        np.testing.assert_array_equal(
            h[~np.isnan(h)], t[~np.isnan(t)]
        )


def test_repeated_leaf_through_batches(tmp_path):
    """Repeated leaves surface the dense value stream + Dremel levels."""
    from parquet_floor_tpu import ParquetFileReader, assemble_nested
    from parquet_floor_tpu.batch.columns import ColumnBatch

    t = types
    schema = t.message(
        "m", t.list_of(t.required(t.INT64).named("element"), "v")
    )
    path = str(tmp_path / "n.parquet")
    rows = [[1, 2], [], [3], [4, 5, 6]]
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"v": rows})
    with ParquetFileReader(path) as r:
        sch = r.schema
    for engine in ENGINES:
        for cols in ParquetReader.stream_batches(path, engine=engine):
            (c,) = cols
            assert c.rep_levels is not None
            defs = np.asarray(c.def_levels).astype(np.uint32)
            reps = np.asarray(c.rep_levels).astype(np.uint32)
            nn = int(np.count_nonzero(defs == c.descriptor.max_definition_level))
            vals = np.asarray(c.values)[:nn]
            cb = ColumnBatch(c.descriptor, len(defs), vals, defs, reps)
            assert assemble_nested(sch, cb).to_pylist() == rows, engine


def test_dataset_batches(tmp_path):
    """A list of sources streams batches file after file (supplier
    called once, per-file real group indices, schema enforcement)."""
    p1, d1 = _write_mixed(tmp_path / "d1.parquet", n=2000, groups=2)
    p2, d2 = _write_mixed(tmp_path / "d2.parquet", n=1500, groups=2)
    calls = []

    def supplier(columns):
        calls.append([c.path[0] for c in columns])
        return FnBatchHydrator(
            lambda gi, cols: (gi, int(np.asarray(cols[0].values).shape[0]))
        )

    out = list(ParquetReader.stream_batches([p1, p2], supplier))
    assert len(calls) == 1  # ONE hydrator for the whole dataset
    assert [gi for gi, _ in out] == [0, 1, 0, 1]  # per-file indices
    assert sum(n for _, n in out) == 3500
    # schema drift at a file boundary fails loudly
    other = str(tmp_path / "odd.parquet")
    schema = types.message("t", types.required(types.INT32).named("k"))
    with ParquetFileWriter(other, schema) as w:
        w.write_columns({"k": [1, 2]})
    with pytest.raises(ValueError, match="disagrees"):
        list(ParquetReader.stream_batches([p1, other]))
    with pytest.raises(ValueError, match="at least one source"):
        ParquetReader.stream_batches([])


def test_batch_stream_closes_on_generator_close(tmp_path):
    path, _ = _write_mixed(tmp_path / "c.parquet")
    gen = ParquetReader.stream_batches(path)
    next(gen)
    gen.close()  # must not leak the file (ResourceWarning would fire)
    # closing BEFORE first iteration never opens the file (lazy open)
    gen2 = ParquetReader.stream_batches(path)
    gen2.close()
    # errors surface at first next(), not at call time
    gen3 = ParquetReader.stream_batches(str(tmp_path / "missing.parquet"))
    with pytest.raises(FileNotFoundError):
        next(gen3)


def test_batch_supplier_of_wraps_plain_callable_factory():
    """ADVICE r4: a factory returning a per-batch FUNCTION (the exact
    shape FnBatchHydrator exists for) is wrapped, not surfaced later as
    an opaque AttributeError; a factory returning junk fails with a
    TypeError naming both accepted callable shapes."""
    import pytest

    from parquet_floor_tpu.api.hydrate import (
        BatchHydrator,
        batch_supplier_of,
    )

    seen = []

    def factory(columns):
        def per_batch(gi, cols):
            seen.append((gi, len(cols)))
            return gi
        return per_batch

    sup = batch_supplier_of(factory)
    hyd = sup.get([])
    assert isinstance(hyd, BatchHydrator)
    assert hyd.batch(3, ["a", "b"]) == 3
    assert seen == [(3, 2)]

    bad = batch_supplier_of(lambda columns: 42)
    with pytest.raises(TypeError, match="BatchHydrator"):
        bad.get([])


def test_supplier_of_duck_typing_and_validation():
    """Duck-typed hydrators (no ABC) pass through BOTH faces; a
    duck-typed .batch object that is ALSO callable is used via .batch,
    not mis-wrapped; a row-face factory returning junk fails at get()
    with the accepted shape named."""
    import pytest

    from parquet_floor_tpu.api.hydrate import batch_supplier_of, supplier_of

    class DuckBatch:  # has .batch AND __call__ — .batch must win
        def __call__(self, *a):
            raise AssertionError("__call__ must not be used")

        def batch(self, gi, cols):
            return ("batch", gi)

    duck = DuckBatch()
    assert batch_supplier_of(lambda cols: duck).get([]) is duck

    class DuckRow:  # start/add/finish, no ABC
        def start(self):
            return []

        def add(self, t, h, v):
            return t

        def finish(self, t):
            return tuple(t)

    row = DuckRow()
    assert supplier_of(lambda cols: row).get([]) is row
    with pytest.raises(TypeError, match="start\\(\\)/add\\(\\)/finish\\(\\)"):
        supplier_of(lambda cols: 42).get([])
