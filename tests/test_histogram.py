"""LogHistogram + Tracer.observe: accuracy vs numpy, the merge law,
windows, the disabled-mode zero-cost pin, and the xplane clock-rebase
math (docs/observability.md)."""

import gc
import json
import sys
import threading

import numpy as np
import pytest

from parquet_floor_tpu.utils import trace
from parquet_floor_tpu.utils.histogram import GROWTH, LogHistogram
from parquet_floor_tpu.utils.trace import ScanReport, Tracer


# --- percentile accuracy vs numpy -------------------------------------------

@pytest.mark.parametrize("seed,dist", [
    (7, lambda rng, n: rng.lognormal(-6, 1.2, n)),     # latency-shaped
    (11, lambda rng, n: rng.exponential(0.01, n)),
    (13, lambda rng, n: rng.uniform(1e-5, 2.0, n)),
])
def test_percentile_tracks_numpy(seed, dist):
    rng = np.random.default_rng(seed)
    xs = dist(rng, 20_000)
    h = LogHistogram()
    for x in xs:
        h.record(float(x))
    # relative quantile error is bounded by the bucket width
    tol = h.growth - 1.0
    for p in (1, 10, 50, 90, 99, 99.9):
        want = float(np.percentile(xs, p))
        got = h.percentile(p)
        assert abs(got - want) / want <= tol, (p, got, want)
    # the extremes are exact (min/max ride along)
    assert h.percentile(0) == pytest.approx(xs.min())
    assert h.percentile(100) == pytest.approx(xs.max())
    assert h.mean == pytest.approx(xs.mean(), rel=1e-9)


def test_count_above_matches_numpy():
    rng = np.random.default_rng(3)
    xs = rng.lognormal(-6, 1.0, 10_000)
    h = LogHistogram()
    for x in xs:
        h.record(float(x))
    for q in (50, 90, 99):
        t = float(np.percentile(xs, q))
        want = int((xs > t).sum())
        got = h.count_above(t)
        assert abs(got - want) <= 0.3 * want + 30, (q, got, want)
    assert h.count_above(xs.max()) == 0
    assert h.count_above(-1.0) == len(xs)


def test_zero_and_negative_values_take_the_zero_bucket():
    h = LogHistogram()
    h.record(0.0)
    h.record(-2.5)
    h.record(1.0)
    assert h.count == 3 and h.zeros == 2
    assert h.min == -2.5 and h.max == 1.0
    assert sum(h.buckets.values()) == 1
    assert h.percentile(10) <= 0.0


# --- the serialize/merge law ------------------------------------------------

def test_merge_is_associative_and_matches_single_recorder():
    rng = np.random.default_rng(17)
    xs = rng.lognormal(-5, 1.0, 9_000)
    whole = LogHistogram()
    parts = [LogHistogram() for _ in range(3)]
    for i, x in enumerate(xs):
        whole.record(float(x))
        parts[i % 3].record(float(x))
    m_left = LogHistogram.merge([LogHistogram.merge(parts[:2]), parts[2]])
    m_right = LogHistogram.merge([parts[0], LogHistogram.merge(parts[1:])])

    def strip_sum(d):
        return {k: v for k, v in d.items() if k != "sum"}

    # bucket-exact associativity; the float sum only to rounding
    assert strip_sum(m_left.as_dict()) == strip_sum(m_right.as_dict())
    assert strip_sum(m_left.as_dict()) == strip_sum(whole.as_dict())
    assert m_left.total == pytest.approx(whole.total, rel=1e-9)


def test_merge_under_concurrent_worker_observes():
    """N worker threads observe into one enabled tracer (the
    Tracer.run carry); the tracer's histogram must equal the
    single-threaded merge of the per-worker sample sets — no lost or
    double-counted samples under contention."""
    t = Tracer(enabled=True)
    per_worker = 2_000
    workers = 6
    rngs = [np.random.default_rng(100 + i) for i in range(workers)]
    samples = [r.lognormal(-6, 1.0, per_worker) for r in rngs]

    def work(i):
        for x in samples[i]:
            trace.observe("serve.lookup_seconds", float(x))

    threads = [
        threading.Thread(target=t.run, args=(work, i))
        for i in range(workers)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    expect = LogHistogram()
    for s in samples:
        for x in s:
            expect.record(float(x))
    got = t.histograms()["serve.lookup_seconds"]
    assert got.count == workers * per_worker
    assert got.buckets == expect.buckets
    assert got.total == pytest.approx(expect.total, rel=1e-6)


def test_as_dict_round_trip_and_growth_mismatch():
    h = LogHistogram()
    for v in (0.001, 0.5, 3.0, 0.0):
        h.record(v)
    rt = LogHistogram.from_dict(json.loads(json.dumps(h.as_dict())))
    assert rt.as_dict() == h.as_dict()
    other = LogHistogram(growth=2.0)
    with pytest.raises(ValueError, match="growth"):
        h.merge_in(other)


def test_subtract_is_the_window_delta():
    h = LogHistogram()
    for v in (0.001, 0.002):
        h.record(v)
    base = h.copy()
    for v in (0.5, 0.6, 0.7):
        h.record(v)
    d = h.subtract(base)
    assert d.count == 3
    assert d.total == pytest.approx(1.8)
    assert sum(d.buckets.values()) == 3
    # a reset between snapshots (count went DOWN) degrades to "all
    # new" — the whole current histogram, never a blind zero window
    fresh = LogHistogram()
    fresh.record(0.1)
    d2 = fresh.subtract(h)
    assert d2.count == 1 and d2.max == pytest.approx(0.1)
    assert not any(c < 0 for c in d2.buckets.values())


def test_scan_report_carries_and_merges_histograms():
    def tracer_with(values):
        t = Tracer(enabled=True)
        for v in values:
            t.observe("serve.lookup_seconds", v)
        return t

    r1 = tracer_with([0.001, 0.002]).scan_report()
    r2 = tracer_with([0.100, 0.200]).scan_report()
    rt = ScanReport.from_dict(json.loads(json.dumps(r1.as_dict())))
    assert rt.histogram("serve.lookup_seconds").count == 2
    merged = ScanReport.merge([r1, r2])
    h = merged.histogram("serve.lookup_seconds")
    assert h.count == 4
    assert h.max == pytest.approx(0.2)
    # pre-histogram dicts (older snapshots) still load
    legacy = r1.as_dict()
    del legacy["histograms"]
    assert ScanReport.from_dict(legacy).histograms == {}


# --- windows ----------------------------------------------------------------

def test_histogram_window_records_only_while_open():
    t = Tracer(enabled=True)
    t.observe("serve.lookup_seconds", 0.5)       # before: not in window
    w = t.histogram_window()
    t.observe("serve.lookup_seconds", 0.001)
    t.observe("serve.fair_wait_seconds", 0.002)
    got = w.close()
    t.observe("serve.lookup_seconds", 0.9)       # after close: ignored
    assert got["serve.lookup_seconds"].count == 1
    assert got["serve.fair_wait_seconds"].count == 1
    assert t.histograms()["serve.lookup_seconds"].count == 3
    assert w.close()["serve.lookup_seconds"].count == 1  # idempotent


# --- the zero-cost disabled path (the PR 4 discipline) ----------------------

class _PoisonedLock:
    def __enter__(self):
        raise AssertionError("disabled observe() acquired the lock")

    def __exit__(self, *exc):
        return False


def test_disabled_observe_no_alloc_no_lock():
    t = Tracer(enabled=False)
    t._lock = _PoisonedLock()

    def burst():
        for _ in range(100):
            trace.observe("serve.lookup_seconds", 0.001)

    with trace.using(t):
        burst()  # warm the call sites (and prove the lock stays idle)
        gc.collect()
        before = sys.getallocatedblocks()
        burst()
        gc.collect()
        assert sys.getallocatedblocks() - before <= 2
    t._lock = threading.Lock()
    assert t.histograms() == {}


# --- the xplane reader + clock rebase ---------------------------------------

def _pb_varint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_field(fn, wt, payload):
    tag = _pb_varint((fn << 3) | wt)
    if wt == 2:
        return tag + _pb_varint(len(payload)) + payload
    return tag + payload


def _tiny_xspace(marker_name, marker_off_ps, kernel_off_ps,
                 line_ts_ns=1000):
    """Hand-encode an XSpace: one plane, event metadata {1: marker,
    2: 'fusion.1'}, one line with both events."""
    def event(mid, off_ps, dur_ps):
        return (_pb_field(1, 0, _pb_varint(mid))
                + _pb_field(2, 0, _pb_varint(off_ps))
                + _pb_field(3, 0, _pb_varint(dur_ps)))

    def emeta(mid, name):
        md = (_pb_field(1, 0, _pb_varint(mid))
              + _pb_field(2, 2, name.encode()))
        entry = _pb_field(1, 0, _pb_varint(mid)) + _pb_field(2, 2, md)
        return _pb_field(4, 2, entry)

    line = (_pb_field(1, 0, _pb_varint(7))
            + _pb_field(2, 2, b"stream#0")
            + _pb_field(3, 0, _pb_varint(line_ts_ns))
            + _pb_field(4, 2, event(1, marker_off_ps, 500_000))
            + _pb_field(4, 2, event(2, kernel_off_ps, 2_000_000)))
    plane = (_pb_field(2, 2, b"/device:TPU:0")
             + emeta(1, marker_name)
             + emeta(2, "fusion.1")
             + _pb_field(3, 2, line))
    return _pb_field(1, 2, plane)


def test_xplane_parse_and_clock_rebase(tmp_path):
    from parquet_floor_tpu.utils.xplane import (
        device_trace_events,
        find_sync_event,
        parse_xplane,
    )

    p = tmp_path / "host.xplane.pb"
    # marker at line_ts 1000 ns + 3_000_000 ps = 4000 ns = 4 µs on the
    # profiler clock; kernel 2 µs later
    p.write_bytes(_tiny_xspace("pftpu_clock_sync", 3_000_000, 5_000_000))
    planes = parse_xplane(str(p))
    assert [pl.name for pl in planes] == ["/device:TPU:0"]
    assert planes[0].lines[0].name == "stream#0"
    assert find_sync_event(planes, "pftpu_clock_sync") == pytest.approx(4.0)
    # host clock says the sync instant was at 10_000 µs since epoch:
    # the kernel (profiler 6 µs) must land at 10_002 µs
    evs = device_trace_events(
        str(p), sync_marker="pftpu_clock_sync", host_sync_us=10_000.0
    )
    kernels = [e for e in evs if e.get("name") == "fusion.1"]
    assert len(kernels) == 1
    assert kernels[0]["ts"] == pytest.approx(10_002.0)
    assert kernels[0]["dur"] == pytest.approx(2.0)
    assert kernels[0]["cat"] == "xla"
    assert kernels[0]["args"]["origin"] == "device"
    meta = [e for e in evs if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta} == {"/device:TPU:0",
                                                "stream#0"}


def test_xplane_rebase_without_marker_pins_earliest_event(tmp_path):
    from parquet_floor_tpu.utils.xplane import device_trace_events

    p = tmp_path / "host.xplane.pb"
    p.write_bytes(_tiny_xspace("not_the_marker", 3_000_000, 5_000_000))
    evs = device_trace_events(
        str(p), sync_marker="pftpu_clock_sync", host_sync_us=500.0
    )
    xs = [e for e in evs if e["ph"] == "X"]
    assert min(e["ts"] for e in xs) == pytest.approx(500.0)


def test_default_growth_is_sane():
    assert 1.05 < GROWTH < 1.2


def test_span_observe_records_the_span_wall():
    """span(..., observe=name) records the SAME wall the stage stat
    gets — one clock read, no drift between stats and histogram."""
    t = Tracer(enabled=True)
    with trace.using(t):
        with trace.span("serve.lookup", observe="serve.lookup_seconds"):
            pass
        with trace.span("serve.lookup"):   # no observe=: no sample
            pass
    h = t.histograms()["serve.lookup_seconds"]
    st = t.stats()["serve.lookup"]
    assert h.count == 1 and st["count"] == 2
    assert 0 <= h.total <= st["seconds"]
    # disabled: the observing span is still the shared no-op instance
    off = Tracer(enabled=False)
    with trace.using(off):
        assert trace.span("serve.lookup",
                          observe="serve.lookup_seconds") is \
            trace.span("decode")
    assert off.histograms() == {}
