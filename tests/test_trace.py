"""Scoped trace contexts, timeline export, and health reports
(``utils.trace``, docs/observability.md): tracer isolation across
threads and concurrent scans, Chrome-trace export validity, the
disabled-mode zero-cost contract, bounded-store eviction counters, the
counters/gauges namespace split, retry-counter durability, and the
``ScanReport`` surfaces."""

import gc
import json
import sys
import threading

import numpy as np
import pytest

from parquet_floor_tpu import (
    IoRetryExhaustedError,
    ParquetFileWriter,
    ParquetReader,
    ReaderOptions,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.format.parquet_thrift import CompressionCodec
from parquet_floor_tpu.io.source import RetryingSource
from parquet_floor_tpu.scan import (
    DatasetScanner,
    ScanOptions,
    scan_device_groups,
)
from parquet_floor_tpu.utils.trace import ScanReport, Tracer, names


def _write(path, n=1500, groups=2, seed=0):
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.DOUBLE).named("d"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    rng = np.random.default_rng(seed)
    per = (n + groups - 1) // groups
    data = {
        "k": np.arange(n, dtype=np.int64) + seed * 1_000_000,
        "d": [
            None if i % 11 == 0 else float(v)
            for i, v in enumerate(rng.standard_normal(n))
        ],
        "s": [None if i % 7 == 0 else f"v{(i + seed) % 37}" for i in range(n)],
    }
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY, row_group_rows=per,
        data_page_values=400,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        for lo in range(0, n, per):
            hi = min(lo + per, n)
            w.write_columns({k: v[lo:hi] for k, v in data.items()})
    return str(path)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("trace_ds")
    return [_write(str(d / f"f{i}.parquet"), seed=i) for i in range(4)]


@pytest.fixture(scope="module")
def small_dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("trace_ds_small")
    return [
        _write(str(d / f"g{i}.parquet"), n=600, seed=10 + i) for i in range(2)
    ]


# --- scoping ----------------------------------------------------------------

def test_scope_isolates_from_global():
    trace.reset()
    trace.count("io.retries", 3)  # global tracer is disabled: dropped
    assert trace.counters() == {}
    with trace.scope() as t:
        trace.count("io.retries", 2)
        assert trace.counters() == {"io.retries": 2}
        assert t.counters() == {"io.retries": 2}
    assert trace.counters() == {}  # back on the (disabled) global tracer
    assert t.counters() == {"io.retries": 2}  # scope snapshot survives


def test_nested_scopes_innermost_wins():
    with trace.scope() as outer:
        trace.count("io.retries", 1)
        with trace.scope() as inner:
            trace.count("io.retries", 10)
        trace.count("io.retries", 1)
    assert outer.counters()["io.retries"] == 2
    assert inner.counters()["io.retries"] == 10


def test_tracer_run_carries_scope_to_plain_threads():
    with trace.scope() as t:
        def work():
            trace.count("scan.bytes_read", 7)
            with trace.span("read"):
                pass
        th = threading.Thread(target=t.run, args=(work,))
        th.start()
        th.join()
    assert t.counters()["scan.bytes_read"] == 7
    assert t.stats()["read"]["count"] == 1


def test_two_concurrent_scoped_scans_report_disjoint_counters(
        dataset, small_dataset):
    """The acceptance contract: two threads running scoped scans see
    isolated, correctly attributed counters — identical to what each
    scan reports when run alone."""
    def run_scan(paths, out, key):
        with trace.scope() as t:
            with DatasetScanner(paths, scan=ScanOptions(threads=2)) as sc:
                rows = sum(u.batch.num_rows for u in sc)
            out[key] = (t.metrics(), t.stats(), rows)

    solo: dict = {}
    run_scan(dataset, solo, "a")
    run_scan(small_dataset, solo, "b")

    both: dict = {}
    ta = threading.Thread(target=run_scan, args=(dataset, both, "a"))
    tb = threading.Thread(target=run_scan, args=(small_dataset, both, "b"))
    ta.start()
    tb.start()
    ta.join()
    tb.join()

    deterministic = (
        "scan.ranges_planned", "scan.extents_planned", "scan.bytes_read",
        "scan.bytes_used", "scan.overread_bytes", "scan.bytes_prefetched",
    )
    for key in ("a", "b"):
        got_m, got_s, got_rows = both[key]
        want_m, want_s, want_rows = solo[key]
        assert got_rows == want_rows
        for name in deterministic:
            assert got_m[name] == want_m[name], (key, name)
        # every worker-side span landed on the right tracer too
        assert got_s["decode"]["count"] == want_s["decode"]["count"]
    # the two scans really are disjoint (different datasets → different
    # byte totals), not two copies of a shared store
    assert both["a"][0]["scan.bytes_read"] != both["b"][0]["scan.bytes_read"]
    assert trace.counters() == {}  # nothing leaked to the global tracer


# --- bounded stores ---------------------------------------------------------

def test_decision_cap_configurable_and_eviction_counted():
    with trace.scope(max_decisions=3) as t:
        for i in range(8):
            trace.decision("scan.plan", {"i": i})
    kept = t.decisions()
    assert len(kept) == 3
    assert [d["i"] for d in kept] == [5, 6, 7]  # oldest evicted first
    assert t.counters()["trace.decisions_dropped"] == 5


def test_default_decision_cap_is_64():
    with trace.scope() as t:
        for i in range(70):
            trace.decision("scan.plan", {"i": i})
    assert len(t.decisions()) == 64
    assert t.counters()["trace.decisions_dropped"] == 6


def test_event_cap_eviction_counted():
    with trace.scope(max_events=8) as t:
        for _ in range(10):
            with trace.span("read"):
                pass
    assert len(t.events()) == 8
    assert t.counters()["trace.events_dropped"] == 12  # 20 recorded - 8 kept


def test_tracer_rejects_degenerate_caps():
    with pytest.raises(ValueError):
        Tracer(max_decisions=0)
    with pytest.raises(ValueError):
        Tracer(max_events=1)


# --- counters/gauges namespace split ----------------------------------------

def test_counters_gauges_split_and_merged_view():
    with trace.scope() as t:
        trace.count("scan.bytes_read", 10)
        trace.gauge_max("scan.queue_depth_max", 4)
        trace.gauge_max("scan.queue_depth_max", 2)  # below high water
    assert t.counters() == {"scan.bytes_read": 10}
    assert t.gauges() == {"scan.queue_depth_max": 4}
    merged = t.metrics()
    assert merged == {"scan.bytes_read": 10, "scan.queue_depth_max": 4}


def test_report_labels_gauges_as_max():
    with trace.scope() as t:
        trace.count("scan.bytes_read", 10)
        trace.gauge_max("scan.queue_depth_max", 4)
    rep = t.report()
    assert "scan.queue_depth_max" in rep and "max=4" in rep
    assert "max=10" not in rep  # additive counters are NOT labelled max=


def test_registry_names_are_disjoint_by_kind():
    assert not names.COUNTERS & names.GAUGES
    assert not names.COUNTERS & names.SPANS
    assert not names.GAUGES & names.SPANS
    assert names.ALL >= names.COUNTERS | names.GAUGES | names.DECISIONS


# --- the zero-cost disabled path --------------------------------------------

class _PoisonedLock:
    """Fails the test if the no-op path ever takes the tracer lock."""

    def __enter__(self):
        raise AssertionError("disabled-mode hot path acquired the lock")

    def __exit__(self, *exc):
        return False

    def acquire(self, *a, **k):
        raise AssertionError("disabled-mode hot path acquired the lock")

    def release(self):
        pass


def test_disabled_noop_path_no_alloc_no_lock():
    t = Tracer(enabled=False)
    t._lock = _PoisonedLock()
    detail = {"engine": "host"}
    attrs = {"file": 0}

    def burst():
        for _ in range(50):
            trace.count("io.retries")
            trace.gauge_max("scan.queue_depth_max", 9)
            trace.decision("engine.auto", detail)
            trace.add("read", 0.1, 5)
            with trace.span("read", 5, attrs):
                pass
            # the distributed-tracing sites must stay free too: a
            # disabled tracer starts no trace (shared immortal handle),
            # leaves no ambient context, and observe takes the early
            # return before the exemplar offer
            with trace.start_trace("request"):
                pass
            trace.current_context()
            trace.observe("io.remote.get_seconds.primary", 0.01)

    with trace.using(t):
        # the no-op span is one shared immortal instance
        assert trace.span("read") is trace.span("decode")
        assert trace.start_trace("a") is trace.start_trace("b")
        assert trace.current_context() is None
        burst()  # warm call sites (and prove the poisoned lock is idle)
        gc.collect()
        before = sys.getallocatedblocks()
        burst()
        gc.collect()
        # the 250 no-op calls retain nothing; the 2-block slack covers
        # the measurement itself (`before` and the delta are fresh ints)
        assert sys.getallocatedblocks() - before <= 2
    t._lock = threading.Lock()  # snapshots below may take the lock
    assert t.counters() == {} and t.events() == []


# --- timeline + chrome export -----------------------------------------------

def _load_trace(path):
    data = json.loads(path.read_text())
    # round-trips through the json module unchanged
    assert json.loads(json.dumps(data)) == data
    return data["traceEvents"]


def _check_balanced(events):
    """B/E pairs must balance per thread, with matching names, and
    timestamps must be monotonic."""
    stacks: dict = {}
    last_ts = None
    for ev in events:
        if ev["ph"] == "M":
            continue
        if last_ts is not None:
            assert ev["ts"] >= last_ts
        last_ts = ev["ts"]
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
        elif ev["ph"] == "E":
            assert stacks.get(ev["tid"]), "E without a B on its thread"
            assert stacks[ev["tid"]].pop() == ev["name"]
    assert not any(s for s in stacks.values()), "unclosed span in export"


def test_export_chrome_trace_threads_and_nesting(tmp_path):
    with trace.scope() as t:
        with trace.span("stage", attrs={"file": "f", "row_group": 0}):
            with trace.span("ship", 10):
                pass
        th = threading.Thread(target=t.run, args=(
            lambda: trace.span("read", 5, {"file": "g"}).__enter__().__exit__(
                None, None, None
            ),
        ))
        th.start()
        th.join()
        trace.decision("engine.auto", {"engine": "host"})
    out = tmp_path / "t.json"
    n = t.export_chrome_trace(str(out))
    events = _load_trace(out)
    assert n == len(events)
    _check_balanced(events)
    tids = {e["tid"] for e in events if e["ph"] == "B"}
    assert len(tids) == 2
    # thread-name metadata rides along
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)
    # instant events keep their attrs
    inst = [e for e in events if e["ph"] == "i"]
    assert inst and inst[0]["args"] == {"engine": "host"}


def test_export_balances_evicted_begin_and_open_span(tmp_path):
    t = Tracer(enabled=True, max_events=2)
    with trace.using(t):
        with trace.span("stage"):
            with trace.span("ship"):
                pass
        # buffer now holds ship-E, stage-E: both orphaned ends
        out = tmp_path / "orphans.json"
        t.export_chrome_trace(str(out))
        _check_balanced(_load_trace(out))
        t.reset()
        sp = trace.span("decode")
        sp.__enter__()  # never exited: export must close it
        out2 = tmp_path / "open.json"
        t.export_chrome_trace(str(out2))
        events = _load_trace(out2)
        _check_balanced(events)
        assert [e["name"] for e in events if e["ph"] == "E"] == ["decode"]
        sp.__exit__(None, None, None)


def test_device_scan_export_attributed_spans(dataset, tmp_path):
    """The acceptance gate: a 4-file device scan exports (file,
    row-group)-attributed read/stage/ship/decode spans on ≥ 2 distinct
    threads, as valid, loadable trace-event JSON."""
    with trace.scope() as t:
        units = list(scan_device_groups(
            dataset, scan=ScanOptions(threads=2), float64_policy="bits"
        ))
    assert len(units) == 8
    out = tmp_path / "scan.json"
    t.export_chrome_trace(str(out))
    events = _load_trace(out)
    _check_balanced(events)
    begins = [e for e in events if e["ph"] == "B"]
    for stage in ("read", "stage", "ship", "decode"):
        spans = [e for e in begins if e["name"] == stage]
        assert spans, f"no {stage} spans in the export"
        attributed = [
            e for e in spans
            if "file" in e.get("args", {})
            and e["args"].get("row_group") is not None
        ]
        assert attributed, f"{stage} spans carry no (file, row_group) attrs"
    pipeline_tids = {
        e["tid"] for e in begins
        if e["name"] in ("read", "stage", "ship", "decode")
    }
    assert len(pipeline_tids) >= 2


# --- retry counters survive the ring buffer ---------------------------------

class _FlakyEveryOther:
    """Positional source whose every read fails once, then succeeds."""

    name = "<flaky>"
    size = 1 << 20

    def __init__(self):
        self.attempts = 0

    def read_at(self, offset, length):
        self.attempts += 1
        if self.attempts % 2 == 1:
            raise OSError("transient")
        return memoryview(bytes(length))

    def close(self):
        pass


def test_retry_totals_survive_decision_eviction():
    with trace.scope(max_decisions=2) as t:
        rs = RetryingSource(_FlakyEveryOther(), retries=3, backoff_s=0,
                            sleep=lambda s: None)
        for _ in range(5):
            rs.read_at(0, 4)
    # only 2 io.retry decisions survive the ring buffer…
    assert len([d for d in t.decisions()
                if d["decision"] == "io.retry"]) == 2
    assert t.counters()["trace.decisions_dropped"] == 3
    # …but the counter keeps the full total
    assert t.counters()["io.retries"] == 5
    assert "io.retry_exhausted" not in t.counters()


class _AlwaysFails:
    name = "<dead>"
    size = 1 << 20

    def read_at(self, offset, length):
        raise OSError("gone")

    def close(self):
        pass


def test_retry_exhaustion_counted():
    with trace.scope() as t:
        rs = RetryingSource(_AlwaysFails(), retries=2, backoff_s=0,
                            sleep=lambda s: None)
        with pytest.raises(IoRetryExhaustedError):
            rs.read_at(0, 4)
    assert t.counters()["io.retries"] == 2
    assert t.counters()["io.retry_exhausted"] == 1


# --- ScanReport surfaces ----------------------------------------------------

def test_dataset_scanner_report(dataset):
    with trace.scope():
        with DatasetScanner(dataset, scan=ScanOptions(threads=2)) as sc:
            rows = sum(u.batch.num_rows for u in sc)
            rep_mid = sc.report()  # mid-scan: wall is elapsed-so-far
            assert rep_mid.wall_seconds is not None
        rep = sc.report()
    assert rows == 6000
    assert isinstance(rep, ScanReport)
    assert rep.wall_seconds > 0
    assert rep.bytes_read >= rep.bytes_used > 0
    assert 0.0 <= rep.overread_ratio < 1.0
    assert rep.budget_bytes == ScanOptions().prefetch_bytes
    assert rep.budget_utilization is not None
    assert 0.0 <= rep.stall_fraction <= 1.0
    assert rep.overlap_fraction == pytest.approx(1.0 - rep.stall_fraction)
    assert rep.stages["decode"]["count"] == 8
    d = rep.as_dict()
    assert json.loads(json.dumps(d)) == d  # bench-JSON-ready
    assert "scan health:" in rep.render()


def test_scan_report_render_in_trace_report(dataset):
    with trace.scope() as t:
        with DatasetScanner(dataset[:1]) as sc:
            for _ in sc:
                pass
    assert "scan health:" in t.report()


def test_scan_device_groups_on_report(small_dataset):
    got = []
    with trace.scope():
        for _ in scan_device_groups(
            small_dataset, scan=ScanOptions(threads=2),
            float64_policy="bits", on_report=got.append,
        ):
            pass
    assert len(got) == 1
    rep = got[0]
    assert isinstance(rep, ScanReport)
    assert rep.wall_seconds > 0
    assert rep.bytes_read > 0
    assert rep.stages["stage"]["count"] == 4
    assert rep.stages["ship"]["count"] >= 4


def test_on_report_error_does_not_mask_scan_error(small_dataset, tmp_path):
    # a raising callback surfaces when the scan itself succeeded…
    with pytest.raises(RuntimeError, match="callback boom"):
        with trace.scope():
            for _ in scan_device_groups(
                small_dataset, float64_policy="bits",
                on_report=lambda rep: (_ for _ in ()).throw(
                    RuntimeError("callback boom")
                ),
            ):
                pass
    # …but never replaces an in-flight scan error (here: a corrupt
    # footer among the sources)
    bad = tmp_path / "bad.parquet"
    bad.write_bytes(b"PAR1 this is not a parquet file")
    with pytest.raises(ValueError) as ei:
        with trace.scope():
            for _ in scan_device_groups(
                [small_dataset[0], str(bad)], float64_policy="bits",
                on_report=lambda rep: (_ for _ in ()).throw(
                    RuntimeError("callback boom")
                ),
            ):
                pass
    assert "callback boom" not in str(ei.value)


def test_stream_content_scan_report_face(small_dataset):
    class Hyd:
        def start(self):
            return {}

        def add(self, tgt, name, value):
            tgt[name] = value
            return tgt

        def finish(self, tgt):
            return tgt

    with trace.scope():
        it = ParquetReader.stream_content(
            small_dataset, lambda cols: Hyd(), scan_options=ScanOptions(),
        )
        n = sum(1 for _ in it)
        rep = it.report()
    assert n == 1200
    assert isinstance(rep, ScanReport)
    assert rep.bytes_read > 0


def test_salvage_counters_registered():
    # the salvage path counters are part of the registry the lint rule
    # enforces (their behavior is pinned in test_salvage)
    assert "salvage.pages_skipped" in names.COUNTERS
    assert "salvage.chunks_quarantined" in names.COUNTERS
    assert names.DECISIONS >= {"salvage.skip_page", "salvage.quarantine_chunk"}


def test_reader_options_still_flow_under_scope(dataset):
    # scoping must not disturb option plumbing on the scan path
    with trace.scope() as t:
        with DatasetScanner(
            dataset[:1], options=ReaderOptions(io_retries=2),
        ) as sc:
            rows = sum(u.batch.num_rows for u in sc)
    assert rows == 1500
    assert t.counters().get("io.retry_exhausted", 0) == 0


# --- nesting-aware stats (self_seconds) --------------------------------------


def test_nested_spans_split_inclusive_and_self_time():
    """A child span's wall charges its own stage AND subtracts from the
    parent's exclusive time: summing self_seconds never counts one
    second twice."""
    import time as _time

    with trace.scope() as t:
        with trace.span("decode"):
            _time.sleep(0.02)
            with trace.span("decode_chunk"):
                _time.sleep(0.03)
            _time.sleep(0.005)
    st = t.stats()
    outer, inner = st["decode"], st["decode_chunk"]
    assert inner["self_seconds"] == inner["seconds"]   # leaf span
    assert inner["seconds"] >= 0.03
    assert outer["seconds"] >= 0.05                    # inclusive
    # exclusive time excludes the nested chunk's wall
    assert outer["self_seconds"] == pytest.approx(
        outer["seconds"] - inner["seconds"], abs=2e-3
    )
    assert outer["self_seconds"] < outer["seconds"]


def test_sibling_threads_do_not_share_nesting():
    """The nesting stack is per-thread: a span on a worker thread is
    not a child of whatever the submitting thread has open."""
    import time as _time

    with trace.scope() as t:
        def worker():
            with t.span("read"):
                _time.sleep(0.01)

        with t.span("decode"):
            th = threading.Thread(target=t.run, args=(worker,))
            th.start()
            th.join()
    st = t.stats()
    assert st["read"]["self_seconds"] == st["read"]["seconds"]
    assert st["decode"]["self_seconds"] == pytest.approx(
        st["decode"]["seconds"], abs=1e-3
    )


def test_bare_add_defaults_self_to_inclusive():
    with trace.scope() as t:
        t.add("read", 0.5, 10)
    st = t.stats()["read"]
    assert st["self_seconds"] == st["seconds"] == 0.5


def test_sequential_reader_emits_per_chunk_decode_spans(dataset):
    """The pure-host sequential reader attributes decode per chunk —
    and under the scan executor's per-group decode span those chunks
    nest instead of double-counting (self_seconds discipline)."""
    from parquet_floor_tpu.format.file_read import ParquetFileReader

    with trace.scope() as t:
        with ParquetFileReader(dataset[0]) as r:
            n_chunks = len(r.row_groups[0].columns)
            r.read_row_group(0)
    st = t.stats()
    assert st["decode_chunk"]["count"] == n_chunks
    # under the scan executor, the group "decode" span contains them
    with trace.scope() as t2:
        with DatasetScanner(dataset[:1]) as sc:
            for _ in sc:
                pass
    st2 = t2.stats()
    assert st2["decode_chunk"]["count"] > 0
    assert st2["decode"]["count"] > 0
    # the chunks' wall is inside the groups' wall, and the group span's
    # exclusive time excludes it
    assert st2["decode"]["self_seconds"] <= (
        st2["decode"]["seconds"] - st2["decode_chunk"]["seconds"] + 1e-3
    )


def test_new_engine_and_prefetch_names_registered():
    assert {
        "engine.launches", "engine.exec_cache_hits",
        "engine.exec_cache_misses", "engine.compile_ms",
        "data.prefetch_to_device_batches",
    } <= names.COUNTERS
    assert {
        "engine.stage_queue_depth_max", "data.prefetch_to_device_depth_max",
    } <= names.GAUGES
    assert "engine.exec_cache" in names.DECISIONS
    assert {"decode_chunk", "data.prefetch_to_device"} <= names.SPANS


def test_bare_add_inside_open_span_charges_the_parent():
    """A bare add() records just-spent wall: it must subtract from the
    enclosing span's exclusive time exactly like a child span would
    (the scan executor's consumer-stall under the loader's
    data.next_batch span)."""
    import time as _time

    with trace.scope() as t:
        with trace.span("data.next_batch"):
            t0 = _time.perf_counter()
            _time.sleep(0.03)
            t.add("scan.consumer_stall", _time.perf_counter() - t0)
            _time.sleep(0.01)
    st = t.stats()
    stall, parent = st["scan.consumer_stall"], st["data.next_batch"]
    assert stall["self_seconds"] == stall["seconds"] >= 0.03
    assert parent["self_seconds"] == pytest.approx(
        parent["seconds"] - stall["seconds"], abs=2e-3
    )
