"""Hostile-input robustness: truncated and bit-flipped files must raise
clean errors (never hang, crash the process, or return wrong data
silently).  SURVEY.md §5 notes the reference *swallows* I/O errors
(FSDataInputStream.java:21-45); this framework's stance is fail-loudly.
"""

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)


@pytest.fixture(scope="module")
def valid_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("fuzz") / "v.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(3)
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=500)) as w:
        w.write_columns({
            "a": rng.integers(0, 10_000, 5000).astype(np.int64),
            "s": [None if i % 11 == 0 else f"val{i % 321}" for i in range(5000)],
            "d": rng.standard_normal(5000),
        })
    return str(path)


def _full_decode(data: bytes, tmp_path):
    p = tmp_path / "f.parquet"
    p.write_bytes(data)
    with ParquetFileReader(str(p)) as r:
        for batch in r.iter_row_groups():
            for c in batch.columns:
                _ = c.values
                _ = c.def_levels


def test_truncations_raise_cleanly(valid_file, tmp_path):
    data = open(valid_file, "rb").read()
    # truncate at a spread of positions incl. footer, pages, magic
    for cut in [0, 1, 3, 4, 7, len(data) // 4, len(data) // 2,
                len(data) - 1000, len(data) - 9, len(data) - 4, len(data) - 1]:
        if cut >= len(data):
            continue
        with pytest.raises((ValueError, EOFError, IndexError, KeyError)):
            _full_decode(data[:cut], tmp_path)


def test_bit_flips_never_hang_or_crash(valid_file, tmp_path):
    """Flip bytes at random positions: decode must either succeed (the
    flip hit slack/unread bytes or undetected payload) or raise a Python
    exception — never deadlock or kill the interpreter."""
    data = bytearray(open(valid_file, "rb").read())
    rng = np.random.default_rng(11)
    for _ in range(60):
        pos = int(rng.integers(0, len(data)))
        old = data[pos]
        data[pos] ^= 0xFF
        try:
            _full_decode(bytes(data), tmp_path)
        except Exception:
            pass  # clean failure is acceptable; silent wrongness isn't tested here
        finally:
            data[pos] = old


def test_footer_length_lies(valid_file, tmp_path):
    """A footer length field pointing outside the file must raise."""
    data = bytearray(open(valid_file, "rb").read())
    data[-8:-4] = (2**31 - 1).to_bytes(4, "little")
    with pytest.raises((ValueError, EOFError)):
        _full_decode(bytes(data), tmp_path)
    data = bytearray(open(valid_file, "rb").read())
    data[-8:-4] = (0).to_bytes(4, "little")
    with pytest.raises((ValueError, EOFError)):
        _full_decode(bytes(data), tmp_path)


def test_crc_verification_catches_payload_flip(valid_file, tmp_path):
    """With verify_crc, a flipped page payload byte is detected."""
    data = bytearray(open(valid_file, "rb").read())
    # find a spot inside the first page payload (after the first header):
    # flip a byte at 1/8 into the file (data pages start near the front)
    pos = len(data) // 8
    data[pos] ^= 0x01
    p = tmp_path / "crc.parquet"
    p.write_bytes(bytes(data))
    with ParquetFileReader(str(p), verify_crc=True) as r:
        with pytest.raises(Exception):
            for batch in r.iter_row_groups():
                for c in batch.columns:
                    _ = c.values


def test_native_delta_plan_survives_hostile_bytes():
    """The native DELTA plan parser must reject garbage/truncations with
    None (host fallback), never crash or loop."""
    from parquet_floor_tpu.native import binding as nb

    if not nb.available():
        pytest.skip("native library not built")
    from parquet_floor_tpu.format.encodings import delta as e_delta

    r = np.random.default_rng(13)
    # pure garbage
    for n in (0, 1, 7, 64, 1000):
        buf = r.integers(0, 256, n).astype(np.uint8)
        nb.delta_parse_plan(buf, 8, True)  # any result ok; no crash
    # truncations and bit flips of a real stream
    vals = r.integers(-(2**40), 2**40, 5000)
    stream = np.frombuffer(e_delta.encode_delta_binary_packed(vals), np.uint8)
    for cut in (1, 5, len(stream) // 2, len(stream) - 1):
        nb.delta_parse_plan(stream[:cut], 8, True)
    for _ in range(50):
        bad = stream.copy()
        i = int(r.integers(0, len(bad)))
        bad[i] ^= np.uint8(1 << int(r.integers(0, 8)))
        got = nb.delta_parse_plan(bad, 8, True)
        if got is not None:
            # parse succeeded: plan fields must at least be self-consistent
            assert got["values_per_miniblock"] > 0
            assert len(got["mb_bw"]) >= 1
