"""Hostile-input robustness: truncated and bit-flipped files must raise
clean errors (never hang, crash the process, or return wrong data
silently).  SURVEY.md §5 notes the reference *swallows* I/O errors
(FSDataInputStream.java:21-45); this framework's stance is fail-loudly.
"""

import pathlib

import numpy as np
import pytest

from parquet_floor_tpu import (
    ChecksumMismatchError,
    CorruptFooterError,
    ParquetError,
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    TruncatedFileError,
    WriterOptions,
    types,
)


@pytest.fixture(scope="module")
def valid_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("fuzz") / "v.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(3)
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=500)) as w:
        w.write_columns({
            "a": rng.integers(0, 10_000, 5000).astype(np.int64),
            "s": [None if i % 11 == 0 else f"val{i % 321}" for i in range(5000)],
            "d": rng.standard_normal(5000),
        })
    return str(path)


def _full_decode(data: bytes, tmp_path):
    p = tmp_path / "f.parquet"
    p.write_bytes(data)
    with ParquetFileReader(str(p)) as r:
        for batch in r.iter_row_groups():
            for c in batch.columns:
                _ = c.values
                _ = c.def_levels


def test_truncations_raise_cleanly(valid_file, tmp_path):
    data = pathlib.Path(valid_file).read_bytes()
    # truncate at a spread of positions incl. footer, pages, magic
    for cut in [0, 1, 3, 4, 7, len(data) // 4, len(data) // 2,
                len(data) - 1000, len(data) - 9, len(data) - 4, len(data) - 1]:
        if cut >= len(data):
            continue
        with pytest.raises((ValueError, EOFError, IndexError, KeyError)):
            _full_decode(data[:cut], tmp_path)


def test_bit_flips_never_hang_or_crash(valid_file, tmp_path):
    """Flip bytes at random positions: decode must either succeed (the
    flip hit slack/unread bytes or undetected payload) or raise a Python
    exception — never deadlock or kill the interpreter."""
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    rng = np.random.default_rng(11)
    for _ in range(60):
        pos = int(rng.integers(0, len(data)))
        old = data[pos]
        data[pos] ^= 0xFF
        try:
            _full_decode(bytes(data), tmp_path)
        except Exception:
            pass  # clean failure is acceptable; silent wrongness isn't tested here
        finally:
            data[pos] = old


def test_footer_truncation_edge_cases(valid_file, tmp_path):
    """Files cut at the magic, mid-footer-length, mid-Thrift-metadata,
    and zero-byte files must each raise CorruptFooterError or
    TruncatedFileError — the footer taxonomy, with the file path in the
    message."""
    data = pathlib.Path(valid_file).read_bytes()
    footer_len = int.from_bytes(data[-8:-4], "little")
    # cut mid-thrift: remove bytes from inside the footer body but keep
    # the (now lying) length word + magic tail intact
    mid_thrift = data[: -8 - footer_len] + data[-8 - footer_len + 40 :]
    cases = {
        "zero-byte": b"",
        "cut-at-magic": data[:4],
        "only-head-magic-plus": data[:7],
        "mid-footer-length": data[: len(data) - 6],
        "mid-thrift-metadata": mid_thrift,
    }
    for name, blob in cases.items():
        p = tmp_path / f"{name}.parquet"
        p.write_bytes(blob)
        with pytest.raises((CorruptFooterError, TruncatedFileError)) as ei:
            ParquetFileReader(str(p))
        assert name in str(ei.value), (
            f"{name}: error message must carry the file path, got {ei.value}"
        )


def test_error_context_names_file_and_column(valid_file, tmp_path):
    """A corrupt page error must say WHICH file and WHICH column — bare
    'page payload truncated' is useless when scanning a directory."""
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    pos = len(data) // 8  # inside an early data page payload
    data[pos] ^= 0x01
    p = tmp_path / "ctx.parquet"
    p.write_bytes(bytes(data))
    with ParquetFileReader(str(p), options=ReaderOptions(verify_crc=True)) as r:
        with pytest.raises(ChecksumMismatchError) as ei:
            for batch in r.iter_row_groups():
                for c in batch.columns:
                    _ = c.values
    err = ei.value
    assert err.path == str(p)
    assert err.column is not None and err.page is not None
    assert err.expected_crc is not None and err.actual_crc is not None
    assert err.expected_crc != err.actual_crc
    assert "ctx.parquet" in str(err) and str(err.column) in str(err)


def test_reader_options_toggles_crc(valid_file, tmp_path):
    """ReaderOptions(verify_crc=...) is the documented CRC toggle: the
    same payload flip passes with verification off (the flip lands in
    Snappy-surviving bytes or raises a decode error) and is *guaranteed*
    caught as ChecksumMismatchError with it on."""
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    data[len(data) // 8] ^= 0x01
    p = tmp_path / "crc2.parquet"
    p.write_bytes(bytes(data))
    with ParquetFileReader(str(p), options=ReaderOptions(verify_crc=True)) as r:
        with pytest.raises(ChecksumMismatchError):
            for batch in r.iter_row_groups():
                for c in batch.columns:
                    _ = c.values
    # off (the default): no ChecksumMismatchError — either a clean decode
    # (flip undetected by the codec) or some other taxonomy error
    with ParquetFileReader(str(p)) as r:
        try:
            for batch in r.iter_row_groups():
                for c in batch.columns:
                    _ = c.values
        except ChecksumMismatchError:  # pragma: no cover - would be a bug
            pytest.fail("CRC verification ran despite verify_crc=False")
        except ParquetError:
            pass


def test_garbage_thrift_footer_is_corrupt_footer_error(valid_file, tmp_path):
    """Unparseable footer thrift (magic + length intact) surfaces as
    CorruptFooterError — sniff loops need ONE class, not bare
    ThriftDecodeError."""
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    footer_len = int.from_bytes(data[-8:-4], "little")
    start = len(data) - 8 - footer_len
    data[start : start + footer_len] = b"\xff" * footer_len
    p = tmp_path / "thrift_garbage.parquet"
    p.write_bytes(bytes(data))
    with pytest.raises(CorruptFooterError) as ei:
        ParquetFileReader(str(p))
    assert ei.value.path == str(p)


def test_huge_declared_page_size_rejected_before_allocation():
    """A header claiming an out-of-i32-range uncompressed size must be
    rejected as CorruptPageError (on BOTH the native and Python parse
    paths) before any decompressor pre-allocates it."""
    from parquet_floor_tpu.format import pages as pg
    from parquet_floor_tpu.format.parquet_thrift import (
        DataPageHeader, Encoding, PageHeader, PageType,
    )

    h = PageHeader(
        type=PageType.DATA_PAGE, uncompressed_page_size=1 << 31,
        compressed_page_size=4,
        data_page_header=DataPageHeader(
            num_values=10, encoding=Encoding.PLAIN,
        ),
    )
    chunk = h.to_bytes() + b"\x00" * 4
    with pytest.raises(ValueError, match="invalid uncompressed size"):
        pg.split_pages(chunk, 10)


def test_verify_crc_shorthand_folds_into_options(valid_file):
    """verify_crc=True must survive ALSO passing options= (adding retry
    options must never silently disable CRC verification)."""
    with ParquetFileReader(
        valid_file, verify_crc=True, options=ReaderOptions(io_retries=2)
    ) as r:
        assert r.verify_crc is True
        assert r.options.io_retries == 2
    with ParquetFileReader(
        valid_file, options=ReaderOptions(verify_crc=True)
    ) as r:
        assert r.verify_crc is True


def test_footer_length_lies(valid_file, tmp_path):
    """A footer length field pointing outside the file must raise."""
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    data[-8:-4] = (2**31 - 1).to_bytes(4, "little")
    with pytest.raises((ValueError, EOFError)):
        _full_decode(bytes(data), tmp_path)
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    data[-8:-4] = (0).to_bytes(4, "little")
    with pytest.raises((ValueError, EOFError)):
        _full_decode(bytes(data), tmp_path)


def test_crc_verification_catches_payload_flip(valid_file, tmp_path):
    """With verify_crc, a flipped page payload byte is detected."""
    data = bytearray(pathlib.Path(valid_file).read_bytes())
    # find a spot inside the first page payload (after the first header):
    # flip a byte at 1/8 into the file (data pages start near the front)
    pos = len(data) // 8
    data[pos] ^= 0x01
    p = tmp_path / "crc.parquet"
    p.write_bytes(bytes(data))
    with ParquetFileReader(str(p), verify_crc=True) as r:
        with pytest.raises(Exception):
            for batch in r.iter_row_groups():
                for c in batch.columns:
                    _ = c.values


def test_native_delta_plan_survives_hostile_bytes():
    """The native DELTA plan parser must reject garbage/truncations with
    None (host fallback), never crash or loop."""
    from parquet_floor_tpu.native import binding as nb

    if not nb.available():
        pytest.skip("native library not built")
    from parquet_floor_tpu.format.encodings import delta as e_delta

    r = np.random.default_rng(13)
    # pure garbage
    for n in (0, 1, 7, 64, 1000):
        buf = r.integers(0, 256, n).astype(np.uint8)
        nb.delta_parse_plan(buf, 8, True)  # any result ok; no crash
    # truncations and bit flips of a real stream
    vals = r.integers(-(2**40), 2**40, 5000)
    stream = np.frombuffer(e_delta.encode_delta_binary_packed(vals), np.uint8)
    for cut in (1, 5, len(stream) // 2, len(stream) - 1):
        nb.delta_parse_plan(stream[:cut], 8, True)
    for _ in range(50):
        bad = stream.copy()
        i = int(r.integers(0, len(bad)))
        bad[i] ^= np.uint8(1 << int(r.integers(0, 8)))
        got = nb.delta_parse_plan(bad, 8, True)
        if got is not None:
            # parse succeeded: plan fields must at least be self-consistent
            assert got["values_per_miniblock"] > 0
            assert len(got["mb_bw"]) >= 1


def test_brotli_corruption_raises_cleanly(tmp_path):
    """Bit-flipped BROTLI pages must never hang or crash the process;
    clean raises are expected for most flips.  (Silent wrongness on the
    rare surviving flip isn't asserted here — same stance as
    test_bit_flips_never_hang_or_crash.)"""
    from parquet_floor_tpu.format import brotli_codec
    from parquet_floor_tpu.format.parquet_thrift import CompressionCodec

    if not (brotli_codec.available() and brotli_codec.encoder_available()):
        pytest.skip("system brotli library not present")
    schema = types.message("t", types.required(types.INT64).named("a"))
    path = tmp_path / "b.parquet"
    rng = np.random.default_rng(4)
    with ParquetFileWriter(
        path, schema, WriterOptions(codec=CompressionCodec.BROTLI)
    ) as w:
        w.write_columns({"a": rng.integers(0, 1 << 40, 4000).astype(np.int64)})
    data = bytearray(path.read_bytes())
    # flip bytes inside the data region (past magic, before footer)
    for _ in range(40):
        bad = bytearray(data)
        i = int(rng.integers(8, len(bad) - 2000))
        bad[i] ^= 1 << int(rng.integers(0, 8))
        try:
            _full_decode(bytes(bad), tmp_path)
        except Exception:
            pass  # any clean raise is acceptable
    # exact roundtrip of the unflipped file still holds
    with ParquetFileReader(str(path)) as r:
        assert r.read_row_group(0).num_rows == 4000


def test_tpu_row_api_on_corrupt_file_raises_wrapped(tmp_path, monkeypatch):
    """engine='tpu' wraps hostile-file failures in the same
    'Failed to read parquet' RuntimeError as the host engine.  The
    corruption trashes the first Snappy page body wholesale, so decode
    MUST fail — the parity assertion always executes."""
    from parquet_floor_tpu import CompressionCodec, ParquetReader

    monkeypatch.setenv("PFTPU_PALLAS", "0")
    schema = types.message("t", types.required(types.INT64).named("a"))
    path = tmp_path / "c.parquet"
    with ParquetFileWriter(
        path, schema, WriterOptions(codec=CompressionCodec.SNAPPY)
    ) as w:
        w.write_columns({"a": np.arange(2000, dtype=np.int64)})
    data = bytearray(path.read_bytes())
    # obliterate 64 bytes of the first page's compressed payload (well
    # past the ~20-byte page header, far before the footer)
    for i in range(40, 104):
        data[i] = 0xA5
    bad = tmp_path / "cbad.parquet"
    bad.write_bytes(bytes(data))

    class _H:
        def start(self):
            return []

        def add(self, t_, h, v):
            t_.append(v)
            return t_

        def finish(self, t_):
            return tuple(t_)

    for engine in ("host", "tpu"):
        with pytest.raises(RuntimeError, match="Failed to read parquet"):
            list(ParquetReader.stream_content(
                str(bad), lambda c: _H(), engine=engine
            ))
    # engine="auto" must surface the same wrapped error, not a cost-model
    # artifact (the footer itself is intact here, so routing succeeds and
    # the decode failure propagates through whichever engine it picked)
    with pytest.raises(RuntimeError, match="Failed to read parquet"):
        list(ParquetReader.stream_content(
            str(bad), lambda c: _H(), engine="auto"
        ))
    # a corrupt FOOTER fails loudly through auto as well (the cost model
    # never runs — the open fails first, unwrapped like the host engine's
    # constructor-time errors)
    trash = bytearray(path.read_bytes())
    trash[-6] = 0xFF  # flip a byte of the footer-length word
    fbad = tmp_path / "fbad.parquet"
    fbad.write_bytes(bytes(trash))
    with pytest.raises((ValueError, RuntimeError)):
        ParquetReader.stream_content(str(fbad), lambda c: _H(), engine="auto")

    # the batch face wraps nothing extra: hostile page bytes raise from
    # the generator on either engine
    for engine in ("host", "tpu", "auto"):
        with pytest.raises(Exception):
            for _ in ParquetReader.stream_batches(str(bad), engine=engine):
                pass


def test_golden_corpus_corruption_never_hangs(tmp_path):
    """Bit-flip fuzz over the THIRD-PARTY golden binaries (foreign
    writer conventions: PLAIN_DICTIONARY stamps, legacy lists,
    BIT_PACKED levels, foreign page indexes): decode must either
    succeed or raise a Python exception — never deadlock or kill the
    process.  Same stance as test_bit_flips_never_hang_or_crash, on
    bytes this repo's writer never produced."""
    from test_golden import corpus_paths

    paths = corpus_paths()
    assert paths, "golden corpus missing"
    rng = np.random.default_rng(23)
    for path in paths:
        data = bytearray(pathlib.Path(path).read_bytes())
        for _ in range(15):
            pos = int(rng.integers(0, len(data)))
            old = data[pos]
            data[pos] ^= 0xFF
            try:
                _full_decode(bytes(data), tmp_path)
            except Exception:
                pass  # clean failure is the acceptable outcome
            finally:
                data[pos] = old


# ---------------------------------------------------------------------------
# Shared taxonomy helpers (errors.classified_decode_errors /
# errors.checked_alloc_size) — the blessed idioms floorlint checks for
# ---------------------------------------------------------------------------

def test_classified_decode_errors_wraps_hostile_crashes():
    from parquet_floor_tpu.errors import (
        CorruptPageError, classified_decode_errors,
    )

    with pytest.raises(CorruptPageError, match=r"page decode failed: .*boom"):
        with classified_decode_errors(CorruptPageError, "page decode failed",
                                      {"path": "f.parquet", "page": 3}):
            raise IndexError("boom")
    try:
        with classified_decode_errors(CorruptPageError, "page decode failed",
                                      {"path": "f.parquet", "page": 3}):
            raise IndexError("boom")
    except CorruptPageError as e:
        assert e.path == "f.parquet" and e.page == 3
        assert isinstance(e.__cause__, IndexError)


def test_classified_decode_errors_passes_transients_through():
    from parquet_floor_tpu.errors import (
        CorruptPageError, classified_decode_errors,
    )

    for transient in (OSError("flaky mount"), MemoryError()):
        with pytest.raises(type(transient)) as ei:
            with classified_decode_errors(CorruptPageError, "decode", {}):
                raise transient
        assert not isinstance(ei.value, ParquetError)


def test_classified_decode_errors_annotates_taxonomy():
    from parquet_floor_tpu.errors import (
        CorruptPageError, classified_decode_errors,
    )

    # inner frames win on fields they already set; missing fields fill in
    with pytest.raises(CorruptPageError) as ei:
        with classified_decode_errors(
            CorruptPageError, "decode", {"path": "outer", "column": "c"}
        ):
            raise CorruptPageError("inner defect", path="inner")
    assert ei.value.path == "inner" and ei.value.column == "c"
    assert ei.value.message == "inner defect"  # not re-wrapped


def test_classified_decode_errors_reclassifies():
    from parquet_floor_tpu.errors import (
        CorruptFooterError, classified_decode_errors,
    )
    from parquet_floor_tpu.format.thrift import ThriftDecodeError

    with pytest.raises(CorruptFooterError, match="does not parse") as ei:
        with classified_decode_errors(
            CorruptFooterError, "footer metadata does not parse",
            {"path": "f"}, reclassify=(ThriftDecodeError,),
        ):
            raise ThriftDecodeError("bad varint")
    assert isinstance(ei.value.__cause__, ThriftDecodeError)


def test_checked_alloc_size_caps_parsed_sizes():
    from parquet_floor_tpu.errors import CorruptPageError, checked_alloc_size

    assert checked_alloc_size(0) == 0
    assert checked_alloc_size(2**31 - 1) == 2**31 - 1
    assert checked_alloc_size(np.int64(17), "x") == 17
    for bad in (-1, 2**31, 2**40):
        with pytest.raises(CorruptPageError, match="implausible"):
            checked_alloc_size(bad, "test size", path="f.parquet")
    with pytest.raises(CorruptPageError):
        checked_alloc_size(64, cap=64)
    assert checked_alloc_size(63, cap=64) == 63
    # it is a ValueError (taxonomy secondary base): pre-taxonomy callers
    # catching ValueError still see these
    with pytest.raises(ValueError):
        checked_alloc_size(-5)


def test_corrupt_delta_total_count_is_corruption_not_memoryerror():
    """A flipped varint claiming a 2^40-value DELTA stream must surface
    as CorruptPageError via the size cap, not as a giant allocation."""
    from parquet_floor_tpu.errors import CorruptPageError
    from parquet_floor_tpu.format.encodings.delta import (
        decode_delta_binary_packed,
    )

    # header: block_size=128, miniblocks=4, total_count=2^40, first=0
    hostile = bytes([0x80, 0x01, 0x04,
                     0x80, 0x80, 0x80, 0x80, 0x80, 0x20,
                     0x00])
    with pytest.raises(CorruptPageError, match="total_count"):
        decode_delta_binary_packed(hostile)
