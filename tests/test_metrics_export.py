"""Prometheus/JSON metrics export: golden-text round-trip, the
cross-process merge law, the live endpoint, and the file emitter
(docs/observability.md)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from parquet_floor_tpu.utils import trace
from parquet_floor_tpu.utils.metrics_export import (
    FileMetricsEmitter,
    MetricsServer,
    merge_snapshots,
    parse_prometheus,
    render_prometheus,
    sanitize,
    snapshot,
)
from parquet_floor_tpu.utils.trace import Tracer


def _fixed_tracer() -> Tracer:
    t = Tracer(enabled=True)
    t.count("serve.cache_hits", 7)
    t.count("serve.cache_miss_bytes", 4096)
    t.gauge_max("scan.queue_depth_max", 3)
    t.add("decode", 0.25, 1000)
    for v in (0.001, 0.001, 0.004):
        t.observe("serve.lookup_seconds", v)
    return t


GOLDEN = """\
# TYPE pftpu_serve_cache_hits counter
pftpu_serve_cache_hits 7
# TYPE pftpu_serve_cache_miss_bytes counter
pftpu_serve_cache_miss_bytes 4096
# TYPE pftpu_scan_queue_depth_max gauge
pftpu_scan_queue_depth_max 3
# TYPE pftpu_stage_count counter
pftpu_stage_count{stage="decode"} 1
# TYPE pftpu_stage_seconds_total counter
pftpu_stage_seconds_total{stage="decode"} 0.25
# TYPE pftpu_stage_bytes_total counter
pftpu_stage_bytes_total{stage="decode"} 1000
# TYPE pftpu_serve_lookup_seconds histogram
pftpu_serve_lookup_seconds_bucket{le="0.00106494896"} 2
pftpu_serve_lookup_seconds_bucket{le="0.00425979583"} 3
pftpu_serve_lookup_seconds_bucket{le="+Inf"} 3
pftpu_serve_lookup_seconds_sum 0.006
pftpu_serve_lookup_seconds_count 3
"""


def test_golden_text_round_trip():
    """The exposition text is pinned byte-for-byte, and the stdlib
    parser reads every value back — format drift breaks HERE, not in a
    scrape dashboard."""
    text = render_prometheus(_fixed_tracer())
    assert text == GOLDEN
    parsed = parse_prometheus(text)
    assert parsed["pftpu_serve_cache_hits"] == 7
    assert parsed["pftpu_scan_queue_depth_max"] == 3
    assert parsed['pftpu_stage_seconds_total{stage="decode"}'] == 0.25
    assert parsed['pftpu_serve_lookup_seconds_bucket{le="+Inf"}'] == 3
    assert parsed["pftpu_serve_lookup_seconds_count"] == 3
    assert parsed["pftpu_serve_lookup_seconds_sum"] == pytest.approx(0.006)


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_prometheus("this is not exposition format\n")


def test_sanitize_names():
    assert sanitize("serve.lookup_seconds") == "pftpu_serve_lookup_seconds"
    assert sanitize("io.remote.get_seconds.primary") == \
        "pftpu_io_remote_get_seconds_primary"


def test_histogram_buckets_are_cumulative_and_consistent():
    text = render_prometheus(_fixed_tracer())
    parsed = parse_prometheus(text)
    buckets = sorted(
        (float(k.split('le="')[1].rstrip('"}')), v)
        for k, v in parsed.items()
        if k.startswith("pftpu_serve_lookup_seconds_bucket")
        and "+Inf" not in k
    )
    values = [v for _, v in buckets]
    assert values == sorted(values)          # cumulative, never decreasing
    assert values[-1] <= parsed["pftpu_serve_lookup_seconds_count"]


def test_merge_snapshots_law():
    a, b = snapshot(_fixed_tracer()), snapshot(_fixed_tracer())
    m = merge_snapshots([a, b])
    assert m["counters"]["serve.cache_hits"] == 14          # sums
    assert m["gauges"]["scan.queue_depth_max"] == 3         # max
    assert m["stages"]["decode"]["count"] == 2              # sums
    assert m["histograms"]["serve.lookup_seconds"]["count"] == 6
    # associative like ScanReport.merge
    c = snapshot(_fixed_tracer())
    left = merge_snapshots([merge_snapshots([a, b]), c])
    right = merge_snapshots([a, merge_snapshots([b, c])])
    assert left["counters"] == right["counters"]
    assert left["histograms"] == right["histograms"]
    with pytest.raises(ValueError):
        merge_snapshots([])


def test_metrics_server_serves_both_faces_and_404():
    t = _fixed_tracer()
    with MetricsServer(t, port=0) as srv:
        text = urllib.request.urlopen(srv.url(), timeout=5).read().decode()
        assert parse_prometheus(text)["pftpu_serve_cache_hits"] == 7
        js = json.loads(urllib.request.urlopen(
            srv.url("/metrics.json"), timeout=5
        ).read().decode())
        assert js["counters"]["serve.cache_hits"] == 7
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url("/nope"), timeout=5)
        # live: a scrape after new traffic sees it
        t.count("serve.cache_hits", 1)
        text2 = urllib.request.urlopen(srv.url(), timeout=5).read().decode()
        assert parse_prometheus(text2)["pftpu_serve_cache_hits"] == 8
    srv.close()  # idempotent


def test_serve_metrics_rides_the_active_tracer():
    with trace.scope() as t:
        trace.count("serve.cache_hits", 5)
        with trace.serve_metrics(0) as srv:
            text = urllib.request.urlopen(
                srv.url(), timeout=5
            ).read().decode()
    assert parse_prometheus(text)["pftpu_serve_cache_hits"] == 5
    assert t.counters()["serve.cache_hits"] == 5


def test_concurrent_scrapes(tmp_path):
    t = _fixed_tracer()
    errors = []
    with MetricsServer(t, port=0) as srv:
        def scrape():
            try:
                for _ in range(5):
                    body = urllib.request.urlopen(
                        srv.url(), timeout=5
                    ).read().decode()
                    parse_prometheus(body)
            except Exception as e:           # noqa: BLE001 (test harness)
                errors.append(e)

        threads = [threading.Thread(target=scrape) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    assert errors == []


def test_file_emitter_writes_atomically(tmp_path):
    t = _fixed_tracer()
    path = tmp_path / "metrics.prom"
    with FileMetricsEmitter(t, str(path), interval_s=30.0) as em:
        em.emit()
        parsed = parse_prometheus(path.read_text())
        assert parsed["pftpu_serve_cache_hits"] == 7
        t.count("serve.cache_hits", 3)
    # close() wrote the final snapshot
    assert parse_prometheus(path.read_text())["pftpu_serve_cache_hits"] == 10
    assert not list(tmp_path.glob("*.tmp.*"))    # rename left no turds
    with pytest.raises(ValueError, match="interval_s"):
        FileMetricsEmitter(t, str(path), interval_s=0)


def test_write_snapshot_and_merge_dir(tmp_path):
    """The multi-worker fold: per-worker write_snapshot files merge
    through the one aggregation law, extras included, and a torn file
    fails LOUDLY (a silent skip would under-report a worker)."""
    from parquet_floor_tpu.utils.metrics_export import (
        merge_snapshot_dir,
        write_snapshot,
    )

    for i in range(3):
        write_snapshot(
            {"counters": {"serve.lookup_probes": 10 + i},
             "gauges": {"serve.daemon_inflight_max": i},
             "stages": {}, "histograms": {}},
            str(tmp_path / f"worker-{i}.json"),
        )
    merged = merge_snapshot_dir(str(tmp_path))
    assert merged["counters"]["serve.lookup_probes"] == 33
    assert merged["gauges"]["serve.daemon_inflight_max"] == 2
    extra = {"counters": {"serve.lookup_probes": 7}, "gauges": {},
             "stages": {}, "histograms": {}}
    assert merge_snapshot_dir(
        str(tmp_path), extra=[extra]
    )["counters"]["serve.lookup_probes"] == 40
    (tmp_path / "worker-torn.json").write_text("{not json")
    with pytest.raises(ValueError, match="does not parse"):
        merge_snapshot_dir(str(tmp_path))
    (tmp_path / "worker-torn.json").unlink()
    with pytest.raises(ValueError, match="no worker snapshots"):
        merge_snapshot_dir(str(tmp_path / "empty-nowhere"))


def test_metrics_server_snapshot_dir_folds_workers(tmp_path):
    """MetricsServer(snapshot_dir=): one scrape sees the worker fleet
    folded with the server's own live tracer."""
    from parquet_floor_tpu.utils.metrics_export import write_snapshot

    write_snapshot(
        {"counters": {"serve.lookup_probes": 5}, "gauges": {},
         "stages": {}, "histograms": {}},
        str(tmp_path / "worker-a.json"),
    )
    t = Tracer(enabled=True)
    with trace.using(t):
        trace.count("serve.lookup_probes", 2)
    with MetricsServer(t, snapshot_dir=str(tmp_path)) as server:
        text = urllib.request.urlopen(
            server.url(), timeout=10
        ).read().decode()
        js = json.loads(urllib.request.urlopen(
            server.url("/metrics.json"), timeout=10
        ).read().decode())
    assert parse_prometheus(text)["pftpu_serve_lookup_probes"] == 7
    assert js["counters"]["serve.lookup_probes"] == 7
