"""The query subsystem (parquet_floor_tpu/query/, docs/query.md):
projection expressions (device/host bit-equality, the pyarrow.compute
differential, salvage/string refusals and the host fallback), the
sorted-merge join (oracle parity, resume at every page boundary,
fingerprint-stamped tokens, sortedness refusals), and persistent
secondary indexes (brute-force differential, staleness refusal,
negative-cache invalidation), on both the library and daemon faces."""

import glob
import json
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.compute as pc  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from parquet_floor_tpu import (  # noqa: E402
    ParquetFileWriter,
    ParquetReader,
    ReaderOptions,
    WriterOptions,
    types,
)
from parquet_floor_tpu.api.hydrate import (  # noqa: E402
    HydratorSupplier,
    dict_hydrator,
)
from parquet_floor_tpu.errors import UnsupportedFeatureError  # noqa: E402
from parquet_floor_tpu.query import (  # noqa: E402
    JoinCursor,
    SecondaryIndex,
    qcol,
    sorted_merge_join,
)
from parquet_floor_tpu.scan import ScanOptions  # noqa: E402
from parquet_floor_tpu.serve import (  # noqa: E402
    DaemonClient,
    Dataset,
    ServeDaemon,
    Serving,
)
from parquet_floor_tpu.utils import trace  # noqa: E402
from parquet_floor_tpu.write import (  # noqa: E402
    CompactOptions,
    DatasetCompactor,
)

N_L = 600
N_R = 450


@pytest.fixture(autouse=True)
def _no_cache(monkeypatch):
    from parquet_floor_tpu.tpu import exec_cache

    monkeypatch.delenv("PFTPU_EXEC_CACHE", raising=False)
    exec_cache.activate(None)
    yield
    exec_cache.activate(None)


def _read_rows(paths):
    out = []
    for p in paths:
        r = ParquetReader(p, HydratorSupplier.constantly(dict_hydrator()))
        out.extend(dict(x) for x in r)
        r.close()
    return out


@pytest.fixture(scope="module")
def corpora(tmp_path_factory):
    """Two sort-compacted corpora (globally sorted int64 ``k`` with
    duplicates both sides, overlapping ranges) + a secondary index on
    the scattered ``tag`` column of the left corpus."""
    tmp = tmp_path_factory.mktemp("query")
    t = types
    lschema = t.message(
        "l", t.required(t.INT64).named("k"),
        t.required(t.DOUBLE).named("lv"),
        t.optional(t.INT64).named("tag"),
        t.required(t.BYTE_ARRAY).as_(t.string()).named("name"),
    )
    rschema = t.message(
        "r", t.required(t.INT64).named("k"),
        t.required(t.DOUBLE).named("rv"),
        t.optional(t.INT64).named("tag"),
    )
    rng = np.random.default_rng(42)
    lk = np.sort(rng.integers(0, N_L // 3, N_L))
    rk = np.sort(rng.integers(N_L // 6, N_L // 2, N_R))
    lsrc, rsrc = str(tmp / "lsrc.parquet"), str(tmp / "rsrc.parquet")
    with ParquetFileWriter(
        lsrc, lschema, WriterOptions(row_group_rows=97)
    ) as w:
        w.write_columns({
            "k": lk, "lv": rng.random(N_L),
            "tag": [None if i % 11 == 0 else int(i % 37)
                    for i in range(N_L)],
            "name": [f"n{i % 23}" for i in range(N_L)],
        })
    with ParquetFileWriter(
        rsrc, rschema, WriterOptions(row_group_rows=83)
    ) as w:
        w.write_columns({
            "k": rk, "rv": rng.random(N_R),
            "tag": [int(i % 29) for i in range(N_R)],
        })
    lout, rout = str(tmp / "lout"), str(tmp / "rout")
    lrep = DatasetCompactor([lsrc], lout, CompactOptions(
        sort_by=["k"], target_row_group_rows=64,
        target_file_rows=256, index_columns=["tag", "name"],
    )).run()
    rrep = DatasetCompactor([rsrc], rout, CompactOptions(
        sort_by=["k"], target_row_group_rows=64, target_file_rows=256,
    )).run()
    return {
        "lsrc": lsrc, "rsrc": rsrc,
        "lpaths": list(lrep.paths), "rpaths": list(rrep.paths),
        "index_paths": list(lrep.index_paths),
        "lrows": _read_rows(lrep.paths), "rrows": _read_rows(rrep.paths),
    }


def _join_oracle(lrows, rrows, on, how, lcols=None, rcols=None):
    """Brute-force nested-loop join with the documented semantics:
    null keys never match, left-order output, right runs in corpus
    order, collision renaming, unmatched-left nulls."""
    out = []
    keyless = set(on)
    for lrow in lrows:
        lkey = tuple(lrow[c] for c in on)
        matched = False
        for rrow in rrows:
            if any(v is None for v in lkey):
                break
            if tuple(rrow[c] for c in on) != lkey:
                continue
            matched = True
            row = {k: v for k, v in lrow.items()
                   if lcols is None or k in lcols}
            for k, v in rrow.items():
                if k in keyless:
                    continue
                if rcols is not None and k not in rcols:
                    continue
                row[f"right.{k}" if k in row else k] = v
            out.append(row)
        if not matched and how == "left":
            row = {k: v for k, v in lrow.items()
                   if lcols is None or k in lcols}
            for k in rrows[0].keys():
                if k in keyless:
                    continue
                if rcols is not None and k not in rcols:
                    continue
                row[f"right.{k}" if k in row else k] = None
            out.append(row)
    return out


# -- expressions ----------------------------------------------------------


def _expr_corpus(tmp_path, with_nulls=True):
    t = types
    schema = t.message(
        "e", t.required(t.INT64).named("a"),
        t.optional(t.INT32).named("b"),
        t.required(t.DOUBLE).named("x"),
    )
    rng = np.random.default_rng(7)
    n = 300
    p = str(tmp_path / "expr.parquet")
    x = rng.random(n) * 100 - 50
    x[5] = np.nan                      # NaN flows through arithmetic
    x[6] = np.inf
    a = rng.integers(-(2 ** 62), 2 ** 62, n)   # overflow territory
    b = [None if with_nulls and i % 7 == 0 else int(i % 1000 - 500)
         for i in range(n)]
    with ParquetFileWriter(
        p, schema, WriterOptions(row_group_rows=64)
    ) as w:
        w.write_columns({"a": a, "b": b, "x": x})
    return p, a, b, x


def _scan_expr(paths, exprs, engine):
    got = {}
    masks = {}
    names = {en for en, _ in exprs}
    for cols in ParquetReader.stream_batches(
        paths, engine=engine,
        scan_options=ScanOptions(project_exprs=tuple(exprs)),
    ):
        for c in cols:
            nm = c.descriptor.path[0]
            if nm in names:
                got.setdefault(nm, []).append(np.asarray(c.values))
                masks.setdefault(nm, []).append(
                    None if c.mask is None else np.asarray(c.mask)
                )
    vals = {nm: np.concatenate(vs) for nm, vs in got.items()}
    mk = {}
    for nm, ms in masks.items():
        if all(m is None for m in ms):
            mk[nm] = None
        else:
            mk[nm] = np.concatenate([
                m if m is not None else np.zeros(len(v), bool)
                for m, v in zip(ms, got[nm])
            ])
    return vals, mk


def test_expr_device_host_bit_equal(tmp_path):
    """The device leg's computed columns are BIT-equal to the host twin
    — values and null masks — for int arithmetic, casts, float64
    division, comparisons, and null propagation."""
    p, _a, _b, _x = _expr_corpus(tmp_path)
    exprs = [
        ("s", qcol("a") + qcol("b")),              # int + nullable int
        ("r", qcol("b").cast("float64") / 3.0),    # f64 true division
        ("c", (qcol("b") > 0) & ~qcol("b").is_null()),
        ("m", qcol("a") * 2 - 1),
    ]
    hv, hm = _scan_expr([p], exprs, "host")
    dv, dm = _scan_expr([p], exprs, "tpu")
    for nm in ("s", "r", "c", "m"):
        assert hv[nm].dtype == dv[nm].dtype, nm
        assert np.array_equal(hv[nm], dv[nm]), nm
        if hm[nm] is None:
            assert dm[nm] is None or not dm[nm].any(), nm
        else:
            assert dm[nm] is not None and \
                np.array_equal(hm[nm], dm[nm]), nm


def test_expr_differential_vs_pyarrow(tmp_path):
    """Null / NaN / overflow semantics pinned to ``pyarrow.compute``:
    nulls propagate, NaN flows IEEE-style, int64 arithmetic wraps the
    same lanes pyarrow computes (checked on the non-null lanes), and
    ``/`` is always float64 true division."""
    p, a, b, x = _expr_corpus(tmp_path)
    pb = pa.array(b, type=pa.int64())
    hv, hm = _scan_expr(
        [p],
        [("d", qcol("x") / qcol("b")),
         ("t", qcol("x") * 2.0 + 1.0),
         ("g", qcol("b") >= 0)],
        "host",
    )
    want_d = pc.divide(
        pa.array(x, type=pa.float64()), pc.cast(pb, pa.float64())
    )
    lanes = ~np.asarray(pc.is_null(want_d).to_numpy(
        zero_copy_only=False))
    got_lanes = ~hm["d"] if hm["d"] is not None else np.ones(len(x), bool)
    assert np.array_equal(lanes, got_lanes)
    wd = want_d.to_numpy(zero_copy_only=False)
    assert np.array_equal(
        hv["d"][lanes], wd[lanes].astype(np.float64), equal_nan=True
    )
    want_t = pc.add(pc.multiply(
        pa.array(x, type=pa.float64()), 2.0), 1.0
    ).to_numpy()
    assert hm["t"] is None or not hm["t"].any()
    assert np.array_equal(hv["t"], want_t, equal_nan=True)
    want_g = pc.greater_equal(pb, 0)
    g_lanes = ~np.asarray(pc.is_null(want_g).to_numpy(
        zero_copy_only=False))
    got_g_lanes = ~hm["g"] if hm["g"] is not None else np.ones(
        len(x), bool)
    assert np.array_equal(g_lanes, got_g_lanes)
    assert np.array_equal(
        hv["g"][g_lanes],
        want_g.to_numpy(zero_copy_only=False)[g_lanes].astype(bool),
    )


def test_expr_salvage_refused(tmp_path):
    p, *_ = _expr_corpus(tmp_path)
    with pytest.raises(UnsupportedFeatureError, match="salvage"):
        for _ in ParquetReader.stream_batches(
            [p], engine="host",
            options=ReaderOptions(salvage=True),
            scan_options=ScanOptions(
                project_exprs=(("y", qcol("a") + 1),)),
        ):
            pass


def test_expr_double_bits_host_fallback(corpora):
    """An expression over a plain DOUBLE input under the default
    ``float64_policy='bits'`` refuses the device leg at plan time (a
    lossy bit-form input would change the numbers) and the WHOLE scan
    falls back to the host leg — full exact results, with the
    ``engine.pushdown host_fallback`` decision recorded."""
    exprs = [("y", qcol("lv") * 2.0)]
    with trace.scope() as t:
        dv, dm = _scan_expr(corpora["lpaths"], exprs, "tpu")
    acts = [d for d in t.decisions()
            if d.get("decision") == "engine.pushdown"
            and d.get("action") == "host_fallback"]
    assert acts, "device refusal did not record the fallback decision"
    want = np.array([r["lv"] * 2.0 for r in corpora["lrows"]])
    assert np.array_equal(dv["y"], want)
    assert dm["y"] is None or not dm["y"].any()


def test_expr_exec_cache_signature(tmp_path):
    """Two different expressions over the same corpus produce different
    computed columns on the device leg — the expression signature is in
    the executable-cache key, so a changed expression can never be
    served a stale program."""
    p, a, _b, _x = _expr_corpus(tmp_path, with_nulls=False)
    v1, _ = _scan_expr([p], [("y", qcol("a") + 1)], "tpu")
    v2, _ = _scan_expr([p], [("y", qcol("a") + 2)], "tpu")
    assert np.array_equal(v2["y"] - v1["y"], np.ones(len(a), np.int64))


# -- sorted-merge join ----------------------------------------------------


@pytest.mark.parametrize("how", ["inner", "left"])
def test_join_vs_oracle(corpora, how):
    with Dataset(corpora["lpaths"], key_column="k") as L, \
            Dataset(corpora["rpaths"], key_column="k") as R:
        got = list(sorted_merge_join(L, R, on=["k"], how=how))
    want = _join_oracle(corpora["lrows"], corpora["rrows"], ["k"], how)
    assert len(got) == len(want)
    assert got == want
    if how == "inner":
        assert any("right.tag" in r for r in got)   # collision renamed


@pytest.mark.parametrize("how", ["inner", "left"])
def test_join_multi_key_null_keys_never_match(tmp_path, how):
    """Multi-key join over corpora compacted with a two-column
    ``sort_by`` prefix; a null in ANY key component never matches (SQL
    semantics) — left rows with null ``tag`` only survive as
    null-filled rows under ``how='left'``."""
    t = types
    schema = t.message(
        "m", t.required(t.INT64).named("k"),
        t.optional(t.INT64).named("tag"),
        t.required(t.INT64).named("v"),
    )
    # input pre-sorted by (k, tag-nulls-last): the compactor's stable
    # per-group sort preserves the global order, so runs crossing group
    # boundaries stay merge-legal
    ltags = [0, 1, 1, 2, None, None]
    rtags = [1, 2, 2, None]
    lsrc, rsrc = str(tmp_path / "l.parquet"), str(tmp_path / "r.parquet")
    with ParquetFileWriter(lsrc, schema, WriterOptions(
            row_group_rows=30)) as w:
        w.write_columns({
            "k": np.repeat(np.arange(20), 6),
            "tag": ltags * 20,
            "v": np.arange(120),
        })
    with ParquetFileWriter(rsrc, schema, WriterOptions(
            row_group_rows=30)) as w:
        w.write_columns({
            "k": np.repeat(np.arange(5, 25), 4),
            "tag": rtags * 20,
            "v": np.arange(80) + 1000,
        })
    lrep = DatasetCompactor([lsrc], str(tmp_path / "lo"), CompactOptions(
        sort_by=["k", "tag"], target_row_group_rows=16,
    )).run()
    rrep = DatasetCompactor([rsrc], str(tmp_path / "ro"), CompactOptions(
        sort_by=["k", "tag"], target_row_group_rows=16,
    )).run()
    lrows, rrows = _read_rows(lrep.paths), _read_rows(rrep.paths)
    with Dataset(lrep.paths, key_column="k") as L, \
            Dataset(rrep.paths, key_column="k") as R:
        got = list(sorted_merge_join(L, R, on=["k", "tag"], how=how))
    want = _join_oracle(lrows, rrows, ["k", "tag"], how)
    assert got == want
    if how == "inner":
        # (k, 1) matches twice per key in 5..19, (k, 2) twice
        assert all(r["tag"] is not None for r in got)
    else:
        nulls = [r for r in got if r["tag"] is None]
        assert nulls and all(r["v"] < 1000 and r["right.v"] is None
                             for r in nulls)


def test_join_projection(corpora):
    """Column projections narrow both sides to exactly the named
    columns (keys still drive the merge but only appear when asked
    for); key columns are never duplicated from the right side."""
    with Dataset(corpora["lpaths"], key_column="k") as L, \
            Dataset(corpora["rpaths"], key_column="k") as R:
        got = list(sorted_merge_join(
            L, R, on=["k"], left_columns=["lv"], right_columns=["rv"],
        ))
        keyed = list(sorted_merge_join(
            L, R, on=["k"], left_columns=["k", "lv"],
            right_columns=["rv"],
        ))
    want = _join_oracle(
        corpora["lrows"], corpora["rrows"], ["k"], "inner",
        lcols={"lv"}, rcols={"rv"},
    )
    assert got == want
    assert set(got[0].keys()) == {"lv", "rv"}
    assert keyed == _join_oracle(
        corpora["lrows"], corpora["rrows"], ["k"], "inner",
        lcols={"k", "lv"}, rcols={"rv"},
    )
    assert set(keyed[0].keys()) == {"k", "lv", "rv"}


def test_join_resume_every_page_boundary(corpora):
    """Exactly-once delivery resuming from EVERY page boundary,
    including boundaries inside an equal-key run (the ``ri`` skip)."""
    with Dataset(corpora["lpaths"], key_column="k") as L, \
            Dataset(corpora["rpaths"], key_column="k") as R:
        with JoinCursor(L, R, on=["k"], page_rows=13) as cur:
            full, toks, offs = [], [cur.token], [0]
            while True:
                page = cur.next_page()
                if not page:
                    break
                full.extend(page)
                offs.append(offs[-1] + len(page))
                toks.append(cur.token)
        assert toks[-1] is None        # exhausted
        for bi, tok in enumerate(toks[:-1]):
            tok = json.loads(json.dumps(tok))   # wire round-trip
            rest = []
            with JoinCursor(L, R, on=["k"], page_rows=50,
                            cursor=tok) as cur:
                while True:
                    page = cur.next_page()
                    if not page:
                        break
                    rest.extend(page)
            assert rest == full[offs[bi]:], f"boundary {bi}"


def test_join_token_fingerprint_rejection(corpora):
    with Dataset(corpora["lpaths"], key_column="k") as L, \
            Dataset(corpora["rpaths"], key_column="k") as R:
        with JoinCursor(L, R, on=["k"], page_rows=20) as cur:
            cur.next_page()
            tok = cur.token
        # different join kind
        with pytest.raises(ValueError, match="different"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                L, R, on=["k"], how="left", cursor=tok)
        # different projection
        with pytest.raises(ValueError, match="different"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                L, R, on=["k"], left_columns=["lv"], cursor=tok)
        # different dataset pair (right joined to itself)
        with Dataset(corpora["rpaths"], key_column="k") as L2:
            with pytest.raises(ValueError, match="different"):
                JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                    L2, R, on=["k"], cursor=tok)
        # malformed
        with pytest.raises(ValueError, match="token"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                L, R, on=["k"], cursor={"bogus": 1})


def test_join_refuses_unsorted_and_bad_args(corpora):
    # U: the raw pre-compaction file — no recorded sorting_columns
    with Dataset([corpora["lsrc"]], key_column="k") as U, \
            Dataset(corpora["rpaths"], key_column="k") as R:
        with pytest.raises(UnsupportedFeatureError, match="sort"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                U, R, on=["k"])
        with pytest.raises(ValueError, match="how"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                U, R, on=["k"], how="outer")
        with pytest.raises(ValueError, match="on"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                U, R, on=[])
        with pytest.raises(ValueError, match="page_rows"):
            JoinCursor(  # floorlint: disable=FL-RES001 — ctor raises
                U, R, on=["k"], page_rows=0)


def test_join_dataset_salvage_refused(corpora):
    """The serving Dataset (the join's corpus face) refuses salvage
    typed — so a salvage-read corpus can never reach the merge."""
    with pytest.raises(UnsupportedFeatureError, match="salvage"):
        Dataset(  # floorlint: disable=FL-RES001 — ctor raises
            corpora["lpaths"], key_column="k",
            options=ReaderOptions(salvage=True))


# -- secondary indexes ----------------------------------------------------


def test_index_vs_brute_force(corpora):
    idx = SecondaryIndex.open(corpora["index_paths"][0])
    assert idx.column == "tag"
    with Dataset(corpora["lpaths"], key_column="tag") as ds:
        ds.install_index(idx)
        for key in (0, 3, 17, 36, 999):
            want = [r for r in corpora["lrows"] if r["tag"] == key]
            with trace.scope() as t:
                got = ds.lookup(key)
            assert got == want, key
            c = t.counters()
            if not want:
                assert c.get("serve.index_hits", 0) == 0
                assert c.get("serve.index_skips", 0) == \
                    len(corpora["lpaths"])


def test_index_string_column(corpora):
    idx = SecondaryIndex.open(corpora["index_paths"][1])
    assert idx.column == "name"
    with Dataset(corpora["lpaths"], key_column="name") as ds:
        ds.install_index(idx)
        want = [r for r in corpora["lrows"] if r["name"] == "n7"]
        assert ds.lookup("n7") == want


def test_index_install_refusals(corpora, tmp_path):
    idx = SecondaryIndex.open(corpora["index_paths"][0])
    # wrong column
    with Dataset(corpora["lpaths"], key_column="k") as ds:
        with pytest.raises(ValueError, match="key_column"):
            ds.install_index(idx)
    # wrong file count
    with Dataset(corpora["lpaths"][:1], key_column="tag") as ds:
        with pytest.raises(ValueError, match="files"):
            ds.install_index(idx)
    # stale: same file names, different bytes (a recompacted corpus)
    alt = str(tmp_path / "alt")
    DatasetCompactor([corpora["lsrc"]], alt, CompactOptions(
        sort_by=["k"], target_row_group_rows=96, target_file_rows=256,
    )).run()
    altp = sorted(glob.glob(os.path.join(alt, "*.parquet")))
    if len(altp) == len(idx.files):
        with Dataset(altp, key_column="tag") as ds:
            with pytest.raises(ValueError, match="rebuild"):
                ds.install_index(idx)


def test_index_sidecar_corruption_loud(tmp_path, corpora):
    src = corpora["index_paths"][0]
    bad = str(tmp_path / "bad.index.json")
    with open(src, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    data["version"] = 99
    with open(bad, "w", encoding="utf-8") as fh:
        json.dump(data, fh)
    with pytest.raises(ValueError, match="version"):
        SecondaryIndex.open(bad)
    with open(bad, "w", encoding="utf-8") as fh:
        fh.write("{not json")
    with pytest.raises(ValueError, match="parse"):
        SecondaryIndex.open(bad)


def test_index_salvage_refused(corpora, tmp_path):
    with pytest.raises(UnsupportedFeatureError, match="salvage"):
        DatasetCompactor(
            [corpora["lsrc"]], str(tmp_path / "o"),
            CompactOptions(salvage=True, index_columns=["tag"]),
        ).run()


def test_install_index_invalidates_negative_cache(corpora):
    """A key the bloom/stats ladder proved ABSENT before the index was
    installed must be re-probed through the index afterwards — the
    per-file negative caches flush on install."""
    idx = SecondaryIndex.open(corpora["index_paths"][0])
    with Dataset(corpora["lpaths"], key_column="tag") as ds:
        key = 3
        want = [r for r in corpora["lrows"] if r["tag"] == key]
        assert want, "fixture key must exist"
        assert ds.lookup(key) == want    # populates per-file neg entries
        # poison the negative caches directly: without invalidation the
        # installed index's answer would be masked for absent files and
        # the ladder's neg short-circuit would skip real probes
        for i in range(len(corpora["lpaths"])):
            lf = ds._file(i)
            lf.neg[key] = True
        ds.install_index(idx)
        for i in range(len(corpora["lpaths"])):
            assert not ds._file(i).neg   # flushed on install
        assert ds.lookup(key) == want


# -- fp-stamped range cursor ----------------------------------------------


def test_range_cursor_token_fingerprint(corpora):
    with Dataset(corpora["lpaths"], key_column="k") as ds:
        cur = ds.range_cursor(0, 100, page_rows=16)
        cur.next_page()
        tok = cur.token
        assert "fp" in tok
        # same window resumes fine (page_rows may differ)
        rest = list(ds.range_cursor(0, 100, page_rows=64,
                                    cursor=dict(tok)))
        assert rest
        # different window refuses
        with pytest.raises(ValueError, match="refusing to resume"):
            ds.range_cursor(0, 200, cursor=dict(tok))
        # different projection refuses
        with pytest.raises(ValueError, match="refusing to resume"):
            ds.range_cursor(0, 100, columns=["k"], cursor=dict(tok))
        # legacy fp-less token refuses
        legacy = {k: v for k, v in tok.items() if k != "fp"}
        with pytest.raises(ValueError, match="cursor token"):
            ds.range_cursor(0, 100, cursor=legacy)
    # different dataset refuses
    with Dataset(corpora["rpaths"], key_column="k") as ds2:
        with pytest.raises(ValueError, match="refusing to resume"):
            ds2.range_cursor(0, 100, cursor=dict(tok))


# -- the daemon faces -----------------------------------------------------


def _daemon(corpora, **kw):
    srv = Serving(prefetch_bytes=8 << 20, device_lanes=2)
    cache = srv.cache
    L = Dataset(corpora["lpaths"], "k", cache=cache)
    R = Dataset(corpora["rpaths"], "k", cache=cache)
    daemon = ServeDaemon(srv, {"left": L, "right": R}, **kw)
    return srv, L, R, daemon


def test_daemon_select(corpora):
    srv, L, R, daemon = _daemon(corpora)
    with srv, L, R, daemon:
        with DaemonClient("127.0.0.1", daemon.port, "sel") as c:
            rows = c.select(
                "left", [("y", qcol("lv") * 2.0)], lo=0, hi=10,
                columns=["k", "lv"],
            )
            want = [
                {"k": r["k"], "lv": r["lv"], "y": r["lv"] * 2.0}
                for r in corpora["lrows"] if 0 <= r["k"] <= 10
            ]
            assert rows == want
            # malformed expression tree is a bad_request, not a hangup
            r = c.request("select", dataset="left",
                          exprs=[["y", ["frob", 1]]])
            assert r["ok"] is False and r["code"] == "bad_request"
            r = c.request("select", dataset="left", exprs=[])
            assert r["ok"] is False and r["code"] == "bad_request"


def test_daemon_join_page_resume_and_fp(corpora):
    srv, L, R, daemon = _daemon(corpora)
    with srv, L, R, daemon:
        with DaemonClient("127.0.0.1", daemon.port, "jn") as c:
            full, cur = [], None
            pages = 0
            while True:
                rows, cur = c.join_page(
                    "left", "right", on=["k"], page_rows=101,
                    cursor=cur,
                )
                full.extend(rows)
                pages += 1
                if pages == 1:
                    first_tok = cur
                if cur is None:    # exhausted — the token IS the state
                    break
            want = _join_oracle(
                corpora["lrows"], corpora["rrows"], ["k"], "inner"
            )
            assert full == want
            assert pages >= 2
            assert first_tok is not None
            # resume from the first boundary, different page size
            rest, cur2 = [], first_tok
            while cur2 is not None:
                rows, cur2 = c.join_page(
                    "left", "right", on=["k"], page_rows=400,
                    cursor=cur2,
                )
                rest.extend(rows)
            assert rest == full[101:]
            # token replayed against a different projection refuses
            r = c.request("join_page", left="left", right="right",
                          on=["k"], how="left", cursor=first_tok)
            assert r["ok"] is False and r["code"] == "bad_request"
            # unknown dataset names the registry
            r = c.request("join_page", left="nope", right="right",
                          on=["k"])
            assert r["ok"] is False and r["code"] == "bad_request"
            # unsorted corpus refusal arrives typed over the wire
            r = c.request("join_page", left="left", right="right",
                          on=["lv"])
            assert r["ok"] is False and r["code"] in (
                "unsupported", "bad_request"
            )


def test_daemon_query_tenant_attribution(corpora):
    """select and join_page land on the CONNECTION's tenant tracer —
    two tenants' reports stay disjoint."""
    srv, L, R, daemon = _daemon(corpora)
    with srv, L, R, daemon:
        with DaemonClient("127.0.0.1", daemon.port, "qa") as ca, \
                DaemonClient("127.0.0.1", daemon.port, "qb") as cb:
            for _ in range(3):
                ca.select("left", [("y", qcol("lv") + 1.0)],
                          lo=0, hi=5)
            cb.join_page("left", "right", on=["k"], page_rows=50)
            ta = srv.tenant("qa").tracer.counters()
            tb = srv.tenant("qb").tracer.counters()
            assert ta.get("serve.select_probes") == 3
            assert "query.join_pages" not in ta
            assert tb.get("query.join_pages") == 1
            assert "serve.select_probes" not in tb


def test_dataset_select_library_face(corpora):
    with Dataset(corpora["lpaths"], key_column="k") as ds:
        with trace.scope() as t:
            rows = ds.select([("half", qcol("lv") / 2.0)],
                             columns=["k"], limit=7)
        assert len(rows) == 7
        want = corpora["lrows"][:7]
        assert rows == [
            {"k": r["k"], "half": r["lv"] / 2.0} for r in want
        ]
        c = t.counters()
        assert c.get("serve.select_probes") == 1
        assert c.get("serve.select_rows") == 7
        assert "serve.select_seconds" in t.histograms()
