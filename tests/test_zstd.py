"""First-party ZSTD codec tests: RFC 8878 decoder + store-mode encoder
(native/src/pftpu_zstd.cc) validated against pyarrow's bundled libzstd.

Parity context: the reference decodes arbitrary footer codecs through its
shim seam + JNI natives (SURVEY.md §2.4); ZSTD here is implemented from
scratch instead of linked.
"""

import numpy as np
import pytest

from parquet_floor_tpu.format import codecs
from parquet_floor_tpu.format.parquet_thrift import CompressionCodec
from parquet_floor_tpu.native import binding as native

pa = pytest.importorskip("pyarrow")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)

rng = np.random.default_rng(7)


def _payloads():
    return [
        b"",
        b"a",
        b"hello zstd " * 400,
        bytes(rng.integers(0, 256, 70_000, dtype=np.uint8)),      # incompressible
        bytes(rng.integers(0, 3, 150_000, dtype=np.uint8)),       # low entropy
        np.arange(40_000, dtype=np.int64).tobytes(),              # structured
        b"\x00" * 200_000,                                        # RLE + 2 blocks
        bytes(rng.choice(list(b"abcdefg "), 250_000)),            # text-like
    ]


@pytest.mark.parametrize("level", [1, 3, 19])
def test_decode_pyarrow_streams(level):
    codec = pa.Codec("zstd", compression_level=level)
    for data in _payloads():
        comp = bytes(codec.compress(data))
        got = native.zstd_decompress(comp, len(data))
        assert got == data


def test_store_encoder_roundtrips_via_pyarrow():
    codec = pa.Codec("zstd")
    for data in _payloads():
        frame = native.zstd_compress(data)
        back = bytes(codec.decompress(frame, decompressed_size=len(data)))
        assert back == data
        # and through our own decoder
        assert native.zstd_decompress(frame, len(data)) == data


def test_multi_frame_concatenation():
    a, b = b"frame one " * 100, bytes(rng.integers(0, 9, 5000, dtype=np.uint8))
    comp = bytes(pa.Codec("zstd").compress(a)) + bytes(pa.Codec("zstd").compress(b))
    assert native.zstd_decompress(comp, len(a) + len(b)) == a + b


def test_truncation_and_garbage_fail_cleanly():
    data = bytes(rng.integers(0, 64, 30_000, dtype=np.uint8))
    comp = bytes(pa.Codec("zstd").compress(data))
    for cut in (1, 5, len(comp) // 2, len(comp) - 1):
        with pytest.raises(ValueError):
            native.zstd_decompress(comp[:cut], len(data))
    for _ in range(100):
        junk = bytes(rng.integers(0, 256, int(rng.integers(1, 500)), dtype=np.uint8))
        try:
            native.zstd_decompress(junk, 4096)
        except ValueError:
            pass  # rejection is the expected outcome; no crash / no hang


def test_wrong_declared_size_rejected():
    data = b"x" * 1000
    comp = bytes(pa.Codec("zstd").compress(data))
    with pytest.raises(ValueError):
        native.zstd_decompress(comp, 999)  # too small: capacity error
    with pytest.raises(ValueError):
        native.zstd_decompress(comp, 1001)  # too large: short decode


def test_codecs_dispatch_uses_native_zstd():
    data = bytes(rng.integers(0, 50, 10_000, dtype=np.uint8))
    comp = bytes(pa.Codec("zstd").compress(data))
    assert codecs.decompress(CompressionCodec.ZSTD, comp, len(data)) == data
    frame = codecs.compress(CompressionCodec.ZSTD, data)
    assert codecs.decompress(CompressionCodec.ZSTD, frame, len(data)) == data
