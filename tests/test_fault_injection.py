"""Fault-injection harness (parquet_floor_tpu.testing) + bounded I/O
retries (ReaderOptions.io_retries / io.source.RetryingSource)."""

import pathlib

import numpy as np
import pytest

from parquet_floor_tpu import (
    IoRetryExhaustedError,
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    TruncatedFileError,
    WriterOptions,
    types,
)
from parquet_floor_tpu.io.source import FileSource, RetryingSource
from parquet_floor_tpu.testing import FaultInjectingSource


@pytest.fixture(scope="module")
def small_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("faults") / "v.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    rng = np.random.default_rng(5)
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=400)) as w:
        w.write_columns({
            "a": rng.integers(0, 1 << 40, 2000).astype(np.int64),
            "s": [None if i % 7 == 0 else f"row{i % 97}" for i in range(2000)],
        })
    return str(path)


def test_bit_flips_are_deterministic_and_nonmutating(small_file):
    flips = [(100, 0x01), (101, 0x80)]
    with FaultInjectingSource(small_file, bit_flips=flips) as src:
        a = bytes(src.read_at(90, 30))
        b = bytes(src.read_at(90, 30))
        assert a == b  # same call, same injected bytes
        assert src.injected_flips == 4
    clean = pathlib.Path(small_file).read_bytes()[90:120]
    assert a != clean
    assert bytes([a[10] ^ 0x01, a[11] ^ 0x80]) == clean[10:12]
    # partial overlap: only the flip inside the window applies
    with FaultInjectingSource(small_file, bit_flips=flips) as src:
        w = bytes(src.read_at(101, 5))
        assert w[0] == clean[11] ^ 0x80
    # the file on disk is untouched
    assert pathlib.Path(small_file).read_bytes()[90:120] == clean


def test_random_flips_deterministic():
    a = FaultInjectingSource.random_flips(10_000, 16, seed=42)
    b = FaultInjectingSource.random_flips(10_000, 16, seed=42)
    c = FaultInjectingSource.random_flips(10_000, 16, seed=43)
    assert a == b
    assert a != c
    assert all(0 <= o < 10_000 and m in {1 << k for k in range(8)} for o, m in a)


def test_truncation_injection(small_file):
    real = FileSource(small_file)
    try:
        cut = real.size // 2
        src = FaultInjectingSource(small_file, truncate_at=cut)
        assert src.size == cut
        src.read_at(cut - 10, 10)  # inside the virtual file: fine
        with pytest.raises(TruncatedFileError):
            src.read_at(cut - 5, 10)
        # a reader over the truncated source fails loudly (footer gone)
        with pytest.raises((ValueError, EOFError)):
            ParquetFileReader(src)
        src.close()
    finally:
        real.close()


def test_transient_errors_and_retry_loop(small_file):
    """Injected transient OSErrors are healed by ReaderOptions.io_retries
    and the whole file decodes to the exact clean values."""
    src = FaultInjectingSource(
        small_file, seed=11, transient_error_rate=0.4,
        max_transient_failures=8,
    )
    opts = ReaderOptions(io_retries=10, io_retry_backoff_s=0.0005)
    with ParquetFileReader(src, options=opts) as r:
        got = [b for b in r.iter_row_groups()]
    with ParquetFileReader(small_file) as r:
        want = [b for b in r.iter_row_groups()]
    assert src.injected_transients > 0
    for gb, wb in zip(got, want):
        assert np.array_equal(gb.column("a").values, wb.column("a").values)


def test_retry_exhaustion_raises_taxonomy(small_file):
    """Unbounded transient failures exhaust the retry budget and surface
    as IoRetryExhaustedError (still an OSError) with attempt count."""
    src = FaultInjectingSource(small_file, seed=1, transient_error_rate=1.0)
    with pytest.raises(IoRetryExhaustedError) as ei:
        ParquetFileReader(src, options=ReaderOptions(
            io_retries=2, io_retry_backoff_s=0.0001
        ))
    assert ei.value.attempts == 3
    assert isinstance(ei.value, OSError)
    src.close()


def test_retries_never_mask_deterministic_errors(small_file):
    """Truncation is a fact about the bytes: the retry loop must re-raise
    immediately, not spin on it."""
    with FileSource(small_file) as real:
        retry = RetryingSource(real, retries=5, backoff_s=10.0)  # would hang if slept
        with pytest.raises(TruncatedFileError):
            retry.read_at(real.size - 4, 100)


def test_retry_off_by_default(small_file):
    """io_retries=0 (the default): the first transient error propagates."""
    src = FaultInjectingSource(small_file, seed=2, transient_error_rate=1.0)
    with pytest.raises(OSError):
        ParquetFileReader(src)
    src.close()


def test_transient_error_is_never_salvaged_as_corruption(small_file):
    """Salvage mode must not quarantine healthy data on an I/O blip: a
    transient OSError mid-decode propagates (it is retryable, not
    corruption) and nothing lands in the salvage report."""
    src = FaultInjectingSource(small_file, seed=21, transient_error_rate=0.0)
    opts = ReaderOptions(salvage=True)
    with ParquetFileReader(src, options=opts) as r:
        src._transient_rate = 1.0  # footer reads done; chunk reads now fail
        with pytest.raises(OSError):
            r.read_row_group(0)
        rep = r.salvage_report
        assert rep.chunks_quarantined == 0 and rep.skips == []


def test_constructor_failure_closes_owned_file(tmp_path, monkeypatch):
    """A corrupt footer raising out of ParquetFileReader(path) must close
    the FileSource the constructor itself opened (directory sniffs over
    damaged corpora must not leak one fd per bad file)."""
    bad = tmp_path / "garbage.parquet"
    bad.write_bytes(b"PAR1" + b"\x00" * 64)
    closed = []
    orig = FileSource.close
    monkeypatch.setattr(
        FileSource, "close",
        lambda self: (closed.append(1), orig(self))[1],
    )
    with pytest.raises(ValueError):
        ParquetFileReader(str(bad))
    assert closed, "constructor leaked the FileSource it opened"


def test_caller_retrying_source_is_not_double_wrapped(small_file):
    """A user-supplied RetryingSource + ReaderOptions.io_retries must not
    nest retry loops (attempts would multiply)."""
    src = RetryingSource(FileSource(small_file), retries=1)
    with ParquetFileReader(src, options=ReaderOptions(io_retries=5)) as r:
        assert r.source is src


def test_short_read_injection(small_file):
    src = FaultInjectingSource(small_file, seed=9, short_read_rate=1.0)
    with pytest.raises(TruncatedFileError, match="injected short read"):
        src.read_at(0, 64)
    assert src.injected_short_reads == 1
    src.close()


def test_retry_backoff_jitter(small_file):
    """Jitter stretches each backoff by up to `jitter` of its base delay
    (never shrinks it), driven by the injected rng."""
    sleeps = []
    src = FaultInjectingSource(small_file, transient_error_rate=1.0,
                               seed=3, max_transient_failures=3)
    retry = RetryingSource(src, retries=3, backoff_s=0.01,
                           sleep=sleeps.append, jitter=0.5, rng=lambda: 1.0)
    try:
        assert bytes(retry.read_at(0, 4)) == b"PAR1"
    finally:
        retry.close()
    # rng pinned at 1.0: every delay is base * (1 + 0.5)
    assert sleeps == pytest.approx([0.01 * 1.5, 0.02 * 1.5, 0.04 * 1.5])

    with pytest.raises(ValueError, match="jitter"):
        RetryingSource(src, retries=1, jitter=-0.1)


def test_retried_reads_surface_as_trace_decisions(small_file):
    """ROADMAP 'retry metrics in trace': every read retry saved lands in
    trace.decisions(), and exhaustion is recorded too."""
    from parquet_floor_tpu.utils import trace

    trace.reset()
    trace.enable()
    try:
        src = FaultInjectingSource(small_file, transient_error_rate=1.0,
                                   seed=7, max_transient_failures=2)
        retry = RetryingSource(src, retries=4, backoff_s=0.0,
                               sleep=lambda s: None)
        try:
            retry.read_at(0, 4)
        finally:
            retry.close()
        saved = [d for d in trace.decisions() if d["decision"] == "io.retry"]
        assert saved and saved[-1]["retried_reads"] == retry.retried_reads == 1
        assert saved[-1]["offset"] == 0

        src2 = FaultInjectingSource(small_file, transient_error_rate=1.0,
                                    seed=7)  # unbounded failures
        retry2 = RetryingSource(src2, retries=1, backoff_s=0.0,
                                sleep=lambda s: None)
        try:
            with pytest.raises(IoRetryExhaustedError):
                retry2.read_at(0, 4)
        finally:
            retry2.close()
        exhausted = [d for d in trace.decisions()
                     if d["decision"] == "io.retry_exhausted"]
        assert exhausted and exhausted[-1]["attempts"] == 2
    finally:
        trace.disable()
        trace.reset()


def test_retry_deadline_stops_the_ladder(small_file):
    """ISSUE 6 satellite: ``deadline_s`` bounds one read's TOTAL wall
    time — the ladder stops when the next sleep would cross it, raising
    IoRetryExhaustedError well before the attempt budget runs out, and
    records the ``io.retry_deadline_exceeded`` decision."""
    from parquet_floor_tpu.utils import trace

    t = [0.0]
    sleeps = []

    def clock():
        return t[0]

    def sleep(s):
        sleeps.append(s)
        t[0] += s

    src = FaultInjectingSource(small_file, transient_error_rate=1.0,
                               seed=5)  # never heals
    # backoff 1, 2, 4, 8, ... with jitter off: the 1+2 sleeps fit a 5s
    # deadline, the third (4s, landing at t=7) would cross it
    retry = RetryingSource(src, retries=50, backoff_s=1.0, jitter=0.0,
                           sleep=sleep, deadline_s=5.0, clock=clock)
    trace.reset()
    trace.enable()
    try:
        with pytest.raises(IoRetryExhaustedError, match="deadline"):
            retry.read_at(0, 4)
        hit = [d for d in trace.decisions()
               if d["decision"] == "io.retry_deadline_exceeded"]
        assert len(hit) == 1
        assert hit[0]["attempts"] == 3 and hit[0]["deadline_s"] == 5.0
    finally:
        trace.disable()
        trace.reset()
        retry.close()
    assert sleeps == [1.0, 2.0]  # the 4s sleep never ran
    assert t[0] == 3.0  # gave up INSIDE the budget, not after it


def test_retry_deadline_generous_budget_never_interferes(small_file):
    """A deadline the ladder fits inside changes nothing: transient
    faults heal exactly as without one."""
    src = FaultInjectingSource(small_file, transient_error_rate=1.0,
                               seed=3, max_transient_failures=3)
    retry = RetryingSource(src, retries=5, backoff_s=0.0,
                           sleep=lambda s: None, deadline_s=3600.0)
    try:
        assert bytes(retry.read_at(0, 4)) == b"PAR1"
    finally:
        retry.close()


def test_retry_deadline_rejects_bad_values(small_file):
    src = FileSource(small_file)
    try:
        for bad in (0.0, -1.0):
            with pytest.raises(ValueError, match="deadline_s"):
                RetryingSource(src, retries=1, deadline_s=bad)
            with pytest.raises(ValueError, match="io_retry_deadline_s"):
                ReaderOptions(io_retries=1, io_retry_deadline_s=bad)
    finally:
        src.close()


def test_reader_options_thread_the_deadline(small_file):
    """``ReaderOptions.io_retry_deadline_s`` reaches the RetryingSource
    on both the sequential open and the scan executor's source chain."""
    from parquet_floor_tpu.scan.executor import _source_chain

    opts = ReaderOptions(io_retries=2, io_retry_deadline_s=7.5)
    with ParquetFileReader(small_file, options=opts) as r:
        assert isinstance(r.source, RetryingSource)
        assert r.source._deadline_s == 7.5
    chain = _source_chain(small_file, opts)
    try:
        assert chain._inner._deadline_s == 7.5
    finally:
        chain.close()
