"""LZO codec seam: Hadoop block framing (always testable via the
injectable block decoder) + the system-liblzo2 path when present."""

import pytest

from parquet_floor_tpu.format import lzo_codec
from parquet_floor_tpu.format.codecs import UnsupportedCodec, decompress
from parquet_floor_tpu.format.parquet_thrift import CompressionCodec


def _fake_block_compress(data: bytes) -> bytes:
    """Stand-in 'codec' for framing tests: zlib raw deflate."""
    import zlib

    return zlib.compress(data)


def _fake_block_decompress(data: bytes, cap: int) -> bytes:
    import zlib

    out = zlib.decompress(data)
    if len(out) > cap:
        raise ValueError("block exceeds record remainder")
    return out


def _frame(records) -> bytes:
    """Build Hadoop BlockCompressorStream bytes: each record is
    (ulen, [inner chunks])."""
    out = bytearray()
    for chunks in records:
        total = sum(len(c) for c in chunks)
        out += total.to_bytes(4, "big")
        for c in chunks:
            blk = _fake_block_compress(c)
            out += len(blk).to_bytes(4, "big")
            out += blk
    return bytes(out)


def test_hadoop_framing_single_and_multi_block():
    payload = [(b"hello world " * 100,), (b"a" * 10, b"b" * 20, b"c" * 5)]
    data = _frame(payload)
    got = lzo_codec.hadoop_decompress(
        data, block_decompress=_fake_block_decompress
    )
    assert got == b"".join(b"".join(r) for r in payload)
    # size bound enforced BEFORE decoding — a hostile multi-record page
    # must not allocate past the declared page size (ADVICE r4: the
    # same amplification bound the brotli ladder applies)
    with pytest.raises(ValueError, match="declared"):
        lzo_codec.hadoop_decompress(
            data, uncompressed_size=1,
            block_decompress=_fake_block_decompress,
        )
    # cumulative bound: record 1 alone fits the declared size, records
    # 1+2 exceed it — the walk must stop before decoding record 2
    first_len = sum(len(c) for c in payload[0])
    calls = []

    def counting_dec(block, hint):
        calls.append(len(block))
        return _fake_block_decompress(block, hint)

    with pytest.raises(ValueError, match="declared"):
        lzo_codec.hadoop_decompress(
            data, uncompressed_size=first_len + 1,
            block_decompress=counting_dec,
        )
    assert len(calls) == len(payload[0])  # record 2 never decoded
    # a short decode that never trips the pre-bound still fails the
    # final exact-length check
    with pytest.raises(ValueError, match="footer said"):
        lzo_codec.hadoop_decompress(
            data,
            uncompressed_size=sum(
                len(c) for r in payload for c in r
            ) + 5,
            block_decompress=_fake_block_decompress,
        )


def test_hadoop_framing_empty_record():
    """ulen==0 records carry no inner block; an empty payload
    round-trips (and decodes to b'' mid-stream too)."""
    assert lzo_codec.hadoop_decompress(
        (0).to_bytes(4, "big"), block_decompress=_fake_block_decompress
    ) == b""
    mixed = (0).to_bytes(4, "big") + _frame([(b"xy" * 40,)])
    assert lzo_codec.hadoop_decompress(
        mixed, block_decompress=_fake_block_decompress
    ) == b"xy" * 40


def test_hadoop_framing_truncation_raises():
    data = _frame([(b"x" * 50,)])
    with pytest.raises(ValueError):
        lzo_codec.hadoop_decompress(
            data[:-3], block_decompress=_fake_block_decompress
        )
    with pytest.raises(ValueError, match="truncated"):
        lzo_codec.hadoop_decompress(
            b"\x00\x00\x00\x10", block_decompress=_fake_block_decompress
        )


def test_lzo_registry_behavior():
    """With liblzo2 present the registry round-trips; without it the
    footer codec raises the guidance error (parity with the reference's
    runtime ClassNotFound on a missing codec class)."""
    if lzo_codec.available():
        from parquet_floor_tpu.format.codecs import compress

        blob = compress(CompressionCodec.LZO, b"round trip " * 500)
        assert decompress(
            CompressionCodec.LZO, blob, len(b"round trip " * 500)
        ) == b"round trip " * 500
    else:
        with pytest.raises(UnsupportedCodec, match="liblzo2"):
            decompress(CompressionCodec.LZO, b"\x00" * 16, 16)


def test_lzo_real_library_blocks():
    if not lzo_codec.available():
        pytest.skip("system liblzo2 not present")
    data = b"the quick brown fox " * 300
    framed = lzo_codec.hadoop_compress(data)
    assert lzo_codec.hadoop_decompress(framed, len(data)) == data
