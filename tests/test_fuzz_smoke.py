"""Seeded corruption fuzz smoke (ISSUE 1 satellite): bit-flip random
offsets of a valid reference file through FaultInjectingSource and assert
every outcome is either a clean ParquetError or a byte-exact correct
decode — never a hang (per-case SIGALRM timeout), never a leaked
non-taxonomy crash, never silent wrong data (strict mode, CRC on,
compared against the known-good decode).

A small subset runs in tier-1; the full >=200-case sweep is ``slow``.
"""

import contextlib
import pathlib
import signal

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetError,
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    WriterOptions,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.testing import FaultInjectingSource

PER_CASE_TIMEOUT_S = 20.0


@pytest.fixture(scope="module")
def reference_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("fuzz_smoke") / "ref.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(17)
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=300)) as w:
        for _ in range(2):
            w.write_columns({
                "a": rng.integers(0, 1 << 30, 1500).astype(np.int64),
                "s": [None if i % 13 == 0 else f"value-{i % 211}"
                      for i in range(1500)],
                "d": rng.standard_normal(1500),
            })
    return str(path)


def _canonical(source):
    """Full strict decode (CRC verified) reduced to comparable bytes."""
    out = []
    with ParquetFileReader(source, options=ReaderOptions(verify_crc=True)) as r:
        for batch in r.iter_row_groups():
            for c in batch.columns:
                v = c.values
                if isinstance(v, ByteArrayColumn):
                    payload = (v.offsets.tobytes(), v.data.tobytes())
                else:
                    payload = np.asarray(v).tobytes()
                levels = (
                    None if c.def_levels is None else c.def_levels.tobytes()
                )
                out.append((tuple(c.descriptor.path), batch.num_rows,
                            payload, levels))
    return out


class _CaseTimeout(Exception):
    pass


@contextlib.contextmanager
def _time_limit(seconds: float):
    def _handler(signum, frame):
        raise _CaseTimeout()

    old = signal.signal(signal.SIGALRM, _handler)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def _flips_for(seed: int, size: int):
    """1-4 deterministic single-bit flips; every 5th seed aims at the
    footer region, where parse complexity (and hang risk) concentrates."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 5))
    if seed % 5 == 0:
        lo = max(0, size - 2048)
        offsets = rng.integers(lo, size, n)
    else:
        offsets = rng.integers(0, size, n)
    bits = rng.integers(0, 8, n)
    return [(int(o), 1 << int(b)) for o, b in zip(offsets, bits)]


def _run_cases(path, good, seeds):
    size = pathlib.Path(path).stat().st_size
    hangs, leaks, wrong = [], [], []
    for seed in seeds:
        src = FaultInjectingSource(path, bit_flips=_flips_for(seed, size))
        try:
            with _time_limit(PER_CASE_TIMEOUT_S):
                got = _canonical(src)
        except _CaseTimeout:
            hangs.append(seed)
        except ParquetError:
            pass  # clean, typed failure: the contract
        except Exception as e:  # noqa: BLE001 - the whole point of the fuzz
            leaks.append((seed, type(e).__name__, str(e)[:120]))
        else:
            if got != good:
                wrong.append(seed)
        finally:
            src.close()
    assert not hangs, f"decode hung (> {PER_CASE_TIMEOUT_S}s) for seeds {hangs}"
    assert not leaks, (
        "corruption escaped the ParquetError taxonomy: "
        + "; ".join(f"seed {s}: {t}: {m}" for s, t, m in leaks)
    )
    assert not wrong, f"SILENT WRONG DATA for seeds {wrong}"


def test_fuzz_smoke_tier1(reference_file):
    """Small always-on subset: fast corruption confidence in tier-1."""
    good = _canonical(reference_file)
    _run_cases(reference_file, good, range(48))


@pytest.mark.slow
def test_fuzz_smoke_full(reference_file):
    """The full sweep: >=200 additional seeded corruptions."""
    good = _canonical(reference_file)
    _run_cases(reference_file, good, range(48, 320))


def test_fuzz_reference_file_is_clean(reference_file):
    """Sanity: the uncorrupted reference decodes and compares equal to
    itself through the same canonicalization."""
    assert _canonical(reference_file) == _canonical(reference_file)
