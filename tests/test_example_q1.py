"""The device-resident analytics example (examples/tpch_q1.py) must stay
exact: fused decode feeding jnp segment aggregation, verified against
the host NumPy engine on the CPU mesh."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.workloads import write_lineitem  # noqa: E402
from examples.tpch_q1 import q1_device, q1_host_reference  # noqa: E402
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader  # noqa: E402


def test_q1_device_matches_host(tmp_path):
    path = str(tmp_path / "li.parquet")
    write_lineitem(path, 20_000)
    want = [
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_shipdate", "l_returnflag", "l_linestatus",
    ]
    total = None
    with TpuRowGroupReader(path, float64_policy="bits") as r:
        for cols in r.iter_row_groups(columns=want):
            part = q1_device(cols)
            total = part if total is None else total + part
    acc = np.asarray(total)
    ref = q1_host_reference(path)
    np.testing.assert_allclose(acc[:, :6], ref[:, :6], rtol=1e-9)
    assert acc[:, 5].sum() > 0  # rows survived the date filter


def test_q1_sharded_matches_host(tmp_path):
    """The mesh-parallel Q1 (sharded read + XLA-inserted reduction) is
    exact on the 8-device CPU mesh and replicates its result."""
    import jax
    from jax.sharding import Mesh

    from examples.tpch_q1_sharded import q1_sharded

    path = str(tmp_path / "li8.parquet")
    # 8 REAL row groups: every device holds real rows, so the
    # cross-device combine sums non-trivial partials (plus ragged last
    # group -> row_mask path)
    write_lineitem(path, 15_500, row_group_rows=2_000)
    from parquet_floor_tpu.parallel.multihost import read_sharded_global

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("rg",))
    want = [
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_shipdate", "l_returnflag", "l_linestatus",
    ]
    # 'bits' exercises the int64-bitcast branch main() uses on real TPU
    out = read_sharded_global(path, mesh, columns=want,
                              float64_policy="bits")
    acc = q1_sharded(out)
    ref = q1_host_reference(path)
    np.testing.assert_allclose(np.asarray(acc)[:, :6], ref[:, :6], rtol=1e-9)
    # the reduction's output is replicated across the whole mesh
    assert len(acc.sharding.device_set) == len(jax.devices())
    assert np.asarray(acc)[:, 5].sum() > 0
