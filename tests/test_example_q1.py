"""The device-resident analytics example (examples/tpch_q1.py) must stay
exact: fused decode feeding jnp segment aggregation, verified against
the host NumPy engine on the CPU mesh."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.workloads import write_lineitem  # noqa: E402
from examples.tpch_q1 import q1_device, q1_host_reference  # noqa: E402
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader  # noqa: E402


def test_q1_device_matches_host(tmp_path):
    path = str(tmp_path / "li.parquet")
    write_lineitem(path, 20_000)
    want = [
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_shipdate", "l_returnflag", "l_linestatus",
    ]
    total = None
    with TpuRowGroupReader(path, float64_policy="bits") as r:
        for cols in r.iter_row_groups(columns=want):
            part = q1_device(cols)
            total = part if total is None else total + part
    acc = np.asarray(total)
    ref = q1_host_reference(path)
    np.testing.assert_allclose(acc[:, :6], ref[:, :6], rtol=1e-9)
    assert acc[:, 5].sum() > 0  # rows survived the date filter
