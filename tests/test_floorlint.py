"""floorlint (parquet_floor_tpu.analysis) self-tests.

Three layers: per-rule seeded fixture pairs (one violating, one clean)
under ``tests/analysis_fixtures/``, the meta-test that the analyzer runs
clean on the live tree (the same gate ``scripts/lint.py`` enforces), and
the CLI/suppression/baseline workflows."""

import pathlib
import subprocess
import sys

import pytest

from parquet_floor_tpu.analysis import (
    ALL_RULES,
    analyze_file,
    load_baseline,
    run,
    write_baseline,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "analysis_fixtures"

CASES = [
    ("exc001", "FL-EXC001"),
    ("exc002", "FL-EXC002"),
    ("exc003", "FL-EXC003"),
    ("tpu001", "FL-TPU001"),
    ("tpu002", "FL-TPU002"),
    ("res001", "FL-RES001"),
    ("res001_tpe", "FL-RES001"),  # executor/scan-handle shapes of the rule
    ("res001_remote", "FL-RES001"),  # remote session/pool + factory shapes
    ("res001_serve", "FL-RES001"),  # serving cache/context/dataset shapes
    ("alloc001", "FL-ALLOC001"),
    ("obs001", "FL-OBS001"),
]


@pytest.mark.parametrize("stem,rule", CASES)
def test_bad_fixture_caught(stem, rule):
    violations = analyze_file(FIXTURES / f"{stem}_bad.py")
    assert any(v.rule == rule for v in violations), (
        f"{stem}_bad.py should trip {rule}; got {violations!r}"
    )


@pytest.mark.parametrize("stem,rule", CASES)
def test_good_fixture_clean(stem, rule):
    violations = analyze_file(FIXTURES / f"{stem}_good.py")
    assert violations == [], (
        f"{stem}_good.py should be clean; got "
        f"{[v.render() for v in violations]}"
    )


def test_every_rule_has_a_fixture_pair():
    covered = {rule for _, rule in CASES}
    assert covered == {rule for rule, _ in ALL_RULES}
    for stem, _ in CASES:
        assert (FIXTURES / f"{stem}_bad.py").exists()
        assert (FIXTURES / f"{stem}_good.py").exists()


def test_live_tree_is_clean():
    """The acceptance gate: the analyzer exits clean on the real code
    (suppressions allowed — each carries an in-code justification)."""
    result = run([str(ROOT / "parquet_floor_tpu"), str(ROOT / "tests"),
                  str(ROOT / "scripts")])
    assert result.ok, "\n".join(v.render() for v in result.violations)
    assert result.files > 50  # the walk really covered the tree


def test_fixture_dir_excluded_from_directory_walks():
    """Walking `tests/` must skip the deliberately-bad fixtures (they are
    only analyzed when named explicitly)."""
    result = run([str(FIXTURES.parent)])
    assert result.ok


def test_suppression_same_line_and_preceding_line(tmp_path):
    bad = ("def f(path):\n"
           "    return open(path).read()\n")
    p = tmp_path / "leak.py"
    p.write_text(bad)
    assert not run([str(p)]).ok

    p.write_text("def f(path):\n"
                 "    return open(path).read()  # floorlint: disable=FL-RES001\n")
    r = run([str(p)])
    assert r.ok and r.suppressed == 1

    p.write_text("def f(path):\n"
                 "    # floorlint: disable=FL-RES\n"
                 "    return open(path).read()\n")
    r = run([str(p)])
    assert r.ok and r.suppressed == 1  # family prefix works too

    p.write_text("# floorlint: disable-file=all\n"
                 "def f(path):\n"
                 "    return open(path).read()\n")
    assert run([str(p)]).ok


def test_baseline_workflow(tmp_path):
    p = tmp_path / "leak.py"
    p.write_text("def f(path):\n    return open(path).read()\n")
    first = run([str(p)])
    assert not first.ok

    baseline_file = tmp_path / "floorlint.baseline"
    write_baseline(baseline_file, first.violations)
    baseline = load_baseline(baseline_file)
    again = run([str(p)], baseline=baseline)
    assert again.ok and again.baselined == len(first.violations)

    # a NEW violation is still reported even with the baseline in place
    p.write_text("def f(path):\n"
                 "    return open(path).read()\n"
                 "def g(path):\n"
                 "    return open(path).read()\n")
    third = run([str(p)], baseline=load_baseline(baseline_file))
    assert len(third.violations) == 1


def test_checked_in_baseline_is_empty():
    assert sum(load_baseline(ROOT / "floorlint.baseline").values()) == 0


def test_cli_exit_codes(tmp_path):
    env_cwd = str(ROOT)
    bad = FIXTURES / "res001_bad.py"
    good = FIXTURES / "res001_good.py"
    rc_bad = subprocess.call(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(bad), "--no-baseline"], cwd=env_cwd,
        stdout=subprocess.DEVNULL)
    rc_good = subprocess.call(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(good), "--no-baseline"], cwd=env_cwd,
        stdout=subprocess.DEVNULL)
    assert (rc_bad, rc_good) == (1, 0)


def test_cli_list_rules():
    out = subprocess.check_output(
        [sys.executable, "-m", "parquet_floor_tpu.analysis", "--list-rules"],
        cwd=str(ROOT), text=True)
    for rule, _ in ALL_RULES:
        assert rule in out


def test_scope_directive_opts_file_in(tmp_path):
    """Without scope=, FL-ALLOC only applies under format/; the directive
    pulls an arbitrary file in (how the fixtures work)."""
    body = ("import numpy as np\n\n\n"
            "def f(buf):\n"
            "    n = int.from_bytes(buf[:4], 'little')\n"
            "    return np.empty(n, dtype=np.uint8)\n")
    p = tmp_path / "mod.py"
    p.write_text(body)
    assert run([str(p)]).ok  # out of scope: not flagged
    p.write_text("# floorlint: scope=FL-ALLOC\n" + body)
    assert not run([str(p)]).ok


def test_exc001_split_transient_arms_not_flagged(tmp_path):
    """`except OSError: raise` + `except MemoryError as e: raise e` as
    separate arms protect transients just as well as one tuple arm."""
    p = tmp_path / "mod.py"
    p.write_text(
        "# floorlint: scope=FL-EXC001\n"
        "def f(data):\n"
        "    try:\n"
        "        return data.decode()\n"
        "    except OSError:\n"
        "        raise\n"
        "    except MemoryError as e:\n"
        "        raise e\n"
        "    except Exception as e:\n"
        "        raise ValueError(f'bad: {e}') from e\n"
    )
    r = run([str(p)])
    assert r.ok, [v.render() for v in r.violations]


def test_analyze_file_honors_suppressions(tmp_path):
    """The public analyze_file API reports the same verdicts as the CLI:
    a suppressed line is not a violation."""
    p = tmp_path / "leak.py"
    p.write_text("def f(path):\n"
                 "    return open(path).read()  # floorlint: disable=FL-RES001\n")
    assert analyze_file(p) == []


def test_exc001_nested_handler_raise_does_not_shadow(tmp_path):
    """A bare `raise` inside a NESTED except handler re-raises the nested
    exception, not the outer one — it must not count as the outer broad
    handler re-raising, nor may nested wrap-raises be attributed out."""
    p = tmp_path / "mod.py"
    p.write_text(
        "# floorlint: scope=FL-EXC001\n"
        "def f(data, cleanup):\n"
        "    try:\n"
        "        return data.decode()\n"
        "    except Exception as e:\n"
        "        try:\n"
        "            cleanup()\n"
        "        except KeyError:\n"
        "            raise\n"
        "        raise ValueError(f'bad: {e}') from e\n"
    )
    r = run([str(p)])
    assert [v.rule for v in r.violations] == ["FL-EXC001"], (
        [v.render() for v in r.violations]
    )
