"""floorlint (parquet_floor_tpu.analysis) self-tests.

Three layers: per-rule seeded fixture pairs (one violating, one clean)
under ``tests/analysis_fixtures/``, the meta-test that the analyzer runs
clean on the live tree (the same gate ``scripts/lint.py`` enforces), and
the CLI/suppression/baseline workflows."""

import pathlib
import subprocess
import sys

import pytest

from parquet_floor_tpu.analysis import (
    ALL_RULES,
    analyze_file,
    iter_python_files,
    load_baseline,
    run,
    write_baseline,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "analysis_fixtures"

CASES = [
    ("exc001", "FL-EXC001"),
    ("exc002", "FL-EXC002"),
    ("exc003", "FL-EXC003"),
    ("tpu001", "FL-TPU001"),
    ("tpu002", "FL-TPU002"),
    ("tpu_chain", "FL-TPU001"),  # call-graph: helper reached from a jit,
    #                              partial hop; good pins the depth bound
    ("tpu_ann", "FL-TPU001"),   # annotated receivers: param / local /
    #                             class-body attr annotations pin types
    ("tpu_attr_chain", "FL-TPU001"),  # chained annotated attribute
    #                             receivers (param.attr.method()) — the
    #                             PR 12 blind spot; good pins the
    #                             untyped-hop under-approximation
    ("res001", "FL-RES001"),
    ("res001_tpe", "FL-RES001"),  # executor/scan-handle shapes of the rule
    ("res001_remote", "FL-RES001"),  # remote session/pool + factory shapes
    ("res001_serve", "FL-RES001"),  # serving cache/context/dataset shapes
    ("res001_shm", "FL-RES001"),  # shm segment / daemon / client shapes
    #                               (classmethod factories create/attach
    #                               are acquisitions too)
    ("res001_fleet", "FL-RES001"),  # fleet fabric: FleetCache owns its
    #                               peer sockets, PeerClient one socket
    ("res001_mesh", "FL-RES001"),  # per-device pools: DevicePools + the
    #                               container-of-acquisitions shape
    #                               (good pins iterate-release in
    #                               finally)
    ("res001_query", "FL-RES001"),  # query subsystem: a JoinCursor pins
    #                               both corpora's readers until close()
    ("alloc001", "FL-ALLOC001"),
    ("obs001", "FL-OBS001"),
    ("lock001", "FL-LOCK001"),
    ("lock002", "FL-LOCK002"),
    ("lock003", "FL-LOCK003"),
    ("lock004", "FL-LOCK004"),
    ("race001", "FL-RACE001"),  # guarded field touched outside its
    #                             inferred guard: multi-site and
    #                             thread-reachable arms
    ("race002", "FL-RACE002"),  # check-then-act with the guard dropped:
    #                             classic if-read-branch-write arm and
    #                             the writer-side unlocked-check arm
    #                             (good pins double-checked locking)
    ("race_ann", "FL-RACE001"),  # `# floorlint: unguarded=<why>` escape
    ("race_once", "FL-RACE001"),  # assign-once / immutable-after-publish
    #                             escape (the membership-snapshot shape)
    ("race_flight", "FL-RACE001"),  # FP pin: single-flight
    #                             release-before-wait
    ("race_checkout", "FL-RACE001"),  # FP pin: PeerClient connection
    #                             checkout (locked swap, unlocked local)
    ("async001", "FL-ASYNC001"),  # blocking sink in coroutine context;
    #                             good pins the run_in_executor offload
    ("async002", "FL-ASYNC002"),  # await holding a threading lock
    ("async003", "FL-ASYNC003"),  # bare-statement coroutine never runs
]


@pytest.mark.parametrize("stem,rule", CASES)
def test_bad_fixture_caught(stem, rule):
    violations = analyze_file(FIXTURES / f"{stem}_bad.py")
    assert any(v.rule == rule for v in violations), (
        f"{stem}_bad.py should trip {rule}; got {violations!r}"
    )


@pytest.mark.parametrize("stem,rule", CASES)
def test_good_fixture_clean(stem, rule):
    violations = analyze_file(FIXTURES / f"{stem}_good.py")
    assert violations == [], (
        f"{stem}_good.py should be clean; got "
        f"{[v.render() for v in violations]}"
    )


def test_every_rule_has_a_fixture_pair():
    covered = {rule for _, rule in CASES}
    assert covered == {rule for rule, _ in ALL_RULES}
    for stem, _ in CASES:
        assert (FIXTURES / f"{stem}_bad.py").exists()
        assert (FIXTURES / f"{stem}_good.py").exists()


def test_live_tree_is_clean():
    """The acceptance gate: the analyzer exits clean on the real code —
    ALL families, the v3 FL-RACE/FL-ASYNC rules included (suppressions
    allowed; each carries an in-code justification)."""
    result = run([str(ROOT / "parquet_floor_tpu"), str(ROOT / "tests"),
                  str(ROOT / "scripts")])
    assert result.ok, "\n".join(v.render() for v in result.violations)
    assert result.files > 50  # the walk really covered the tree


def test_race_model_guards_the_serving_fabric():
    """The lockset inference actually has coverage: the guard map over
    the live tree binds the fleet/cache/daemon-adjacent fields this PR
    exists to protect (an empty map would mean the rules pass
    vacuously)."""
    from parquet_floor_tpu.analysis.core import _parse_contexts
    from parquet_floor_tpu.analysis import build_project
    from parquet_floor_tpu.analysis.rules_race import race_model

    contexts, _ = _parse_contexts([str(ROOT / "parquet_floor_tpu")])
    _findings, guards = race_model(build_project(contexts))
    flat = {f"{cls.rsplit('.', 1)[-1]}.{field}"
            for cls, fields in guards.items() for field in fields}
    for expected in ("FleetCache._peers", "FleetCache._flights",
                     "PeerClient._sock", "SharedBufferCache._used_data",
                     "CircuitBreaker._failures", "Tracer._counters"):
        assert expected in flat, f"{expected} lost its inferred guard"


def test_fixture_dir_excluded_from_directory_walks():
    """Walking `tests/` must skip the deliberately-bad fixtures (they are
    only analyzed when named explicitly)."""
    result = run([str(FIXTURES.parent)])
    assert result.ok


def test_lint_gate_floorlints_tests_but_skips_fixture_dir():
    """scripts/lint.py's floorlint stage covers tests/ — and the walk it
    triggers must skip the deliberately-bad fixture dir (explicit paths
    only), or the gate would fail on its own seed corpus."""
    src = (ROOT / "scripts" / "lint.py").read_text()
    targets = src.split("FLOORLINT_TARGETS")[1].split("]")[0]
    assert '"tests"' in targets
    walked = list(iter_python_files([str(ROOT / "tests")]))
    assert walked, "the tests/ walk found files"
    assert not any("analysis_fixtures" in str(p) for p in walked)


def test_suppression_same_line_and_preceding_line(tmp_path):
    bad = ("def f(path):\n"
           "    return open(path).read()\n")
    p = tmp_path / "leak.py"
    p.write_text(bad)
    assert not run([str(p)]).ok

    p.write_text("def f(path):\n"
                 "    return open(path).read()  # floorlint: disable=FL-RES001\n")
    r = run([str(p)])
    assert r.ok and r.suppressed == 1

    p.write_text("def f(path):\n"
                 "    # floorlint: disable=FL-RES\n"
                 "    return open(path).read()\n")
    r = run([str(p)])
    assert r.ok and r.suppressed == 1  # family prefix works too

    p.write_text("# floorlint: disable-file=all\n"
                 "def f(path):\n"
                 "    return open(path).read()\n")
    assert run([str(p)]).ok


def test_baseline_workflow(tmp_path):
    p = tmp_path / "leak.py"
    p.write_text("def f(path):\n    return open(path).read()\n")
    first = run([str(p)])
    assert not first.ok

    baseline_file = tmp_path / "floorlint.baseline"
    write_baseline(baseline_file, first.violations)
    baseline = load_baseline(baseline_file)
    again = run([str(p)], baseline=baseline)
    assert again.ok and again.baselined == len(first.violations)

    # a NEW violation is still reported even with the baseline in place
    p.write_text("def f(path):\n"
                 "    return open(path).read()\n"
                 "def g(path):\n"
                 "    return open(path).read()\n")
    third = run([str(p)], baseline=load_baseline(baseline_file))
    assert len(third.violations) == 1


def test_baseline_span_fingerprint_survives_line_moves_and_rewording(
        tmp_path):
    """Fingerprints are ``path:RULE:normalized-span`` — keyed on the
    violating SOURCE LINE, not the message (rewording a rule's message
    must not orphan entries: the PR 2 bug) and not the line number
    (unrelated edits above must not churn the file).  Legacy
    message-keyed entries still match during the transition."""
    p = tmp_path / "leak.py"
    p.write_text("def f(path):\n    return open(path).read()\n")
    first = run([str(p)])
    assert not first.ok
    baseline_file = tmp_path / "fl.baseline"
    write_baseline(baseline_file, first.violations)
    text = baseline_file.read_text()
    assert "return open(path).read()" in text     # span-keyed
    assert first.violations[0].message not in text  # NOT message-keyed

    # unrelated edit shifts the line: still baselined
    p.write_text("# unrelated comment\n\n"
                 "def f(path):\n    return open(path).read()\n")
    again = run([str(p)], baseline=load_baseline(baseline_file))
    assert again.ok and again.baselined == 1

    # legacy (message-keyed) entries keep matching
    legacy = tmp_path / "legacy.baseline"
    legacy.write_text(first.violations[0].legacy_fingerprint() + "\n")
    r = run([str(p)], baseline=load_baseline(legacy))
    assert r.ok and r.baselined == 1


def test_cli_update_baseline_rekeys_legacy_entries(tmp_path):
    """--update-baseline regenerates the file in the span format:
    violations the old (legacy message-keyed) baseline accepted come
    back span-keyed; nothing new is silently blessed."""
    p = tmp_path / "leak.py"
    p.write_text("def f(path):\n    return open(path).read()\n")
    first = run([str(p)])
    bl = tmp_path / "fl.baseline"
    bl.write_text(first.violations[0].legacy_fingerprint() + "\n")
    rc = subprocess.call(
        [sys.executable, "-m", "parquet_floor_tpu.analysis", str(p),
         "--baseline", str(bl), "--update-baseline"],
        cwd=str(ROOT), stdout=subprocess.DEVNULL)
    assert rc == 0  # everything was accepted, nothing new
    text = bl.read_text()
    assert "return open(path).read()" in text
    assert first.violations[0].message not in text
    r = run([str(p)], baseline=load_baseline(bl))
    assert r.ok and r.baselined == 1

    # a NEW violation is not blessed by the regeneration: it reports
    p.write_text("def f(path):\n    return open(path).read()\n"
                 "def g(path):\n    return open(path).read()\n")
    rc2 = subprocess.call(
        [sys.executable, "-m", "parquet_floor_tpu.analysis", str(p),
         "--baseline", str(bl), "--update-baseline"],
        cwd=str(ROOT), stdout=subprocess.DEVNULL)
    assert rc2 == 1


def test_checked_in_baseline_is_empty():
    assert sum(load_baseline(ROOT / "floorlint.baseline").values()) == 0


def test_cli_exit_codes(tmp_path):
    env_cwd = str(ROOT)
    bad = FIXTURES / "res001_bad.py"
    good = FIXTURES / "res001_good.py"
    rc_bad = subprocess.call(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(bad), "--no-baseline"], cwd=env_cwd,
        stdout=subprocess.DEVNULL)
    rc_good = subprocess.call(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(good), "--no-baseline"], cwd=env_cwd,
        stdout=subprocess.DEVNULL)
    assert (rc_bad, rc_good) == (1, 0)


def test_cli_list_rules():
    out = subprocess.check_output(
        [sys.executable, "-m", "parquet_floor_tpu.analysis", "--list-rules"],
        cwd=str(ROOT), text=True)
    for rule, _ in ALL_RULES:
        assert rule in out


def test_scope_directive_opts_file_in(tmp_path):
    """Without scope=, FL-ALLOC only applies under format/; the directive
    pulls an arbitrary file in (how the fixtures work)."""
    body = ("import numpy as np\n\n\n"
            "def f(buf):\n"
            "    n = int.from_bytes(buf[:4], 'little')\n"
            "    return np.empty(n, dtype=np.uint8)\n")
    p = tmp_path / "mod.py"
    p.write_text(body)
    assert run([str(p)]).ok  # out of scope: not flagged
    p.write_text("# floorlint: scope=FL-ALLOC\n" + body)
    assert not run([str(p)]).ok


def test_exc001_split_transient_arms_not_flagged(tmp_path):
    """`except OSError: raise` + `except MemoryError as e: raise e` as
    separate arms protect transients just as well as one tuple arm."""
    p = tmp_path / "mod.py"
    p.write_text(
        "# floorlint: scope=FL-EXC001\n"
        "def f(data):\n"
        "    try:\n"
        "        return data.decode()\n"
        "    except OSError:\n"
        "        raise\n"
        "    except MemoryError as e:\n"
        "        raise e\n"
        "    except Exception as e:\n"
        "        raise ValueError(f'bad: {e}') from e\n"
    )
    r = run([str(p)])
    assert r.ok, [v.render() for v in r.violations]


def test_analyze_file_honors_suppressions(tmp_path):
    """The public analyze_file API reports the same verdicts as the CLI:
    a suppressed line is not a violation."""
    p = tmp_path / "leak.py"
    p.write_text("def f(path):\n"
                 "    return open(path).read()  # floorlint: disable=FL-RES001\n")
    assert analyze_file(p) == []


def test_tpu_chain_reports_at_jit_site_with_chain():
    """The call-graph FL-TPU finding lands at the call site inside the
    traced function, names the sink helper, and carries the chain —
    including the functools.partial hop (depth 2)."""
    vs = analyze_file(FIXTURES / "tpu_chain_bad.py")
    assert [v.rule for v in vs] == ["FL-TPU001"]
    v = vs[0]
    assert "_limit_for(path)" in (FIXTURES / "tpu_chain_bad.py").read_text(
    ).splitlines()[v.line - 1]
    assert "_read_config" in v.message and "->" in v.message
    assert len(v.chain) == 3  # decode_step -> _limit_for -> _read_config


def test_tpu_cross_module_needs_the_project_pass():
    """Analyzed together, the import edge resolves and the jit file is
    flagged (the helper file stays clean — nothing there is traced);
    analyzed alone, the edge dangles and the file is clean.  Pins that
    chain findings come from resolved edges, never guesses."""
    jit_f = FIXTURES / "tpu_xmod_jit.py"
    helper = FIXTURES / "tpu_xmod_helper.py"
    together = run([str(jit_f), str(helper)])
    assert [v.rule for v in together.violations] == ["FL-TPU001"]
    v = together.violations[0]
    assert v.path.endswith("tpu_xmod_jit.py")
    assert "read_limit" in v.message and "->" in v.message
    assert analyze_file(jit_f) == []
    assert analyze_file(helper) == []


def test_lock002_chain_reported_at_lock_site():
    """The chained FL-LOCK002 finding points at the call under the lock
    and names both the chain and the blocking sink's location."""
    vs = [v for v in analyze_file(FIXTURES / "lock002_bad.py")
          if "via" in v.message]
    assert vs, "expected chained findings"
    assert any("time.sleep" in v.message for v in vs)
    assert any(".read_at()" in v.message for v in vs)
    for v in vs:
        assert "_fetch" in v.message and "->" in v.message


def test_lock003_blessed_wait_is_not_lock002():
    """Condition.wait on the condition the `with` block holds releases
    it — the good LOCK003 fixture must not trip FL-LOCK002 either."""
    assert analyze_file(FIXTURES / "lock003_good.py") == []


def test_lock004_both_orders_reported():
    vs = analyze_file(FIXTURES / "lock004_bad.py")
    assert [v.rule for v in vs] == ["FL-LOCK004", "FL-LOCK004"]
    msgs = " | ".join(v.message for v in vs)
    assert "_accounts" in msgs and "_audit" in msgs
    assert any("via" in v.message for v in vs)  # the chained direction


def test_scope_directive_parity_under_project_pass(tmp_path):
    """The project pass honors per-file `# floorlint: scope=` and
    `disable=` directives exactly like the old per-file pass: the same
    file analyzed alone and inside a multi-file run gets identical
    verdicts, and a scoped file never leaks its opt-in to siblings."""
    scoped = tmp_path / "scoped.py"
    scoped.write_text(
        "# floorlint: scope=FL-LOCK\n"
        "import threading\n"
        "_lock = threading.Lock()\n\n\n"
        "def f(registry):\n"
        "    _lock.acquire()\n"
        "    registry.clear()\n"
        "    _lock.release()\n"
    )
    sibling = tmp_path / "sibling.py"
    sibling.write_text(  # same shape, NO scope=: out of FL-LOCK scope
        "import threading\n"
        "_lock = threading.Lock()\n\n\n"
        "def f(registry):\n"
        "    _lock.acquire()\n"
        "    registry.clear()\n"
        "    _lock.release()\n"
    )
    alone = analyze_file(scoped)
    project_run = run([str(scoped), str(sibling)])
    assert [v.rule for v in alone] == ["FL-LOCK001"]
    assert [v.rule for v in project_run.violations] == ["FL-LOCK001"]
    assert all("sibling" not in v.path for v in project_run.violations)

    # a line disable suppresses the project-pass verdict identically
    scoped.write_text(
        "# floorlint: scope=FL-LOCK\n"
        "import threading\n"
        "_lock = threading.Lock()\n\n\n"
        "def f(registry):\n"
        "    _lock.acquire()  # floorlint: disable=FL-LOCK001\n"
        "    registry.clear()\n"
        "    _lock.release()\n"
    )
    assert analyze_file(scoped) == []
    again = run([str(scoped), str(sibling)])
    assert again.ok and again.suppressed == 1


def test_init_relative_imports_resolve_into_the_package(tmp_path):
    """An __init__.py's module name IS its package, so `from .core
    import helper` there must resolve into the package — a chain
    through an init re-export stays visible to the graph rules."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "core.py").write_text(
        "def helper(path):\n"
        "    with open(path) as fh:\n"
        "        return len(fh.read())\n"
    )
    (pkg / "__init__.py").write_text(
        "# floorlint: scope=FL-TPU\n"
        "from .core import helper\n\n\n"
        "def jit(fn):\n"
        "    return fn\n\n\n"
        "@jit\n"
        "def step(payload, path):\n"
        "    return payload + helper(path)\n"
    )
    r = run([str(pkg / "__init__.py"), str(pkg / "core.py")])
    assert [v.rule for v in r.violations] == ["FL-TPU001"], (
        [v.render() for v in r.violations]
    )
    assert "helper" in r.violations[0].message


def test_cyclic_class_bases_do_not_crash(tmp_path):
    """`class A(B)` / `class B(A)` parses fine (the analyzer is static);
    lock-attribute inheritance lookup must terminate, not recurse."""
    p = tmp_path / "cyc.py"
    p.write_text(
        "# floorlint: scope=FL-LOCK\n"
        "import threading\n\n\n"
        "class A(B):\n"
        "    def run(self):\n"
        "        with self._lock:\n"
        "            pass\n\n\n"
        "class B(A):\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
    )
    assert run([str(p)]).ok  # and, crucially, no RecursionError


def test_lock002_chained_wait_keeps_callers_lock_flagged(tmp_path):
    """Moving a cv-wait into a helper must not silence FL-LOCK002: the
    helper's Condition.wait releases only ITS cv — the caller's
    distinct lock stays held while the wait blocks."""
    p = tmp_path / "mod.py"
    p.write_text(
        "# floorlint: scope=FL-LOCK\n"
        "import threading\n\n\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition()\n"
        "        self.ready = False\n\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self.helper()\n\n"
        "    def helper(self):\n"
        "        with self._cv:\n"
        "            while not self.ready:\n"
        "                self._cv.wait()\n"
    )
    r = run([str(p)])
    waits = [v for v in r.violations
             if v.rule == "FL-LOCK002" and ".wait()" in v.message]
    assert waits, [v.render() for v in r.violations]
    assert "_lock" in waits[0].message and "helper" in waits[0].message


def test_lock004_multi_item_with_counts_as_nesting(tmp_path):
    """`with a, b:` is Python-defined as the nested form — its
    left-to-right order must pair against an explicit b→a nesting."""
    p = tmp_path / "mod.py"
    p.write_text(
        "# floorlint: scope=FL-LOCK\n"
        "import threading\n\n\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n\n"
        "    def one(self):\n"
        "        with self._a, self._b:\n"
        "            pass\n\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    r = run([str(p)])
    assert [v.rule for v in r.violations] == ["FL-LOCK004", "FL-LOCK004"], (
        [v.render() for v in r.violations]
    )


def test_cli_json_format():
    """--format=json: one machine-readable document with rule id, path,
    line, message, and the call chain; exit code matches the text form."""
    import json

    proc = subprocess.run(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(FIXTURES / "tpu_chain_bad.py"), "--no-baseline",
         "--format=json"],
        cwd=str(ROOT), text=True, capture_output=True)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["ok"] is False and doc["files"] == 1
    (v,) = doc["violations"]
    assert v["rule"] == "FL-TPU001"
    assert v["path"].endswith("tpu_chain_bad.py")
    assert isinstance(v["line"], int) and v["line"] > 0
    assert len(v["call_chain"]) == 3

    clean = subprocess.run(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(FIXTURES / "lock001_good.py"), "--no-baseline",
         "--format=json"],
        cwd=str(ROOT), text=True, capture_output=True)
    assert clean.returncode == 0
    assert json.loads(clean.stdout)["ok"] is True


def test_incremental_cache_warm_hit_and_invalidation(tmp_path):
    """Warm run with nothing changed is a run-tier hit (identical
    verdicts, from_cache set); touching a file invalidates the run tier
    but keeps the verdicts correct — an edit that INTRODUCES a
    violation is seen, never masked by stale artifacts."""
    from parquet_floor_tpu.analysis.cache import LintCache

    p = tmp_path / "mod.py"
    p.write_text("def f(path):\n    with open(path) as fh:\n"
                 "        return fh.read()\n")
    cache = LintCache(tmp_path / ".floorlint_cache")
    cold = run([str(p)], cache=cache)
    assert cold.ok and not cold.from_cache
    warm = run([str(p)], cache=cache)
    assert warm.ok and warm.from_cache

    # the edit lands a leak: the cache must not hide it
    p.write_text("def f(path):\n    return open(path).read()\n")
    third = run([str(p)], cache=cache)
    assert not third.ok and not third.from_cache
    again = run([str(p)], cache=cache)
    assert not again.ok and again.from_cache  # new verdict cached too


def test_cache_corruption_falls_back(tmp_path):
    """A truncated/garbage artifact — context tier or run tier — is a
    miss, never an error: the engine silently does the full pass and
    repairs the cache."""
    from parquet_floor_tpu.analysis.cache import LintCache

    p = tmp_path / "mod.py"
    p.write_text("def f(path):\n    return open(path).read()\n")
    root = tmp_path / ".floorlint_cache"
    cache = LintCache(root)
    first = run([str(p)], cache=cache)
    assert not first.ok
    for artifact in root.rglob("*.pkl"):
        artifact.write_bytes(b"not a pickle")
    again = run([str(p)], cache=cache)
    assert not again.from_cache  # corrupt run tier did not serve
    assert [v.rule for v in again.violations] == \
        [v.rule for v in first.violations]
    healed = run([str(p)], cache=cache)
    assert healed.from_cache  # the full pass re-stored good artifacts


def test_cache_invalidates_on_analyzer_change(tmp_path, monkeypatch):
    """The analyzer stamp folds analysis/*.py into every key: a rule
    edit must orphan all artifacts (here: forced by faking the
    stamp)."""
    from parquet_floor_tpu.analysis.cache import LintCache

    p = tmp_path / "mod.py"
    p.write_text("def f(path):\n    return open(path).read()\n")
    root = tmp_path / ".floorlint_cache"
    first = run([str(p)], cache=LintCache(root))
    fresh = LintCache(root)
    fresh._stamp = "different-analyzer"
    redo = run([str(p)], cache=fresh)
    assert not redo.from_cache
    assert [v.rule for v in redo.violations] == \
        [v.rule for v in first.violations]


def test_cli_sarif_format():
    """--format=sarif: a SARIF 2.1.0 document — version, driver rule
    metadata, one result per violation with a physical location, and
    the call chain round-tripped through relatedLocations in root→sink
    order."""
    import json

    proc = subprocess.run(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(FIXTURES / "tpu_chain_bad.py"), "--no-baseline",
         "--format=sarif"],
        cwd=str(ROOT), text=True, capture_output=True)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert "sarif-2.1.0" in doc["$schema"]
    (sarif_run,) = doc["runs"]
    driver = sarif_run["tool"]["driver"]
    assert driver["name"] == "floorlint"
    rule_ids = [r["id"] for r in driver["rules"]]
    for rule, _ in ALL_RULES:
        assert rule in rule_ids
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]

    (res,) = sarif_run["results"]
    assert res["ruleId"] == "FL-TPU001"
    assert res["level"] == "error"
    assert driver["rules"][res["ruleIndex"]]["id"] == "FL-TPU001"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("tpu_chain_bad.py")
    assert loc["region"]["startLine"] > 0

    # the chain round-trips: one relatedLocation per hop, in order
    vs = analyze_file(FIXTURES / "tpu_chain_bad.py")
    hops = [rl["message"]["text"] for rl in res["relatedLocations"]]
    assert hops == list(vs[0].chain) and len(hops) == 3

    clean = subprocess.run(
        [sys.executable, "-m", "parquet_floor_tpu.analysis",
         str(FIXTURES / "lock001_good.py"), "--no-baseline",
         "--format=sarif"],
        cwd=str(ROOT), text=True, capture_output=True)
    assert clean.returncode == 0
    assert json.loads(clean.stdout)["runs"][0]["results"] == []


def test_race001_thread_chain_in_message():
    """The thread-reachable arm names the spawn shape and the chain
    from the thread entry in the finding text."""
    vs = [v for v in analyze_file(FIXTURES / "race001_bad.py")
          if v.rule == "FL-RACE001"]
    assert vs, "race001_bad must fire"
    assert any("written under" in v.message for v in vs)


def test_async001_chained_finding_carries_chain():
    """The chained FL-ASYNC001 finding lands at the coroutine's call
    site and carries the handler→helper chain."""
    vs = [v for v in analyze_file(FIXTURES / "async001_bad.py")
          if v.rule == "FL-ASYNC001" and "via" in v.message]
    assert vs, "expected a chained finding"
    assert vs[0].chain and vs[0].chain[0] == "handle"
    assert "storage read" in vs[0].message


def test_exc001_nested_handler_raise_does_not_shadow(tmp_path):
    """A bare `raise` inside a NESTED except handler re-raises the nested
    exception, not the outer one — it must not count as the outer broad
    handler re-raising, nor may nested wrap-raises be attributed out."""
    p = tmp_path / "mod.py"
    p.write_text(
        "# floorlint: scope=FL-EXC001\n"
        "def f(data, cleanup):\n"
        "    try:\n"
        "        return data.decode()\n"
        "    except Exception as e:\n"
        "        try:\n"
        "            cleanup()\n"
        "        except KeyError:\n"
        "            raise\n"
        "        raise ValueError(f'bad: {e}') from e\n"
    )
    r = run([str(p)])
    assert [v.rule for v in r.violations] == ["FL-EXC001"], (
        [v.render() for v in r.violations]
    )
