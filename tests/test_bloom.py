"""Split-block Bloom filters: XXH64 exactness, SBBF round-trip, pyarrow
interop (both directions), and predicate row-group pruning.

Capability parity: parquet-mr 1.12's bloom filter surface
(ColumnMetaData fields 14/15), which the reference links against.
"""

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    col,
    types,
)
from parquet_floor_tpu.format.bloom import (
    SplitBlockBloomFilter,
    hash_values,
    optimal_num_bytes,
    xxh64,
    xxh64_fixed,
)
from parquet_floor_tpu.format.parquet_thrift import Type

rng = np.random.default_rng(11)


# -- XXH64 ------------------------------------------------------------------

def test_xxh64_known_vectors():
    # public xxHash reference vectors, seed 0
    assert xxh64(b"") == 0xEF46DB3751D8E999
    assert xxh64(b"a") == 0xD24EC4F1A98C6E5B
    assert xxh64(b"abc") == 0x44BC2CF5AD770999
    assert xxh64(b"Nobody inspects the spammish repetition") == 0xFBCEA83C8A378BF1
    # ≥ 32 bytes exercises the stripe loop
    assert xxh64(bytes(range(64))) == xxh64(bytes(range(64)))


@pytest.mark.parametrize("width", list(range(1, 9)))
def test_xxh64_fixed_matches_scalar(width):
    rows = rng.integers(0, 256, (500, width)).astype(np.uint8)
    got = xxh64_fixed(rows)
    want = np.array([xxh64(r.tobytes()) for r in rows], np.uint64)
    np.testing.assert_array_equal(got, want)


# -- SBBF -------------------------------------------------------------------

def test_sbbf_no_false_negatives_and_wire_roundtrip():
    vals = rng.integers(-(2**62), 2**62, 5000)
    h = hash_values(Type.INT64, vals)
    bf = SplitBlockBloomFilter(optimal_num_bytes(5000, 0.01))
    bf.insert_hashes(h)
    assert bf.check_hashes(h).all()
    # absent values: false-positive rate near the configured fpp
    absent = hash_values(Type.INT64, rng.integers(-(2**62), 2**62, 4000) | 1)
    fp = bf.check_hashes(absent).mean()
    assert fp < 0.05
    # wire round-trip preserves every bit
    back = SplitBlockBloomFilter.from_bytes(bf.to_bytes())
    np.testing.assert_array_equal(back.bitset, bf.bitset)
    assert back.check_hashes(h).all()


def test_optimal_num_bytes_monotone():
    a = optimal_num_bytes(100, 0.01)
    b = optimal_num_bytes(100_000, 0.01)
    c = optimal_num_bytes(100_000, 0.0001)
    assert 32 <= a < b < c
    for v in (a, b, c):
        assert v & (v - 1) == 0  # power of two


# -- file round-trip + predicate pruning -----------------------------------

def _write_two_groups(tmp_path, with_bloom=True):
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    opts = WriterOptions(
        bloom_filter_columns={"k": True, "s": {"fpp": 0.005}} if with_bloom else None,
        row_group_rows=1000,
    )
    path = tmp_path / "bf.parquet"
    with ParquetFileWriter(path, schema, opts) as w:
        # both groups share the SAME min/max envelope so min/max stats
        # cannot prune an equality probe — only the bloom filter can
        w.write_columns({"k": np.r_[0, np.arange(2, 1998, 2), 10_000],
                         "s": [f"even_{i}" for i in range(1000)]})
        w.write_columns({"k": np.r_[0, np.arange(1, 1997, 2), 10_000],
                         "s": [f"odd_{i}" for i in range(1000)]})
    return path


def test_bloom_roundtrip_and_pruning(tmp_path):
    path = _write_two_groups(tmp_path)
    with ParquetFileReader(path) as r:
        for rg in r.row_groups:
            for chunk in rg.columns:
                bf = r.read_bloom_filter(chunk)
                assert bf is not None and bf.num_bytes >= 32
        # value 222 is even: lives in group 0 only; stats can't tell
        assert (col("k") == 222).row_groups(r) == [0]
        assert (col("k") == 333).row_groups(r) == [1]
        # absent everywhere (within [0, 10000] so stats keep both)
        assert (col("k") == 5555).row_groups(r) == []
        # string bloom
        assert (col("s") == "even_7").row_groups(r) == [0]
        assert (col("s") == "odd_7").row_groups(r) == [1]
        assert (col("s") == "missing").row_groups(r) == []
        # non-equality ops never consult the bloom (and still work)
        assert (col("k") > 9_000).row_groups(r) == [0, 1]


def test_bloom_absent_without_option(tmp_path):
    path = _write_two_groups(tmp_path, with_bloom=False)
    with ParquetFileReader(path) as r:
        for rg in r.row_groups:
            for chunk in rg.columns:
                assert r.read_bloom_filter(chunk) is None
        # equality stays conservative without a bloom
        assert (col("k") == 5555).row_groups(r) == [0, 1]


def test_pyarrow_reads_nothing_dropped(tmp_path):
    """pyarrow must still read files that carry our bloom filters."""
    import pyarrow.parquet as pq

    path = _write_two_groups(tmp_path)
    t = pq.read_table(path)
    assert t.num_rows == 2000
    assert t.column("s").to_pylist()[0] == "even_0"


def test_pyarrow_written_bloom_interop(tmp_path):
    """Read pyarrow-written blooms: no false negatives, equality pruning."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "pa_bf.parquet")
    k = np.arange(0, 3000, 3, dtype=np.int64)        # multiples of 3
    s = [f"cat_{i:04d}" for i in range(1000)]
    pq.write_table(
        pa.table({"k": k, "s": s}), path,
        bloom_filter_options={"k": {"ndv": 1000, "fpp": 0.01},
                              "s": {"ndv": 1000, "fpp": 0.01}},
        use_dictionary=False,
    )
    with ParquetFileReader(path) as r:
        chunk_k = r.row_groups[0].columns[0]
        bf = r.read_bloom_filter(chunk_k)
        assert bf is not None
        assert bf.check_hashes(hash_values(Type.INT64, k)).all()
        assert (col("k") == 333).row_groups(r) == [0]
        assert (col("k") == 334).row_groups(r) == []   # not a multiple of 3
        assert (col("s") == "cat_0042").row_groups(r) == [0]
        assert (col("s") == "dog_0042").row_groups(r) == []


def test_bloom_optional_column_hashes_nonnull_only(tmp_path):
    schema = types.message(
        "t", types.optional(types.INT32).named("v"),
    )
    path = tmp_path / "opt.parquet"
    with ParquetFileWriter(
        path, schema, WriterOptions(bloom_filter_columns={"v": True})
    ) as w:
        w.write_columns({"v": [1, None, 3, None, 5]})
    with ParquetFileReader(path) as r:
        assert (col("v") == 3).row_groups(r) == [0]
        assert (col("v") == 4).row_groups(r) == []


def test_negative_zero_and_overflow_probes(tmp_path):
    schema = types.message(
        "t",
        types.required(types.DOUBLE).named("f"),
        types.required(types.INT32).named("k"),
    )
    path = tmp_path / "z.parquet"
    with ParquetFileWriter(
        path, schema,
        WriterOptions(bloom_filter_columns={"f": True, "k": True}),
    ) as w:
        w.write_columns({"f": np.array([0.0, 1.5, -2.5]),
                         "k": np.array([1, 2, 3], np.int32)})
    with ParquetFileReader(path) as r:
        # -0.0 == 0.0 numerically: the bloom must not prune it
        assert (col("f") == -0.0).row_groups(r) == [0]
        assert (col("f") == 0.0).row_groups(r) == [0]
        # in-range stats: an out-of-int32 literal prunes via min/max
        assert (col("k") == 2**40).row_groups(r) == []

    # stats-less file: the bloom path sees the overflowing literal and
    # must stay conservative instead of crashing
    path2 = tmp_path / "z2.parquet"
    with ParquetFileWriter(
        path2, schema,
        WriterOptions(bloom_filter_columns={"f": True, "k": True},
                      write_statistics=False),
    ) as w:
        w.write_columns({"f": np.array([0.0, 1.5, -2.5]),
                         "k": np.array([1, 2, 3], np.int32)})
    with ParquetFileReader(path2) as r:
        assert (col("k") == 2**40).row_groups(r) == [0]
        assert (col("k") == 2).row_groups(r) == [0]
        assert (col("k") == 7).row_groups(r) == []  # bloom prunes


def test_foreign_negative_zero_not_pruned(tmp_path):
    """A spec-following writer inserts only the stored zero's bit pattern;
    probing either sign of zero must still match (never a false negative)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "nz.parquet")
    pq.write_table(
        pa.table({"f": np.array([-0.0, 7.25])}), path,
        bloom_filter_options={"f": {"ndv": 10, "fpp": 0.01}},
        use_dictionary=False,
    )
    with ParquetFileReader(path) as r:
        assert (col("f") == 0.0).row_groups(r) == [0]
        assert (col("f") == -0.0).row_groups(r) == [0]
        assert (col("f") == 1.0).row_groups(r) == []


def test_close_with_live_page_views(tmp_path):
    """Zero-copy page payloads must not turn close() into a BufferError
    (and must not mask the original exception when a with-block unwinds)."""
    path = _write_two_groups(tmp_path)
    with ParquetFileReader(path) as r:
        pages = r.read_raw_column_chunk(r.row_groups[0].columns[0])
    # reader closed while `pages` still holds views: no BufferError,
    # and the payload bytes stay readable until the views die
    assert len(pages) > 0 and len(bytes(pages[0].payload)) > 0


def test_numpy_string_arrays_hash_like_lists():
    """'S' and '<U' arrays must hash per item (padding-stripped / UTF-8),
    never as raw fixed-width buffers."""
    want = hash_values(Type.BYTE_ARRAY, [b"a", b"ab"])
    got_s = hash_values(Type.BYTE_ARRAY, np.array([b"a", b"ab"], dtype="S2"))
    got_u = hash_values(Type.BYTE_ARRAY, np.array(["a", "ab"], dtype="<U2"))
    np.testing.assert_array_equal(got_s, want)
    np.testing.assert_array_equal(got_u, want)


def test_from_bytes_rejects_malformed_headers():
    bf = SplitBlockBloomFilter(64)
    raw = bytearray(bf.to_bytes())
    good = SplitBlockBloomFilter.from_bytes(bytes(raw))
    assert good.num_bytes == 64
    # corrupt numBytes to a non-multiple-of-32 value (field 1, varint)
    from parquet_floor_tpu.format.thrift import CompactWriter
    from parquet_floor_tpu.format.bloom import (
        BloomFilterHeader, BloomFilterAlgorithm, BloomFilterHash,
        BloomFilterCompression, SplitBlockAlgorithm, XxHash, Uncompressed,
    )
    w = CompactWriter()
    BloomFilterHeader(
        numBytes=40,
        algorithm=BloomFilterAlgorithm(BLOCK=SplitBlockAlgorithm()),
        hash=BloomFilterHash(XXHASH=XxHash()),
        compression=BloomFilterCompression(UNCOMPRESSED=Uncompressed()),
    ).write(w)
    with pytest.raises(ValueError, match="invalid bloom filter size"):
        SplitBlockBloomFilter.from_bytes(w.getvalue() + b"\0" * 40)
