"""Differential corruption fuzz (ISSUE 6 tentpole part d): seeded bit
flips replayed through all four read faces — sequential host, host scan,
device scan, DataLoader — asserting identical quarantine sets, identical
surviving bytes, fatality agreement, and no silent divergence vs the
clean-corpus decode (pyarrow oracle when available).

A small always-on subset runs in tier-1 (host faces every case, device
face sampled); the >=300-case sweep is ``slow``.
"""

import pytest

from parquet_floor_tpu import ReaderOptions
from parquet_floor_tpu.testing.differential import (
    CaseTimeout,
    _pyarrow_clean_groups,
    case_flips,
    differential_case,
    materialize_case,
    run_ranged,
    run_sequential,
    time_limit,
    write_reference_corpus,
)

PER_CASE_TIMEOUT_S = 30.0


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("diff_corpus")
    return write_reference_corpus(str(d))


@pytest.fixture(scope="module")
def oracle(corpus):
    o = _pyarrow_clean_groups(corpus)
    assert o is not None, "pyarrow oracle unavailable in this env"
    return o


def _sweep(corpus, oracle, tmp_path, seeds, device_every=None):
    fails = []
    for seed in seeds:
        faces = ("sequential", "host_scan", "loader")
        if device_every and seed % device_every == 0:
            faces = ("sequential", "host_scan", "device_scan", "loader")
        try:
            differential_case(
                corpus, seed, str(tmp_path), faces=faces,
                clean_oracle=oracle, timeout_s=PER_CASE_TIMEOUT_S,
            )
        except CaseTimeout:
            fails.append((seed, "HANG"))
        except AssertionError as e:
            fails.append((seed, str(e)[:200]))
    assert not fails, "differential divergence:\n" + "\n".join(
        f"  seed {s}: {m}" for s, m in fails
    )


def test_differential_tier1(corpus, oracle, tmp_path):
    """Always-on subset: host faces on every case, the device face on
    every 6th (jit compiles dominate its cost)."""
    _sweep(corpus, oracle, tmp_path, range(24), device_every=6)


@pytest.mark.slow
def test_differential_full(corpus, oracle, tmp_path):
    """The acceptance sweep: >=300 further seeded corruptions through
    the host faces, the device face sampled."""
    _sweep(corpus, oracle, tmp_path, range(24, 330), device_every=25)


def test_case_flips_deterministic(corpus):
    assert case_flips(corpus, 7) == case_flips(corpus, 7)
    assert case_flips(corpus, 7) != case_flips(corpus, 8)


def test_materialized_case_deterministic(corpus, tmp_path):
    a, _ = materialize_case(corpus, 5, tmp_path / "a")
    b, _ = materialize_case(corpus, 5, tmp_path / "b")
    import pathlib

    for pa, pb in zip(a, b):
        assert pathlib.Path(pa).read_bytes() == pathlib.Path(pb).read_bytes()


def test_clean_corpus_is_clean(corpus):
    """Sanity: the uncorrupted corpus salvages to zero quarantines and
    survives the time limit (the harness's own plumbing works)."""
    with time_limit(PER_CASE_TIMEOUT_S):
        res = run_sequential(
            corpus, ReaderOptions(salvage=True, verify_crc=True)
        )
    assert res.fatal is None and res.quarantine == frozenset()
    assert len(res.groups) == 9
    total = sum(
        len(next(iter(g.values()))) for g in res.groups.values()
    )
    assert total == 3 * 1200


def test_fatal_cases_agree(corpus, tmp_path):
    """A footer-destroying flip must be fatal on EVERY face — build one
    explicitly instead of waiting for a lucky seed."""
    import pathlib

    data = bytearray(pathlib.Path(corpus[1]).read_bytes())
    data[-2] ^= 0xFF  # the magic trailer: unreadable everywhere
    bad = tmp_path / "fatal.parquet"
    bad.write_bytes(bytes(data))
    paths = [corpus[0], str(bad), corpus[2]]
    from parquet_floor_tpu.testing.differential import (
        run_host_scan,
        run_loader,
    )

    opts = ReaderOptions(salvage=True, verify_crc=True)
    with time_limit(PER_CASE_TIMEOUT_S):
        assert run_sequential(paths, opts).fatal is not None
        assert run_host_scan(paths, opts).fatal is not None
        assert run_loader(paths, opts)[0].fatal is not None


def test_ranged_reads_match_sequential_salvage(corpus, tmp_path):
    """Salvage under ranged reads, both covers.  A FULL-cover ranged
    request (cover == the group) must produce the SAME quarantine set
    and the SAME surviving bytes as the sequential whole-group face on
    every seeded corruption case.  A PARTIAL request keeps its
    I/O-pruned page cover even under salvage (docs/scan.md): it must
    never go fatal where the sequential face did not, and its
    quarantine set must be a SUBSET of the sequential face's — pruned
    damage stays undiscovered, but nothing is ever invented.  (The
    deterministic partial-cover laws — clean chunks keep pruning,
    in-cover damage quarantines identically, out-of-cover damage stays
    pruned bit-identically — are pinned in test_salvage.py.)"""
    opts = ReaderOptions(salvage=True, verify_crc=True)
    fails = []
    for seed in range(400, 412):
        paths, _flips = materialize_case(corpus, seed, str(tmp_path))
        with time_limit(PER_CASE_TIMEOUT_S):
            ref = run_sequential(paths, opts)
            full = run_ranged(paths, opts, request=None)
            part = run_ranged(paths, opts)
        if (ref.fatal is None) != (full.fatal is None):
            fails.append((seed, f"fatality diverged: sequential="
                          f"{ref.fatal} full-cover={full.fatal}"))
            continue
        if ref.fatal is not None:
            continue
        if full.quarantine != ref.quarantine:
            fails.append((seed, "quarantine sets diverged (full cover)"))
        elif full.groups != ref.groups:
            fails.append((seed, "surviving bytes diverged (full cover)"))
        if part.fatal is not None:
            fails.append((seed, f"partial cover went fatal: {part.fatal}"))
        elif not (part.quarantine <= ref.quarantine):
            fails.append((seed, "partial cover invented quarantines: "
                          f"{sorted(part.quarantine - ref.quarantine)}"))
    assert not fails, fails


def test_ranged_strict_mode_still_prunes(corpus):
    """The delegation is salvage-only: strict-mode ranged reads keep
    their I/O-pruned page cover (covered stays a page-aligned subset
    when the index allows it)."""
    from parquet_floor_tpu import ParquetFileReader

    with ParquetFileReader(corpus[0]) as r:
        n = int(r.row_groups[0].num_rows or 0)
        batch, covered = r.read_row_group_ranges(0, [(10, 60)])
        assert covered and covered != [(0, n)]
        assert batch.num_rows == sum(b - a for a, b in covered)
