"""DatasetCompactor — re-shard / re-sort / re-encode at scan speed,
salvage retirement, and the serving ladder over compacted output
(docs/write.md)."""

import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

import pyarrow as pa  # noqa: E402
import pyarrow.parquet as pq  # noqa: E402

from parquet_floor_tpu import (  # noqa: E402
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    WriterOptions,
    types,
)
from parquet_floor_tpu.errors import UnsupportedFeatureError  # noqa: E402
from parquet_floor_tpu.format.parquet_thrift import (  # noqa: E402
    CompressionCodec,
)
from parquet_floor_tpu.utils import trace  # noqa: E402
from parquet_floor_tpu.write import (  # noqa: E402
    CompactOptions,
    DatasetCompactor,
)

from tests.test_salvage import (  # noqa: F401  (fixture re-export)
    PAGE_VALUES,
    ROWS_PER_GROUP,
    _flip_in_page,
    salvage_file,
)


def corpus_schema():
    t = types
    return t.message(
        "c",
        t.required(t.INT64).named("k"),
        t.optional(t.DOUBLE).named("v"),
        t.required(t.BYTE_ARRAY).as_(t.string()).named("s"),
    )


def write_corpus(tmp_path, n_files=3, rows=1100, group_rows=400):
    """Ragged small-file corpus; ``k`` is a unique EVEN key per row (odd
    probes are bloom-skippable absences)."""
    paths = []
    base = 0
    for fi in range(n_files):
        n = rows + fi * 137
        r = np.random.default_rng(fi)
        path = tmp_path / f"in_{fi}.parquet"
        with ParquetFileWriter(
            str(path), corpus_schema(),
            WriterOptions(data_page_values=200,
                          row_group_rows=group_rows),
        ) as w:
            done = 0
            while done < n:
                take = min(group_rows, n - done)
                ks = (np.arange(base, base + take) * 2).astype(np.int64)
                r.shuffle(ks)  # unsorted input: compaction re-sorts
                w.write_columns({
                    "k": ks,
                    "v": [
                        None if i % 9 == 0 else float(i % 31) / 4
                        for i in range(take)
                    ],
                    "s": [f"s{int(k) % 97}" for k in ks],
                })
                base += take
                done += take
        paths.append(str(path))
    return paths


def read_all(paths):
    return pa.concat_tables([pq.read_table(p) for p in paths])


def test_reshard_band_and_values(tmp_path):
    """Output row groups sit exactly at the target (last of each file
    excepted), files rotate at target_file_rows, and every value
    survives in delivery order."""
    paths = write_corpus(tmp_path)
    out = tmp_path / "out"
    rep = DatasetCompactor(paths, str(out), CompactOptions(
        target_row_group_rows=1000, target_file_rows=2000,
        writer=WriterOptions(codec=CompressionCodec.ZSTD, engine="tpu"),
    )).run()
    tin, tout = read_all(paths), read_all(rep.paths)
    assert tout.num_rows == tin.num_rows == rep.rows_out == rep.rows_in
    for name in tin.column_names:
        assert tout[name].to_pylist() == tin[name].to_pylist(), name
    # group-size band: every group == target except each file's last
    for p in rep.paths:
        md = pq.ParquetFile(p).metadata
        sizes = [
            md.row_group(i).num_rows for i in range(md.num_row_groups)
        ]
        assert all(s == 1000 for s in sizes[:-1])
        assert 0 < sizes[-1] <= 1000
        assert sum(sizes) <= 2000
    assert rep.groups_out == len(rep.group_rows)
    assert rep.units_in == 11  # 3 files × 3-4 ragged groups


def test_sort_by_and_unit_order(tmp_path):
    """``sort_by`` orders rows within each output group (recorded as
    sorting_columns); ``unit_order`` replays units in an explicit
    permutation."""
    paths = write_corpus(tmp_path, n_files=2)
    out = tmp_path / "out"
    rep = DatasetCompactor(paths, str(out), CompactOptions(
        target_row_group_rows=1500, sort_by=["k"],
        writer=WriterOptions(engine="tpu"),
    )).run()
    md = pq.ParquetFile(rep.paths[0]).metadata
    assert md.row_group(0).sorting_columns[0].column_index == 0
    tout = read_all(rep.paths)
    ks = tout["k"].to_pylist()
    off = 0
    for p in rep.paths:
        m = pq.ParquetFile(p).metadata
        for i in range(m.num_row_groups):
            nr = m.row_group(i).num_rows
            seg = ks[off : off + nr]
            assert seg == sorted(seg)
            off += nr
    # multiset preserved
    assert sorted(ks) == sorted(read_all(paths)["k"].to_pylist())

    # explicit unit order: reversed units deliver reversed
    units = []
    for fi, p in enumerate(paths):
        with ParquetFileReader(p) as r:
            units.extend((fi, gi) for gi in range(len(r.row_groups)))
    out2 = tmp_path / "out2"
    rep2 = DatasetCompactor(paths, str(out2), CompactOptions(
        target_row_group_rows=10 ** 6, unit_order=list(reversed(units)),
        writer=WriterOptions(engine="host"),
    )).run()
    got = read_all(rep2.paths)["k"].to_pylist()
    want = []
    for fi, gi in reversed(units):
        with ParquetFileReader(paths[fi]) as r:
            b = r.read_row_group(gi)
            want.extend(np.asarray(b.column("k").values).tolist())
    assert got == want


def test_projection_and_nulls(tmp_path):
    """Column projection drops fields from the output schema; optional
    columns keep their null pattern through the carry buffer."""
    paths = write_corpus(tmp_path, n_files=2)
    out = tmp_path / "out"
    rep = DatasetCompactor(paths, str(out), CompactOptions(
        target_row_group_rows=700, columns=["k", "v"],
        writer=WriterOptions(engine="tpu"),
    )).run()
    tout = read_all(rep.paths)
    assert tout.column_names == ["k", "v"]
    tin = read_all(paths)
    assert tout["v"].to_pylist() == tin["v"].to_pylist()
    assert tout["v"].null_count == tin["v"].null_count > 0


def test_repeated_columns_rejected(tmp_path):
    t = types
    schema = t.message(
        "r",
        t.required(t.INT64).named("a"),
        t.repeated(t.INT64).named("xs"),
    )
    p = tmp_path / "rep.parquet"
    with ParquetFileWriter(str(p), schema) as w:
        w.write_columns({"a": np.arange(4, dtype=np.int64),
                         "xs": [[1], [2, 3], [], [4]]})
    with pytest.raises(UnsupportedFeatureError, match="flat"):
        DatasetCompactor([str(p)], str(tmp_path / "o"),
                         CompactOptions()).run()


def test_compact_report_counters(tmp_path):
    paths = write_corpus(tmp_path, n_files=2)
    with trace.scope() as tr:
        rep = DatasetCompactor(paths, str(tmp_path / "o"), CompactOptions(
            target_row_group_rows=800,
            writer=WriterOptions(engine="tpu"),
        )).run()
    c = tr.counters()
    for name in c:
        assert name in trace.names.ALL, name
    assert c["compact.units_in"] == rep.units_in
    assert c["compact.rows_in"] == rep.rows_in
    assert c["compact.groups_out"] == rep.groups_out
    assert rep.rows_per_sec > 0
    d = rep.as_dict()
    assert d["rows_out"] == rep.rows_out


# ---------------------------------------------------------------------------
# salvage → compact → clean corpus (the QuarantineMap retirement loop)
# ---------------------------------------------------------------------------

def test_salvage_compact_retires_quarantine(salvage_file, tmp_path):
    """The acceptance pin: compacting a corpus with quarantined units
    under ``salvage=True`` produces files that (a) re-read with NO
    salvage, (b) keep a fresh QuarantineMap EMPTY, and (c) contain
    exactly the undamaged units' rows."""
    from parquet_floor_tpu.quarantine import QuarantineMap
    from parquet_floor_tpu.scan import DatasetScanner

    # damage a REQUIRED column's page in group 0: row-mask tier →
    # geometry damage → the compactor must drop the whole unit
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "cmp_bad")
    out = tmp_path / "clean"
    rep = DatasetCompactor([bad], str(out), CompactOptions(
        salvage=True, reader=ReaderOptions(verify_crc=True),
        target_row_group_rows=ROWS_PER_GROUP,
        writer=WriterOptions(engine="tpu"),
    )).run()
    assert rep.units_dropped == 1
    # the row-mask tier already removed the damaged page's rows at read
    # time; the compactor then discards the unit's DELIVERED remainder
    assert rep.rows_dropped == ROWS_PER_GROUP - PAGE_VALUES
    assert rep.rows_out == ROWS_PER_GROUP  # group 1 survived whole
    assert rep.salvage is not None and rep.salvage.skips

    # (a) strict re-read, no salvage, bit-compare against the pristine
    # file's group 1
    with ParquetFileReader(salvage_file) as r:
        want = r.read_row_group(1)
    with ParquetFileReader(rep.paths[0]) as r:
        got = r.read_row_group(0)
        assert got.num_rows == ROWS_PER_GROUP
        for name in ("a", "d"):
            assert np.array_equal(
                np.asarray(got.column(name).values),
                np.asarray(want.column(name).values),
            )
        assert got.column("s").values.to_list() == \
            want.column("s").values.to_list()

    # (b) a fresh QuarantineMap over the compacted corpus stays empty
    qm_path = tmp_path / "clean_map.json"
    qmap = QuarantineMap(str(qm_path))
    with DatasetScanner(
        rep.paths,
        options=ReaderOptions(salvage=True, verify_crc=True,
                              quarantine_map=qmap),
    ) as s:
        n = sum(u.batch.num_rows for u in s)
        assert n == ROWS_PER_GROUP
        assert not s.salvage_report.skips
    qmap.save()
    assert not qmap._files  # no file earned an entry: the map retired


def test_salvage_page_null_flows_through(salvage_file, tmp_path):
    """Page-null tier (optional column): the unit is KEPT — the lost
    page's rows become legal nulls in the compacted output."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "s", 1, "cmp_opt")
    out = tmp_path / "cleaned2"
    rep = DatasetCompactor([bad], str(out), CompactOptions(
        salvage=True, reader=ReaderOptions(verify_crc=True),
        writer=WriterOptions(engine="tpu"),
    )).run()
    assert rep.units_dropped == 0
    assert rep.rows_out == 2 * ROWS_PER_GROUP
    tab = pq.read_table(rep.paths[0])
    with ParquetFileReader(salvage_file) as r:
        pristine = r.read_row_group(0)
    base_nulls = int(np.count_nonzero(pristine.column("s").null_mask))
    # the damaged page's PAGE_VALUES slots turned null (minus any that
    # already were)
    assert tab.slice(0, ROWS_PER_GROUP)["s"].null_count > base_nulls
    # strict re-read needs no salvage
    with ParquetFileReader(rep.paths[0], verify_crc=True) as r:
        r.read_row_group(0)


# ---------------------------------------------------------------------------
# the serving ladder over compacted output
# ---------------------------------------------------------------------------

def test_compacted_output_feeds_serving_ladder(tmp_path):
    """Acceptance pin: a ``serve.Dataset.lookup`` against compactor
    output fires all three rungs — footer-stats pruning, bloom skip,
    and page-index page reads."""
    from parquet_floor_tpu.serve.lookup import Dataset

    paths = write_corpus(tmp_path, n_files=3)
    out = tmp_path / "served"
    rep = DatasetCompactor(paths, str(out), CompactOptions(
        target_row_group_rows=600, target_file_rows=1800,
        sort_by=["k"], unit_order=None,
        writer=WriterOptions(
            engine="tpu",
            bloom_filter_columns={"k": True},
        ),
    )).run()
    assert len(rep.paths) >= 2
    # NOTE: sort_by is per-GROUP; the corpus delivery order is already
    # globally near-sorted (keys ascend across units), so group stats
    # are disjoint enough for the stats rung to prune.
    with trace.scope() as tr:
        with Dataset(rep.paths, "k") as ds:
            present = ds.lookup(2 * 100)      # an even key that exists
            assert present and present[0]["k"] == 200
            absent = ds.lookup(2 * 100 + 1)   # odd: bloom-skippable
            assert absent == []
            assert ds.lookup(10 ** 15) == []  # stats-prunable
    c = tr.counters()
    assert c.get("serve.lookup_groups_pruned", 0) > 0   # stats rung
    assert c.get("serve.lookup_bloom_skips", 0) > 0     # bloom rung
    assert c.get("serve.lookup_pages_read", 0) > 0      # page rung


def test_pyarrow_written_corpus_compacts_bit_exact(tmp_path):
    """Acceptance pin (foreign writer end to end): a corpus written by
    PYARROW — its own encodings, its own page layout — compacts through
    our engine and reads back under pyarrow bit-identical, across
    snappy/zstd/uncompressed inputs."""
    rng2 = np.random.default_rng(5)
    paths = []
    for fi, comp in enumerate(["snappy", "zstd", "none"]):
        n = 900 + fi * 113
        tab = pa.table({
            "k": pa.array(
                rng2.integers(0, 10 ** 6, n), type=pa.int64()
            ),
            "x": pa.array(rng2.standard_normal(n), type=pa.float64()),
            "o": pa.array(
                [None if i % 6 == 0 else i % 19 for i in range(n)],
                type=pa.int32(),
            ),
            "s": pa.array(
                [f"v{int(i) % 41}" for i in range(n)], type=pa.string()
            ),
        })
        p = str(tmp_path / f"pa_{fi}.parquet")
        pq.write_table(
            tab, p, compression=comp, row_group_size=400,
            use_dictionary=True, data_page_version="2.0",
        )
        paths.append(p)
    out = tmp_path / "pa_out"
    rep = DatasetCompactor(paths, str(out), CompactOptions(
        target_row_group_rows=1000,
        writer=WriterOptions(engine="tpu"),
    )).run()
    tin = read_all(paths)
    tout = read_all(rep.paths)
    assert tout.num_rows == tin.num_rows
    for name in tin.column_names:
        if name == "x":
            a = np.asarray(tin["x"].to_numpy()).view(np.uint64)
            b = np.asarray(tout["x"].to_numpy()).view(np.uint64)
            assert np.array_equal(a, b)  # float bit patterns exact
        else:
            assert tout[name].to_pylist() == tin[name].to_pylist(), name


def test_writer_failure_raises_not_hangs(tmp_path):
    """A write-leg failure under queue backpressure must surface as a
    raise from run(), never a hang: the writer thread records the error
    and KEEPS DRAINING the bounded queue until the sentinel (the
    deadlock shape a dead consumer would cause)."""
    import signal

    paths = write_corpus(tmp_path, n_files=2)

    calls = {"n": 0}

    def bad_dest(index: int) -> str:
        calls["n"] += 1
        if index >= 1:
            raise OSError("simulated destination failure")
        return str(tmp_path / f"bd-{index:05d}.parquet")

    def on_alarm(*_):  # pragma: no cover - only fires on regression
        raise AssertionError("compactor hung on writer failure")

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(60)
    try:
        with pytest.raises(OSError, match="simulated destination"):
            DatasetCompactor(paths, bad_dest, CompactOptions(
                # many tiny groups + a 1-group file cap: the rotation to
                # file 1 fails while the read leg is still producing
                target_row_group_rows=100, target_file_rows=100,
                writer=WriterOptions(engine="pipelined"),
            )).run()
    finally:
        signal.alarm(0)
    assert calls["n"] >= 2


def test_nested_optional_structure_survives(tmp_path):
    """Multi-level definition levels (outer null vs inner null of
    ``optional group g { optional int64 x }``): the auto read leg must
    pin HOST — the device face ships only a row null-mask and would
    collapse outer nulls into inner nulls."""
    t = types
    schema = t.message(
        "n",
        t.required(t.INT64).named("id"),
        t.optional_group(t.optional(t.INT64).named("x")).named("g"),
    )
    p = str(tmp_path / "nested.parquet")
    # def 0 = g null, 1 = g present / x null, 2 = value: the two null
    # tiers only exist through explicit definition levels
    from parquet_floor_tpu.format.file_write import ColumnData

    pattern = [0, 1, 2, 0, 2] * 60
    defs = np.array(pattern, dtype=np.uint32)
    vals = np.array(
        [7 + i for i, d in enumerate(pattern) if d == 2],
        dtype=np.int64,
    )
    gx = [c for c in schema.columns if c.path[-1] == "x"][0]
    with ParquetFileWriter(p, schema) as w:
        w.write_columns({
            "id": np.arange(300, dtype=np.int64),
            "g.x": ColumnData(gx, vals, def_levels=defs),
        })
    out = tmp_path / "nout"
    rep = DatasetCompactor([p], str(out), CompactOptions(
        target_row_group_rows=100,
        writer=WriterOptions(engine="host"),
    )).run()
    assert rep.rows_out == 300
    tin = pq.read_table(p).to_pylist()
    tout = read_all(rep.paths).to_pylist()
    assert tout == tin  # outer None vs {"x": None} both preserved
    # and the explicit device leg refuses rather than corrupting
    with pytest.raises(UnsupportedFeatureError, match="definition"):
        DatasetCompactor([p], str(tmp_path / "n2"), CompactOptions(
            read_leg="tpu",
        )).run()


def test_device_writer_ctor_failure_closes_sink(tmp_path, monkeypatch):
    """A DeviceFileWriter whose engine cannot construct (no x64 jax)
    must close the sink the base ctor opened — the same ctor-guard
    contract ParquetFileWriter holds (FL-RES001's leak class)."""
    from parquet_floor_tpu.io.source import FileSink
    from parquet_floor_tpu.write import DeviceFileWriter
    from parquet_floor_tpu.write import encode as _enc

    closed = []
    orig = FileSink.close

    def tracking_close(self):
        closed.append(self)
        return orig(self)

    monkeypatch.setattr(FileSink, "close", tracking_close)

    def boom(*a, **k):
        raise RuntimeError("no backend")

    monkeypatch.setattr(_enc, "EncodeEngine", boom)
    t = types
    schema = t.message("m", t.required(t.INT64).named("a"))
    with pytest.raises(RuntimeError, match="no backend"):
        # ctor self-closes on engine failure (pinned below)
        DeviceFileWriter(  # floorlint: disable=FL-RES001
            str(tmp_path / "leak.parquet"), schema,
            WriterOptions(engine="tpu"),
        )
    assert len(closed) == 1
