"""Snappy codec tests: self-roundtrip + interop against pyarrow's canonical
snappy implementation (the external oracle; SURVEY.md §4 interop stance)."""

import numpy as np
import pytest

from parquet_floor_tpu.format import snappy

try:
    import pyarrow as pa

    _SNAPPY_ORACLE = pa.Codec.is_available("snappy")
except ImportError:
    _SNAPPY_ORACLE = False

rng = np.random.default_rng(7)

CASES = [
    b"",
    b"a",
    b"aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa",
    b"abcabcabcabcabcabcabcabcabcabc",
    bytes(rng.integers(0, 256, 10000).astype(np.uint8)),  # incompressible
    bytes(np.repeat(rng.integers(0, 4, 1000), 17).astype(np.uint8)),  # runs
    b"the quick brown fox jumps over the lazy dog " * 200,
    bytes(20) + b"x" * 100 + bytes(20),
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_roundtrip(i):
    data = CASES[i]
    comp = snappy.compress(data)
    assert snappy.decompress(comp) == data


def test_compression_actually_compresses():
    data = b"hello world " * 1000
    assert len(snappy.compress(data)) < len(data) // 4


@pytest.mark.skipif(not _SNAPPY_ORACLE, reason="pyarrow snappy not available")
@pytest.mark.parametrize("i", range(len(CASES)))
def test_oracle_decodes_ours(i):
    codec = pa.Codec("snappy")
    data = CASES[i]
    assert codec.decompress(snappy.compress(data), len(data)).to_pybytes() == data


@pytest.mark.skipif(not _SNAPPY_ORACLE, reason="pyarrow snappy not available")
@pytest.mark.parametrize("i", range(len(CASES)))
def test_we_decode_oracle(i):
    codec = pa.Codec("snappy")
    data = CASES[i]
    assert snappy.decompress(codec.compress(data).to_pybytes()) == data


def test_overlapping_copy():
    # pattern repetition exercises offset < length copies
    data = b"ab" * 1000
    comp = snappy.compress(data)
    assert snappy.decompress(comp) == data


def test_corrupt_stream_raises():
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(b"\x20\x01")  # claims 32 bytes, provides garbage


# ------------------------------------------------------------------------ LZ4

def test_lz4_raw_roundtrip_and_pyarrow_interop(tmp_path):
    """LZ4_RAW: our decode reads pyarrow-written files; native and Python
    block decoders agree."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from parquet_floor_tpu import ParquetFileReader
    from parquet_floor_tpu.format import codecs
    from parquet_floor_tpu.format.parquet_thrift import CompressionCodec
    from parquet_floor_tpu.native import binding

    rng = np.random.default_rng(23)
    n = 50_000
    data = {
        "a": rng.integers(0, 100, n),
        "b": rng.standard_normal(n),
        "s": [f"row-{i % 500:05d}" for i in range(n)],
    }
    path = str(tmp_path / "lz4.parquet")
    pq.write_table(pa.table(data), path, compression="LZ4")  # LZ4_RAW id
    with ParquetFileReader(path) as r:
        got = r.read_row_group(0)
        np.testing.assert_array_equal(got.column("a").values, data["a"])
        np.testing.assert_array_equal(got.column("b").values, data["b"])
        assert got.column("s").values.to_list()[:3] == [b"row-00000", b"row-00001", b"row-00002"]

    # block-level: python and native decode agree on pyarrow-compressed bytes
    payload = rng.integers(0, 8, 100_000).astype(np.uint8).tobytes()
    comp = codecs.compress(CompressionCodec.LZ4_RAW, payload)
    out_py = codecs._lz4_raw_decompress(comp)  # python path (no size hint)
    assert out_py == payload
    if binding.available():
        assert binding.lz4_decompress(comp, len(payload)) == payload
    # round-trip through the dispatch (native path with size)
    assert codecs.decompress(CompressionCodec.LZ4_RAW, comp, len(payload)) == payload
    # Hadoop-framed LZ4 dispatch round-trip
    framed = codecs.compress(CompressionCodec.LZ4, payload)
    assert codecs.decompress(CompressionCodec.LZ4, framed, len(payload)) == payload


def test_lz4_hostile_blocks():
    import pytest
    from parquet_floor_tpu.native import binding

    if not binding.available():
        pytest.skip("native lib not built")
    # offset beyond output start
    bad = bytes([0x10, ord('A'), 0x05, 0x00])  # 1 literal, offset 5 > produced 1
    with pytest.raises(ValueError):
        binding.lz4_decompress(bad, 64)
    # literal run past end of input
    bad2 = bytes([0xF0, 0xFF])
    with pytest.raises(ValueError):
        binding.lz4_decompress(bad2, 64)


def test_lz4_hadoop_multiblock_record():
    """Hadoop BlockCompressorStream splits input larger than its codec
    buffer into several [clen][block] inner records under one [ulen]
    header — the decoder must loop until ulen bytes have been produced."""
    from parquet_floor_tpu.format import codecs

    part1 = bytes(range(256)) * 8   # 2048 bytes
    part2 = b"tail-bytes" * 100     # 1000 bytes
    payload = part1 + part2
    rec = len(payload).to_bytes(4, "big")
    for part in (part1, part2):
        blk = codecs._lz4_raw_compress(part)
        rec += len(blk).to_bytes(4, "big") + blk
    assert codecs._lz4_hadoop_decompress(rec, len(payload)) == payload
    assert codecs._lz4_hadoop_decompress(rec) == payload

    # two records, the second itself multi-block
    rec2 = codecs._lz4_hadoop_compress(b"solo") + rec
    assert codecs._lz4_hadoop_decompress(rec2) == b"solo" + payload


def test_register_codec_roundtrip_and_guidance(tmp_path):
    """The open codec seam (reference: ReflectionUtils instantiates any
    codec class the footer names): an unregistered BROTLI footer raises
    actionable guidance; a user-registered implementation round-trips a
    whole file."""
    import zlib

    import pytest

    from parquet_floor_tpu import (
        CompressionCodec,
        ParquetFileReader,
        ParquetFileWriter,
        UnsupportedCodec,
        WriterOptions,
        register_codec,
        types,
    )
    from parquet_floor_tpu.format import codecs as C

    # LZO stays guidance-only (GPL upstream); BROTLI is built-in via the
    # system library since round 3, so the unregistered-codec guidance is
    # probed through LZO on both sides
    with pytest.raises(UnsupportedCodec, match="register_codec"):
        C.decompress(CompressionCodec.LZO, b"xx", 4)
    with pytest.raises(UnsupportedCodec, match="register_codec"):
        C.compress(CompressionCodec.LZO, b"xx")

    schema = types.message("t", types.required(types.INT64).named("v"))
    path = str(tmp_path / "brotli_like.parquet")
    data = np.arange(5000, dtype=np.int64)
    saved_c = dict(C._COMPRESSORS)
    saved_d = dict(C._DECOMPRESSORS)
    try:
        # stand-in implementation: zlib under the BROTLI id — exercises
        # exactly the registration seam a real brotli wheel would use
        register_codec(
            CompressionCodec.BROTLI,
            compressor=zlib.compress,
            decompressor=lambda d, n: zlib.decompress(d),
        )
        assert CompressionCodec.BROTLI in C.supported_codecs()
        with ParquetFileWriter(
            path, schema, WriterOptions(codec=CompressionCodec.BROTLI)
        ) as w:
            w.write_columns({"v": data})
        with ParquetFileReader(path) as r:
            assert r.row_groups[0].columns[0].meta_data.codec == CompressionCodec.BROTLI
            np.testing.assert_array_equal(
                r.read_row_group(0).column("v").values, data
            )
    finally:
        C._COMPRESSORS.clear()
        C._COMPRESSORS.update(saved_c)
        C._DECOMPRESSORS.clear()
        C._DECOMPRESSORS.update(saved_d)
    # with the registration rolled back the same file hits the built-in
    # decoder, which rejects the zlib bytes as an invalid brotli stream
    # (or, without the system library, refuses with guidance)
    from parquet_floor_tpu.format import brotli_codec

    expected = ValueError if brotli_codec.available() else UnsupportedCodec
    with ParquetFileReader(path) as r:
        with pytest.raises(expected, match="brotli"):
            r.read_row_group(0)


def test_register_codec_override_wins_in_decompress_into():
    """A register_codec override must be honored on the arena-fill hot
    path (decompress_into), not just the bytes path."""
    from parquet_floor_tpu.format import codecs as C
    from parquet_floor_tpu.format.parquet_thrift import CompressionCodec

    payload = b"abc" * 10
    saved = dict(C._DECOMPRESSORS)
    calls = []

    def fake(data, n=None):
        calls.append(len(data))
        return payload

    try:
        C.register_codec(CompressionCodec.SNAPPY, decompressor=fake)
        out = np.zeros(64, np.uint8)
        C.decompress_into(CompressionCodec.SNAPPY, b"whatever", out, 4, len(payload))
        assert calls, "override was bypassed"
        assert out[4 : 4 + len(payload)].tobytes() == payload
    finally:
        C._DECOMPRESSORS.clear()
        C._DECOMPRESSORS.update(saved)
