"""Snappy codec tests: self-roundtrip + interop against pyarrow's canonical
snappy implementation (the external oracle; SURVEY.md §4 interop stance)."""

import numpy as np
import pytest

from parquet_floor_tpu.format import snappy

try:
    import pyarrow as pa

    _SNAPPY_ORACLE = pa.Codec.is_available("snappy")
except ImportError:
    _SNAPPY_ORACLE = False

rng = np.random.default_rng(7)

CASES = [
    b"",
    b"a",
    b"aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa",
    b"abcabcabcabcabcabcabcabcabcabc",
    bytes(rng.integers(0, 256, 10000).astype(np.uint8)),  # incompressible
    bytes(np.repeat(rng.integers(0, 4, 1000), 17).astype(np.uint8)),  # runs
    b"the quick brown fox jumps over the lazy dog " * 200,
    bytes(20) + b"x" * 100 + bytes(20),
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_roundtrip(i):
    data = CASES[i]
    comp = snappy.compress(data)
    assert snappy.decompress(comp) == data


def test_compression_actually_compresses():
    data = b"hello world " * 1000
    assert len(snappy.compress(data)) < len(data) // 4


@pytest.mark.skipif(not _SNAPPY_ORACLE, reason="pyarrow snappy not available")
@pytest.mark.parametrize("i", range(len(CASES)))
def test_oracle_decodes_ours(i):
    codec = pa.Codec("snappy")
    data = CASES[i]
    assert codec.decompress(snappy.compress(data), len(data)).to_pybytes() == data


@pytest.mark.skipif(not _SNAPPY_ORACLE, reason="pyarrow snappy not available")
@pytest.mark.parametrize("i", range(len(CASES)))
def test_we_decode_oracle(i):
    codec = pa.Codec("snappy")
    data = CASES[i]
    assert snappy.decompress(codec.compress(data).to_pybytes()) == data


def test_overlapping_copy():
    # pattern repetition exercises offset < length copies
    data = b"ab" * 1000
    comp = snappy.compress(data)
    assert snappy.decompress(comp) == data


def test_corrupt_stream_raises():
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(b"\x20\x01")  # claims 32 bytes, provides garbage
