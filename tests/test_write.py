"""Device write engine — round-trip differential suite (docs/write.md).

The engine's correctness claim is DIFFERENTIAL: every file the fused
device encode path writes must read back bit-identical under pyarrow (a
foreign reader, end to end) AND under our own read faces, across every
encoding the engine emits (RLE_DICTIONARY, DELTA_BINARY_PACKED,
BYTE_STREAM_SPLIT, PLAIN + host-fallback strings/bools) × codecs
(snappy / zstd / uncompressed) × page versions (v1 / v2).
"""

import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

import pyarrow.parquet as pq  # noqa: E402

from parquet_floor_tpu import (  # noqa: E402
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.utils import trace  # noqa: E402
from parquet_floor_tpu.write import DeviceFileWriter  # noqa: E402
from parquet_floor_tpu.write.encode import resolve_writer  # noqa: E402

rng = np.random.default_rng(7)

N = 2000  # > one aligned device page (multiple-of-128 grid) per group


def mixed_schema():
    t = types
    return t.message(
        "m",
        t.required(t.INT64).named("di64"),        # dictionary
        t.required(t.INT32).named("di32"),        # dictionary
        t.optional(t.INT64).named("opt"),         # optional dictionary
        t.required(t.DOUBLE).named("dd"),         # dictionary double
        t.required(t.INT64).named("delta64"),     # DELTA_BINARY_PACKED
        t.required(t.INT32).named("delta32"),     # DELTA_BINARY_PACKED
        t.required(t.DOUBLE).named("bss64"),      # BYTE_STREAM_SPLIT
        t.required(t.FLOAT).named("bss32"),       # BYTE_STREAM_SPLIT
        t.required(t.INT64).named("plain"),       # PLAIN (host identity)
        t.required(t.BYTE_ARRAY).as_(t.string()).named("s"),  # host
        t.required(t.BOOLEAN).named("b"),         # host
    )


def mixed_columns(n=N, seed=7):
    r = np.random.default_rng(seed)
    return {
        "di64": r.integers(0, 50, n).astype(np.int64),
        "di32": r.integers(-40, 0, n).astype(np.int32),
        "opt": [None if i % 7 == 0 else i % 13 for i in range(n)],
        "dd": np.round(r.standard_normal(n), 1),
        "delta64": np.cumsum(r.integers(-5, 1000, n)).astype(np.int64),
        "delta32": np.cumsum(r.integers(-3, 7, n)).astype(np.int32),
        "bss64": r.standard_normal(n),
        "bss32": r.standard_normal(n).astype(np.float32),
        "plain": r.integers(-(2 ** 62), 2 ** 62, n).astype(np.int64),
        "s": [f"tag_{i % 23}" for i in range(n)],
        "b": (np.arange(n) % 3 == 0),
    }


def device_options(codec, page_version, **kw):
    return WriterOptions(
        codec=codec, page_version=page_version, engine="tpu",
        data_page_values=512,  # several pages per group
        column_encodings={
            "delta64": "DELTA_BINARY_PACKED",
            "delta32": "DELTA_BINARY_PACKED",
            "bss64": "BYTE_STREAM_SPLIT",
            "bss32": "BYTE_STREAM_SPLIT",
            "plain": "PLAIN",
        },
        **kw,
    )


def write_device(path, opts, n=N, groups=2):
    with DeviceFileWriter(str(path), mixed_schema(), opts) as w:
        for g in range(groups):
            w.write_columns(mixed_columns(n, seed=7 + g))


def assert_pyarrow_equal(path, n=N, groups=2):
    tab = pq.read_table(str(path))
    assert tab.num_rows == n * groups
    for g in range(groups):
        cols = mixed_columns(n, seed=7 + g)
        sl = tab.slice(g * n, n)
        for name, want in cols.items():
            got = sl[name].to_pylist()
            if isinstance(want, np.ndarray):
                if want.dtype.kind == "f":
                    # bit-exact, not approx: compare raw bit patterns
                    got_arr = np.asarray(
                        sl[name].to_numpy(zero_copy_only=False),
                        dtype=want.dtype,
                    )
                    assert np.array_equal(
                        got_arr.view(np.uint64 if want.itemsize == 8
                                     else np.uint32),
                        want.view(np.uint64 if want.itemsize == 8
                                  else np.uint32),
                    ), name
                else:
                    assert got == want.tolist(), name
            else:
                assert got == want, name


@pytest.mark.parametrize("codec", [
    CompressionCodec.UNCOMPRESSED,
    CompressionCodec.SNAPPY,
    CompressionCodec.ZSTD,
])
@pytest.mark.parametrize("page_version", [1, 2])
def test_device_writer_pyarrow_differential(tmp_path, codec, page_version):
    """Acceptance matrix: dict/delta/BSS/plain (+host strings/bools) ×
    snappy/zstd/uncompressed × v1/v2 — bit-identical under pyarrow."""
    path = tmp_path / "d.parquet"
    write_device(path, device_options(codec, page_version))
    assert_pyarrow_equal(path)
    # the device encodings actually landed (not silently host-PLAIN)
    md = pq.ParquetFile(str(path)).metadata
    enc = {
        md.schema.column(i).name: set(md.row_group(0).column(i).encodings)
        for i in range(md.num_columns)
    }
    assert "RLE_DICTIONARY" in enc["di64"]
    assert "RLE_DICTIONARY" in enc["opt"]
    assert "DELTA_BINARY_PACKED" in enc["delta64"]
    assert "BYTE_STREAM_SPLIT" in enc["bss64"]


def test_device_writer_our_read_faces(tmp_path):
    """A device-written file reads identically through the sequential
    host reader, the host scan scheduler, the device scan leg, and the
    DataLoader."""
    from parquet_floor_tpu.data import DataLoader
    from parquet_floor_tpu.scan import DatasetScanner, scan_device_groups

    path = tmp_path / "faces.parquet"
    write_device(path, device_options(CompressionCodec.SNAPPY, 2))
    cols0 = mixed_columns(N, seed=7)

    def check_batch(by_name, sl=slice(None)):
        assert np.array_equal(
            np.asarray(by_name["di64"]), cols0["di64"][sl]
        )
        assert np.array_equal(
            np.asarray(by_name["delta64"]), cols0["delta64"][sl]
        )
        assert np.array_equal(
            np.asarray(by_name["bss64"]).view(np.uint64),
            cols0["bss64"][sl].view(np.uint64),
        )

    with ParquetFileReader(str(path)) as r:
        b = r.read_row_group(0)
        check_batch({
            cb.descriptor.path[0]: cb.values for cb in b.columns
        })
    with DatasetScanner([str(path)]) as s:
        u = next(iter(s))
        check_batch({
            cb.descriptor.path[0]: cb.values for cb in u.batch.columns
        })
    got = next(iter(
        scan_device_groups([str(path)], float64_policy="float64")
    ))[2]
    check_batch({k: np.asarray(v.values) for k, v in got.items()})
    with DataLoader([str(path)], batch_size=N, engine="host") as dl:
        lb = next(iter(dl))
        by = {c.descriptor.path[0]: c for c in lb.columns}
        assert np.array_equal(np.asarray(by["di64"].values),
                              cols0["di64"])


def test_device_vs_host_writer_value_identical(tmp_path):
    """Same columns through engine=host and engine=tpu: the files need
    not be byte-identical (dictionary ORDER differs by design), but
    every decoded value must match."""
    opts_t = device_options(CompressionCodec.SNAPPY, 2)
    opts_h = WriterOptions(
        codec=CompressionCodec.SNAPPY, page_version=2,
        data_page_values=512,
        column_encodings=opts_t.column_encodings,
    )
    pt, ph = tmp_path / "t.parquet", tmp_path / "h.parquet"
    write_device(pt, opts_t, groups=1)
    with ParquetFileWriter(str(ph), mixed_schema(), opts_h) as w:
        w.write_columns(mixed_columns(N, seed=7))
    ta, tb = pq.read_table(str(pt)), pq.read_table(str(ph))
    assert ta.equals(tb)


def test_resolve_writer_engines(tmp_path):
    schema = types.message(
        "m", types.required(types.INT64).named("x")
    )
    w = resolve_writer(str(tmp_path / "h.parquet"), schema,
                       WriterOptions(engine="host"))
    try:
        assert type(w) is ParquetFileWriter
    finally:
        w.abort()
    w = resolve_writer(str(tmp_path / "t.parquet"), schema,
                       WriterOptions(engine="tpu"))
    try:
        assert isinstance(w, DeviceFileWriter)
    finally:
        w.abort()
    w = resolve_writer(str(tmp_path / "a.parquet"), schema,
                       WriterOptions(engine="auto"))
    try:
        # the CPU backend is up: auto picks the PIPELINED writer (the
        # fused launches only win on a real accelerator)
        assert isinstance(w, DeviceFileWriter)
        assert w._engine is None
    finally:
        w.abort()
    w = resolve_writer(str(tmp_path / "p.parquet"), schema,
                       WriterOptions(engine="pipelined"))
    try:
        assert isinstance(w, DeviceFileWriter) and w._engine is None
    finally:
        w.abort()
    with pytest.raises(ValueError, match="engine"):
        # validation raises before any sink is constructed (no leak)
        resolve_writer(  # floorlint: disable=FL-RES001
            str(tmp_path / "b.parquet"), schema,
            WriterOptions(engine="gpu"),
        )


def test_api_facade_rides_engine(tmp_path):
    """ParquetWriter (the row-at-a-time reference facade) flushes
    through the device engine when options.engine says so."""
    from parquet_floor_tpu import Dehydrator, ParquetWriter

    t = types
    schema = t.message(
        "m",
        t.required(t.INT64).named("a"),
        t.required(t.DOUBLE).named("d"),
    )

    class D(Dehydrator):
        def dehydrate(self, record, vw):
            vw.write("a", record[0])
            vw.write("d", record[1])

    path = tmp_path / "api.parquet"
    opts = WriterOptions(engine="tpu", row_group_rows=600)
    records = [(i % 9, float(i % 5)) for i in range(1500)]
    ParquetWriter.write_file(schema, str(path), D(), records, opts)
    tab = pq.read_table(str(path))
    assert tab["a"].to_pylist() == [r[0] for r in records]
    assert tab["d"].to_pylist() == [r[1] for r in records]
    md = pq.ParquetFile(str(path)).metadata
    assert md.num_row_groups == 3  # 600/600/300: facade flush rode through


def test_dict_reject_falls_back_to_host(tmp_path):
    """A high-cardinality column fails the dictionary cutoff AFTER the
    analyze launch: the column must re-encode on host, values intact."""
    t = types
    schema = t.message("m", t.required(t.INT64).named("u"))
    vals = np.arange(4000, dtype=np.int64) * 7  # all distinct
    path = tmp_path / "rej.parquet"
    with trace.scope() as tr:
        with DeviceFileWriter(
            str(path), schema,
            WriterOptions(engine="tpu", dictionary_max_fraction=0.5),
        ) as w:
            w.write_columns({"u": vals})
    assert any(
        d.get("decision") == "write.engine"
        and d.get("action") == "dict_reject"
        for d in tr.decisions()
    )
    assert pq.read_table(str(path))["u"].to_pylist() == vals.tolist()
    md = pq.ParquetFile(str(path)).metadata
    assert "RLE_DICTIONARY" not in md.row_group(0).column(0).encodings


def test_delta_wide_offsets_fall_back_to_host(tmp_path):
    """INT64 deltas spanning more than 32 bits cannot pack on device:
    the column host-encodes, and the file still reads back exactly."""
    t = types
    schema = t.message("m", t.required(t.INT64).named("w"))
    vals = np.array(
        [0, 2 ** 40, -(2 ** 50), 2 ** 60, 1, -1] * 300, dtype=np.int64
    )
    path = tmp_path / "wide.parquet"
    with trace.scope() as tr:
        with DeviceFileWriter(
            str(path), schema,
            WriterOptions(
                engine="tpu", enable_dictionary=False,
                delta_integers=True,
            ),
        ) as w:
            w.write_columns({"w": vals})
    assert any(
        d.get("decision") == "write.engine"
        and d.get("action") == "delta_wide"
        for d in tr.decisions()
    )
    assert pq.read_table(str(path))["w"].to_pylist() == vals.tolist()


@pytest.mark.parametrize("n", [1, 127, 128, 129, 512, 513])
def test_page_grid_edges(tmp_path, n):
    """Row counts straddling the 128-value device page grid: first/last
    page slicing of the fused packed stream must stay exact."""
    t = types
    schema = t.message(
        "m",
        t.required(t.INT64).named("k"),
        t.required(t.INT64).named("dl"),
        t.required(t.DOUBLE).named("bs"),
    )
    r = np.random.default_rng(n)
    cols = {
        "k": r.integers(0, 9, n).astype(np.int64),
        "dl": np.cumsum(r.integers(0, 5, n)).astype(np.int64),
        "bs": r.standard_normal(n),
    }
    path = tmp_path / f"edge{n}.parquet"
    with DeviceFileWriter(
        str(path), schema,
        WriterOptions(
            engine="tpu", data_page_values=128,
            column_encodings={
                "dl": "DELTA_BINARY_PACKED", "bs": "BYTE_STREAM_SPLIT",
            },
        ),
    ) as w:
        w.write_columns(cols)
    tab = pq.read_table(str(path))
    assert tab["k"].to_pylist() == cols["k"].tolist()
    assert tab["dl"].to_pylist() == cols["dl"].tolist()
    assert np.array_equal(
        np.asarray(tab["bs"].to_numpy()).view(np.uint64),
        cols["bs"].view(np.uint64),
    )


def test_float_bit_patterns_survive(tmp_path):
    """-0.0, NaN payloads, and infinities are dictionary-distinct by
    BIT PATTERN and must round-trip bit-exactly."""
    t = types
    schema = t.message("m", t.required(t.DOUBLE).named("f"))
    vals = np.array(
        [0.0, -0.0, np.nan, np.inf, -np.inf, 1.5] * 100
    )
    path = tmp_path / "bits.parquet"
    with DeviceFileWriter(str(path), schema,
                          WriterOptions(engine="tpu")) as w:
        w.write_columns({"f": vals})
    got = pq.read_table(str(path))["f"].to_numpy(zero_copy_only=False)
    assert np.array_equal(
        np.asarray(got, dtype=np.float64).view(np.uint64),
        vals.view(np.uint64),
    )


def test_pipeline_depth_orders_groups(tmp_path):
    """Many small groups through a depth-2 pipeline: emission must stay
    in submission order and all groups must land."""
    t = types
    schema = t.message("m", t.required(t.INT64).named("g"))
    path = tmp_path / "pipe.parquet"
    with trace.scope() as tr:
        with DeviceFileWriter(
            str(path), schema,
            WriterOptions(engine="tpu", write_pipeline_depth=2),
        ) as w:
            for g in range(7):
                w.write_columns({
                    "g": np.full(300, g, dtype=np.int64)
                })
    tab = pq.read_table(str(path))
    assert tab["g"].to_pylist() == [
        g for g in range(7) for _ in range(300)
    ]
    c = tr.counters()
    assert c["write.groups"] == 7
    assert c["write.rows"] == 2100
    assert tr.gauges()["write.inflight_groups_max"] >= 2


def test_writer_error_aborts_cleanly(tmp_path):
    """A mid-stream error must abort (no footer) and release the pool;
    the partial file must not parse."""
    t = types
    schema = t.message("m", t.required(t.INT64).named("a"))
    path = tmp_path / "abort.parquet"
    with pytest.raises(ValueError):
        with DeviceFileWriter(str(path), schema,
                              WriterOptions(engine="tpu")) as w:
            w.write_columns({"a": np.arange(256, dtype=np.int64)})
            raise ValueError("boom")
    with pytest.raises(Exception):
        ParquetFileReader(str(path))


def test_prepared_chunk_stats_and_index_parity(tmp_path):
    """Device-encoded chunks carry the same statistics/ColumnIndex/
    OffsetIndex metadata machinery as host chunks (the shared
    pagination path): stats exist, bounds are right, pages counted."""
    t = types
    schema = t.message("m", t.required(t.INT64).named("k"))
    vals = np.arange(1000, dtype=np.int64) % 37
    path = tmp_path / "stats.parquet"
    with DeviceFileWriter(
        str(path), schema,
        WriterOptions(engine="tpu", data_page_values=256),
    ) as w:
        w.write_columns({"k": vals})
    md = pq.ParquetFile(str(path)).metadata
    col = md.row_group(0).column(0)
    assert col.statistics.min == 0 and col.statistics.max == 36
    # the page index exists and pyarrow can use it
    pr = pq.ParquetReader()
    pr.open(str(path))
    ci = pr.metadata.row_group(0).column(0)
    assert ci.total_compressed_size > 0
    tab = pq.read_table(str(path), filters=[("k", "=", 36)])
    assert set(tab["k"].to_pylist()) == {36}


def test_empty_and_all_null_groups(tmp_path):
    """Zero-row groups and all-null optional columns take the host path
    and still write valid files under engine=tpu."""
    t = types
    schema = t.message(
        "m",
        t.required(t.INT64).named("a"),
        t.optional(t.INT64).named("o"),
    )
    path = tmp_path / "empty.parquet"
    with DeviceFileWriter(str(path), schema,
                          WriterOptions(engine="tpu")) as w:
        w.write_columns({
            "a": np.array([], dtype=np.int64), "o": [],
        })
        w.write_columns({
            "a": np.arange(300, dtype=np.int64), "o": [None] * 300,
        })
    tab = pq.read_table(str(path))
    assert tab.num_rows == 300
    assert tab["o"].null_count == 300


def test_write_trace_counters_registered(tmp_path):
    """Every counter/span the write path emits is a registered name
    (FL-OBS001's runtime twin) and the launch counter reflects the
    two-launch shape."""
    t = types
    schema = t.message(
        "m",
        t.required(t.INT64).named("k"),
        t.required(t.DOUBLE).named("bs"),
    )
    with trace.scope() as tr:
        with DeviceFileWriter(
            str(tmp_path / "tr.parquet"), schema,
            WriterOptions(engine="tpu", column_encodings={
                "bs": "BYTE_STREAM_SPLIT",
            }),
        ) as w:
            w.write_columns({
                "k": np.arange(500, dtype=np.int64) % 5,
                "bs": rng.standard_normal(500),
            })
    c = tr.counters()
    for name in c:
        assert name in trace.names.ALL, name
    # dict column needs analyze+pack; bss finishes in analyze: 2 launches
    assert c["write.launches"] == 2
    assert c["write.device_columns"] == 2
    st = tr.stats()
    assert "write.encode" in st and "write.emit" in st


def test_persisted_pushdown_hwm(tmp_path):
    """Satellite (docs/pushdown.md): the pushdown capacity HWM persists
    next to the exec cache — a fresh ComputeRequest with the same
    predicate skips the initial-capacity guess."""
    from benchmarks.workloads import write_lineitem
    from parquet_floor_tpu.batch.predicate import col
    from parquet_floor_tpu.scan import ScanOptions, scan_device_groups
    from parquet_floor_tpu.tpu import exec_cache
    from parquet_floor_tpu.tpu.compute import ComputeRequest

    p = str(tmp_path / "hwm.parquet")
    write_lineitem(p, 800, row_group_rows=400, seed=3)
    pred = col("l_quantity") > 1.0  # nearly all rows survive
    cache = exec_cache.ExecutableCache(str(tmp_path / "cache"))
    exec_cache.activate(cache)
    try:
        for _ in scan_device_groups(
            [p], predicate=pred, scan=ScanOptions(pushdown=True),
            float64_policy="float64",
        ):
            pass
        warm = ComputeRequest(predicate=pred, cache_scope=p)
        key = warm._hwm_cache_key()
        assert cache.load_hwm(key) is not None
        assert warm.capacity_for(400) >= 384  # bucketed observed HWM
        # a different predicate stays cold (keys don't collide)
        cold = ComputeRequest(predicate=col("l_quantity") > 2.0,
                              cache_scope=p)
        assert cold.capacity_for(400) == 256  # the n//8-floor guess
        # a different DATASET stays cold too: selectivity is a property
        # of (predicate, data) — one corpus must not inflate another
        other = ComputeRequest(predicate=pred, cache_scope="/elsewhere")
        assert other.capacity_for(400) == 256
        # an EXPLICIT initial_capacity wins over the cached hint
        pinned = ComputeRequest(predicate=pred, cache_scope=p,
                                initial_capacity=32)
        assert pinned.capacity_for(400) <= 48  # bucketed 32, not 395
        # corrupt sidecar degrades to the guess, never raises
        (tmp_path / "cache" / "pushdown_hwm.json").write_text("{nope")
        fresh = exec_cache.ExecutableCache(str(tmp_path / "cache"))
        exec_cache.activate(fresh)
        again = ComputeRequest(predicate=pred, cache_scope=p)
        assert again.capacity_for(400) == 256
    finally:
        exec_cache.activate(None)
