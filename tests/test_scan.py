"""Scan scheduler (``parquet_floor_tpu.scan``): planner coalescing,
vectored reads, bounded cross-file prefetch, sequential-loop equivalence,
and the edge-case contract (empty dataset, faulted sources, salvage
rejection, clean shutdown on abandonment)."""

import threading

import numpy as np
import pytest

from parquet_floor_tpu import (
    IoRetryExhaustedError,
    ParquetFileReader,
    ParquetFileWriter,
    ParquetReader,
    ReaderOptions,
    TruncatedFileError,
    UnsupportedFeatureError,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.format.parquet_thrift import CompressionCodec
from parquet_floor_tpu.io.source import FileSource, RetryingSource
from parquet_floor_tpu.scan import (
    DatasetScanner,
    PrefetchedSource,
    ScanOptions,
    coalesce,
    plan_file,
    scan_batches,
    scan_device_groups,
)
from parquet_floor_tpu.scan.plan import Extent
from parquet_floor_tpu.testing import FaultInjectingSource


def _write(path, n=3000, groups=2, seed=0):
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.DOUBLE).named("d"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    rng = np.random.default_rng(seed)
    per = (n + groups - 1) // groups
    data = {
        "k": np.arange(n, dtype=np.int64) + seed * 1_000_000,
        "d": [
            None if i % 11 == 0 else float(v)
            for i, v in enumerate(rng.standard_normal(n))
        ],
        "s": [None if i % 7 == 0 else f"v{(i * 13 + seed) % 37}" for i in range(n)],
    }
    opts = WriterOptions(
        codec=CompressionCodec.SNAPPY, row_group_rows=per,
        data_page_values=400,
    )
    with ParquetFileWriter(path, schema, opts) as w:
        for lo in range(0, n, per):
            hi = min(lo + per, n)
            w.write_columns({k: v[lo:hi] for k, v in data.items()})
    return str(path)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("scan_ds")
    return [_write(str(d / f"f{i}.parquet"), seed=i) for i in range(4)]


def _seq_units(paths, column_filter=None):
    """The sequential per-file loop the scheduler must match bit-for-bit."""
    out = []
    for fi, p in enumerate(paths):
        with ParquetFileReader(p) as r:
            for gi in range(len(r.row_groups)):
                out.append((fi, gi, r.read_row_group(gi, column_filter)))
    return out


def _assert_batches_equal(a, b):
    assert a.num_rows == b.num_rows
    assert len(a.columns) == len(b.columns)
    for ca, cb in zip(a.columns, b.columns):
        assert ca.descriptor.path == cb.descriptor.path
        assert ca.num_values == cb.num_values
        if isinstance(ca.values, ByteArrayColumn):
            assert np.array_equal(ca.values.offsets, cb.values.offsets)
            assert np.array_equal(ca.values.data, cb.values.data)
        else:
            assert np.array_equal(np.asarray(ca.values), np.asarray(cb.values))
        for la, lb in ((ca.def_levels, cb.def_levels),
                       (ca.rep_levels, cb.rep_levels)):
            assert (la is None) == (lb is None)
            if la is not None:
                assert np.array_equal(la, lb)


# --- planner ---------------------------------------------------------------

def test_coalesce_merges_within_gap():
    ext = coalesce([(1000, 10), (0, 100), (150, 100)], 64, 1 << 20)
    assert [(e.offset, e.length, e.used) for e in ext] == [
        (0, 250, 200), (1000, 10, 10),
    ]


def test_coalesce_zero_gap_merges_touching_only():
    ext = coalesce([(0, 100), (100, 50), (151, 9)], 0, 1 << 20)
    assert [(e.offset, e.length) for e in ext] == [(0, 150), (151, 9)]


def test_coalesce_respects_extent_cap():
    assert len(coalesce([(0, 100), (100, 100)], 64, 150)) == 2
    # a single range larger than the cap stays one extent
    big = coalesce([(0, 1000)], 0, 10)
    assert len(big) == 1 and big[0].length == 1000


def test_coalesce_unions_overlapping_ranges():
    (e,) = coalesce([(0, 100), (50, 100)], 0, 1 << 20)
    assert (e.offset, e.length, e.used) == (0, 150, 150)


def test_plan_file_extents_and_counters(dataset):
    trace.enable()
    trace.reset()
    try:
        with ParquetFileReader(dataset[0]) as r:
            plan = plan_file(r)
        assert len(plan.groups) == 2
        for g in plan.groups:
            assert g.extents
            assert g.read_bytes >= g.used_bytes > 0
            assert g.num_rows > 0
        c = trace.counters()
        assert c["scan.extents_planned"] >= len(plan.groups)
        assert c["scan.bytes_read"] >= c["scan.bytes_used"] > 0
        assert c["scan.overread_bytes"] == (
            c["scan.bytes_read"] - c["scan.bytes_used"]
        )
    finally:
        trace.disable()
        trace.reset()


def test_plan_projection_shrinks_reads(dataset):
    with ParquetFileReader(dataset[0]) as r:
        full = plan_file(r)
        proj = plan_file(r, column_filter={"k"})
    assert sum(g.used_bytes for g in proj.groups) < \
        sum(g.used_bytes for g in full.groups)


# --- vectored source reads -------------------------------------------------

def test_read_many_matches_read_at(dataset, tmp_path):
    with FileSource(dataset[0]) as src:
        ranges = [(0, 64), (100, 17), (4, 1)]
        got = src.read_many(ranges)
        # one-shot iterables must not be silently exhausted by validation
        gen_got = src.read_many((o, n) for o, n in ranges)
        assert [bytes(b) for b in gen_got] == [bytes(b) for b in got]
        assert [bytes(b) for b in got] == [
            bytes(src.read_at(o, n)) for o, n in ranges
        ]
        with pytest.raises(TruncatedFileError):
            src.read_many([(0, 8), (src.size - 1, 2)])
    # stream without mmap/fileno: same results through the locked path
    import io as _io
    import pathlib

    data = pathlib.Path(dataset[0]).read_bytes()[:256]
    with FileSource(_io.BytesIO(data)) as src:
        assert bytes(src.read_many([(10, 5)])[0]) == data[10:15]


def test_retrying_read_many_budget_is_per_range(dataset):
    class Flaky:
        """Fails the first attempt of EVERY read; a shared budget would
        exhaust after the first range retried."""

        def __init__(self, inner):
            self._inner = inner
            self._seen = set()
            self.name = inner.name
            self.size = inner.size

        def read_at(self, offset, length):
            if (offset, length) not in self._seen:
                self._seen.add((offset, length))
                raise OSError("first-attempt flake")
            return self._inner.read_at(offset, length)

    with FileSource(dataset[0]) as inner:
        src = RetryingSource(Flaky(inner), retries=1, backoff_s=0.0)
        got = src.read_many([(0, 16), (16, 16), (64, 8)])
        assert [len(b) for b in got] == [16, 16, 8]
        assert src.retried_reads == 3


def test_prefetched_source_hit_miss_drop(dataset):
    with FileSource(dataset[0]) as inner:
        raw = bytes(inner.read_at(0, 256))
        cache = PrefetchedSource(inner)
        ext = [Extent(0, 128, 128)]
        assert cache.load(ext) == 128
        assert cache.load(ext) == 0  # idempotent
        assert bytes(cache.read_at(10, 20)) == raw[10:30]     # hit
        assert bytes(cache.read_at(100, 100)) == raw[100:200]  # miss → inner
        cache.drop(ext)
        assert bytes(cache.read_at(10, 20)) == raw[10:30]     # miss again


# --- the scheduler ---------------------------------------------------------

def test_scan_matches_sequential_loop(dataset):
    seq = _seq_units(dataset)
    with DatasetScanner(dataset) as scanner:
        got = list(scanner)
    assert [(u.file_index, u.group_index) for u in got] == [
        (fi, gi) for fi, gi, _ in seq
    ]
    for u, (_, _, b) in zip(got, seq):
        _assert_batches_equal(u.batch, b)


def test_scan_projection_and_predicate(dataset):
    from parquet_floor_tpu import col

    pred = col("k") > 1_000_000  # prunes every group of file 0
    with DatasetScanner(dataset, columns=["k"], predicate=pred) as scanner:
        got = list(scanner)
    assert got and all(u.file_index > 0 for u in got)
    for u in got:
        assert [c.descriptor.path for c in u.batch.columns] == [("k",)]


def test_scan_empty_dataset_yields_nothing():
    assert list(scan_batches([])) == []


def test_scan_single_row_group_file(tmp_path):
    path = _write(str(tmp_path / "one.parquet"), n=500, groups=1, seed=9)
    with DatasetScanner([path]) as scanner:
        units = list(scanner)
    assert [(u.file_index, u.group_index) for u in units] == [(0, 0)]
    (_, _, b), = _seq_units([path])
    _assert_batches_equal(units[0].batch, b)


def test_scan_budget_never_exceeded(dataset):
    costs = []
    for p in dataset:
        with ParquetFileReader(p) as r:
            for g in plan_file(r).groups:
                costs.append(max(g.read_bytes, g.uncompressed_bytes, 1))
    budget = max(costs)  # room for ~one group at a time
    trace.enable()
    trace.reset()
    try:
        with DatasetScanner(
            dataset, scan=ScanOptions(prefetch_bytes=budget, threads=3)
        ) as scanner:
            n = sum(u.batch.num_rows for u in scanner)
            assert scanner._budget.high_water <= budget
        # gauges are namespaced apart from additive counters now
        assert trace.gauges()["scan.inflight_bytes_max"] <= budget
        assert trace.metrics()["scan.inflight_bytes_max"] <= budget
    finally:
        trace.disable()
        trace.reset()
    assert n == sum(b.num_rows for _, _, b in _seq_units(dataset))


def test_scan_oversized_group_admitted_alone(dataset):
    # a budget smaller than any group still scans (units run one at a time)
    with DatasetScanner(
        dataset, scan=ScanOptions(prefetch_bytes=1, threads=2)
    ) as scanner:
        units = list(scanner)
    assert len(units) == 8


def test_scan_mid_scan_retry_exhausted(dataset):
    faulty = FaultInjectingSource(
        dataset[1], seed=3, transient_error_rate=1.0
    )
    sources = [dataset[0], faulty, dataset[2]]
    got = []
    with pytest.raises(IoRetryExhaustedError):
        for u in scan_batches(
            sources,
            options=ReaderOptions(io_retries=2, io_retry_backoff_s=0.0),
            scan=ScanOptions(threads=1),
        ):
            got.append(u)
    # the healthy head of the stream was delivered before the fault, in
    # sequential error order: every group of file 0, then the raise
    assert [(u.file_index, u.group_index) for u in got] == [(0, 0), (0, 1)]
    assert not [
        t for t in threading.enumerate() if t.name.startswith("pftpu-scan")
    ]


def _break_required_chunk(path, tmp_path, rg_idx=0, col="k", stem="bad"):
    """Corrupt the SECOND page header of one column chunk: framing
    damage the row-mask tier cannot localize — the chunk quarantines."""
    import pathlib

    from parquet_floor_tpu.format.parquet_thrift import PageHeader
    from parquet_floor_tpu.format.thrift import CompactReader

    with ParquetFileReader(path) as r:
        rg = r.row_groups[rg_idx]
        chunk = [
            c for c in rg.columns if c.meta_data.path_in_schema[0] == col
        ][0]
        m = chunk.meta_data
        start = m.data_page_offset
        if m.dictionary_page_offset:
            start = min(start, m.dictionary_page_offset)
        raw = bytes(r.source.read_at(start, m.total_compressed_size))
    cr = CompactReader(raw)
    h = PageHeader.read(cr)
    second = start + cr.pos + h.compressed_page_size
    data = bytearray(pathlib.Path(path).read_bytes())
    data[second] = 0xFF  # compact type 0x0F: unskippable garbage
    out = tmp_path / f"{stem}.parquet"
    out.write_bytes(bytes(data))
    return str(out)


def test_scan_salvage_merges_unit_reports(dataset, tmp_path):
    """The host scan face honors salvage: the damaged unit delivers its
    OWN per-unit report, the scanner folds them in delivery order, and
    the fold equals the sequential per-file reports' merge — identical
    skip keys, identical surviving bytes."""
    from parquet_floor_tpu.format.file_read import SalvageReport

    paths = list(dataset[:3])
    paths[1] = _break_required_chunk(dataset[1], tmp_path, 1, "k", "scan_q")

    # the sequential salvage face is the reference
    seq_units, seq_reports = [], []
    for p in paths:
        with ParquetFileReader(
            p, options=ReaderOptions(salvage=True)
        ) as r:
            for gi in range(len(r.row_groups)):
                seq_units.append(r.read_row_group(gi))
            seq_reports.append(r.salvage_report)
    seq_fold = SalvageReport.merge(seq_reports)

    with DatasetScanner(
        paths, options=ReaderOptions(salvage=True)
    ) as scanner:
        units = list(scanner)
        fold = scanner.salvage_report

    assert len(units) == len(seq_units)
    damaged = [u for u in units if u.file_index == 1 and u.group_index == 1]
    assert len(damaged) == 1
    assert damaged[0].salvage is not None
    assert [s.key() for s in damaged[0].salvage.skips] == \
        [(1, "k", None, "chunk")]
    # every clean unit still carries its (empty) per-unit report
    assert all(
        u.salvage is not None and
        (u is damaged[0] or not u.salvage.skips) for u in units
    )
    # dataset-level fold == sequential fold, key for key and counter
    # for counter
    assert [s.key() for s in fold.skips] == [s.key() for s in seq_fold.skips]
    assert fold.summary()["chunks_quarantined"] == 1
    assert fold.summary() == seq_fold.summary()
    # surviving decoded bytes are bit-identical to the sequential loop
    for got, want in zip(units, seq_units):
        _assert_batches_equal(got.batch, want)


def test_scan_verify_crc_passes_through(dataset):
    with DatasetScanner(
        dataset[:2], options=ReaderOptions(verify_crc=True)
    ) as scanner:
        units = list(scanner)
    assert len(units) == 4


def test_scan_abandoned_iterator_shuts_down_cleanly(dataset):
    gen = scan_batches(dataset, scan=ScanOptions(threads=3))
    first = next(gen)
    assert first.batch.num_rows > 0
    gen.close()  # consumer walks away mid-scan
    assert not [
        t for t in threading.enumerate() if t.name.startswith("pftpu-scan")
    ]
    # the scanner object form shuts down the same way (unmanaged on
    # purpose: this test IS the abandonment scenario)
    scanner = DatasetScanner(dataset, scan=ScanOptions(threads=2))  # floorlint: disable=FL-RES001
    next(iter(scanner))
    scanner.close()
    scanner.close()  # idempotent
    assert not [
        t for t in threading.enumerate() if t.name.startswith("pftpu-scan")
    ]


def test_scan_schema_mismatch_raises(dataset, tmp_path):
    other = str(tmp_path / "other.parquet")
    schema = types.message("t", types.required(types.INT32).named("x"))
    with ParquetFileWriter(other, schema) as w:
        w.write_columns({"x": np.arange(10, dtype=np.int32)})
    with pytest.raises(ValueError, match="schema"):
        list(scan_batches([dataset[0], other]))
    # the ROW stream keeps the sequential contract: a bare ValueError at
    # the file boundary, NOT the per-row RuntimeError wrap
    with pytest.raises(ValueError, match="schema") as ei:
        list(ParquetReader.stream_content(
            [dataset[0], other], _row_tuples, scan_options=ScanOptions()
        ))
    assert not isinstance(ei.value, RuntimeError)


# --- stream faces ----------------------------------------------------------

def _row_tuples(columns):
    class H:
        def start(self):
            return []

        def add(self, t, h, v):
            t.append(v)
            return t

        def finish(self, t):
            return tuple(t)

    return H()


def test_stream_content_scan_matches_sequential(dataset):
    seq = list(ParquetReader.stream_content(list(dataset), _row_tuples))
    scan = list(ParquetReader.stream_content(
        list(dataset), _row_tuples, scan_options=ScanOptions(threads=3)
    ))
    assert scan == seq


def test_stream_content_scan_single_source(dataset):
    seq = list(ParquetReader.stream_content(dataset[0], _row_tuples))
    scan = list(ParquetReader.stream_content(
        dataset[0], _row_tuples, scan_options=ScanOptions()
    ))
    assert scan == seq


def test_stream_content_scan_surface_parity(dataset):
    seq_it = ParquetReader.stream_content(list(dataset), _row_tuples)
    scan_it = ParquetReader.stream_content(
        list(dataset), _row_tuples, scan_options=ScanOptions()
    )
    try:
        # metadata/columns work before iteration, like the sequential face
        assert scan_it.metadata.num_rows == seq_it.metadata.num_rows
        assert [c.path for c in scan_it.columns] == [
            c.path for c in seq_it.columns
        ]
        assert scan_it.salvage_report is None
    finally:
        seq_it.close()
        scan_it.close()


def test_stream_content_scan_file_boundary_errors_stay_bare(dataset, tmp_path):
    from parquet_floor_tpu import CorruptFooterError

    bad = tmp_path / "trunc.parquet"
    bad.write_bytes(b"PAR1 definitely not a footer")
    # sequential contract: the second file's corrupt footer raises BARE
    with pytest.raises(CorruptFooterError):
        list(ParquetReader.stream_content(
            [dataset[0], str(bad)], _row_tuples,
            scan_options=ScanOptions(threads=1),
        ))


def test_stream_content_scan_supplier_called_per_file(dataset):
    calls = {"seq": 0, "scan": 0}

    def make_supplier(key):
        def supplier(columns):
            calls[key] += 1
            return _row_tuples(columns)
        return supplier

    list(ParquetReader.stream_content(list(dataset[:2]), make_supplier("seq")))
    list(ParquetReader.stream_content(
        list(dataset[:2]), make_supplier("scan"), scan_options=ScanOptions()
    ))
    assert calls["scan"] == calls["seq"] == 2


def test_scanner_columns_after_close_raises(dataset):
    with DatasetScanner(dataset[:1]) as scanner:
        pass  # closed by the with-exit without ever iterating
    with pytest.raises(ValueError, match="closed"):
        scanner.columns
    with pytest.raises(ValueError, match="closed"):
        scanner.metadata


def test_stream_content_scan_rejects_tpu_engine(dataset):
    with pytest.raises(ValueError, match="scan"):
        ParquetReader.stream_content(
            list(dataset), _row_tuples, engine="tpu",
            scan_options=ScanOptions(),
        )


def test_stream_batches_scan_matches_sequential(dataset):
    seq = list(ParquetReader.stream_batches(list(dataset)))
    scan = list(ParquetReader.stream_batches(
        list(dataset), scan_options=ScanOptions(threads=3)
    ))
    assert len(scan) == len(seq)
    for cols_a, cols_b in zip(seq, scan):
        assert len(cols_a) == len(cols_b)
        for a, b in zip(cols_a, cols_b):
            assert a.descriptor.path == b.descriptor.path
            va, vb = np.asarray(a.values), np.asarray(b.values)
            assert np.array_equal(va, vb)
            assert (a.mask is None) == (b.mask is None)
            if a.mask is not None:
                assert np.array_equal(np.asarray(a.mask), np.asarray(b.mask))


def test_stream_batches_scan_salvage_placeholder(dataset, tmp_path):
    """The scan-scheduled batch face under salvage matches the
    sequential batch face: the quarantined chunk stays IN POSITION as a
    fail-loudly placeholder, every other column is bit-identical."""
    paths = list(dataset[:2])
    paths[1] = _break_required_chunk(dataset[1], tmp_path, 0, "k", "sb_q")

    def stream(scan_options):
        return list(ParquetReader.stream_batches(
            list(paths), options=ReaderOptions(salvage=True),
            scan_options=scan_options,
        ))

    seq, scan = stream(None), stream(ScanOptions())
    assert len(seq) == len(scan) == 4
    for a, b in zip(seq, scan):
        assert [c.descriptor.path for c in a] == \
            [c.descriptor.path for c in b]
        assert [c.quarantined for c in a] == [c.quarantined for c in b]
        for ca, cb in zip(a, b):
            if ca.quarantined:
                continue
            if isinstance(ca.values, ByteArrayColumn):
                assert np.array_equal(ca.values.offsets, cb.values.offsets)
                assert np.array_equal(ca.values.data, cb.values.data)
            else:
                assert np.array_equal(
                    np.asarray(ca.values), np.asarray(cb.values)
                )
            assert (ca.mask is None) == (cb.mask is None)
            if ca.mask is not None:
                assert np.array_equal(
                    np.asarray(ca.mask), np.asarray(cb.mask)
                )
    # file 1 group 0's k chunk is the one placeholder, in position 0
    flags = [[c.quarantined for c in cols] for cols in scan]
    assert flags == [
        [False, False, False], [False, False, False],
        [True, False, False], [False, False, False],
    ]


# --- device leg ------------------------------------------------------------

def test_scan_device_groups_rejects_crc_without_salvage(dataset):
    # verify_crc alone: rejected by TpuRowGroupReader (host-pinned
    # feature) — the UnsupportedFeatureError contract, and nothing
    # leaks.  (salvage=True is HONORED now — see the test below — and
    # verify_crc+salvage rides the host salvage decode.)
    with pytest.raises(UnsupportedFeatureError):
        list(scan_device_groups(
            dataset[:2], options=ReaderOptions(verify_crc=True)
        ))
    assert not [
        t for t in threading.enumerate() if t.name.startswith("pftpu-scanio")
    ]


def test_scan_device_groups_salvage(dataset, tmp_path):
    """The device scan face honors salvage: the quarantined chunk
    arrives IN POSITION as a fail-loudly placeholder, surviving columns
    are the same device arrays a clean scan ships, and ``on_salvage``
    receives the dataset-level fold."""
    from parquet_floor_tpu.batch.columns import BatchColumn

    paths = list(dataset[:2])
    paths[1] = _break_required_chunk(dataset[1], tmp_path, 0, "k", "dev_q")
    reports = []
    got = list(scan_device_groups(
        paths, options=ReaderOptions(salvage=True),
        on_salvage=reports.append,
    ))
    assert [(fi, gi) for fi, gi, _ in got] == \
        [(0, 0), (0, 1), (1, 0), (1, 1)]
    # the damaged unit: k is a placeholder IN POSITION, d/s are real
    cols = got[2][2]
    assert list(cols) == ["k", "d", "s"]
    assert isinstance(cols["k"], BatchColumn) and cols["k"].quarantined
    assert not isinstance(cols["d"], BatchColumn)
    # surviving device arrays match the sequential device face's
    clean = list(scan_device_groups(paths[:1]))
    assert np.array_equal(
        np.asarray(got[0][2]["k"].values), np.asarray(clean[0][2]["k"].values)
    )
    assert len(reports) == 1
    fold = reports[0]
    assert [s.key() for s in fold.skips] == [(0, "k", None, "chunk")]
    assert fold.chunks_quarantined == 1
    assert not [
        t for t in threading.enumerate() if t.name.startswith("pftpu-scanio")
    ]

def test_scan_device_groups_matches_per_file_engine(dataset):
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    expect = []
    for fi, p in enumerate(dataset[:2]):
        with TpuRowGroupReader(p, float64_policy="bits") as tr:
            for gi, cols in enumerate(tr.iter_row_groups()):
                expect.append((fi, gi, {
                    k: (np.asarray(v.values),
                        None if v.mask is None else np.asarray(v.mask))
                    for k, v in cols.items()
                }))
    got = list(scan_device_groups(
        dataset[:2], scan=ScanOptions(threads=2), float64_policy="bits"
    ))
    assert [(fi, gi) for fi, gi, _ in got] == [
        (fi, gi) for fi, gi, _ in expect
    ]
    for (_, _, cols), (_, _, want) in zip(got, expect):
        assert set(cols) == set(want)
        for name, dc in cols.items():
            wv, wm = want[name]
            assert np.array_equal(np.asarray(dc.values), wv)
            assert (dc.mask is None) == (wm is None)
            if wm is not None:
                assert np.array_equal(np.asarray(dc.mask), wm)


def test_scan_device_groups_abandoned_early_quiesces(dataset):
    gen = scan_device_groups(dataset[:3], scan=ScanOptions(threads=2))
    next(gen)
    gen.close()  # consumer walks away: engine pipeline must drain FIRST,
    #              then readers close (no stage read races a close)
    lingering = [
        t.name for t in threading.enumerate()
        if t.name.startswith(("pftpu-scanio", "pftpu-stage", "pftpu-ship"))
    ]
    assert not lingering


def test_iter_dataset_row_groups_crosses_file_boundaries(dataset):
    from parquet_floor_tpu.tpu.engine import (
        TpuRowGroupReader,
        iter_dataset_row_groups,
    )

    readers = [
        TpuRowGroupReader(p, float64_policy="bits") for p in dataset[:3]
    ]
    try:
        tasks = [(r, i) for r in readers for i in range(r.num_row_groups)]
        ks = []
        for cols in iter_dataset_row_groups(tasks):
            ks.append(int(np.asarray(cols["k"].values)[0]))
        # six groups, in (file, group) order: first row of each group
        assert len(ks) == 6
        assert ks == sorted(ks)
    finally:
        for r in readers:
            r.close()


# -- predicate page pruning (ScanOptions.page_prune, docs/scan.md) -----------

def test_page_prune_delivers_covered_pages_bit_identical(dataset):
    from parquet_floor_tpu.batch.predicate import col

    # one exact key: stats prune 7 of 8 groups, the ColumnIndex narrows
    # the survivor to one page span per column
    pred = col("k") == 2_000_700  # file 2 (seed=2), group 0
    with trace.scope() as t:
        with DatasetScanner(dataset, predicate=pred,
                            scan=ScanOptions(page_prune=True)) as s:
            units = list(s)
    assert len(units) == 1
    fi, gi, batch = units[0].file_index, units[0].group_index, units[0].batch
    with ParquetFileReader(dataset[fi]) as r:
        n_group = int(r.row_groups[gi].num_rows)
        want, covered = r.read_row_group_ranges(gi, pred.row_ranges(r, gi))
    assert 0 < batch.num_rows < n_group
    assert batch.num_rows == want.num_rows == sum(b - a for a, b in covered)
    for a, b in zip(batch.columns, want.columns):
        va, vb = a.values, b.values
        if hasattr(va, "offsets"):
            np.testing.assert_array_equal(np.asarray(va.offsets),
                                          np.asarray(vb.offsets))
            np.testing.assert_array_equal(np.asarray(va.data),
                                          np.asarray(vb.data))
        else:
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
        if b.def_levels is not None:
            np.testing.assert_array_equal(a.def_levels, b.def_levels)
    assert t.counters().get("scan.pages_pruned", 0) >= 1
    # the covered rows contain every actually-matching row
    ks = np.asarray(batch.columns[0].values)
    assert 2_000_700 in ks


def test_page_prune_off_by_default_and_ignored_without_predicate(dataset):
    from parquet_floor_tpu.batch.predicate import col

    pred = col("k") == 2_000_700
    with trace.scope() as t:
        with DatasetScanner(dataset, predicate=pred) as s:
            full = [u.batch.num_rows for u in s]
    assert t.counters().get("scan.pages_pruned") is None
    with ParquetFileReader(dataset[2]) as r:
        assert full == [int(r.row_groups[0].num_rows)]
    # page_prune without a predicate: a plain full scan
    with trace.scope() as t:
        with DatasetScanner(dataset, scan=ScanOptions(page_prune=True)) as s:
            rows = sum(u.batch.num_rows for u in s)
    assert rows == 4 * 3000
    assert t.counters().get("scan.pages_pruned") is None


def test_page_prune_projection_composes(dataset):
    from parquet_floor_tpu.batch.predicate import col

    # predicate column NOT in the projection: covered pages are computed
    # over the SELECTED chunks, so only d's page spans are read
    pred = col("k") == 1_000_700
    with DatasetScanner(dataset, columns=["d"], predicate=pred,
                        scan=ScanOptions(page_prune=True)) as s:
        units = list(s)
    assert len(units) == 1
    batch = units[0].batch
    assert [b.descriptor.path[0] for b in batch.columns] == ["d"]
    with ParquetFileReader(dataset[1]) as r:
        want, _cov = r.read_row_group_ranges(
            units[0].group_index, pred.row_ranges(r, units[0].group_index),
            {"d"},
        )
    assert batch.num_rows == want.num_rows
    np.testing.assert_array_equal(
        np.asarray(batch.columns[0].values), np.asarray(want.columns[0].values)
    )


def test_page_prune_column_index_can_drop_whole_group(dataset):
    from parquet_floor_tpu.batch.predicate import col

    # an absent key INSIDE a group's min/max range: footer stats keep
    # the group, the per-page ColumnIndex kills every page — the group
    # must drop without reading a data byte
    with ParquetFileReader(dataset[0]) as r:
        ks = np.asarray(r.read_row_group(0, {"k"}).columns[0].values)
    absent = int(ks[0]) + 1
    while absent in ks:
        absent += 1
    pred = col("k") == absent
    with trace.scope() as t:
        with DatasetScanner(dataset[:1], predicate=pred,
                            scan=ScanOptions(page_prune=True)) as s:
            units = list(s)
    if units:  # a page whose [min,max] brackets the hole still covers it
        assert all(u.batch.num_rows < 1500 for u in units)
    else:
        assert t.counters().get("scan.pages_pruned", 0) >= 1


def test_page_prune_salvage_keeps_pruning_on_clean_files(dataset):
    from parquet_floor_tpu.batch.predicate import col

    pred = col("k") == 2_000_700
    with DatasetScanner(
        dataset, predicate=pred, options=ReaderOptions(salvage=True),
        scan=ScanOptions(page_prune=True),
    ) as s:
        units = list(s)
    # ranged salvage keeps the I/O pruning on clean chunks: the
    # surviving group arrives narrowed to its page cover, bit-identical
    # to the strict pruned read (only a DAMAGED chunk's spans widen)
    assert len(units) == 1
    batch = units[0].batch
    with ParquetFileReader(dataset[2]) as r:
        n_group = int(r.row_groups[0].num_rows)
        want, covered = r.read_row_group_ranges(0, pred.row_ranges(r, 0))
    assert 0 < batch.num_rows < n_group
    assert batch.num_rows == want.num_rows == sum(b - a for a, b in covered)
    for a, b in zip(batch.columns, want.columns):
        va, vb = a.values, b.values
        if hasattr(va, "offsets"):
            np.testing.assert_array_equal(np.asarray(va.offsets),
                                          np.asarray(vb.offsets))
            np.testing.assert_array_equal(np.asarray(va.data),
                                          np.asarray(vb.data))
        else:
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    # clean file: nothing quarantined, nothing widened
    assert units[0].salvage is None or units[0].salvage.skips == []
