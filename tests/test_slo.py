"""Per-tenant SLO monitor: window/burn-rate math on an injected clock,
the breach decision on the tenant's tracer, and the render-paths-
don't-hold-the-gate-lock pin (docs/serving.md)."""

import threading
import time

import pytest

from parquet_floor_tpu.serve import Serving, SloMonitor, SloTarget
from parquet_floor_tpu.serve.slo import tenant_errors
from parquet_floor_tpu.utils.histogram import LogHistogram


def _hist(values):
    h = LogHistogram()
    for v in values:
        h.record(v)
    return h


def _target(**kw):
    kw.setdefault("p99_seconds", 0.01)
    kw.setdefault("fast_window_s", 60.0)
    kw.setdefault("slow_window_s", 600.0)
    return SloTarget(**kw)


# --- target validation ------------------------------------------------------

def test_target_validation():
    with pytest.raises(ValueError, match="p99_seconds"):
        SloTarget(p99_seconds=0)
    with pytest.raises(ValueError, match="latency_budget"):
        SloTarget(p99_seconds=1, latency_budget=1.5)
    with pytest.raises(ValueError, match="windows"):
        SloTarget(p99_seconds=1, fast_window_s=10, slow_window_s=5)


# --- window / burn-rate math ------------------------------------------------

def test_no_traffic_is_not_a_breach():
    m = SloMonitor("t", _target())
    st = m.evaluate(now=0.0)
    assert not st.breach and st.fast_burn == 0.0 and st.samples == 0
    m.observe(None, now=1.0)      # empty snapshot still advances windows
    assert not m.evaluate(now=2.0).breach


def test_burn_rate_is_violation_fraction_over_budget():
    # 5% of requests over the bound with a 1% budget = burn 5.0
    m = SloMonitor("t", _target())
    good = [0.001] * 95
    bad = [0.5] * 5
    m.observe(_hist(good + bad), now=10.0)
    st = m.evaluate(now=10.0)
    assert st.fast_burn == pytest.approx(5.0, rel=0.05)
    assert st.slow_burn == pytest.approx(5.0, rel=0.05)
    # 5x burns neither threshold: no breach
    assert not st.breach


def test_breach_requires_both_windows_burning():
    t = _target(fast_window_s=60.0, slow_window_s=600.0)
    m = SloMonitor("t", t)
    # hour of clean traffic, then a hot fast window: the slow window is
    # diluted below its threshold -> no page (the blip guard)
    clean = _hist([0.001] * 5000)
    m.observe(clean, now=0.0)
    hot = clean.copy()
    for _ in range(60):
        hot.record(0.5)
    m.observe(hot, now=550.0)
    st = m.evaluate(now=550.0)
    assert st.fast_burn >= t.fast_burn        # the fast window IS hot
    assert st.slow_burn < t.slow_burn         # ...but diluted over 10 min
    assert not st.breach
    # sustained: the slow window fills with violations too -> breach
    m2 = SloMonitor("t", t)
    m2.observe(_hist([]), now=0.0)
    cum = _hist([])
    for step in range(1, 11):
        for _ in range(50):
            cum.record(0.5)
        m2.observe(cum, now=step * 60.0)
    st2 = m2.evaluate(now=600.0)
    assert st2.fast_burn >= t.fast_burn and st2.slow_burn >= t.slow_burn
    assert st2.breach and st2.latency_breach


def test_window_subtracts_the_far_edge_snapshot():
    t = _target(fast_window_s=10.0, slow_window_s=100.0)
    m = SloMonitor("t", t)
    first = _hist([0.5] * 100)            # old violations
    m.observe(first, now=0.0)
    cum = first.copy()
    for _ in range(100):
        cum.record(0.001)                 # recent traffic is clean
    m.observe(cum, now=50.0)
    st = m.evaluate(now=50.0)
    # fast window (40..50): only the clean increase counts
    assert st.fast_burn == 0.0
    assert st.samples == 100
    # slow window still sees everything (first snapshot is its edge)
    assert st.slow_burn > 0.0


def test_old_snapshots_are_pruned_but_edge_kept():
    t = _target(fast_window_s=1.0, slow_window_s=10.0)
    m = SloMonitor("t", t)
    cum = _hist([])
    for step in range(50):
        cum.record(0.001)
        m.observe(cum, now=float(step))
    assert len(m._snaps) <= 13   # ~slow window + edge, never all 50
    assert m.evaluate(now=49.0).samples >= 1


def test_error_burn_path():
    t = _target(error_rate=0.01, fast_burn=2.0, slow_burn=2.0)
    m = SloMonitor("t", t)
    h = _hist([0.001] * 90)       # latencies all fine
    m.observe(h, errors=10, now=5.0)    # 10 errors / 100 requests
    st = m.evaluate(now=5.0)
    assert st.error_breach and st.breach and not st.latency_breach
    assert st.fast_error_burn == pytest.approx(10.0, rel=0.01)


def test_tenant_errors_counts_the_registered_counters():
    assert tenant_errors({"io.retry_exhausted": 2,
                          "io.remote.breaker_fast_fails": 3,
                          "serve.cache_hits": 99}) == 5


# --- Serving integration ----------------------------------------------------

def test_injected_slow_tenant_breaches_healthy_does_not():
    with Serving(prefetch_bytes=8 << 20) as srv:
        slow = srv.tenant("slow")
        healthy = srv.tenant("healthy")
        target = _target(p99_seconds=0.002)
        srv.set_slo("slow", target)
        srv.set_slo("healthy", target)
        assert not any(s.breach for s in srv.check_slos(now=0.0).values())
        for _ in range(100):
            slow.tracer.observe("serve.lookup_seconds", 0.05)
            healthy.tracer.observe("serve.lookup_seconds", 0.0004)
        statuses = srv.check_slos(now=30.0)
        assert statuses["slow"].breach
        assert not statuses["healthy"].breach
        # the alert lands on the BREACHING tenant's tracer, registered
        assert any(d["decision"] == "serve.slo_breach"
                   for d in slow.tracer.decisions())
        assert not any(d["decision"] == "serve.slo_breach"
                       for d in healthy.tracer.decisions())
        # and the one-page summary renders both states
        page = srv.health(now=31.0)
        assert "BREACH" in page and "healthy" in page and "slow" in page


def test_set_slo_requires_registered_tenant():
    with Serving() as srv:
        with pytest.raises(ValueError, match="not registered"):
            srv.set_slo("ghost", _target())


def test_closed_tenant_drops_its_monitor():
    with Serving() as srv:
        t = srv.tenant("gone")
        srv.set_slo("gone", _target())
        t.close()
        assert srv.check_slos(now=1.0) == {}


# --- the FL-LOCK002 pin: render paths never hold the WFQ gate lock ----------

def _assert_completes(fn, timeout=5.0):
    out = {}

    def run():
        out["v"] = fn()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout)
    assert not th.is_alive(), (
        "render path blocked while another thread held the gate lock"
    )
    return out["v"]


def test_health_and_report_do_not_take_the_gate_lock_while_formatting():
    """Hold the fair gate's condition variable hostage on one thread;
    Serving.health() and Tenant.report() must still complete — they
    snapshot under the lock (bounded) or not at all, and format
    outside.  A formatter that renders UNDER the cv would deadlock
    here and trip the join timeout."""
    with Serving(prefetch_bytes=8 << 20) as srv:
        tenant = srv.tenant("t")
        srv.set_slo("t", _target())
        tenant.tracer.observe("serve.lookup_seconds", 0.001)
        gate_cv = srv._gate._cv
        acquired = threading.Event()
        release = threading.Event()

        def hog():
            with gate_cv:
                acquired.set()
                release.wait(10)

        hogger = threading.Thread(target=hog, daemon=True)
        hogger.start()
        assert acquired.wait(5)
        try:
            # Tenant.report never touches the gate; health's only gate
            # contact is the bounded stats() snapshot — it must NOT be
            # part of the formatting phase.  With the cv held, health()
            # may block only inside that snapshot; to pin the contract
            # the snapshot is taken hostage-free first:
            rep = _assert_completes(lambda: tenant.report())
            assert rep.histogram("serve.lookup_seconds").count == 1
        finally:
            release.set()
            hogger.join(5)
        # with the gate free again, health() completes and is formed
        page = _assert_completes(lambda: srv.health(now=1.0))
        assert page.startswith("serving health:")


def test_gate_stats_is_a_bounded_snapshot():
    with Serving(prefetch_bytes=8 << 20) as srv:
        t0 = time.perf_counter()
        st = srv._gate.stats()
        assert time.perf_counter() - t0 < 1.0
        assert st["inflight_bytes"] == 0 and st["waiters"] == 0
        assert st["capacity_bytes"] == 8 << 20


def test_set_slo_baselines_out_historic_traffic():
    """Attaching an SLO to a tenant with PRIOR slow traffic must not
    fire on the first tick — only post-attach increases count (the
    spurious-page guard); fresh slow traffic after the attach still
    breaches."""
    with Serving(prefetch_bytes=8 << 20) as srv:
        t = srv.tenant("t")
        for _ in range(100):
            t.tracer.observe("serve.lookup_seconds", 1.0)  # historic
        srv.set_slo("t", _target(p99_seconds=0.005))
        st = srv.check_slos(now=10.0)["t"]
        assert not st.breach and st.samples == 0, st.render()
        for _ in range(50):
            t.tracer.observe("serve.lookup_seconds", 1.0)  # post-attach
        assert srv.check_slos(now=20.0)["t"].breach
