"""Persistent AOT executable cache (docs/perf.md): key correctness, the
corrupt/stale failure domain, cross-process concurrency, and the
one-launch contract the cache dispatches under."""

import os
import threading

import numpy as np
import pytest

import jax

from parquet_floor_tpu import (
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.tpu import exec_cache
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
from parquet_floor_tpu.utils import trace

rng = np.random.default_rng(77)


@pytest.fixture(autouse=True)
def _isolate_cache(monkeypatch):
    """Every test starts with the cache OFF and no leaked forced cache."""
    monkeypatch.delenv("PFTPU_EXEC_CACHE", raising=False)
    exec_cache.activate(None)
    yield
    exec_cache.activate(None)


def _write(tmp_path, name="t.parquet", n=600, group=300, options=None):
    """A 3-column file written GROUP rows per row group (write_columns
    emits one group per call)."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.INT32).named("b"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    path = tmp_path / name
    with ParquetFileWriter(
        path, schema,
        options or WriterOptions(row_group_rows=group, data_page_values=group),
    ) as w:
        for lo in range(0, n, group):
            m = min(group, n - lo)
            w.write_columns({
                "a": rng.integers(0, 50, m).astype(np.int64),
                "b": [None if i % 5 == 0 else i % 40 for i in range(m)],
                "s": [f"v{i % 30}" for i in range(m)],
            })
    return path


def _decode(path, cache_dir=None, out_perm=None, columns=None):
    """Decode group 0 under a fresh tracer scope; returns (cols-as-
    numpy, counters).  ``cache_dir`` installs a FRESH ExecutableCache
    (empty memory — the disk is the only carry-over, exactly like a new
    process)."""
    exec_cache.activate(
        exec_cache.ExecutableCache(str(cache_dir)) if cache_dir else None
    )
    try:
        with trace.scope() as t:
            with TpuRowGroupReader(path, float64_policy="bits") as tr:
                cols = tr.read_row_group(0, columns=columns,
                                         out_perm=out_perm)
                jax.block_until_ready([c.values for c in cols.values()])
                out = {
                    k: (
                        np.asarray(v.values),
                        None if v.mask is None else np.asarray(v.mask),
                        None if v.lengths is None else np.asarray(v.lengths),
                    )
                    for k, v in cols.items()
                }
        return out, t.counters()
    finally:
        exec_cache.activate(None)


def _assert_same(a, b):
    assert a.keys() == b.keys()
    for k in a:
        for x, y in zip(a[k], b[k]):
            if x is None:
                assert y is None
            else:
                np.testing.assert_array_equal(x, y, err_msg=k)


def _entries(cache_dir):
    return sorted(p for p in os.listdir(cache_dir) if p.endswith(".pfexec"))


# -- hit/miss + bit-identity --------------------------------------------------

def test_warm_cache_skips_compile_bit_identically(tmp_path):
    path = _write(tmp_path)
    d = tmp_path / "cache"
    ref, _ = _decode(path)                       # uncached reference
    cold, cc = _decode(path, cache_dir=d)        # cold: compile + store
    assert cc.get("engine.exec_cache_misses") == 1
    assert cc.get("engine.exec_cache_hits", 0) == 0
    assert cc.get("engine.compile_ms", 0) > 0
    assert len(_entries(d)) == 1
    warm, wc = _decode(path, cache_dir=d)        # fresh cache object ≙ 2nd process
    assert wc.get("engine.exec_cache_hits") == 1
    assert wc.get("engine.exec_cache_misses", 0) == 0
    assert wc.get("engine.compile_ms", 0) == 0
    _assert_same(ref, cold)
    _assert_same(ref, warm)


def test_cache_off_without_env_or_activation(tmp_path):
    path = _write(tmp_path)
    _, c = _decode(path)
    assert "engine.exec_cache_misses" not in c
    assert "engine.exec_cache_hits" not in c
    assert c.get("engine.launches") == 1


# -- key separation -----------------------------------------------------------

def test_keys_distinct_by_encoding_set(tmp_path):
    """Two files differing ONLY in encoding (dictionary vs PLAIN int
    columns) must not share an executable."""
    d = tmp_path / "cache"
    p1 = _write(tmp_path, "dict.parquet")
    p2 = _write(tmp_path, "plain.parquet",
                options=WriterOptions(row_group_rows=300,
                                      data_page_values=300,
                                      enable_dictionary=False))
    _decode(p1, cache_dir=d)
    one = _entries(d)
    _, c2 = _decode(p2, cache_dir=d)
    assert c2.get("engine.exec_cache_misses") == 1  # no false hit
    assert len(_entries(d)) == len(one) + 1


def test_keys_distinct_by_shape_bucket(tmp_path):
    """Different bucketed group shapes compile different programs —
    each keys its own entry."""
    d = tmp_path / "cache"
    _decode(_write(tmp_path, "n300.parquet", n=300, group=300), cache_dir=d)
    n1 = len(_entries(d))
    _, c = _decode(
        _write(tmp_path, "n900.parquet", n=900, group=900), cache_dir=d
    )
    assert c.get("engine.exec_cache_misses") == 1
    assert len(_entries(d)) == n1 + 1


def test_keys_distinct_by_out_perm_presence(tmp_path):
    path = _write(tmp_path)
    d = tmp_path / "cache"
    ref, _ = _decode(path)
    _decode(path, cache_dir=d)
    n1 = len(_entries(d))
    perm = np.arange(300, dtype=np.int32)[::-1].copy()
    permed, c = _decode(path, cache_dir=d, out_perm=perm)
    assert c.get("engine.exec_cache_misses") == 1   # separate program
    assert len(_entries(d)) == n1 + 1
    for k in ref:
        vals, mask, lens = permed[k]
        np.testing.assert_array_equal(vals, ref[k][0][::-1], err_msg=k)
    # warm hit on the perm-fused program replays bit-identically
    permed2, c2 = _decode(path, cache_dir=d, out_perm=perm)
    assert c2.get("engine.exec_cache_hits") == 1
    _assert_same(permed, permed2)


# -- failure domain -----------------------------------------------------------

def test_corrupt_entry_falls_back_to_fresh_compile(tmp_path):
    path = _write(tmp_path)
    d = tmp_path / "cache"
    cold, _ = _decode(path, cache_dir=d)
    (entry,) = _entries(d)
    (d / entry).write_bytes(b"garbage" * 100)
    warm, c = _decode(path, cache_dir=d)
    assert c.get("engine.exec_cache_misses") == 1   # corrupt ⇒ miss
    assert c.get("engine.compile_ms", 0) > 0
    _assert_same(cold, warm)
    # the fresh compile re-published a loadable entry
    again, c2 = _decode(path, cache_dir=d)
    assert c2.get("engine.exec_cache_hits") == 1
    _assert_same(cold, again)


def test_truncated_entry_falls_back(tmp_path):
    path = _write(tmp_path)
    d = tmp_path / "cache"
    cold, _ = _decode(path, cache_dir=d)
    (entry,) = _entries(d)
    blob = (d / entry).read_bytes()
    (d / entry).write_bytes(blob[: len(blob) // 3])
    warm, c = _decode(path, cache_dir=d)
    assert c.get("engine.exec_cache_misses") == 1
    _assert_same(cold, warm)


def test_version_mismatched_entry_is_a_miss(tmp_path):
    """An entry whose header names a different toolchain must be
    ignored (defense in depth past the key hash) — decode falls back to
    a fresh compile, bit-identically."""
    import json as _json

    path = _write(tmp_path)
    d = tmp_path / "cache"
    cold, _ = _decode(path, cache_dir=d)
    (entry,) = _entries(d)
    blob = (d / entry).read_bytes()
    off = len(b"PFEXEC1\n")
    hlen = int.from_bytes(blob[off : off + 4], "little")
    header = _json.loads(blob[off + 4 : off + 4 + hlen])
    header["jax"] = "0.0.0-stale"
    new_header = _json.dumps(header, sort_keys=True).encode()
    (d / entry).write_bytes(
        blob[:off]
        + len(new_header).to_bytes(4, "little")
        + new_header
        + blob[off + 4 + hlen :]
    )
    warm, c = _decode(path, cache_dir=d)
    assert c.get("engine.exec_cache_misses") == 1
    assert c.get("engine.exec_cache_hits", 0) == 0
    _assert_same(cold, warm)


def test_concurrent_processes_racing_one_key(tmp_path):
    """Two cache objects (≙ two processes) compiling + publishing the
    same key concurrently: both land complete entries (atomic replace),
    and a third loader reads a valid one."""
    path = _write(tmp_path)
    d = tmp_path / "cache"
    results = {}
    errs = []

    def race(tag):
        try:
            results[tag] = _decode_with_own_cache(path, d)
        except BaseException as e:  # pragma: no cover - diagnostic
            errs.append(e)

    def _decode_with_own_cache(path, d):
        cache = exec_cache.ExecutableCache(str(d))
        with trace.scope():
            with TpuRowGroupReader(path, float64_policy="bits") as tr:
                sg = tr._stage_row_group(0, None)
                shipped = tr._ship(sg)
                parts = (
                    shipped[0] if isinstance(shipped[0], tuple)
                    else (shipped[0],)
                )
                # the full launch arg list, extras included, exactly as
                # _decode_shipped builds it
                extra_args = []
                for key in sg.extra_keys:
                    rows_d, lens_d = tr._sdict_dev[key]
                    extra_args.extend((rows_d, lens_d))
                args = [*parts, shipped[1], *extra_args]
                from parquet_floor_tpu.tpu.engine import _decode_fused

                outs = cache.call(
                    _decode_fused, (sg.program, len(parts)), args
                )
                jax.block_until_ready([o[0] for o in outs])
                return [np.asarray(o[0]) for o in outs]

    t1 = threading.Thread(target=race, args=("a",))
    t2 = threading.Thread(target=race, args=("b",))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert not errs
    assert len(_entries(d)) == 1
    for x, y in zip(results["a"], results["b"]):
        np.testing.assert_array_equal(x, y)
    # the published entry is loadable by a fresh "process"
    _, c = _decode(path, cache_dir=d)
    assert c.get("engine.exec_cache_hits") == 1


# -- one-launch contract ------------------------------------------------------

def test_in_cap_group_is_exactly_one_launch(tmp_path):
    path = _write(tmp_path)
    with trace.scope() as t:
        with TpuRowGroupReader(path, float64_policy="bits") as tr:
            cols = tr.read_row_group(0)
            jax.block_until_ready([c.values for c in cols.values()])
    assert t.counters().get("engine.launches") == 1


def test_chunked_fallback_launches_more_but_matches(tmp_path, monkeypatch):
    from parquet_floor_tpu import ParquetFileReader

    path = _write(tmp_path, n=900, group=900)
    ref, _ = _decode(path)
    with ParquetFileReader(path) as r:
        est = sum(
            int(c.meta_data.total_uncompressed_size or 0)
            for c in (r.row_groups[0].columns or [])
        )
    cap = max(est // 3, 1 << 9)   # force the multi-launch column bins
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(cap))
    with trace.scope() as t:
        with TpuRowGroupReader(path, float64_policy="bits") as tr:
            assert tr._arena_cap == cap
            cols = tr.read_row_group(0)
            jax.block_until_ready([c.values for c in cols.values()])
            got = {
                k: (
                    np.asarray(v.values),
                    None if v.mask is None else np.asarray(v.mask),
                    None if v.lengths is None else np.asarray(v.lengths),
                )
                for k, v in cols.items()
            }
    assert t.counters().get("engine.launches", 0) > 1
    # bit-exact across the multi-launch fallback (strings: same bucket
    # discipline — compare through lengths)
    for k in ref:
        rv, rm, rl = ref[k]
        gv, gm, gl = got[k]
        if rl is not None:
            np.testing.assert_array_equal(gl, rl, err_msg=k)
            w = min(rv.shape[1], gv.shape[1])
            ix = np.arange(w)[None, :]
            keep = ix < rl[:, None]
            np.testing.assert_array_equal(
                np.where(keep, gv[:, :w], 0), np.where(keep, rv[:, :w], 0),
                err_msg=k,
            )
        else:
            np.testing.assert_array_equal(gv, rv, err_msg=k)
        if rm is not None:
            np.testing.assert_array_equal(gm, rm, err_msg=k)


# -- k concurrent stage workers (scan-scheduler carry-over) -------------------

def _write_plain_ints(tmp_path, name, n=800, group=200, seed=0):
    r = np.random.default_rng(seed)
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.optional(types.INT32).named("y"),
    )
    path = tmp_path / name
    with ParquetFileWriter(
        path, schema,
        WriterOptions(row_group_rows=group, data_page_values=group),
    ) as w:
        for lo in range(0, n, group):
            m = min(group, n - lo)
            w.write_columns({
                "x": r.integers(0, 1 << 40, m).astype(np.int64),
                "y": [None if i % 3 == 0 else lo + i for i in range(m)],
            })
    return path


def test_concurrent_stage_workers_preserve_order_and_bytes(
    tmp_path, monkeypatch
):
    """PFTPU_STAGE_WORKERS=2 on a multi-file scan: delivery order and
    decoded bytes identical to the single-worker pipeline, and the
    queue-depth gauge records real depth."""
    from parquet_floor_tpu.tpu.engine import iter_dataset_row_groups

    paths = [
        _write_plain_ints(tmp_path, f"f{i}.parquet", seed=i)
        for i in range(3)
    ]

    def run():
        out = []
        readers = [TpuRowGroupReader(p, float64_policy="bits")
                   for p in paths]
        try:
            tasks = [
                (r, gi)
                for r in readers
                for gi in range(r.num_row_groups)
            ]
            for cols in iter_dataset_row_groups(tasks):
                out.append({
                    k: (
                        np.asarray(v.values),
                        None if v.mask is None else np.asarray(v.mask),
                    )
                    for k, v in cols.items()
                })
        finally:
            for r in readers:
                r.close()
        return out

    monkeypatch.delenv("PFTPU_STAGE_WORKERS", raising=False)
    want = run()
    monkeypatch.setenv("PFTPU_STAGE_WORKERS", "2")
    with trace.scope() as t:
        got = run()
    depth = t.gauges().get("engine.stage_queue_depth_max", 0)
    assert 1 <= depth <= 3
    assert len(got) == len(want) == 12
    for a, b in zip(got, want):
        assert a.keys() == b.keys()
        for k in a:
            np.testing.assert_array_equal(a[k][0], b[k][0], err_msg=k)
            if b[k][1] is not None:
                np.testing.assert_array_equal(a[k][1], b[k][1], err_msg=k)


def test_jax_compilation_cache_flag_survives_resolution(tmp_path):
    """The cache compiles with jax's own persistent compilation cache
    BYPASSED (a jax-cache-deserialized executable cannot be
    re-serialized faithfully on XLA:CPU — storing one poisons every
    later process); the flag must come back exactly as it was."""
    import jax

    prev = bool(jax.config.jax_enable_compilation_cache)
    _decode(_write(tmp_path), cache_dir=tmp_path / "c")
    assert bool(jax.config.jax_enable_compilation_cache) == prev


def test_keys_distinct_by_target_device(tmp_path):
    """Readers pinned to different devices must not share an
    executable (it is bound to the device its inputs live on) — and a
    store failure (read-only dir) must never fail a decode."""
    path = _write(tmp_path)
    d = tmp_path / "cache"
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the multi-device CPU mesh")

    def decode_on(device):
        exec_cache.activate(exec_cache.ExecutableCache(str(d)))
        try:
            with trace.scope() as t:
                with TpuRowGroupReader(
                    path, device=device, float64_policy="bits"
                ) as tr:
                    cols = tr.read_row_group(0)
                    jax.block_until_ready(
                        [c.values for c in cols.values()]
                    )
                    out = {k: np.asarray(v.values) for k, v in cols.items()}
            return out, t.counters()
        finally:
            exec_cache.activate(None)

    a, ca = decode_on(devs[0])
    b, cb = decode_on(devs[1])
    assert ca.get("engine.exec_cache_misses") == 1
    assert cb.get("engine.exec_cache_misses") == 1   # no cross-device hit
    assert len(_entries(d)) == 2
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_store_failure_degrades_to_uncached(tmp_path):
    path = _write(tmp_path)
    d = tmp_path / "cache"
    d.mkdir()
    os.chmod(d, 0o500)   # read-only: every store fails
    try:
        out, c = _decode(path, cache_dir=d)
        assert c.get("engine.exec_cache_misses") == 1
        assert c.get("engine.launches") == 1
        ref, _ = _decode(path)
        _assert_same(ref, out)
    finally:
        os.chmod(d, 0o700)


# -- directory GC (PFTPU_EXEC_CACHE_MAX_BYTES) -------------------------------

def test_gc_collects_old_toolchain_entries_at_publish(tmp_path):
    """A size-capped cache dir evicts LRU-by-mtime at publish time: the
    stale-toolchain entries (different header, never touched again — a
    jax upgrade's leftovers) die first; the fresh publish survives."""
    path = _write(tmp_path)
    d = tmp_path / "cache"
    d.mkdir()
    stale = []
    for i in range(3):
        p = d / (f"{i:064x}.pfexec")
        p.write_bytes(b"PFEXEC0\n" + b"old-toolchain-entry" * 512)
        os.utime(p, (1_000_000 + i, 1_000_000 + i))  # ancient mtimes
        stale.append(p)
    cache = exec_cache.ExecutableCache(
        str(d), max_bytes=sum(p.stat().st_size for p in stale) // 2
    )
    exec_cache.activate(cache)
    try:
        out, c = _decode_active(path)
        assert c.get("engine.exec_cache_misses") == 1
    finally:
        exec_cache.activate(None)
    left = _entries(d)
    # the fresh entry survives even if it alone exceeds the cap; every
    # stale entry old enough to make room is gone
    assert len(left) >= 1
    for p in stale:
        assert not p.exists(), f"stale entry {p.name} survived GC"
    # the survivor is the just-published one (loadable on a fresh cache)
    out2, c2 = _decode(path, cache_dir=d)
    assert c2.get("engine.exec_cache_hits") == 1
    _assert_same(out, out2)


def test_gc_env_default_and_validation(tmp_path, monkeypatch):
    monkeypatch.setenv("PFTPU_EXEC_CACHE_MAX_BYTES", "12345")
    assert exec_cache.ExecutableCache(str(tmp_path)).max_bytes == 12345
    monkeypatch.delenv("PFTPU_EXEC_CACHE_MAX_BYTES")
    assert exec_cache.ExecutableCache(str(tmp_path)).max_bytes is None
    with pytest.raises(ValueError):
        exec_cache.ExecutableCache(str(tmp_path), max_bytes=-1)


def test_load_touches_mtime_so_hot_entries_survive(tmp_path):
    """A disk hit refreshes the entry's mtime — the GC's LRU signal."""
    path = _write(tmp_path)
    d = tmp_path / "cache"
    _decode(path, cache_dir=d)  # publish
    entry = d / _entries(d)[0]
    os.utime(entry, (1_000_000, 1_000_000))
    before = entry.stat().st_mtime
    _decode(path, cache_dir=d)  # fresh cache object: disk hit
    assert entry.stat().st_mtime > before


def _decode_active(path):
    """Like _decode but uses the ALREADY-activated cache (GC tests
    install a configured ExecutableCache first)."""
    with trace.scope() as t:
        with TpuRowGroupReader(path, float64_policy="bits") as tr:
            cols = tr.read_row_group(0)
            jax.block_until_ready([c.values for c in cols.values()])
            out = {
                k: (
                    np.asarray(v.values),
                    None if v.mask is None else np.asarray(v.mask),
                    None if v.lengths is None else np.asarray(v.lengths),
                )
                for k, v in cols.items()
            }
    return out, t.counters()


# -- footer bucket pre-seed (PFTPU_STAGE_WORKERS > 1) ------------------------

def test_stage_workers_k2_padded_widths_byte_stable(tmp_path, monkeypatch):
    """PR 8's caveat, closed: with k=2 stage workers, two runs over the
    same multi-file dataset must produce IDENTICAL device-column shapes
    (padded widths included) — the footer pre-seed pins every
    size-driven bucket to its file-wide max before staging starts."""
    from parquet_floor_tpu.tpu.engine import iter_dataset_row_groups

    paths = []
    for i in range(2):
        # uneven group sizes: the short last group is exactly what made
        # k>1 bucket growth order-dependent
        p = tmp_path / f"ps{i}.parquet"
        schema = types.message(
            "t",
            types.required(types.INT64).named("x"),
            types.optional(types.INT32).named("y"),
        )
        r = np.random.default_rng(i)
        with ParquetFileWriter(
            p, schema,
            WriterOptions(row_group_rows=300, data_page_values=100),
        ) as w:
            for m in (300, 300, 140):
                w.write_columns({
                    "x": r.integers(0, 1 << 40, m).astype(np.int64),
                    "y": [None if j % 3 == 0 else j for j in range(m)],
                })
        paths.append(p)

    def shapes():
        out = []
        readers = [TpuRowGroupReader(p, float64_policy="bits")
                   for p in paths]
        try:
            tasks = [
                (r, gi) for r in readers for gi in range(r.num_row_groups)
            ]
            for cols in iter_dataset_row_groups(tasks):
                out.append({
                    k: (
                        tuple(v.values.shape),
                        None if v.mask is None else tuple(v.mask.shape),
                    )
                    for k, v in cols.items()
                })
        finally:
            for r in readers:
                r.close()
        return out

    monkeypatch.setenv("PFTPU_STAGE_WORKERS", "2")
    first = shapes()
    second = shapes()
    assert first == second
    # and the seed actually fired: footer-derivable buckets pre-set
    with TpuRowGroupReader(paths[0], float64_policy="bits") as tr:
        seeded = {k[0] for k in tr._hwm_state}
        assert {"nexp", "arena"} <= seeded


def test_no_preseed_at_single_stage_worker(tmp_path, monkeypatch):
    monkeypatch.delenv("PFTPU_STAGE_WORKERS", raising=False)
    path = _write_plain_ints(tmp_path, "np.parquet")
    with TpuRowGroupReader(path, float64_policy="bits") as tr:
        assert tr._hwm_state == {}


# ---------------------------------------------------------------------------
# eager preload (docs/perf.md)
# ---------------------------------------------------------------------------

def test_preload_populates_memory_then_hits(tmp_path):
    """preload() deserializes disk entries ahead of use; the first
    dispatch that finds one still counts a cache HIT with zero compile
    wall (accounting is preload-agnostic)."""
    path = _write(tmp_path)
    cache_dir = tmp_path / "cache"
    want, c1 = _decode(path, cache_dir)
    assert c1.get("engine.exec_cache_misses", 0) >= 1

    fresh = exec_cache.ExecutableCache(str(cache_dir))
    with trace.scope() as t:
        n = fresh.preload()
    assert n >= 1
    assert len(fresh._mem) >= 1
    acts = [d for d in t.decisions()
            if d.get("decision") == "engine.exec_cache"
            and d.get("action") == "preload"]
    assert acts and acts[0]["entries"] == n
    # second preload is a no-op (idempotent per cache object)
    assert fresh.preload() == 0

    exec_cache.activate(fresh)
    try:
        with trace.scope() as t2:
            with TpuRowGroupReader(path, float64_policy="bits") as tr:
                cols = tr.read_row_group(0)
                jax.block_until_ready([c.values for c in cols.values()])
                got = {
                    k: np.asarray(v.values) for k, v in cols.items()
                }
        c2 = t2.counters()
        assert c2.get("engine.exec_cache_hits", 0) >= 1
        assert c2.get("engine.exec_cache_misses", 0) == 0
        assert c2.get("engine.compile_ms", 0) == 0
        for k in want:
            assert np.array_equal(got[k], want[k][0])
    finally:
        exec_cache.activate(None)


def test_preload_async_env_trigger(tmp_path, monkeypatch):
    """Reader construction kicks the env-configured cache's preload on
    a background thread; a test-forced cache is never auto-preloaded."""
    path = _write(tmp_path)
    cache_dir = tmp_path / "cache"
    _decode(path, cache_dir)  # seed one entry on disk

    monkeypatch.setenv("PFTPU_EXEC_CACHE", str(cache_dir))
    exec_cache.activate(None)
    t = exec_cache.preload_async()
    assert t is not None
    t.join(30)
    cache = exec_cache.active()
    assert len(cache._mem) >= 1
    # idempotent: the engine's constructor hook finds it already done
    assert exec_cache.preload_async() is None
    # gate: PFTPU_EXEC_CACHE_PRELOAD=0 disables
    monkeypatch.setenv("PFTPU_EXEC_CACHE_PRELOAD", "0")
    exec_cache._caches.pop(str(cache_dir), None)
    assert exec_cache.preload_async() is None
    # forced caches (the test hook) never auto-preload
    monkeypatch.delenv("PFTPU_EXEC_CACHE_PRELOAD", raising=False)
    exec_cache.activate(exec_cache.ExecutableCache(str(cache_dir)))
    assert exec_cache.preload_async() is None


# ---------------------------------------------------------------------------
# loader batch shapes ride the exec cache (docs/perf.md, PR 8 follow-on)
# ---------------------------------------------------------------------------

def _batch_parts(n=64):
    import jax.numpy as jnp

    from parquet_floor_tpu.data.batcher import ColumnSpec

    specs = [
        ColumnSpec("a", None, is_string=False, has_mask=False),
        ColumnSpec("b", None, is_string=False, has_mask=True),
    ]
    parts = [
        (jnp.arange(n, dtype=jnp.int64), None, None),
        (jnp.arange(n, dtype=jnp.int32), jnp.zeros(n, bool), None),
    ]
    return specs, parts


def test_batcher_split_and_assemble_ride_exec_cache(tmp_path):
    """fused_assemble/aligned_split dispatch through exec_cache: a cold
    'process' compiles+stores, a fresh cache object over the same dir
    (a new process's shape) hits with zero compile wall."""
    from parquet_floor_tpu.data.batcher import aligned_split, fused_assemble

    cache_dir = tmp_path / "cache"
    specs, parts = _batch_parts()

    def run():
        with trace.scope() as t:
            out = aligned_split(specs, parts, {}, 2)
            windows = [[(p, 0, 32)] for p in parts]
            out2 = fused_assemble(specs, windows, {}, pad=0, split=1)
        return out, out2, t.counters()

    exec_cache.activate(exec_cache.ExecutableCache(str(cache_dir)))
    try:
        cold_split, cold_asm, c_cold = run()
        assert c_cold.get("engine.exec_cache_misses", 0) >= 2
        exec_cache.activate(exec_cache.ExecutableCache(str(cache_dir)))
        warm_split, warm_asm, c_warm = run()
        assert c_warm.get("engine.exec_cache_hits", 0) >= 2
        assert c_warm.get("engine.exec_cache_misses", 0) == 0
        assert c_warm.get("engine.compile_ms", 0) == 0
    finally:
        exec_cache.activate(None)
    for cb, wb in zip(cold_split, warm_split):
        for (cv, cm, _), (wv, wm, _) in zip(cb, wb):
            assert np.array_equal(np.asarray(cv), np.asarray(wv))
            assert (cm is None) == (wm is None)
    for (cv, _cm, _), (wv, _wm, _) in zip(cold_asm[0], warm_asm[0]):
        assert np.array_equal(np.asarray(cv), np.asarray(wv))
