"""Salvage mode: quarantine damaged pages/chunks, decode the rest, account
for every lost row (ISSUE 1 tentpole part 3).  Strict mode stays the
default and fails loudly on the same files."""

import pathlib

import numpy as np
import pytest

from parquet_floor_tpu import (
    ChecksumMismatchError,
    ParquetError,
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.format.parquet_thrift import PageHeader, PageType
from parquet_floor_tpu.format.thrift import CompactReader

ROWS_PER_GROUP = 2500
PAGE_VALUES = 500
N_GROUPS = 2


@pytest.fixture(scope="module")
def salvage_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("salvage") / "v.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(3)
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=PAGE_VALUES)
    ) as w:
        for _ in range(N_GROUPS):
            w.write_columns({
                "a": rng.integers(0, 10_000, ROWS_PER_GROUP).astype(np.int64),
                "s": [None if i % 11 == 0 else f"val{i % 321}"
                      for i in range(ROWS_PER_GROUP)],
                "d": rng.standard_normal(ROWS_PER_GROUP),
            })
    return str(path)


def _page_spans(reader, rg_idx, col):
    """(payload_offset, payload_size, is_dict, ordinal) per page of the
    chunk, by walking the real header chain."""
    rg = reader.row_groups[rg_idx]
    chunk = [c for c in rg.columns if c.meta_data.path_in_schema[0] == col][0]
    m = chunk.meta_data
    start = m.data_page_offset
    if m.dictionary_page_offset:
        start = min(start, m.dictionary_page_offset)
    raw = bytes(reader.source.read_at(start, m.total_compressed_size))
    cr = CompactReader(raw)
    spans, i = [], 0
    while cr.pos < len(raw):
        h = PageHeader.read(cr)
        spans.append((
            start + cr.pos, h.compressed_page_size,
            h.type == PageType.DICTIONARY_PAGE, i,
        ))
        cr.pos += h.compressed_page_size
        i += 1
    return spans


def _flip_in_page(path, tmp_path, rg_idx, col, data_page_index, stem):
    """Flip one payload bit of the chunk's N-th DATA page; returns the
    corrupted file's path and the page's ordinal within the chunk."""
    with ParquetFileReader(path) as r:
        spans = _page_spans(r, rg_idx, col)
    off, size, _, ordinal = [s for s in spans if not s[2]][data_page_index]
    data = bytearray(pathlib.Path(path).read_bytes())
    data[off + size // 2] ^= 0x10
    out = tmp_path / f"{stem}.parquet"
    out.write_bytes(bytes(data))
    return str(out), ordinal


def _decode_all(path, **options):
    opts = ReaderOptions(**options)
    with ParquetFileReader(path, options=opts) as r:
        groups = list(r.iter_row_groups())
        for g in groups:
            for c in g.columns:
                _ = c.values
                _ = c.def_levels
        return groups, r.salvage_report


def test_salvage_demo_required_column(salvage_file, tmp_path):
    """The acceptance demo: one bit-flipped data page in column ``d``
    (required — no null substitution possible) decodes all other columns
    and all row groups in salvage mode, raises ChecksumMismatchError in
    strict mode, and the report accounts for exactly the quarantined
    rows."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_d")

    # strict mode (the default): fail loudly
    with pytest.raises(ChecksumMismatchError):
        _decode_all(bad, verify_crc=True)

    # salvage: everything except (d, rg0) decodes
    groups, rep = _decode_all(bad, verify_crc=True, salvage=True)
    assert [g.num_rows for g in groups] == [ROWS_PER_GROUP] * N_GROUPS
    assert sorted(c.descriptor.path[0] for c in groups[0].columns) == ["a", "s"]
    assert sorted(c.descriptor.path[0] for c in groups[1].columns) == ["a", "d", "s"]

    # surviving data is byte-identical to the pristine decode
    pristine, _ = _decode_all(salvage_file)
    assert np.array_equal(groups[0].column("a").values,
                          pristine[0].column("a").values)
    assert np.array_equal(groups[1].column("d").values,
                          pristine[1].column("d").values)
    assert np.array_equal(groups[0].column("s").def_levels,
                          pristine[0].column("s").def_levels)

    # the report accounts for exactly the quarantined rows
    assert rep.chunks_quarantined == 1
    assert rep.rows_quarantined == ROWS_PER_GROUP
    assert rep.pages_skipped == 0
    assert [s.column for s in rep.skips] == ["d"]
    assert rep.skips[0].row_group == 0 and rep.skips[0].page is None
    assert "CRC mismatch" in rep.first_errors["d"]


def test_salvage_nulls_optional_column_page(salvage_file, tmp_path):
    """A damaged page of an OPTIONAL flat column quarantines only that
    page: its rows survive as nulls, the rest of the column (and every
    other column) decodes exactly."""
    bad, ordinal = _flip_in_page(salvage_file, tmp_path, 1, "s", 2, "bad_s")

    groups, rep = _decode_all(bad, verify_crc=True, salvage=True)
    # every column of every group present; all rows preserved
    for g in groups:
        assert sorted(c.descriptor.path[0] for c in g.columns) == ["a", "d", "s"]
        assert g.num_rows == ROWS_PER_GROUP

    assert rep.pages_skipped == 1 and rep.chunks_quarantined == 0
    assert rep.rows_quarantined == PAGE_VALUES
    skip = rep.skips[0]
    assert skip.column == "s" and skip.row_group == 1
    assert skip.page == ordinal and skip.rows == PAGE_VALUES

    # nulled page = def levels forced 0 exactly on its row span; all other
    # spans identical to pristine
    pristine, _ = _decode_all(salvage_file)
    dl_bad = groups[1].column("s").def_levels
    dl_good = pristine[1].column("s").def_levels
    data_page_index = 2
    lo, hi = data_page_index * PAGE_VALUES, (data_page_index + 1) * PAGE_VALUES
    assert np.all(dl_bad[lo:hi] == 0)
    assert np.array_equal(dl_bad[:lo], dl_good[:lo])
    assert np.array_equal(dl_bad[hi:], dl_good[hi:])
    # values outside the quarantined page are the exact pristine bytes
    sb, sg = groups[1].column("s"), pristine[1].column("s")
    vals_bad = [sb.cell(i) for i in range(lo)]
    vals_good = [sg.cell(i) for i in range(lo)]
    assert vals_bad == vals_good


def test_salvage_records_trace_decisions(salvage_file, tmp_path):
    """Each quarantine lands as a structured trace.decision event."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 0, "bad_trace")
    trace.enable()
    try:
        trace.reset()
        _decode_all(bad, verify_crc=True, salvage=True)
        kinds = [d["decision"] for d in trace.decisions()]
        assert "salvage.quarantine_chunk" in kinds
        assert "salvage.report" in kinds
        chunk_evt = [d for d in trace.decisions()
                     if d["decision"] == "salvage.quarantine_chunk"][0]
        assert chunk_evt["column"] == "d" and chunk_evt["row_group"] == 0
    finally:
        trace.disable()
        trace.reset()


def test_salvage_without_crc_catches_framing_damage(salvage_file, tmp_path):
    """Even without CRC verification, damage that breaks page framing
    (here: the second page's Thrift header) fails loudly in strict mode
    and quarantines the chunk in salvage mode."""
    with ParquetFileReader(salvage_file) as r:
        spans = _page_spans(r, 0, "a")
    # header of the second page starts where the first page's payload ends
    off0, size0, _, _ = spans[0]
    second_header = off0 + size0
    data = bytearray(pathlib.Path(salvage_file).read_bytes())
    data[second_header] = 0xFF  # compact type 0x0F: unskippable garbage
    bad = tmp_path / "bad_framing.parquet"
    bad.write_bytes(bytes(data))

    with pytest.raises(ParquetError) as ei:
        _decode_all(str(bad))
    # framing errors name the ABSOLUTE byte offset of the bad header
    assert ei.value.offset == second_header

    groups, rep = _decode_all(str(bad), salvage=True)
    assert rep.rows_quarantined >= PAGE_VALUES
    assert any(s.column == "a" for s in rep.skips)
    # untouched groups/columns still whole
    assert groups[-1].num_rows == ROWS_PER_GROUP


def test_salvage_batch_face_marks_quarantined_column(salvage_file, tmp_path):
    """stream_batches over a salvaged file: the quarantined chunk stays
    in POSITION as a quarantined placeholder (positional hydrators never
    silently read a shifted column), not a KeyError."""
    from parquet_floor_tpu import ParquetReader

    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_batch")
    opts = ReaderOptions(verify_crc=True, salvage=True)
    groups = list(ParquetReader.stream_batches(bad, options=opts))
    names = [[c.descriptor.path[0] for c in cols] for cols in groups]
    assert names == [["a", "s", "d"], ["a", "s", "d"]]  # order intact
    flags = [[c.quarantined for c in cols] for cols in groups]
    assert flags == [[False, False, True], [False, False, False]]
    assert groups[0][2].values is None
    # touching the placeholder's data fails LOUDLY on every accessor
    for accessor in ("to_numpy", "to_arrow", "bytes_list"):
        with pytest.raises(ValueError, match="quarantined"):
            getattr(groups[0][2], accessor)()
    assert groups[1][2].values is not None
    assert groups[1][2].to_numpy().shape[0] == ROWS_PER_GROUP


def test_salvage_row_api_serves_none_for_quarantined_column(salvage_file, tmp_path):
    """The row-streaming API keeps flowing over a chunk quarantine:
    cells of the quarantined column come back None for that group (and
    real values elsewhere), instead of an opaque RuntimeError."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_rows")
    opts = ReaderOptions(verify_crc=True, salvage=True)
    rows = list(ParquetReader.stream_content(
        bad, HydratorSupplier.constantly(dict_hydrator()), options=opts))
    assert len(rows) == N_GROUPS * ROWS_PER_GROUP
    assert all(r["d"] is None for r in rows[:ROWS_PER_GROUP])
    assert all(r["d"] is not None for r in rows[ROWS_PER_GROUP:])
    assert all(r["a"] is not None for r in rows)
    # strict mode on the same file still fails loudly through the row API
    with pytest.raises(RuntimeError, match="Failed to read parquet"):
        list(ParquetReader.stream_content(
            bad, HydratorSupplier.constantly(dict_hydrator()),
            options=ReaderOptions(verify_crc=True)))


def test_salvage_null_cursor_needs_a_quarantine_record(salvage_file):
    """A column missing from a row group WITHOUT a recorded quarantine
    (corrupt-but-parseable footer) must raise, not silently serve nulls
    — null substitution is only for losses the report accounts for."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    r = ParquetReader.spliterator(
        salvage_file, HydratorSupplier.constantly(dict_hydrator()),
        options=ReaderOptions(salvage=True),
    )
    try:
        rg = r._reader.row_groups[0]
        rg.columns = [
            c for c in rg.columns if c.meta_data.path_in_schema[0] != "d"
        ]
        with pytest.raises(RuntimeError, match="Failed to read parquet"):
            next(r)
        assert r._reader.salvage_report.skips == []
    finally:
        r.close()


def test_robustness_options_pin_host_engine(salvage_file):
    """verify_crc/salvage are host-only: engine='tpu' refuses loudly,
    engine='auto' routes to host (recorded as a trace decision)."""
    from parquet_floor_tpu import ParquetReader, UnsupportedFeatureError, trace
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    sup = HydratorSupplier.constantly(dict_hydrator())
    with pytest.raises(UnsupportedFeatureError, match="host-engine"):
        ParquetReader.spliterator(
            salvage_file, sup, engine="tpu",
            options=ReaderOptions(verify_crc=True),
        )
    trace.enable()
    try:
        trace.reset()
        r = ParquetReader.spliterator(
            salvage_file, sup, engine="auto",
            options=ReaderOptions(salvage=True),
        )
        try:
            assert r.engine == "host"
            why = [d for d in trace.decisions()
                   if d["decision"] == "engine.auto"]
            assert why and "pin the host" in why[0]["why"]
        finally:
            r.close()
    finally:
        trace.disable()
        trace.reset()


def test_tpu_engine_refuses_robustness_options_directly(salvage_file):
    """The guard holds at the engine boundary too: constructing
    TpuRowGroupReader on an options-carrying reader raises instead of
    silently skipping CRC/salvage."""
    from parquet_floor_tpu import UnsupportedFeatureError
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    r = ParquetFileReader(salvage_file, options=ReaderOptions(verify_crc=True))
    try:
        with pytest.raises(UnsupportedFeatureError, match="host-engine"):
            TpuRowGroupReader(r)
    finally:
        r.close()


def test_projection_never_hides_metaless_chunk(salvage_file):
    """A chunk whose meta_data is gone cannot be silently skipped by a
    column_filter — it must fail loudly as CorruptFooterError."""
    from parquet_floor_tpu import CorruptFooterError

    with ParquetFileReader(salvage_file) as r:
        r.row_groups[0].columns[0].meta_data = None
        with pytest.raises(CorruptFooterError):
            r.read_row_group(0, {"a"})


def test_salvage_report_is_idempotent_per_chunk(salvage_file, tmp_path):
    """Re-decoding a row group (restore(), repeated read_row_group) must
    not double-count its quarantines or recoveries."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_twice")
    opts = ReaderOptions(verify_crc=True, salvage=True)
    with ParquetFileReader(bad, options=opts) as r:
        r.read_row_group(0)
        first = r.salvage_report.summary()
        r.read_row_group(0)  # deterministic re-decode of the same group
        assert r.salvage_report.summary() == first
        assert r.salvage_report.chunks_quarantined == 1
        assert r.salvage_report.rows_quarantined == ROWS_PER_GROUP
        assert len(r.salvage_report.skips) == 1
        # unknown group index never dedupes (None keys would collide
        # across groups and hide real losses)
        assert r.salvage_report._first_count("a", None, "q")
        assert r.salvage_report._first_count("a", None, "q")


def test_salvage_report_reachable_from_row_stream(salvage_file, tmp_path):
    """The public row stream exposes the SalvageReport, and the report
    survives stream exhaustion (losses stay accountable)."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_report")
    it = ParquetReader.stream_content(
        bad, HydratorSupplier.constantly(dict_hydrator()),
        options=ReaderOptions(verify_crc=True, salvage=True))
    n = sum(1 for _ in it)  # exhausts and closes the stream
    assert n == N_GROUPS * ROWS_PER_GROUP
    rep = it.salvage_report
    assert rep is not None and rep.chunks_quarantined == 1
    assert rep.skips[0].column == "d"


def test_quarantine_after_earlier_success_still_recorded(salvage_file):
    """A chunk that decoded fine once but fails on a later re-read (file
    changed underneath, flaky storage) must STILL get a skip record —
    every omission has a report entry."""
    from parquet_floor_tpu.testing import FaultInjectingSource

    src = FaultInjectingSource(salvage_file)
    with ParquetFileReader(src, options=ReaderOptions(salvage=True)) as r:
        g0 = r.read_row_group(0)
        assert len(g0.columns) == 3  # clean decode, all counted as "ok"
        src._truncate_at = 64  # storage "changes underneath"
        g0b = r.read_row_group(0)
        assert len(g0b.columns) == 0  # every chunk now quarantined
        assert len(r.salvage_report.skips) == 3
        assert r.salvage_report.chunks_quarantined == 3


def test_strict_mode_is_default_and_identical(salvage_file):
    """salvage defaults off; a clean file decodes identically with and
    without the flag, and no report is accumulated in strict mode."""
    strict, rep_strict = _decode_all(salvage_file)
    salv, rep_salv = _decode_all(salvage_file, salvage=True)
    assert rep_strict is None
    assert rep_salv is not None and rep_salv.skips == []
    assert rep_salv.rows_quarantined == 0
    for gs, gv in zip(strict, salv):
        for cs, cv in zip(gs.columns, gv.columns):
            assert cs.descriptor.path == cv.descriptor.path
            if isinstance(cs.values, np.ndarray):
                assert np.array_equal(cs.values, cv.values)
