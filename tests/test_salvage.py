"""Salvage mode: quarantine damaged pages/chunks, decode the rest, account
for every lost row (ISSUE 1 tentpole part 3).  Strict mode stays the
default and fails loudly on the same files."""

import pathlib

import numpy as np
import pytest

from parquet_floor_tpu import (
    ChecksumMismatchError,
    ParquetError,
    ParquetFileReader,
    ParquetFileWriter,
    ReaderOptions,
    WriterOptions,
    trace,
    types,
)
from parquet_floor_tpu.format.parquet_thrift import PageHeader, PageType
from parquet_floor_tpu.format.thrift import CompactReader

ROWS_PER_GROUP = 2500
PAGE_VALUES = 500
N_GROUPS = 2


@pytest.fixture(scope="module")
def salvage_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("salvage") / "v.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(3)
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=PAGE_VALUES)
    ) as w:
        for _ in range(N_GROUPS):
            w.write_columns({
                "a": rng.integers(0, 10_000, ROWS_PER_GROUP).astype(np.int64),
                "s": [None if i % 11 == 0 else f"val{i % 321}"
                      for i in range(ROWS_PER_GROUP)],
                "d": rng.standard_normal(ROWS_PER_GROUP),
            })
    return str(path)


def _page_spans(reader, rg_idx, col):
    """(payload_offset, payload_size, is_dict, ordinal) per page of the
    chunk, by walking the real header chain."""
    rg = reader.row_groups[rg_idx]
    chunk = [c for c in rg.columns if c.meta_data.path_in_schema[0] == col][0]
    m = chunk.meta_data
    start = m.data_page_offset
    if m.dictionary_page_offset:
        start = min(start, m.dictionary_page_offset)
    raw = bytes(reader.source.read_at(start, m.total_compressed_size))
    cr = CompactReader(raw)
    spans, i = [], 0
    while cr.pos < len(raw):
        h = PageHeader.read(cr)
        spans.append((
            start + cr.pos, h.compressed_page_size,
            h.type == PageType.DICTIONARY_PAGE, i,
        ))
        cr.pos += h.compressed_page_size
        i += 1
    return spans


def _flip_in_page(path, tmp_path, rg_idx, col, data_page_index, stem):
    """Flip one payload bit of the chunk's N-th DATA page; returns the
    corrupted file's path and the page's ordinal within the chunk."""
    with ParquetFileReader(path) as r:
        spans = _page_spans(r, rg_idx, col)
    off, size, _, ordinal = [s for s in spans if not s[2]][data_page_index]
    data = bytearray(pathlib.Path(path).read_bytes())
    data[off + size // 2] ^= 0x10
    out = tmp_path / f"{stem}.parquet"
    out.write_bytes(bytes(data))
    return str(out), ordinal


def _break_page_header(path, tmp_path, rg_idx, col, stem,
                       page_index: int = 1):
    """Overwrite the start of the chunk's N-th page HEADER with compact
    garbage: framing damage no tier can localize — the whole chunk
    quarantines (the row-mask tier needs a readable header to know the
    page's row span)."""
    with ParquetFileReader(path) as r:
        spans = _page_spans(r, rg_idx, col)
    off, size, _, _ = spans[page_index - 1]
    header_start = off + size  # next page's header follows this payload
    data = bytearray(pathlib.Path(path).read_bytes())
    data[header_start] = 0xFF  # compact type 0x0F: unskippable garbage
    out = tmp_path / f"{stem}.parquet"
    out.write_bytes(bytes(data))
    return str(out)


def _decode_all(path, **options):
    opts = ReaderOptions(**options)
    with ParquetFileReader(path, options=opts) as r:
        groups = list(r.iter_row_groups())
        for g in groups:
            for c in g.columns:
                _ = c.values
                _ = c.def_levels
        return groups, r.salvage_report


def test_salvage_demo_required_column_row_mask(salvage_file, tmp_path):
    """The row-mask tier demo: one bit-flipped data page in column ``d``
    (required — no null substitution possible) drops exactly that page's
    row span from EVERY column of the group (alignment preserved), keeps
    the other 2000 rows AND the whole column, raises
    ChecksumMismatchError in strict mode, and the report accounts for
    exactly the dropped rows."""
    bad, ordinal = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_d")

    # strict mode (the default): fail loudly
    with pytest.raises(ChecksumMismatchError):
        _decode_all(bad, verify_crc=True)

    # salvage: group 0 survives minus the damaged page's 500-row span
    groups, rep = _decode_all(bad, verify_crc=True, salvage=True)
    assert [g.num_rows for g in groups] == \
        [ROWS_PER_GROUP - PAGE_VALUES, ROWS_PER_GROUP]
    for g in groups:
        assert sorted(c.descriptor.path[0] for c in g.columns) == \
            ["a", "d", "s"]

    # surviving rows are byte-identical to the pristine decode with the
    # same span removed — in EVERY column, so alignment is exact
    pristine, _ = _decode_all(salvage_file)
    lo, hi = PAGE_VALUES, 2 * PAGE_VALUES  # data page 1 of the chunk
    keep = np.r_[0:lo, hi:ROWS_PER_GROUP]
    assert np.array_equal(groups[0].column("a").values,
                          pristine[0].column("a").values[keep])
    assert np.array_equal(groups[0].column("d").values[:lo],
                          pristine[0].column("d").values[:lo])
    assert np.array_equal(groups[0].column("d").values[lo:],
                          pristine[0].column("d").values[hi:])
    assert np.array_equal(groups[0].column("s").def_levels,
                          pristine[0].column("s").def_levels[keep])
    assert np.array_equal(groups[1].column("d").values,
                          pristine[1].column("d").values)

    # the report accounts for exactly the dropped rows
    assert rep.chunks_quarantined == 0
    assert rep.pages_skipped == 1
    assert rep.rows_quarantined == PAGE_VALUES
    assert rep.rows_dropped == PAGE_VALUES
    s = rep.skips[0]
    assert s.column == "d" and s.row_group == 0 and s.page == ordinal
    assert s.kind == "row_mask" and tuple(s.row_span) == (lo, hi)
    assert "CRC mismatch" in rep.first_errors["d"]


def test_salvage_required_framing_damage_quarantines_chunk(salvage_file,
                                                           tmp_path):
    """When the damage takes the page HEADER (no row span to localize),
    the chunk tier still owns the loss: the whole ``d`` chunk of group 0
    is quarantined, every other column keeps all its rows."""
    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "bad_d_hdr")

    groups, rep = _decode_all(bad, salvage=True)
    assert [g.num_rows for g in groups] == [ROWS_PER_GROUP] * N_GROUPS
    assert sorted(c.descriptor.path[0] for c in groups[0].columns) == ["a", "s"]
    assert sorted(c.descriptor.path[0] for c in groups[1].columns) == \
        ["a", "d", "s"]
    assert rep.chunks_quarantined == 1 and rep.rows_dropped == 0
    assert rep.rows_quarantined == ROWS_PER_GROUP
    s = rep.skips[0]
    assert s.column == "d" and s.kind == "chunk" and s.page is None


def test_salvage_nulls_optional_column_page(salvage_file, tmp_path):
    """A damaged page of an OPTIONAL flat column quarantines only that
    page: its rows survive as nulls, the rest of the column (and every
    other column) decodes exactly."""
    bad, ordinal = _flip_in_page(salvage_file, tmp_path, 1, "s", 2, "bad_s")

    groups, rep = _decode_all(bad, verify_crc=True, salvage=True)
    # every column of every group present; all rows preserved
    for g in groups:
        assert sorted(c.descriptor.path[0] for c in g.columns) == ["a", "d", "s"]
        assert g.num_rows == ROWS_PER_GROUP

    assert rep.pages_skipped == 1 and rep.chunks_quarantined == 0
    assert rep.rows_quarantined == PAGE_VALUES
    skip = rep.skips[0]
    assert skip.column == "s" and skip.row_group == 1
    assert skip.page == ordinal and skip.rows == PAGE_VALUES

    # nulled page = def levels forced 0 exactly on its row span; all other
    # spans identical to pristine
    pristine, _ = _decode_all(salvage_file)
    dl_bad = groups[1].column("s").def_levels
    dl_good = pristine[1].column("s").def_levels
    data_page_index = 2
    lo, hi = data_page_index * PAGE_VALUES, (data_page_index + 1) * PAGE_VALUES
    assert np.all(dl_bad[lo:hi] == 0)
    assert np.array_equal(dl_bad[:lo], dl_good[:lo])
    assert np.array_equal(dl_bad[hi:], dl_good[hi:])
    # values outside the quarantined page are the exact pristine bytes
    sb, sg = groups[1].column("s"), pristine[1].column("s")
    vals_bad = [sb.cell(i) for i in range(lo)]
    vals_good = [sg.cell(i) for i in range(lo)]
    assert vals_bad == vals_good


def test_salvage_records_trace_decisions(salvage_file, tmp_path):
    """Each quarantine lands as a structured trace.decision event —
    row-mask drops under ``salvage.row_mask``, chunk losses under
    ``salvage.quarantine_chunk``."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 0, "bad_trace")
    trace.enable()
    try:
        trace.reset()
        _decode_all(bad, verify_crc=True, salvage=True)
        kinds = [d["decision"] for d in trace.decisions()]
        assert "salvage.row_mask" in kinds
        assert "salvage.report" in kinds
        evt = [d for d in trace.decisions()
               if d["decision"] == "salvage.row_mask"][0]
        assert evt["column"] == "d" and evt["row_group"] == 0

        trace.reset()
        hdr_bad = _break_page_header(
            salvage_file, tmp_path, 0, "d", "bad_trace_hdr"
        )
        _decode_all(hdr_bad, salvage=True)
        kinds = [d["decision"] for d in trace.decisions()]
        assert "salvage.quarantine_chunk" in kinds
        chunk_evt = [d for d in trace.decisions()
                     if d["decision"] == "salvage.quarantine_chunk"][0]
        assert chunk_evt["column"] == "d" and chunk_evt["row_group"] == 0
    finally:
        trace.disable()
        trace.reset()


def test_salvage_without_crc_catches_framing_damage(salvage_file, tmp_path):
    """Even without CRC verification, damage that breaks page framing
    (here: the second page's Thrift header) fails loudly in strict mode
    and quarantines the chunk in salvage mode."""
    with ParquetFileReader(salvage_file) as r:
        spans = _page_spans(r, 0, "a")
    # header of the second page starts where the first page's payload ends
    off0, size0, _, _ = spans[0]
    second_header = off0 + size0
    data = bytearray(pathlib.Path(salvage_file).read_bytes())
    data[second_header] = 0xFF  # compact type 0x0F: unskippable garbage
    bad = tmp_path / "bad_framing.parquet"
    bad.write_bytes(bytes(data))

    with pytest.raises(ParquetError) as ei:
        _decode_all(str(bad))
    # framing errors name the ABSOLUTE byte offset of the bad header
    assert ei.value.offset == second_header

    groups, rep = _decode_all(str(bad), salvage=True)
    assert rep.rows_quarantined >= PAGE_VALUES
    assert any(s.column == "a" for s in rep.skips)
    # untouched groups/columns still whole
    assert groups[-1].num_rows == ROWS_PER_GROUP


def test_salvage_batch_face_marks_quarantined_column(salvage_file, tmp_path):
    """stream_batches over a salvaged file: the quarantined chunk stays
    in POSITION as a quarantined placeholder (positional hydrators never
    silently read a shifted column), not a KeyError."""
    from parquet_floor_tpu import ParquetReader

    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "bad_batch")
    opts = ReaderOptions(salvage=True)
    groups = list(ParquetReader.stream_batches(bad, options=opts))
    names = [[c.descriptor.path[0] for c in cols] for cols in groups]
    assert names == [["a", "s", "d"], ["a", "s", "d"]]  # order intact
    flags = [[c.quarantined for c in cols] for cols in groups]
    assert flags == [[False, False, True], [False, False, False]]
    assert groups[0][2].values is None
    # touching the placeholder's data fails LOUDLY on every accessor
    for accessor in ("to_numpy", "to_arrow", "bytes_list"):
        with pytest.raises(ValueError, match="quarantined"):
            getattr(groups[0][2], accessor)()
    assert groups[1][2].values is not None
    assert groups[1][2].to_numpy().shape[0] == ROWS_PER_GROUP


def test_salvage_row_api_serves_none_for_quarantined_column(salvage_file, tmp_path):
    """The row-streaming API keeps flowing over a chunk quarantine:
    cells of the quarantined column come back None for that group (and
    real values elsewhere), instead of an opaque RuntimeError."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "bad_rows")
    opts = ReaderOptions(salvage=True)
    rows = list(ParquetReader.stream_content(
        bad, HydratorSupplier.constantly(dict_hydrator()), options=opts))
    assert len(rows) == N_GROUPS * ROWS_PER_GROUP
    assert all(r["d"] is None for r in rows[:ROWS_PER_GROUP])
    assert all(r["d"] is not None for r in rows[ROWS_PER_GROUP:])
    assert all(r["a"] is not None for r in rows)
    # strict mode on the same file still fails loudly through the row API
    with pytest.raises(RuntimeError, match="Failed to read parquet"):
        list(ParquetReader.stream_content(
            bad, HydratorSupplier.constantly(dict_hydrator()),
            options=ReaderOptions(verify_crc=True)))


def test_salvage_row_mask_row_api_drops_span(salvage_file, tmp_path):
    """The row API over a row-masked group: the damaged REQUIRED page's
    span vanishes from the stream (every column advances together), the
    rest of the stream is the pristine rows."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_rows_rm")
    rows = list(ParquetReader.stream_content(
        bad, HydratorSupplier.constantly(dict_hydrator()),
        options=ReaderOptions(verify_crc=True, salvage=True)))
    assert len(rows) == N_GROUPS * ROWS_PER_GROUP - PAGE_VALUES
    good = list(ParquetReader.stream_content(
        salvage_file, HydratorSupplier.constantly(dict_hydrator())))
    expected = (
        good[:PAGE_VALUES] + good[2 * PAGE_VALUES:]
    )
    assert rows == expected


def test_salvage_null_cursor_needs_a_quarantine_record(salvage_file):
    """A column missing from a row group WITHOUT a recorded quarantine
    (corrupt-but-parseable footer) must raise, not silently serve nulls
    — null substitution is only for losses the report accounts for."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    r = ParquetReader.spliterator(
        salvage_file, HydratorSupplier.constantly(dict_hydrator()),
        options=ReaderOptions(salvage=True),
    )
    try:
        rg = r._reader.row_groups[0]
        rg.columns = [
            c for c in rg.columns if c.meta_data.path_in_schema[0] != "d"
        ]
        with pytest.raises(RuntimeError, match="Failed to read parquet"):
            next(r)
        assert r._reader.salvage_report.skips == []
    finally:
        r.close()


def test_robustness_options_pin_host_engine(salvage_file):
    """verify_crc/salvage are host-only: engine='tpu' refuses loudly,
    engine='auto' routes to host (recorded as a trace decision)."""
    from parquet_floor_tpu import ParquetReader, UnsupportedFeatureError, trace
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    sup = HydratorSupplier.constantly(dict_hydrator())
    with pytest.raises(UnsupportedFeatureError, match="host-engine"):
        ParquetReader.spliterator(
            salvage_file, sup, engine="tpu",
            options=ReaderOptions(verify_crc=True),
        )
    trace.enable()
    try:
        trace.reset()
        r = ParquetReader.spliterator(
            salvage_file, sup, engine="auto",
            options=ReaderOptions(salvage=True),
        )
        try:
            assert r.engine == "host"
            why = [d for d in trace.decisions()
                   if d["decision"] == "engine.auto"]
            assert why and "pin the host" in why[0]["why"]
        finally:
            r.close()
    finally:
        trace.disable()
        trace.reset()


def test_tpu_engine_refuses_robustness_options_directly(salvage_file):
    """The guard holds at the engine boundary too: constructing
    TpuRowGroupReader on an options-carrying reader raises instead of
    silently skipping CRC/salvage."""
    from parquet_floor_tpu import UnsupportedFeatureError
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    r = ParquetFileReader(salvage_file, options=ReaderOptions(verify_crc=True))
    try:
        with pytest.raises(UnsupportedFeatureError, match="host-engine"):
            TpuRowGroupReader(r)
    finally:
        r.close()


def test_projection_never_hides_metaless_chunk(salvage_file):
    """A chunk whose meta_data is gone cannot be silently skipped by a
    column_filter — it must fail loudly as CorruptFooterError."""
    from parquet_floor_tpu import CorruptFooterError

    with ParquetFileReader(salvage_file) as r:
        r.row_groups[0].columns[0].meta_data = None
        with pytest.raises(CorruptFooterError):
            r.read_row_group(0, {"a"})


def test_salvage_report_is_idempotent_per_chunk(salvage_file, tmp_path):
    """Re-decoding a row group (restore(), repeated read_row_group) must
    not double-count its quarantines or recoveries."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "bad_twice")
    opts = ReaderOptions(verify_crc=True, salvage=True)
    with ParquetFileReader(bad, options=opts) as r:
        g0 = r.read_row_group(0)
        first = r.salvage_report.summary()
        g0b = r.read_row_group(0)  # deterministic re-decode of the group
        assert r.salvage_report.summary() == first
        assert r.salvage_report.pages_skipped == 1
        assert r.salvage_report.rows_quarantined == PAGE_VALUES
        assert r.salvage_report.rows_dropped == PAGE_VALUES
        assert len(r.salvage_report.skips) == 1
        # ...and the row-mask ACTION (unlike the accounting) applies on
        # every decode: the re-read drops the same span again
        assert g0.num_rows == g0b.num_rows == ROWS_PER_GROUP - PAGE_VALUES
        # unknown group index never dedupes (None keys would collide
        # across groups and hide real losses)
        assert r.salvage_report._first_count("a", None, "q")
        assert r.salvage_report._first_count("a", None, "q")


def test_salvage_report_reachable_from_row_stream(salvage_file, tmp_path):
    """The public row stream exposes the SalvageReport, and the report
    survives stream exhaustion (losses stay accountable)."""
    from parquet_floor_tpu import ParquetReader
    from parquet_floor_tpu.api.hydrate import HydratorSupplier, dict_hydrator

    bad = _break_page_header(salvage_file, tmp_path, 0, "d", "bad_report")
    it = ParquetReader.stream_content(
        bad, HydratorSupplier.constantly(dict_hydrator()),
        options=ReaderOptions(salvage=True))
    n = sum(1 for _ in it)  # exhausts and closes the stream
    assert n == N_GROUPS * ROWS_PER_GROUP
    rep = it.salvage_report
    assert rep is not None and rep.chunks_quarantined == 1
    assert rep.skips[0].column == "d"


def test_quarantine_after_earlier_success_still_recorded(salvage_file):
    """A chunk that decoded fine once but fails on a later re-read (file
    changed underneath, flaky storage) must STILL get a skip record —
    every omission has a report entry."""
    from parquet_floor_tpu.testing import FaultInjectingSource

    src = FaultInjectingSource(salvage_file)
    with ParquetFileReader(src, options=ReaderOptions(salvage=True)) as r:
        g0 = r.read_row_group(0)
        assert len(g0.columns) == 3  # clean decode, all counted as "ok"
        src._truncate_at = 64  # storage "changes underneath"
        g0b = r.read_row_group(0)
        assert len(g0b.columns) == 0  # every chunk now quarantined
        assert len(r.salvage_report.skips) == 3
        assert r.salvage_report.chunks_quarantined == 3


def test_strict_mode_is_default_and_identical(salvage_file):
    """salvage defaults off; a clean file decodes identically with and
    without the flag, and no report is accumulated in strict mode."""
    strict, rep_strict = _decode_all(salvage_file)
    salv, rep_salv = _decode_all(salvage_file, salvage=True)
    assert rep_strict is None
    assert rep_salv is not None and rep_salv.skips == []
    assert rep_salv.rows_quarantined == 0
    for gs, gv in zip(strict, salv):
        for cs, cv in zip(gs.columns, gv.columns):
            assert cs.descriptor.path == cv.descriptor.path
            if isinstance(cs.values, np.ndarray):
                assert np.array_equal(cs.values, cv.values)


# ---------------------------------------------------------------------------
# dictionary recovery (ISSUE 6 tentpole part b): borrowed or demoted
# ---------------------------------------------------------------------------


def _write_dict_file(path, order2=None, write_crc=True):
    """Two row groups of one OPTIONAL string column whose values cycle a
    small set — dictionary pages on both chunks.  ``order2`` reorders
    group 2's first-occurrence sequence (different dictionary bytes)."""
    vals = [f"word{i}" for i in range(23)]
    schema = types.message(
        "t",
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.INT64).named("k"),
    )
    with ParquetFileWriter(
        path, schema,
        WriterOptions(data_page_values=PAGE_VALUES, write_crc=write_crc),
    ) as w:
        for order in (vals, order2 or vals):
            w.write_columns({
                "s": [order[i % len(order)] for i in range(ROWS_PER_GROUP)],
                "k": np.arange(ROWS_PER_GROUP, dtype=np.int64),
            })
    return str(path)


def _flip_dict_page(path, tmp_path, stem):
    """Flip one payload bit of row group 0's dictionary page for ``s``."""
    with ParquetFileReader(path) as r:
        spans = _page_spans(r, 0, "s")
    off, size, is_dict, _ = spans[0]
    assert is_dict, "fixture must emit a dictionary page"
    data = bytearray(pathlib.Path(path).read_bytes())
    data[off + size // 2] ^= 0x04
    out = tmp_path / f"{stem}.parquet"
    out.write_bytes(bytes(data))
    return str(out)


def test_dictionary_recovered_from_sibling_group(tmp_path):
    """The borrow: group 1's chunk holds the byte-identical dictionary
    (payload CRC proves it), so group 0 decodes to the exact clean
    values — zero rows lost, the recovery on record as a ``dict`` skip,
    and pages_skipped stays 0 (a recovered dictionary is not a
    substituted data page: report and trace counter tell one story)."""
    clean = _write_dict_file(tmp_path / "dict_clean.parquet")
    bad = _flip_dict_page(clean, tmp_path, "dict_bad")

    with pytest.raises(ChecksumMismatchError):
        _decode_all(bad, verify_crc=True)

    want, _ = _decode_all(clean)
    trace.enable()
    try:
        trace.reset()
        got, rep = _decode_all(bad, verify_crc=True, salvage=True)
        kinds = [d["decision"] for d in trace.decisions()]
        assert "salvage.dict_recovery" in kinds
        assert trace.counters().get("salvage.pages_skipped") is None
    finally:
        trace.disable()
        trace.reset()

    assert [s.kind for s in rep.skips] == ["dict"]
    assert "re-derived from row group 1" in rep.skips[0].error
    assert rep.pages_skipped == 0 and rep.rows_quarantined == 0
    assert rep.rows_dropped == 0 and rep.chunks_quarantined == 0
    assert [g.num_rows for g in got] == [ROWS_PER_GROUP] * 2
    for gw, gg in zip(want, got):
        sw = gw.column("s").values
        sg = gg.column("s").values
        assert np.array_equal(sw.offsets, sg.offsets)
        assert np.array_equal(sw.data, sg.data)


def test_dictionary_not_borrowed_across_different_order(tmp_path):
    """The near-miss that MUST not borrow: group 1 holds the same value
    set in a different first-occurrence order (same count, same size —
    only the payload CRC tells them apart).  Decoding indices through
    the wrong table would be silent wrong data, so the dictionary is
    declared lost and the damage falls through to the page tiers."""
    vals = [f"word{i}" for i in range(23)]
    rotated = vals[7:] + vals[:7]
    clean = _write_dict_file(tmp_path / "dict_rot.parquet", order2=rotated)
    bad = _flip_dict_page(clean, tmp_path, "dict_rot_bad")

    got, rep = _decode_all(bad, verify_crc=True, salvage=True)
    dict_skips = [s for s in rep.skips if s.kind == "dict"]
    assert len(dict_skips) == 1
    assert "lost" in dict_skips[0].error
    # every dict-encoded page of the OPTIONAL column nulls out
    # (page_null tier) — the rows and the other column survive intact
    assert rep.pages_skipped == ROWS_PER_GROUP // PAGE_VALUES
    assert [g.num_rows for g in got] == [ROWS_PER_GROUP] * 2
    g0 = got[0]
    s0 = g0.column("s")
    assert int(np.count_nonzero(
        np.asarray(s0.def_levels) == 1
    )) == 0  # all nulls
    assert np.array_equal(
        g0.column("k").values, np.arange(ROWS_PER_GROUP, dtype=np.int64)
    )
    # group 1 (its own dictionary undamaged) is untouched
    assert not any(s.row_group == 1 for s in rep.skips)


def test_dictionary_without_crc_is_never_borrowed(tmp_path):
    """No recorded page CRC, no byte proof, no borrow — even when the
    sibling's dictionary IS identical (it cannot be proven so).  The
    damage is a corrupted entry length prefix: framing the decoder
    catches without any CRC."""
    clean = _write_dict_file(tmp_path / "dict_nocrc.parquet",
                             write_crc=False)
    with ParquetFileReader(clean) as r:
        spans = _page_spans(r, 0, "s")
    off, _, is_dict, _ = spans[0]
    assert is_dict
    data = bytearray(pathlib.Path(clean).read_bytes())
    data[off + 2] ^= 0x10  # first entry's length += 0x100000: overruns
    bad = tmp_path / "dict_nocrc_bad.parquet"
    bad.write_bytes(bytes(data))

    _, rep = _decode_all(str(bad), salvage=True)
    dict_skips = [s for s in rep.skips if s.kind == "dict"]
    assert len(dict_skips) == 1
    assert "no page CRC" in dict_skips[0].error


# ---------------------------------------------------------------------------
# ranged reads under salvage: I/O pruning kept for clean chunks
# ---------------------------------------------------------------------------


def _skip_records(rep):
    """Comparable identity of a report's skip records."""
    return [
        (s.column, s.row_group, s.page, s.rows, s.kind,
         tuple(s.row_span) if s.row_span else None)
        for s in rep.skips
    ]


def _rowwise(col):
    """Per-row python values, None in null slots (packed values are
    expanded through def_levels so row selections line up)."""
    vals = col.values
    packed = vals.to_list() if hasattr(vals, "to_list") else list(
        np.asarray(vals))
    if col.def_levels is None:
        return packed
    out, it = [], iter(packed)
    for d in np.asarray(col.def_levels):
        out.append(next(it) if d else None)
    return out


def _assert_columns_equal(got, want, sel=None):
    for a, b in zip(got.columns, want.columns):
        assert a.descriptor.path == b.descriptor.path
        rows_b = _rowwise(b)
        if sel is not None:
            rows_b = [r for r, k in zip(rows_b, sel) if k]
        assert _rowwise(a) == rows_b


def test_ranged_salvage_clean_chunks_keep_pruning(salvage_file):
    """A clean file's ranged salvage read stays PRUNED: same cover and
    bytes as the strict ranged read, nothing widened, nothing lost."""
    ranges = [(0, 400)]
    with ParquetFileReader(salvage_file) as strict:
        want, cov = strict.read_row_group_ranges(0, ranges)
    with trace.scope() as t:
        with ParquetFileReader(
            salvage_file, options=ReaderOptions(salvage=True)
        ) as r:
            got, cov2 = r.read_row_group_ranges(0, ranges)
            rep = r.salvage_report
    assert cov2 == cov
    assert got.num_rows == want.num_rows == sum(b - a for a, b in cov)
    assert got.num_rows < ROWS_PER_GROUP
    _assert_columns_equal(got, want)
    assert rep.skips == [] and rep.rows_dropped == 0
    assert t.counters().get("salvage.ranged_widens", 0) == 0


def test_ranged_salvage_quarantine_identity_inside_cover(salvage_file,
                                                         tmp_path):
    """Damage INSIDE the cover: the damaged chunk widens to the
    whole-chunk ladder, so the quarantine records are identical to the
    whole-group path's; the clean chunks stay pruned (exactly one
    widen); survivors are byte-identical to the whole-group batch
    restricted to the cover."""
    bad, ordinal = _flip_in_page(salvage_file, tmp_path, 0, "d", 1, "rwq")
    opts = dict(verify_crc=True, salvage=True)
    with ParquetFileReader(bad, options=ReaderOptions(**opts)) as r:
        whole = r.read_row_group(0)
        skips_whole = _skip_records(r.salvage_report)
        dropped_whole = r.salvage_report.rows_dropped
    with trace.scope() as t:
        with ParquetFileReader(bad, options=ReaderOptions(**opts)) as r:
            got, cov = r.read_row_group_ranges(0, [(450, 1100)])
            rep = r.salvage_report
    assert _skip_records(rep) == skips_whole
    assert rep.rows_dropped == dropped_whole == PAGE_VALUES
    assert t.counters().get("salvage.ranged_widens", 0) == 1
    cov_rows = sum(b - a for a, b in cov)
    assert cov_rows < ROWS_PER_GROUP  # the cover really pruned
    assert got.num_rows == cov_rows - PAGE_VALUES
    # whole's surviving rows are group rows minus the damaged span;
    # got's are the covered subset of exactly those
    keep_w = np.r_[0:PAGE_VALUES, 2 * PAGE_VALUES:ROWS_PER_GROUP]
    cov_mask = np.zeros(ROWS_PER_GROUP, bool)
    for a, b in cov:
        cov_mask[a:b] = True
    _assert_columns_equal(got, whole, sel=cov_mask[keep_w])


def test_ranged_salvage_damage_outside_cover_stays_pruned(salvage_file,
                                                          tmp_path):
    """Damage entirely OUTSIDE the cover is never decoded — the read
    stays pruned and clean (the non-salvage pruned read's contract),
    bit-identical to the pristine strict ranged read."""
    bad, _ = _flip_in_page(salvage_file, tmp_path, 0, "d", 3, "outq")
    with ParquetFileReader(salvage_file) as strict:
        want, cov_w = strict.read_row_group_ranges(0, [(0, 400)])
    with trace.scope() as t:
        with ParquetFileReader(
            bad, options=ReaderOptions(verify_crc=True, salvage=True)
        ) as r:
            got, cov = r.read_row_group_ranges(0, [(0, 400)])
            rep = r.salvage_report
    assert cov == cov_w
    assert rep.skips == [] and rep.rows_dropped == 0
    assert t.counters().get("salvage.ranged_widens", 0) == 0
    assert got.num_rows == want.num_rows
    _assert_columns_equal(got, want)
