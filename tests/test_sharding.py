"""Multi-device sharded decode on the 8-device virtual CPU mesh (the
SURVEY.md §4 analogue of testing multi-node without a cluster)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
from parquet_floor_tpu.format.encodings.dictionary import encode_dict_indices
from parquet_floor_tpu.parallel import shard as pshard
from parquet_floor_tpu.tpu import bitops

rng = np.random.default_rng(31)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _make_group(n, dict_size, bw):
    idx = rng.integers(0, dict_size, n).astype(np.uint32)
    stream = encode_dict_indices(idx, 1 << bw)  # force bit width
    assert stream[0] == bw or dict_size <= (1 << stream[0])
    bw_actual = stream[0]
    table, _ = e_rle.parse_runs(stream, n, bw_actual, 1)
    plan = bitops.run_table_to_device_plan(table, n, 64)
    return idx, stream, plan, bw_actual


def test_sharded_decode_step_matches_host():
    n_per_group = 1024
    dict_pad = 512
    bw = 9  # indices up to 512
    mesh = pshard.make_mesh(8, rg=2, seq=2, dict_=2)

    G = 4  # two row groups per rg shard
    bufs = []
    plans = {"run_out_end": [], "run_kind": [], "run_value": [], "run_bytebase": []}
    expected_idx = []
    B = 4096
    for _ in range(G):
        idx, stream, plan, bwa = _make_group(n_per_group, dict_pad, bw)
        assert bwa == bw
        buf = np.zeros(B, np.uint8)
        buf[: len(stream)] = np.frombuffer(stream, np.uint8)
        bufs.append(buf)
        expected_idx.append(idx)
        for k in plans:
            plans[k].append(plan[k])
    dictionary = (rng.standard_normal(dict_pad) * 100).astype(np.float32)

    step = pshard.build_sharded_decode_step(
        mesh, n_per_group, bw, dict_pad, jnp.float32
    )
    out = step(
        jnp.asarray(np.stack(bufs)),
        jnp.asarray(np.stack(plans["run_out_end"]).astype(np.int32)),
        jnp.asarray(np.stack(plans["run_kind"]).astype(np.int32)),
        jnp.asarray(np.stack(plans["run_value"]).astype(np.int32)),
        jnp.asarray(np.stack(plans["run_bytebase"]).astype(np.int32)),
        jnp.asarray(dictionary),
    )
    assert out.shape == (G, n_per_group)
    expect = dictionary[np.stack(expected_idx)]
    np.testing.assert_array_equal(np.asarray(out), expect)
    # output really is sharded over the mesh
    assert len(out.sharding.device_set) == 8


def test_read_table_sharded(tmp_path):
    n, groups = 1000, 4
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.required(types.DOUBLE).named("b"),
    )
    path = tmp_path / "s.parquet"
    cols = {
        "a": rng.integers(0, 50, n * groups).astype(np.int64),
        "b": rng.integers(0, 9, n * groups).astype(np.float64),
    }
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(groups):
            w.write_columns({k: v[g * n : (g + 1) * n] for k, v in cols.items()})
    mesh = pshard.make_mesh(4, rg=4, seq=1, dict_=1)
    out = pshard.read_table_sharded(path, mesh)
    np.testing.assert_array_equal(np.asarray(out["a"].values), cols["a"])
    np.testing.assert_array_equal(np.asarray(out["b"].values), cols["b"])
    assert len(out["a"].values.sharding.device_set) == 4


def test_read_table_sharded_masks_and_errors(tmp_path):
    """Regression: nullable columns keep their masks; uneven group counts
    pad to a group stride with a row_mask instead of raising."""
    n, groups = 400, 4
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.INT64).named("o"),
    )
    path = tmp_path / "m.parquet"
    a = rng.integers(0, 50, n * groups).astype(np.int64)
    o = [None if i % 3 == 0 else int(i % 100) for i in range(n * groups)]
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(groups):
            w.write_columns({"a": a[g * n : (g + 1) * n], "o": o[g * n : (g + 1) * n]})
    mesh = pshard.make_mesh(4, rg=4, seq=1, dict_=1)
    out = pshard.read_table_sharded(path, mesh)
    np.testing.assert_array_equal(np.asarray(out["a"].values), a)
    assert out["a"].mask is None
    exp_mask = np.array([v is None for v in o])
    np.testing.assert_array_equal(np.asarray(out["o"].mask), exp_mask)
    got = np.asarray(out["o"].values)
    valid = ~exp_mask
    np.testing.assert_array_equal(got[valid], np.array([v for v in o if v is not None]))
    assert len(out["a"].values.sharding.device_set) == 4

    # 4 groups over a 3-device axis: padded ghost groups + row_mask
    mesh3 = pshard.make_mesh(3, rg=3, seq=1, dict_=1)
    out3 = pshard.read_table_sharded(path, mesh3)
    rm = np.asarray(out3["a"].row_mask)
    np.testing.assert_array_equal(np.asarray(out3["a"].values)[rm], a)
    assert out3["a"].num_rows == len(a)
    assert len(out3["a"].values.sharding.device_set) == 3


def test_read_sharded_global_single_process(tmp_path):
    """Multi-host entry degrades correctly under one process: global
    arrays come back sharded over the mesh axis with exact contents."""
    import numpy as np
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
    from parquet_floor_tpu.parallel.multihost import read_sharded_global
    from parquet_floor_tpu.parallel.shard import make_mesh

    rng = np.random.default_rng(61)
    n = 4096
    vals = rng.integers(0, 1000, n).astype(np.int64)
    schema = types.message("t", types.required(types.INT64).named("v"))
    path = tmp_path / "mh.parquet"
    with ParquetFileWriter(path, schema, WriterOptions(row_group_rows=512)) as w:
        for lo in range(0, n, 512):
            w.write_columns({"v": vals[lo : lo + 512]})

    mesh = make_mesh(8, rg=8)
    # axis name in make_mesh is "rg"
    out = read_sharded_global(path, mesh, axis="rg")
    got = np.asarray(out["v"].values)
    np.testing.assert_array_equal(got, vals)
    assert out["v"].mask is None
    assert len(out["v"].values.sharding.device_set) == 8


def test_tpu_iter_with_predicate(tmp_path):
    """TpuRowGroupReader.iter_row_groups(predicate=...) skips groups
    before any staging."""
    import numpy as np
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, col, types
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    schema = types.message("t", types.required(types.INT64).named("v"))
    path = tmp_path / "pred.parquet"
    with ParquetFileWriter(path, schema, WriterOptions(row_group_rows=100)) as w:
        for lo in range(0, 400, 100):
            w.write_columns({"v": np.arange(lo, lo + 100, dtype=np.int64)})
    with TpuRowGroupReader(path) as r:
        groups = list(r.iter_row_groups(predicate=(col("v") >= 250)))
        assert len(groups) == 2
        first = np.asarray(next(iter(groups[0].values())).values)
        assert first[0] == 200


def _ragged_file(tmp_path, name="rag.parquet", seed=7):
    """4 groups (300/300/300/170 rows): int64, strings, optional double,
    optional LIST<int32> — every sharded-assembly kind at once."""
    r = np.random.default_rng(seed)
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.optional(types.DOUBLE).named("o"),
        types.list_of(types.required(types.INT32).named("element"), "l",
                      optional=True),
    )
    path = str(tmp_path / name)
    truth = {"x": [], "s": [], "o": [], "l": []}
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g, n in enumerate([300, 300, 300, 170]):
            x = r.integers(0, 1000, n).astype(np.int64)
            s = [f"g{g}-row{i}" * (i % 3 + 1) for i in range(n)]
            o = [None if i % 5 == 0 else float(i) for i in range(n)]
            l = [None if i % 7 == 0 else [int(i), int(i + 1)][: i % 3]
                 for i in range(n)]
            truth["x"].append(x)
            truth["s"].extend(s)
            truth["o"].extend(o)
            truth["l"].extend(l)
            w.write_columns({"x": x, "s": s, "o": o, "l": l})
    truth["x"] = np.concatenate(truth["x"])
    return path, schema, truth


def test_read_table_sharded_strings_nested_ragged(tmp_path):
    """VERDICT r1 item 3: sharded assembly covers strings, nested LIST,
    optionals, and ragged files (non-uniform groups, non-divisible group
    count) — verified bit-exact against the host reader."""
    from parquet_floor_tpu import ParquetFileReader

    path, schema, truth = _ragged_file(tmp_path)
    mesh = pshard.make_mesh(8, rg=8)
    out = pshard.read_table_sharded(path, mesh)

    xc = out["x"]
    rm = np.asarray(xc.row_mask)
    np.testing.assert_array_equal(np.asarray(xc.values)[rm], truth["x"])
    assert xc.num_rows == len(truth["x"])
    assert len(xc.values.sharding.device_set) == 8

    assert out["s"].to_list() == [s.encode() for s in truth["s"]]
    assert out["o"].to_list() == truth["o"]

    nc = out["l.list.element"]
    assert len(nc.def_levels.sharding.device_set) == 8
    with ParquetFileReader(path) as r:
        assert nc.to_pylist(r.schema) == truth["l"]


def test_read_sharded_global_strings_nested_ragged(tmp_path):
    """The multi-host entry handles the same surface (single-process
    degenerate path) — strings, nested, optionals, raggedness."""
    from parquet_floor_tpu import ParquetFileReader
    from parquet_floor_tpu.parallel.multihost import read_sharded_global

    path, schema, truth = _ragged_file(tmp_path, "rag_mh.parquet", seed=11)
    mesh = pshard.make_mesh(8, rg=8)
    out = read_sharded_global(path, mesh)

    xc = out["x"]
    rm = np.asarray(xc.row_mask)
    np.testing.assert_array_equal(np.asarray(xc.values)[rm], truth["x"])
    assert xc.num_rows == len(truth["x"])

    sc = out["s"]
    vals, lens = np.asarray(sc.values), np.asarray(sc.lengths)
    srm = np.flatnonzero(np.asarray(sc.row_mask))
    got = [vals[i, : lens[i]].tobytes().decode() for i in srm]
    assert got == truth["s"]

    oc = out["o"]
    om = np.asarray(oc.mask)
    ov = np.asarray(oc.values)
    got_o = [None if om[i] else ov[i].item() for i in srm]
    assert got_o == truth["o"]

    nc = out["l.list.element"]
    with ParquetFileReader(path) as r:
        assert nc.to_pylist(r.schema) == truth["l"]


def test_read_sharded_global_nested_group_leaf(tmp_path):
    """Regression: a non-repeated group leaf is keyed by dotted path
    ('g.a') — the multihost name derivation must mirror the engine."""
    from parquet_floor_tpu.parallel.multihost import read_sharded_global

    schema = types.message(
        "t",
        types.required_group(types.required(types.INT64).named("a")).named("g"),
    )
    path = str(tmp_path / "nng.parquet")
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns({"g.a": np.arange(64, dtype=np.int64)})
        w.write_columns({"g.a": np.arange(64, 128, dtype=np.int64)})
    out = read_sharded_global(path, pshard.make_mesh(8, rg=8))
    c = out["g.a"]
    rm = (
        np.asarray(c.row_mask)
        if c.row_mask is not None
        else np.ones(128, bool)
    )
    np.testing.assert_array_equal(np.asarray(c.values)[rm], np.arange(128))


def test_read_sharded_global_with_predicate(tmp_path):
    """Predicate-pruned groups become masked ghost slots: identical global
    layout on every process, surviving rows intact, num_rows adjusted."""
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, col, types
    from parquet_floor_tpu.parallel.multihost import read_sharded_global
    from parquet_floor_tpu.parallel.shard import make_mesh

    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    path = tmp_path / "g.parquet"
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(4):
            base = g * 1000
            w.write_columns({
                "k": np.arange(base, base + 500, dtype=np.int64),
                "s": [f"g{g}_{i}" for i in range(500)],
            })
    mesh = make_mesh(8, rg=8, seq=1, dict_=1)
    out = read_sharded_global(path, mesh, predicate=col("k") >= 2000)
    kcol = out["k"]
    assert kcol.num_rows == 1000  # groups 2 and 3 survive
    rm = np.asarray(kcol.row_mask)
    vals = np.asarray(kcol.values)
    assert rm.sum() == 1000
    np.testing.assert_array_equal(
        np.sort(vals[rm]), np.arange(2000, 2500).tolist() + np.arange(3000, 3500).tolist()
    )
    # strings survive too, and pruned slots are fully masked
    scol = out["s"]
    srm = np.asarray(scol.row_mask)
    assert srm.sum() == 1000
    lens = np.asarray(scol.lengths)
    assert (lens[~srm] == 0).all()


def test_read_sharded_global_all_pruned(tmp_path):
    """A predicate excluding every group still yields correctly-typed
    ghost columns (schema-derived metadata, all rows masked)."""
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, col, types
    from parquet_floor_tpu.parallel.multihost import read_sharded_global
    from parquet_floor_tpu.parallel.shard import make_mesh

    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    path = tmp_path / "ap.parquet"
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(2):
            w.write_columns({"k": np.arange(100, dtype=np.int64),
                             "s": [f"x{i}" for i in range(100)]})
    mesh = make_mesh(8, rg=8, seq=1, dict_=1)
    out = read_sharded_global(path, mesh, predicate=col("k") == 10_000)
    kcol, scol = out["k"], out["s"]
    assert kcol.num_rows == 0 and not np.asarray(kcol.row_mask).any()
    assert np.asarray(kcol.values).dtype == np.int64
    assert scol.lengths is not None  # still a string column
    assert not np.asarray(scol.row_mask).any()


@pytest.mark.parametrize("seed", range(6))
def test_generative_sharded_global_soak(tmp_path, seed):
    """Random schemas × random data × random writer options through
    read_sharded_global on the full 8-device mesh, verified value-exact
    against the host engine's dense forms (the sharded sibling of
    test_soak's generative roundtrip)."""
    from jax.sharding import Mesh

    from parquet_floor_tpu.parallel.multihost import read_sharded_global
    from tests.test_soak import _CODECS, _random_column

    rng_l = np.random.default_rng(1000 + seed)
    n = int(rng_l.integers(10, 2500))
    n_cols = int(rng_l.integers(1, 5))
    fields, names, datas = [], [], []
    for i in range(n_cols):
        f, name, data, _ = _random_column(rng_l, n, i)
        fields.append(f)
        names.append(name)
        datas.append(data)
    schema = types.message("t", *fields)
    opts = WriterOptions(
        codec=int(rng_l.choice(_CODECS)),
        page_version=int(rng_l.choice([1, 2])),
        data_page_values=int(rng_l.choice([97, 20_000])),
        enable_dictionary=bool(rng_l.integers(0, 2)),
        row_group_rows=int(rng_l.choice([n, max(1, n // 3), max(1, n // 7)])),
    )
    path = str(tmp_path / f"shsoak{seed}.parquet")
    with ParquetFileWriter(path, schema, opts) as w:
        done = 0
        while done < n:
            take = min(opts.row_group_rows, n - done)
            w.write_columns(
                {nm: d[done : done + take] for nm, d in zip(names, datas)}
            )
            done += take

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("rg",))
    out = read_sharded_global(path, mesh, float64_policy="float64")
    # reassemble per-column values across all groups and compare to source
    for nm, exp in zip(names, datas):
        col = out[nm]
        assert col.num_rows == n, f"seed {seed} {nm}"
        gv = np.asarray(col.values)
        gmask = None if col.mask is None else np.asarray(col.mask)
        rowm = None if col.row_mask is None else np.asarray(col.row_mask)
        lens = None if col.lengths is None else np.asarray(col.lengths)
        got_vals = []
        for i in range(len(gv) if rowm is None else len(rowm)):
            if rowm is not None and not rowm[i]:
                continue
            is_null = gmask is not None and bool(gmask[i])
            if lens is not None:
                v = None if is_null else gv[i, : int(lens[i])].tobytes().decode()
            else:
                v = None if is_null else gv[i]
            got_vals.append(v)
        assert len(got_vals) == n, f"seed {seed} {nm}"
        for g, e in zip(got_vals, exp):
            if e is None or g is None:
                assert g == e, f"seed {seed} {nm}"
            elif isinstance(e, float):
                assert g == e or (np.isnan(g) and np.isnan(e)), (
                    f"seed {seed} {nm}"
                )
            elif isinstance(e, bool):
                assert bool(g) == e, f"seed {seed} {nm}"
            else:
                assert g == e or str(g) == str(e), f"seed {seed} {nm}"
