"""Multi-device sharded decode on the 8-device virtual CPU mesh (the
SURVEY.md §4 analogue of testing multi-node without a cluster)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
from parquet_floor_tpu.format.encodings.dictionary import encode_dict_indices
from parquet_floor_tpu.parallel import shard as pshard
from parquet_floor_tpu.tpu import bitops

rng = np.random.default_rng(31)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _make_group(n, dict_size, bw):
    idx = rng.integers(0, dict_size, n).astype(np.uint32)
    stream = encode_dict_indices(idx, 1 << bw)  # force bit width
    assert stream[0] == bw or dict_size <= (1 << stream[0])
    bw_actual = stream[0]
    table, _ = e_rle.parse_runs(stream, n, bw_actual, 1)
    plan = bitops.run_table_to_device_plan(table, n, 64)
    return idx, stream, plan, bw_actual


def test_sharded_decode_step_matches_host():
    n_per_group = 1024
    dict_pad = 512
    bw = 9  # indices up to 512
    mesh = pshard.make_mesh(8, rg=2, seq=2, dict_=2)

    G = 4  # two row groups per rg shard
    bufs = []
    plans = {"run_out_end": [], "run_kind": [], "run_value": [], "run_bitbase": []}
    expected_idx = []
    B = 4096
    for _ in range(G):
        idx, stream, plan, bwa = _make_group(n_per_group, dict_pad, bw)
        assert bwa == bw
        buf = np.zeros(B, np.uint8)
        buf[: len(stream)] = np.frombuffer(stream, np.uint8)
        bufs.append(buf)
        expected_idx.append(idx)
        for k in plans:
            plans[k].append(plan[k])
    dictionary = (rng.standard_normal(dict_pad) * 100).astype(np.float32)

    step = pshard.build_sharded_decode_step(
        mesh, n_per_group, bw, dict_pad, jnp.float32
    )
    out = step(
        jnp.asarray(np.stack(bufs)),
        jnp.asarray(np.stack(plans["run_out_end"]).astype(np.int32)),
        jnp.asarray(np.stack(plans["run_kind"]).astype(np.int32)),
        jnp.asarray(np.stack(plans["run_value"]).astype(np.int32)),
        jnp.asarray(np.stack(plans["run_bitbase"]).astype(np.int32)),
        jnp.asarray(dictionary),
    )
    assert out.shape == (G, n_per_group)
    expect = dictionary[np.stack(expected_idx)]
    np.testing.assert_array_equal(np.asarray(out), expect)
    # output really is sharded over the mesh
    assert len(out.sharding.device_set) == 8


def test_read_table_sharded(tmp_path):
    n, groups = 1000, 4
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.required(types.DOUBLE).named("b"),
    )
    path = tmp_path / "s.parquet"
    cols = {
        "a": rng.integers(0, 50, n * groups).astype(np.int64),
        "b": rng.integers(0, 9, n * groups).astype(np.float64),
    }
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(groups):
            w.write_columns({k: v[g * n : (g + 1) * n] for k, v in cols.items()})
    mesh = pshard.make_mesh(4, rg=4, seq=1, dict_=1)
    out = pshard.read_table_sharded(path, mesh)
    np.testing.assert_array_equal(np.asarray(out["a"].values), cols["a"])
    np.testing.assert_array_equal(np.asarray(out["b"].values), cols["b"])
    assert len(out["a"].values.sharding.device_set) == 4


def test_read_table_sharded_masks_and_errors(tmp_path):
    """Regression: nullable columns keep their masks; uneven group counts
    raise instead of silently degrading to one device."""
    n, groups = 400, 4
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.INT64).named("o"),
    )
    path = tmp_path / "m.parquet"
    a = rng.integers(0, 50, n * groups).astype(np.int64)
    o = [None if i % 3 == 0 else int(i % 100) for i in range(n * groups)]
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(groups):
            w.write_columns({"a": a[g * n : (g + 1) * n], "o": o[g * n : (g + 1) * n]})
    mesh = pshard.make_mesh(4, rg=4, seq=1, dict_=1)
    out = pshard.read_table_sharded(path, mesh)
    np.testing.assert_array_equal(np.asarray(out["a"].values), a)
    assert out["a"].mask is None
    exp_mask = np.array([v is None for v in o])
    np.testing.assert_array_equal(np.asarray(out["o"].mask), exp_mask)
    got = np.asarray(out["o"].values)
    valid = ~exp_mask
    np.testing.assert_array_equal(got[valid], np.array([v for v in o if v is not None]))
    assert len(out["a"].values.sharding.device_set) == 4

    mesh3 = pshard.make_mesh(3, rg=3, seq=1, dict_=1)
    with pytest.raises(ValueError, match="shard evenly"):
        pshard.read_table_sharded(path, mesh3)


def test_read_sharded_global_single_process(tmp_path):
    """Multi-host entry degrades correctly under one process: global
    arrays come back sharded over the mesh axis with exact contents."""
    import numpy as np
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types
    from parquet_floor_tpu.parallel.multihost import read_sharded_global
    from parquet_floor_tpu.parallel.shard import make_mesh

    rng = np.random.default_rng(61)
    n = 4096
    vals = rng.integers(0, 1000, n).astype(np.int64)
    schema = types.message("t", types.required(types.INT64).named("v"))
    path = tmp_path / "mh.parquet"
    with ParquetFileWriter(path, schema, WriterOptions(row_group_rows=512)) as w:
        for lo in range(0, n, 512):
            w.write_columns({"v": vals[lo : lo + 512]})

    mesh = make_mesh(8, rg=8)
    # axis name in make_mesh is "rg"
    out = read_sharded_global(path, mesh, axis="rg")
    got = np.asarray(out["v"].values)
    np.testing.assert_array_equal(got, vals)
    assert out["v"].mask is None
    assert len(out["v"].values.sharding.device_set) == 8


def test_tpu_iter_with_predicate(tmp_path):
    """TpuRowGroupReader.iter_row_groups(predicate=...) skips groups
    before any staging."""
    import numpy as np
    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, col, types
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    schema = types.message("t", types.required(types.INT64).named("v"))
    path = tmp_path / "pred.parquet"
    with ParquetFileWriter(path, schema, WriterOptions(row_group_rows=100)) as w:
        for lo in range(0, 400, 100):
            w.write_columns({"v": np.arange(lo, lo + 100, dtype=np.int64)})
    with TpuRowGroupReader(path) as r:
        groups = list(r.iter_row_groups(predicate=(col("v") >= 250)))
        assert len(groups) == 2
        first = np.asarray(next(iter(groups[0].values())).values)
        assert first[0] == 200
