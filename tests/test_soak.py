"""Generative soak: random schemas × random data × random writer options,
round-tripped through our writer, our host reader, the TPU engine, and
the pyarrow oracle.  The closest thing to fuzzing the full stack."""

import struct

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

_CODECS = [
    CompressionCodec.UNCOMPRESSED,
    CompressionCodec.SNAPPY,
    CompressionCodec.GZIP,
    CompressionCodec.ZSTD,
    CompressionCodec.LZ4_RAW,
]
try:  # system-library codec joins the soak where present
    from parquet_floor_tpu.format import brotli_codec as _bc

    if _bc.available() and _bc.encoder_available():
        _CODECS.append(CompressionCodec.BROTLI)
except Exception:  # pragma: no cover
    pass


def _random_column(rng, n, idx):
    """(field_builder, data, pyarrow_comparator) for one random column."""
    kind = rng.integers(0, 6)
    optional = bool(rng.integers(0, 2))
    name = f"c{idx}"
    t = types

    def opt(values):
        if not optional:
            return values
        return [None if rng.random() < 0.25 else v for v in values]

    if kind == 0:
        b = (t.optional if optional else t.required)(t.INT64)
        data = opt([int(v) for v in rng.integers(-(2**62), 2**62, n)])
    elif kind == 1:
        b = (t.optional if optional else t.required)(t.INT32)
        data = opt([int(v) for v in rng.integers(-(2**31), 2**31, n)])
    elif kind == 2:
        b = (t.optional if optional else t.required)(t.DOUBLE)
        data = opt([float(v) for v in rng.standard_normal(n)])
    elif kind == 3:
        b = (t.optional if optional else t.required)(t.FLOAT)
        data = opt([float(np.float32(v)) for v in rng.standard_normal(n)])
    elif kind == 4:
        b = (t.optional if optional else t.required)(t.BOOLEAN)
        data = opt([bool(v) for v in rng.integers(0, 2, n)])
    else:
        b = (t.optional if optional else t.required)(t.BYTE_ARRAY).as_(t.string())
        card = int(rng.choice([3, 50, 100_000]))  # low → dict; high → fallback
        data = opt([f"s{int(v)}" for v in rng.integers(0, card, n)])
    return b.named(name), name, data, int(kind)


@pytest.mark.parametrize("seed", range(18))
def test_random_roundtrip(tmp_path, seed, monkeypatch):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 4000))
    n_cols = int(rng.integers(1, 6))
    fields, names, datas, bools, kinds = [], [], [], [], []
    for i in range(n_cols):
        f, name, data, kind = _random_column(rng, n, i)
        fields.append(f)
        names.append(name)
        datas.append(data)
        bools.append(kind == 4)  # kind 4 = BOOLEAN
        kinds.append(kind)
    schema = types.message("t", *fields)
    # randomly bloom-filter the non-boolean columns (write + read below;
    # selection by column KIND — BOOLEAN rejects blooms by design)
    bloom_cols = None
    if rng.integers(0, 2):
        bloom_cols = {
            nm: True for nm, is_bool in zip(names, bools) if not is_bool
        } or None
    # randomly exercise the chunked fill-and-ship staging path (only
    # meaningful via read_row_group — the pipelined iterator disables
    # intra-group chunking by design, so force the direct path below)
    chunked = bool(rng.integers(0, 2))
    if chunked:
        import parquet_floor_tpu.tpu.engine as _eng

        monkeypatch.setenv("PFTPU_CHUNKED_SHIP", "1")
        monkeypatch.setattr(_eng, "_SHIP_CHUNK", 1 << 14)
    # random per-column overrides (round 4): an explicit encoding where
    # the column's kind allows one, and random dictionary disables
    col_encs = {}
    col_dict = {}
    _KIND_ENCS = {
        0: ["DELTA_BINARY_PACKED"],                       # INT64
        1: ["DELTA_BINARY_PACKED", "BYTE_STREAM_SPLIT"],  # INT32
        2: ["BYTE_STREAM_SPLIT"],                         # DOUBLE
        3: ["BYTE_STREAM_SPLIT"],                         # FLOAT
        5: ["DELTA_BYTE_ARRAY"],                          # strings
    }
    for nm, k in zip(names, kinds):
        if rng.random() < 0.15:
            col_dict[nm] = bool(rng.integers(0, 2))
        if k in _KIND_ENCS and rng.random() < 0.2:
            col_encs[nm] = str(rng.choice(_KIND_ENCS[k]))
    opts = WriterOptions(
        codec=int(rng.choice(_CODECS)),
        page_version=int(rng.choice([1, 2])),
        data_page_values=int(rng.choice([97, 500, 20_000])),
        data_page_bytes=(
            int(rng.choice([1 << 10, 1 << 14])) if rng.integers(0, 2) else None
        ),
        enable_dictionary=bool(rng.integers(0, 2)),
        delta_integers=bool(rng.integers(0, 2)),
        byte_stream_split_floats=bool(rng.integers(0, 2)),
        delta_strings=bool(rng.integers(0, 2)),
        row_group_rows=int(rng.choice([n, max(1, n // 3)])),
        bloom_filter_columns=bloom_cols,
        column_encodings=col_encs or None,
        column_dictionary=col_dict or None,
    )
    path = str(tmp_path / f"soak{seed}.parquet")
    with ParquetFileWriter(path, schema, opts) as w:
        done = 0
        while done < n:
            take = min(opts.row_group_rows, n - done)
            w.write_columns({nm: d[done : done + take] for nm, d in zip(names, datas)})
            done += take

    # oracle 1: pyarrow reads identical values
    table = pq.read_table(path)
    for nm, exp in zip(names, datas):
        got = table.column(nm).to_pylist()
        if exp and isinstance(next((v for v in exp if v is not None), None), float):
            assert len(got) == len(exp)
            for g, e in zip(got, exp):
                assert (g is None) == (e is None)
                if g is not None:
                    assert g == pytest.approx(e, rel=0, abs=0) or (
                        np.isnan(g) and np.isnan(e)
                    )
        else:
            assert got == exp, f"seed {seed} col {nm}"

    # oracle 2: host reader agrees
    with ParquetFileReader(path) as r:
        per_col = {nm: [] for nm in names}
        for gi in range(len(r.row_groups)):
            batch = r.read_row_group(gi)
            for cb in batch.columns:
                nm = cb.descriptor.path[0]
                for i in range(batch.num_rows):
                    v = cb.cell(i)
                    if isinstance(v, bytes):
                        v = v.decode()
                    elif isinstance(v, np.generic):
                        v = v.item()
                    per_col[nm].append(v)
        for nm, exp in zip(names, datas):
            assert per_col[nm] == exp, f"seed {seed} host col {nm}"

    # oracle 3: TPU engine matches the host dense forms — alternating
    # between direct group reads and the pipelined iterator (stage ‖
    # ship ‖ decode workers) so both decode paths stay covered
    with TpuRowGroupReader(path, float64_policy="float64") as tr, \
            ParquetFileReader(path) as hr:
        if seed % 2 and not chunked:
            dev_groups = list(tr.iter_row_groups())
        else:
            dev_groups = [
                tr.read_row_group(gi) for gi in range(tr.num_row_groups)
            ]
        for gi in range(tr.num_row_groups):
            dev = dev_groups[gi]
            hb = hr.read_row_group(gi)
            for cb in hb.columns:
                nm = cb.descriptor.path[0]
                dc = dev[nm]
                dense, mask = cb.dense()
                if mask is not None:
                    np.testing.assert_array_equal(
                        np.asarray(dc.mask), mask, err_msg=f"seed {seed} {nm}"
                    )
                if isinstance(dense, ByteArrayColumn):
                    lens = np.asarray(dc.lengths)
                    rows = np.asarray(dc.values)
                    got = [rows[i, : lens[i]].tobytes() for i in range(len(lens))]
                    assert got == dense.to_list(), f"seed {seed} {nm}"
                else:
                    got = np.asarray(dc.values)
                    if mask is not None:
                        got = np.where(mask, 0, got)
                        dense = np.where(mask, 0, dense)
                    np.testing.assert_array_equal(
                        got, dense, err_msg=f"seed {seed} {nm}"
                    )

    # oracle 5 (every third seed): the declarative row API returns
    # identical rows through the host and device engines — the one-front-
    # door contract (api/reader.py engine="tpu")
    if seed % 3 == 0:
        from parquet_floor_tpu import ParquetReader

        class _Rows:
            def start(self):
                return []

            def add(self, t_, h, v):
                t_.append((h, v))
                return t_

            def finish(self, t_):
                return tuple(t_)

        def _key(row):
            return [
                (h, struct.pack("<d", v) if isinstance(v, float) else v)
                for h, v in row
            ]

        host_rows = list(
            ParquetReader.stream_content(path, lambda c: _Rows())
        )
        tpu_rows = list(
            ParquetReader.stream_content(path, lambda c: _Rows(), engine="tpu")
        )
        assert len(host_rows) == len(tpu_rows) == n
        for hr_, tr_ in zip(host_rows, tpu_rows):
            assert _key(hr_) == _key(tr_), f"seed {seed}"

    # oracle 6 (every fourth seed): the BATCH face agrees between
    # engines across the random encoding/codec/page matrix — values,
    # masks, and string bytes per group (stream_batches contract)
    if seed % 4 == 0:
        from parquet_floor_tpu import ParquetReader

        def _batch_cells(engine):
            out = []
            for cols in ParquetReader.stream_batches(path, engine=engine):
                for c in cols:
                    if c.is_strings:
                        cells = c.bytes_list()
                    else:
                        v = c.to_numpy()
                        cells = (
                            [v[i].tobytes() for i in range(len(v))]
                            if v.ndim == 2
                            else [
                                struct.pack("<d", x)
                                if isinstance(x, float)
                                else x
                                for x in v.tolist()
                            ]
                        )
                    if c.mask is not None:
                        m = np.asarray(c.mask)
                        cells = [
                            None if m[i] else cells[i]
                            for i in range(len(cells))
                        ]
                    out.append((c.descriptor.path[0], cells))
            return out

        hb_ = _batch_cells("host")
        tb_ = _batch_cells("tpu")
        assert len(hb_) == len(tb_)
        for (hn, hc), (tn, tc) in zip(hb_, tb_):
            assert hn == tn and hc == tc, f"seed {seed} batch col {hn}"

    # oracle 4: bloom filters never produce a false negative on any
    # value actually present
    if bloom_cols:
        from parquet_floor_tpu import col

        with ParquetFileReader(path) as r:
            for nm, exp in zip(names, datas):
                if nm not in bloom_cols:
                    continue
                present = [v for v in exp if v is not None]
                if not present:
                    continue
                probe = present[int(rng.integers(0, len(present)))]
                if isinstance(probe, float) and np.isnan(probe):
                    continue
                groups = (col(nm) == probe).row_groups(r)
                assert groups, f"seed {seed} bloom false negative on {nm}"


@pytest.mark.parametrize("seed", range(12))
def test_random_nested_roundtrip(tmp_path, seed, monkeypatch):
    """Random LIST columns (optional lists, optional elements, random
    lengths incl. empties) through writer → pyarrow + host + TPU.
    Small-page seeds lower the arena cap so the repeated-leaf chunk
    path (multi-launch split + traced-count packing) soaks too
    (single-page chunks have no boundary to split on — those keep the
    default cap)."""
    rng = np.random.default_rng(100 + seed)
    n = int(rng.integers(1, 1500))
    elem_optional = bool(rng.integers(0, 2))
    list_optional = bool(rng.integers(0, 2))
    str_elems = bool(rng.integers(0, 2))

    def elem():
        if elem_optional and rng.random() < 0.2:
            return None
        if str_elems:
            return f"e{int(rng.integers(0, 50))}"
        return int(rng.integers(-1000, 1000))

    rows = []
    for _ in range(n):
        if list_optional and rng.random() < 0.15:
            rows.append(None)
        else:
            rows.append([elem() for _ in range(int(rng.integers(0, 6)))])

    t = types
    eb = (t.optional if elem_optional else t.required)(
        t.BYTE_ARRAY if str_elems else t.INT64
    )
    if str_elems:
        eb = eb.as_(t.string())
    schema = t.message(
        "m", t.list_of(eb.named("element"), "v", optional=list_optional)
    )
    opts = WriterOptions(
        codec=int(rng.choice(_CODECS)),
        page_version=int(rng.choice([1, 2])),
        data_page_values=int(rng.choice([131, 5000])),
        enable_dictionary=bool(rng.integers(0, 2)),
    )
    if opts.data_page_values < n:
        # multiple pages exist → page boundaries exist → the chunk path
        # can split; force it to run
        monkeypatch.setenv("PFTPU_ARENA_CAP", str(4 << 10))
    path = str(tmp_path / f"ns{seed}.parquet")
    with ParquetFileWriter(path, schema, opts) as w:
        w.write_columns({"v": rows})

    # pyarrow oracle
    got = pq.read_table(path).column("v").to_pylist()
    assert got == rows, f"seed {seed}"

    # host assembly
    from parquet_floor_tpu.batch.nested import assemble_nested

    with ParquetFileReader(path) as r:
        out = []
        for gi in range(len(r.row_groups)):
            cb = r.read_row_group(gi).columns[0]
            out.extend(assemble_nested(r.schema, cb).to_pylist())
    if str_elems:
        out = [
            None if row is None else [
                None if e is None else e.decode() for e in row
            ]
            for row in out
        ]
    assert out == rows, f"seed {seed} host"

    # TPU engine assembly
    with ParquetFileReader(path) as hr:
        sch = hr.schema
    with TpuRowGroupReader(path) as tr:
        out2 = []
        for gi in range(tr.num_row_groups):
            (dc,) = tr.read_row_group(gi).values()
            out2.extend(dc.assemble(sch).to_pylist())
    if str_elems:
        out2 = [
            None if row is None else [
                None if e is None else e.decode() for e in row
            ]
            for row in out2
        ]
    assert out2 == rows, f"seed {seed} tpu"


@pytest.mark.parametrize("seed", range(6))
def test_random_repeated_flba_int96(tmp_path, seed):
    """Repeated FLBA and INT96 leaves through the device engine (the
    reference's engine decodes any physical type at any repetition level,
    ParquetReader.java:147-163).  pyarrow dict-encodes FLBA/INT96 by
    default, so these chunks fall back to host decode and ship as dense
    2-D byte rows (engine ``hostr_rows``) — value parity vs the host
    assembly and the pyarrow oracle, zero user-visible errors."""
    import pyarrow as pa

    from parquet_floor_tpu.batch.nested import assemble_nested

    rng = np.random.default_rng(300 + seed)
    n = int(rng.integers(1, 800))
    width = int(rng.choice([4, 16]))
    use_int96 = bool(seed % 2)

    rows = []
    for _ in range(n):
        r = rng.random()
        if r < 0.15:
            rows.append(None)
        else:
            ln = int(rng.integers(0, 5))
            if use_int96:
                rows.append([int(rng.integers(0, 2**48)) for _ in range(ln)])
            else:
                # low cardinality → dictionary encoding kicks in
                rows.append([
                    bytes([int(rng.integers(0, 8))]) * width
                    for _ in range(ln)
                ])
    path = str(tmp_path / f"rep{seed}.parquet")
    if use_int96:
        arr = pa.array(rows, type=pa.list_(pa.timestamp("ns")))
        pq.write_table(
            pa.table({"v": arr}), path, use_deprecated_int96_timestamps=True
        )
    else:
        arr = pa.array(rows, type=pa.list_(pa.binary(width)))
        pq.write_table(pa.table({"v": arr}), path)

    def render(nested_rows):
        # byte-row leaves render as uint8 arrays; normalize to bytes
        return [
            None if row is None
            else [None if e is None else np.asarray(e).tobytes() for e in row]
            for row in nested_rows
        ]

    with ParquetFileReader(path) as r:
        host_out = []
        for gi in range(len(r.row_groups)):
            cb = r.read_row_group(gi).columns[0]
            host_out.extend(assemble_nested(r.schema, cb).to_pylist())
        sch = r.schema
    with TpuRowGroupReader(path) as tr:
        dev_out = []
        for gi in range(tr.num_row_groups):
            (dc,) = tr.read_row_group(gi).values()
            dev_out.extend(dc.assemble(sch).to_pylist())
    assert render(dev_out) == render(host_out), f"seed {seed}"
    if not use_int96:
        # FLBA: the raw bytes match the pyarrow oracle exactly
        got = pq.read_table(path).column("v").to_pylist()
        assert render(dev_out) == got, f"seed {seed}"


@pytest.mark.parametrize("seed", range(12))
def test_random_selective_reads(tmp_path, seed):
    """Fuzz predicate pushdown + selective page reads: for random files
    and random predicates, pruning must never drop a matching row, and
    ranged decode must agree with the full decode on covered rows."""
    from parquet_floor_tpu import col

    rng = np.random.default_rng(200 + seed)
    n = int(rng.integers(50, 3000))
    xs = rng.integers(-500, 500, n).astype(np.int64)
    ys = [None if rng.random() < 0.2 else float(v) for v in rng.standard_normal(n)]
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.optional(types.DOUBLE).named("y"),
    )
    opts = WriterOptions(
        codec=int(rng.choice(_CODECS)),
        page_version=int(rng.choice([1, 2])),
        data_page_values=int(rng.choice([37, 100, 999])),
        row_group_rows=int(rng.choice([n, max(10, n // 2)])),
    )
    path = str(tmp_path / f"sel{seed}.parquet")
    with ParquetFileWriter(path, schema, opts) as w:
        done = 0
        while done < n:
            take = min(opts.row_group_rows, n - done)
            w.write_columns({"x": xs[done : done + take],
                             "y": ys[done : done + take]})
            done += take

    with ParquetFileReader(path) as r:
        for _ in range(6):
            lo = int(rng.integers(-600, 600))
            hi = lo + int(rng.integers(0, 400))
            op = rng.integers(0, 4)
            if op == 0:
                pred, match = (col("x") >= lo), lambda v: v >= lo
            elif op == 1:
                pred, match = (col("x") < hi), lambda v: v < hi
            elif op == 2:
                pred = (col("x") >= lo) & (col("x") < hi)
                match = lambda v: lo <= v < hi
            else:
                pred = (col("x") == lo) | (col("x") >= hi)
                match = lambda v: v == lo or v >= hi

            row_base = 0
            kept_groups = set(pred.row_groups(r))
            for gi in range(len(r.row_groups)):
                g_rows = int(r.row_groups[gi].num_rows)
                g_slice = xs[row_base : row_base + g_rows]
                has_match = any(match(int(v)) for v in g_slice)
                if has_match:
                    assert gi in kept_groups, (seed, gi, lo, hi, op)
                if gi in kept_groups:
                    ranges = pred.row_ranges(r, gi)
                    in_ranges = np.zeros(g_rows, bool)
                    for a, b in ranges:
                        in_ranges[a:b] = True
                    matching = np.array([match(int(v)) for v in g_slice])
                    # conservative: every matching row inside some range
                    assert not (matching & ~in_ranges).any(), (seed, gi, op)
                    # ranged decode agrees with ground truth on cover
                    batch, covered = r.read_row_group_ranges(gi, ranges)
                    if covered:
                        got = np.asarray(batch.column("x").values)
                        exp = np.concatenate([g_slice[a:b] for a, b in covered])
                        np.testing.assert_array_equal(got, exp)
                    else:
                        assert batch.num_rows == 0
                row_base += g_rows


@pytest.mark.parametrize("seed", range(8))
def test_random_arena_caps_fallback_matrix(tmp_path, seed, monkeypatch):
    """Fuzz the chunk/fallback matrix (round-5 flagship change): random
    file shapes — pyarrow-default (no page index) and this repo's
    writer (page index, random page sizes) — under random arena caps
    must decode identically on host and device engines, whether the
    cap forces column bins, row splits, or the whole-column host
    fallback."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    rng = np.random.default_rng(7000 + seed)
    n = int(rng.integers(500, 4000))
    use_pyarrow = bool(seed % 2)
    path = str(tmp_path / f"cap{seed}.parquet")
    ints = rng.integers(-(2**40), 2**40, n).astype(np.int64)
    strs = [
        None if rng.random() < 0.15
        else f"s{int(v)}-" + "x" * int(rng.integers(0, 30))
        for v in rng.integers(0, 50, n)
    ]
    floats = [None if rng.random() < 0.1 else float(v)
              for v in rng.standard_normal(n)]
    if use_pyarrow:
        pq.write_table(
            pa.table({"a": ints, "s": strs, "b": floats}), path,
            write_page_index=False,
            data_page_size=int(rng.integers(1, 64)) << 10,
        )
    else:
        schema = types.message(
            "t",
            types.required(types.INT64).named("a"),
            types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
            types.optional(types.DOUBLE).named("b"),
        )
        with ParquetFileWriter(
            path, schema,
            WriterOptions(data_page_values=int(rng.integers(100, 1500))),
        ) as w:
            w.write_columns({"a": ints, "s": strs, "b": floats})
    cap = int(rng.integers(2, 64)) << 10
    monkeypatch.setenv("PFTPU_ARENA_CAP", str(cap))
    with ParquetFileReader(path) as hr, \
            TpuRowGroupReader(path, float64_policy="float64") as tr:
        assert tr._arena_cap == cap
        for gi in range(tr.num_row_groups):
            dev = tr.read_row_group(gi)
            hb = hr.read_row_group(gi)
            for cb in hb.columns:
                nm = cb.descriptor.path[0]
                dc = dev[nm]
                dense, mask = cb.dense()
                if mask is not None:
                    np.testing.assert_array_equal(
                        np.asarray(dc.mask), mask, err_msg=f"{seed}:{nm}"
                    )
                if isinstance(dense, ByteArrayColumn):
                    lens = np.asarray(dc.lengths)
                    rows = np.asarray(dc.values)
                    got = [rows[i, : lens[i]].tobytes()
                           for i in range(len(lens))]
                    assert got == dense.to_list(), f"{seed}:{nm}"
                else:
                    got = np.asarray(dc.values)
                    if mask is not None:
                        got = np.where(mask, 0, got)
                        dense = np.where(mask, 0, dense)
                    np.testing.assert_array_equal(
                        got, dense, err_msg=f"{seed}:{nm}"
                    )
