"""Worker for the real multi-process sharded-read test (not a test file).

Launched by ``test_multiprocess.py`` as 2 OS processes, each owning 4
virtual CPU devices, joined through ``jax.distributed.initialize``.  Runs
``read_sharded_global`` (strings + predicate + all-pruned ghost case),
reshards every global column to fully-replicated so THIS process holds
the complete global value, and writes a digest the parent compares
across processes and against a single-process expectation.

Usage: python multiproc_worker.py <coord_addr> <pid> <nproc> <parquet> <out.json>
"""

import hashlib
import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def _digest(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        if a is None:
            h.update(b"<none>")
            continue
        a = np.asarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def main() -> None:
    coord, pid, nproc, path, out_path = sys.argv[1:6]
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(pid),
    )
    assert jax.process_count() == int(nproc), jax.process_count()
    assert len(jax.devices()) == 4 * int(nproc)

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from parquet_floor_tpu import col
    from parquet_floor_tpu.parallel.multihost import read_sharded_global

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("rg",))

    def replicated(x):
        """Fetch the FULL global value onto this host (resharding
        collective — exercises the cross-process layout agreement)."""
        if x is None:
            return None
        full = jax.jit(
            lambda a: a, out_shardings=NamedSharding(mesh, P())
        )(x)
        return np.asarray(full)

    report = {"pid": int(pid)}

    # 1) plain read: strings + optional + int columns, ragged groups
    out = read_sharded_global(path, mesh, float64_policy="float64")
    dig = []
    for name in sorted(out):
        c = out[name]
        dig.append(_digest(
            replicated(c.values), replicated(c.mask),
            replicated(c.lengths), replicated(c.row_mask),
        ))
        report.setdefault("num_rows", {})[name] = c.num_rows
    report["plain"] = _digest(*[d.encode() for d in dig])

    # 2) predicate read: prunes some groups on statistics
    out_p = read_sharded_global(
        path, mesh, predicate=(col("id") >= 2600), float64_policy="float64"
    )
    dig_p = []
    for name in sorted(out_p):
        c = out_p[name]
        dig_p.append(_digest(
            replicated(c.values), replicated(c.mask),
            replicated(c.lengths), replicated(c.row_mask),
        ))
        report.setdefault("num_rows_pred", {})[name] = c.num_rows
    report["pred"] = _digest(*[d.encode() for d in dig_p])

    # 3) ghost case: a predicate no row can satisfy prunes EVERY group;
    # typed ghosts must come back via the schema-meta path, identically
    out_g = read_sharded_global(
        path, mesh, predicate=(col("id") < -1), float64_policy="float64"
    )
    report["ghost"] = _digest(*[
        _digest(replicated(out_g[n].values)).encode() for n in sorted(out_g)
    ])
    report["ghost_rows"] = {n: out_g[n].num_rows for n in sorted(out_g)}
    report["ghost_dtypes"] = {
        n: str(np.asarray(out_g[n].values.addressable_shards[0].data).dtype)
        for n in sorted(out_g)
    }

    # 4) dataset-sharded read: multi-file, UNEVEN groups-per-file —
    # the cross-file global assembly must agree across processes
    # (VERDICT r3 #6: these process_count()>1 branches must execute)
    ds_dir = os.path.join(os.path.dirname(path), "dataset")
    ds_paths = sorted(
        os.path.join(ds_dir, f) for f in os.listdir(ds_dir)
        if f.endswith(".parquet")
    )
    from parquet_floor_tpu.parallel.multihost import read_dataset_sharded

    out_d = read_dataset_sharded(ds_paths, mesh, float64_policy="float64")
    dig_d = []
    for name in sorted(out_d):
        c = out_d[name]
        dig_d.append(_digest(
            replicated(c.values), replicated(c.mask),
            replicated(c.lengths), replicated(c.row_mask),
        ))
        report.setdefault("ds_rows", {})[name] = c.num_rows
    report["dataset"] = _digest(*[d.encode() for d in dig_d])

    # 5) the declarative row stream through the DEVICE engine, executed
    # under process_count() > 1: per-process local decode, identical
    # hydrated rows on every process
    from parquet_floor_tpu import ParquetReader

    class _Rows:
        def start(self):
            return []

        def add(self, t, h, v):
            t.append(v)
            return t

        def finish(self, t):
            return tuple(t)

    h = hashlib.sha256()
    n_rows_stream = 0
    for row in ParquetReader.stream_content(
        path, lambda c: _Rows(), engine="tpu"
    ):
        h.update(repr(row).encode())
        n_rows_stream += 1
    report["tpu_rows"] = h.hexdigest()
    report["tpu_rows_n"] = n_rows_stream

    with open(out_path, "w") as f:
        json.dump(report, f)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
