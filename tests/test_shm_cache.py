"""Cross-process cache tier (serve/shm_cache.py, docs/serving.md).

The laws under test: exact-range keyed hits, two-ring (pinned/data)
eviction accounting, cross-process single-flight (exactly one storage
read per unique range across attached caches), expired-lease takeover,
the copy-out borrow guarantee under churn, and the real-subprocess
attach/stats path."""

import json
import subprocess
import sys
import threading
import time

import pytest

from parquet_floor_tpu.serve import SharedBufferCache, ShmCacheTier
from parquet_floor_tpu.serve.shm_cache import _digest


def small_tier(**kw):
    kw.setdefault("data_bytes", 1 << 16)
    kw.setdefault("meta_bytes", 1 << 14)
    kw.setdefault("slots", 64)
    kw.setdefault("flights", 16)
    return ShmCacheTier.create(**kw)


def test_put_get_exact_range():
    with small_tier() as tier:
        key = ("f", 100)
        tier.put(key, 0, b"hello world")
        assert tier.get(key, 0, 11) == b"hello world"
        # exact-range keying: containment is the L1's job, not this
        # tier's — a sub-range is a miss here
        assert tier.get(key, 0, 5) is None
        assert tier.get(key, 1, 10) is None
        # a different file key never aliases
        assert tier.get(("g", 100), 0, 11) is None


def test_get_returns_an_independent_copy():
    """The borrow law, met by copy-out: churning the ring after a get
    must never mutate the returned bytes."""
    with small_tier() as tier:
        tier.put(("f", 1), 0, b"A" * 600)
        borrowed = tier.get(("f", 1), 0, 600)
        for i in range(400):   # churn far past the ring budget
            tier.put(("e", i), 0, bytes([i % 251]) * 500)
        assert borrowed == b"A" * 600


def test_ring_eviction_counted_and_bounded():
    with small_tier() as tier:
        for i in range(300):
            tier.put(("e", i), 0, bytes(500))
        st = tier.stats()
        assert st["evictions"] > 0
        assert st["data_bytes_used"] <= st["data_bytes"]
        # oldest entries evicted, newest still present
        assert tier.get(("e", 0), 0, 500) is None
        assert tier.get(("e", 299), 0, 500) == bytes(500)


def test_pinned_ring_is_separate():
    """Data churn must never evict pinned metadata (the pinned law)."""
    with small_tier() as tier:
        tier.put(("meta", 1), 0, b"M" * 256, pinned=True)
        for i in range(300):
            tier.put(("e", i), 0, bytes(500))
        assert tier.get(("meta", 1), 0, 256) == b"M" * 256
        st = tier.stats()
        assert st["meta_evictions"] == 0
        # the meta ring has its OWN budget: overflow it and evictions
        # are counted there, not silently
        for i in range(40):
            tier.put(("m", i), 0, bytes(600), pinned=True)
        assert tier.stats()["meta_evictions"] > 0


def test_oversized_entry_serves_through_uncached():
    with small_tier() as tier:
        big = bytes(tier.data_bytes + 64)
        tier.put(("f", 1), 0, big)
        assert tier.get(("f", 1), 0, len(big)) is None


def test_read_through_miss_then_hit():
    with small_tier() as tier:
        calls = []

        def rm(ranges):
            calls.append(list(ranges))
            return [bytes([n % 251]) * n for _, n in ranges]

        out = tier.read_through(("f", 9), [(0, 64), (100, 32)], rm)
        assert [len(b) for b in out] == [64, 32]
        assert calls == [[(0, 64), (100, 32)]]
        out2 = tier.read_through(("f", 9), [(0, 64), (100, 32)], rm)
        assert out2 == out
        assert len(calls) == 1        # second pass fully from the tier
        st = tier.stats()
        assert st["hits"] == 2 and st["misses"] == 2


def test_single_flight_across_attached_caches():
    """Two SharedBufferCaches over one tier model two worker
    processes: a concurrent identical range issues ONE storage read;
    the other side waits and gets the leader's bytes."""
    with small_tier() as tier:
        reads = []
        ev = threading.Event()

        def slow_rm(ranges):
            reads.append(list(ranges))
            ev.set()
            time.sleep(0.05)
            return [bytes(n) for _, n in ranges]

        with SharedBufferCache(data_bytes=1 << 20, shm=tier) as ca, \
                SharedBufferCache(data_bytes=1 << 20, shm=tier) as cb:
            res = {}

            def go(name, c):
                res[name] = bytes(
                    c.fetch_many(("h", 9), [(0, 64)], slow_rm)[0]
                )

            ta = threading.Thread(target=go, args=("a", ca))
            tb = threading.Thread(target=go, args=("b", cb))
            ta.start()
            ev.wait(5)          # the leader is mid-read when b arrives
            tb.start()
            ta.join()
            tb.join()
            assert res["a"] == res["b"] == bytes(64)
            assert len(reads) == 1
            assert tier.stats()["singleflight_waits"] >= 1


def test_failed_leader_lets_a_waiter_relead():
    """A leader whose storage read raises clears its flight; the waiter
    takes over and re-issues (the cross-process analogue of error
    propagation)."""
    with small_tier() as tier:
        state = {"calls": 0}
        started = threading.Event()

        def flaky_rm(ranges):
            state["calls"] += 1
            started.set()
            if state["calls"] == 1:
                time.sleep(0.02)
                raise OSError("transient storage failure")
            return [bytes(n) for _, n in ranges]

        results = {}

        def lead():
            try:
                tier.read_through(("f", 5), [(0, 32)], flaky_rm)
            except OSError as e:
                results["lead"] = str(e)

        def wait():
            results["wait"] = tier.read_through(("f", 5), [(0, 32)],
                                                flaky_rm)[0]

        tl = threading.Thread(target=lead)
        tw = threading.Thread(target=wait)
        tl.start()
        started.wait(5)
        tw.start()
        tl.join()
        tw.join()
        assert results["lead"] == "transient storage failure"
        assert results["wait"] == bytes(32)
        assert state["calls"] == 2
        assert tier.stats()["takeovers"] >= 1


def test_expired_lease_takeover():
    """A dead leader (lease expiry, nothing ever installed) must not
    wedge waiters: they claim the flight and lead themselves."""
    with small_tier(lease_s=0.05) as tier:
        d = _digest(("f", 7), 0, 16)
        with tier._locked():
            assert tier._flight_check(*d, claim=True) is False  # claimed

        def rm(ranges):
            return [bytes(n) for _, n in ranges]

        t0 = time.perf_counter()
        out = tier.read_through(("f", 7), [(0, 16)], rm)
        assert out[0] == bytes(16)
        assert time.perf_counter() - t0 < 5.0
        assert tier.stats()["takeovers"] == 1


def test_duplicate_ranges_one_call_single_read():
    with small_tier() as tier:
        calls = []

        def rm(ranges):
            calls.append(list(ranges))
            return [bytes(n) for _, n in ranges]

        out = tier.read_through(("f", 2), [(0, 8), (0, 8), (0, 8)], rm)
        assert [bytes(b) for b in out] == [bytes(8)] * 3
        assert calls == [[(0, 8)]]


def test_l1_pinned_put_lands_in_meta_ring():
    with small_tier() as tier:
        with SharedBufferCache(data_bytes=1 << 20, shm=tier) as cache:
            cache.fetch_many(
                ("f", 3), [(0, 128)],
                lambda rs: [bytes(n) for _, n in rs], pinned=True,
            )
        st = tier.stats()
        assert st["meta_bytes_used"] > 0
        assert st["data_bytes_used"] == 0


def test_attach_validates_magic():
    from multiprocessing import shared_memory

    seg = shared_memory.SharedMemory(create=True, size=4096)
    try:
        seg.buf[:8] = b"notatier"
        with pytest.raises(ValueError, match="not a ShmCacheTier"):
            with ShmCacheTier.attach(seg.name):
                pass
    finally:
        seg.close()
        seg.unlink()


def test_closed_tier_refuses():
    tier = small_tier()
    tier.close()
    tier.close()     # idempotent
    with pytest.raises(ValueError, match="closed"):
        tier.get(("f", 1), 0, 4)


def test_real_subprocess_shares_the_segment():
    """An actual second OS process attaches by name, reads what we
    wrote, writes back, and its traffic lands in the shared header
    stats."""
    with small_tier() as tier:
        tier.put(("x", 1), 0, b"parent-bytes")
        code = (
            "import sys, json\n"
            "sys.path.insert(0, %r)\n"
            "from parquet_floor_tpu.serve import ShmCacheTier\n"
            "tier = ShmCacheTier.attach(%r)\n"
            "try:\n"
            "    got = tier.get(('x', 1), 0, 12)\n"
            "    assert got == b'parent-bytes', got\n"
            "    tier.put(('x', 2), 0, b'child-bytes!')\n"
            "finally:\n"
            "    tier.close()\n"
            "print('CHILD_OK')\n"
        ) % (str(__import__("pathlib").Path(__file__).parent.parent),
             tier.name)
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            timeout=60,
        )
        assert out.returncode == 0, out.stderr.decode()
        assert b"CHILD_OK" in out.stdout
        # the child's detach did NOT unlink the segment under us
        assert tier.get(("x", 2), 0, 12) == b"child-bytes!"
        st = tier.stats()
        assert st["hits"] >= 2    # child's hit + ours, one shared ledger


def test_worker_json_result_shape():
    """The serve_worker result contract the smoke/bench drivers parse
    (probes/rows/wall/ranges/counters/shm_stats)."""
    import os
    import pathlib
    import tempfile

    import numpy as np

    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types

    schema = types.message(
        "t", types.required(types.INT64).named("k"),
    )
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "f.parquet")
        with ParquetFileWriter(path, schema, WriterOptions(
            row_group_rows=64, data_page_values=16,
            bloom_filter_columns={"k": True},
        )) as w:
            w.write_columns({"k": 2 * np.arange(128, dtype=np.int64)})
        with small_tier(data_bytes=1 << 20) as tier:
            cfg = {
                "mode": "flight", "shm": tier.name, "paths": [path],
                "keys": [0, 64, 128], "columns": ["k"], "tenant": "t0",
            }
            cfg_path = os.path.join(tmp, "cfg.json")
            pathlib.Path(cfg_path).write_text(json.dumps(cfg))
            script = str(
                pathlib.Path(__file__).parent.parent / "scripts"
                / "serve_worker.py"
            )
            out = subprocess.run(
                [sys.executable, script, cfg_path],
                capture_output=True, timeout=120,
            )
            assert out.returncode == 0, out.stderr.decode()
            res = json.loads(out.stdout.decode().splitlines()[-1])
            assert res["probes"] == 3 and res["rows"] == 3
            assert res["ranges"], "worker recorded no storage reads"
            assert res["counters"].get("serve.lookup_probes") == 3
            assert res["shm_stats"]["misses"] > 0
