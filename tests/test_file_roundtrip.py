"""End-to-end file round-trips through our own writer+reader across codecs,
page versions, encodings, and null patterns."""

import numpy as np
import pytest

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn

rng = np.random.default_rng(11)


def flat_schema():
    return types.message(
        "test",
        types.required(types.INT64).named("id"),
        types.optional(types.DOUBLE).named("score"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("name"),
        types.optional(types.INT32).named("count"),
        types.required(types.BOOLEAN).named("flag"),
        types.required(types.FLOAT).named("ratio"),
    )


def sample_columns(n=1000):
    return {
        "id": np.arange(n, dtype=np.int64),
        "score": [float(i) / 3 if i % 5 else None for i in range(n)],
        "name": [f"user_{i % 100}" for i in range(n)],
        "count": [i % 7 if i % 3 else None for i in range(n)],
        "flag": (np.arange(n) % 2 == 0),
        "ratio": rng.standard_normal(n).astype(np.float32),
    }


def roundtrip(tmp_path, options, n=1000, row_groups=1):
    path = tmp_path / "t.parquet"
    schema = flat_schema()
    cols = sample_columns(n)
    with ParquetFileWriter(path, schema, options) as w:
        for _ in range(row_groups):
            w.write_columns(cols)
    with ParquetFileReader(path) as r:
        assert r.record_count == n * row_groups
        assert len(r.row_groups) == row_groups
        for gi in range(row_groups):
            batch = r.read_row_group(gi)
            assert batch.num_rows == n
            by_name = {b.descriptor.path[0]: b for b in batch.columns}
            np.testing.assert_array_equal(by_name["id"].values, cols["id"])
            np.testing.assert_array_equal(by_name["flag"].values, cols["flag"])
            np.testing.assert_array_equal(by_name["ratio"].values, cols["ratio"])
            # optional double with nulls
            score = by_name["score"]
            expected_vals = [v for v in cols["score"] if v is not None]
            np.testing.assert_allclose(score.values, expected_vals)
            mask = score.null_mask
            assert mask is not None
            np.testing.assert_array_equal(
                mask, np.array([v is None for v in cols["score"]])
            )
            # strings
            name = by_name["name"]
            assert name.values.to_list() == [s.encode() for s in cols["name"]]
        return r.metadata


@pytest.mark.parametrize(
    "codec",
    [
        CompressionCodec.UNCOMPRESSED,
        CompressionCodec.SNAPPY,
        CompressionCodec.GZIP,
        CompressionCodec.ZSTD,
        CompressionCodec.LZ4_RAW,
    ],
)
def test_roundtrip_codecs(tmp_path, codec):
    roundtrip(tmp_path, WriterOptions(codec=codec))


@pytest.mark.parametrize("version", [1, 2])
def test_roundtrip_page_versions(tmp_path, version):
    roundtrip(tmp_path, WriterOptions(page_version=version))


def test_roundtrip_no_dictionary(tmp_path):
    roundtrip(tmp_path, WriterOptions(enable_dictionary=False))


def test_roundtrip_delta_integers(tmp_path):
    roundtrip(tmp_path, WriterOptions(enable_dictionary=False, delta_integers=True))


def test_roundtrip_byte_stream_split(tmp_path):
    roundtrip(
        tmp_path,
        WriterOptions(enable_dictionary=False, byte_stream_split_floats=True),
    )


def test_roundtrip_multiple_row_groups_and_pages(tmp_path):
    roundtrip(tmp_path, WriterOptions(data_page_values=100), n=1000, row_groups=3)


def test_roundtrip_crc_verification(tmp_path):
    path = tmp_path / "t.parquet"
    schema = flat_schema()
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns(sample_columns(100))
    with ParquetFileReader(path, verify_crc=True) as r:
        r.read_row_group(0)


def test_metadata_surface(tmp_path):
    meta = roundtrip(tmp_path, WriterOptions())
    assert meta.created_by and "parquet-floor-tpu" in meta.created_by
    assert meta.schema.is_flat
    rg = meta.row_groups[0]
    id_chunk = rg.columns[0]
    assert id_chunk.meta_data.path_in_schema == ["id"]
    st = id_chunk.meta_data.statistics
    assert st.null_count == 0
    assert int.from_bytes(st.min_value, "little") == 0
    assert int.from_bytes(st.max_value, "little") == 999


def test_key_value_metadata(tmp_path):
    path = tmp_path / "kv.parquet"
    schema = types.message("m", types.required(types.INT32).named("x"))
    with ParquetFileWriter(
        path, schema, key_value_metadata={"origin": "unit-test"}
    ) as w:
        w.write_columns({"x": np.array([1, 2, 3], dtype=np.int32)})
    with ParquetFileReader(path) as r:
        assert r.metadata.key_value_metadata["origin"] == "unit-test"


def test_all_null_column(tmp_path):
    path = tmp_path / "nulls.parquet"
    schema = types.message("m", types.optional(types.INT64).named("x"))
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"x": [None] * 50})
    with ParquetFileReader(path) as r:
        batch = r.read_row_group(0)
        col = batch.columns[0]
        assert col.num_values == 50
        assert len(col.values) == 0
        assert np.all(col.null_mask)


def test_empty_strings_and_large_values(tmp_path):
    path = tmp_path / "strs.parquet"
    schema = types.message("m", types.required(types.BYTE_ARRAY).named("b"))
    values = [b"", b"\x00" * 3, bytes(rng.integers(0, 256, 70000).astype(np.uint8)), b"end"]
    with ParquetFileWriter(path, schema, WriterOptions(enable_dictionary=False)) as w:
        w.write_columns({"b": ByteArrayColumn.from_list(values)})
    with ParquetFileReader(path) as r:
        col = r.read_row_group(0).columns[0]
        assert col.values.to_list() == values


def test_zero_row_row_group(tmp_path):
    """Regression: empty row groups written by our writer must read back."""
    path = tmp_path / "zero.parquet"
    schema = types.message("m", types.required(types.INT64).named("a"))
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"a": np.array([], dtype=np.int64)})
    with ParquetFileReader(path) as r:
        batch = r.read_row_group(0)
        assert batch.num_rows == 0
        assert len(batch.columns[0].values) == 0


def test_writer_exception_releases_file(tmp_path):
    """Regression: an exception mid-write must close the sink (no fd leak,
    no footer over partial data)."""
    path = tmp_path / "partial.parquet"
    schema = types.message("m", types.required(types.INT64).named("a"))
    with pytest.raises(ValueError):
        with ParquetFileWriter(path, schema) as w:
            w.write_columns({"a": np.array([1, 2], dtype=np.int64)})
            raise ValueError("boom")
    assert w.sink._fh.closed if w.sink._own else True
    # the truncated file must not parse as valid parquet
    with pytest.raises(ValueError):
        ParquetFileReader(path)


def test_corrupt_rle_stream_raises_valueerror(tmp_path):
    from parquet_floor_tpu.format.encodings import rle_hybrid as rle

    # header promises more values than the stream carries
    good = rle.encode_rle_hybrid(np.ones(100, dtype=np.uint32), 1)
    with pytest.raises(ValueError):
        rle.decode_rle_hybrid(good[: len(good) // 2], 1000, 1)


def test_truncated_plain_page_raises(tmp_path):
    from parquet_floor_tpu.format.encodings import plain as e_plain
    from parquet_floor_tpu.format.parquet_thrift import Type as _T

    with pytest.raises(ValueError, match="truncated"):
        e_plain.decode_plain(b"\x01\x02", 100, _T.INT64)


def test_delta_byte_array_write(tmp_path):
    """delta_strings option: v2 non-dict strings write as DELTA_BYTE_ARRAY
    (parquet-mr's PARQUET_2_0 behavior); pyarrow and our readers agree."""
    import numpy as np
    import pyarrow.parquet as pq
    from parquet_floor_tpu import (
        Encoding, ParquetFileReader, ParquetFileWriter, WriterOptions, types,
    )

    rng = np.random.default_rng(83)
    vals = [f"prefix-common-{int(v):05d}-suffix" for v in rng.integers(0, 10_000, 4000)]
    opt = [None if rng.random() < 0.2 else v for v in vals]
    schema = types.message(
        "t",
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("o"),
    )
    path = str(tmp_path / "dba.parquet")
    with ParquetFileWriter(
        path, schema,
        WriterOptions(enable_dictionary=False, delta_strings=True,
                      page_version=2, data_page_values=700),
    ) as w:
        w.write_columns({"s": vals, "o": opt})
    t = pq.read_table(path)
    assert t.column("s").to_pylist() == vals
    assert t.column("o").to_pylist() == opt
    with ParquetFileReader(path) as r:
        meta = r.row_groups[0].columns[0].meta_data
        assert Encoding.DELTA_BYTE_ARRAY in meta.encodings
        b = r.read_row_group(0)
        assert b.column("s").values.to_list() == [v.encode() for v in vals]
    # TPU engine host-fallback path still decodes correctly
    import jax
    jax.config.update("jax_enable_x64", True)
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    with TpuRowGroupReader(path) as tr:
        dc = tr.read_row_group(0)["s"]
        rows = np.asarray(dc.values); lens = np.asarray(dc.lengths)
        assert rows[0, : lens[0]].tobytes().decode() == vals[0]


def test_boundary_order_and_sorting_columns(tmp_path):
    """ColumnIndex boundary_order is computed by the column's SORT order
    (readers can binary-search); WriterOptions.sorting_columns records
    the declared order in every row group (parquet-mr's
    withSortingColumns — pyarrow surfaces it back)."""
    import numpy as np
    import pyarrow.parquet as pq
    import pytest
    from parquet_floor_tpu import (
        ParquetFileReader, ParquetFileWriter, WriterOptions, types,
    )

    schema = types.message(
        "t",
        types.required(types.INT64).named("asc"),
        types.required(types.INT64).named("desc_"),
        types.required(types.INT64).named("mixed"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    n = 4000
    path = str(tmp_path / "bo.parquet")
    rng = np.random.default_rng(3)
    with ParquetFileWriter(
        path, schema,
        WriterOptions(
            data_page_values=500, enable_dictionary=False,
            sorting_columns=["asc", ("desc_", True, False)],
        ),
    ) as w:
        w.write_columns({
            # asc crosses a sign boundary: byte-lex would call the LE
            # encodings unordered/misordered; value order is ascending
            "asc": np.arange(-n // 2, n // 2, dtype=np.int64),
            "desc_": np.arange(n, 0, -1, dtype=np.int64),
            "mixed": rng.integers(-1000, 1000, n).astype(np.int64),
            "s": [f"k{i:06d}" for i in range(n)],
        })
    with ParquetFileReader(path) as r:
        rg = r.row_groups[0]
        by = {
            tuple(c.meta_data.path_in_schema)[0]: r.read_column_index(c)
            for c in rg.columns
        }
        assert by["asc"].boundary_order == 1      # value-order ascending
        assert by["desc_"].boundary_order == 2
        assert by["mixed"].boundary_order == 0
        assert by["s"].boundary_order == 1        # lex ascending
    # order-altering logical types always report UNORDERED (an unsigned
    # column ascending by BYTE pattern may be unordered by VALUE)
    schema_u = types.message(
        "t",
        types.required(types.INT64).as_(
            types.int_(64, signed=False)
        ).named("u"),
    )
    pu = str(tmp_path / "uns.parquet")
    with ParquetFileWriter(
        pu, schema_u,
        WriterOptions(data_page_values=500, enable_dictionary=False),
    ) as w:
        w.write_columns({
            "u": np.concatenate([
                (np.arange(500, dtype=np.uint64) + np.uint64(1 << 63))
                .view(np.int64),
                np.arange(1, 501, dtype=np.int64),
            ])
        })
    with ParquetFileReader(pu) as r:
        ci_u = r.read_column_index(r.row_groups[0].columns[0])
        assert ci_u.boundary_order == 0
        sc = rg.sorting_columns
        assert [s.column_idx for s in sc] == [0, 1]
        assert [bool(s.descending) for s in sc] == [False, True]
    # pyarrow surfaces the declared order
    md = pq.read_metadata(path)
    srt = md.row_group(0).sorting_columns
    assert [s.column_index for s in srt] == [0, 1]
    assert [s.descending for s in srt] == [False, True]
    # unknown sort column fails fast
    with pytest.raises(ValueError, match="no column named"):
        # ctor raises pre-ownership and closes its own sink (pinned by
        # test_ctor_failure_closes_sink)
        ParquetFileWriter(  # floorlint: disable=FL-RES001
            str(tmp_path / "bad.parquet"), schema,
            WriterOptions(sorting_columns=["zz"]),
        )


def test_codec_level_knob(tmp_path):
    """WriterOptions.codec_level: level-aware codecs honor it (higher
    ZSTD/GZIP levels compress more), level-less codecs ignore it, and
    every readable result stays byte-identical on read."""
    import numpy as np
    import pyarrow.parquet as pq
    from parquet_floor_tpu import (
        CompressionCodec, ParquetFileWriter, WriterOptions, types,
    )

    rng = np.random.default_rng(7)
    # compressible: low-entropy text
    vals = [f"record-{int(v) % 50:06d}-payload" for v in rng.integers(0, 50, 5000)]
    schema = types.message(
        "t", types.required(types.BYTE_ARRAY).as_(types.string()).named("s")
    )

    def write(codec, level):
        p = str(tmp_path / f"lv_{codec}_{level}.parquet")
        with ParquetFileWriter(
            p, schema,
            WriterOptions(codec=codec, codec_level=level,
                          enable_dictionary=False),
        ) as w:
            w.write_columns({"s": vals})
        assert pq.read_table(p).column("s").to_pylist() == vals
        import os

        return os.path.getsize(p)

    try:
        import zstandard  # noqa: F401

        # levels change the output (zstd sizes are NOT monotonic in
        # level on synthetic data — only assert the knob takes effect)
        assert write(CompressionCodec.ZSTD, 1) != write(
            CompressionCodec.ZSTD, 19
        )
    except ImportError:
        pass
    g_fast = write(CompressionCodec.GZIP, 1)
    g_slow = write(CompressionCodec.GZIP, 9)
    assert g_slow < g_fast  # deflate IS monotonic here
    # level-less codec: level is ignored, not an error
    write(CompressionCodec.SNAPPY, 9)
    # out-of-range levels fail at CONSTRUCTION, before bytes hit the sink
    import pytest
    from parquet_floor_tpu import ParquetFileWriter as PFW
    with pytest.raises(ValueError, match="out of range"):
        PFW(str(tmp_path / "bad.parquet"), schema,
            WriterOptions(codec=CompressionCodec.GZIP, codec_level=12))
    # GZIP level 0 is stored-mode deflate (no compression) — rejected
    # like parquet-mr's 1..9 range, so nothing silently writes
    # uncompressed bytes under CompressionCodec.GZIP (ADVICE r4)
    with pytest.raises(ValueError, match="out of range"):
        PFW(str(tmp_path / "bad0.parquet"), schema,
            WriterOptions(codec=CompressionCodec.GZIP, codec_level=0))
    # a register_codec override wins over the level fast path
    from parquet_floor_tpu.format import codecs as _codecs
    calls = []

    def plugin(data):
        calls.append(len(data))
        return _codecs._gzip_compress(data)

    orig = _codecs._COMPRESSORS[CompressionCodec.GZIP]
    try:
        _codecs.register_codec(CompressionCodec.GZIP, compressor=plugin)
        out = _codecs.compress(CompressionCodec.GZIP, b"x" * 100, level=5)
        assert calls and _codecs.decompress(
            CompressionCodec.GZIP, out, 100
        ) == b"x" * 100
    finally:
        _codecs.register_codec(CompressionCodec.GZIP, compressor=orig)


def test_binary_stats_truncation(tmp_path):
    """Long BYTE_ARRAY min/max truncate with parquet-mr semantics: the
    ColumnIndex bounds cap at column_index_truncate_length (64 default)
    with min a prefix and max prefix+increment — still valid bounds, so
    predicate pruning stays correct; chunk stats truncate only when
    statistics_truncate_length is set."""
    import pytest
    from parquet_floor_tpu import (
        ParquetFileReader, ParquetFileWriter, WriterOptions, col, types,
    )
    from parquet_floor_tpu.format.file_write import _truncate_min_max

    long_lo = "a" * 200
    long_hi = "z" * 200
    vals = [long_lo + f"{i:04d}" for i in range(100)] + [long_hi]
    schema = types.message(
        "t", types.required(types.BYTE_ARRAY).as_(types.string()).named("s")
    )
    path = str(tmp_path / "trunc.parquet")
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"s": vals})
    with ParquetFileReader(path) as r:
        chunk = r.row_groups[0].columns[0]
        ci = r.read_column_index(chunk)
        assert all(len(m) <= 64 for m in ci.min_values)
        assert all(len(m) <= 65 for m in ci.max_values)
        assert ci.min_values[0] == long_lo.encode()[:64]
        # max: prefix with last byte incremented → still an upper bound
        assert ci.max_values[-1] > long_hi.encode()[:64]
        # chunk stats stay whole by default (parquet-mr 1.12)
        st = chunk.meta_data.statistics
        assert st.min_value == vals[0].encode()
        # truncated bounds still bound: pruning keeps the group for a
        # present value and drops it for an impossible one
        keep = (col("s") == vals[5]).row_groups(r)
        assert 0 in set(keep)
        none = (col("s") == "~~~~").row_groups(r)  # above every max
        assert 0 not in set(none)
    # statistics_truncate_length bounds chunk stats too
    path2 = str(tmp_path / "trunc2.parquet")
    with ParquetFileWriter(
        path2, schema, WriterOptions(statistics_truncate_length=16)
    ) as w:
        w.write_columns({"s": vals})
    with ParquetFileReader(path2) as r:
        st = r.row_groups[0].columns[0].meta_data.statistics
        assert len(st.min_value) <= 16 and len(st.max_value) <= 17
        assert st.min_value <= vals[0].encode()
        assert st.max_value >= vals[-1].encode()
    # all-0xFF prefixes cannot increment: the full max survives
    schema_b = types.message(
        "t", types.required(types.BYTE_ARRAY).named("b")
    )
    desc = None
    with ParquetFileWriter(str(tmp_path / "ff.parquet"), schema_b) as w:
        desc = w.schema.columns[0]
    mm = _truncate_min_max(desc, (b"\x01" * 100, b"\xff" * 100), 8)
    assert mm[0] == b"\x01" * 8
    assert mm[1] == b"\xff" * 100  # kept whole
    # None limit / None mm pass through untouched
    assert _truncate_min_max(desc, (b"a" * 99, b"b" * 99), None) == (
        b"a" * 99, b"b" * 99
    )
    assert _truncate_min_max(desc, None, 8) is None


def test_per_column_encoding_overrides(tmp_path):
    """WriterOptions.column_encodings / column_dictionary: per-column
    control (parquet-mr's per-path builder config; pyarrow's
    column_encoding).  Naming a column in column_encodings disables its
    dictionary attempt; pyarrow and both engines read the result."""
    import numpy as np
    import pyarrow.parquet as pq
    import pytest
    from parquet_floor_tpu import (
        Encoding, ParquetFileReader, ParquetFileWriter, WriterOptions, types,
    )

    rng = np.random.default_rng(97)
    n = 3000
    data = {
        "a": rng.integers(-1000, 1000, n).astype(np.int64),
        "b": rng.standard_normal(n).astype(np.float32),
        "s": [f"v{int(x) % 10}" for x in rng.integers(0, 10, n)],
        "c": (np.arange(n) % 7).astype(np.int32),
    }
    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.required(types.FLOAT).named("b"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.INT32).named("c"),
    )
    path = str(tmp_path / "enc.parquet")
    with ParquetFileWriter(
        path, schema,
        WriterOptions(
            page_version=2,
            column_encodings={
                "a": "DELTA_BINARY_PACKED",
                "b": Encoding.BYTE_STREAM_SPLIT,
                "s": "DELTA_BYTE_ARRAY",
            },
            # low-cardinality c stays dictionary; s would dictionary-
            # encode but its explicit encoding turns that off
            column_dictionary={"a": True},  # ignored: encoding named
        ),
    ) as w:
        w.write_columns(data)

    with ParquetFileReader(path) as r:
        by = {
            tuple(c.meta_data.path_in_schema)[0]: c.meta_data
            for c in r.row_groups[0].columns
        }
        assert Encoding.DELTA_BINARY_PACKED in by["a"].encodings
        assert Encoding.BYTE_STREAM_SPLIT in by["b"].encodings
        assert Encoding.DELTA_BYTE_ARRAY in by["s"].encodings
        assert Encoding.RLE_DICTIONARY in by["c"].encodings
    t = pq.read_table(path)
    assert t.column("a").to_pylist() == data["a"].tolist()
    assert t.column("s").to_pylist() == data["s"]
    # per-column dictionary disable without an explicit encoding
    path2 = str(tmp_path / "nodict.parquet")
    with ParquetFileWriter(
        path2, schema, WriterOptions(column_dictionary={"c": False})
    ) as w:
        w.write_columns(data)
    with ParquetFileReader(path2) as r:
        by = {
            tuple(c.meta_data.path_in_schema)[0]: c.meta_data
            for c in r.row_groups[0].columns
        }
        assert Encoding.RLE_DICTIONARY not in by["c"].encodings
        assert Encoding.RLE_DICTIONARY in by["s"].encodings  # others keep it
    # validation fails fast, before any bytes hit the sink
    with pytest.raises(ValueError, match="no column named"):
        ParquetFileWriter(  # floorlint: disable=FL-RES001 — ctor self-closes
            str(tmp_path / "x1.parquet"), schema,
            WriterOptions(column_encodings={"zz": "PLAIN"}),
        )
    with pytest.raises(ValueError, match="does not apply"):
        ParquetFileWriter(  # floorlint: disable=FL-RES001 — ctor self-closes
            str(tmp_path / "x2.parquet"), schema,
            WriterOptions(column_encodings={"s": "DELTA_BINARY_PACKED"}),
        )
    with pytest.raises(ValueError, match="unknown encoding"):
        ParquetFileWriter(  # floorlint: disable=FL-RES001 — ctor self-closes
            str(tmp_path / "x3.parquet"), schema,
            WriterOptions(column_encodings={"a": "RLE_HYBRID"}),
        )
    # TPU engine reads the override file bit-exact
    import jax
    jax.config.update("jax_enable_x64", True)
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    with TpuRowGroupReader(path, float64_policy="float64") as tr:
        g = tr.read_row_group(0)
        np.testing.assert_array_equal(np.asarray(g["a"].values), data["a"])
        np.testing.assert_array_equal(np.asarray(g["b"].values), data["b"])
        np.testing.assert_array_equal(np.asarray(g["c"].values), data["c"])


def test_byte_based_page_and_group_thresholds(tmp_path):
    """parquet-mr-style size tunables: data_page_bytes closes pages by
    estimated size (composed with the count bound) and row_group_bytes
    flushes the row-at-a-time writer by buffered estimate."""
    from parquet_floor_tpu import ParquetWriter
    from parquet_floor_tpu.api.hydrate import FnDehydrator

    t = types
    schema = t.message(
        "t",
        t.required(t.INT64).named("i"),
        t.required(t.BYTE_ARRAY).as_(t.string()).named("s"),
    )
    n = 4000
    # ~102 B/row estimate → groups of ~1000 rows at 100 KiB, pages of
    # ~40 rows at 4 KiB
    path = str(tmp_path / "bytes.parquet")
    opts = WriterOptions(
        enable_dictionary=False,
        data_page_bytes=1 << 12,
        row_group_bytes=100 << 10,
    )
    rows = [(i, "x" * 90) for i in range(n)]
    ParquetWriter.write_file(
        schema, path,
        FnDehydrator(lambda r, w: (w.write("i", r[0]), w.write("s", r[1]))),
        rows, options=opts,
    )
    with ParquetFileReader(path) as r:
        groups = r.row_groups
        assert len(groups) > 1, "row_group_bytes must split groups"
        # every group's total uncompressed size respects the ballpark
        for rg in groups[:-1]:
            assert (rg.num_rows or 0) < n
        # pages: OffsetIndex shows multiple pages per chunk
        oi = r.read_offset_index(groups[0].columns[1])
        assert oi is not None and len(oi.page_locations) > 1
        batch = r.read_row_group(0)
        assert batch.column("s").cell(0) == b"x" * 90
    # full-content check via the host reader
    total = 0
    with ParquetFileReader(path) as r:
        for gi in range(len(r.row_groups)):
            total += r.read_row_group(gi).num_rows
    assert total == n


def test_write_numpy_string_array_column(tmp_path):
    """Regression (round 5): a numpy array of strings through the
    BYTE_ARRAY coercion path — the fast-path guard must type-check
    BEFORE truthiness ('if items' on an ndarray raises the ambiguous
    truth-value error)."""
    import numpy as np
    import pyarrow.parquet as pq

    from parquet_floor_tpu import ParquetFileWriter, WriterOptions, types

    vals = np.array(["alpha", "beta", "gamma", "delta"] * 50)
    schema = types.message(
        "m", types.required(types.BYTE_ARRAY).as_(types.string()).named("s")
    )
    p = str(tmp_path / "npstr.parquet")
    with ParquetFileWriter(p, schema, WriterOptions()) as w:
        w.write_columns({"s": vals})
    assert pq.read_table(p).column("s").to_pylist() == vals.tolist()
