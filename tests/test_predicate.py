"""Row-group statistics pushdown: conservative skipping, never false
negatives (every group containing a matching row must be kept)."""

import numpy as np
import pytest

from parquet_floor_tpu import ParquetFileReader, ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.batch.predicate import col


@pytest.fixture(scope="module")
def filt_file(tmp_path_factory):
    """4 row groups: x in [0..99], [100..199], [200..299], [300..399];
    s = 'g{group}'; y optional, all-null in group 2."""
    path = tmp_path_factory.mktemp("pred") / "p.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.optional(types.DOUBLE).named("y"),
    )
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(4):
            xs = np.arange(g * 100, g * 100 + 100, dtype=np.int64)
            ys = [None] * 100 if g == 2 else [float(v) for v in xs]
            w.write_columns({"x": xs, "s": [f"g{g}"] * 100, "y": ys})
    return str(path)


def _groups(path, pred):
    with ParquetFileReader(path) as r:
        return pred.row_groups(r)


def test_range_pushdown(filt_file):
    assert _groups(filt_file, col("x") < 100) == [0]
    assert _groups(filt_file, col("x") >= 300) == [3]
    assert _groups(filt_file, col("x") == 150) == [1]
    assert _groups(filt_file, (col("x") >= 150) & (col("x") < 250)) == [1, 2]
    assert _groups(filt_file, (col("x") < 50) | (col("x") > 350)) == [0, 3]
    assert _groups(filt_file, col("x") > 1000) == []
    assert _groups(filt_file, col("x") <= 0) == [0]


def test_string_pushdown(filt_file):
    assert _groups(filt_file, col("s") == "g2") == [2]
    assert _groups(filt_file, col("s") >= "g3") == [3]
    # != on a constant-value group rules it out
    assert _groups(filt_file, col("s") != "g1") == [0, 2, 3]


def test_null_pushdown(filt_file):
    assert _groups(filt_file, col("y").is_null()) == [2]
    assert _groups(filt_file, col("y").is_not_null()) == [0, 1, 3]


def test_unknown_column_keeps_all(filt_file):
    assert _groups(filt_file, col("nope") > 1) == [0, 1, 2, 3]


def test_no_false_negatives_random(filt_file):
    """Property: every group that truly contains a match is kept."""
    rng = np.random.default_rng(3)
    with ParquetFileReader(filt_file) as r:
        truth = []
        for gi in range(4):
            xs = r.read_row_group(gi).column("x").values
            truth.append(np.asarray(xs))
        for _ in range(50):
            v = int(rng.integers(-50, 450))
            for pred, fn in [
                (col("x") > v, lambda a: (a > v).any()),
                (col("x") <= v, lambda a: (a <= v).any()),
                (col("x") == v, lambda a: (a == v).any()),
            ]:
                keep = set(pred.row_groups(r))
                for gi, xs in enumerate(truth):
                    if fn(xs):
                        assert gi in keep, (v, pred)


def test_pyarrow_written_stats(tmp_path):
    """Stats written by pyarrow (truncated/exact) drive the same pushdown."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "pa.parquet")
    t = pa.table({"a": list(range(1000))})
    pq.write_table(t, path, row_group_size=250)
    assert _groups(path, col("a") < 250) == [0]
    assert _groups(path, col("a") >= 750) == [3]


# ------------------------------------------------------- page-level indexes

def test_page_index_roundtrip(tmp_path):
    """Writer emits ColumnIndex/OffsetIndex; reader parses them; pyarrow
    sees the same page statistics."""
    import pyarrow.parquet as pq

    schema = types.message("t", types.required(types.INT64).named("x"))
    path = str(tmp_path / "pi.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=100)
    ) as w:
        w.write_columns({"x": np.arange(1000, dtype=np.int64)})
    with ParquetFileReader(path) as r:
        chunk = r.row_groups[0].columns[0]
        ci = r.read_column_index(chunk)
        oi = r.read_offset_index(chunk)
    assert ci is not None and oi is not None
    assert len(oi.page_locations) == 10
    assert [pl.first_row_index for pl in oi.page_locations] == list(range(0, 1000, 100))
    assert ci.null_pages == [False] * 10
    assert ci.null_counts == [0] * 10
    # pyarrow recognizes the indexes we wrote
    md = pq.read_metadata(path)
    pa_col = md.row_group(0).column(0)
    assert pa_col.has_column_index and pa_col.has_offset_index


def test_page_level_row_ranges(tmp_path):
    """row_ranges prunes within a row group using the page index."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.optional(types.INT64).named("y"),
    )
    path = str(tmp_path / "rr.parquet")
    ys = [None if (i // 100) == 3 else int(i) for i in range(1000)]
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=100)
    ) as w:
        w.write_columns({"x": np.arange(1000, dtype=np.int64), "y": ys})
    with ParquetFileReader(path) as r:
        # x in [250, 449] → pages 2,3,4 → rows [200, 500)
        pred = (col("x") >= 250) & (col("x") < 450)
        assert pred.row_ranges(r, 0) == [(200, 500)]
        # equality in one page
        assert (col("x") == 42).row_ranges(r, 0) == [(0, 100)]
        # OR merges
        assert ((col("x") < 50) | (col("x") >= 950)).row_ranges(r, 0) == [
            (0, 100), (900, 1000),
        ]
        # no match → empty
        assert (col("x") > 10_000).row_ranges(r, 0) == []
        # all-null page excluded for comparisons, included for is_null
        assert (col("y") == 310).row_ranges(r, 0) == []
        assert (300, 400) in [
            tuple(t_) for t_ in col("y").is_null().row_ranges(r, 0)
        ]
        # column without index (unknown) keeps whole group
        assert (col("zz") > 1).row_ranges(r, 0) == [(0, 1000)]


def test_pyarrow_reads_our_page_index(tmp_path):
    """pyarrow reads files carrying our page indexes (no corruption) and
    its metadata reports both indexes present for the chunk."""
    import pyarrow.parquet as pq

    schema = types.message("t", types.required(types.INT32).named("v"))
    path = str(tmp_path / "pa.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=50)
    ) as w:
        w.write_columns({"v": np.arange(200, dtype=np.int32)})
    t = pq.read_table(path)
    assert t.column("v").to_pylist() == list(range(200))
    pa_col = pq.read_metadata(path).row_group(0).column(0)
    assert pa_col.has_column_index and pa_col.has_offset_index


def test_ne_keeps_null_pages(tmp_path):
    """'!=' must keep all-null pages at page level (nulls count as
    matching under the chunk-level convention)."""
    schema = types.message("t", types.optional(types.INT64).named("y"))
    path = str(tmp_path / "ne.parquet")
    ys = [None if (i // 100) == 3 else int(i) for i in range(1000)]
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=100)) as w:
        w.write_columns({"y": ys})
    with ParquetFileReader(path) as r:
        ranges = (col("y") != 5).row_ranges(r, 0)
        assert any(a <= 300 and 400 <= b for a, b in ranges), ranges


def test_all_nan_page_drops_column_index(tmp_path):
    """A non-null page with no valid bounds (all NaN) must suppress the
    chunk's ColumnIndex (spec: non-null pages carry valid bounds); the
    OffsetIndex survives."""
    schema = types.message("t", types.required(types.DOUBLE).named("v"))
    path = str(tmp_path / "nan.parquet")
    vals = [1.0] * 100 + [float("nan")] * 100 + [2.0] * 100
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=100)) as w:
        w.write_columns({"v": vals})
    with ParquetFileReader(path) as r:
        chunk = r.row_groups[0].columns[0]
        assert r.read_column_index(chunk) is None
        oi = r.read_offset_index(chunk)
        assert oi is not None and len(oi.page_locations) == 3
        # pruning degrades to whole-group, never wrong
        assert (col("v") >= 1.5).row_ranges(r, 0) == [(0, 300)]


def test_selective_page_read(tmp_path):
    """read_row_group_ranges decodes only intersecting pages (I/O pruning)
    and the covered ranges align with the returned rows."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    path = str(tmp_path / "sel.parquet")
    ss = [None if i % 7 == 0 else f"s{i}" for i in range(1000)]
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=100)) as w:
        w.write_columns({"x": np.arange(1000, dtype=np.int64), "s": ss})
    with ParquetFileReader(path) as r:
        pred = (col("x") >= 250) & (col("x") < 450)
        ranges = pred.row_ranges(r, 0)
        batch, covered = r.read_row_group_ranges(0, ranges)
        assert covered == [(200, 500)]
        assert batch.num_rows == 300
        xs = batch.column("x").values
        np.testing.assert_array_equal(xs, np.arange(200, 500))
        # strings decode consistently within the cover
        sc = batch.column("s")
        exp = ss[200:500]
        got = [sc.cell(i) for i in range(300)]
        got = [None if g is None else g.decode() for g in got]
        assert got == exp
        # dictionary-encoded column still decodes (dict page read separately)
        # empty request
        b2, c2 = r.read_row_group_ranges(0, [])
        assert c2 == [] and b2.num_rows == 0
        # whole group falls back to plain read
        b3, c3 = r.read_row_group_ranges(0, [(0, 1000)])
        assert c3 == [(0, 1000)] and b3.num_rows == 1000


def test_selective_page_read_no_index_fallback(tmp_path):
    """Without an OffsetIndex the selective read degrades to full decode."""
    schema = types.message("t", types.required(types.INT32).named("v"))
    path = str(tmp_path / "noidx.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(write_statistics=False, data_page_values=50)
    ) as w:
        w.write_columns({"v": np.arange(200, dtype=np.int32)})
    with ParquetFileReader(path) as r:
        batch, covered = r.read_row_group_ranges(0, [(10, 20)])
        assert covered == [(0, 200)]
        assert batch.num_rows == 200


def test_selective_read_mixed_page_boundaries(tmp_path):
    """Regression: columns with different page boundaries (level-based
    pagination makes nested columns cut pages at different rows) must
    stay row-aligned — the cover iterates to a fixpoint over every
    chunk's page spans."""
    from parquet_floor_tpu.batch.nested import assemble_nested

    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.list_of(types.required(types.INT32).named("element"), "l",
                      optional=True),
    )
    rows_l = [[int(i), int(i), int(i)] for i in range(1000)]  # 3 levels/row
    path = str(tmp_path / "mixed.parquet")
    with ParquetFileWriter(path, schema, WriterOptions(data_page_values=100)) as w:
        w.write_columns({"x": np.arange(1000, dtype=np.int64), "l": rows_l})
    with ParquetFileReader(path) as r:
        batch, covered = r.read_row_group_ranges(0, [(250, 260)])
        rows = sum(b - a for a, b in covered)
        assert batch.num_rows == rows
        xs = batch.column("x").values
        exp_x = np.concatenate([np.arange(a, b) for a, b in covered])
        np.testing.assert_array_equal(xs, exp_x)
        # the nested column must describe exactly the same rows
        lcol = [c for c in batch.columns if c.descriptor.path[0] == "l"][0]
        nc = assemble_nested(r.schema, lcol)
        assert nc.num_rows == rows
        assert nc.to_pylist() == [rows_l[i] for a, b in covered for i in range(a, b)]


# ---------------------------------------------------- advisor regressions


def test_legacy_binary_stats_not_trusted(filt_file):
    """Legacy Statistics.min/max on BYTE_ARRAY came from signed-byte
    comparison in old parquet-mr writers (PARQUET-251): when only the
    legacy fields are present the group must be KEPT, not pruned."""
    with ParquetFileReader(filt_file) as r:
        pred = col("s") == "zzz-not-present"
        # sanity: with trustworthy min_value/max_value the groups prune
        assert pred.row_groups(r) == []
        for rg in r.row_groups:
            for ch in rg.columns:
                st = ch.meta_data.statistics
                if st is not None and st.min_value is not None:
                    st.min = st.min_value
                    st.max = st.max_value
                    st.min_value = None
                    st.max_value = None
        # legacy-only binary stats are unknown -> every group kept
        assert pred.row_groups(r) == [0, 1, 2, 3]
        # numeric columns keep using legacy min/max (those are sound)
        assert (col("x") < 100).row_groups(r) == [0]


def test_group_name_does_not_prune(tmp_path):
    """A predicate naming a top-level *group* must not silently evaluate
    against the group's first leaf: keep everything (no stats)."""
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.list_of(types.required(types.INT32).named("element"), "l",
                      optional=True),
    )
    path = str(tmp_path / "grp.parquet")
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        w.write_columns({"x": np.arange(10, dtype=np.int64),
                         "l": [[i] for i in range(10)]})
        w.write_columns({"x": np.arange(10, 20, dtype=np.int64),
                         "l": [[i] for i in range(10, 20)]})
    with ParquetFileReader(path) as r:
        # "l" names the group, not the leaf "l.list.element": keep all
        assert (col("l") > 100).row_groups(r) == [0, 1]
        # the exact dotted leaf path still prunes
        leaf = [".".join(c.meta_data.path_in_schema)
                for c in r.row_groups[0].columns if
                c.meta_data.path_in_schema[0] == "l"][0]
        assert (col(leaf) < 5).row_groups(r) == [0]


def test_short_column_index_keeps_pages(tmp_path):
    """A ColumnIndex with fewer min/max entries than the OffsetIndex has
    pages (foreign/truncated writer) must keep the uncovered pages, not
    raise IndexError."""
    schema = types.message("t", types.required(types.INT64).named("x"))
    path = str(tmp_path / "short.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(data_page_values=100)
    ) as w:
        w.write_columns({"x": np.arange(400, dtype=np.int64)})
    with ParquetFileReader(path) as r:
        pred = col("x") >= 1000
        assert pred.row_ranges(r, 0) == []  # all four pages prune
        real_read_ci = r.read_column_index

        def truncated(chunk):
            ci = real_read_ci(chunk)
            if ci is not None:
                ci.min_values = ci.min_values[:1]
                ci.max_values = ci.max_values[:1]
            return ci

        r.read_column_index = truncated
        # page 0 still prunes; pages 1..3 have no stats entries -> kept
        assert pred.row_ranges(r, 0) == [(100, 400)]


def test_utf8_stats_never_prune_matching_rows(tmp_path):
    """Property (VERDICT r1 item 10): BYTE_ARRAY pushdown with
    UNSIGNED/UTF8 column order must never prune a group or page that
    truly contains a match — including against pyarrow's TRUNCATED
    column-index statistics (long values with shared prefixes force
    lower/upper-bound truncation rather than exact min/max)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng2 = np.random.default_rng(17)
    pool = []
    for i in range(2000):
        # adversarial mix: shared long prefixes (truncation), high
        # codepoints (unsigned byte order vs signed), empty strings
        kind = i % 5
        if kind == 0:
            s = "prefix-" * 12 + chr(0x10000 + int(rng2.integers(0, 0xFF))) + str(i)
        elif kind == 1:
            s = chr(int(rng2.integers(0x7F, 0x2FF))) * int(rng2.integers(1, 9))
        elif kind == 2:
            s = ""
        else:
            s = "".join(
                chr(int(c))
                for c in rng2.integers(0x20, 0xFFF, int(rng2.integers(1, 20)))
            )
        pool.append(s)
    rng2.shuffle(pool)
    path = str(tmp_path / "utf8.parquet")
    pq.write_table(
        pa.table({"s": pool}), path,
        row_group_size=250, data_page_size=512, write_page_index=True,
    )
    with ParquetFileReader(path) as r:
        n_groups = len(r.row_groups)
        per_group = [
            pool[g * 250 : (g + 1) * 250] for g in range(n_groups)
        ]
        probes = [pool[i] for i in rng2.integers(0, len(pool), 60)]
        probes += ["", "prefix-" * 12, "￿", "zz"]
        for v in probes:
            for pred, fn in [
                (col("s") == v, lambda s: s == v),
                (col("s") <= v, lambda s: s <= v),
                (col("s") >= v, lambda s: s >= v),
                (col("s") != v, lambda s: s != v),
            ]:
                keep = set(pred.row_groups(r))
                for gi, strings in enumerate(per_group):
                    match_rows = [j for j, s in enumerate(strings) if fn(s)]
                    if match_rows:
                        assert gi in keep, (v, pred, gi)
                        ranges = pred.row_ranges(r, gi)
                        covered = set()
                        for a, b in ranges:
                            covered.update(range(a, b))
                        missing = set(match_rows) - covered
                        assert not missing, (v, pred, gi, sorted(missing)[:5])
