"""Row-group statistics pushdown: conservative skipping, never false
negatives (every group containing a matching row must be kept)."""

import numpy as np
import pytest

from parquet_floor_tpu import ParquetFileReader, ParquetFileWriter, WriterOptions, types
from parquet_floor_tpu.batch.predicate import col


@pytest.fixture(scope="module")
def filt_file(tmp_path_factory):
    """4 row groups: x in [0..99], [100..199], [200..299], [300..399];
    s = 'g{group}'; y optional, all-null in group 2."""
    path = tmp_path_factory.mktemp("pred") / "p.parquet"
    schema = types.message(
        "t",
        types.required(types.INT64).named("x"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.optional(types.DOUBLE).named("y"),
    )
    with ParquetFileWriter(path, schema, WriterOptions()) as w:
        for g in range(4):
            xs = np.arange(g * 100, g * 100 + 100, dtype=np.int64)
            ys = [None] * 100 if g == 2 else [float(v) for v in xs]
            w.write_columns({"x": xs, "s": [f"g{g}"] * 100, "y": ys})
    return str(path)


def _groups(path, pred):
    with ParquetFileReader(path) as r:
        return pred.row_groups(r)


def test_range_pushdown(filt_file):
    assert _groups(filt_file, col("x") < 100) == [0]
    assert _groups(filt_file, col("x") >= 300) == [3]
    assert _groups(filt_file, col("x") == 150) == [1]
    assert _groups(filt_file, (col("x") >= 150) & (col("x") < 250)) == [1, 2]
    assert _groups(filt_file, (col("x") < 50) | (col("x") > 350)) == [0, 3]
    assert _groups(filt_file, col("x") > 1000) == []
    assert _groups(filt_file, col("x") <= 0) == [0]


def test_string_pushdown(filt_file):
    assert _groups(filt_file, col("s") == "g2") == [2]
    assert _groups(filt_file, col("s") >= "g3") == [3]
    # != on a constant-value group rules it out
    assert _groups(filt_file, col("s") != "g1") == [0, 2, 3]


def test_null_pushdown(filt_file):
    assert _groups(filt_file, col("y").is_null()) == [2]
    assert _groups(filt_file, col("y").is_not_null()) == [0, 1, 3]


def test_unknown_column_keeps_all(filt_file):
    assert _groups(filt_file, col("nope") > 1) == [0, 1, 2, 3]


def test_no_false_negatives_random(filt_file):
    """Property: every group that truly contains a match is kept."""
    rng = np.random.default_rng(3)
    with ParquetFileReader(filt_file) as r:
        truth = []
        for gi in range(4):
            xs = r.read_row_group(gi).column("x").values
            truth.append(np.asarray(xs))
        for _ in range(50):
            v = int(rng.integers(-50, 450))
            for pred, fn in [
                (col("x") > v, lambda a: (a > v).any()),
                (col("x") <= v, lambda a: (a <= v).any()),
                (col("x") == v, lambda a: (a == v).any()),
            ]:
                keep = set(pred.row_groups(r))
                for gi, xs in enumerate(truth):
                    if fn(xs):
                        assert gi in keep, (v, pred)


def test_pyarrow_written_stats(tmp_path):
    """Stats written by pyarrow (truncated/exact) drive the same pushdown."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "pa.parquet")
    t = pa.table({"a": list(range(1000))})
    pq.write_table(t, path, row_group_size=250)
    assert _groups(path, col("a") < 250) == [0]
    assert _groups(path, col("a") >= 750) == [3]
