"""Golden compatibility corpus (VERDICT r4 #2): third-party and
parquet-mr-convention binaries in ``tests/data/golden/`` must decode
cell-identically on BOTH engines.

Two provenance classes (see tests/data/golden/README.md):
* ``parquet-cpp/v0.7.1.*`` — genuine 2017 parquet-cpp writer output
  (Apache-licensed, shipped with the pyarrow wheel); oracled by pyarrow.
* ``mr_*`` — parquet-mr 1.12.2 output conventions this repo's writer
  never produces (legacy 2-level lists, MSB-first BIT_PACKED levels,
  PLAIN_DICTIONARY stamps, INT96, the reference's pinned
  SNAPPY+PARQUET_2_0 v2 shape — reference ParquetWriter.java:65-66),
  pinned in ``expected.json`` and (where arrow agrees with the spec)
  cross-checked against pyarrow.
"""

import datetime
import glob
import json
import os

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_floor_tpu import ParquetFileReader, assemble_nested
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn
from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "golden")

CPP_FILES = sorted(
    os.path.basename(p)
    for p in glob.glob(os.path.join(GOLDEN, "parquet-cpp", "*.parquet"))
)
MR_FILES = sorted(
    os.path.basename(p)
    for p in glob.glob(os.path.join(GOLDEN, "*.parquet"))
)


def corpus_paths() -> list:
    """Every golden binary, all provenance classes — the ONE corpus
    enumeration (other test modules reuse it, e.g. the corruption
    fuzz in test_robustness)."""
    return (
        [os.path.join(GOLDEN, "parquet-cpp", f) for f in CPP_FILES]
        + [os.path.join(GOLDEN, f) for f in MR_FILES]
    )


def _host_cells(path):
    """Decode every column with the host engine into plain pylists:
    numbers (None for nulls), ``bytes`` for binary-ish leaves, nested
    lists for repeated fields."""
    out = {}
    with ParquetFileReader(path) as r:
        for gi in range(len(r.row_groups)):
            for cb in r.read_row_group(gi).columns:
                top = cb.descriptor.path[0]
                if cb.descriptor.max_repetition_level > 0:
                    vals = assemble_nested(r.schema, cb).to_pylist()
                    vals = [
                        None if row is None
                        else [
                            None if e is None else _as_bytes_or_num(e)
                            for e in row
                        ]
                        for row in vals
                    ]
                else:
                    dense, mask = cb.dense()
                    if isinstance(dense, ByteArrayColumn):
                        raw = dense.to_list()
                        vals = [
                            None if (mask is not None and mask[i])
                            else bytes(raw[i])
                            for i in range(len(raw))
                        ]
                    elif getattr(dense, "ndim", 1) == 2:
                        vals = [
                            None if (mask is not None and mask[i])
                            else dense[i].tobytes()
                            for i in range(dense.shape[0])
                        ]
                    else:
                        vals = [
                            None if (mask is not None and mask[i])
                            else dense[i].item()
                            for i in range(len(dense))
                        ]
                out.setdefault(top, []).extend(vals)
    return out


def _as_bytes_or_num(e):
    a = np.asarray(e)
    if a.dtype == np.uint8 and a.ndim >= 1:
        return a.tobytes()
    return a.item()


def _device_cells(path):
    """Same rendering through the device engine."""
    out = {}
    with TpuRowGroupReader(path, float64_policy="float64") as tr:
        sch = tr.reader.schema
        for gi in range(tr.num_row_groups):
            for name, dc in tr.read_row_group(gi).items():
                top = name.split(".")[0]
                if dc.descriptor.max_repetition_level > 0:
                    vals = dc.assemble(sch).to_pylist()
                    vals = [
                        None if row is None
                        else [
                            None if e is None else _as_bytes_or_num(e)
                            for e in row
                        ]
                        for row in vals
                    ]
                else:
                    mask = (
                        np.asarray(dc.mask) if dc.mask is not None else None
                    )
                    if dc.lengths is not None:
                        lens = np.asarray(dc.lengths)
                        rows = np.asarray(dc.values)
                        vals = [
                            None if (mask is not None and mask[i])
                            else rows[i, : lens[i]].tobytes()
                            for i in range(len(lens))
                        ]
                    else:
                        arr = np.asarray(dc.values)
                        if arr.ndim == 2:
                            vals = [
                                None if (mask is not None and mask[i])
                                else arr[i].tobytes()
                                for i in range(arr.shape[0])
                            ]
                        else:
                            vals = [
                                None if (mask is not None and mask[i])
                                else arr[i].item()
                                for i in range(len(arr))
                            ]
                out.setdefault(top, []).extend(vals)
    return out


def _normalize_oracle(values):
    """pyarrow pylist → the same plain form ``_host_cells`` renders."""
    out = []
    for v in values:
        if isinstance(v, str):
            out.append(v.encode())
        elif isinstance(v, datetime.datetime):
            # ConvertedType TIMESTAMP_MICROS columns come back as tz-aware
            # datetimes; our engines surface the raw int64 micros.
            # timedelta floor-division stays exact for pre-epoch values
            # (int(timestamp()) would truncate toward zero)
            epoch = datetime.datetime(1970, 1, 1,
                                      tzinfo=datetime.timezone.utc)
            out.append(
                (v.replace(tzinfo=datetime.timezone.utc) - epoch)
                // datetime.timedelta(microseconds=1)
            )
        elif isinstance(v, list):
            out.append(_normalize_oracle(v))
        else:
            out.append(v)
    return out


def _assert_same(got, want, label):
    assert len(got) == len(want), label
    for i, (g, w) in enumerate(zip(got, want)):
        if isinstance(w, float) and isinstance(g, float):
            assert g == w or abs(g - w) < 1e-12, f"{label}[{i}]: {g} != {w}"
        else:
            assert g == w, f"{label}[{i}]: {g!r} != {w!r}"


@pytest.mark.parametrize("fname", CPP_FILES)
def test_parquet_cpp_files_both_engines(fname):
    """2017 parquet-cpp binaries: host engine == pyarrow oracle, device
    engine == host, every column cell-identical."""
    path = os.path.join(GOLDEN, "parquet-cpp", fname)
    host = _host_cells(path)
    oracle = pq.read_table(path)
    assert set(host) == set(oracle.column_names)
    for col in oracle.column_names:
        want = _normalize_oracle(oracle.column(col).to_pylist())
        _assert_same(host[col], want, f"{fname}:{col}")
    dev = _device_cells(path)
    assert set(dev) == set(host)
    for col in host:
        _assert_same(dev[col], host[col], f"{fname}:{col} (device)")


@pytest.mark.parametrize("fname", MR_FILES)
def test_mr_convention_files_both_engines(fname):
    """parquet-mr-convention binaries: both engines == the pinned
    expected cells (bytes hex-encoded in expected.json)."""
    with open(os.path.join(GOLDEN, "expected.json")) as f:
        expected_all = json.load(f)
    assert fname in expected_all, f"{fname} missing from expected.json"
    path = os.path.join(GOLDEN, fname)

    # expected.json stores raw-binary cells hex-encoded ("ts") and text
    # cells as strings ("name"); our engines render both as bytes
    decode = {"ts": bytes.fromhex, "name": str.encode}
    expected = {}
    for col, vals in expected_all[fname].items():
        fn = decode.get(col)
        expected[col] = (
            [None if v is None else fn(v) for v in vals] if fn else vals
        )
    host = _host_cells(path)
    assert set(host) == set(expected)
    for col, want in expected.items():
        _assert_same(host[col], want, f"{fname}:{col}")
    dev = _device_cells(path)
    assert set(dev) == set(expected)
    for col, want in expected.items():
        _assert_same(dev[col], want, f"{fname}:{col} (device)")


def test_created_by_surfaces():
    """The third-party created_by stamps parse and surface through the
    metadata API (readers must not choke on foreign writer strings)."""
    with ParquetFileReader(
        os.path.join(GOLDEN, "mr_v2_delta_snappy.parquet")
    ) as r:
        assert "parquet-mr version 1.12.2" in (r.metadata.created_by or "")
    with ParquetFileReader(
        os.path.join(GOLDEN, "parquet-cpp", "v0.7.1.parquet")
    ) as r:
        assert "parquet-cpp" in (r.metadata.created_by or "")


def test_foreign_page_index_drives_selective_reads():
    """The third-party-convention OffsetIndex actually DRIVES the
    selective-read machinery: projected to the 3-page 'f' column, a
    100-row range covers a strict SUBSET of the group on the foreign
    page grid, identically on both engines.  (Unprojected, the
    single-page 'o' column would expand the cover to the whole group
    and short-circuit into read_row_group — proving nothing.)"""
    path = os.path.join(GOLDEN, "mr_pageindex_bss_lz4.parquet")
    ranges = [(50, 150)]
    with ParquetFileReader(path) as r:
        hb, hcov = r.read_row_group_ranges(0, ranges, column_filter={"f"})
        n = int(r.row_groups[0].num_rows)
        # a strict subset, page-aligned on the foreign 100-row grid
        assert hcov and hcov != [(0, n)]
        assert all(a % 100 == 0 and b % 100 == 0 for a, b in hcov)
        host_vals = {
            cb.descriptor.path[0]: cb.dense()[0] for cb in hb.columns
        }
    with TpuRowGroupReader(path, float64_policy="float64") as tr:
        dev, dcov = tr.read_row_group_ranges(0, ranges, columns=["f"])
        assert dcov == hcov
        for name, hv in host_vals.items():
            np.testing.assert_array_equal(
                np.asarray(dev[name].values), hv, err_msg=name
            )


def test_foreign_column_index_prunes_pages():
    """The third-party-convention ColumnIndex drives page-level
    predicate pruning: 'f' pages are value-disjoint (page p of group g
    spans g*10000 + p*1000 ..+100), so a point predicate must narrow
    the row ranges to ONE page per matching group."""
    from parquet_floor_tpu import col

    path = os.path.join(GOLDEN, "mr_pageindex_bss_lz4.parquet")
    pred = col("f") >= 2000.0
    with ParquetFileReader(path) as r:
        # group 0 pages span [0..100), [1000..1100), [2000..2100):
        # only page 2 can match f >= 2000 within group 0
        rr = pred.row_ranges(r, 0)
        assert rr == [(200, 300)], rr
        # group 1 spans [10000..12100): every page matches
        rr1 = pred.row_ranges(r, 1)
        assert rr1 == [(0, 300)], rr1
